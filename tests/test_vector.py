"""Vector (ANN) index contract tests.

The equality gate mirrors the covering-index E2E contract: with
nprobe == num_partitions the index search must return EXACTLY the
brute-force top-k (same scores, same rows); with smaller nprobe recall
must stay high on clustered data. Lifecycle (delete/restore/vacuum)
applies to vector indexes unchanged because they share the log-entry
envelope.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, VectorIndexConfig
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.ops.topk import topk


@pytest.fixture
def session(tmp_system_path):
    return HyperspaceSession(system_path=tmp_system_path, num_buckets=8)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


@pytest.fixture
def emb_parquet(tmp_path):
    """Clustered embeddings (so k-means partitions are meaningful)."""
    rng = np.random.default_rng(0)
    n, d, c = 4000, 32, 16
    centers = rng.standard_normal((c, d)).astype(np.float32) * 5
    assign = rng.integers(0, c, n)
    emb = centers[assign] + rng.standard_normal((n, d)).astype(np.float32)
    table = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "emb": pa.FixedSizeListArray.from_arrays(
                pa.array(emb.reshape(-1), type=pa.float32()), d
            ),
            "label": pa.array([f"l{i % 5}" for i in range(n)]),
        }
    )
    root = tmp_path / "embdata"
    root.mkdir()
    pq.write_table(table, root / "part-0.parquet")
    return str(root), emb


def test_topk_nan_scores_treated_as_minus_inf():
    x = np.random.default_rng(4).standard_normal((3, 2000)).astype(np.float32)
    x[0, 5] = np.nan
    x[2, :] = np.nan
    for impl in ("pallas", "xla"):
        v, i = topk(x, 5, impl=impl)
        assert not np.isnan(v).any(), impl
        assert (i < 2000).all(), impl  # never out-of-range
        assert np.isinf(v[2]).all(), impl  # all-NaN row → all -inf


def test_topk_pallas_matches_xla():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((5, 3000)).astype(np.float32)
    pv, pi = topk(x, 7, impl="pallas")
    xv, xi = topk(x, 7, impl="xla")
    np.testing.assert_allclose(pv, xv, rtol=1e-6)
    np.testing.assert_array_equal(
        np.take_along_axis(x, pi, 1), np.take_along_axis(x, xi, 1)
    )


def test_vector_index_full_probe_equals_brute_force(session, hs, emb_parquet):
    root, emb = emb_parquet
    df = session.parquet(root)
    hs.create_vector_index(df, VectorIndexConfig("vidx", "emb", ["id", "label"], num_partitions=16))

    rng = np.random.default_rng(2)
    queries = emb[rng.choice(len(emb), 6, replace=False)] + 0.01

    session.disable_hyperspace()
    exact = hs.ann_search(df, queries, k=10)

    session.enable_hyperspace()
    approx = hs.ann_search(df, queries, k=10, nprobe=16)  # all partitions

    np.testing.assert_allclose(
        np.sort(exact.scores, axis=1), np.sort(approx.scores, axis=1), rtol=1e-4
    )
    # Same ids per query (order may differ on score ties).
    eids = exact.rows.columns["id"].reshape(6, -1)
    aids = approx.rows.columns["id"].reshape(6, -1)
    for i in range(6):
        assert set(eids[i]) == set(aids[i])


def test_vector_index_partial_probe_recall(session, hs, emb_parquet):
    root, emb = emb_parquet
    df = session.parquet(root)
    hs.create_vector_index(df, VectorIndexConfig("vidx2", "emb", ["id"], num_partitions=16))
    rng = np.random.default_rng(3)
    queries = emb[rng.choice(len(emb), 8, replace=False)]

    session.disable_hyperspace()
    exact = hs.ann_search(df, queries, k=10)
    session.enable_hyperspace()
    approx = hs.ann_search(df, queries, k=10, nprobe=4)

    eids = exact.rows.columns["id"].reshape(8, -1)
    aids = approx.rows.columns["id"].reshape(8, -1)
    recall = np.mean([len(set(eids[i]) & set(aids[i])) / 10 for i in range(8)])
    assert recall >= 0.8, f"recall@10 too low: {recall}"


def test_vector_index_metrics(session, hs, emb_parquet):
    root, emb = emb_parquet
    df = session.parquet(root)
    hs.create_vector_index(
        df, VectorIndexConfig("vip", "emb", ["id"], num_partitions=8, metric="ip")
    )
    session.enable_hyperspace()
    q = emb[:3]
    res = hs.ann_search(df, q, k=5, nprobe=8)
    session.disable_hyperspace()
    exact = hs.ann_search(df, q, k=5, embedding_column="emb", metric="ip")
    np.testing.assert_allclose(
        np.sort(res.scores, axis=1), np.sort(exact.scores, axis=1), rtol=1e-4
    )


def test_vector_index_lifecycle_and_summary(session, hs, emb_parquet):
    root, _ = emb_parquet
    df = session.parquet(root)
    hs.create_vector_index(df, VectorIndexConfig("vlife", "emb", ["id"]))
    summary = hs.indexes()
    row = summary[summary["name"] == "vlife"].iloc[0]
    assert row["kind"] == "VectorIndex"
    assert row["state"] == "ACTIVE"

    hs.delete_index("vlife")
    assert hs.indexes().iloc[0]["state"] == "DELETED"
    hs.restore_index("vlife")
    assert hs.indexes().iloc[0]["state"] == "ACTIVE"

    # refresh/optimize are first-class for vector indexes (round-2;
    # deep coverage in test_vector_lifecycle.py) — a full refresh with no
    # new data still rebuilds into the next version.
    hs.refresh_index("vlife")
    assert hs.indexes().iloc[0]["state"] == "ACTIVE"
    hs.optimize_index("vlife")
    assert hs.indexes().iloc[0]["state"] == "ACTIVE"


def test_fewer_candidates_than_k_drops_unprobed_rows(session, hs, emb_parquet):
    """A query probing partitions with < k rows must NOT surface rows from
    partitions it never probed; missing slots carry -inf scores."""
    root, emb = emb_parquet
    df = session.parquet(root)
    hs.create_vector_index(df, VectorIndexConfig("vsmall", "emb", ["id"], num_partitions=64))
    session.enable_hyperspace()
    q = emb[:2]
    res = hs.ann_search(df, q, k=500, nprobe=1)  # one partition of ~62 rows
    n_rows = res.rows.num_rows
    assert n_rows < 2 * 500, "short results must be trimmed"
    # every -inf slot (candidate from an unprobed partition) is dropped
    assert np.isinf(res.scores).sum() == res.scores.size - n_rows
    assert np.all(np.isfinite(res.scores[:, 0]))  # best match always real


def test_vector_index_requires_vector_column(session, hs, emb_parquet):
    root, _ = emb_parquet
    df = session.parquet(root)
    with pytest.raises(HyperspaceError, match="vector dtype"):
        hs.create_vector_index(df, VectorIndexConfig("bad", "id"))


def test_stale_vector_index_falls_back_to_brute_force(session, hs, emb_parquet, tmp_path):
    root, emb = emb_parquet
    df = session.parquet(root)
    hs.create_vector_index(df, VectorIndexConfig("vstale", "emb", ["id"]))
    # Append data: signature mismatch => index unusable, falls back exact.
    rng = np.random.default_rng(5)
    extra = rng.standard_normal((50, 32)).astype(np.float32)
    t = pa.table(
        {
            "id": pa.array(np.arange(10_000, 10_050, dtype=np.int64)),
            "emb": pa.FixedSizeListArray.from_arrays(
                pa.array(extra.reshape(-1), type=pa.float32()), 32
            ),
            "label": pa.array(["x"] * 50),
        }
    )
    import pathlib

    pq.write_table(t, pathlib.Path(root) / "part-new.parquet")

    session.enable_hyperspace()
    res = hs.ann_search(df, extra[:2], k=3)
    # Brute force sees the appended rows; their ids must surface as the
    # exact matches of their own vectors.
    ids = res.rows.columns["id"].reshape(2, -1)
    assert 10_000 in ids[0] and 10_001 in ids[1]
