"""Dynamic partition pruning + included-column manifest stats.

DPP (the analog of Spark 3's dynamic partition pruning, which post-dates
the reference's engine): the filtered dimension side of a bucket-aligned
join executes first, its surviving key range prunes the fact side's
bucket files via manifest key stats. Included-column stats extend the
FileSourceScanExec-style min/max pruning (SURVEY.md §2.2) beyond the
leading indexed column.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col, lit
from hyperspace_tpu.execution import io as hio

NB = 8


@pytest.fixture
def star(tmp_path):
    """Fact bucketed on a date-like contiguous key + a small dimension;
    both indexed with equal bucket counts (the aligned-join setup)."""
    rng = np.random.default_rng(17)
    n = 40_000
    fact = pd.DataFrame(
        {
            "dk": rng.integers(0, 2_000, n).astype(np.int64),  # "date" key
            "v": rng.normal(size=n),
            "q": rng.integers(1, 100, n).astype(np.int64),
        }
    )
    dim = pd.DataFrame(
        {
            "dk": np.arange(2_000, dtype=np.int64),
            "year": (np.arange(2_000) // 400).astype(np.int64),  # 5 "years"
        }
    )
    for name, df in (("fact", fact), ("dim", dim)):
        (tmp_path / name).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=NB)
    hs = Hyperspace(session)
    f = session.parquet(tmp_path / "fact")
    d = session.parquet(tmp_path / "dim")
    hs.create_index(f, IndexConfig("f_dk", ["dk"], ["v", "q"]))
    hs.create_index(d, IndexConfig("d_dk", ["dk"], ["year"]))
    session.enable_hyperspace()
    return session, f, d, fact, dim


def test_dpp_prunes_fact_files_on_aligned_join(star):
    session, f, d, fact, dim = star
    q = (
        f.join(d.filter(col("year") == lit(2)), ["dk"])
        .aggregate([], [AggSpec.of("sum", "q", "sq"), AggSpec.of("count", None, "n")])
    )
    got = session.to_pandas(q)
    stats = session.last_query_stats
    assert stats["join_path"] == "zero-exchange-aligned"
    # Year 2 spans dk 800..1199 — hash bucketing scatters those keys
    # across every bucket FILE, but within each sorted file they form
    # one contiguous run: DPP slices ~4/5 of the fact rows away.
    j = fact.merge(dim[dim.year == 2], on="dk")
    assert int(got.loc[0, "n"]) == len(j)
    np.testing.assert_allclose(got.loc[0, "sq"], j.q.sum())
    assert "dpp_rows_pruned" in repr(session.last_physical_plan)
    assert stats["rows_pruned"] > 0


def test_dpp_point_filter_prunes_and_matches(star):
    session, f, d, fact, dim = star
    # A single dim row survives: the fact side must read at most the
    # files whose [min, max] covers that one key.
    q = (
        f.join(d.filter(col("dk") == lit(1_234)), ["dk"])
        .aggregate([], [AggSpec.of("count", None, "n")])
    )
    got = session.to_pandas(q)
    stats = session.last_query_stats
    assert stats["join_path"] == "zero-exchange-aligned"
    exp = len(fact[fact.dk == 1_234])
    assert int(got.loc[0, "n"]) == exp
    phys = repr(session.last_physical_plan)
    assert "dpp_files_pruned" in phys, phys


def test_dpp_empty_producer_short_circuits(star):
    session, f, d, fact, dim = star
    q = (
        f.join(d.filter(col("year") == lit(99)), ["dk"])  # no dim rows
        .aggregate([], [AggSpec.of("count", None, "n")])
    )
    got = session.to_pandas(q)
    assert int(got.loc[0, "n"]) == 0
    assert "dpp_files_pruned" in repr(session.last_physical_plan)


def test_dpp_not_applied_to_outer_joins(star):
    session, f, d, fact, dim = star
    # LEFT join preserves every fact row: DPP on the fact side would be
    # unsound and must not engage; results stay complete.
    q = f.join(d.filter(col("year") == lit(2)), ["dk"], how="left").aggregate(
        [], [AggSpec.of("count", None, "n")]
    )
    got = session.to_pandas(q)
    assert int(got.loc[0, "n"]) == len(fact)
    assert "dpp_files_pruned" not in repr(session.last_physical_plan)


def test_dpp_disabled_for_nan_float_producer_keys(tmp_path):
    """A float join key with NaN values must DISABLE DPP (NaN bounds
    would slice every finite consumer row away) — results stay complete."""
    n = 8_000
    rng = np.random.default_rng(9)
    fk = rng.integers(0, 500, n).astype(np.float64)
    fact = pd.DataFrame({"fk": fk, "v": rng.normal(size=n)})
    dk = np.arange(500, dtype=np.float64)
    dk[7] = np.nan  # a NaN key on the producer side
    dim = pd.DataFrame({"fk": dk, "w": np.arange(500) * 1.0})
    for name, df in (("fact", fact), ("dim", dim)):
        (tmp_path / name).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    f = session.parquet(tmp_path / "fact")
    d = session.parquet(tmp_path / "dim")
    hs.create_index(f, IndexConfig("fnan", ["fk"], ["v"]))
    hs.create_index(d, IndexConfig("dnan", ["fk"], ["w"]))
    session.enable_hyperspace()
    q = f.join(d.filter(col("w") >= lit(0.0)), ["fk"]).aggregate(
        [], [AggSpec.of("count", None, "n")]
    )
    got = session.to_pandas(q)
    exp = fact.merge(dim[dim.w >= 0], on="fk")  # pandas drops NaN-key matches... compute manually
    finite = fact[~np.isnan(fact.fk)].merge(dim[~np.isnan(dim.fk)], on="fk")
    assert int(got.loc[0, "n"]) >= len(finite)
    assert "dpp_rows_pruned" not in repr(session.last_physical_plan)


def test_included_column_stats_in_manifest(star, tmp_path):
    m = hio.read_manifest(tmp_path / "idx" / "f_dk" / "v__=0")
    assert m is not None and "columnStats" in m
    cs = m["columnStats"]
    assert len(cs) == NB
    vdir = tmp_path / "idx" / "f_dk" / "v__=0"
    for b, s in enumerate(cs):
        t = pq.read_table(vdir / hio.bucket_file_name(b)).to_pandas()
        if len(t) == 0:
            continue
        assert s["q"][0] == t["q"].min() and s["q"][1] == t["q"].max()


def test_included_column_predicate_prunes_files(tmp_path):
    """q48-style shape: the filter constrains an INCLUDED column whose
    per-file ranges are disjoint; files outside the band are skipped."""
    n = 30_000
    # Key correlates with the included column so bucket files get
    # distinguishable included-column ranges (hash-bucketing keeps
    # same-key rows together; q = k makes per-file q ranges ~disjoint
    # subsets of the key space... not contiguous, so instead use few
    # distinct keys => each file holds FEW distinct q values).
    k = np.repeat(np.arange(16, dtype=np.int64), n // 16)
    df = pd.DataFrame({"k": k, "band": k * 100, "v": np.random.default_rng(3).normal(size=len(k))})
    root = tmp_path / "src"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=NB)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_index(scan, IndexConfig("inc_k", ["k"], ["band", "v"]))
    session.enable_hyperspace()
    # Filter touches the indexed column loosely (keeps every file by key
    # range) AND an included column tightly (drops most files).
    q = scan.filter((col("k") >= lit(0)) & (col("band") == lit(700)))
    out = session.run(q)
    stats = session.last_query_stats
    exp = len(df[df.band == 700])
    assert out.num_rows == exp
    assert stats["files_pruned"] > 0, stats
