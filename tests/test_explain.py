"""Explain output tests (analog of the reference's ExplainTest, which pins
exact explain strings per display mode)."""

import numpy as np
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.explain.display_mode import (
    EXPLAIN_DISPLAY_MODE,
    ConsoleMode,
    HTMLMode,
    PlainTextMode,
    display_mode_from_conf,
)


@pytest.fixture
def session(tmp_system_path):
    return HyperspaceSession(system_path=tmp_system_path, num_buckets=8)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def test_display_mode_selection(session):
    assert isinstance(display_mode_from_conf(session.conf), PlainTextMode)
    session.conf.set(EXPLAIN_DISPLAY_MODE, "console")
    assert isinstance(display_mode_from_conf(session.conf), ConsoleMode)
    session.conf.set(EXPLAIN_DISPLAY_MODE, "html")
    assert isinstance(display_mode_from_conf(session.conf), HTMLMode)


def test_explain_highlights_replaced_subtree(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("eidx", ["key"], ["value"]))
    q = df.filter(col("key") == 1).select("key", "value")

    text = hs.explain(q)
    assert "Plan with indexes:" in text
    assert "Plan without indexes:" in text
    assert "IndexScan" in text
    assert "eidx" in text  # listed under "Indexes used"
    # plaintext mode: the replaced scans get trailing markers
    marked = [l for l in text.splitlines() if l.endswith("<----")]
    assert any("IndexScan" in l for l in marked)
    assert any("Scan" in l and "IndexScan" not in l for l in marked)
    # unchanged nodes (Project/Filter) are NOT highlighted
    assert not any("Project" in l for l in marked)


def test_explain_console_and_html_modes(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("eidx2", ["key"], ["value"]))
    q = df.filter(col("key") == 1).select("key", "value")

    session.conf.set(EXPLAIN_DISPLAY_MODE, "console")
    text = hs.explain(q)
    assert "\x1b[7m" in text and "\x1b[27m" in text

    session.conf.set(EXPLAIN_DISPLAY_MODE, "html")
    text = hs.explain(q)
    assert "<b>" in text and "</b>" in text
    assert "<br/>" in text and "\n" not in text
    assert text.startswith("<pre>") and text.endswith("</pre>")

    session.conf.set(EXPLAIN_DISPLAY_MODE, "bogus")
    with pytest.raises(ValueError, match="unknown"):
        display_mode_from_conf(session.conf)


def test_explain_verbose_counts_eliminated_exchanges(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("eidx3", ["key"], ["value"]))
    q = df.filter(col("key") == 1).select("key", "value")
    text = hs.explain(q, verbose=True)
    assert "Physical operator stats:" in text
    assert "IndexScan: 0 -> 1" in text
    assert "Scan: 1 -> 0" in text
    assert "ShuffleExchange-equivalents eliminated: 1" in text


def test_explain_no_rewrite_has_no_highlights(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)  # no index created
    q = df.filter(col("key") == 1).select("key", "value")
    text = hs.explain(q)
    assert "<----" not in text


def test_explain_shared_node_marks_only_rewritten_occurrence(session, hs, sample_parquet):
    """The same df (one Scan OBJECT) on both join legs: only the leg the
    rewriter replaced may be highlighted — occurrence-path marking, not
    object identity."""
    from hyperspace_tpu.plan.nodes import Filter as FilterNode, Join, Project

    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("shidx", ["key"], ["value"]))
    # Left leg: Project(Filter(Scan)) covered by the index → FilterIndexRule
    # rewrites it. Right leg: the SAME Scan object projecting a non-covered
    # column ('name') → stays a raw source scan.
    q = Join(
        Project(FilterNode(df, col("key") == 1), ["key", "value"]),
        Project(df, ["key", "name"]),
        ["key"],
        ["key"],
    )
    session.enable_hyperspace()
    opt = session.optimized_plan(q)
    session.disable_hyperspace()
    rewritten = [s for s in opt.leaves() if s.bucket_spec is not None]
    assert len(rewritten) == 1, "exactly the left leg must be rewritten"

    text = hs.explain(q)
    without = text.split("Plan without indexes:")[1].split("=" * 64)[0]
    marked = [l for l in without.splitlines() if l.endswith("<----")]
    unmarked_scans = [
        l for l in without.splitlines() if "Scan" in l and not l.endswith("<----")
    ]
    assert marked, "the rewritten occurrence must be highlighted"
    assert unmarked_scans, "the unchanged occurrence must not be highlighted"
