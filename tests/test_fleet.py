"""Multi-process serving fleet tests (docs/serving.md "fleet topology").

Covers the file-lease primitive (stale-holder reaping), cross-process
single-flight (leader/follower/local-fallback/takeover), the RefCache
single-flight wait timeout, per-tenant token-bucket quotas and
queue-depth shedding in the scheduler, the disk-backed shared
plan/result caches (round-trip, versioned invalidation, advisory
corruption handling, lease-held eviction), and — with REAL processes
over one store — the promoted staleness proof (process A refreshes,
process B must never serve a pre-refresh cached result), lease takeover
from a SIGKILLed holder, and supervisor crash-restart.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, stats
from hyperspace_tpu.exceptions import AdmissionRejected, QuotaExceeded
from hyperspace_tpu.serve import QueryServer, fleet
from hyperspace_tpu.serve.fleet.lease import FileLease
from hyperspace_tpu.serve.fleet.quota import TenantQuotas, TokenBucket
from hyperspace_tpu.serve.fleet.shared_cache import SharedResultCache
from hyperspace_tpu.serve.fleet.singleflight import SingleFlight, key_name


def _session(tmp_system_path) -> HyperspaceSession:
    return HyperspaceSession(system_path=tmp_system_path)


def _assert_same(a, b, label=""):
    da, db = a.decode(), b.decode()
    assert set(da) == set(db), (label, set(da), set(db))
    for c in da:
        av, bv = np.asarray(da[c]), np.asarray(db[c])
        assert len(av) == len(bv), (label, c, len(av), len(bv))
        if av.dtype.kind in "fc" and bv.dtype.kind in "fc":
            np.testing.assert_allclose(av, bv, rtol=1e-9, err_msg=f"{label}.{c}")
        else:
            assert (av.astype(object) == bv.astype(object)).all(), (label, c)


# -- file lease ---------------------------------------------------------------

class TestFileLease:
    def test_acquire_release_roundtrip(self, tmp_path):
        lease = FileLease(tmp_path / "a.lease", ttl_s=30)
        claim = lease.try_acquire()
        assert claim is not None
        token, reaped = claim
        assert not reaped
        assert lease.try_acquire() is None  # held by a live contender
        lease.release(token)
        assert lease.try_acquire() is not None  # free again

    def test_stale_holder_is_reaped(self, tmp_path):
        path = tmp_path / "b.lease"
        # A lease whose creator epoch is long past the TTL: a crashed
        # holder's leftover.
        path.write_text(f"{time.time() - 120:.6f}:99999:dead")
        lease = FileLease(path, ttl_s=1.0)
        claim = lease.try_acquire()
        assert claim is not None and claim[1] is True  # reaped

    def test_release_of_stolen_lease_is_noop(self, tmp_path):
        path = tmp_path / "c.lease"
        lease = FileLease(path, ttl_s=30)
        token, _ = lease.try_acquire()
        path.write_text("other-holder-token")  # our lease was reaped/stolen
        lease.release(token)
        assert path.read_text() == "other-holder-token"  # not unlinked


# -- cross-process single-flight (driven in-process for determinism) ----------

def _walk(span):
    yield span
    for c in span.get("children", ()):
        yield from _walk(c)


class TestSingleFlight:
    def test_leader_builds_follower_observes(self, tmp_path):
        sf = SingleFlight(tmp_path, lease_ttl_s=30, wait_s=10)
        artifact = tmp_path / "artifact.json"
        built = []
        release = threading.Event()

        def leader_build():
            release.wait(30)
            artifact.write_text(json.dumps({"v": 42}))
            built.append("leader")
            return 42

        def check():
            if artifact.exists():
                return json.loads(artifact.read_text())["v"]
            return None

        def follower_build():
            built.append("follower")  # must never run
            return -1

        results = []
        t1 = threading.Thread(target=lambda: results.append(sf.run("k", leader_build, check)))
        t1.start()
        time.sleep(0.2)  # leader holds the lease now
        t2 = threading.Thread(target=lambda: results.append(sf.run("k", follower_build, check)))
        t2.start()
        time.sleep(0.2)
        release.set()
        t1.join(30)
        t2.join(30)
        assert sorted(results) == [42, 42]
        assert built == ["leader"]  # exactly one build across "processes"
        assert stats.get("fleet.singleflight.leader") == 1
        assert stats.get("fleet.singleflight.follower_hits") == 1

    def test_follower_wait_span_links_leader_trace_id(self, tmp_path):
        """Cross-process trace propagation (docs/observability.md): the
        leader stamps its root trace id into the lease token note; a
        follower that waited records a `fleet.singleflight.wait` span
        carrying that leader id — the fleet chrome trace can join the
        follower's stall to the trace that actually did the work."""
        from hyperspace_tpu.obs import trace as obs_trace

        sf = SingleFlight(tmp_path, lease_ttl_s=30, wait_s=10)
        artifact = tmp_path / "artifact.json"
        release = threading.Event()
        leader_trace = []

        def leader():
            with obs_trace.trace("leader.query"):
                leader_trace.append(obs_trace.current_trace_id())
                sf.run("k", build=lambda: (
                    release.wait(30),
                    artifact.write_text(json.dumps({"v": 1})),
                )[0], check=check)

        def check():
            return 1 if artifact.exists() else None

        follower_roots = []

        def follower():
            with obs_trace.trace("follower.query"):
                sf.run("k", build=lambda: -1, check=check)
            follower_roots.append(obs_trace.last_trace().to_json())

        t1 = threading.Thread(target=leader)
        t1.start()
        time.sleep(0.3)  # leader holds the lease, note = its trace id
        t2 = threading.Thread(target=follower)
        t2.start()
        time.sleep(0.3)  # follower is in the wait loop
        release.set()
        t1.join(30)
        t2.join(30)
        (root,) = follower_roots
        waits = [s for s in _walk(root) if s["name"] == "fleet.singleflight.wait"]
        assert waits, "follower never recorded its wait"
        (wait,) = waits
        assert wait["attrs"]["outcome"] == "follower_hit"
        assert wait["attrs"]["leader_trace_id"] == leader_trace[0]

    def test_wait_expiry_falls_back_to_local_build(self, tmp_path):
        sf = SingleFlight(tmp_path, lease_ttl_s=30, wait_s=0.1)
        # A live (non-stale) foreign lease, artifact never appears.
        FileLease(tmp_path / f"{key_name('k2')}.lease", ttl_s=30).try_acquire()
        out = sf.run("k2", build=lambda: "local", check=lambda: None)
        assert out == "local"
        assert stats.get("fleet.singleflight.local_fallbacks") == 1

    def test_stale_lease_takeover(self, tmp_path):
        sf = SingleFlight(tmp_path, lease_ttl_s=0.5, wait_s=10)
        stale = tmp_path / f"{key_name('k3')}.lease"
        stale.write_text(f"{time.time() - 60:.6f}:99999:dead")
        out = sf.run("k3", build=lambda: "rebuilt", check=lambda: None)
        assert out == "rebuilt"
        assert stats.get("fleet.singleflight.takeovers") == 1
        from hyperspace_tpu.obs import events as obs_events

        names = [e["name"] for e in obs_events.recent()]
        assert "fleet.singleflight.takeover" in names

    def test_build_error_releases_lease(self, tmp_path):
        sf = SingleFlight(tmp_path, lease_ttl_s=30, wait_s=0.1)
        with pytest.raises(ValueError):
            sf.run("k4", build=lambda: (_ for _ in ()).throw(ValueError("boom")))
        # The lease is free again: the next run leads immediately.
        assert sf.run("k4", build=lambda: "ok") == "ok"


# -- RefCache single-flight wait timeout (satellite fix) ----------------------

class TestRefCacheWaitTimeout:
    def test_abandoned_build_event_no_longer_blocks(self):
        from hyperspace_tpu.execution.device_cache import RefCache

        rc = RefCache(budget_bytes=1 << 20, name="t_refcache_timeout")
        key = ("k", 1)
        # Simulate an abandoned in-process build: the building slot is
        # claimed but its event will never be set (builder thread died
        # without unwinding through get_or_build).
        with rc._lock:
            rc._building[key] = threading.Event()
        t0 = time.monotonic()
        out = rc.get_or_build(key, (), lambda: ("value", 8), wait_timeout=0.05)
        assert out == "value"
        assert time.monotonic() - t0 < 5.0  # returned promptly, not wedged
        # The abandoned slot still belongs to the stuck builder.
        with rc._lock:
            assert key in rc._building

    def test_timeout_path_still_caches(self):
        from hyperspace_tpu.execution.device_cache import RefCache

        rc = RefCache(budget_bytes=1 << 20, name="t_refcache_timeout2")
        key = ("k", 2)
        with rc._lock:
            rc._building[key] = threading.Event()
        rc.get_or_build(key, (), lambda: ("v1", 8), wait_timeout=0.01)
        with rc._lock:
            del rc._building[key]  # stuck builder "finally" goes away
        calls = []
        out = rc.get_or_build(key, (), lambda: calls.append(1) or ("v2", 8))
        assert out == "v1" and not calls  # the local build was admitted


# -- tenant quotas ------------------------------------------------------------

class TestQuota:
    def test_token_bucket_math(self):
        b = TokenBucket(rate=2.0, burst=2.0, now=0.0)
        assert b.try_take(0.0) == 0.0
        assert b.try_take(0.0) == 0.0
        wait = b.try_take(0.0)
        assert wait == pytest.approx(0.5)  # 1 token / 2 per second
        assert b.try_take(0.6) == 0.0  # refilled

    def test_tenants_are_isolated(self):
        clk = [0.0]
        tq = TenantQuotas(rate=1.0, burst=1, clock=lambda: clk[0])
        tq.admit("a")
        with pytest.raises(QuotaExceeded) as ei:
            tq.admit("a")
        assert ei.value.tenant == "a" and ei.value.retry_after_s > 0
        tq.admit("b")  # b's bucket is untouched by a's exhaustion

    def test_per_tenant_limit_override(self):
        clk = [0.0]
        tq = TenantQuotas(rate=100.0, burst=100, clock=lambda: clk[0])
        tq.set_limit("starved", rate=1.0, burst=1)
        tq.admit("starved")
        with pytest.raises(QuotaExceeded):
            tq.admit("starved")

    def test_scheduler_integration(self, tmp_system_path):
        session = _session(tmp_system_path)
        clk = [0.0]
        quotas = TenantQuotas(rate=1.0, burst=2, clock=lambda: clk[0])
        server = QueryServer(session, workers=1, max_queue_depth=16,
                             plan_cache=False, run_fn=lambda p: p, quotas=quotas)
        try:
            assert server.submit("q1", tenant="t1").result(timeout=30) == "q1"
            assert server.submit("q2", tenant="t1").result(timeout=30) == "q2"
            with pytest.raises(QuotaExceeded):
                server.submit("q3", tenant="t1")
            # QuotaExceeded IS an AdmissionRejected (one typed surface).
            with pytest.raises(AdmissionRejected):
                server.submit("q4", tenant="t1")
            # Tenant-less submits are unmetered by contract.
            assert server.submit("q5").result(timeout=30) == "q5"
            # Another tenant is unaffected.
            assert server.submit("q6", tenant="t2").result(timeout=30) == "q6"
        finally:
            server.shutdown()


# -- queue-depth shedding (graceful saturation) -------------------------------

class TestShedding:
    def test_non_priority_sheds_at_threshold_priority_continues(self, tmp_system_path):
        session = _session(tmp_system_path)
        started, release = threading.Event(), threading.Event()

        def blocking_run(plan):
            started.set()
            assert release.wait(30)
            return plan

        server = QueryServer(session, workers=1, max_queue_depth=8,
                             plan_cache=False, run_fn=blocking_run,
                             shed_depth_ratio=0.5)
        try:
            assert server.shed_depth == 4
            server.submit("head")
            assert started.wait(10)  # worker busy; queue empty
            for i in range(4):
                server.submit(f"q{i}")  # depth reaches the shed threshold
            with pytest.raises(AdmissionRejected, match="load shed"):
                server.submit("ordinary")
            # The priority lane keeps admitting up to the hard limit —
            # saturation degrades ordinary traffic first, never collapses.
            h = server.submit("urgent", priority=True)
            sat = server.saturation()
            assert sat["queue_depth"] == 5 and sat["shed_depth"] == 4
            release.set()
            assert h.result(timeout=30) == "urgent"
        finally:
            release.set()
            server.shutdown()


# -- shared caches (single process) -------------------------------------------

class TestSharedCaches:
    def test_result_roundtrip_with_strings_and_nulls(self, tmp_path, tmp_system_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        root = tmp_path / "nulls"
        root.mkdir()
        pq.write_table(pa.table({
            "id": pa.array([1, 2, 3, 4], type=pa.int64()),
            "key": pa.array([7, 7, 7, 8], type=pa.int64()),
            "name": pa.array(["a", None, "c", "d"]),
            "value": pa.array([1.5, None, 3.5, 4.5], type=pa.float64()),
        }), root / "p0.parquet")
        session = _session(tmp_system_path)
        df = session.parquet(root)
        q = df.filter(col("key") == 7).select("id", "key", "name", "value")
        serial = session.run(q)
        rc = SharedResultCache(tmp_path / "cache", max_bytes=1 << 20)
        key = rc.key(session, q)
        assert rc.get(key) is None
        assert rc.put(key, serial)
        out = rc.get(key)
        assert out is not None
        _assert_same(serial, out, "roundtrip")

    def test_refresh_changes_key_old_entry_unreachable(
        self, sample_parquet, tmp_system_path, tmp_path
    ):
        import pyarrow as pa
        import pyarrow.parquet as pq

        session = _session(tmp_system_path)
        hs = Hyperspace(session)
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("fl_idx", ["key"], ["value", "id"]))
        session.enable_hyperspace()
        q = df.filter(col("key") == 77).select("id", "key", "value")
        rc = SharedResultCache(tmp_path / "cache", max_bytes=1 << 20)
        k1 = rc.key(session, q)
        rc.put(k1, session.run(q))
        assert rc.get(k1) is not None
        extra = pa.table({
            "id": np.arange(20_000, 20_004, dtype=np.int64),
            "key": np.full(4, 77, dtype=np.int64),
            "value": np.linspace(0.0, 1.0, 4),
            "name": [f"l{i}" for i in range(4)],
        })
        pq.write_table(extra, f"{sample_parquet}/part-9.parquet")
        hs.refresh_index("fl_idx")
        k2 = rc.key(session, q)
        assert k2 != k1  # the stamp moved: pre-refresh entry unreachable
        assert rc.get(k2) is None

    def test_corrupt_entry_is_advisory_miss(self, sample_parquet, tmp_system_path, tmp_path):
        session = _session(tmp_system_path)
        df = session.parquet(sample_parquet)
        q = df.filter(col("key") == 5).select("id", "key")
        rc = SharedResultCache(tmp_path / "cache", max_bytes=1 << 20)
        key = rc.key(session, q)
        rc.put(key, session.run(q))
        rc.entry_path(key).write_bytes(b"garbage not arrow")
        e0 = stats.get("fleet.shared_cache.errors")
        assert rc.get(key) is None  # miss, not a failed query
        assert stats.get("fleet.shared_cache.errors") == e0 + 1

    def test_oversized_result_never_admitted(self, sample_parquet, tmp_system_path, tmp_path):
        session = _session(tmp_system_path)
        df = session.parquet(sample_parquet)
        q = df.select("id", "key", "value", "name")
        rc = SharedResultCache(tmp_path / "cache", max_bytes=64)  # everything too big
        key = rc.key(session, q)
        assert rc.put(key, session.run(q)) is False
        assert rc.stats()["entries"] == 0

    def test_eviction_under_lease_respects_budget(self, tmp_path, tmp_system_path):
        session = _session(tmp_system_path)
        import pyarrow as pa
        import pyarrow.parquet as pq

        root = tmp_path / "d"
        root.mkdir()
        pq.write_table(pa.table({
            "id": pa.array(np.arange(64, dtype=np.int64)),
            "key": pa.array(np.arange(64, dtype=np.int64) % 8),
        }), root / "p0.parquet")
        df = session.parquet(root)
        serial = session.run(df.filter(col("key") == 1).select("id", "key"))
        entry_bytes = None
        rc = SharedResultCache(tmp_path / "cache", max_bytes=1 << 30)
        # Size one entry, then rebuild the cache with a budget of ~3 entries.
        rc.put(("probe",), serial)
        entry_bytes = rc.stats()["bytes"]
        rc.clear()
        rc = SharedResultCache(tmp_path / "cache", max_bytes=int(entry_bytes * 3.5))
        for i in range(6):
            assert rc.put(("k", i), serial)
            time.sleep(0.02)  # distinct mtimes for deterministic LRU order
        st = rc.stats()
        assert st["bytes"] <= rc.max_bytes
        assert st["entries"] < 6
        assert stats.get("fleet.shared_cache.evictions") > 0
        # The newest entries survive (oldest-mtime eviction).
        assert rc.get(("k", 5)) is not None

    def test_plan_cache_shared_across_servers(self, sample_parquet, tmp_system_path):
        session = _session(tmp_system_path)
        hs = Hyperspace(session)
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("fl_idx2", ["key"], ["value"]))
        session.enable_hyperspace()
        q = df.filter(col("key") == 3).select("key", "value")
        plans, results = fleet.shared_caches(session)
        with session.serve(workers=1, plan_cache=plans, result_cache=False) as server:
            server.submit(q).result(timeout=300)
        h0 = stats.get("fleet.shared_cache.hits")
        # A SECOND server (fresh process stand-in) hits the disk entry.
        with session.serve(workers=1, plan_cache=plans, result_cache=False) as server:
            server.submit(q).result(timeout=300)
        assert stats.get("fleet.shared_cache.hits") > h0


# -- real multi-process proofs ------------------------------------------------

def _mp_ctx():
    import multiprocessing as mp

    return mp.get_context("spawn")


def _cache_worker(ctx, data_root, system_path, cmd_q, out_q):
    """Fleet member: serve one point query over the shared store through
    the shared caches, reporting (ids, shared hit count, port)."""
    from hyperspace_tpu import HyperspaceSession
    from hyperspace_tpu import col as _col
    from hyperspace_tpu import stats as _stats
    from hyperspace_tpu.serve import fleet as _fleet

    session = HyperspaceSession(system_path=system_path)
    session.conf.set("hyperspace.obs.http.enabled", "true")  # port=0 default
    session.enable_hyperspace()
    df = session.parquet(data_root)
    q = df.filter(_col("key") == 7).select("id", "key", "value")
    plans, results = _fleet.shared_caches(session)
    with session.serve(workers=1, plan_cache=plans, result_cache=results) as server:
        endpoint = server.health_endpoint
        _fleet.register_worker(ctx.fleet_dir, ctx.worker_id, endpoint.port)
        import queue as _queue

        while not ctx.stop_event.is_set():
            try:
                cmd = cmd_q.get(timeout=0.2)
            except _queue.Empty:
                continue
            if cmd == "stop":
                break
            out = server.submit(q).result(timeout=300)
            import numpy as _np

            ids = sorted(_np.asarray(out.decode()["id"]).tolist())
            out_q.put({
                "ids": ids,
                "shared_hits": _stats.get("fleet.shared_cache.hits"),
                "port": endpoint.port,
            })


class TestMultiProcessFleet:
    def test_cross_process_invalidation_and_port_discovery(self, tmp_path):
        """The promoted staleness proof: process A (this one) runs
        refresh(); process B must never serve a pre-refresh cached
        result — the versioned key it computes AFTER the refresh commit
        embeds the new log id, so A's published entries are simply
        unreachable from B. Also proves ephemeral-port discovery: B
        binds port=0 and registers the real port in the fleet dir."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        data = tmp_path / "data"
        data.mkdir()
        rng = np.random.default_rng(3)
        pq.write_table(pa.table({
            "id": pa.array(np.arange(400, dtype=np.int64)),
            "key": pa.array(rng.integers(0, 16, 400, dtype=np.int64)),
            "value": pa.array(rng.standard_normal(400)),
        }), data / "p0.parquet")
        system_path = str(tmp_path / "indexes")
        session = _session(system_path)
        hs = Hyperspace(session)
        df = session.parquet(data)
        hs.create_index(df, IndexConfig("mp_idx", ["key"], ["value", "id"]))
        session.enable_hyperspace()
        q = df.filter(col("key") == 7).select("id", "key", "value")

        # Process A warms the SHARED result cache with the pre-refresh rows.
        plans, results = fleet.shared_caches(session)
        with session.serve(workers=1, plan_cache=plans, result_cache=results) as server:
            pre = server.submit(q).result(timeout=300)
        pre_ids = sorted(np.asarray(pre.decode()["id"]).tolist())

        ctx = _mp_ctx()
        cmd_q, out_q = ctx.Queue(), ctx.Queue()
        sup = fleet.FleetSupervisor(
            _cache_worker, fleet_dir=str(tmp_path / "fleet"), n=1,
            args=(str(data), system_path, cmd_q, out_q), max_restarts=0,
        )
        sup.start()
        try:
            cmd_q.put("query")
            first = out_q.get(timeout=180)
            assert first["ids"] == pre_ids
            # B served A's published entry (shared cache crossed the
            # process boundary) — plan or result hit, either proves it.
            assert first["shared_hits"] >= 1
            assert first["port"] and first["port"] > 0

            # Port discovery + fleet aggregation over the real socket.
            health = sup.fleet_health()
            assert health["members"][0]["port"] == first["port"]
            assert health["members"][0]["status"] in ("ok", "degraded")
            assert health["saturation"]["workers"] >= 1

            # A's world change: append rows with key=7, refresh.
            extra = pa.table({
                "id": np.arange(10_000, 10_006, dtype=np.int64),
                "key": np.full(6, 7, dtype=np.int64),
                "value": np.linspace(0.0, 1.0, 6),
            })
            pq.write_table(extra, data / "p1.parquet")
            hs.refresh_index("mp_idx")
            post = session.run(q)
            post_ids = sorted(np.asarray(post.decode()["id"]).tolist())
            assert set(post_ids) >= set(pre_ids) | {10_000, 10_005}

            # B, queried AFTER the commit, must see the new world — its
            # key embeds the bumped log id; the stale entry cannot hit.
            cmd_q.put("query")
            second = out_q.get(timeout=180)
            assert second["ids"] == post_ids
            cmd_q.put("stop")
        finally:
            sup.stop(timeout=60)

    def test_sigkilled_singleflight_holder_is_taken_over(self, tmp_path):
        """A SIGKILLed lease holder gets no cleanup; the next claimant
        must reap its lease after the TTL and run the build — the
        crashed-holder-never-wedges-the-fleet guarantee."""
        ctx = _mp_ctx()
        ready = ctx.Queue()
        p = ctx.Process(
            target=_lease_holder, args=(str(tmp_path / "sf"), "hot-key", ready)
        )
        p.start()
        try:
            assert ready.get(timeout=120) == "held"
            os.kill(p.pid, signal.SIGKILL)
            p.join(timeout=30)
            time.sleep(0.7)  # let the dead holder's epoch go stale (ttl 0.5)
            sf = SingleFlight(tmp_path / "sf", lease_ttl_s=0.5, wait_s=10)
            t0 = stats.get("fleet.singleflight.takeovers")
            out = sf.run("hot-key", build=lambda: "recovered", check=lambda: None)
            assert out == "recovered"
            assert stats.get("fleet.singleflight.takeovers") == t0 + 1
        finally:
            if p.is_alive():
                p.terminate()

    def test_supervisor_restarts_crashed_worker(self, tmp_path):
        marker = tmp_path / "attempts"
        marker.mkdir()
        sup = fleet.FleetSupervisor(
            _crasher, fleet_dir=str(tmp_path / "fleet"), n=1,
            args=(str(marker),), max_restarts=1,
        )
        r0 = stats.get("fleet.supervisor.restarts")
        sup.start()
        try:
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                if sup.restarts().get(0, 0) >= 1 and sup.alive_count() == 0:
                    break
                time.sleep(0.2)
            assert sup.restarts().get(0, 0) == 1  # budget spent, slot left down
            assert len(list(marker.iterdir())) == 2  # original + one respawn
            assert stats.get("fleet.supervisor.restarts") == r0 + 1
        finally:
            sup.stop(timeout=30)

    def test_crash_loop_backs_off_instead_of_burning_budget(self, tmp_path):
        """A crash-looping member must not spend its whole maxRestarts
        budget in milliseconds: the first respawn is immediate, repeat
        respawns of the SAME member wait out an exponential backoff
        (announced by a WARN fleet.worker.crash_loop event naming the
        member and its delay)."""
        from hyperspace_tpu.obs import events

        marker = tmp_path / "attempts"
        marker.mkdir()
        sup = fleet.FleetSupervisor(
            _crasher, fleet_dir=str(tmp_path / "fleet"), n=1,
            args=(str(marker),), max_restarts=3, restart_backoff=0.4,
        )
        sup.start()
        try:
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                if sup.restarts().get(0, 0) >= 3 and sup.alive_count() == 0:
                    break
                time.sleep(0.2)
            assert sup.restarts().get(0, 0) == 3
        finally:
            sup.stop(timeout=30)
        loops = [e for e in events.recent() if e["name"] == "fleet.worker.crash_loop"]
        restarted = [e for e in events.recent() if e["name"] == "fleet.worker.restarted"]
        # respawns 2 and 3 each engaged a backoff window first
        assert len(loops) == 2 and len(restarted) == 3
        assert all(e["severity"] == "warn" for e in loops)
        assert all(e["fields"]["worker_id"] == 0 for e in loops)
        delays = [e["fields"]["delay_s"] for e in loops]
        assert 0.4 <= delays[0] <= 0.5  # base x (1 + jitter<0.25)
        assert 0.8 <= delays[1] <= 1.0  # base x 2 x (1 + jitter)
        # the scheduled delay was actually waited out: the respawn event
        # lands no earlier than crash_loop + delay
        for loop in loops:
            after = min(
                (e for e in restarted if e["seq"] > loop["seq"]),
                key=lambda e: e["seq"],
            )
            assert after["ts"] - loop["ts"] >= loop["fields"]["delay_s"] - 0.05


def _lease_holder(sf_dir, name, ready_q):
    """Child: take the single-flight lease for `name` and hang until
    killed (the crashed-holder simulation)."""
    from pathlib import Path

    from hyperspace_tpu.serve.fleet.lease import FileLease
    from hyperspace_tpu.serve.fleet.singleflight import key_name as _kn

    lease = FileLease(Path(sf_dir) / f"{_kn(name)}.lease", ttl_s=300)
    claim = lease.try_acquire()
    ready_q.put("held" if claim is not None else "failed")
    time.sleep(300)


def _crasher(ctx, marker_dir):
    """Child: record the attempt, then die with a non-zero exit."""
    from pathlib import Path

    Path(marker_dir, f"pid-{os.getpid()}").write_text("x")
    raise SystemExit(3)


def _fault_probe(ctx, marker_dir):
    """Child: report whether the coordinator's registered fault rule
    fired INSIDE this spawned fleet worker (fresh module state — the
    rule can only be here if the supervisor shipped it)."""
    from pathlib import Path

    from hyperspace_tpu import faults

    try:
        faults.fault_point("fleet.lease.acquire", "probe")
        out = "no-fault"
    except faults.FaultError:
        out = "fault-fired"
    Path(marker_dir, f"{ctx.worker_id}.txt").write_text(out)


class TestSupervisorFaultContinuity:
    def test_fault_rules_ship_into_fleet_workers(self, tmp_path):
        """The HSL022 contract at runtime (the fleet half of procpool's
        cross-process injection test): a rule registered in the
        coordinator fires inside a spawned fleet worker because
        FleetSupervisor ships faults.export_state() through the worker
        shim."""
        from hyperspace_tpu import faults

        marker = tmp_path / "probe"
        marker.mkdir()
        faults.inject("fleet.lease.acquire", times=1)
        try:
            sup = fleet.FleetSupervisor(
                _fault_probe, fleet_dir=str(tmp_path / "fleet"), n=1,
                args=(str(marker),), max_restarts=0,
            )
            with sup:
                sup.start()
                deadline = time.monotonic() + 60
                out = marker / "0.txt"
                while not out.exists() and time.monotonic() < deadline:
                    time.sleep(0.05)
        finally:
            faults.reset()
        assert out.read_text() == "fault-fired"

    def test_export_state_carries_brownout_schedule(self):
        """The spawn-shipping contract covers the slow path too: a
        delay rule's full brownout schedule (delay, jitter, the
        configured clamp) survives export_state -> install_state, with
        fresh per-process call counters."""
        from hyperspace_tpu import faults

        faults.inject("bucket.read", delay_s=0.25, jitter_s=0.05, times=3)
        faults.set_max_delay(12.0)
        try:
            state = faults.export_state()
            (rule,) = state["rules"]
            assert rule.delay_s == 0.25 and rule.jitter_s == 0.05
            assert rule.calls == 0 and rule.fired == 0  # fresh schedule
            assert state["max_delay_s"] == 12.0
            # a "worker": install and verify the delay actually applies
            faults.reset()
            faults.install_state(state)
            slept = []
            faults.set_sleeper(slept.append)
            faults.fault_point("bucket.read")
            assert sum(slept) == pytest.approx(
                0.25 + 0.05 * ((1 * 2654435761) % 1000) / 1000.0
            )
        finally:
            faults.set_max_delay(30.0)
            faults.reset()


# -- obs/http port=0 satellite ------------------------------------------------

class TestEphemeralHealthPort:
    def test_healthz_reports_bound_port(self):
        from hyperspace_tpu.obs.http import HealthServer

        hs = HealthServer(port=0).start()
        try:
            assert hs.port and hs.port > 0  # kernel-picked ephemeral port
            doc = hs.healthz()
            assert doc["endpoint"] == {"host": "127.0.0.1", "port": hs.port}
        finally:
            hs.stop()

    def test_two_servers_two_ports_one_host(self):
        """The reason port=0 is the fleet default: two health planes on
        one host never fight over a configured port."""
        from hyperspace_tpu.obs.http import HealthServer

        a = HealthServer(port=0).start()
        b = HealthServer(port=0).start()
        try:
            assert a.port != b.port
        finally:
            a.stop()
            b.stop()


def _journaling_member(ctx):
    """Child: journal root spans forever (the supervisor shipped the
    parent's journal config in via env, so this member writes its own
    `<_obs>/<pid>/` segments) until stopped or killed."""
    from hyperspace_tpu.obs import trace as _trace

    i = 0
    while not ctx.stop_event.is_set():
        with _trace.trace("member.query") as _:
            i += 1
        time.sleep(0.005)


class TestFleetJournal:
    def test_sigkilled_member_journal_merges_into_fleet_chrome_trace(
        self, tmp_path
    ):
        """The flight-recorder promise end to end: a fleet member dies by
        a REAL SIGKILL mid-write, and its durable journal segments still
        merge into the fleet chrome trace on a pid-qualified lane —
        post-mortem observability does not require the process."""
        from hyperspace_tpu.obs import export as obs_export
        from hyperspace_tpu.obs import journal

        jroot = tmp_path / "_obs"
        # Small segments so the member seals quickly; the supervisor
        # ships this exact config into the spawned member.
        journal.configure(
            enabled=True, root=str(jroot), segment_bytes=4096
        )
        sup = fleet.FleetSupervisor(
            _journaling_member, fleet_dir=str(tmp_path / "fleet"), n=1,
            max_restarts=0,
        )
        sup.start()
        pid = None
        try:
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                p = sup._host.get(0)
                if p is not None and p.pid is not None:
                    pid = p.pid
                    if journal.segment_paths(jroot / str(pid)):
                        break  # at least one sealed segment on disk
                time.sleep(0.05)
            assert pid is not None and journal.segment_paths(jroot / str(pid))
            os.kill(pid, signal.SIGKILL)  # no cleanup handlers run
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and sup.alive_count() > 0:
                time.sleep(0.05)
            assert sup.alive_count() == 0
        finally:
            sup.stop(timeout=30)
        # The dead member's sealed history survives and merges: a
        # `process` start marker (install_state) and its root spans.
        merged = journal.merge_dir(jroot)
        member_recs = [r for r in merged if r["pid"] == pid]
        assert any(r["kind"] == "process" for r in member_recs)
        spans = [r for r in member_recs if r["kind"] == "span"]
        assert spans and all(
            r["trace"]["name"] == "member.query" for r in spans
        )
        # Fleet chrome export lanes the dead member by pid.
        doc = obs_export.chrome_trace(obs_export.roots_from_fleet(str(jroot)))
        slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["pid"] for e in slices} >= {pid}
        names = [
            e["args"]["name"] for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        ]
        assert f"member pid {pid}" in names
        # The kill tore at most the active tmp tail; sweep reaps it
        # without touching sealed history.
        before = journal.merge_dir(jroot)
        journal.sweep(jroot)
        assert journal.merge_dir(jroot) == before
        assert not [
            p for p in (jroot / str(pid)).iterdir()
            if p.name.startswith(".tmp-seg-")
        ]
