"""Device-plane kernel tests on the 8-device CPU mesh: hashing parity,
bucketize exchange, lex sort, merge join."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperspace_tpu.ops.bucketize import bucketize
from hyperspace_tpu.ops.hashing import bucket_ids, combine_hashes, hash_int_column, string_dict_hashes
from hyperspace_tpu.ops import join as join_ops
from hyperspace_tpu.parallel.mesh import make_mesh


def test_host_device_hash_parity():
    # Device lanes are 32-bit native (no x64 flag anywhere): device-side
    # hashing covers 32-bit dtypes; 64-bit hashing is host-only (builder
    # computes row hashes with numpy before upload).
    rng = np.random.default_rng(0)
    for dtype in (np.int32, np.float32):
        arr = rng.integers(-1000, 1000, 256).astype(dtype)
        h_host = hash_int_column(arr, np)
        h_dev = np.asarray(hash_int_column(jnp.asarray(arr), jnp))
        np.testing.assert_array_equal(h_host, h_dev, err_msg=str(dtype))


def test_string_hash_dictionary_independent():
    d1 = np.array(["a", "b", "c"], dtype=object)
    d2 = np.array(["b", "c", "z"], dtype=object)
    h1 = string_dict_hashes(d1)
    h2 = string_dict_hashes(d2)
    # same strings hash identically regardless of dictionary membership
    assert h1[1] == h2[0] and h1[2] == h2[1]
    assert len({h1[0], h1[1], h1[2]}) == 3


def test_combine_order_dependent():
    a = np.array([1, 2], np.uint32)
    b = np.array([3, 4], np.uint32)
    assert not np.array_equal(combine_hashes([a, b], np), combine_hashes([b, a], np))


def test_bucketize_preserves_rows_and_ownership():
    mesh = make_mesh()
    d = mesh.shape["x"]
    assert d == 8, "tests expect the 8-device CPU mesh from conftest"
    rng = np.random.default_rng(1)
    n, num_buckets = 4096, 32
    keys = rng.integers(0, 5000, n).astype(np.int32)
    vals = rng.standard_normal(n).astype(np.float32)
    bucket = bucket_ids(hash_int_column(keys, np), num_buckets, np)
    valid = np.ones(n, np.int32)
    out_cols, out_bucket, out_valid = bucketize(
        mesh, [jnp.asarray(keys), jnp.asarray(vals)], jnp.asarray(bucket), jnp.asarray(valid), num_buckets
    )
    ob = np.asarray(out_bucket)
    ov = np.asarray(out_valid)
    ok = np.asarray(out_cols[0])
    oval = np.asarray(out_cols[1])
    real = ov > 0
    assert real.sum() == n
    # Ownership: device i's segment only holds its bucket range.
    bpd = num_buckets // d
    seg = len(ob) // d
    for i in range(d):
        s = slice(i * seg, (i + 1) * seg)
        bs = ob[s][ov[s] > 0]
        assert (bs // bpd == i).all()
    # No data loss/corruption.
    assert sorted(zip(keys.tolist(), vals.tolist())) == sorted(zip(ok[real].tolist(), oval[real].tolist()))


def test_bucketize_skew_retry():
    """All rows hash to one bucket — exercises the overflow-retry path."""
    mesh = make_mesh()
    n, num_buckets = 512, 8
    keys = np.full(n, 42, np.int32)
    bucket = bucket_ids(hash_int_column(keys, np), num_buckets, np)
    out_cols, out_bucket, out_valid = bucketize(
        mesh, [jnp.asarray(keys)], jnp.asarray(bucket), jnp.asarray(np.ones(n, np.int32)), num_buckets,
        capacity_factor=0.25,
    )
    assert (np.asarray(out_valid) > 0).sum() == n


def test_merge_join_kernel():
    # bucket 0: left [1,1,2,5], right [1,2,2,7] → matches: 1x1*2, 2x2*2 = 4
    S = join_ops.SENTINEL
    lk = np.array([[1, 1, 2, 5], [10, 20, S, S]], dtype=np.int64)
    rk = np.array([[1, 2, 2, 7], [20, 20, 30, S]], dtype=np.int64)
    li, ri, totals = join_ops.merge_join(lk, rk)
    # bucket 0: (0,0),(1,0),(2,1),(2,2); bucket 1: (1,0),(1,1)
    assert totals.tolist() == [4, 2]
    got0 = sorted(zip(li[:4].tolist(), ri[:4].tolist()))
    got1 = sorted(zip(li[4:6].tolist(), ri[4:6].tolist()))
    assert got0 == [(0, 0), (1, 0), (2, 1), (2, 2)]
    assert got1 == [(1, 0), (1, 1)]


def test_merge_join_empty():
    S = join_ops.SENTINEL
    lk = np.full((2, 3), S, dtype=np.int64)
    rk = np.full((2, 4), S, dtype=np.int64)
    li, ri, totals = join_ops.merge_join(lk, rk)
    assert totals.sum() == 0 and len(li) == 0 and len(ri) == 0


def test_merge_join_wide_bucket_unpacked_path():
    """Bucket width >= 2^16 takes the non-pack16 download branch."""
    rng = np.random.default_rng(3)
    w = 70_000
    lvals = np.sort(rng.integers(0, 50_000, w)).astype(np.int32)
    rvals = np.sort(rng.integers(0, 50_000, w)).astype(np.int32)
    li, ri, totals = join_ops.merge_join(lvals[None, :], rvals[None, :])
    # verify against a host-side expansion
    import pandas as pd

    expected = pd.merge(
        pd.DataFrame({"k": lvals, "li": np.arange(w)}),
        pd.DataFrame({"k": rvals, "ri": np.arange(w)}),
        on="k",
    )
    assert totals.sum() == len(expected)
    got = set(zip(li.tolist(), ri.tolist()))
    want = set(zip(expected["li"].tolist(), expected["ri"].tolist()))
    assert got == want


def test_multi_key_join_rerank_path_equality():
    """Three key columns with cardinalities whose product exceeds int32 —
    exercises the executor's int32 re-rank of mixed-radix codes."""
    import pandas as pd

    from hyperspace_tpu.execution.executor import _factorize_keys
    from hyperspace_tpu.execution.table import ColumnTable
    from hyperspace_tpu.schema import Field, Schema

    rng = np.random.default_rng(4)
    n = 2000
    schema = Schema.of(Field("a", "int64"), Field("b", "int64"), Field("c", "int64"))

    def tbl(seed):
        r = np.random.default_rng(seed)
        return ColumnTable(
            schema,
            {
                "a": r.integers(0, 1400, n).astype(np.int64),
                "b": r.integers(0, 1400, n).astype(np.int64),
                "c": r.integers(0, 1400, n).astype(np.int64),
            },
            {},
        )

    lt, rt = tbl(1), tbl(2)
    lcodes, rcodes = _factorize_keys([lt], [rt], ["a", "b", "c"], ["a", "b", "c"])
    assert lcodes[0].dtype == np.int32 and rcodes[0].dtype == np.int32
    # code equality ⇔ full key-tuple equality
    ldf = pd.DataFrame({k: lt.columns[k] for k in ("a", "b", "c")})
    rdf = pd.DataFrame({k: rt.columns[k] for k in ("a", "b", "c")})
    merged = pd.merge(ldf.assign(lc=lcodes[0]), rdf.assign(rc=rcodes[0]), on=["a", "b", "c"])
    assert (merged["lc"] == merged["rc"]).all()
    # codes must also be order-preserving within the shared space
    order = np.argsort(lcodes[0], kind="stable")
    sorted_tuples = list(zip(*(lt.columns[k][order] for k in ("a", "b", "c"))))
    assert sorted_tuples == sorted(sorted_tuples)


def test_multislice_mesh_build_matches_single_axis():
    """(dcn, x) multi-slice mesh: the exchange over combined axes must
    produce the same per-bucket contents as the 1-D ICI mesh."""
    import tempfile
    from pathlib import Path

    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.dataset import Dataset
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.execution.builder import DeviceIndexBuilder
    from hyperspace_tpu.parallel.mesh import make_mesh, make_multislice_mesh

    tmp = Path(tempfile.mkdtemp())
    data = tmp / "d"
    data.mkdir()
    rng = np.random.default_rng(0)
    n = 2048
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 500, n).astype(np.int64),
                "v": rng.standard_normal(n),
            }
        ),
        data / "p.parquet",
    )
    ds = Dataset.parquet(data)
    d1 = tmp / "idx1" / "v__=0"
    d2 = tmp / "idx2" / "v__=0"
    DeviceIndexBuilder(mesh=make_mesh()).write(ds.scan(), ["k", "v"], ["k"], 16, d1)
    DeviceIndexBuilder(mesh=make_multislice_mesh(2)).write(ds.scan(), ["k", "v"], ["k"], 16, d2)
    m1, m2 = hio.read_manifest(d1), hio.read_manifest(d2)
    assert m1["bucketRows"] == m2["bucketRows"]
    for b in range(16):
        t1 = hio.read_parquet([str(d1 / hio.bucket_file_name(b))])
        t2 = hio.read_parquet([str(d2 / hio.bucket_file_name(b))])
        assert np.array_equal(np.sort(t1.columns["k"]), np.sort(t2.columns["k"]))


def test_merge_join_sharded_matches_single_device():
    """The bucket-sharded distributed SMJ must emit exactly the same match
    set as the single-device kernel, for both the pack16 and wide paths."""
    from hyperspace_tpu.ops import join as join_ops
    from hyperspace_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    rng = np.random.default_rng(7)
    # Second case: 17+16 index bits > 32 forces the UNPACKED (interleaved)
    # sharded output path.
    for L, R in [(64, 96), (1 << 17, 1 << 16)]:
        B = 16
        s = join_ops.sentinel_for(np.int32)
        lk = np.full((B, L), s, np.int32)
        rk = np.full((B, R), s, np.int32)
        for b in range(B):
            nl, nr = rng.integers(1, min(L, 64)), rng.integers(1, min(R, 64))
            lk[b, :nl] = np.sort(rng.integers(0, 40, nl)).astype(np.int32)
            rk[b, :nr] = np.sort(rng.integers(0, 40, nr)).astype(np.int32)
        li1, ri1, t1 = join_ops.merge_join(lk, rk)
        li2, ri2, t2 = join_ops.merge_join_sharded(lk, rk, mesh)
        assert np.array_equal(t1, t2)
        # Match pairs per bucket must agree as sets.
        o1 = np.concatenate([[0], np.cumsum(t1)])
        for b in range(B):
            p1 = set(zip(li1[o1[b]:o1[b+1]].tolist(), ri1[o1[b]:o1[b+1]].tolist()))
            p2 = set(zip(li2[o1[b]:o1[b+1]].tolist(), ri2[o1[b]:o1[b+1]].tolist()))
            assert p1 == p2


def test_e2e_join_distributed_on_mesh(tmp_path):
    """Full query path with a session mesh: the rewritten join must run
    bucket-sharded over all 8 virtual devices and match the un-indexed
    result row-for-row (the device kernel is the subject — pinned
    explicitly so a HYPERSPACE_VENUE=host sweep does not reroute it)."""
    from hyperspace_tpu.config import JOIN_VENUE
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
    from hyperspace_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(3)
    n = 4000
    fact_root = tmp_path / "fact"
    fact_root.mkdir()
    pq.write_table(
        pa.table({
            "k": rng.integers(0, 200, n).astype(np.int64),
            "v": rng.standard_normal(n),
        }),
        fact_root / "f.parquet",
    )
    dim_root = tmp_path / "dim"
    dim_root.mkdir()
    pq.write_table(
        pa.table({
            "k": np.arange(200, dtype=np.int64),
            "label": pa.array([f"l{i % 5}" for i in range(200)]),
        }),
        dim_root / "d.parquet",
    )
    session = HyperspaceSession(
        system_path=str(tmp_path / "idx"), num_buckets=16, mesh=make_mesh()
    )
    session.conf.set(JOIN_VENUE, "device")
    hs = Hyperspace(session)
    fact = session.parquet(fact_root)
    dim = session.parquet(dim_root)
    hs.create_index(fact, IndexConfig("f_k", ["k"], ["v"]))
    hs.create_index(dim, IndexConfig("d_k", ["k"], ["label"]))
    q = fact.select("k", "v").join(dim.select("k", "label"), ["k"])

    session.disable_hyperspace()
    expected = session.to_pandas(q).sort_values(["k", "v"]).reset_index(drop=True)
    session.enable_hyperspace()
    got = session.to_pandas(q).sort_values(["k", "v"]).reset_index(drop=True)
    stats = session.last_query_stats
    assert stats["join_path"] == "zero-exchange-aligned"
    assert stats["join_devices"] == 8
    assert got.equals(expected[got.columns.tolist()])


def test_mesh_distributed_top_n_matches_host(tmp_path):
    """ORDER BY ... LIMIT n over an 8-device mesh: per-shard first-n
    selection + threshold mask must match the single-device result
    exactly (ties included)."""
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import HyperspaceSession
    from hyperspace_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(41)
    n = 200_000
    df = pd.DataFrame(
        {
            "v": np.round(rng.normal(size=n), 2),  # heavy ties
            "tag": rng.integers(0, 1000, n).astype(np.int64),
        }
    )
    root = tmp_path / "topn"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")

    outs = {}
    for mesh in (None, make_mesh()):
        session = HyperspaceSession(
            system_path=str(tmp_path / f"idx_{mesh is None}"), num_buckets=4, mesh=mesh
        )
        if mesh is not None:
            # Pin the venue: the assertion below is about the device
            # kernel, and must hold under a HYPERSPACE_VENUE=host sweep.
            from hyperspace_tpu.config import SORT_VENUE

            session.conf.set(SORT_VENUE, "device")
        ds = session.parquet(root)
        q = ds.sort([("v", False), ("tag", True)]).limit(25)
        outs[mesh is None] = session.to_pandas(q).reset_index(drop=True)
        if mesh is not None:
            plan = repr(session.last_physical_plan)
            assert "mesh-sharded-select" in plan, plan
    pd.testing.assert_frame_equal(outs[True], outs[False])
    exp = (
        df.sort_values(["v", "tag"], ascending=[False, True]).head(25).reset_index(drop=True)
    )
    np.testing.assert_allclose(outs[False]["v"], exp["v"])
    np.testing.assert_array_equal(outs[False]["tag"], exp["tag"])
