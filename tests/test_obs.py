"""Observability plane: tracer spans, metrics registry, per-query
profiles, EXPLAIN ANALYZE, sink export, and the fault-plane interplay
(spans must survive — and record — injected faults and crashes).
See docs/observability.md."""

import json
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, faults, stats
from hyperspace_tpu.obs import metrics, trace
from hyperspace_tpu.obs.export import registry_from_sink, render_prometheus


@pytest.fixture
def tables(tmp_path):
    rng = np.random.default_rng(11)
    n = 5_000
    fact = pd.DataFrame(
        {
            "k": rng.integers(0, 100, n).astype(np.int64),
            "v": rng.normal(size=n).round(4),
        }
    )
    dim = pd.DataFrame(
        {
            "k": np.arange(100, dtype=np.int64),
            "g": (np.arange(100) % 7).astype(np.int64),
        }
    )
    for name, df in (("fact", fact), ("dim", dim)):
        (tmp_path / name).mkdir()
        pq.write_table(
            pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet"
        )
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    f = session.parquet(tmp_path / "fact")
    d = session.parquet(tmp_path / "dim")
    hs.create_index(f, IndexConfig("f_k", ["k"], ["v"]))
    session.enable_hyperspace()
    return session, hs, f, d, fact, dim


# -- tracer basics ---------------------------------------------------------


def test_span_nesting_and_attrs():
    with trace.trace("root") as root:
        with trace.span("a", x=1):
            with trace.span("a.b") as inner:
                inner.set(rows=7)
            trace.event("tick", n=1)
        with trace.span("c"):
            pass
    assert [c.name for c in root.children] == ["a", "c"]
    a = root.children[0]
    assert [c.name for c in a.children] == ["a.b"]
    assert a.children[0].attrs == {"rows": 7}
    assert a.events == [{"name": "tick", "n": 1}]
    assert all(s.wall_s is not None and s.wall_s >= 0 for s in root.walk())
    # self time never exceeds wall time, and the tree telescopes to root.
    assert sum(s.self_s() for s in root.walk()) == pytest.approx(root.wall_s, rel=0.02)
    assert trace.last_trace() is root


def test_untraced_spans_are_noops():
    # No enclosing trace ⇒ the shared no-op singleton, nothing recorded.
    assert trace.span("orphan") is trace.NOOP
    trace.event("orphan-event")  # must not raise
    assert trace.last_trace() is None


def test_disabled_mode_allocates_nothing():
    trace.set_enabled(False)
    assert trace.span("x") is trace.NOOP
    with trace.trace("t") as root:
        assert root is trace.NOOP
        assert trace.span("y") is trace.NOOP
    assert trace.last_trace() is None


def test_worker_threads_inherit_active_span():
    with trace.trace("root") as root:
        with trace.span("parent"):

            def task(i):
                with trace.span(f"child-{i}"):
                    return i

            with ThreadPoolExecutor(max_workers=4) as ex:
                assert sorted(ex.map(trace.wrap(task), range(4))) == [0, 1, 2, 3]
    parent = root.children[0]
    assert sorted(c.name for c in parent.children) == [f"child-{i}" for i in range(4)]


# -- metrics registry ------------------------------------------------------


def test_undeclared_counter_raises():
    with pytest.raises(KeyError, match="retyr.attempts"):
        stats.increment("retyr.attempts")  # noqa: HSL007 — the typo under test
    stats.increment("retry.attempts")
    assert stats.get("retry.attempts") == 1
    assert stats.snapshot()["retry.attempts"] == 1
    stats.reset()
    assert stats.get("retry.attempts") == 0


def test_histogram_percentiles_bounded():
    h = metrics.Histogram("t", buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0))
    for v in range(1, 101):
        h.observe(float(v))
    assert h.count == 100
    assert h.sum == pytest.approx(5050.0)
    p = h.percentiles()
    # Bucket interpolation: coarse but order-correct and bounded.
    assert 30 <= p["p50"] <= 70
    assert p["p95"] >= p["p50"]
    assert p["p99"] <= 100.0
    h._reset()
    assert h.count == 0 and h.quantile(0.5) is None


def test_registry_kind_conflict_raises():
    metrics.REGISTRY.counter("obs_test.metric")
    with pytest.raises(ValueError, match="already declared"):
        metrics.REGISTRY.gauge("obs_test.metric")


# -- per-query profiles ----------------------------------------------------


def test_filter_query_profile(tables):
    from hyperspace_tpu.execution import io as hio

    session, hs, f, d, fact, dim = tables
    hio.clear_table_cache()  # cold read: files/bytes evidence must appear
    q = f.filter(col("k") == 7).select("k", "v")
    res = session.run(q)
    prof = session.last_profile()
    assert prof is not None and prof is session.last_profile()
    ops = {o.op: o for o in prof.operators()}
    assert "IndexPointLookup" in ops
    lookup = ops["IndexPointLookup"]
    assert lookup.rows_out == res.num_rows == int((fact.k == 7).sum())
    assert lookup.detail["files"] == 1  # bucket-pruned point lookup
    assert lookup.detail["bytes"] > 0
    assert prof.stats["bytes_scanned"] > 0
    # Wall-time attribution: the tree telescopes (self times sum to the
    # root frame) and the root frame fits inside the end-to-end total.
    assert prof.root.wall_s > 0
    assert prof.operator_total_s() == pytest.approx(prof.root.wall_s, rel=0.05)
    assert prof.root.wall_s <= prof.total_s
    assert prof.venue["platform"] == "cpu"
    assert prof.cache["table_misses"] >= 1
    assert prof.fallback == {"replans": 0, "degraded_indexes": [], "used_indexes": True}
    # Span tree mirrors the physical tree and carries the rule phase.
    names = [s["name"] for s in _walk(prof.trace)]
    assert "plan.optimize" in names
    assert any(n.startswith("rule.") for n in names)
    assert "execute.IndexPointLookup" in names


def test_join_query_profile(tables):
    session, hs, f, d, fact, dim = tables
    res = session.run(f.join(d, ["k"]))
    prof = session.last_profile()
    joins = [o for o in prof.operators() if "Join" in o.op]
    assert joins, [o.op for o in prof.operators()]
    root = prof.root
    assert root.rows_out == res.num_rows == len(fact.merge(dim, on="k"))
    # rows_in = children's rows_out: both sides feed the join.
    assert joins[0].rows_in == len(fact) + len(dim)
    assert prof.operator_total_s() == pytest.approx(prof.root.wall_s, rel=0.05)
    assert prof.stats["join_path"] is not None


def test_profile_available_with_tracing_disabled(tables):
    session, hs, f, d, fact, dim = tables
    trace.set_enabled(False)
    res = session.run(f.filter(col("k") == 3).select("k", "v"))
    prof = session.last_profile()
    assert prof.trace is None  # no spans allocated...
    assert prof.root is not None and prof.root.wall_s > 0  # ...profile still real
    assert prof.root.rows_out == res.num_rows


def test_explain_analyze_renders(tables):
    session, hs, f, d, fact, dim = tables
    text = hs.explain(f.filter(col("k") == 7).select("k", "v"), mode="analyze")
    assert "EXPLAIN ANALYZE" in text
    assert "IndexPointLookup" in text
    assert "total:" in text and "cache:" in text and "venue:" in text
    assert "indexes used: f_k" in text
    with pytest.raises(Exception, match="unknown explain mode"):
        hs.explain(f, mode="bogus")


# -- fault interplay -------------------------------------------------------


def test_spans_close_with_error_on_fault(tables, tmp_path):
    session, hs, f, d, fact, dim = tables
    session.conf.set("hyperspace.retry.maxAttempts", 1)
    try:
        with faults.injected("bucket.read"):
            with pytest.raises(OSError):
                session.run(d.filter(col("g") == 1))  # raw scan: no fallback
    finally:
        session.conf.set("hyperspace.retry.maxAttempts", 3)
    root = trace.last_trace()
    assert root is not None and root.name == "query"
    assert root.error and "injected" in root.error
    # Every span closed (wall recorded) and the failing read is tagged.
    spans = list(root.walk())
    assert all(s.wall_s is not None for s in spans)
    assert any(s.error for s in spans if s.name.startswith("execute."))


def test_retry_events_recorded_on_span(tables):
    session, hs, f, d, fact, dim = tables
    from hyperspace_tpu.execution import io as hio

    hio.clear_table_cache()
    with faults.injected("bucket.read", times=1):
        session.run(d.filter(col("g") == 1))  # retry absorbs the fault
    root = trace.last_trace()
    events = [e for s in root.walk() for e in s.events]
    assert any(e["name"] == "retry" for e in events)
    assert stats.get("retry.attempts") >= 1


def test_spans_close_on_crash(tables, tmp_path):
    session, hs, f, d, fact, dim = tables
    with faults.injected("log.write", crash=True):
        with pytest.raises(faults.CrashPoint):
            hs.create_index(d, IndexConfig("d_g", ["g"], ["k"]))
    root = trace.last_trace()
    assert root is not None and root.name == "action.CreateAction"
    assert root.error and "CrashPoint" in root.error
    assert all(s.wall_s is not None for s in root.walk())
    begin = [s for s in root.walk() if s.name == "action.begin"]
    assert begin and begin[0].error


# -- sink + export ---------------------------------------------------------


def _walk(span_json):
    yield span_json
    for c in span_json.get("children", ()):
        yield from _walk(c)


def test_sink_and_export(tables, tmp_path):
    session, hs, f, d, fact, dim = tables
    sink = tmp_path / "events.jsonl"
    session.conf.set("hyperspace.obs.sink", str(sink))
    session.run(f.filter(col("k") == 7).select("k", "v"))
    session.run(f.join(d, ["k"]))
    lines = [json.loads(l) for l in sink.read_text().splitlines()]
    assert len(lines) == 2
    assert all(l["trace"]["name"] == "query" for l in lines)
    reg = registry_from_sink(str(sink))
    assert reg.get("query.count").value == 2
    assert reg.get("query.operator.seconds").count > 0
    text = render_prometheus(reg)
    assert "hyperspace_query_count 2" in text
    assert 'hyperspace_query_seconds_bucket{le="+Inf"}' in text
    # Live-registry exposition carries the cache/metrics families too.
    live = render_prometheus()
    assert "hyperspace_table_cache_hits" in live
    assert "hyperspace_query_operator_seconds_count" in live


def test_metrics_fed_from_profiles(tables):
    session, hs, f, d, fact, dim = tables
    before = metrics.REGISTRY.get("query.count").value
    session.run(f.filter(col("k") == 5).select("k", "v"))
    assert metrics.REGISTRY.get("query.count").value == before + 1
    assert metrics.REGISTRY.get("query.operator.seconds").count > 0


# -- monotonic TTL (clock-step satellite) ----------------------------------


def test_metadata_cache_uses_monotonic(monkeypatch):
    from hyperspace_tpu.metadata.cache import CreationTimeBasedCache

    c = CreationTimeBasedCache(expiry_seconds=3600.0)
    c.set("entry")
    # A wall-clock step (time.time jumping) must not expire the entry:
    # the implementation may not consult time.time at all.
    import time as _time

    monkeypatch.setattr(_time, "time", lambda: _time.monotonic() + 10_000_000)
    assert c.get() == "entry"
    expired = CreationTimeBasedCache(expiry_seconds=0.0)
    expired.set("entry")
    _time.sleep(0.002)
    assert expired.get() is None


# -- lint HSL007 -----------------------------------------------------------


def test_lint_hsl007():
    from hyperspace_tpu.analysis.lint import lint_source

    src = (
        "import time\n"
        "t0 = time.time()\n"
        "d = time.time() - t0\n"
        "from hyperspace_tpu import stats\n"
        "stats.increment('retyr.attempts')\n"
        "stats.increment('retry.attempts')\n"
        "ok = time.perf_counter() - 0.0\n"
    )
    found = lint_source(src, "x.py")
    assert [f.rule for f in found] == ["HSL007", "HSL007"]
    assert found[0].line == 3 and found[1].line == 5
    # noqa suppression works per line.
    src2 = "import time\nd = time.time() - 0.0  # noqa: HSL007\n"
    assert lint_source(src2, "y.py") == []
    # The package itself is HSL007-clean (the linter gates CI on this).
    from pathlib import Path

    from hyperspace_tpu.analysis.lint import lint_paths

    pkg = Path(__file__).resolve().parent.parent / "hyperspace_tpu"
    assert [str(f) for f in lint_paths([str(pkg)])] == []
