"""Action state-machine protocol tests with a fake writer.

Analog of actions/ActionTest.scala:139-166 (exact writeLog(0, CREATING) →
writeLog(1, ACTIVE) → latestStable swap sequence), the per-action validate()
matrices (CreateActionTest etc.), and VacuumActionTest's per-version delete
fan-out.
"""

from pathlib import Path

import pytest

from hyperspace_tpu import states
from hyperspace_tpu.actions import (
    CancelAction,
    CreateAction,
    DeleteAction,
    RefreshAction,
    RestoreAction,
    VacuumAction,
)
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.dataset import Dataset
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_manager import IndexLogManager


class FakeWriter:
    """Records build requests and fabricates bucket files."""

    def __init__(self):
        self.calls = []

    def write(self, plan, columns, indexed_columns, num_buckets, dest_path):
        self.calls.append(
            {
                "columns": list(columns),
                "indexed": list(indexed_columns),
                "num_buckets": num_buckets,
                "dest": str(dest_path),
            }
        )
        Path(dest_path).mkdir(parents=True, exist_ok=True)
        for b in range(num_buckets):
            (Path(dest_path) / f"bucket-{b:05d}.parquet").write_bytes(b"fake")


@pytest.fixture
def ctx(tmp_system_path, sample_parquet):
    conf = HyperspaceConf(system_path=tmp_system_path, num_buckets=4)
    ds = Dataset.parquet(sample_parquet)
    index_path = Path(tmp_system_path) / "idx1"
    lm = IndexLogManager(index_path)
    dm = IndexDataManager(index_path)
    writer = FakeWriter()
    cfg = IndexConfig("idx1", ["key"], ["value"])
    return dict(conf=conf, ds=ds, index_path=index_path, lm=lm, dm=dm, writer=writer, cfg=cfg)


def run_create(ctx):
    action = CreateAction(
        ctx["ds"].scan(), ctx["cfg"], ctx["lm"], ctx["dm"], ctx["index_path"], ctx["conf"], ctx["writer"]
    )
    action.run()
    return action


def test_create_protocol_sequence(ctx):
    run_create(ctx)
    lm = ctx["lm"]
    # Exact write sequence: id 0 CREATING, id 1 ACTIVE, latestStable → 1.
    assert lm.get_log(0).state == states.CREATING
    assert lm.get_log(1).state == states.ACTIVE
    assert lm.get_latest_id() == 1
    stable = lm.get_latest_stable_log()
    assert stable.id == 1 and stable.state == states.ACTIVE
    # Entry contents.
    entry = lm.get_latest_log()
    assert entry.name == "idx1"
    assert entry.indexed_columns == ["key"]
    assert entry.included_columns == ["value"]
    assert entry.num_buckets == 4
    assert entry.signature.kind == "fileBased" and entry.signature.value
    assert len(entry.source.files) == 2
    assert entry.content.directories == ["v__=0"]
    # Writer was invoked once with the right spec.
    assert ctx["writer"].calls == [
        {
            "columns": ["key", "value"],
            "indexed": ["key"],
            "num_buckets": 4,
            "dest": str(ctx["index_path"] / "v__=0"),
        }
    ]


def test_create_validates_schema_and_collision(ctx):
    bad_cfg = IndexConfig("idx1", ["nope"])
    with pytest.raises(HyperspaceError, match="not found"):
        CreateAction(
            ctx["ds"].scan(), bad_cfg, ctx["lm"], ctx["dm"], ctx["index_path"], ctx["conf"], ctx["writer"]
        ).run()
    run_create(ctx)
    with pytest.raises(HyperspaceError, match="already exists"):
        run_create(ctx)


def test_delete_restore_vacuum_lifecycle(ctx):
    run_create(ctx)
    lm, dm = ctx["lm"], ctx["dm"]

    # Delete: valid only from ACTIVE.
    DeleteAction(lm).run()
    assert lm.get_latest_log().state == states.DELETED
    with pytest.raises(HyperspaceError):
        DeleteAction(lm).run()

    # Restore: back to ACTIVE; data untouched.
    RestoreAction(lm).run()
    assert lm.get_latest_log().state == states.ACTIVE
    assert dm.get_version_ids() == [0]
    with pytest.raises(HyperspaceError):
        RestoreAction(lm).run()  # not DELETED

    # Vacuum: only from DELETED; deletes all versions.
    with pytest.raises(HyperspaceError):
        VacuumAction(lm, dm).run()
    DeleteAction(lm).run()
    VacuumAction(lm, dm).run()
    assert lm.get_latest_log().state == states.DOESNOTEXIST
    assert dm.get_version_ids() == []


def test_refresh_builds_next_version(ctx, sample_parquet):
    run_create(ctx)
    # Append a new source file; refresh must pick it up via live listing.
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    pq.write_table(
        pa.table(
            {
                "id": pa.array(np.arange(5, dtype=np.int64)),
                "key": pa.array(np.arange(5, dtype=np.int64)),
                "value": pa.array(np.zeros(5)),
                "name": pa.array(["x"] * 5),
            }
        ),
        Path(sample_parquet) / "part-2.parquet",
    )
    old_sig = ctx["lm"].get_latest_log().signature.value
    RefreshAction(ctx["lm"], ctx["dm"], ctx["index_path"], ctx["conf"], ctx["writer"]).run()
    entry = ctx["lm"].get_latest_log()
    assert entry.state == states.ACTIVE
    assert entry.content.directories == ["v__=1"]
    assert len(entry.source.files) == 3
    assert entry.signature.value != old_sig
    assert ctx["dm"].get_version_ids() == [0, 1]
    # Refresh is rejected in non-ACTIVE states.
    DeleteAction(ctx["lm"]).run()
    with pytest.raises(HyperspaceError):
        RefreshAction(ctx["lm"], ctx["dm"], ctx["index_path"], ctx["conf"], ctx["writer"]).run()


def test_cancel_rolls_forward_to_stable(ctx):
    run_create(ctx)
    lm = ctx["lm"]
    # Simulate a refresh that died after begin: transient REFRESHING at id 2.
    dead = lm.get_latest_log().with_state(states.REFRESHING)
    assert lm.write_log(2, dead)
    # Cancel in a stable state is rejected only when latest IS stable;
    # here latest is transient, so cancel rolls forward to ACTIVE.
    CancelAction(lm).run()
    latest = lm.get_latest_log()
    assert latest.state == states.ACTIVE
    assert latest.id == 3
    # Now latest is stable: cancel is rejected.
    with pytest.raises(HyperspaceError):
        CancelAction(lm).run()


def test_cancel_without_stable_goes_doesnotexist(ctx):
    # A create that died after begin: only CREATING at id 0.
    action = CreateAction(
        ctx["ds"].scan(), ctx["cfg"], ctx["lm"], ctx["dm"], ctx["index_path"], ctx["conf"], ctx["writer"]
    )
    action.validate()
    action.begin()
    assert ctx["lm"].get_latest_log().state == states.CREATING
    CancelAction(ctx["lm"]).run()
    assert ctx["lm"].get_latest_log().state == states.DOESNOTEXIST
