"""Multi-format sources: parquet/ORC/CSV/JSON, the same four formats the
reference gates sources to (index/serde/LogicalPlanSerDeUtils.scala:
225-245). Each format must register, build a covering index, rewrite
queries through it, and return results identical to the raw scan."""

import json

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, lit
from hyperspace_tpu.exceptions import HyperspaceError


def _frame(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "k": rng.integers(0, 500, n).astype(np.int64),
            "v": np.round(rng.normal(size=n), 6),
            "tag": rng.choice(["x", "y", "z"], n),
        }
    )


def _write(df, root, fmt):
    root.mkdir()
    t = pa.Table.from_pandas(df, preserve_index=False)
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(t, root / "p.parquet")
    elif fmt == "orc":
        from pyarrow import orc

        orc.write_table(t, root / "p.orc")
    elif fmt == "csv":
        df.to_csv(root / "p.csv", index=False)
    elif fmt == "json":
        (root / "p.json").write_text(
            "\n".join(json.dumps(r) for r in df.to_dict(orient="records"))
        )


@pytest.mark.parametrize("fmt", ["parquet", "orc", "csv", "json"])
def test_index_over_any_source_format(tmp_path, fmt):
    df = _frame()
    root = tmp_path / "src"
    _write(df, root, fmt)
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    scan = getattr(session, fmt)(root)
    assert scan.format == fmt
    assert set(n.lower() for n in scan.schema.names) == {"k", "v", "tag"}

    hs.create_index(scan, IndexConfig("f_k", ["k"], ["v", "tag"]))
    q = scan.filter(col("k") == lit(123)).select("k", "v", "tag")

    session.disable_hyperspace()
    raw = session.to_pandas(q).sort_values(["v"]).reset_index(drop=True)
    session.enable_hyperspace()
    idx = session.to_pandas(q).sort_values(["v"]).reset_index(drop=True)
    exp = df[df.k == 123][["k", "v", "tag"]].sort_values(["v"]).reset_index(drop=True)
    assert len(raw) == len(exp) and len(idx) == len(exp)
    np.testing.assert_allclose(raw["v"], exp["v"])
    np.testing.assert_allclose(idx["v"], exp["v"])
    assert list(idx["tag"]) == list(exp["tag"])
    # The rewritten query actually used the index (bucket pruning fired).
    assert session.last_query_stats["files_pruned"] > 0


@pytest.mark.parametrize("fmt", ["orc", "csv"])
def test_signature_staleness_per_format(tmp_path, fmt):
    """Appending a file of the same format invalidates the index (falls
    back to the raw scan) — the listing respects the format suffix."""
    df = _frame()
    root = tmp_path / "src"
    _write(df, root, fmt)
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    scan = getattr(session, fmt)(root)
    hs.create_index(scan, IndexConfig("s_k", ["k"], ["v", "tag"]))
    session.enable_hyperspace()

    extra = _frame(100, seed=9)
    if fmt == "orc":
        from pyarrow import orc

        orc.write_table(pa.Table.from_pandas(extra, preserve_index=False), root / "q.orc")
    else:
        extra.to_csv(root / "q.csv", index=False)
    q = scan.filter(col("k") == lit(7)).select("k", "v")
    got = session.to_pandas(q)
    both = pd.concat([df, extra], ignore_index=True)
    assert len(got) == int((both.k == 7).sum())  # stale index NOT used


def test_unsupported_format_raises(tmp_path):
    from hyperspace_tpu.dataset import Dataset

    with pytest.raises(HyperspaceError, match="unsupported source format"):
        Dataset.of_format(tmp_path, "avro")


def test_non_parquet_over_budget_streams(tmp_path):
    """A CSV source above the memory budget no longer raises: it builds
    through the streaming out-of-core pipeline (record-batch chunks) and
    the resulting index serves queries identically."""
    df = _frame(2000)
    root = tmp_path / "src"
    _write(df, root, "csv")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    session.conf.set("hyperspace.index.build.memoryBudgetBytes", 1024)
    hs = Hyperspace(session)
    scan = session.csv(root)
    hs.create_index(scan, IndexConfig("c_k", ["k"], ["v", "tag"]))
    session.enable_hyperspace()
    some_k = int(df.k.iloc[0])
    got = session.to_pandas(scan.filter(col("k") == some_k))
    assert len(got) == int((df.k == some_k).sum())


def test_csv_decode_pinned_to_registered_schema(tmp_path):
    """CSV decode is pinned to the REGISTERED schema, not re-inferred per
    file: a later numeric-looking file still decodes as string under a
    string registration (no silent type divergence across files), and a
    file violating the registered type fails with a clear conversion
    error instead of concat-time chaos."""
    root = tmp_path / "src"
    root.mkdir()
    # First file registers "code" as string (alphanumeric values).
    pd.DataFrame({"k": [1, 2, 3], "code": ["00x", "00y", "00z"]}).to_csv(
        root / "a.csv", index=False
    )
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    scan = session.csv(root)
    # A later file whose values LOOK numeric must still decode as string.
    pd.DataFrame({"k": [4, 5], "code": ["001", "002"]}).to_csv(root / "b.csv", index=False)
    got = session.to_pandas(scan)
    assert len(got) == 5
    assert {"00x", "001"} <= set(got["code"])

    # The reverse direction errors clearly (int registration, alpha data).
    root2 = tmp_path / "src2"
    root2.mkdir()
    pd.DataFrame({"k": [1], "code": ["001"]}).to_csv(root2 / "a.csv", index=False)
    scan2 = session.csv(root2)  # "code" registers as int64
    pd.DataFrame({"k": [2], "code": ["0zz"]}).to_csv(root2 / "b.csv", index=False)
    with pytest.raises(Exception, match="conversion error"):
        session.run(scan2)
