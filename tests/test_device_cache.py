"""HBM-resident array cache: repeat device-venue queries over the same
index version serve uploads from the cache (no re-staging), entries pin
their base arrays, refresh invalidates by identity, and results stay
byte-identical with the cache cold or hot."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.config import FILTER_VENUE, JOIN_VENUE
from hyperspace_tpu.execution import device_cache as dc


@pytest.fixture()
def indexed(tmp_path):
    rng = np.random.default_rng(31)
    n = 30_000
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 5_000, n).astype(np.int32),
            "v": rng.normal(size=n),
        }
    )
    root = tmp_path / "src"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    ds = session.parquet(root)
    hs.create_index(ds, IndexConfig("dc_k", ["k"], ["v"]))
    session.enable_hyperspace()
    dc.clear_all()
    return session, ds, df, hs


def test_repeat_filter_hits_device_cache(indexed):
    """A rewritten filter with no key bounds reads whole (cached, frozen)
    bucket files; the repeat run serves every upload from the device
    cache and the non-rewritten raw path inserts NOTHING (per-query scan
    arrays must never pollute the identity-keyed caches)."""
    session, ds, df, _ = indexed
    session.conf.set(FILTER_VENUE, "device")
    q = ds.filter(((col("k") % 2) == 0) & (col("v") > 0.0))

    first = session.to_pandas(q)
    assert "IndexScan" in repr(session.last_physical_plan)
    h0 = dc.DEVICE_CACHE.stats()["hits"]
    second = session.to_pandas(q)
    h1 = dc.DEVICE_CACHE.stats()["hits"]
    assert h1 > h0, "repeat query did not serve uploads from the device cache"
    pd.testing.assert_frame_equal(
        first.sort_values(["k", "v"]).reset_index(drop=True),
        second.sort_values(["k", "v"]).reset_index(drop=True),
    )
    exp = df[(df.k % 2 == 0) & (df.v > 0.0)]
    assert len(second) == len(exp)

    # Raw (unrewritten) repeat queries: fresh scan arrays are writeable,
    # so no cache entries accrue.
    session.disable_hyperspace()
    session.to_pandas(q)
    e0 = dc.DEVICE_CACHE.stats()["entries"] + dc.HOST_DERIVED.stats()["entries"]
    session.to_pandas(q)
    e1 = dc.DEVICE_CACHE.stats()["entries"] + dc.HOST_DERIVED.stats()["entries"]
    assert e1 == e0, "raw scans polluted the identity-keyed caches"
    session.enable_hyperspace()


def test_repeat_point_lookup_hits_device_cache(indexed):
    session, ds, df, _ = indexed
    session.conf.set(FILTER_VENUE, "device")
    q = ds.filter(col("k") == 1234)
    first = session.to_pandas(q)
    h0 = dc.DEVICE_CACHE.stats()["hits"]
    second = session.to_pandas(q)
    h1 = dc.DEVICE_CACHE.stats()["hits"]
    assert h1 > h0
    assert len(first) == len(second) == int((df.k == 1234).sum())


def test_repeat_join_skips_factorization(tmp_path):
    rng = np.random.default_rng(32)
    f = pd.DataFrame({"k": rng.integers(0, 1000, 40_000).astype(np.int64), "a": rng.normal(size=40_000)})
    d = pd.DataFrame({"k": np.arange(900, dtype=np.int64), "b": rng.normal(size=900)})
    for nm, fr in (("f", f), ("d", d)):
        (tmp_path / nm).mkdir()
        pq.write_table(pa.Table.from_pandas(fr, preserve_index=False), tmp_path / nm / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    fs, ds = session.parquet(tmp_path / "f"), session.parquet(tmp_path / "d")
    hs.create_index(fs, IndexConfig("fk2", ["k"], ["a"]))
    hs.create_index(ds, IndexConfig("dk2", ["k"], ["b"]))
    session.enable_hyperspace()
    session.conf.set(JOIN_VENUE, "device")
    dc.clear_all()

    q = fs.join(ds, ["k"])
    r1 = session.to_pandas(q)
    m0 = dc.HOST_DERIVED.stats()
    r2 = session.to_pandas(q)
    m1 = dc.HOST_DERIVED.stats()
    assert m1["hits"] > m0["hits"], "repeat join re-derived the key codes"
    assert len(r1) == len(r2) == len(f.merge(d, on="k"))


def test_derived_entries_are_frozen_and_pinned(indexed):
    session, ds, _, _ = indexed
    session.conf.set(FILTER_VENUE, "device")
    session.to_pandas(ds.filter(((col("k") % 2) == 0) & (col("v") > 0.5)))
    st = dc.HOST_DERIVED.stats()
    # 64-bit pair lowering of the float column produced derived entries.
    assert st["entries"] > 0
    for key, (nb, refs, val) in list(dc.HOST_DERIVED._entries.items()):
        if isinstance(val, np.ndarray):
            assert not val.flags.writeable


def test_refresh_invalidates_by_identity(indexed, tmp_path):
    session, ds, df, hs = indexed
    session.conf.set(FILTER_VENUE, "device")
    q = ds.filter(col("k") == 123)
    n1 = len(session.to_pandas(q))
    assert n1 == int((df.k == 123).sum())

    # Append rows and refresh: new version => new files => new host
    # arrays => cache misses, fresh correct results.
    extra = pd.DataFrame({"k": np.full(7, 123, dtype=np.int32), "v": np.zeros(7)})
    pq.write_table(
        pa.Table.from_pandas(extra, preserve_index=False), tmp_path / "src" / "p2.parquet"
    )
    hs.refresh_index("dc_k")
    n2 = len(session.to_pandas(q))
    assert n2 == n1 + 7


def test_cache_budget_bounds_memory():
    c = dc.RefCache(budget_bytes=1000)
    base = np.arange(10)
    base.flags.writeable = False
    for i in range(50):
        c.get_or_build(("x", i), (base,), lambda: (np.zeros(30), 240))
    st = c.stats()
    assert st["bytes"] <= 1000
    assert st["entries"] <= 1000 // 240 + 1


def test_repeat_fused_join_agg_device_venue_hits_cache(tmp_path):
    """The fused join-aggregate DEVICE path serves its pads, channel
    stacks, and uploads from the caches on repeat queries."""
    from hyperspace_tpu import AggSpec, IndexConfig
    from hyperspace_tpu.config import AGG_VENUE

    rng = np.random.default_rng(33)
    f = pd.DataFrame({"k": rng.integers(0, 500, 30_000).astype(np.int64), "a": rng.normal(size=30_000)})
    d = pd.DataFrame({"k": np.arange(500, dtype=np.int64), "w": rng.normal(size=500)})
    for nm, fr in (("ff", f), ("dd", d)):
        (tmp_path / nm).mkdir()
        pq.write_table(pa.Table.from_pandas(fr, preserve_index=False), tmp_path / nm / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    fs, ds = session.parquet(tmp_path / "ff"), session.parquet(tmp_path / "dd")
    hs.create_index(fs, IndexConfig("fj_f", ["k"], ["a"]))
    hs.create_index(ds, IndexConfig("fj_d", ["k"], ["w"]))
    session.enable_hyperspace()
    session.conf.set(JOIN_VENUE, "device")
    session.conf.set(AGG_VENUE, "device")
    dc.clear_all()

    q = fs.join(ds, ["k"]).aggregate([], [AggSpec.of("sum", "a", "sa"), AggSpec.of("count", None, "n")])
    r1 = session.to_pandas(q)
    assert session.last_query_stats["agg_path"] == "fused-join-agg"
    h0 = dc.DEVICE_CACHE.stats()["hits"]
    r2 = session.to_pandas(q)
    h1 = dc.DEVICE_CACHE.stats()["hits"]
    assert h1 > h0, "fused join-agg repeat did not hit the device cache"
    np.testing.assert_allclose(r1["sa"], r2["sa"])
    exp = f.merge(d, on="k")
    np.testing.assert_allclose(float(r1.loc[0, "sa"]), float(exp["a"].sum()), rtol=1e-9)
    assert int(r1.loc[0, "n"]) == len(exp)
