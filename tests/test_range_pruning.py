"""Range (min/max) pruning over sorted index buckets.

The analog of FileSourceScanExec's parquet min/max pruning, which the
reference inherits from Spark (SURVEY.md §2.2): the index manifest
persists per-bucket key stats, range predicates skip non-overlapping
bucket files, and surviving files are searchsorted-sliced on the sorted
key instead of full-scan masked.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, lit
from hyperspace_tpu.execution import io as hio

NB = 8


@pytest.fixture
def indexed(tmp_path):
    """Parquet source + covering index on an int64 key, returning
    (session, scan, source pandas)."""
    rng = np.random.default_rng(11)
    n = 50_000
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 100_000, n).astype(np.int64),
            "v": rng.normal(size=n),
            "tag": rng.choice(["x", "y", "z"], n),
        }
    )
    root = tmp_path / "src"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=NB)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_index(scan, IndexConfig("r_k", ["k"], ["v", "tag"]))
    session.enable_hyperspace()
    return session, scan, df


def test_manifest_has_key_stats(indexed, tmp_path):
    vdir = tmp_path / "idx" / "r_k" / "v__=0"
    m = hio.read_manifest(vdir)
    assert m is not None and "keyStats" in m
    ks = m["keyStats"]
    assert len(ks) == NB
    # Stats must bound the actual file contents.
    for b, s in enumerate(ks):
        t = pq.read_table(vdir / hio.bucket_file_name(b)).to_pandas()
        if len(t) == 0:
            assert s is None
        else:
            assert s[0] == t["k"].min() and s[1] == t["k"].max()


def test_between_query_prunes_and_matches(indexed):
    session, scan, df = indexed
    lo, hi = 40_000, 40_500
    q = scan.filter((col("k") >= lit(lo)) & (col("k") <= lit(hi)))
    got = (
        session.to_pandas(q)
        .sort_values(["k", "v"])
        .reset_index(drop=True)
    )
    exp = (
        df[(df.k >= lo) & (df.k <= hi)]
        .sort_values(["k", "v"])
        .reset_index(drop=True)
    )
    np.testing.assert_array_equal(got["k"], exp["k"])
    np.testing.assert_allclose(got["v"], exp["v"])
    assert list(got["tag"]) == list(exp["tag"])
    # The narrow range must not read every row: slicing kicked in.
    assert session.last_query_stats["rows_pruned"] > 0


def test_open_range_prunes_files(indexed):
    session, scan, df = indexed
    # Keys are hash-bucketed, so every bucket spans ~the full key range;
    # a threshold beyond every file's max prunes ALL files.
    q = scan.filter(col("k") > lit(100_000))
    got = session.to_pandas(q)
    assert len(got) == 0
    stats = session.last_query_stats
    assert stats["files_pruned"] == NB
    assert stats["files_read"] == 0


def test_strict_vs_inclusive_bounds(indexed):
    session, scan, df = indexed
    kmax = int(df.k.max())
    inc = session.to_pandas(scan.filter(col("k") >= lit(kmax)))
    strict = session.to_pandas(scan.filter(col("k") > lit(kmax)))
    assert len(inc) == int((df.k == kmax).sum())
    assert len(strict) == 0


def test_range_with_null_keys_falls_back_correctly(tmp_path):
    t = pa.table(
        {
            "k": pa.array([1, 5, None, 9, None, 3], type=pa.int64()),
            "v": np.arange(6, dtype=np.float64),
        }
    )
    root = tmp_path / "nsrc"
    root.mkdir()
    pq.write_table(t, root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_index(scan, IndexConfig("n_k", ["k"], ["v"]))
    session.enable_hyperspace()
    got = session.to_pandas(scan.filter(col("k") >= lit(4)))
    assert sorted(got["k"]) == [5, 9]  # nulls fail the comparison


def test_string_key_file_level_pruning(tmp_path):
    df = pd.DataFrame(
        {
            "s": [f"key{i:04d}" for i in range(2_000)],
            "v": np.arange(2_000, dtype=np.float64),
        }
    )
    root = tmp_path / "ssrc"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_index(scan, IndexConfig("s_k", ["s"], ["v"]))
    session.enable_hyperspace()
    got = session.to_pandas(scan.filter(col("s") < lit("key0010")))
    exp = df[df.s < "key0010"]
    assert sorted(got["s"]) == sorted(exp["s"])
    # Beyond-max range prunes every file via string stats.
    empty = session.to_pandas(scan.filter(col("s") > lit("zzz")))
    assert len(empty) == 0 and session.last_query_stats["files_read"] == 0


def test_range_pruning_survives_incremental_refresh(tmp_path):
    rng = np.random.default_rng(3)
    root = tmp_path / "isrc"
    root.mkdir()
    d1 = pd.DataFrame({"k": rng.integers(0, 1000, 3000).astype(np.int64), "v": rng.normal(size=3000)})
    pq.write_table(pa.Table.from_pandas(d1, preserve_index=False), root / "a.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_index(scan, IndexConfig("i_k", ["k"], ["v"]))
    d2 = pd.DataFrame({"k": rng.integers(0, 1000, 1000).astype(np.int64), "v": rng.normal(size=1000)})
    pq.write_table(pa.Table.from_pandas(d2, preserve_index=False), root / "b.parquet")
    hs.refresh_index("i_k", mode="incremental")
    session.enable_hyperspace()
    both = pd.concat([d1, d2], ignore_index=True)
    lo, hi = 200, 260
    got = session.to_pandas(scan.filter((col("k") >= lit(lo)) & (col("k") < lit(hi))))
    exp = both[(both.k >= lo) & (both.k < hi)]
    assert sorted(got["k"]) == sorted(exp["k"])
    np.testing.assert_allclose(sorted(got["v"]), sorted(exp["v"]))
    assert session.last_query_stats["rows_pruned"] > 0


def test_float32_key_weak_literal_not_overpruned(tmp_path):
    """Pruning must compare in the filter's own domain: a python-float
    literal against a float32 key compares IN float32 (NEP 50), so the
    literal rounds. Comparing raw float64 instead would prune files/rows
    the mask keeps."""
    v = np.float32(0.1)  # 0.10000000149... as float64
    df = pd.DataFrame(
        {
            "k": np.full(300, v, dtype=np.float32),
            "p": np.arange(300, dtype=np.float64),
        }
    )
    root = tmp_path / "f32"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_index(scan, IndexConfig("f_k", ["k"], ["p"]))

    q = scan.filter(col("k") <= lit(0.1))
    session.disable_hyperspace()
    raw = session.to_pandas(q)
    session.enable_hyperspace()
    idx = session.to_pandas(q)
    assert len(raw) == 300  # float32(0.1) <= float32(0.1)
    assert len(idx) == len(raw)


def test_range_pruning_in_hybrid_scan(tmp_path):
    """After an append WITHOUT refresh, the rewritten plan is a hybrid
    Union(index, delta); range pruning must still skip index files."""
    rng = np.random.default_rng(9)
    root = tmp_path / "hsrc"
    root.mkdir()
    d1 = pd.DataFrame({"k": rng.integers(0, 1000, 4000).astype(np.int64), "v": rng.normal(size=4000)})
    pq.write_table(pa.Table.from_pandas(d1, preserve_index=False), root / "a.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_index(scan, IndexConfig("h_k", ["k"], ["v"]))
    d2 = pd.DataFrame({"k": rng.integers(0, 1000, 500).astype(np.int64), "v": rng.normal(size=500)})
    pq.write_table(pa.Table.from_pandas(d2, preserve_index=False), root / "b.parquet")
    from hyperspace_tpu.config import INDEX_HYBRID_SCAN_ENABLED, INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO

    session.conf.set(INDEX_HYBRID_SCAN_ENABLED, True)
    session.conf.set(INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO, 10.0)
    session.enable_hyperspace()
    both = pd.concat([d1, d2], ignore_index=True)
    # Above every key: index files all pruned; delta still scanned.
    got = session.to_pandas(scan.filter(col("k") > lit(10_000)))
    assert len(got) == 0
    assert session.last_query_stats["files_pruned"] == 4
    lo, hi = 100, 150
    got2 = session.to_pandas(scan.filter((col("k") >= lit(lo)) & (col("k") < lit(hi))))
    exp2 = both[(both.k >= lo) & (both.k < hi)]
    assert sorted(got2["k"]) == sorted(exp2["k"])
    np.testing.assert_allclose(sorted(got2["v"]), sorted(exp2["v"]))


def test_exact_slice_skips_residual_mask(indexed):
    """A predicate made ONLY of key bounds is fully implemented by the
    slice — the physical plan records the skipped mask and results stay
    identical to the raw scan."""
    session, scan, df = indexed
    lo, hi = 30_000, 31_000
    q = scan.filter((col("k") >= lit(lo)) & (col("k") < lit(hi)))
    got = session.to_pandas(q)
    phys = session.last_physical_plan
    node = next(n for n in phys.walk() if n.op == "IndexRangeScan")
    assert "mask skipped" in node.detail["kernel"]
    exp = df[(df.k >= lo) & (df.k < hi)]
    assert len(got) == len(exp)
    np.testing.assert_allclose(sorted(got["v"]), sorted(exp["v"]))

    # A residual conjunct on another column keeps the mask.
    q2 = scan.filter((col("k") >= lit(lo)) & (col("k") < lit(hi)) & (col("v") > lit(0.0)))
    got2 = session.to_pandas(q2)
    node2 = next(n for n in session.last_physical_plan.walk() if n.op == "IndexRangeScan")
    assert "-mask" in node2.detail["kernel"]  # mask ran (either venue)
    exp2 = exp[exp.v > 0.0]
    assert len(got2) == len(exp2)


def test_nan_bound_returns_no_rows(indexed):
    """NaN comparisons are False for every row; the range path must not
    treat NaN as an orderable bound (searchsorted sorts NaN last, which
    would return EVERY row as an 'exact' slice)."""
    session, scan, df = indexed
    q = scan.filter(col("k") <= lit(float("nan")))
    session.disable_hyperspace()
    assert len(session.to_pandas(q)) == 0
    session.enable_hyperspace()
    assert len(session.to_pandas(q)) == 0


def test_float_key_with_nan_values_not_overincluded(tmp_path):
    """A float key column holding NaN VALUES: a lower-bound-only slice
    includes the trailing NaN run, so the mask must still run (exactness
    is never claimed for float keys)."""
    df = pd.DataFrame(
        {
            "k": np.array([1.0, 2.0, 3.0, np.nan, np.nan], dtype=np.float64),
            "v": np.arange(5, dtype=np.float64),
        }
    )
    root = tmp_path / "nan_src"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=1)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_index(scan, IndexConfig("nk", ["k"], ["v"]))
    session.enable_hyperspace()
    got = session.to_pandas(scan.filter(col("k") >= lit(2.0)))
    assert sorted(got["k"]) == [2.0, 3.0]  # NaN rows dropped by the mask
