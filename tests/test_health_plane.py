"""Runtime health plane: structured events, JIT/compile introspection,
SLO burn rates, the /metrics + /healthz HTTP endpoints, and the
chrome-trace exporter (docs/observability.md "live endpoints")."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.obs import events, metrics, runtime, slo, trace
from hyperspace_tpu.obs import http as obs_http
from hyperspace_tpu.obs.export import (
    chrome_trace,
    escape_help,
    escape_label_value,
    render_prometheus,
    roots_from_sink,
)


class FakeSession:
    """The session surface the health plane reads: conf + the
    lock-guarded index_health map."""

    def __init__(self, **conf_overrides):
        self.conf = HyperspaceConf()
        for k, v in conf_overrides.items():
            self.conf.set(k, v)
        self._state_lock = threading.RLock()
        self.index_health = {}


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# -- structured events -----------------------------------------------------


def test_event_ring_records_and_bounds():
    evt = events.declare("fallback.replan")
    events.configure(max_events=4)
    for i in range(7):
        evt.emit(index=f"i{i}")
    recent = events.recent()
    assert len(recent) == 4
    assert [e["fields"]["index"] for e in recent] == ["i3", "i4", "i5", "i6"]
    assert metrics.REGISTRY.get("obs.events.dropped").value == 3
    assert all(e["severity"] == "warn" for e in recent)
    # seq strictly increases; ts is wall-clock
    seqs = [e["seq"] for e in recent]
    assert seqs == sorted(seqs)


def test_undeclared_event_raises_at_declare():
    with pytest.raises(KeyError, match="undeclared event"):
        events.declare("fallbck.replan")


def test_event_severity_filter_and_counts():
    events.declare("advisor.routing.demoted").emit(signature="s")
    events.declare("index.quarantined").emit(index="x")
    assert len(events.recent(level="warn")) == 1
    assert len(events.recent(level="info")) == 2
    counts = events.counts_by_severity()
    assert counts["info"] == 1 and counts["warn"] == 1
    with pytest.raises(ValueError):
        events.recent(level="loud")


def test_event_carries_active_trace_id():
    evt = events.declare("fallback.replan")
    with trace.trace("query"):
        inside = evt.emit(index="a")
    outside = evt.emit(index="b")
    assert inside["trace_id"] is not None
    assert outside["trace_id"] is None
    root = trace.last_trace()
    assert root.trace_id == inside["trace_id"]


# -- JIT/compile introspection ---------------------------------------------


def test_compat_jit_counts_compiles_per_key():
    import jax.numpy as jnp

    from hyperspace_tpu.compat import jit

    f = jit(lambda x: x + 1, key="test.stable")
    for _ in range(5):
        f(jnp.ones(3))
    report = runtime.jit_report()["test.stable"]
    assert report["calls"] == 5
    assert report["compiles"] == 1  # one shape, one executable
    assert report["storms"] == 0
    # a second shape compiles once more
    f(jnp.ones((2, 2)))
    assert runtime.jit_report()["test.stable"]["compiles"] == 2


def test_jit_in_a_loop_trips_recompile_storm_naming_the_key():
    """The dynamic mirror of lint rule HSL015: a fresh callable jitted
    per call at one call site must emit jit.recompile_storm naming it."""
    import jax.numpy as jnp

    from hyperspace_tpu.compat import jit

    for i in range(runtime.STORM_THRESHOLD + 2):
        f = jit(lambda x, _i=i: x + _i, key="test.jit_loop")  # noqa: HSL015 — deliberate storm
        f(jnp.ones(2))
    storms = [e for e in events.recent() if e["name"] == "jit.recompile_storm"]
    assert len(storms) == 1  # re-armed per threshold multiple, not per compile
    assert storms[0]["fields"]["key"] == "test.jit_loop"
    assert storms[0]["fields"]["compiles"] >= runtime.STORM_THRESHOLD
    assert metrics.REGISTRY.get("jit.recompile_storms").value == 1
    assert runtime.jit_report()["test.jit_loop"]["storms"] == 1


def test_warm_call_sites_never_storm():
    import jax.numpy as jnp

    from hyperspace_tpu.compat import jit

    f = jit(lambda x: x * 2, key="test.warm")
    # Many distinct shapes (legitimate warm-up) but far more warm calls.
    for n in range(1, 1 + runtime.STORM_THRESHOLD + 4):
        for _ in range(4):
            f(jnp.ones(n))
    site = runtime.jit_report()["test.warm"]
    assert site["compiles"] >= runtime.STORM_THRESHOLD
    assert site["storms"] == 0  # compile ratio stays under the floor


def test_instrumented_jit_forwards_attributes_and_default_key():
    import jax.numpy as jnp

    from hyperspace_tpu.compat import jit

    def doubler(x):
        return x * 2

    f = jit(doubler)
    f(jnp.ones(2))
    assert f.jit_key.endswith("doubler")
    assert callable(getattr(f, "lower", None))  # pjit attr forwarded
    assert f.jit_key in runtime.jit_report()


def test_process_gauges_refresh():
    import jax.numpy as jnp

    from hyperspace_tpu.compat import jit

    f = jit(lambda x: x + 3, key="test.gauges")
    f(jnp.ones(2))
    vals = runtime.refresh_process_gauges()
    assert vals["map_count"] > 0
    assert vals["rss_watermark_bytes"] > 0
    assert vals["live_executables"] >= 1
    assert metrics.REGISTRY.get("proc.map_count").value == vals["map_count"]
    assert metrics.REGISTRY.get("jit.live_executables").value == vals["live_executables"]


def test_jit_memory_drop_is_observable(monkeypatch):
    from hyperspace_tpu import stats
    from hyperspace_tpu.utils import jit_memory

    monkeypatch.setattr(jit_memory, "_limit_cache", [1])  # force "over limit"
    dropped = False
    for _ in range(jit_memory._CHECK_EVERY + 1):  # sampled: hit the stride once
        dropped = jit_memory.maybe_relieve_jit_pressure() or dropped
    assert dropped
    assert stats.get("jit_memory.cache_drops") >= 1
    drops = [e for e in events.recent() if e["name"] == "jit.cache_drop"]
    assert drops and drops[0]["fields"]["limit"] == 1
    assert drops[0]["fields"]["map_count"] > 1


# -- SLO burn rates --------------------------------------------------------


def _serve_counters():
    return (
        metrics.counter("serve.completed"),
        metrics.counter("serve.failed"),
        metrics.counter("serve.timeouts"),
        metrics.counter("serve.cancelled"),
        metrics.histogram("serve.latency.seconds"),
    )


def test_burn_rate_math_is_exact():
    completed, failed, *_ = _serve_counters()
    slo.sample(now=100.0)
    completed.inc(980)
    failed.inc(20)  # bad fraction 0.02; budget 0.001 -> burn 20
    slo.sample(now=160.0)
    burn = slo.objective("serve.availability").window_burn(60.0, now=160.0)
    assert burn == pytest.approx(20.0)


def test_burn_windows_clamp_to_observed_span():
    completed, failed, *_ = _serve_counters()
    slo.sample(now=0.0)
    completed.inc(9)
    failed.inc(1)
    slo.sample(now=10.0)  # only 10s of history; the 3600s window clamps
    burn = slo.objective("serve.availability").window_burn(3600.0, now=10.0)
    assert burn == pytest.approx(0.1 / 0.001)


def _availability_burn_events():
    return [
        e for e in events.recent()
        if e["name"] == "slo.burn" and e["fields"]["objective"] == "serve.availability"
    ]


def test_verdicts_ok_page_recover_and_event_rearm():
    completed, failed, *_ = _serve_counters()
    slo.sample(now=0.0)
    completed.inc(10_000)
    slo.sample(now=4000.0)
    out = slo.evaluate(now=4000.0)
    assert out["serve.availability"]["verdict"] == "ok"
    # a hard failure burst, judged while it is still inside every window
    failed.inc(3_000)
    slo.sample(now=4030.0)
    out = slo.evaluate(now=4030.0)
    assert out["serve.availability"]["verdict"] == "page"
    assert len(_availability_burn_events()) == 1
    # still paging: no duplicate event
    slo.evaluate(now=4030.0)
    assert len(_availability_burn_events()) == 1
    # recovery: clean traffic pushes the burst out of the PAGE windows;
    # the long warn window still remembers it — exactly the SRE shape
    # (stop paging fast, keep warning while the budget is still burnt)
    completed.inc(50_000)
    slo.sample(now=4100.0)
    out = slo.evaluate(now=4100.0)
    assert out["serve.availability"]["verdict"] == "warn"
    # a second burst re-arms the event
    failed.inc(5_000)
    slo.sample(now=4130.0)
    assert slo.evaluate(now=4130.0)["serve.availability"]["verdict"] == "page"
    assert len(_availability_burn_events()) == 2


def test_latency_objective_counts_goods_from_buckets():
    *_, latency = _serve_counters()
    slo.configure(latency_threshold_s=0.1)
    slo.sample(now=0.0)
    for _ in range(99):
        latency.observe(0.01)
    latency.observe(50.0)  # one terrible tail query
    slo.sample(now=60.0)
    burn = slo.objective("serve.latency_p99").window_burn(60.0, now=60.0)
    # bad fraction 1/100 = budget exactly -> burn 1.0
    assert burn == pytest.approx(1.0)


def test_undeclared_objective_raises():
    with pytest.raises(KeyError, match="undeclared SLO objective"):
        slo.objective("serve.availabilty")


def test_insufficient_data_is_none_not_zero():
    _serve_counters()
    assert slo.objective("serve.availability").window_burn(60.0) is None
    slo.sample(now=0.0)
    assert slo.objective("serve.availability").window_burn(60.0, now=0.0) is None


# -- histogram percentile edge shapes (SLO math depends on these) ----------


def test_histogram_empty_quantiles_are_none():
    h = metrics.Histogram("t.empty", buckets=(1.0, 2.0))
    assert h.quantile(0.5) is None
    assert h.percentiles() == {"p50": None, "p95": None, "p99": None}
    assert h.bucket_counts()[-1] == (float("inf"), 0)


def test_histogram_single_sample_returns_that_value():
    h = metrics.Histogram("t.one", buckets=(1.0, 2.0, 4.0))
    h.observe(1.7)
    for q in (0.01, 0.5, 0.99):
        assert h.quantile(q) == pytest.approx(1.7)


def test_histogram_all_in_one_bucket_interpolates_min_max():
    h = metrics.Histogram("t.tight", buckets=(1.0, 10.0))
    for v in (2.0, 3.0, 4.0):
        h.observe(v)
    # owning bucket is (1, 10] but observed range is [2, 4] — quantiles
    # must stay inside the observed range, not smear across the bucket.
    assert 2.0 <= h.quantile(0.5) <= 4.0
    assert h.quantile(0.0) == pytest.approx(2.0)
    assert h.quantile(1.0) == pytest.approx(4.0)


def test_histogram_overflow_bucket_uses_observed_max():
    h = metrics.Histogram("t.over", buckets=(1.0, 2.0))
    for v in (5.0, 7.0, 9.0):
        h.observe(v)  # all past the last bound
    assert h.bucket_counts() == [(1.0, 0), (2.0, 0), (float("inf"), 3)]
    assert 5.0 <= h.quantile(0.5) <= 9.0
    assert h.quantile(1.0) == pytest.approx(9.0)


# -- Prometheus escaping ---------------------------------------------------


def test_prometheus_escapes_help_and_labels():
    assert escape_help("a\\b\nc") == "a\\\\b\\nc"
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    reg = metrics.MetricsRegistry()
    reg.counter("hostile", 'line1\nline2 "q" \\slash')
    reg.histogram("hostile.h", "multi\nline", buckets=(1.0,))
    text = render_prometheus(reg)
    for line in text.splitlines():
        # the exposition must stay line-structured: every line is a
        # comment or `name{labels} value`
        assert line.startswith("#") or len(line.split(" ")) == 2, line
    assert "# HELP hyperspace_hostile line1\\nline2" in text


def test_prometheus_round_trip_recovers_values():
    reg = metrics.MetricsRegistry()
    c = reg.counter("rt.count", "with\nnewline")
    c.inc(41)
    g = reg.gauge("rt.gauge")
    g.set(2.5)
    text = render_prometheus(reg)
    parsed = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        parsed[name] = float(value)
    assert parsed["hyperspace_rt_count"] == 41
    assert parsed["hyperspace_rt_gauge"] == 2.5


# -- HTTP endpoints --------------------------------------------------------


@pytest.fixture
def http_server():
    """A QueryServer with the health plane enabled on an ephemeral port
    (DI run_fn: scheduler semantics without a real dataset)."""
    from hyperspace_tpu.serve.scheduler import QueryServer

    session = FakeSession(**{"hyperspace.obs.http.enabled": "true"})
    server = QueryServer(session, workers=4, max_queue_depth=512, run_fn=lambda p: p * 2)
    try:
        yield session, server, server.health_endpoint
    finally:
        server.shutdown()


def test_endpoints_scrape_under_16_client_hammer(http_server):
    session, server, ep = http_server
    stop = threading.Event()
    errors = []

    def client(cid):
        try:
            while not stop.is_set():
                assert server.submit(cid).result(timeout=30) == cid * 2
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 3.0
        scrapes = 0
        while time.monotonic() < deadline:
            code, body = _get(ep.url("/metrics"))
            assert code == 200
            assert "hyperspace_serve_completed" in body
            assert "hyperspace_slo_serve_availability_burn_rate" in body
            code, body = _get(ep.url("/healthz"))
            assert code == 200
            doc = json.loads(body)
            assert doc["status"] in ("ok", "degraded")
            assert doc["scheduler"][0]["workers"] == 4
            scrapes += 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors
    assert scrapes >= 2
    # enough scrape samples accumulated to compute a burn rate
    assert slo.objective("serve.availability").window_burn(60.0) is not None


def test_disabled_http_means_no_thread_no_socket():
    from hyperspace_tpu.serve.scheduler import QueryServer

    session = FakeSession()  # hyperspace.obs.http.enabled defaults false
    server = QueryServer(session, workers=1, run_fn=lambda p: p)
    try:
        assert server.health_endpoint is None
        assert obs_http.shared() is None
        assert not any(t.name == "hs-obs-http" for t in threading.enumerate())
    finally:
        server.shutdown()


def test_http_lifecycle_refcounts_across_servers():
    from hyperspace_tpu.serve.scheduler import QueryServer

    session = FakeSession(**{"hyperspace.obs.http.enabled": "true"})
    s1 = QueryServer(session, workers=1, run_fn=lambda p: p)
    s2 = QueryServer(session, workers=1, run_fn=lambda p: p)
    try:
        assert s1.health_endpoint is s2.health_endpoint  # one port per process
        port = s1.health_endpoint.port
        s1.shutdown()
        # still serving for s2
        code, _ = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200
    finally:
        s2.shutdown()
    assert obs_http.shared() is None
    assert not any(t.name == "hs-obs-http" for t in threading.enumerate())


def test_healthz_reports_quarantine_and_jit_sites(http_server):
    session, server, ep = http_server
    with session._state_lock:
        session.index_health["/idx/broken"] = {"reason": "torn bucket", "path": "b0"}
    code, body = _get(ep.url("/healthz"))
    doc = json.loads(body)
    assert doc["status"] == "degraded"
    assert doc["indexes"]["/idx/broken"]["reason"] == "torn bucket"
    assert "sites" in doc["jit"] and "map_count" in doc["jit"]


def test_debug_events_and_trace_endpoints(http_server):
    session, server, ep = http_server
    events.declare("index.quarantined").emit(index="x")
    events.declare("advisor.routing.demoted").emit(signature="s")
    with trace.trace("query"):
        with trace.span("execute.Filter"):
            pass
    code, body = _get(ep.url("/debug/events?level=warn"))
    doc = json.loads(body)
    assert code == 200
    assert [e["name"] for e in doc["events"]] == ["index.quarantined"]
    code, body = _get(ep.url("/debug/trace?limit=4"))
    doc = json.loads(body)
    assert [t["name"] for t in doc["traces"]] == ["query"]
    assert doc["traces"][0]["children"][0]["name"] == "execute.Filter"
    assert doc["traces"][0]["trace_id"]


def test_http_unknown_path_404_and_bad_query_400(http_server):
    _, _, ep = http_server
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(ep.url("/nope"))
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(ep.url("/debug/events?limit=banana"))
    assert e.value.code == 400


def test_healthz_standalone_server_without_session():
    hs = obs_http.HealthServer().start()
    try:
        code, body = _get(hs.url("/healthz"))
        doc = json.loads(body)
        assert doc["status"] == "ok"
        assert doc["indexes"] == {} and doc["scheduler"] == []
        assert doc["controller"] == []  # none attached; the key is always there
    finally:
        hs.stop()


def test_slo_page_recover_repage_reemits_through_healthz():
    """Regression pin for the slo.burn re-arm contract driven end to end
    through the health plane: a page that recovers and then re-fires
    must emit a SECOND slo.burn event (the re-arm logic in
    SLOTracker.evaluate), and /healthz must surface the current SLO and
    controller verdicts while it happens."""
    from hyperspace_tpu.serve.controller import OpsController

    completed, failed, *_ = _serve_counters()
    session = FakeSession()
    session.conf.set("hyperspace.controller.enabled", "true")

    class _Facade:
        def __init__(self, s):
            self.session = s

    ctrl = OpsController(_Facade(session), clock=lambda: 0.0)
    # page: a hard failure burst inside every window
    completed.inc(10_000)
    slo.sample(now=0.0)
    slo.sample(now=4000.0)
    failed.inc(3_000)
    slo.sample(now=4030.0)
    assert slo.evaluate(now=4030.0)["serve.availability"]["verdict"] == "page"
    assert len([e for e in events.recent() if e["name"] == "slo.burn"]) == 1
    # recover: clean traffic pushes the burst out of the page windows
    completed.inc(80_000)
    slo.sample(now=4100.0)
    assert slo.evaluate(now=4100.0)["serve.availability"]["verdict"] != "page"
    # re-page: a second burst must RE-EMIT (the re-arm contract)
    failed.inc(9_000)
    slo.sample(now=4130.0)
    assert slo.evaluate(now=4130.0)["serve.availability"]["verdict"] == "page"
    assert len([e for e in events.recent() if e["name"] == "slo.burn"]) == 2
    # the controller sees the same verdict on its own clock, and
    # /healthz surfaces its snapshot next to the SLO section (the scrape
    # re-samples on the real clock, so only the controller view — which
    # carries the verdict the controller last acted on — is pinned here)
    ctrl.step(now=4131.0)
    hs = obs_http.HealthServer().start()
    try:
        hs.attach_controller(ctrl)
        code, body = _get(hs.url("/healthz"))
        doc = json.loads(body)
        assert doc["controller"][0]["mode"] == "actuate"
        assert doc["controller"][0]["verdicts"]["serve.availability"] == "page"
        assert "slo" in doc
    finally:
        hs.stop()


# -- chrome trace export ---------------------------------------------------


def test_chrome_trace_lanes_and_overlap(tmp_path):
    sink = tmp_path / "events.jsonl"
    trace.configure(sink=str(sink))
    with trace.trace("root"):
        def work():
            with trace.span("stage"):
                time.sleep(0.03)

        threads = [threading.Thread(target=trace.wrap(work)) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    doc = chrome_trace(roots_from_sink(str(sink)))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    stages = [e for e in xs if e["name"] == "stage"]
    assert len(stages) == 2
    assert len({e["tid"] for e in stages}) == 2  # separate thread lanes
    a, b = [(e["ts"], e["ts"] + e["dur"]) for e in stages]
    assert a[0] < b[1] and b[0] < a[1]  # genuinely overlapping slices
    # every event is a well-formed complete event
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] >= 0 and e["pid"] and e["tid"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {(m["pid"], m["tid"]) for m in metas} >= {(e["pid"], e["tid"]) for e in xs}


def test_chrome_trace_tolerates_missing_timeline_fields():
    legacy = {
        "name": "root", "wall_s": 0.5,
        "children": [{"name": "child", "wall_s": 0.2}],
    }
    doc = chrome_trace([legacy])
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["root", "child"]
    assert all(e["ts"] == 0.0 for e in xs)


def test_export_cli_chrome_and_prom(tmp_path, capsys):
    from hyperspace_tpu.obs import export

    sink = tmp_path / "s.jsonl"
    trace.configure(sink=str(sink))
    with trace.trace("query"):
        with trace.span("execute.Scan"):
            pass
    out = tmp_path / "trace.json"
    assert export.main(["--format", "chrome", "--sink", str(sink), "--output", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert any(e["name"] == "execute.Scan" for e in doc["traceEvents"])
    assert export.main(["--sink", str(sink)]) == 0
    assert "hyperspace_query_count 1" in capsys.readouterr().out


# -- config plumbing -------------------------------------------------------


def test_new_config_keys_round_trip():
    conf = HyperspaceConf()
    assert conf.get("hyperspace.obs.http.enabled") is False
    conf.set("hyperspace.obs.http.enabled", "true")
    conf.set("hyperspace.obs.http.port", 19464)
    conf.set("hyperspace.obs.http.host", "0.0.0.0")
    assert conf.obs_http_enabled is True
    assert conf.get("hyperspace.obs.http.port") == 19464
    assert conf.get("hyperspace.obs.http.host") == "0.0.0.0"
    conf.set("hyperspace.obs.events.maxEvents", 8)
    assert conf.get("hyperspace.obs.events.maxEvents") == 8
    conf.set("hyperspace.obs.slo.availabilityTarget", 0.99)
    conf.set("hyperspace.obs.slo.latencyP99Seconds", 0.25)
    assert slo.TRACKER.availability_target == pytest.approx(0.99)
    assert conf.get("hyperspace.obs.slo.latencyP99Seconds") == pytest.approx(0.25)
