"""Executed physical plan + profiler capture.

The reference's explain diffs executedPlans and counts physical
operators (PlanAnalyzer.scala:163-178, PhysicalOperatorAnalyzer.scala:
39-56); our physical layer is recorded as the executor runs, so
explain(physical=True) diffs measured evidence — files read, kernels,
bucket/device counts, rows per operator. The profiler hook is the
jax.profiler/xplane capture SURVEY.md §5 names as the TPU story.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, lit


@pytest.fixture
def session(tmp_system_path):
    return HyperspaceSession(system_path=tmp_system_path, num_buckets=8)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def test_physical_plan_point_lookup(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("p_key", ["key"], ["id", "value"]))
    session.enable_hyperspace()
    q = df.filter(col("key") == lit(7)).select("id", "value")
    session.run(q)
    phys = session.last_physical_plan
    assert phys is not None
    ops = [n.op for n in phys.walk()]
    assert "IndexPointLookup" in ops
    lookup = next(n for n in phys.walk() if n.op == "IndexPointLookup")
    assert "bucket-hash-prune" in lookup.detail["kernel"]
    assert lookup.rows_out is not None
    # JSON round-trip for tooling.
    j = phys.to_json()
    assert j["op"] == "Project" and j["children"]


def test_physical_plan_range_scan_and_join(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("r_key", ["key"], ["id", "value"]))
    session.enable_hyperspace()
    session.run(df.filter(col("key") > lit(90)).select("id", "value"))
    ops = {n.op for n in session.last_physical_plan.walk()}
    assert "IndexRangeScan" in ops

    q = df.select("key", "value").join(df.select("key", "id"), ["key"])
    session.run(q)
    smj = next(n for n in session.last_physical_plan.walk() if n.op == "SortMergeJoin")
    assert smj.detail["path"] == "zero-exchange-aligned"
    assert smj.detail["buckets"] == 8


def test_physical_plan_without_index_uses_table_scan(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    session.run(df.filter(col("key") == lit(7)))
    phys = session.last_physical_plan
    ops = [n.op for n in phys.walk()]
    assert "TableScan" in ops and "IndexPointLookup" not in ops
    scan = next(n for n in phys.walk() if n.op == "TableScan")
    assert scan.detail["files"] == 2  # both source files read


def test_explain_physical_diffs_executed_plans(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("e_key", ["key"], ["id", "value"]))
    out = hs.explain(df.filter(col("key") == lit(3)).select("id", "value"), physical=True)
    assert "Executed plan with indexes:" in out
    assert "IndexPointLookup" in out
    assert "TableScan" in out  # the without-index side
    assert "Indexes used:" in out and "e_key" in out
    assert "files read:" in out and "files pruned:" in out
    # Aggregate evidence shows up too.
    out2 = hs.explain(
        df.aggregate(["key"], [("sum", "value", "s")]), physical=True
    )
    assert "SegmentReduceAggregate" in out2


def test_profile_dir_writes_trace(session, hs, sample_parquet, tmp_path):
    df = session.parquet(sample_parquet)
    trace_dir = tmp_path / "trace"
    session.run(df.filter(col("key") == lit(1)), profile_dir=trace_dir)
    found = [
        os.path.join(r, f)
        for r, _, fs in os.walk(trace_dir)
        for f in fs
        if f.endswith(".xplane.pb")
    ]
    assert found, "jax.profiler trace artifact not written"


def test_physical_plan_hybrid_scan_filter(session, hs, sample_parquet, tmp_path):
    """Filter over a hybrid Union must surface pruning evidence."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.config import (
        INDEX_HYBRID_SCAN_ENABLED,
        INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO,
    )

    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("h_key", ["key"], ["id", "value"]))
    extra = pa.table(
        {
            "id": np.arange(5000, 5100, dtype=np.int64),
            "key": np.full(100, 7, dtype=np.int64),
            "value": np.zeros(100),
            "name": ["x"] * 100,
        }
    )
    pq.write_table(extra, f"{sample_parquet}/part-2.parquet")
    session.conf.set(INDEX_HYBRID_SCAN_ENABLED, True)
    session.conf.set(INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO, 10.0)
    session.enable_hyperspace()
    session.run(df.filter(col("key") == lit(7)).select("id", "value"))
    phys = session.last_physical_plan
    hybrid = [n for n in phys.walk() if n.op == "HybridScanFilter"]
    assert hybrid, [n.op for n in phys.walk()]
    assert hybrid[0].detail["files_pruned"] > 0
