"""Broadcast hash join: a heavily asymmetric non-aligned join sorts only
the small side and probes it — results identical to the merge path for
every join type; config can force the merge path back."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import HyperspaceSession
from hyperspace_tpu.config import JOIN_BROADCAST_MAX_ROWS


@pytest.fixture(scope="module")
def tables(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("bcast")
    rng = np.random.default_rng(17)
    n_f, n_d = 60_000, 500
    fact = pd.DataFrame(
        {
            "k": rng.integers(0, 700, n_f).astype(np.int64),  # some keys miss the dim
            "x": rng.normal(size=n_f),
        }
    )
    dim = pd.DataFrame(
        {
            "dk": np.arange(n_d, dtype=np.int64),
            "name": [f"d{int(i)}" for i in range(n_d)],
        }
    )
    for nm, df in (("f", fact), ("d", dim)):
        (tmp_path / nm).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / nm / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    return session, session.parquet(tmp_path / "f"), session.parquet(tmp_path / "d"), fact, dim


@pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
def test_broadcast_matches_merge_and_pandas(tables, how):
    session, f, d, fact, dim = tables
    q = f.join(d, ["k"], ["dk"], how=how)

    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 1_000_000)
    bc = session.to_pandas(q)
    st = dict(session.last_query_stats)
    assert st["join_path"] == "broadcast-hash"
    assert st["join_kernel"] == "host-broadcast-hash"

    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 0)
    mg = session.to_pandas(q)
    assert session.last_query_stats["join_path"] == "single-partition"

    key = ["k", "x"]
    bc_s = bc.sort_values(key).reset_index(drop=True)
    mg_s = mg.sort_values(key).reset_index(drop=True)
    pd.testing.assert_frame_equal(bc_s, mg_s)

    if how == "inner":
        exp = fact.merge(dim, left_on="k", right_on="dk")
        assert len(bc) == len(exp)
    elif how == "left":
        exp = fact.merge(dim, left_on="k", right_on="dk", how="left")
        assert len(bc) == len(exp)
    elif how == "full":
        assert len(bc) == len(fact) + int((~dim.dk.isin(fact.k)).sum())


def test_broadcast_swaps_when_left_is_small(tables):
    """Small LEFT side: the probe swaps roles but pair orientation is
    preserved."""
    session, f, d, fact, dim = tables
    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 1_000_000)
    q = d.join(f, ["dk"], ["k"])
    got = session.to_pandas(q)
    assert session.last_query_stats["join_path"] == "broadcast-hash"
    exp = dim.merge(fact, left_on="dk", right_on="k")
    assert len(got) == len(exp)
    np.testing.assert_allclose(
        np.sort(got["x"].values), np.sort(exp["x"].values)
    )


def test_symmetric_sizes_keep_merge_path(tables):
    session, f, d, fact, dim = tables
    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 1_000_000)
    q = f.select("k").join(f, ["k"], ["k"])  # equal-size self-join
    # Self-join of equal sizes: not asymmetric enough for broadcast.
    session.to_pandas(q.limit(1))
    assert session.last_query_stats["join_path"] == "single-partition"


def test_broadcast_with_duplicate_build_keys(tmp_path):
    """The build side may repeat keys (not a clean dimension): the run
    expansion emits every pair."""
    rng = np.random.default_rng(23)
    big = pd.DataFrame({"k": rng.integers(0, 50, 8_000).astype(np.int64), "x": rng.normal(size=8_000)})
    small = pd.DataFrame({"dk": np.repeat(np.arange(50, dtype=np.int64), 3), "w": np.arange(150, dtype=np.int64)})
    for nm, df in (("big", big), ("small", small)):
        (tmp_path / nm).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / nm / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 1_000_000)
    b = session.parquet(tmp_path / "big")
    s = session.parquet(tmp_path / "small")
    got = session.to_pandas(b.join(s, ["k"], ["dk"]))
    assert session.last_query_stats["join_path"] == "broadcast-hash"
    exp = big.merge(small, left_on="k", right_on="dk")
    assert len(got) == len(exp)
    assert int(got.w.sum()) == int(exp.w.sum())


def test_broadcast_negative_keys_match(tmp_path):
    """Raw negative key VALUES must join (only null-coded rows are
    negative after factorization shifts the code space non-negative)."""
    big = pd.DataFrame({"k": np.tile(np.arange(-3, 2, dtype=np.int64), 8), "x": np.arange(40, dtype=np.int64)})
    small = pd.DataFrame({"dk": np.arange(-3, 2, dtype=np.int64), "w": np.arange(5, dtype=np.int64)})
    for nm, df in (("nbig", big), ("nsmall", small)):
        (tmp_path / nm).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / nm / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 1_000_000)
    got = session.to_pandas(
        session.parquet(tmp_path / "nbig").join(session.parquet(tmp_path / "nsmall"), ["k"], ["dk"])
    )
    assert session.last_query_stats["join_path"] == "broadcast-hash"
    assert len(got) == 40


def test_broadcast_all_null_keys_no_crash(tmp_path):
    big = pd.DataFrame({"k": pd.array([None] * 40, dtype="Int64"), "x": np.arange(40, dtype=np.int64)})
    small = pd.DataFrame({"dk": pd.array([None] * 5, dtype="Int64"), "w": np.arange(5, dtype=np.int64)})
    for nm, df in (("zbig", big), ("zsmall", small)):
        (tmp_path / nm).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / nm / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 1_000_000)
    got = session.to_pandas(
        session.parquet(tmp_path / "zbig").join(session.parquet(tmp_path / "zsmall"), ["k"], ["dk"])
    )
    assert len(got) == 0
