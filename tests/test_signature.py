"""Signature stability tests.

Analog of index/FileBasedSignatureProviderTests.scala:40-116: signature is
stable across recomputation, changes on file append/modify, and is pluggable.
"""

import os
import time
from pathlib import Path

from hyperspace_tpu.dataset import Dataset
from hyperspace_tpu.plan.nodes import Filter
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.signature import FileBasedSignatureProvider, create_signature_provider


def test_signature_stable_and_sensitive(sample_parquet):
    ds = Dataset.parquet(sample_parquet)
    p = create_signature_provider("fileBased")
    s1 = p.signature(ds.scan())
    s2 = p.signature(ds.scan())
    assert s1.kind == "fileBased"
    assert s1.value == s2.value

    # Signature covers the whole plan, not just the leaf.
    s_filter = p.signature(Filter(ds.scan(), col("key") == 1))
    assert s_filter.value == s1.value  # same data ⇒ same fingerprint

    # Appending a file changes the fingerprint.
    extra = Path(sample_parquet) / "part-9.parquet"
    extra.write_bytes(Path(sample_parquet, "part-0.parquet").read_bytes())
    s3 = p.signature(ds.scan())
    assert s3.value != s1.value
    extra.unlink()

    # Touching mtime changes the fingerprint too.
    f = Path(sample_parquet) / "part-0.parquet"
    st = f.stat()
    os.utime(f, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    s4 = p.signature(ds.scan())
    assert s4.value != s1.value


def test_provider_registry():
    from hyperspace_tpu.signature import SignatureProvider, register_signature_provider

    class Fake(SignatureProvider):
        name = "fake"

        def signature(self, plan):
            from hyperspace_tpu.metadata.log_entry import Fingerprint

            return Fingerprint("fake", "1")

    register_signature_provider(Fake)
    assert create_signature_provider("fake").signature(None).value == "1"
