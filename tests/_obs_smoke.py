"""EXPLAIN ANALYZE smoke on a TPC-DS query — the CI observability gate.

Run as ``python tests/_obs_smoke.py``: generates the tiny-SF TPC-DS
slice, builds the benchmark indexes, runs one query through
``explain(mode="analyze")``, and asserts the profile rendered with
measured operator evidence. Kept out of pytest collection (leading
underscore) because the tier-1 suite already covers profile semantics;
this is the cheap end-to-end "the whole pipeline renders" check."""

import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from benchmarks.tpcds import cached_tpcds, tpcds_indexes, tpcds_queries
    from hyperspace_tpu import Hyperspace, HyperspaceSession

    base = Path(tempfile.mkdtemp(prefix="hs_obs_smoke_"))
    roots = cached_tpcds(sf=0.01, cache_root=base)
    session = HyperspaceSession(system_path=str(base / "idx"), num_buckets=8)
    session.conf.set("hyperspace.obs.sink", str(base / "events.jsonl"))
    hs = Hyperspace(session)
    scans = {name: session.parquet(root) for name, root in roots.items()}
    tpcds_indexes(hs, scans)
    session.enable_hyperspace()
    queries = tpcds_queries(scans)
    name, plan = sorted(queries.items())[0]
    text = hs.explain(plan, mode="analyze")
    print(f"-- EXPLAIN ANALYZE {name} --")
    print(text)
    assert "EXPLAIN ANALYZE" in text and "total:" in text and "cache:" in text, text
    prof = session.last_profile()
    assert prof is not None and prof.root is not None and prof.root.wall_s > 0
    assert prof.operators(), "no operators profiled"
    assert (base / "events.jsonl").exists(), "sink received no trace"
    print(f"OK: {len(prof.operators())} operators profiled, "
          f"total {prof.total_s * 1e3:.1f}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
