"""TPC-DS slice correctness: the nine bench queries produce identical
results rewritten vs raw at a tiny scale factor, and a pandas
ground-truth check pins the semantics of representative queries
(star joins, CASE pivots, OR'd band predicates, count-star)."""

import numpy as np
import pandas as pd
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession

SF = 0.01


@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from benchmarks.tpcds import cached_tpcds, tpcds_indexes, tpcds_queries

    base = tmp_path_factory.mktemp("tpcds_data")
    roots = cached_tpcds(sf=SF, cache_root=base)
    session = HyperspaceSession(system_path=str(base / "idx"), num_buckets=8)
    hs = Hyperspace(session)
    scans = {name: session.parquet(root) for name, root in roots.items()}
    tpcds_indexes(hs, scans)
    queries = tpcds_queries(scans)
    frames = {
        name: pq.read_table(root).to_pandas() for name, root in roots.items()
    }
    return session, queries, frames


def test_all_queries_raw_equals_indexed(tpcds):
    from benchmarks.harness import assert_same_results

    session, queries, _ = tpcds
    # q44 probes a single store with no dimension join on an indexed key;
    # q18/q40/q50/q76/q84 join through keys no index buckets (bill_cdemo
    # chains, order+item pairs, customer triples, IS-NULL unions); every
    # other query's innermost join must ride an aligned / rebucketized /
    # pushdown path (outer dimension joins in the chain may legitimately
    # take the broadcast-hash path).
    no_aligned_join = {"q44", "q18", "q40", "q50", "q76", "q84"}
    for name, plan in queries.items():
        session.disable_hyperspace()
        raw = session.run(plan)
        session.enable_hyperspace()
        idx = session.run(plan)
        if name not in no_aligned_join:
            phys = repr(session.last_physical_plan)
            assert (
                "zero-exchange-aligned" in phys
                or "rebucketized-aligned" in phys
                or "bucket-preserved-aligned" in phys
                or "PartialAggPushdown" in phys
            ), name
        assert_same_results(name, raw, idx)


def test_q52_matches_pandas(tpcds):
    session, queries, f = tpcds
    session.enable_hyperspace()
    got = session.to_pandas(queries["q52"])
    ss, dd, item = f["store_sales"], f["date_dim"], f["item"]
    dd2 = dd[(dd.d_moy == 11) & (dd.d_year == 2000)]
    it2 = item[item.i_manager_id == 1]
    j = ss.merge(dd2, left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        it2, left_on="ss_item_sk", right_on="i_item_sk"
    )
    exp = (
        j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)["ss_ext_sales_price"]
        .sum()
        .rename(columns={"ss_ext_sales_price": "sum_sales"})
        .sort_values(["d_year", "sum_sales", "i_brand_id"], ascending=[True, False, True])
        .head(100)
        .reset_index(drop=True)
    )
    assert len(got) == len(exp)
    np.testing.assert_array_equal(got["i_brand_id"], exp["i_brand_id"])
    np.testing.assert_allclose(got["sum_sales"], exp["sum_sales"], rtol=1e-9)


def test_q43_day_pivot_matches_pandas(tpcds):
    session, queries, f = tpcds
    session.enable_hyperspace()
    got = session.to_pandas(queries["q43"]).reset_index(drop=True)
    ss, dd, store = f["store_sales"], f["date_dim"], f["store"]
    dd2 = dd[dd.d_year == 2000]
    j = ss.merge(dd2, left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        store, left_on="ss_store_sk", right_on="s_store_sk"
    )
    sun = (
        j[j.d_day_name == "Sunday"]
        .groupby(["s_store_name", "s_store_id"])["ss_sales_price"]
        .sum()
    )
    grp = got.set_index(["s_store_name", "s_store_id"])["sun_sales"]
    for key, v in sun.items():
        np.testing.assert_allclose(grp.loc[key], v, rtol=1e-9)


def test_q96_count_matches_pandas(tpcds):
    session, queries, f = tpcds
    session.enable_hyperspace()
    got = session.to_pandas(queries["q96"])
    ss, hd, td, store = (
        f["store_sales"],
        f["household_demographics"],
        f["time_dim"],
        f["store"],
    )
    j = (
        ss.merge(hd[hd.hd_dep_count == 7], left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        .merge(
            td[(td.t_hour == 20) & (td.t_minute >= 30)],
            left_on="ss_sold_time_sk",
            right_on="t_time_sk",
        )
        .merge(store[store.s_store_name == "ese"], left_on="ss_store_sk", right_on="s_store_sk")
    )
    assert int(got.loc[0, "cnt"]) == len(j)


def test_q48_band_predicate_matches_pandas(tpcds):
    session, queries, f = tpcds
    session.enable_hyperspace()
    got = session.to_pandas(queries["q48"])
    ss, cd, dd, ca = (
        f["store_sales"],
        f["customer_demographics"],
        f["date_dim"],
        f["customer_address"],
    )
    j = (
        ss.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        .merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
    )
    m1 = (
        ((j.cd_marital_status == "M") & (j.cd_education_status == "4 yr Degree") & j.ss_sales_price.between(100, 150))
        | ((j.cd_marital_status == "D") & (j.cd_education_status == "2 yr Degree") & j.ss_sales_price.between(50, 100))
        | ((j.cd_marital_status == "S") & (j.cd_education_status == "College") & j.ss_sales_price.between(150, 200))
    )
    m2 = (j.ca_country == "United States") & (
        (j.ca_state.isin(["CA", "OR", "WA"]) & j.ss_net_profit.between(0, 2000))
        | (j.ca_state.isin(["TX", "OH", "GA"]) & j.ss_net_profit.between(150, 3000))
        | (j.ca_state.isin(["FL", "NM", "KY"]) & j.ss_net_profit.between(50, 25000))
    )
    exp = int(j[m1 & m2].ss_quantity.sum())
    assert int(got.loc[0, "quantity"]) == exp
