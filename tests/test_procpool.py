"""parallel/procpool.py: the shared spawn-context worker lifecycle.

The satellite contract pinned here standalone (no builder involved): a
worker crash during a pooled run must abort with a typed
`WorkerCrashed` via the bounded join's liveness check — including a
REAL SIGKILLed worker — never hang the coordinator on a result queue
that will never fill."""

import os
import signal
import time

import pytest

from hyperspace_tpu import faults, stats
from hyperspace_tpu.exceptions import WorkerCrashed, WorkerFailed
from hyperspace_tpu.parallel.procpool import ProcessHost, TaskPool, spawn_context


# Worker bodies must be module-level (spawn pickles them by qualified
# name and re-imports this module in the child).

def _double(x):
    return x * 2


def _sleep_forever(_seconds):
    time.sleep(3600)


def _value_error(msg):
    raise ValueError(msg)


def _hard_exit(code):
    os._exit(code)


def _hit_point():
    faults.fault_point("build.exchange.write", "/tmp/probe")
    return "ok"


def _idle_until_stopped(stop_seconds):
    time.sleep(stop_seconds)


def _import_census():
    """Runs INSIDE a spawned worker: report which heavyweight modules
    the fresh interpreter paid for before the task body ran."""
    import sys

    return {
        "jax": "jax" in sys.modules,
        "jaxlib": "jaxlib" in sys.modules,
        "hyperspace": sorted(
            m for m in sys.modules if m.startswith("hyperspace_tpu")
        ),
    }


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def test_spawn_context_is_spawn():
    assert spawn_context().get_start_method() == "spawn"


def test_taskpool_collects_all_results():
    with TaskPool("hs-test") as pool:
        for i in range(3):
            pool.submit(i, _double, i)
        results = pool.join()
    assert results == {0: 0, 1: 2, 2: 4}


def test_posted_error_reraises_typed():
    """A worker body that raises posts the error; join re-raises it as a
    typed WorkerFailed carrying the worker-side traceback."""
    with TaskPool("hs-test") as pool:
        pool.submit("bad", _value_error, "boom-xyz")
        with pytest.raises(WorkerFailed) as ei:
            pool.join()
    assert ei.value.error_type == "ValueError"
    assert "boom-xyz" in str(ei.value)
    assert "worker traceback" in str(ei.value)


def test_sigkilled_worker_raises_typed_abort_bounded():
    """The satellite: a real SIGKILL mid-task must surface as a typed
    WorkerCrashed within a bounded wait (liveness check), not a hang."""
    before = stats.get("build.worker.crashes")
    with TaskPool("hs-test", poll_s=0.1, crash_grace_s=0.5) as pool:
        pool.submit("victim", _sleep_forever, 0)
        p = pool.host.get("victim")
        # Wait for the process to actually be up before killing it.
        deadline = time.monotonic() + 30
        while not p.is_alive() and time.monotonic() < deadline:
            time.sleep(0.05)
        os.kill(p.pid, signal.SIGKILL)
        t0 = time.monotonic()
        with pytest.raises(WorkerCrashed) as ei:
            pool.join()
        assert time.monotonic() - t0 < 30, "join did not bound the wait"
    assert ei.value.task_id == "victim"
    assert ei.value.exitcode == -signal.SIGKILL
    assert stats.get("build.worker.crashes") == before + 1


def test_hard_exit_worker_raises_typed_abort():
    with TaskPool("hs-test", poll_s=0.1, crash_grace_s=0.5) as pool:
        pool.submit("exiter", _hard_exit, 7)
        with pytest.raises(WorkerCrashed) as ei:
            pool.join()
    assert ei.value.exitcode == 7


def test_join_timeout_is_typed():
    with TaskPool("hs-test", poll_s=0.05) as pool:
        pool.submit("slow", _sleep_forever, 0)
        with pytest.raises(WorkerCrashed, match="timed out"):
            pool.join(timeout=0.5)
        # __exit__ terminates the straggler.
    assert not pool.host.get("slow").is_alive()


def test_fault_rules_ship_into_workers_and_observed_merge_back():
    """The coordinator's registered rules fire INSIDE the spawned worker
    (fresh per-process schedules), and the worker's observed points merge
    back on join — the cross-process leg of the deterministic harness."""
    faults.inject("build.exchange.write", times=1)
    with TaskPool("hs-test") as pool:
        pool.submit("w", _hit_point)
        with pytest.raises(WorkerFailed) as ei:
            pool.join()
    assert ei.value.error_type == "FaultError"
    assert "build.exchange.write" in faults.observed_points()
    faults.reset()
    # recording() (armed, zero rules) also sees worker-side points.
    with faults.recording() as seen:
        with TaskPool("hs-test") as pool:
            pool.submit("w", _hit_point)
            assert pool.join() == {"w": "ok"}
    assert "build.exchange.write" in seen


def test_worker_never_imports_jax_at_start():
    """The runtime mirror of static rule HSL019 (spawn-import purity):
    a spawned TaskPool worker — which imports procpool and the task
    body's module (this file) to unpickle its entry — must reach the
    task body with jax NOT in sys.modules. The static proof says the
    module-level import closure of every spawn-domain module is
    jax-free; this asserts the same fact in a real spawned interpreter,
    shipped back through the result envelope."""
    with TaskPool("hs-test") as pool:
        pool.submit("census", _import_census)
        results = pool.join()
    census = results["census"]
    assert census["jax"] is False, (
        "spawned worker paid the jax import before the task ran: "
        f"{census['hyperspace']}"
    )
    assert census["jaxlib"] is False
    # and the worker DID import the spawn plumbing (the census is not
    # vacuous — procpool and its jax-free deps are present).
    assert "hyperspace_tpu.parallel.procpool" in census["hyperspace"]
    assert "hyperspace_tpu.faults" in census["hyperspace"]


def test_process_host_stop_terminates_stragglers():
    host = ProcessHost("hs-test-host")
    p = host.spawn("w", _idle_until_stopped, args=(3600,))
    assert host.alive_count() == 1
    t0 = time.monotonic()
    host.stop(timeout=0.5, grace=5.0)
    assert time.monotonic() - t0 < 30
    assert not p.is_alive()
    assert host.alive_count() == 0
