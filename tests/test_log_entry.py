"""Golden-spec tests for the log entry JSON model.

Analog of index/IndexLogEntryTest.scala:25-120 which pins the exact on-disk
JSON layout.
"""

import json

from hyperspace_tpu.metadata.log_entry import (
    Content,
    CoveringIndex,
    FileInfo,
    Fingerprint,
    IndexLogEntry,
    Source,
    entry_from_json,
)


def make_entry() -> IndexLogEntry:
    return IndexLogEntry(
        id=0,
        state="ACTIVE",
        timestamp=1234.5,
        enabled=True,
        name="idx1",
        derived_dataset=CoveringIndex(
            indexed_columns=["key"],
            included_columns=["value"],
            schema=[
                {"name": "key", "dtype": "int64", "nullable": False},
                {"name": "value", "dtype": "float64", "nullable": False},
            ],
            num_buckets=8,
        ),
        content=Content(root="/idx/idx1", directories=["v__=0"]),
        source=Source(
            plan={"type": "scan", "root": "/data", "format": "parquet", "schema": []},
            fingerprint=Fingerprint("fileBased", "abc123"),
            files=[FileInfo("/data/p0.parquet", 100, 999)],
        ),
        extra={},
    )


GOLDEN = {
    "version": "0.1",
    "id": 0,
    "state": "ACTIVE",
    "timestamp": 1234.5,
    "enabled": True,
    "name": "idx1",
    "derivedDataset": {
        "kind": "CoveringIndex",
        "properties": {
            "indexedColumns": ["key"],
            "includedColumns": ["value"],
            "schema": [
                {"name": "key", "dtype": "int64", "nullable": False},
                {"name": "value", "dtype": "float64", "nullable": False},
            ],
            "numBuckets": 8,
        },
    },
    "content": {"root": "/idx/idx1", "directories": ["v__=0"]},
    "source": {
        "plan": {"type": "scan", "root": "/data", "format": "parquet", "schema": []},
        "fingerprint": {"kind": "fileBased", "value": "abc123"},
        "files": [{"path": "/data/p0.parquet", "size": 100, "mtimeNs": 999}],
    },
    "extra": {},
}


def test_to_json_matches_golden():
    assert make_entry().to_json() == GOLDEN


def test_round_trip():
    entry = make_entry()
    back = entry_from_json(json.loads(json.dumps(entry.to_json())))
    assert back == entry


def test_unknown_version_rejected():
    bad = dict(GOLDEN, version="9.9")
    try:
        entry_from_json(bad)
        assert False, "expected ValueError"
    except ValueError as e:
        assert "version" in str(e)


def test_with_state_bumps_timestamp():
    entry = make_entry()
    new = entry.with_state("DELETING")
    assert new.state == "DELETING"
    assert new.timestamp > entry.timestamp
    assert entry.state == "ACTIVE"  # original untouched
