"""Protocol-level action tests against FAKE log/data managers injected
through the collection manager's factory seam — the analog of the
reference's mock-based state-machine tests (ActionTest.scala:139-166
verifies the exact writeLog(0, CREATING) → writeLog(1, ACTIVE) →
latestStable swap sequence through mock(classOf[IndexLogManager]);
factories.scala:22-52 is the DI seam they inject through)."""

import pytest

from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.collection_manager import IndexCollectionManager
from hyperspace_tpu.metadata.log_entry import (
    Content,
    CoveringIndex,
    Fingerprint,
    IndexLogEntry,
    Source,
)


def _entry(state=states.ACTIVE, name="idx"):
    e = IndexLogEntry(
        name=name,
        derived_dataset=CoveringIndex(
            indexed_columns=["k"], included_columns=["v"],
            schema=[{"name": "k", "dtype": "int64", "nullable": False},
                    {"name": "v", "dtype": "float64", "nullable": False}],
            num_buckets=4,
        ),
        content=Content(root="/idx", directories=["v__=0"]),
        source=Source(plan={"type": "scan", "root": "/src", "format": "parquet",
                            "schema": [{"name": "k", "dtype": "int64", "nullable": False},
                                       {"name": "v", "dtype": "float64", "nullable": False}]},
                      fingerprint=Fingerprint(kind="fileBased", value="f0"),
                      files=[]),
    )
    e.state = state
    return e


class FakeLogManager:
    """In-memory log manager recording every protocol call in order."""

    def __init__(self, path=None, latest=None):
        self.path = path
        self.calls: list[tuple] = []
        self.logs: dict[int, IndexLogEntry] = {}
        if latest is not None:
            self.logs[0] = latest
        self.stable_id: int | None = 0 if latest is not None else None
        self.fail_write_ids: set[int] = set()

    def get_latest_id(self):
        return max(self.logs) if self.logs else None

    def get_latest_log(self):
        lid = self.get_latest_id()
        return self.logs.get(lid) if lid is not None else None

    def get_latest_stable_log(self):
        return self.logs.get(self.stable_id) if self.stable_id is not None else None

    def write_log(self, id, entry):
        self.calls.append(("write_log", id, entry.state))
        if id in self.fail_write_ids or id in self.logs:
            return False
        self.logs[id] = entry
        return True

    def delete_latest_stable_log(self):
        self.calls.append(("delete_latest_stable",))
        self.stable_id = None

    def create_latest_stable_log(self, id):
        self.calls.append(("create_latest_stable", id))
        self.stable_id = id


class FakeDataManager:
    def __init__(self, path=None):
        self.path = path
        self.deleted: list[int] = []

    def get_latest_version_id(self):
        return 0

    def get_path(self, version):
        return f"/idx/v__={version}"

    def get_version_ids(self):
        return [0]

    def delete(self, version):
        self.deleted.append(version)


class NoopAction(Action):
    transient_state = states.CREATING
    final_state = states.ACTIVE

    def build_log_entry(self):
        return _entry()


def test_run_commits_exact_two_phase_sequence():
    """Empty log: run() must write id 0 transient, id 1 final, then swap
    latestStable to 1 — the ActionTest.scala:139-166 sequence, minus the
    reference's delete-then-recreate of the pointer: the pointer is
    atomically REPLACED (never deleted first), so a concurrent reader can
    never catch a window with no pointer and fall into the backward scan."""
    lm = FakeLogManager()
    NoopAction(lm).run()
    assert lm.calls == [
        ("write_log", 0, states.CREATING),
        ("write_log", 1, states.ACTIVE),
        ("create_latest_stable", 1),
    ]
    assert ("delete_latest_stable",) not in lm.calls


def test_run_on_existing_log_advances_base_id_by_two():
    lm = FakeLogManager(latest=_entry(states.ACTIVE))
    NoopAction(lm).run()
    assert [c for c in lm.calls if c[0] == "write_log"] == [
        ("write_log", 1, states.CREATING),
        ("write_log", 2, states.ACTIVE),
    ]
    assert lm.stable_id == 2


def test_losing_cas_aborts_with_no_final_write():
    """A concurrent writer winning the transient CAS must abort the action
    before op()/end() — single-writer optimistic concurrency."""
    lm = FakeLogManager()
    lm.fail_write_ids = {0}
    with pytest.raises(HyperspaceError, match="Could not acquire proper state"):
        NoopAction(lm).run()
    assert lm.calls == [("write_log", 0, states.CREATING)]
    assert lm.stable_id is None


def test_cas_contention_retry_rereads_log_and_commits():
    """With hyperspace.retry.casAttempts > 1, a begin() that loses its
    CAS re-reads the log (fresh base_id) and retries the whole protocol
    instead of aborting — the committed ids sit ABOVE the winner's."""
    from hyperspace_tpu.utils import retry

    class ContendedLM(FakeLogManager):
        """The concurrent winner's entry materializes exactly when our
        CAS for id 0 fails — as a real race would leave the log."""

        def write_log(self, id, entry):
            if id == 0 and 0 not in self.logs:
                self.calls.append(("write_log", id, entry.state))
                self.logs[0] = _entry(states.ACTIVE)
                self.stable_id = 0
                return False
            return super().write_log(id, entry)

    lm = ContendedLM()
    retry.configure(cas_attempts=2)
    try:
        NoopAction(lm).run()
    finally:
        retry.configure(cas_attempts=1)
    assert [c for c in lm.calls if c[0] == "write_log"] == [
        ("write_log", 0, states.CREATING),  # lost to the winner
        ("write_log", 1, states.CREATING),  # re-read, retried above it
        ("write_log", 2, states.ACTIVE),
    ]
    assert lm.stable_id == 2


def test_op_failure_rolls_back_to_stable_and_cleans_up():
    """A software failure in op() must not leave the log transient: run()
    rolls the log back to the last stable state (DOESNOTEXIST when there
    is none), repoints latestStable, and calls the cleanup hook — the
    original exception still surfaces."""
    cleaned = []

    class ExplodingAction(NoopAction):
        def op(self):
            raise RuntimeError("mid-flight failure")

        def cleanup_failed_op(self):
            cleaned.append(True)

    lm = FakeLogManager()
    with pytest.raises(RuntimeError, match="mid-flight failure"):
        ExplodingAction(lm).run()
    assert lm.calls == [
        ("write_log", 0, states.CREATING),
        ("write_log", 1, states.DOESNOTEXIST),
        ("create_latest_stable", 1),
    ]
    assert lm.get_latest_log().state == states.DOESNOTEXIST
    assert lm.get_latest_stable_log().state == states.DOESNOTEXIST
    assert cleaned == [True]


def test_op_failure_with_prior_stable_restores_it():
    """With an ACTIVE entry in the log, a failed op() rolls back to
    ACTIVE — readers keep resolving the pre-action index."""
    class ExplodingAction(NoopAction):
        transient_state = states.REFRESHING

        def op(self):
            raise RuntimeError("mid-flight failure")

    lm = FakeLogManager(latest=_entry(states.ACTIVE))
    with pytest.raises(RuntimeError):
        ExplodingAction(lm).run()
    assert [c for c in lm.calls if c[0] == "write_log"] == [
        ("write_log", 1, states.REFRESHING),
        ("write_log", 2, states.ACTIVE),
    ]
    assert lm.get_latest_log().state == states.ACTIVE
    assert lm.stable_id == 2


def test_simulated_crash_leaves_transient_state_for_recover():
    """A hard crash (CrashPoint is a BaseException) must NOT trigger the
    in-process rollback — the dying writer gets no cleanup, and the log
    stays transient for recover() to repair from the next process."""
    from hyperspace_tpu.faults import CrashPoint

    class DyingAction(NoopAction):
        def op(self):
            raise CrashPoint("test.point")

    lm = FakeLogManager()
    with pytest.raises(CrashPoint):
        DyingAction(lm).run()
    assert lm.calls == [("write_log", 0, states.CREATING)]
    assert lm.get_latest_log().state == states.CREATING


def test_collection_manager_factory_seam_injects_fakes(tmp_path):
    """delete() through the manager must use ONLY the injected fakes —
    the factory seam the reference's IndexCollectionManagerTest uses."""
    created: dict = {}

    def log_factory(path):
        fake = FakeLogManager(path, latest=_entry(states.ACTIVE))
        created["log"] = fake
        return fake

    def data_factory(path):
        created["data"] = FakeDataManager(path)
        return created["data"]

    conf = HyperspaceConf(system_path=str(tmp_path / "sys"))
    mgr = IndexCollectionManager(
        conf, log_manager_factory=log_factory, data_manager_factory=data_factory
    )
    mgr.delete("idx")
    assert created["log"].get_latest_log().state == states.DELETED
    assert [c for c in created["log"].calls if c[0] == "write_log"] == [
        ("write_log", 1, states.DELETING),
        ("write_log", 2, states.DELETED),
    ]


def test_vacuum_fans_out_per_version_delete(tmp_path):
    """VacuumAction deletes every data version (VacuumActionTest.scala:50
    verifies the per-version delete fan-out through a mock data manager)."""
    class MultiVersionData(FakeDataManager):
        def get_version_ids(self):
            return [0, 1, 2]

        def get_latest_version_id(self):
            return 2

    created: dict = {}

    def log_factory(path):
        created["log"] = FakeLogManager(path, latest=_entry(states.DELETED))
        return created["log"]

    def data_factory(path):
        created["data"] = MultiVersionData(path)
        return created["data"]

    conf = HyperspaceConf(system_path=str(tmp_path / "sys"))
    mgr = IndexCollectionManager(
        conf, log_manager_factory=log_factory, data_manager_factory=data_factory
    )
    mgr.vacuum("idx")
    assert sorted(created["data"].deleted) == [0, 1, 2]
    assert created["log"].get_latest_log().state == states.DOESNOTEXIST
