"""String column-vs-column comparisons.

Codes from two different dictionaries are not comparable — the
translation layer remaps both sides into one merged sorted dictionary
(filter.py _StrColCmp), covering Col<>Col and SUBSTRING(col)<>
SUBSTRING(col) shapes (TPC-DS q19/q46/q68) with 3-valued null
semantics on both venues. Before this leaf existed the engine silently
compared raw codes and returned wrong rows.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import HyperspaceSession, col
from hyperspace_tpu.config import FILTER_VENUE
from hyperspace_tpu.exceptions import HyperspaceError


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("strcc")
    rng = np.random.default_rng(13)
    n = 4_000
    words = np.array(["apple", "pear", "kiwi", "fig", "plum"], dtype=object)
    df = pd.DataFrame(
        {
            "a": words[rng.integers(0, 5, n)],
            "b": words[rng.integers(0, 5, n)],
            "z1": [f"{x:05d}" for x in rng.integers(0, 99_999, n)],
            "z2": [f"{x:05d}" for x in rng.integers(0, 99_999, n)],
            "num": rng.integers(0, 9, n).astype(np.int64),
        }
    )
    df.loc[rng.random(n) < 0.06, "a"] = None
    root = tmp_path / "t"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    return session, session.parquet(root), df


@pytest.mark.parametrize("venue", ["host", "device"])
def test_col_col_comparisons_match_pandas(data, venue):
    session, ds, df = data
    session.conf.set(FILTER_VENUE, venue)
    known = df.a.notna()
    cases = {
        "eq": ((df.a == df.b) & known, col("a") == col("b")),
        "ne": ((df.a != df.b) & known, col("a") != col("b")),
        "lt": ((df.a < df.b) & known, col("a") < col("b")),
        "ge": ((df.a >= df.b) & known, col("a") >= col("b")),
    }
    for name, (exp_mask, pred) in cases.items():
        got = session.run(ds.filter(pred)).num_rows
        assert got == int(exp_mask.sum()), (venue, name, got, int(exp_mask.sum()))


@pytest.mark.parametrize("venue", ["host", "device"])
def test_substr_col_col(data, venue):
    session, ds, df = data
    session.conf.set(FILTER_VENUE, venue)
    got = session.run(ds.filter(col("z1").substr(1, 2) != col("z2").substr(1, 2))).num_rows
    assert got == int((df.z1.str[:2] != df.z2.str[:2]).sum())
    got2 = session.run(ds.filter(col("z1").substr(1, 5) == col("z2").substr(1, 5))).num_rows
    assert got2 == int((df.z1 == df.z2).sum())


def test_string_vs_numeric_column_raises(data):
    session, ds, _ = data
    # The plan validator rejects the cross-domain comparison before
    # execution (analysis/validator.py); the runtime guard in
    # ops/filter.py still backstops validator-off sessions.
    with pytest.raises(
        HyperspaceError,
        match="cannot compare string|string column with a non-string",
    ):
        session.run(ds.filter(col("a") == col("num")))
