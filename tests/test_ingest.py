"""Continuous-ingestion service (hyperspace_tpu/ingest/, docs/ingestion.md).

The contract under test, end to end:

- **Snapshot isolation**: a reader pinned BEFORE a micro-batch commit
  repeatably sees the old stamp across the live commit; a new reader
  sees the new rows immediately; releasing the stamp un-pins; a
  released handle fails loudly instead of silently reading live.
- **CDC tailing**: appended-row batches materialize idempotently (a
  crash between batch publish and cursor save re-writes the SAME
  file — no duplicate rows ever reach the index), and file arrivals
  are observed exactly once.
- **Crash sweeps**: a hard crash at EVERY fault point a daemon tick
  passes through (kill-mid-append) and at the compaction points
  (kill-mid-compact) leaves the index crash-consistent — recover()
  converges, queries answer correctly, and a disarmed re-tick drains
  to exactly-once delivery.
- **SIGKILL**: a processWorker-mode daemon killed with a real SIGKILL
  mid-stream leaves no torn snapshot; a fresh daemon drains the rest.
- **Controller backoff**: OpsController pauses the daemon while serve
  SLOs burn (audited, budgeted, hysteresis-gated) and resumes it on
  recovery; the kill switch releases a held pause.
"""

import json
import os
import signal
import time
from pathlib import Path

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (
    Hyperspace,
    HyperspaceSession,
    IndexConfig,
    col,
    faults,
    stats,
)
from hyperspace_tpu.analysis.duradomain import TORN_WINDOWS
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.faults import CrashPoint
from hyperspace_tpu.ingest import writer as ingest_writer
from hyperspace_tpu.ingest.tailer import Cursor, FileArrivalWatcher
from hyperspace_tpu.obs import events, metrics
from hyperspace_tpu.utils import retry


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends disarmed, with a no-sleep retry
    schedule (the test_fault_injection discipline)."""
    faults.reset()
    retry.configure(max_attempts=3, backoff_base=0.0, sleeper=lambda s: None)
    yield
    faults.reset()
    retry.configure(max_attempts=3, backoff_base=0.005, sleeper=time.sleep)


def _write_source(root: Path, n: int = 40) -> str:
    rng = np.random.default_rng(11)
    table = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "key": pa.array(np.arange(n, dtype=np.int64) % 4),
            "value": pa.array(rng.standard_normal(n)),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(table, root / "part-0.parquet")
    return str(root)


def _append_changelog(path: Path, start: int, n: int) -> None:
    with open(path, "a", encoding="utf-8") as f:
        for i in range(start, start + n):
            f.write(json.dumps({"id": i, "key": i % 4, "value": float(i)}) + "\n")


def _setup(tmp_path, n: int = 40, cdc: int = 24, **conf):
    """Source + ACTIVE index + changelog + watching daemon, harness
    disarmed during the build."""
    source = _write_source(tmp_path / "src", n=n)
    session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
    session.conf.set("hyperspace.ingest.enabled", "true")
    for k, v in conf.items():
        session.conf.set(k, v)
    hs = Hyperspace(session)
    hs.create_index(
        session.parquet(source), IndexConfig("idx1", ["key"], ["id", "value"])
    )
    session.enable_hyperspace()
    changelog = tmp_path / "changes.jsonl"
    _append_changelog(changelog, n, cdc)
    daemon = hs.ingest().watch("idx1", changelog=changelog)
    return source, session, hs, daemon, changelog


def _plan(session, source):
    return session.parquet(source).filter(col("key") == 1).select("id", "value")


def _ids(session, source, snapshot=None):
    out = session.run(_plan(session, source), snapshot=snapshot).decode()
    return sorted(int(i) for i in out["id"])


def _query_matches(session, source: str) -> None:
    """Canonical probe: the indexed filter answers row-identically to
    pandas over the raw source (whatever files exist right now)."""
    import pyarrow.dataset as pads

    got = session.to_pandas(_plan(session, source))
    raw = pads.dataset(source, format="parquet").to_table().to_pandas()
    exp = raw[raw["key"] == 1][["id", "value"]]
    cols = ["id", "value"]
    pd.testing.assert_frame_equal(
        got[cols].sort_values(cols).reset_index(drop=True),
        exp[cols].sort_values(cols).reset_index(drop=True),
        check_dtype=False,
    )


# ---------------------------------------------------------------------------
# MVCC snapshot isolation
# ---------------------------------------------------------------------------


class TestSnapshotIsolation:
    def test_pinned_reader_repeatable_across_live_commit(self, tmp_path):
        """THE tentpole property: pin before the commit, commit a live
        micro-batch, and the pinned reader repeatably sees the old
        world while a fresh reader sees the new rows."""
        source, session, hs, daemon, _ = _setup(tmp_path)
        snap = session.pin_snapshot()
        before = _ids(session, source, snapshot=snap)
        assert before  # key==1 exists in the seed data

        out = daemon.tick()
        assert out["commits"] == 1  # the CDC batch committed underneath us

        live = _ids(session, source)
        assert set(live) > set(before)  # new reader sees the new rows
        # Repeatable: the pinned view is byte-stable across the commit,
        # read after read.
        assert _ids(session, source, snapshot=snap) == before
        assert _ids(session, source, snapshot=snap) == before
        assert stats.get("ingest.pinned_reads") >= 3

        snap.release()
        # Release un-pins: the same session reads the live world again.
        assert _ids(session, source) == live

    def test_released_snapshot_fails_loudly(self, tmp_path):
        source, session, hs, daemon, _ = _setup(tmp_path)
        with session.pin_snapshot() as snap:
            _ids(session, source, snapshot=snap)
        with pytest.raises(HyperspaceError, match="snapshot released"):
            session.run(_plan(session, source), snapshot=snap)

    def test_stamp_versions_the_plan_cache_key(self, tmp_path):
        """A pinned query and a live query after a commit must never
        share a cache entry: the snapshot stamp replaces the live
        version vector in the plan-cache key."""
        from hyperspace_tpu.serve.plan_cache import versioned_plan_key

        source, session, hs, daemon, _ = _setup(tmp_path)
        snap = session.pin_snapshot()
        plan = _plan(session, source)
        # run_query pins the plan before keying — mirror that order.
        pinned = snap.pin_plan(plan)
        k_pinned = versioned_plan_key(session, pinned, snapshot=snap)
        assert k_pinned == versioned_plan_key(session, snap.pin_plan(plan), snapshot=snap)
        daemon.tick()
        # Live key moved with the commit; pinned key did not.
        assert versioned_plan_key(session, plan) != k_pinned
        assert versioned_plan_key(session, snap.pin_plan(plan), snapshot=snap) == k_pinned
        snap.release()

    def test_snapshot_pins_unindexed_sources_on_first_touch(self, tmp_path):
        """A source no index covers is pinned at first read: files that
        arrive later are invisible to the snapshot."""
        extra = tmp_path / "plain"
        _write_source(extra, n=20)
        source, session, hs, daemon, _ = _setup(tmp_path)
        snap = session.pin_snapshot()
        q = session.parquet(str(extra)).select("id")
        n0 = len(session.run(q, snapshot=snap).decode()["id"])
        pq.write_table(
            pa.table({"id": [900], "key": [0], "value": [0.0]}),
            extra / "late.parquet",
        )
        assert len(session.run(q, snapshot=snap).decode()["id"]) == n0
        assert len(session.run(q).decode()["id"]) == n0 + 1
        snap.release()


# ---------------------------------------------------------------------------
# CDC tailer + arrival watcher
# ---------------------------------------------------------------------------


class TestTailer:
    def test_arrival_watcher_sees_each_file_once(self, tmp_path):
        root = _write_source(tmp_path / "src", n=10)
        w = FileArrivalWatcher(root, "parquet", Cursor(tmp_path / "cur.json"))
        assert w.poll() == 1  # the seed file, observed once
        assert w.poll() == 0
        pq.write_table(
            pa.table({"id": [99], "key": [0], "value": [0.0]}),
            Path(root) / "part-9.parquet",
        )
        assert w.poll() == 1
        assert w.poll() == 0

    def test_tailer_waits_for_complete_lines(self, tmp_path):
        dest = tmp_path / "dest"
        dest.mkdir()
        log = tmp_path / "c.jsonl"
        log.write_text(json.dumps({"id": 1, "v": 1}) + "\n" + '{"id": 2, "v"')
        t = __import__(
            "hyperspace_tpu.ingest.tailer", fromlist=["CdcTailer"]
        ).CdcTailer(log, dest, Cursor(tmp_path / "cur.json"))
        assert t.poll(100) == 1  # only the complete line
        with open(log, "a", encoding="utf-8") as f:
            f.write(': 2}\n')
        assert t.poll(100) == 1  # the completed tail line, exactly once
        assert t.poll(100) == 0

    def test_batch_publish_fsyncs_data_before_the_rename(self, tmp_path,
                                                         monkeypatch):
        """Atomic-publish completeness (HSL027 regression): the batch
        bytes are fsynced before os.replace, so a crash can never make
        a zero-length cdc- file's NAME durable ahead of its data."""
        from hyperspace_tpu.ingest.tailer import CdcTailer

        calls = []
        real_fsync, real_replace = os.fsync, os.replace

        def spy_fsync(fd):
            calls.append("fsync")
            return real_fsync(fd)

        def spy_replace(src, dst):
            calls.append(("replace", os.path.basename(str(dst))))
            return real_replace(src, dst)

        monkeypatch.setattr(os, "fsync", spy_fsync)
        monkeypatch.setattr(os, "replace", spy_replace)
        dest = tmp_path / "dest"
        dest.mkdir()
        log = tmp_path / "c.jsonl"
        _append_changelog(log, 0, 6)
        t = CdcTailer(log, dest, Cursor(tmp_path / "cur.json"))
        assert t.poll(100) == 6
        publish = next(
            i for i, c in enumerate(calls)
            if isinstance(c, tuple) and c[1].startswith("cdc-")
        )
        assert "fsync" in calls[:publish], calls

    def test_crash_between_batch_and_cursor_is_idempotent(self, tmp_path):
        """ingest.tail fires after the batch file publishes, before the
        cursor saves — the canonical torn window. The re-poll must
        rewrite the SAME batch (same offset, same seq), not append a
        duplicate."""
        from hyperspace_tpu.ingest.tailer import CdcTailer

        dest = tmp_path / "dest"
        dest.mkdir()
        log = tmp_path / "c.jsonl"
        _append_changelog(log, 0, 6)
        t = CdcTailer(log, dest, Cursor(tmp_path / "cur.json"))
        faults.inject("ingest.tail", crash=True, at_call=1)
        with pytest.raises(CrashPoint):
            t.poll(100)
        faults.reset()
        batches = sorted(dest.glob("cdc-*.parquet"))
        assert len(batches) == 1  # published before the crash
        assert t.poll(100) == 6  # replay from the unadvanced cursor
        batches = sorted(dest.glob("cdc-*.parquet"))
        assert len(batches) == 1  # rewritten, not duplicated
        table = pq.read_table(batches[0])
        assert sorted(table.column("id").to_pylist()) == list(range(6))
        assert t.poll(100) == 0


# ---------------------------------------------------------------------------
# Crash sweeps: kill-mid-append, kill-mid-compact
# ---------------------------------------------------------------------------


def _assert_converges(tmp_path, source, session, hs, daemon, point, total_ids):
    """Post-crash invariants: stable log resolves, recover() converges,
    queries answer correctly, and a disarmed drain reaches exactly-once
    delivery of every CDC row."""
    ctx = f"point={point}"
    mgr = session.manager
    lm = mgr.log_manager_factory(mgr.path_resolver.get_index_path("idx1"))
    lm.get_latest_stable_log()  # 1. still resolves, crash or not
    hs.recover("idx1")  # 2. converges
    again = hs.recover("idx1")  # 3. idempotent
    assert not again["rolled"] and again["orphans_removed"] == 0, ctx
    _query_matches(session, source)  # 4. correct on whatever landed
    # 5. disarmed re-ticks drain to exactly-once delivery.
    assert daemon.drain(timeout=60), ctx
    got = _ids(session, source)
    assert got == sorted(i for i in total_ids if i % 4 == 1), ctx


class TestCrashSweep:
    def test_kill_mid_append_every_tick_fault_point(self, tmp_path_factory):
        """Discover every fault point a committing tick passes through
        (ingest.tail, ingest.commit, then the refresh action's own
        log/bucket points), then replay with a hard crash at each."""
        base = tmp_path_factory.mktemp("disc")
        source, session, hs, daemon, _ = _setup(base)
        with faults.recording() as seen:
            daemon.tick()
        points = sorted(seen)
        assert "ingest.tail" in points and "ingest.commit" in points

        crashed_at = []
        for point in points:
            tmp = tmp_path_factory.mktemp("sweep")
            source, session, hs, daemon, _ = _setup(tmp)
            faults.inject(point, crash=True, at_call=1)
            try:
                daemon.tick()
            except CrashPoint:
                crashed_at.append(point)
            finally:
                faults.reset()
            _assert_converges(
                tmp, source, session, hs, daemon, point, range(40 + 24)
            )
        assert crashed_at, f"no crash fired across {points}"

    def test_kill_mid_compact(self, tmp_path_factory):
        """Deltas past lifecycle.maxDeltas trigger advisor-gated
        compaction; a hard crash inside it (at ingest.compact and at
        the optimize action's stable-log swap) must leave the merged
        state recoverable and the data exactly-once."""
        for point in ("ingest.compact", "log.stable.write"):
            tmp = tmp_path_factory.mktemp("compact")
            source, session, hs, daemon, changelog = _setup(
                tmp,
                **{
                    "hyperspace.advisor.lifecycle.autoOptimize": "true",
                    "hyperspace.advisor.lifecycle.maxDeltas": "1",
                },
            )
            daemon.tick()  # delta 1 (the seeded CDC batch)
            _append_changelog(changelog, 64, 8)
            # This tick appends delta 2 then crosses maxDeltas=1 and
            # compacts — crash inside the compaction.
            faults.inject(point, crash=True, at_call=2 if point == "log.stable.write" else 1)
            with pytest.raises(CrashPoint):
                daemon.tick()
            faults.reset()
            _assert_converges(
                tmp, source, session, hs, daemon, point, range(40 + 24 + 8)
            )

    def test_compaction_runs_and_is_deferred_while_burning(self, tmp_path):
        # Advisor gate OFF during setup so the ticks below only commit.
        source, session, hs, daemon, changelog = _setup(
            tmp_path, **{"hyperspace.advisor.lifecycle.maxDeltas": "1"}
        )
        daemon.tick()  # delta 1 (the seeded CDC batch)
        _append_changelog(changelog, 64, 8)
        daemon.tick()  # delta 2: past maxDeltas, but the gate is off
        assert ingest_writer.delta_count(session, "idx1") > 1
        session.conf.set("hyperspace.advisor.lifecycle.autoOptimize", "true")
        # While SLOs burn, the compaction is deferred — not skipped
        # silently: the deferral is counted.
        base = stats.get("ingest.deferred")
        assert ingest_writer.maybe_compact(hs, "idx1", burning=True) is False
        assert stats.get("ingest.deferred") == base + 1
        assert ingest_writer.delta_count(session, "idx1") > 1
        # Calm again: the compaction fires and collapses the deltas.
        assert ingest_writer.maybe_compact(hs, "idx1", burning=False) is True
        assert stats.get("ingest.compactions") >= 1
        assert ingest_writer.delta_count(session, "idx1") <= 1
        _query_matches(session, source)


# ---------------------------------------------------------------------------
# Torn-window sweeps, driven BY NAME from the static registry
# ---------------------------------------------------------------------------


def _drive_batch_before_cursor(tmp_path_factory, point):
    """Kill between the CDC batch publish and the cursor save: the
    batch must be whole on disk, the cursor must not have advanced, and
    the re-poll must rewrite the SAME seq-named file."""
    from hyperspace_tpu.ingest.tailer import CdcTailer

    tmp = tmp_path_factory.mktemp("torn_tail")
    dest = tmp / "dest"
    dest.mkdir()
    log = tmp / "c.jsonl"
    _append_changelog(log, 0, 6)
    t = CdcTailer(log, dest, Cursor(tmp / "cur.json"))
    faults.inject(point, crash=True, at_call=1)
    with pytest.raises(CrashPoint):
        t.poll(100)
    faults.reset()
    # First half of the window held: the batch published whole …
    (batch,) = sorted(dest.glob("cdc-*.parquet"))
    # … and the second half never ran: no cursor was published.
    assert not (tmp / "cur.json").exists()
    assert t.poll(100) == 6  # replay from the unadvanced cursor
    assert sorted(dest.glob("cdc-*.parquet")) == [batch]  # rewritten
    table = pq.read_table(batch)
    assert sorted(table.column("id").to_pylist()) == list(range(6))
    assert t.poll(100) == 0


def _drive_commit_before_lag_stamp(tmp_path_factory, point):
    """Kill between the micro-batch commit and the daemon's lag/commit
    stamp: the commit is durable, the bookkeeping is torn, recover()
    converges, and the disarmed drain restamps."""
    tmp = tmp_path_factory.mktemp("torn_stamp")
    source, session, hs, daemon, changelog = _setup(tmp)
    faults.inject(point, crash=True, at_call=1)
    with pytest.raises(CrashPoint):
        daemon.tick()
    faults.reset()
    # The commit landed but the stamp never did — the torn state the
    # window declares.
    assert daemon.snapshot()["last_commit_ids"] == {}
    _assert_converges(tmp, source, session, hs, daemon, point, range(40 + 24))
    # The stamp is advisory bookkeeping: the next COMMITTING tick
    # restamps it from the log.
    _append_changelog(changelog, 64, 4)
    daemon.tick()
    assert daemon.snapshot()["last_commit_ids"].get("idx1", 0) >= 1


_TORN_WINDOW_DRIVERS = {
    "ingest.cdc.batch_before_cursor": _drive_batch_before_cursor,
    "ingest.commit_before_lag_stamp": _drive_commit_before_lag_stamp,
}


class TestTornWindowSweep:
    """Parametrized over the NAMES in `analysis.duradomain.TORN_WINDOWS`:
    an ingest window added to the registry without a driver here fails
    with a KeyError, so the crash sweep can never silently drift from
    the statically proven protocol set."""

    @pytest.mark.parametrize(
        "window", sorted(k for k in TORN_WINDOWS if k.startswith("ingest."))
    )
    def test_kill_inside_window_converges(self, window, tmp_path_factory):
        _fn, _first, _second, point, why = TORN_WINDOWS[window]
        assert point in faults.KNOWN_POINTS, why
        _TORN_WINDOW_DRIVERS[window](tmp_path_factory, point)


# ---------------------------------------------------------------------------
# Daemon lifecycle, drain, healthz
# ---------------------------------------------------------------------------


class TestDaemonLifecycle:
    def test_disabled_kill_switch_makes_ticks_noops(self, tmp_path):
        source, session, hs, daemon, _ = _setup(tmp_path)
        session.conf.set("hyperspace.ingest.enabled", "false")
        base = stats.get("ingest.ticks")
        out = daemon.tick()
        assert stats.get("ingest.ticks") == base and out["commits"] == 0

    def test_pause_defers_resume_commits(self, tmp_path):
        source, session, hs, daemon, _ = _setup(tmp_path)
        daemon.pause(reason="test")
        assert daemon.paused()
        out = daemon.tick()
        assert out["commits"] == 0 and stats.get("ingest.deferred") >= 1
        daemon.resume()
        out = daemon.tick()
        assert out["commits"] == 1
        names = [e["name"] for e in events.recent()]
        assert "ingest.paused" in names and "ingest.resumed" in names

    def test_watch_requires_existing_index(self, tmp_path):
        session = HyperspaceSession(system_path=str(tmp_path / "sys"))
        daemon = Hyperspace(session).ingest()
        with pytest.raises(HyperspaceError, match="create the index first"):
            daemon.watch("nope")

    def test_thread_mode_start_commits_then_drains(self, tmp_path):
        source, session, hs, daemon, changelog = _setup(
            tmp_path, **{"hyperspace.ingest.pollSeconds": "0.05"}
        )
        daemon.start()
        try:
            assert daemon.drain(timeout=60)
            assert set(_ids(session, source)) >= {i for i in range(64) if i % 4 == 1}
            _append_changelog(changelog, 64, 8)
            assert daemon.drain(timeout=60)
            got = _ids(session, source)
            assert got == [i for i in range(72) if i % 4 == 1]
        finally:
            daemon.stop()
        snap = daemon.snapshot()
        assert not snap["running"] and snap["commits"] >= 2

    def test_snapshot_shape_for_healthz(self, tmp_path):
        source, session, hs, daemon, _ = _setup(tmp_path)
        daemon.tick()
        snap = daemon.snapshot()
        assert snap["watched"] == ["idx1"]
        assert snap["enabled"] and not snap["running"]
        assert snap["last_commit_ids"]["idx1"] >= 1
        assert snap["last_commit_lag_seconds"] is not None

    def test_lagging_event_when_commit_cannot_keep_up(self, tmp_path):
        source, session, hs, daemon, _ = _setup(
            tmp_path, **{"hyperspace.ingest.maxLagSeconds": "0.5"}
        )
        # Observe the pending data but fail the commit (transient faults
        # exhaust the retry budget), then tick past the lag bound.
        t = [0.0]
        daemon._clock = lambda: t[0]
        with faults.injected("log.write", times=100):
            daemon.tick(now=0.0)
            t[0] = 10.0
            daemon.tick(now=10.0)
        assert any(e["name"] == "ingest.lagging" for e in events.recent())
        assert stats.get("ingest.commit_failures") >= 1


# ---------------------------------------------------------------------------
# SIGKILL: processWorker mode leaves no torn snapshot
# ---------------------------------------------------------------------------


class TestSigkill:
    def test_sigkilled_daemon_leaves_no_torn_snapshot(self, tmp_path):
        """A REAL SIGKILL (no cleanup handlers) against the worker
        process mid-stream: the last stable log still resolves,
        recover() converges, queries answer correctly, and a fresh
        daemon drains the remaining CDC rows exactly once."""
        source, session, hs, daemon, changelog = _setup(
            tmp_path,
            cdc=48,
            **{
                "hyperspace.ingest.processWorker": "true",
                "hyperspace.ingest.pollSeconds": "0.05",
                "hyperspace.ingest.cdcBatchRows": "8",
            },
        )
        mgr = session.manager
        lm = mgr.log_manager_factory(mgr.path_resolver.get_index_path("idx1"))
        base_id = lm.get_latest_id()
        daemon.start()
        try:
            pid = daemon.worker_pid()
            assert pid is not None
            # Wait until the worker has committed at least once, so the
            # kill lands mid-stream rather than pre-flight.
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if (lm.get_latest_id() or 0) > (base_id or 0):
                    break
                time.sleep(0.05)
            assert (lm.get_latest_id() or 0) > (base_id or 0), "worker never committed"
            os.kill(pid, signal.SIGKILL)  # no cleanup handlers run
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and daemon._host.alive_count() > 0:
                time.sleep(0.05)
            assert daemon._host.alive_count() == 0
        finally:
            daemon.stop()
        # No torn snapshot: stable state resolves and recovery converges.
        assert lm.get_latest_stable_log() is not None
        hs.recover("idx1")
        _query_matches(session, source)
        # A fresh (thread-mode) daemon finishes the job exactly-once.
        session.conf.set("hyperspace.ingest.processWorker", "false")
        d2 = hs.ingest().watch("idx1", changelog=changelog)
        assert d2.drain(timeout=120)
        got = _ids(session, source)
        assert got == [i for i in range(40 + 48) if i % 4 == 1]


# ---------------------------------------------------------------------------
# Controller backoff: pause while burning, resume on recovery
# ---------------------------------------------------------------------------


class _CtrlSession:
    """The session surface OpsController + IngestDaemon read: conf and
    the lock-guarded index_health map (test_controller.FakeSession)."""

    def __init__(self, tmp_path, **conf_overrides):
        import threading

        self.conf = HyperspaceConf()
        self.conf.set("hyperspace.system.path", str(tmp_path / "sys"))
        self.conf.set("hyperspace.controller.enabled", "true")
        self.conf.set("hyperspace.ingest.enabled", "true")
        for k, v in conf_overrides.items():
            self.conf.set(k, v)
        self._state_lock = threading.RLock()
        self.index_health = {}


class _CtrlHyperspace:
    def __init__(self, session):
        self.session = session

    def recover(self, name=None):
        return {}

    def lifecycle(self):
        class _L:
            def sweep(self):
                return {"applied": [], "skipped": [], "failed": []}

        return _L()


def _drive_page(completed, failed, ctrl, t0=0.0):
    """Baseline traffic then a failure burst — two consecutive page
    ticks (hysteresis 2) so the controller actuates."""
    completed.inc(10_000)
    ctrl.step(now=t0)
    ctrl.step(now=t0 + 4000.0)
    failed.inc(3_000)
    ctrl.step(now=t0 + 4030.0)  # page tick 1: hysteresis holds
    ctrl.step(now=t0 + 4031.0)  # page tick 2: actuate
    return t0 + 4031.0


def _actuations(action):
    return [
        e
        for e in events.recent()
        if e["name"] == "controller.actuation"
        and e["fields"]["action"] == action
    ]


class TestControllerBackoff:
    def _wire(self, tmp_path, **conf):
        from hyperspace_tpu.ingest.daemon import IngestDaemon
        from hyperspace_tpu.serve.controller import OpsController

        session = _CtrlSession(tmp_path, **conf)
        hs = _CtrlHyperspace(session)
        daemon = IngestDaemon(hs)
        ctrl = OpsController(hs, clock=lambda: 0.0, ingest=daemon)
        completed = metrics.counter("serve.completed")
        failed = metrics.counter("serve.failed")
        return session, daemon, ctrl, completed, failed

    def test_burn_pauses_ingest_recovery_resumes(self, tmp_path):
        session, daemon, ctrl, completed, failed = self._wire(tmp_path)
        t = _drive_page(completed, failed, ctrl)
        assert daemon.paused()
        assert ctrl.snapshot()["ingest_paused"]
        evts = _actuations("ingest.pause")
        assert evts and evts[-1]["fields"]["trigger"] == "slo.page"
        assert evts[-1]["fields"]["outcome"] == "executed"
        # Daemon honors it: ticks defer instead of committing.
        base = stats.get("ingest.deferred")
        daemon.tick()
        assert stats.get("ingest.deferred") == base + 1
        # Clean traffic pushes the burst out of the page windows; two
        # consecutive ok ticks (recovery hysteresis) release the pause.
        completed.inc(1_000_000)
        ctrl.step(now=t + 70.0)  # ok tick 1: still paused
        assert daemon.paused()
        ctrl.step(now=t + 71.0)  # ok tick 2: resume
        assert not daemon.paused()
        assert not ctrl.snapshot()["ingest_paused"]
        resumes = _actuations("ingest.resume")
        assert resumes and resumes[-1]["fields"]["trigger"] == "slo.recovered"

    def test_pause_respects_hysteresis(self, tmp_path):
        session, daemon, ctrl, completed, failed = self._wire(tmp_path)
        completed.inc(10_000)
        ctrl.step(now=0.0)
        ctrl.step(now=4000.0)
        failed.inc(3_000)
        ctrl.step(now=4030.0)  # page tick 1 of hysteresis 2
        assert not daemon.paused()  # no actuation on a single page tick

    def test_kill_switch_releases_held_pause(self, tmp_path):
        session, daemon, ctrl, completed, failed = self._wire(tmp_path)
        _drive_page(completed, failed, ctrl)
        assert daemon.paused()
        session.conf.set("hyperspace.controller.enabled", "false")
        ctrl.step(now=9000.0)
        assert not daemon.paused()
        assert not ctrl.snapshot()["ingest_paused"]

    def test_pause_spends_actuation_budget(self, tmp_path):
        """The pause goes through the budgeted _actuate path — with the
        hourly budget already spent, the controller degrades to
        observe-only and the daemon keeps committing."""
        session, daemon, ctrl, completed, failed = self._wire(
            tmp_path, **{"hyperspace.controller.actuationBudget": "0"}
        )
        _drive_page(completed, failed, ctrl)
        assert not daemon.paused()
        # Audited as observe-only, never executed: budget discipline.
        evts = _actuations("ingest.pause")
        assert evts and all(e["fields"]["outcome"] == "observe_only" for e in evts)


# ---------------------------------------------------------------------------
# Registry honesty (the ingest.* names this subsystem declares)
# ---------------------------------------------------------------------------


class TestRegistries:
    def test_fault_points_known(self):
        for point in ("ingest.tail", "ingest.commit", "ingest.compact"):
            assert point in faults.KNOWN_POINTS

    def test_counters_declared(self):
        for c in (
            "ingest.ticks",
            "ingest.commits",
            "ingest.commit_failures",
            "ingest.rows",
            "ingest.bytes",
            "ingest.compactions",
            "ingest.compact_failures",
            "ingest.deferred",
            "ingest.snapshots",
            "ingest.pinned_reads",
        ):
            assert c in stats.KNOWN_COUNTERS, c

    def test_error_contracts_cover_daemon_entry_points(self):
        from hyperspace_tpu.exceptions import ERROR_CONTRACTS

        for qname in (
            "hyperspace_tpu.ingest.daemon.IngestDaemon.tick",
            "hyperspace_tpu.ingest.daemon._service_entry",
            "hyperspace_tpu.ingest.tailer.CdcTailer.poll",
            "hyperspace_tpu.ingest.writer.commit_micro_batch",
            "hyperspace_tpu.ingest.writer.maybe_compact",
        ):
            assert qname in ERROR_CONTRACTS, qname
