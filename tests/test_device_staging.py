"""Device data path: Arrow→device zero-copy staging + fused-kernel
venue parity (docs/architecture.md "device data path").

The contract this suite pins: the THREE execution configurations —
host venues, device venues with staged uploads, and device venues with
the fused Pallas kernels engaged — produce byte-identical results for
every query class (filter / join / group_agg / join_agg) over nullable,
dict-coded, zero-row, and offset-view inputs; the staging layer keeps
eligible columns as zero-copy buffer views (counted) and degrades to
the copied path for everything else; and the byte-budgeted caches
account dict-coded columns at their (codes + dictionary) footprint.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col, lit
from hyperspace_tpu import stats
from hyperspace_tpu.config import (
    AGG_VENUE,
    DEVICE_FUSED_KERNELS,
    DEVICE_STAGING_ENABLED,
    FILTER_VENUE,
    JOIN_VENUE,
    SORT_VENUE,
)
from hyperspace_tpu.execution import device_cache as dc
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution import staging
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.schema import Schema

N = 4_000


@pytest.fixture(autouse=True)
def _staging_on():
    staging.set_enabled(True)
    yield
    staging.set_enabled(True)


@pytest.fixture
def dataset(tmp_path):
    """Fact/dim pair exercising every staging class: null-free ints
    (zero-copy eligible), a nullable int column, dict-coded strings, and
    an INTEGER-VALUED float column (so fused sums are provably exact and
    must engage the Pallas path)."""
    rng = np.random.default_rng(7)
    fact = pa.table(
        {
            "k": rng.integers(0, 200, N).astype(np.int32),
            "q": rng.integers(0, 1000, N).astype(np.float64),  # integral floats
            "n": pa.array(
                [None if i % 7 == 0 else int(i % 97) for i in range(N)],
                type=pa.int64(),
            ),
            "s": pa.array([f"cat_{i % 13:02d}" for i in range(N)]),
        }
    )
    dim = pa.table(
        {
            "k": np.arange(180, dtype=np.int32),
            "w": rng.integers(0, 50, 180).astype(np.float64),
            "t": pa.array([f"tag_{i % 5}" for i in range(180)]),
        }
    )
    (tmp_path / "fact").mkdir()
    (tmp_path / "dim").mkdir()
    pq.write_table(fact, tmp_path / "fact" / "p.parquet")
    pq.write_table(dim, tmp_path / "dim" / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=8)
    hs = Hyperspace(session)
    fs = session.parquet(tmp_path / "fact")
    ds = session.parquet(tmp_path / "dim")
    hs.create_index(fs, IndexConfig("pf_k", ["k"], ["q", "n", "s"]))
    hs.create_index(ds, IndexConfig("pd_k", ["k"], ["w", "t"]))
    session.enable_hyperspace()
    return session, fs, ds


def _canon(table: ColumnTable):
    """Decoded columns in a deterministic row order, for EXACT (bitwise
    for floats — no tolerance) cross-venue comparison."""
    dec = table.decode()
    names = sorted(dec)
    if not names or table.num_rows == 0:
        return {k: np.asarray(v) for k, v in dec.items()}
    keys = [np.asarray(dec[n], dtype="U32") if dec[n].dtype == object else dec[n] for n in reversed(names)]
    order = np.lexsort(tuple(np.nan_to_num(k.astype(np.float64), nan=-1e300) if k.dtype.kind == "f" else k for k in keys))
    return {k: np.asarray(v)[order] for k, v in dec.items()}


def _assert_identical(a: ColumnTable, b: ColumnTable, label: str):
    ca, cb = _canon(a), _canon(b)
    assert set(ca) == set(cb), label
    for name in ca:
        va, vb = ca[name], cb[name]
        assert len(va) == len(vb), (label, name)
        if va.dtype.kind == "f" and vb.dtype.kind == "f":
            # Bitwise: the venues must agree to the last ulp.
            ints = f"i{va.dtype.itemsize}"
            assert np.array_equal(va.view(ints), vb.view(ints)), (label, name)
        else:
            assert np.array_equal(va, vb), (label, name)


_CONFIGS = {
    "host": {"venue": "host", "fused": "off"},
    "device-staged": {"venue": "device", "fused": "off"},
    "pallas-fused": {"venue": "device", "fused": "auto"},
}


def _run_all(session, plan):
    outs = {}
    for name, cfg in _CONFIGS.items():
        for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE, SORT_VENUE):
            session.conf.set(key, cfg["venue"])
        session.conf.set(DEVICE_FUSED_KERNELS, cfg["fused"])
        outs[name] = session.run(plan)
    return outs


def _queries(fs, ds):
    return {
        "filter": fs.filter(((col("k") % 3) == 0) & (col("q") > 500.0)),
        "filter_null": fs.filter(col("n") > lit(40)),
        "group_agg": fs.aggregate(
            ["s"],
            [
                AggSpec.of("sum", "q", "sq"),
                AggSpec.of("count", None, "cnt"),
                AggSpec.of("min", "q", "mn"),
                AggSpec.of("max", "n", "mx"),
            ],
        ),
        "join": fs.join(ds, ["k"]),
        "join_agg": fs.join(ds, ["k"]).aggregate(
            ["s"], [AggSpec.of("sum", "w", "sw"), AggSpec.of("count", None, "cnt")]
        ),
        "zero_row": fs.filter(col("q") > 1e9),
        "zero_row_agg": fs.filter(col("q") > 1e9).aggregate(
            ["s"], [AggSpec.of("sum", "q", "sq")]
        ),
    }


@pytest.mark.parametrize("qname", [
    "filter", "filter_null", "group_agg", "join", "join_agg", "zero_row", "zero_row_agg",
])
def test_venue_parity_byte_identical(dataset, qname):
    session, fs, ds = dataset
    plan = _queries(fs, ds)[qname]
    outs = _run_all(session, plan)
    _assert_identical(outs["host"], outs["device-staged"], f"{qname}: host vs staged")
    _assert_identical(outs["host"], outs["pallas-fused"], f"{qname}: host vs pallas")


def test_pallas_fused_engages_on_group_agg(dataset):
    session, fs, ds = dataset
    plan = _queries(fs, ds)["group_agg"]
    for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE, SORT_VENUE):
        session.conf.set(key, "device")
    session.conf.set(DEVICE_FUSED_KERNELS, "auto")
    before = stats.get("device.kernel.fused")
    session.run(plan)
    assert stats.get("device.kernel.fused") > before, (
        "integral sums over a 13-group dict key must take the fused Pallas path"
    )
    # And "off" must keep the lax path.
    session.conf.set(DEVICE_FUSED_KERNELS, "off")
    mid = stats.get("device.kernel.fused")
    session.run(plan)
    assert stats.get("device.kernel.fused") == mid


def test_non_integral_sums_fall_back(dataset):
    session, fs, ds = dataset
    # q/3 is not integral: exactness is unprovable, the fused kernel
    # must NOT engage (results would risk ulp drift vs the host order).
    plan = fs.aggregate([], [AggSpec.of("sum", col("q") / lit(3.0), "x")])
    for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE, SORT_VENUE):
        session.conf.set(key, "device")
    session.conf.set(DEVICE_FUSED_KERNELS, "auto")
    before_fused = stats.get("device.kernel.fused")
    before_fb = stats.get("device.kernel.fallbacks")
    out = session.run(plan)
    assert stats.get("device.kernel.fused") == before_fused
    assert stats.get("device.kernel.fallbacks") > before_fb
    # ... and the lax fallback still matches the host venue bitwise.
    for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE, SORT_VENUE):
        session.conf.set(key, "host")
    _assert_identical(out, session.run(plan), "fallback sum")


# -- staging unit surface -----------------------------------------------------

def test_zero_copy_counters_and_views(tmp_path):
    t = pa.table(
        {
            "a": np.arange(10_000, dtype=np.int64),
            "b": np.arange(10_000, dtype=np.float32),
            "c": pa.array([None if i % 9 == 0 else i for i in range(10_000)], type=pa.int32()),
        }
    )
    pq.write_table(t, tmp_path / "p.parquet")
    before_zc = stats.get("device.stage.bytes_zero_copy")
    before_cp = stats.get("device.stage.bytes_copied")
    ct = hio.read_parquet_cached([str(tmp_path / "p.parquet")])
    zc = stats.get("device.stage.bytes_zero_copy") - before_zc
    cp = stats.get("device.stage.bytes_copied") - before_cp
    # a (80k) + b (40k) are views; c (nullable) copies.
    assert zc == 10_000 * (8 + 4)
    assert cp >= 10_000 * 4
    assert not ct.columns["a"].flags.writeable
    np.testing.assert_array_equal(ct.columns["a"], np.arange(10_000))


def test_staging_disabled_copies_everything(tmp_path, tmp_system_path):
    t = pa.table({"a": np.arange(1000, dtype=np.int64)})
    pq.write_table(t, tmp_path / "p.parquet")
    session = HyperspaceSession(system_path=tmp_system_path)
    session.conf.set(DEVICE_STAGING_ENABLED, False)
    try:
        assert session.conf.get(DEVICE_STAGING_ENABLED) is False
        before = stats.get("device.stage.bytes_zero_copy")
        ct = hio.read_parquet_cached([str(tmp_path / "p.parquet")])
        assert stats.get("device.stage.bytes_zero_copy") == before
        assert stats.get("device.stage.bytes_copied") >= 8000
        np.testing.assert_array_equal(ct.columns["a"], np.arange(1000))
    finally:
        session.conf.set(DEVICE_STAGING_ENABLED, True)


def test_offset_view_slices_stage_correctly():
    base = pa.table(
        {
            "a": np.arange(1000, dtype=np.int64),
            "s": pa.array([f"v{i % 3}" for i in range(1000)]),
        }
    )
    sliced = base.slice(17, 400)  # offset view: non-zero arr.offset
    ct = ColumnTable.from_arrow(sliced, zero_copy_ok=True)
    np.testing.assert_array_equal(ct.columns["a"], np.arange(17, 417))
    got = ct.dictionaries["s"][ct.columns["s"]]
    np.testing.assert_array_equal(got.astype(str), np.array([f"v{i % 3}" for i in range(17, 417)]))


def test_uncached_read_is_downgraded_writable(tmp_path):
    """A table too large for the io cache must come back with OWNED
    writable arrays (read-only would masquerade as identity-stable)."""
    t = pa.table({"a": np.arange(50_000, dtype=np.int64)})
    pq.write_table(t, tmp_path / "p.parquet")
    old = hio._CACHE_BUDGET
    hio.set_table_cache_budget(1024)  # nothing fits
    try:
        ct = hio.read_parquet_cached([str(tmp_path / "p.parquet")])
        assert ct.columns["a"].flags.writeable
        np.testing.assert_array_equal(ct.columns["a"], np.arange(50_000))
    finally:
        hio.set_table_cache_budget(old)


def test_bool_and_multichunk_columns_take_copy_path():
    t1 = pa.table({"b": pa.array([True, False] * 50)})
    ct1 = ColumnTable.from_arrow(t1, zero_copy_ok=True)
    assert ct1.columns["b"].dtype == np.bool_
    np.testing.assert_array_equal(ct1.columns["b"], np.array([True, False] * 50))
    chunked = pa.table(
        {"a": pa.chunked_array([np.arange(5, dtype=np.int64), np.arange(5, 10, dtype=np.int64)])}
    )
    ct2 = ColumnTable.from_arrow(chunked, zero_copy_ok=True)
    np.testing.assert_array_equal(ct2.columns["a"], np.arange(10))


# -- staged-view immutability (the HSL025 runtime mirror) ---------------------
#
# The static rule (analysis/tracedomain.py HSL025) proves no code path
# mutates or donates a writeable=False staged view; these tests pin the
# runtime half of the same contract: the views really are read-only (a
# mutation attempt raises rather than corrupting the Arrow buffer), and
# own_arrays() is the one sanctioned way to writable arrays.

def test_mutating_zero_copy_staged_view_raises():
    t = pa.table({"a": np.arange(1000, dtype=np.int64)})
    ct = ColumnTable.from_arrow(t, zero_copy_ok=True)
    assert not ct.columns["a"].flags.writeable
    with pytest.raises(ValueError):
        ct.columns["a"][0] = -1
    # the Arrow buffer is untouched
    assert t.column("a")[0].as_py() == 0


def test_date32_and_timestamp_views_are_read_only():
    # These stage through Arrow's zero-copy .view() reinterpretation
    # (date32→int32 days, timestamp[us]→int64 micros) — the re-viewed
    # arrays must carry the same read-only contract as direct views.
    t = pa.table(
        {
            "d": pa.array([0, 1, 20000], type=pa.date32()),
            "ts": pa.array([0, 1_000_000, 2_000_000], type=pa.timestamp("us")),
        }
    )
    ct = ColumnTable.from_arrow(t, zero_copy_ok=True)
    assert ct.columns["d"].dtype == np.int32
    assert ct.columns["ts"].dtype == np.int64
    np.testing.assert_array_equal(ct.columns["d"], [0, 1, 20000])
    np.testing.assert_array_equal(ct.columns["ts"], [0, 1_000_000, 2_000_000])
    for name in ("d", "ts"):
        assert not ct.columns[name].flags.writeable, name
        with pytest.raises(ValueError):
            ct.columns[name][0] = 7


def test_every_zero_copy_column_is_read_only():
    """Whatever the staging layer kept as a view (counted in
    bytes_zero_copy) must be non-writeable — a writable view would let
    query code corrupt the shared Arrow buffer silently."""
    t = pa.table(
        {
            "i64": np.arange(500, dtype=np.int64),
            "f32": np.arange(500, dtype=np.float32),
            "i32": np.arange(500, dtype=np.int32),
            "d": pa.array(list(range(500)), type=pa.date32()),
            "ts": pa.array([i * 1000 for i in range(500)], type=pa.timestamp("us")),
            "nullable": pa.array(
                [None if i % 5 == 0 else i for i in range(500)], type=pa.int64()
            ),
        }
    )
    before = stats.get("device.stage.bytes_zero_copy")
    ct = ColumnTable.from_arrow(t, zero_copy_ok=True)
    staged = stats.get("device.stage.bytes_zero_copy") - before
    assert staged == 500 * (8 + 4 + 4 + 4 + 8)  # every eligible column viewed
    for name in ("i64", "f32", "i32", "d", "ts"):
        assert not ct.columns[name].flags.writeable, name
    # the nullable column took the copy path and stays writable
    assert ct.columns["nullable"].flags.writeable


def test_own_arrays_is_the_writable_gateway():
    t = pa.table({"a": np.arange(1000, dtype=np.int64)})
    ct = ColumnTable.from_arrow(t, zero_copy_ok=True)
    view = ct.columns["a"]
    assert not view.flags.writeable
    before_cp = stats.get("device.stage.bytes_copied")
    ct.own_arrays()
    # downgraded to an owned writable copy, accounted to the counters
    assert ct.columns["a"].flags.writeable
    assert ct.columns["a"] is not view
    assert stats.get("device.stage.bytes_copied") - before_cp == view.nbytes
    ct.columns["a"][0] = -1  # now legal
    assert ct.columns["a"][0] == -1
    # the original staged view and its Arrow buffer are untouched
    assert view[0] == 0 and t.column("a")[0].as_py() == 0


# -- dict-coded footprint accounting (RefCache satellite) --------------------

def test_dict_footprint_counts_codes_plus_dictionary():
    n = 50_000
    strings = [f"{'x' * 60}_{i % 4}" for i in range(n)]  # 4 long distinct values
    ct = ColumnTable.from_arrow(pa.table({"s": pa.array(strings)}))
    fp = dc.table_footprint_bytes(ct)
    codes_bytes = n * 4
    payload = sum(len(s) for s in set(strings)) + 8 * 4
    assert fp == codes_bytes + payload
    # NOT the inflated per-row string size (n * 62 chars).
    assert fp < n * 62 // 4


def test_refcache_admits_dict_column_under_true_footprint():
    """The over-count regression: a dict-coded side table whose TRUE
    footprint fits budget/4 must be admitted (the inflated per-row
    string size would have rejected it and evicted dict columns
    eagerly)."""
    n = 20_000
    ct = ColumnTable.from_arrow(
        pa.table({"s": pa.array([f"{'y' * 100}_{i % 3}" for i in range(n)])})
    )
    for a in (*ct.columns.values(), *ct.dictionaries.values()):
        dc.freeze(a)
    fp = dc.table_footprint_bytes(ct)
    inflated = n * 103
    budget = (fp + 1024) * 4  # true footprint fits; inflated would not
    assert inflated > budget // 4
    cache = dc.RefCache(budget, name="ref_cache")
    got = cache.get_or_build(("t", id(ct)), (ct,), lambda: (ct, fp))
    assert got is ct
    assert cache.stats()["entries"] == 1, "dict column must be admitted at its true footprint"


def test_result_cache_accounting_matches_canonical():
    from hyperspace_tpu.serve.result_cache import table_nbytes

    ct = ColumnTable.from_arrow(
        pa.table({"s": pa.array(["aa", "bb", "aa"]), "v": np.arange(3, dtype=np.int64)})
    )
    assert table_nbytes(ct) == dc.table_footprint_bytes(ct)


def test_to_arrow_keeps_strings_dictionary_coded():
    ct = ColumnTable.from_arrow(pa.table({"s": pa.array(["b", "a", "b", None])}))
    back = ct.to_arrow()
    assert pa.types.is_dictionary(back.column("s").type)
    assert back.column("s").to_pylist() == ["b", "a", "b", None]
    # Round trip: codes + dictionary survive without inflating.
    again = ColumnTable.from_arrow(back)
    assert list(again.dictionaries["s"]) == list(ct.dictionaries["s"])
    np.testing.assert_array_equal(again.columns["s"], ct.columns["s"])
