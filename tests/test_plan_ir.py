"""Plan IR and expression serde/eval tests.

The JSON round-trip here is the analog of the reference's serde suite
(index/LogicalPlanSerDeTests.scala:77-183) — but over our JSON-native IR.
"""

import json

import numpy as np
import pytest

from hyperspace_tpu.plan import col, lit, expr_from_json, plan_from_json
from hyperspace_tpu.plan.expr import evaluate, split_conjuncts
from hyperspace_tpu.plan.nodes import Filter, Join, Project, Scan
from hyperspace_tpu.schema import Field, Schema

SCHEMA = Schema.of(Field("a", "int64"), Field("b", "float64"), Field("c", "string"))


def scan() -> Scan:
    return Scan("/data", "parquet", SCHEMA)


def rt_plan(p):
    return plan_from_json(json.loads(json.dumps(p.to_json())))


def test_expr_round_trip_and_refs():
    e = ((col("a") == 5) & (col("b") > 1.5)) | ~(col("c") == "x")
    back = expr_from_json(json.loads(json.dumps(e.to_json())))
    assert back.to_json() == e.to_json()
    assert e.references() == {"a", "b", "c"}


def test_expr_eval_numpy():
    e = (col("a") + 1 == 3) & (col("b") >= 0.0)
    cols = {"a": np.array([1, 2, 3]), "b": np.array([0.5, -1.0, 2.0])}
    out = evaluate(e, cols.__getitem__, np)
    np.testing.assert_array_equal(out, [False, False, False])
    e2 = (col("a") == 2) | (col("a") == 3)
    np.testing.assert_array_equal(evaluate(e2, cols.__getitem__, np), [False, True, True])


def test_split_conjuncts():
    e = (col("a") == 1) & (col("b") == 2) & (col("c") == 3)
    parts = split_conjuncts(e)
    assert len(parts) == 3


def test_plan_round_trip_all_nodes():
    p = Project(
        Filter(scan(), (col("a") == 5) & (col("c") == "x")),
        ["a", "b"],
    )
    assert rt_plan(p).to_json() == p.to_json()
    j = Join(scan(), Scan("/other", "parquet", SCHEMA), ["a"], ["a"])
    assert rt_plan(j).to_json() == j.to_json()


def test_bucketed_scan_round_trip():
    s = Scan("/idx/v__=0", "parquet", SCHEMA, files=["/idx/v__=0/b0.parquet"], bucket_spec=(8, ["a"]))
    back = rt_plan(s)
    assert back.bucket_spec == (8, ["a"])
    assert back.files == ["/idx/v__=0/b0.parquet"]


def test_schema_propagation_and_linearity():
    p = Project(Filter(scan(), col("a") == 1), ["b"])
    assert p.schema.names == ["b"]
    assert p.is_linear()
    right = Scan("/other", "parquet", Schema.of(Field("a", "int64"), Field("d", "float64")))
    j = Join(scan(), right, ["a"], ["a"])
    assert not j.is_linear()
    assert j.leaves() == [j.left, j.right]
    # Key column appears once; right-side non-key columns appended.
    assert j.schema.names == ["a", "b", "c", "d"]
    # Ambiguous non-key collision is rejected.
    amb = Join(scan(), scan(), ["a"], ["a"])
    with pytest.raises(ValueError, match="ambiguous"):
        _ = amb.schema


def test_join_key_arity_checked():
    with pytest.raises(ValueError):
        Join(scan(), scan(), ["a", "b"], ["a"])


def test_projection_pushdown_prunes_scan_columns():
    """prune_columns must narrow Scan schemas to what ancestors need
    (project cols + predicate refs + join keys) without changing the
    user-visible output schema."""
    from hyperspace_tpu.plan.prune import prune_columns
    from hyperspace_tpu.plan.nodes import Scan, Filter, Project, Join
    from hyperspace_tpu.plan.expr import col
    from hyperspace_tpu.schema import Schema, Field

    sch = Schema([Field("a", "int64"), Field("b", "float64"), Field("c", "string"), Field("d", "int64")])
    scan = Scan(root="/x", format="parquet", scan_schema=sch, files=None, bucket_spec=None)
    plan = scan.filter(col("b") > 1.0).select("a")
    pruned = prune_columns(plan)
    leaf = pruned.child.child
    assert leaf.scan_schema.names == ["a", "b"]  # predicate ref kept, c/d dropped
    assert pruned.schema.names == ["a"]

    sch2 = Schema([Field("a", "int64"), Field("x", "string")])
    scan2 = Scan(root="/y", format="parquet", scan_schema=sch2, files=None, bucket_spec=None)
    j = scan.select("a", "b").join(scan2.select("a", "x"), ["a"]).select("b")
    pj = prune_columns(j)
    leaves = pj.leaves()
    assert leaves[0].scan_schema.names == ["a", "b"]
    assert leaves[1].scan_schema.names == ["a"]  # join key only; x dropped


class TestPushdown:
    def _scans(self):
        from hyperspace_tpu.plan.nodes import Scan
        from hyperspace_tpu.schema import Field, Schema

        l = Scan("/l", "parquet", Schema.of(Field("k", "int64"), Field("a", "float64")))
        r = Scan("/r", "parquet", Schema.of(Field("k2", "int64"), Field("b", "float64")))
        return l, r

    def test_side_local_conjuncts_push_below_inner_join(self):
        from hyperspace_tpu.plan.expr import col, lit
        from hyperspace_tpu.plan.nodes import Filter, Join
        from hyperspace_tpu.plan.pushdown import push_down_filters

        l, r = self._scans()
        q = l.join(r, ["k"], ["k2"]).filter(
            (col("a") > lit(1.0)) & (col("b") < lit(0.0)) & (col("a") + col("b") > lit(0.0))
        )
        out = push_down_filters(q)
        # Mixed conjunct stays above; side-local ones moved into the sides.
        assert isinstance(out, Filter)
        assert out.predicate.references() == {"a", "b"}
        join = out.child
        assert isinstance(join, Join)
        assert isinstance(join.left, Filter) and join.left.predicate.references() == {"a"}
        assert isinstance(join.right, Filter) and join.right.predicate.references() == {"b"}

    def test_fully_local_filter_leaves_no_residual(self):
        from hyperspace_tpu.plan.expr import col, lit
        from hyperspace_tpu.plan.nodes import Filter, Join
        from hyperspace_tpu.plan.pushdown import push_down_filters

        l, r = self._scans()
        out = push_down_filters(l.join(r, ["k"], ["k2"]).filter(col("a") > lit(0.0)))
        assert isinstance(out, Join)
        assert isinstance(out.left, Filter)
