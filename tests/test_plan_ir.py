"""Plan IR and expression serde/eval tests.

The JSON round-trip here is the analog of the reference's serde suite
(index/LogicalPlanSerDeTests.scala:77-183) — but over our JSON-native IR.
"""

import json

import numpy as np
import pytest

from hyperspace_tpu.plan import col, lit, expr_from_json, plan_from_json
from hyperspace_tpu.plan.expr import evaluate, split_conjuncts
from hyperspace_tpu.plan.nodes import Filter, Join, Project, Scan
from hyperspace_tpu.schema import Field, Schema

SCHEMA = Schema.of(Field("a", "int64"), Field("b", "float64"), Field("c", "string"))


def scan() -> Scan:
    return Scan("/data", "parquet", SCHEMA)


def rt_plan(p):
    return plan_from_json(json.loads(json.dumps(p.to_json())))


def test_expr_round_trip_and_refs():
    e = ((col("a") == 5) & (col("b") > 1.5)) | ~(col("c") == "x")
    back = expr_from_json(json.loads(json.dumps(e.to_json())))
    assert back.to_json() == e.to_json()
    assert e.references() == {"a", "b", "c"}


def test_expr_eval_numpy():
    e = (col("a") + 1 == 3) & (col("b") >= 0.0)
    cols = {"a": np.array([1, 2, 3]), "b": np.array([0.5, -1.0, 2.0])}
    out = evaluate(e, cols.__getitem__, np)
    np.testing.assert_array_equal(out, [False, False, False])
    e2 = (col("a") == 2) | (col("a") == 3)
    np.testing.assert_array_equal(evaluate(e2, cols.__getitem__, np), [False, True, True])


def test_split_conjuncts():
    e = (col("a") == 1) & (col("b") == 2) & (col("c") == 3)
    parts = split_conjuncts(e)
    assert len(parts) == 3


def test_plan_round_trip_all_nodes():
    p = Project(
        Filter(scan(), (col("a") == 5) & (col("c") == "x")),
        ["a", "b"],
    )
    assert rt_plan(p).to_json() == p.to_json()
    j = Join(scan(), Scan("/other", "parquet", SCHEMA), ["a"], ["a"])
    assert rt_plan(j).to_json() == j.to_json()


def test_bucketed_scan_round_trip():
    s = Scan("/idx/v__=0", "parquet", SCHEMA, files=["/idx/v__=0/b0.parquet"], bucket_spec=(8, ["a"]))
    back = rt_plan(s)
    assert back.bucket_spec == (8, ["a"])
    assert back.files == ["/idx/v__=0/b0.parquet"]


def test_schema_propagation_and_linearity():
    p = Project(Filter(scan(), col("a") == 1), ["b"])
    assert p.schema.names == ["b"]
    assert p.is_linear()
    right = Scan("/other", "parquet", Schema.of(Field("a", "int64"), Field("d", "float64")))
    j = Join(scan(), right, ["a"], ["a"])
    assert not j.is_linear()
    assert j.leaves() == [j.left, j.right]
    # Key column appears once; right-side non-key columns appended.
    assert j.schema.names == ["a", "b", "c", "d"]
    # Ambiguous non-key collision is rejected.
    amb = Join(scan(), scan(), ["a"], ["a"])
    with pytest.raises(ValueError, match="ambiguous"):
        _ = amb.schema


def test_join_key_arity_checked():
    with pytest.raises(ValueError):
        Join(scan(), scan(), ["a", "b"], ["a"])
