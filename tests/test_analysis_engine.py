"""Whole-program analysis engine tests (analysis/program.py,
callgraph.py, locks.py, check.py): fixture-package goldens, the seeded
lock-inversion regression, the per-rule corpus, and the repo-wide
guarantees the CI check gate rides on (cycle-free lock graph, zero
config/fault drift)."""

from __future__ import annotations

import ast
import json
import pathlib
import subprocess
import sys

import pytest

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.check import (
    TEST_ALLOWLIST,
    config_key_findings,
    default_paths,
    fault_point_findings,
    main as check_main,
    run_check,
    validator_corpus,
)
from hyperspace_tpu.analysis.lint import (
    EXIT_CLEAN,
    EXIT_FINDINGS,
    EXIT_INTERNAL_ERROR,
    RULES,
    lint_source,
)
from hyperspace_tpu.analysis.locks import LockGraph, resource_findings
from hyperspace_tpu.analysis.program import Program, _index_module, _module_name

TESTS_DIR = pathlib.Path(__file__).resolve().parent
FIXTURES = TESTS_DIR / "analysis_fixtures"
REPO_ROOT = TESTS_DIR.parent


# -- shared fixtures ----------------------------------------------------------

@pytest.fixture(scope="module")
def lockdemo():
    program = Program.load([FIXTURES / "lockdemo"])
    callgraph = CallGraph(program)
    return program, callgraph, LockGraph(program, callgraph)


@pytest.fixture(scope="module")
def repo_program():
    program = Program.load(default_paths(REPO_ROOT))
    callgraph = CallGraph(program)
    return program, callgraph


# -- fixture-package goldens --------------------------------------------------

class TestLockdemoGoldens:
    def test_call_graph_matches_golden(self, lockdemo):
        _, callgraph, _ = lockdemo
        golden = json.loads((FIXTURES / "goldens" / "lockdemo_callgraph.json").read_text())
        assert json.loads(json.dumps(callgraph.to_json())) == golden

    def test_lock_graph_matches_golden(self, lockdemo):
        _, _, lockgraph = lockdemo
        golden = json.loads((FIXTURES / "goldens" / "lockdemo_lockgraph.json").read_text())
        assert json.loads(json.dumps(lockgraph.to_json())) == golden

    def test_lock_identities_and_kinds(self, lockdemo):
        program, _, _ = lockdemo
        assert program.locks["lockdemo.alpha._registry_lock"].kind == "Lock"
        assert program.locks["lockdemo.alpha.Session._state_lock"].kind == "RLock"
        assert program.locks["lockdemo.alpha.Cache._lock"].cls == "Cache"

    def test_typed_attribute_call_resolution(self, lockdemo):
        # self.cache = Cache() makes self.cache.put_entry resolve without
        # any unique-name fallback.
        _, callgraph, _ = lockdemo
        assert "lockdemo.alpha.Cache.put_entry" in callgraph.callees(
            "lockdemo.alpha.Session.publish"
        )

    def test_cross_module_call_resolution(self, lockdemo):
        _, callgraph, _ = lockdemo
        assert "lockdemo.beta.audit" in callgraph.callees("lockdemo.alpha.register")
        assert "lockdemo.alpha.register" in callgraph.callees("lockdemo.beta.rollback")

    def test_reachability(self, lockdemo):
        _, callgraph, _ = lockdemo
        reach = callgraph.reachable("lockdemo.beta.rollback")
        assert "lockdemo.beta.audit" in reach  # rollback -> register -> audit


class TestSeededInversion:
    """The acceptance regression: HSL009 catches the deliberately
    inverted lock pair in the fixture package, with a two-chain witness
    naming both conflicting call chains."""

    def test_inversion_reported(self, lockdemo):
        _, _, lockgraph = lockdemo
        rules = [f.rule for f in lockgraph.inversions()]
        assert "HSL009" in rules

    def test_two_chain_witness(self, lockdemo):
        _, _, lockgraph = lockdemo
        pair = [
            f for f in lockgraph.inversions()
            if "_registry_lock" in f.message and "_audit_lock" in f.message
            and "inversion" in f.message
        ]
        assert len(pair) == 1
        msg = pair[0].message
        assert "chain 1" in msg and "chain 2" in msg
        # chain 1: register (holds registry) -> audit; chain 2:
        # rollback (holds audit) -> register.
        assert "lockdemo.alpha.register -> lockdemo.beta.audit" in msg
        assert "lockdemo.beta.rollback -> lockdemo.alpha.register" in msg

    def test_transitive_self_deadlock_reported(self, lockdemo):
        # rollback holds the (non-reentrant) audit lock and the chain
        # register -> audit re-acquires it: a real self-deadlock.
        _, _, lockgraph = lockdemo
        assert any(
            "re-acquired while already held" in f.message
            for f in lockgraph.inversions()
        )

    def test_rlock_reentry_not_flagged(self, lockdemo):
        # Session.refresh -> snapshot re-enters the session RLock: legal.
        _, _, lockgraph = lockdemo
        assert not any(
            "_state_lock" in f.message for f in lockgraph.inversions()
        )

    def test_edge_direction_recorded_both_ways(self, lockdemo):
        _, _, lockgraph = lockdemo
        best = lockgraph.order_edges()
        assert ("lockdemo.alpha._registry_lock", "lockdemo.beta._audit_lock") in best
        assert ("lockdemo.beta._audit_lock", "lockdemo.alpha._registry_lock") in best


# -- per-rule corpus ----------------------------------------------------------

CORPUS = sorted((FIXTURES / "rules").glob("hsl*.py"))


def _expected(path: pathlib.Path) -> set[tuple[int, str]]:
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), 1):
        if "# expect:" in line:
            out.add((i, line.split("# expect:", 1)[1].strip()))
    return out


def _corpus_findings(path: pathlib.Path) -> set[tuple[int, str]]:
    """Run the full rule set (per-file lint + whole-program rules) over
    one corpus file, exactly as check.py composes them."""
    src = path.read_text()
    tree = ast.parse(src)
    findings = list(lint_source(src, str(path), tree=tree))
    name = _module_name(path)
    program = Program({name: _index_module(name, str(path), src, tree)})
    callgraph = CallGraph(program)
    findings += LockGraph(program, callgraph).inversions()
    findings += resource_findings(program)
    findings += config_key_findings(program, [])
    findings += fault_point_findings(program)
    return {(f.line, f.rule) for f in findings}


@pytest.mark.parametrize("path", CORPUS, ids=[p.stem for p in CORPUS])
def test_rule_corpus(path):
    """Each corpus file must produce exactly its `# expect:` annotations:
    flagged lines flag, clean lines stay clean, nothing extra fires."""
    assert _corpus_findings(path) == _expected(path)


def test_corpus_covers_every_rule():
    covered = {p.stem.upper() for p in CORPUS}
    declared = {r for r in RULES if r not in ("HSL000",)}
    assert covered == declared


# -- repo-wide guarantees (what the CI gate asserts) --------------------------

class TestRepoWideGuarantees:
    def test_lock_graph_is_cycle_free(self, repo_program):
        """The acceptance proof: the full lock-acquisition graph —
        session RLock, metadata cache, device cache, serve scheduler
        condvar, plan/result caches, module memo locks — has no cycle."""
        program, callgraph = repo_program
        lockgraph = LockGraph(program, callgraph)
        assert lockgraph.inversions() == []
        # and it actually covers the locks the serving PR added:
        for lock_id in (
            "hyperspace_tpu.hyperspace.HyperspaceSession._state_lock",
            "hyperspace_tpu.metadata.cache.CreationTimeBasedCache._lock",
            "hyperspace_tpu.execution.device_cache.RefCache._lock",
            "hyperspace_tpu.serve.scheduler.QueryServer._cv",
            "hyperspace_tpu.serve.plan_cache.PlanCache._lock",
            "hyperspace_tpu.serve.result_cache.ResultCache._lock",
            "hyperspace_tpu.ops.filter._MASK_FN_LOCK",
            "hyperspace_tpu.utils.jit_memory._limit_lock",
        ):
            assert lock_id in program.locks, lock_id

    def test_lock_holders_reach_only_leaf_metric_locks(self, repo_program):
        # The shape of the healthy graph: every order edge terminates in
        # a metrics-registry leaf lock (which never calls out).
        program, callgraph = repo_program
        lockgraph = LockGraph(program, callgraph)
        inner = {b for (_, b) in lockgraph.order_edges()}
        outer = {a for (a, _) in lockgraph.order_edges()}
        assert not any(lock.startswith("hyperspace_tpu.obs.metrics") for lock in outer)
        assert inner  # the graph is not trivially empty

    def test_zero_config_key_drift(self, repo_program):
        program, _ = repo_program
        assert config_key_findings(program, [TESTS_DIR]) == []

    def test_zero_fault_point_drift(self, repo_program):
        program, _ = repo_program
        assert fault_point_findings(program) == []

    def test_zero_resource_findings(self, repo_program):
        program, _ = repo_program
        assert resource_findings(program) == []

    def test_validator_corpus_passes(self):
        report = validator_corpus()
        assert report["status"] == "ok", report

    def test_run_check_clean(self, repo_program):
        report = run_check(default_paths(REPO_ROOT), REPO_ROOT, [TESTS_DIR])
        assert report["_findings"] == []
        assert report["summary"]["allowlisted"] == len(report["allowlisted"])
        assert report["summary"]["locks"] >= 20

    def test_seeded_typo_counter_is_caught(self, repo_program):
        # Sanity that the repo-wide zero isn't vacuous: a typo'd key in a
        # scratch module next to the real program is flagged with a
        # did-you-mean naming the declared key.
        src = 'def f(conf):\n    return conf.get("hyperspace.serve.workerz")\n'
        name, path = "scratch_mod", "scratch_mod.py"
        program = Program({name: _index_module(name, path, src, ast.parse(src))})
        findings = config_key_findings(program, [])
        assert [f.rule for f in findings] == ["HSL010"]
        assert "hyperspace.serve.workers" in findings[0].message

    def test_seeded_unthreaded_fault_point_is_caught(self, repo_program, monkeypatch):
        from hyperspace_tpu import faults as faults_mod

        program, _ = repo_program
        monkeypatch.setattr(
            faults_mod, "KNOWN_POINTS", (*faults_mod.KNOWN_POINTS, "ghost.point")
        )
        findings = fault_point_findings(program)
        assert [f.rule for f in findings] == ["HSL012"]
        assert "ghost.point" in findings[0].message
        assert "never threaded" in findings[0].message

    def test_allowlist_is_narrow_and_justified(self):
        for (suffix, rule), why in TEST_ALLOWLIST.items():
            assert not suffix.startswith("hyperspace_tpu/"), (
                "the allowlist is for test/benchmark surfaces only — "
                "package findings get fixed"
            )
            assert why


# -- check CLI ----------------------------------------------------------------

class TestCheckCli:
    def test_exit_clean_on_repo(self):
        proc = subprocess.run(
            [sys.executable, "-m", "hyperspace_tpu.analysis.check"],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )
        assert proc.returncode == EXIT_CLEAN, proc.stdout + proc.stderr
        assert "cycle-free=True" in proc.stderr

    def test_exit_findings_without_baseline(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        assert check_main([str(bad), "--no-baseline"]) == EXIT_FINDINGS

    def test_exit_internal_error(self, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        monkeypatch.setattr(
            check_mod, "run_check",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        assert check_mod.main(["--no-baseline"]) == EXIT_INTERNAL_ERROR

    def test_baseline_masks_old_findings_only(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        baseline = tmp_path / "baseline.json"
        # 1. write the baseline: current findings become "known"
        assert check_main([str(bad), "--baseline", str(baseline),
                           "--write-baseline"]) == EXIT_CLEAN
        assert json.loads(baseline.read_text())["findings"]
        # 2. same findings, baseline present -> clean
        assert check_main([str(bad), "--baseline", str(baseline)]) == EXIT_CLEAN
        # 3. a NEW finding fails even with the baseline
        bad.write_text("from jax import shard_map\nimport numpy as np\nv = np.random.rand(3)\n")
        assert check_main([str(bad), "--baseline", str(baseline)]) == EXIT_FINDINGS

    def test_json_report_shape(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        out = tmp_path / "report.json"
        rc = check_main([str(bad), "--no-baseline", "--format", "json",
                         "--output", str(out)])
        assert rc == EXIT_FINDINGS
        report = json.loads(out.read_text())
        assert report["summary"]["new_findings"] == 1
        (finding,) = report["findings"]
        assert finding["rule"] == "HSL001"
        assert finding["slug"] == "fragile-jax-import"
        assert finding["new"] is True
        assert report["validator_corpus"]["status"] in ("ok", "skipped")
        assert "lock_graph" in report

    def test_docs_table_in_sync(self):
        # docs/configuration.md's key table is generated from
        # config.KNOWN_KEYS; this is the no-drift assertion.
        from hyperspace_tpu.analysis.check import docs_findings

        assert docs_findings(REPO_ROOT) == []
