"""Whole-program analysis engine tests (analysis/program.py,
callgraph.py, locks.py, check.py): fixture-package goldens, the seeded
lock-inversion regression, the per-rule corpus, and the repo-wide
guarantees the CI check gate rides on (cycle-free lock graph, zero
config/fault drift)."""

from __future__ import annotations

import ast
import json
import pathlib
import subprocess
import sys

import pytest

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.check import (
    TEST_ALLOWLIST,
    changed_files as check_mod_changed_files,
    config_key_findings,
    default_paths,
    fault_point_findings,
    main as check_main,
    run_check,
    validator_corpus,
)
from hyperspace_tpu.analysis.lint import (
    EXIT_CLEAN,
    EXIT_FINDINGS,
    EXIT_INTERNAL_ERROR,
    RULES,
    lint_source,
)
from hyperspace_tpu.analysis.effects import Effects
from hyperspace_tpu.analysis.locks import LockGraph, resource_findings
from hyperspace_tpu.analysis.program import Program, _index_module, _module_name
from hyperspace_tpu.analysis.races import (
    RACE_ALLOWLIST,
    atomicity_findings,
    jit_hygiene_findings,
    lockset_race_findings,
)

TESTS_DIR = pathlib.Path(__file__).resolve().parent
FIXTURES = TESTS_DIR / "analysis_fixtures"
REPO_ROOT = TESTS_DIR.parent


# -- shared fixtures ----------------------------------------------------------

@pytest.fixture(scope="module")
def lockdemo():
    program = Program.load([FIXTURES / "lockdemo"])
    callgraph = CallGraph(program)
    return program, callgraph, LockGraph(program, callgraph)


@pytest.fixture(scope="module")
def repo_program():
    program = Program.load(default_paths(REPO_ROOT))
    callgraph = CallGraph(program)
    return program, callgraph


# -- fixture-package goldens --------------------------------------------------

class TestLockdemoGoldens:
    def test_call_graph_matches_golden(self, lockdemo):
        _, callgraph, _ = lockdemo
        golden = json.loads((FIXTURES / "goldens" / "lockdemo_callgraph.json").read_text())
        assert json.loads(json.dumps(callgraph.to_json())) == golden

    def test_lock_graph_matches_golden(self, lockdemo):
        _, _, lockgraph = lockdemo
        golden = json.loads((FIXTURES / "goldens" / "lockdemo_lockgraph.json").read_text())
        assert json.loads(json.dumps(lockgraph.to_json())) == golden

    def test_lock_identities_and_kinds(self, lockdemo):
        program, _, _ = lockdemo
        assert program.locks["lockdemo.alpha._registry_lock"].kind == "Lock"
        assert program.locks["lockdemo.alpha.Session._state_lock"].kind == "RLock"
        assert program.locks["lockdemo.alpha.Cache._lock"].cls == "Cache"

    def test_typed_attribute_call_resolution(self, lockdemo):
        # self.cache = Cache() makes self.cache.put_entry resolve without
        # any unique-name fallback.
        _, callgraph, _ = lockdemo
        assert "lockdemo.alpha.Cache.put_entry" in callgraph.callees(
            "lockdemo.alpha.Session.publish"
        )

    def test_cross_module_call_resolution(self, lockdemo):
        _, callgraph, _ = lockdemo
        assert "lockdemo.beta.audit" in callgraph.callees("lockdemo.alpha.register")
        assert "lockdemo.alpha.register" in callgraph.callees("lockdemo.beta.rollback")

    def test_reachability(self, lockdemo):
        _, callgraph, _ = lockdemo
        reach = callgraph.reachable("lockdemo.beta.rollback")
        assert "lockdemo.beta.audit" in reach  # rollback -> register -> audit


class TestSeededInversion:
    """The acceptance regression: HSL009 catches the deliberately
    inverted lock pair in the fixture package, with a two-chain witness
    naming both conflicting call chains."""

    def test_inversion_reported(self, lockdemo):
        _, _, lockgraph = lockdemo
        rules = [f.rule for f in lockgraph.inversions()]
        assert "HSL009" in rules

    def test_two_chain_witness(self, lockdemo):
        _, _, lockgraph = lockdemo
        pair = [
            f for f in lockgraph.inversions()
            if "_registry_lock" in f.message and "_audit_lock" in f.message
            and "inversion" in f.message
        ]
        assert len(pair) == 1
        msg = pair[0].message
        assert "chain 1" in msg and "chain 2" in msg
        # chain 1: register (holds registry) -> audit; chain 2:
        # rollback (holds audit) -> register.
        assert "lockdemo.alpha.register -> lockdemo.beta.audit" in msg
        assert "lockdemo.beta.rollback -> lockdemo.alpha.register" in msg

    def test_transitive_self_deadlock_reported(self, lockdemo):
        # rollback holds the (non-reentrant) audit lock and the chain
        # register -> audit re-acquires it: a real self-deadlock.
        _, _, lockgraph = lockdemo
        assert any(
            "re-acquired while already held" in f.message
            for f in lockgraph.inversions()
        )

    def test_rlock_reentry_not_flagged(self, lockdemo):
        # Session.refresh -> snapshot re-enters the session RLock: legal.
        _, _, lockgraph = lockdemo
        assert not any(
            "_state_lock" in f.message for f in lockgraph.inversions()
        )

    def test_edge_direction_recorded_both_ways(self, lockdemo):
        _, _, lockgraph = lockdemo
        best = lockgraph.order_edges()
        assert ("lockdemo.alpha._registry_lock", "lockdemo.beta._audit_lock") in best
        assert ("lockdemo.beta._audit_lock", "lockdemo.alpha._registry_lock") in best


# -- per-rule corpus ----------------------------------------------------------

CORPUS = sorted((FIXTURES / "rules").glob("hsl*.py"))


def _expected(path: pathlib.Path) -> set[tuple[int, str]]:
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), 1):
        if "# expect:" in line:
            out.add((i, line.split("# expect:", 1)[1].strip()))
    return out


def _corpus_findings(path: pathlib.Path) -> set[tuple[int, str]]:
    """Run the full rule set (per-file lint + whole-program rules) over
    one corpus file, exactly as check.py composes them."""
    src = path.read_text()
    tree = ast.parse(src)
    findings = list(lint_source(src, str(path), tree=tree))
    name = _module_name(path)
    program = Program({name: _index_module(name, str(path), src, tree)})
    callgraph = CallGraph(program)
    findings += LockGraph(program, callgraph).inversions()
    findings += resource_findings(program)
    findings += config_key_findings(program, [])
    findings += fault_point_findings(program)
    effects = Effects(program, callgraph)
    findings += lockset_race_findings(program, effects)
    findings += atomicity_findings(program, effects)
    findings += jit_hygiene_findings(program)
    return {(f.line, f.rule) for f in findings}


@pytest.mark.parametrize("path", CORPUS, ids=[p.stem for p in CORPUS])
def test_rule_corpus(path):
    """Each corpus file must produce exactly its `# expect:` annotations:
    flagged lines flag, clean lines stay clean, nothing extra fires."""
    assert _corpus_findings(path) == _expected(path)


def test_corpus_covers_every_rule():
    covered = {p.stem.upper() for p in CORPUS}
    declared = {r for r in RULES if r not in ("HSL000",)}
    assert covered == declared


# -- racedemo fixture package (effects + race rules) --------------------------

@pytest.fixture(scope="module")
def racedemo():
    program = Program.load([FIXTURES / "racedemo"])
    callgraph = CallGraph(program)
    return program, callgraph, Effects(program, callgraph)


class TestRacedemo:
    def test_effect_summaries_match_golden(self, racedemo):
        _, _, effects = racedemo
        golden = json.loads((FIXTURES / "goldens" / "racedemo_effects.json").read_text())
        assert json.loads(json.dumps(effects.to_json())) == golden

    def test_exactly_three_planted_findings(self, racedemo):
        program, _, effects = racedemo
        findings = (
            lockset_race_findings(program, effects)
            + atomicity_findings(program, effects)
            + jit_hygiene_findings(program)
        )
        assert sorted(f.rule for f in findings) == ["HSL013", "HSL014", "HSL015"]

    def test_hsl013_two_path_witness(self, racedemo):
        program, _, effects = racedemo
        (f,) = lockset_race_findings(program, effects)
        assert f.rule == "HSL013"
        # the witness names BOTH conflicting access paths with locksets
        assert "path 1" in f.message and "path 2" in f.message
        assert "racedemo.store.Store.put" in f.message
        assert "racedemo.store.Store.reset_unsafe" in f.message
        assert "holding racedemo.store.Store._lock" in f.message
        assert "holding no lock" in f.message
        assert "held at 5/6 accesses" in f.message

    def test_hsl014_names_both_critical_sections(self, racedemo):
        program, _, effects = racedemo
        (f,) = atomicity_findings(program, effects)
        assert f.rule == "HSL014"
        assert "bump_torn" in f.message
        assert "read under" in f.message and "re-acquired" in f.message

    def test_hsl015_flags_loop_lambda_only(self, racedemo):
        program, _, _ = racedemo
        (f,) = jit_hygiene_findings(program)
        assert f.rule == "HSL015"
        assert "fresh lambda" in f.message
        assert f.path.endswith("kernels.py")

    def test_guarded_state_stays_clean(self, racedemo):
        # _entries (consistently locked) and _FN_CACHE (memo under lock)
        # are tracked but not reported — the proof isn't vacuous.
        _, _, effects = racedemo
        assert "racedemo.store.Store._entries" in effects.by_state
        assert "racedemo.kernels._FN_CACHE" in effects.by_state

    def test_entry_lock_guarantee_credits_callers(self):
        # A helper only ever called under the lock is credited with it
        # (must-hold-on-entry fixpoint) — no false race on its accesses.
        src = (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "_reg = {}\n"
            "def public(k, v):\n"
            "    with _lock:\n"
            "        _helper(k, v)\n"
            "def other(k):\n"
            "    with _lock:\n"
            "        _helper(k, None)\n"
            "def _helper(k, v):\n"
            "    _reg[k] = v\n"
            "def reader():\n"
            "    with _lock:\n"
            "        return dict(_reg)\n"
        )
        program = Program({"entrymod": _index_module("entrymod", "entrymod.py", src, ast.parse(src))})
        effects = Effects(program, CallGraph(program))
        assert effects.entry_locks["entrymod._helper"] == {"entrymod._lock"}
        assert lockset_race_findings(program, effects) == []


# -- repo-wide guarantees (what the CI gate asserts) --------------------------

class TestRepoWideGuarantees:
    def test_lock_graph_is_cycle_free(self, repo_program):
        """The acceptance proof: the full lock-acquisition graph —
        session RLock, metadata cache, device cache, serve scheduler
        condvar, plan/result caches, module memo locks — has no cycle."""
        program, callgraph = repo_program
        lockgraph = LockGraph(program, callgraph)
        assert lockgraph.inversions() == []
        # and it actually covers the locks the serving PR added:
        for lock_id in (
            "hyperspace_tpu.hyperspace.HyperspaceSession._state_lock",
            "hyperspace_tpu.metadata.cache.CreationTimeBasedCache._lock",
            "hyperspace_tpu.execution.device_cache.RefCache._lock",
            "hyperspace_tpu.serve.scheduler.QueryServer._cv",
            "hyperspace_tpu.serve.plan_cache.PlanCache._lock",
            "hyperspace_tpu.serve.result_cache.ResultCache._lock",
            "hyperspace_tpu.ops.filter._MASK_FN_LOCK",
            "hyperspace_tpu.utils.jit_memory._limit_lock",
        ):
            assert lock_id in program.locks, lock_id

    def test_lock_holders_reach_only_leaf_metric_locks(self, repo_program):
        # The shape of the healthy graph: every order edge terminates in
        # a metrics-registry leaf lock (which never calls out).
        program, callgraph = repo_program
        lockgraph = LockGraph(program, callgraph)
        inner = {b for (_, b) in lockgraph.order_edges()}
        outer = {a for (a, _) in lockgraph.order_edges()}
        assert not any(lock.startswith("hyperspace_tpu.obs.metrics") for lock in outer)
        assert inner  # the graph is not trivially empty

    def test_zero_config_key_drift(self, repo_program):
        program, _ = repo_program
        assert config_key_findings(program, [TESTS_DIR]) == []

    def test_zero_fault_point_drift(self, repo_program):
        program, _ = repo_program
        assert fault_point_findings(program) == []

    def test_zero_resource_findings(self, repo_program):
        program, _ = repo_program
        assert resource_findings(program) == []

    def test_repo_is_race_free_under_hsl013(self, repo_program):
        """The HSL013 analog of the HSL009 cycle-free proof: every
        shared state in serve/, the session, and the caches is accessed
        under a consistent lockset (docs/serving.md)."""
        program, callgraph = repo_program
        effects = Effects(program, callgraph)
        assert lockset_race_findings(program, effects) == []
        # and the proof is about the state that matters — the serving
        # plane's mutable attributes are all tracked:
        for state in (
            "hyperspace_tpu.serve.scheduler.QueryServer._inflight",
            "hyperspace_tpu.serve.scheduler.QueryServer._fifo",
            "hyperspace_tpu.serve.plan_cache.PlanCache._entries",
            "hyperspace_tpu.serve.result_cache.ResultCache._entries",
            "hyperspace_tpu.hyperspace.HyperspaceSession._last_profile",
            "hyperspace_tpu.hyperspace.HyperspaceSession.index_health",
            "hyperspace_tpu.metadata.cache.CreationTimeBasedCache._entry",
            "hyperspace_tpu.execution.device_cache.RefCache._entries",
            "hyperspace_tpu.ops.filter._MASK_FN_CACHE",
        ):
            assert state in effects.by_state, state

    def test_repo_has_no_atomicity_violations(self, repo_program):
        program, callgraph = repo_program
        effects = Effects(program, callgraph)
        assert atomicity_findings(program, effects) == []

    def test_repo_jit_sites_are_cache_hygienic(self, repo_program):
        """Every jit-of-local-fn site in ops/ is behind an lru_cache
        factory or an explicit memo — no per-call cache keys (the
        recompile-storm pattern behind the map-count segfault)."""
        program, _ = repo_program
        assert jit_hygiene_findings(program) == []

    def test_race_allowlist_is_narrow_and_justified(self, repo_program):
        program, callgraph = repo_program
        effects = Effects(program, callgraph)
        for state, why in RACE_ALLOWLIST.items():
            assert why, state
            # a stale entry silently widens the exemption surface
            assert state in effects.by_state, f"stale RACE_ALLOWLIST entry: {state}"

    def test_unresolved_call_accounting_and_bound(self, repo_program):
        """The unresolved-call ratio is recorded in the report summary,
        and resolution quality can't silently degrade: the deliberately
        under-approximate resolver leaves stdlib/numpy/jax calls
        unresolved (~3/4 of all sites today), but a jump past the bound
        means a resolver regression is hiding lock/effect edges."""
        report = run_check(default_paths(REPO_ROOT), REPO_ROOT, [TESTS_DIR])
        s = report["summary"]
        assert s["calls_unresolved"] > 0
        assert 0.0 < s["calls_unresolved_ratio"] < 0.85
        program, callgraph = repo_program
        total = len(callgraph.edges) + len(callgraph.unresolved)
        assert s["calls_unresolved_ratio"] == round(len(callgraph.unresolved) / total, 4)

    def test_entry_lock_fixpoint_on_repo(self, repo_program):
        # io._evict_locked is only ever called with the IO cache lock
        # held — the fixpoint must prove it (this is what keeps its
        # unlocked-looking mutations out of HSL013).
        program, callgraph = repo_program
        effects = Effects(program, callgraph)
        assert (
            "hyperspace_tpu.execution.io._cache_lock"
            in effects.entry_locks["hyperspace_tpu.execution.io._evict_locked"]
        )

    def test_validator_corpus_passes(self):
        report = validator_corpus()
        assert report["status"] == "ok", report

    def test_run_check_clean(self, repo_program):
        report = run_check(default_paths(REPO_ROOT), REPO_ROOT, [TESTS_DIR])
        assert report["_findings"] == []
        assert report["summary"]["allowlisted"] == len(report["allowlisted"])
        assert report["summary"]["locks"] >= 20

    def test_seeded_typo_counter_is_caught(self, repo_program):
        # Sanity that the repo-wide zero isn't vacuous: a typo'd key in a
        # scratch module next to the real program is flagged with a
        # did-you-mean naming the declared key.
        src = 'def f(conf):\n    return conf.get("hyperspace.serve.workerz")\n'
        name, path = "scratch_mod", "scratch_mod.py"
        program = Program({name: _index_module(name, path, src, ast.parse(src))})
        findings = config_key_findings(program, [])
        assert [f.rule for f in findings] == ["HSL010"]
        assert "hyperspace.serve.workers" in findings[0].message

    def test_seeded_unthreaded_fault_point_is_caught(self, repo_program, monkeypatch):
        from hyperspace_tpu import faults as faults_mod

        program, _ = repo_program
        monkeypatch.setattr(
            faults_mod, "KNOWN_POINTS", (*faults_mod.KNOWN_POINTS, "ghost.point")
        )
        findings = fault_point_findings(program)
        assert [f.rule for f in findings] == ["HSL012"]
        assert "ghost.point" in findings[0].message
        assert "never threaded" in findings[0].message

    def test_allowlist_is_narrow_and_justified(self):
        for (suffix, rule), why in TEST_ALLOWLIST.items():
            assert not suffix.startswith("hyperspace_tpu/"), (
                "the allowlist is for test/benchmark surfaces only — "
                "package findings get fixed"
            )
            assert why


# -- check CLI ----------------------------------------------------------------

class TestCheckCli:
    def test_exit_clean_on_repo(self):
        proc = subprocess.run(
            [sys.executable, "-m", "hyperspace_tpu.analysis.check"],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )
        assert proc.returncode == EXIT_CLEAN, proc.stdout + proc.stderr
        assert "cycle-free=True" in proc.stderr

    def test_exit_findings_without_baseline(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        assert check_main([str(bad), "--no-baseline"]) == EXIT_FINDINGS

    def test_exit_internal_error(self, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        monkeypatch.setattr(
            check_mod, "run_check",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        assert check_mod.main(["--no-baseline"]) == EXIT_INTERNAL_ERROR

    def test_baseline_masks_old_findings_only(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        baseline = tmp_path / "baseline.json"
        # 1. write the baseline: current findings become "known"
        assert check_main([str(bad), "--baseline", str(baseline),
                           "--write-baseline"]) == EXIT_CLEAN
        assert json.loads(baseline.read_text())["findings"]
        # 2. same findings, baseline present -> clean
        assert check_main([str(bad), "--baseline", str(baseline)]) == EXIT_CLEAN
        # 3. a NEW finding fails even with the baseline
        bad.write_text("from jax import shard_map\nimport numpy as np\nv = np.random.rand(3)\n")
        assert check_main([str(bad), "--baseline", str(baseline)]) == EXIT_FINDINGS

    def test_json_report_shape(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        out = tmp_path / "report.json"
        rc = check_main([str(bad), "--no-baseline", "--format", "json",
                         "--output", str(out)])
        assert rc == EXIT_FINDINGS
        report = json.loads(out.read_text())
        assert report["summary"]["new_findings"] == 1
        (finding,) = report["findings"]
        assert finding["rule"] == "HSL001"
        assert finding["slug"] == "fragile-jax-import"
        assert finding["new"] is True
        assert report["validator_corpus"]["status"] in ("ok", "skipped")
        assert "lock_graph" in report

    def test_docs_table_in_sync(self):
        # docs/configuration.md's key table is generated from
        # config.KNOWN_KEYS; this is the no-drift assertion.
        from hyperspace_tpu.analysis.check import docs_findings

        assert docs_findings(REPO_ROOT) == []

    def test_sarif_exit_codes_match_json(self, tmp_path):
        # the SARIF renderer changes the artifact, never the gate:
        # 0 = clean, 1 = new findings, 2 = internal error — same as json.
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        assert check_main([str(clean), "--no-baseline", "--format", "sarif"]) == EXIT_CLEAN
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        out = tmp_path / "report.sarif"
        rc = check_main([str(bad), "--no-baseline", "--format", "sarif",
                         "--output", str(out)])
        assert rc == EXIT_FINDINGS
        sarif = json.loads(out.read_text())
        assert sarif["version"] == "2.1.0"
        run = sarif["runs"][0]
        assert run["tool"]["driver"]["name"] == "hyperspace-analysis"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"HSL013", "HSL014", "HSL015"} <= rule_ids
        (result,) = run["results"]
        assert result["ruleId"] == "HSL001"
        assert result["baselineState"] == "new"
        assert result["locations"][0]["physicalLocation"]["region"]["startLine"] == 1

    def test_sarif_internal_error_exit(self, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        monkeypatch.setattr(
            check_mod, "run_check",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        assert check_mod.main(["--no-baseline", "--format", "sarif"]) == EXIT_INTERNAL_ERROR

    def test_sarif_baseline_state_unchanged(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        baseline = tmp_path / "baseline.json"
        assert check_main([str(bad), "--baseline", str(baseline),
                           "--write-baseline"]) == EXIT_CLEAN
        out = tmp_path / "report.sarif"
        rc = check_main([str(bad), "--baseline", str(baseline),
                         "--format", "sarif", "--output", str(out)])
        assert rc == EXIT_CLEAN  # known finding: gate passes...
        (result,) = json.loads(out.read_text())["runs"][0]["results"]
        assert result["baselineState"] == "unchanged"  # ...but SARIF keeps it

    def test_changed_mode_restricts_reporting(self, tmp_path, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        other = tmp_path / "other.py"
        other.write_text("import numpy as np\nv = np.random.rand(3)\n")
        # only other.py "changed": bad.py's finding must be masked
        monkeypatch.setattr(
            check_mod, "changed_files", lambda root: ("origin/main", {"other.py"})
        )
        monkeypatch.setattr(check_mod, "_repo_root", lambda: tmp_path)
        out = tmp_path / "report.json"
        rc = check_mod.main([str(bad), str(other), "--no-baseline", "--changed",
                             "--format", "json", "--output", str(out)])
        assert rc == EXIT_FINDINGS
        report = json.loads(out.read_text())
        assert report["changed"] == {"base": "origin/main", "files": ["other.py"]}
        assert [f["rule"] for f in report["findings"]] == ["HSL005"]
        # nothing changed -> clean exit even with the bad file on disk
        monkeypatch.setattr(check_mod, "changed_files", lambda root: ("origin/main", set()))
        assert check_mod.main([str(bad), "--no-baseline", "--changed"]) == EXIT_CLEAN

    def test_changed_mode_falls_back_without_git(self, tmp_path, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        monkeypatch.setattr(check_mod, "changed_files", lambda root: None)
        # git unavailable: full run, the finding still fails the gate
        assert check_mod.main([str(bad), "--no-baseline", "--changed"]) == EXIT_FINDINGS

    def test_changed_files_parses_git(self):
        # against the real repo: returns a base ref and a set of paths
        got = check_mod_changed_files(REPO_ROOT)
        if got is None:
            pytest.skip("git unavailable in this environment")
        base, files = got
        assert base in ("origin/main", "main", "HEAD")
        assert all(isinstance(p, str) for p in files)
