"""Whole-program analysis engine tests (analysis/program.py,
callgraph.py, locks.py, check.py): fixture-package goldens, the seeded
lock-inversion regression, the per-rule corpus, and the repo-wide
guarantees the CI check gate rides on (cycle-free lock graph, zero
config/fault drift)."""

from __future__ import annotations

import ast
import json
import pathlib
import subprocess
import sys

import pytest

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.check import (
    TEST_ALLOWLIST,
    changed_files as check_mod_changed_files,
    config_key_findings,
    default_paths,
    fault_point_findings,
    main as check_main,
    run_check,
    validator_corpus,
)
from hyperspace_tpu.analysis.lint import (
    EXIT_CLEAN,
    EXIT_FINDINGS,
    EXIT_INTERNAL_ERROR,
    RULES,
    lint_source,
)
from hyperspace_tpu.analysis.duradomain import DurabilityDomains
from hyperspace_tpu.analysis.effects import Effects
from hyperspace_tpu.analysis.locks import LockGraph, resource_findings
from hyperspace_tpu.analysis.procdomain import (
    SPAWN_ENTRY_POINTS,
    ProcessDomains,
    declared_entry_points,
    module_level_imports,
)
from hyperspace_tpu.analysis.program import Program, _index_module, _module_name
from hyperspace_tpu.analysis.tracedomain import (
    TraceDomains,
    declared_static_domains,
)
from hyperspace_tpu.analysis.races import (
    RACE_ALLOWLIST,
    atomicity_findings,
    jit_hygiene_findings,
    lockset_race_findings,
)
from hyperspace_tpu.analysis.raises import (
    DYNAMIC,
    DYNAMIC_RAISES,
    Raises,
    declared_contracts,
    error_contract_findings,
    known_fault_points,
    recovery_roots,
    swallowed_findings,
    unwind_findings,
)

TESTS_DIR = pathlib.Path(__file__).resolve().parent
FIXTURES = TESTS_DIR / "analysis_fixtures"
REPO_ROOT = TESTS_DIR.parent


# -- shared fixtures ----------------------------------------------------------

@pytest.fixture(scope="module")
def lockdemo():
    program = Program.load([FIXTURES / "lockdemo"])
    callgraph = CallGraph(program)
    return program, callgraph, LockGraph(program, callgraph)


@pytest.fixture(scope="module")
def repo_program():
    program = Program.load(default_paths(REPO_ROOT))
    callgraph = CallGraph(program)
    return program, callgraph


@pytest.fixture(scope="module")
def repo_check():
    """One timed full run_check over the real tree. run_check is pure
    (static analysis of on-disk sources), so every test that reads the
    report shares this pass — including the wall-time gate, which reads
    the clock captured here instead of paying for its own full run."""
    import time

    t0 = time.perf_counter()
    report = run_check(default_paths(REPO_ROOT), REPO_ROOT, [TESTS_DIR])
    return report, time.perf_counter() - t0


# -- fixture-package goldens --------------------------------------------------

class TestLockdemoGoldens:
    def test_call_graph_matches_golden(self, lockdemo):
        _, callgraph, _ = lockdemo
        golden = json.loads((FIXTURES / "goldens" / "lockdemo_callgraph.json").read_text())
        assert json.loads(json.dumps(callgraph.to_json())) == golden

    def test_lock_graph_matches_golden(self, lockdemo):
        _, _, lockgraph = lockdemo
        golden = json.loads((FIXTURES / "goldens" / "lockdemo_lockgraph.json").read_text())
        assert json.loads(json.dumps(lockgraph.to_json())) == golden

    def test_lock_identities_and_kinds(self, lockdemo):
        program, _, _ = lockdemo
        assert program.locks["lockdemo.alpha._registry_lock"].kind == "Lock"
        assert program.locks["lockdemo.alpha.Session._state_lock"].kind == "RLock"
        assert program.locks["lockdemo.alpha.Cache._lock"].cls == "Cache"

    def test_typed_attribute_call_resolution(self, lockdemo):
        # self.cache = Cache() makes self.cache.put_entry resolve without
        # any unique-name fallback.
        _, callgraph, _ = lockdemo
        assert "lockdemo.alpha.Cache.put_entry" in callgraph.callees(
            "lockdemo.alpha.Session.publish"
        )

    def test_cross_module_call_resolution(self, lockdemo):
        _, callgraph, _ = lockdemo
        assert "lockdemo.beta.audit" in callgraph.callees("lockdemo.alpha.register")
        assert "lockdemo.alpha.register" in callgraph.callees("lockdemo.beta.rollback")

    def test_reachability(self, lockdemo):
        _, callgraph, _ = lockdemo
        reach = callgraph.reachable("lockdemo.beta.rollback")
        assert "lockdemo.beta.audit" in reach  # rollback -> register -> audit


class TestSeededInversion:
    """The acceptance regression: HSL009 catches the deliberately
    inverted lock pair in the fixture package, with a two-chain witness
    naming both conflicting call chains."""

    def test_inversion_reported(self, lockdemo):
        _, _, lockgraph = lockdemo
        rules = [f.rule for f in lockgraph.inversions()]
        assert "HSL009" in rules

    def test_two_chain_witness(self, lockdemo):
        _, _, lockgraph = lockdemo
        pair = [
            f for f in lockgraph.inversions()
            if "_registry_lock" in f.message and "_audit_lock" in f.message
            and "inversion" in f.message
        ]
        assert len(pair) == 1
        msg = pair[0].message
        assert "chain 1" in msg and "chain 2" in msg
        # chain 1: register (holds registry) -> audit; chain 2:
        # rollback (holds audit) -> register.
        assert "lockdemo.alpha.register -> lockdemo.beta.audit" in msg
        assert "lockdemo.beta.rollback -> lockdemo.alpha.register" in msg

    def test_transitive_self_deadlock_reported(self, lockdemo):
        # rollback holds the (non-reentrant) audit lock and the chain
        # register -> audit re-acquires it: a real self-deadlock.
        _, _, lockgraph = lockdemo
        assert any(
            "re-acquired while already held" in f.message
            for f in lockgraph.inversions()
        )

    def test_rlock_reentry_not_flagged(self, lockdemo):
        # Session.refresh -> snapshot re-enters the session RLock: legal.
        _, _, lockgraph = lockdemo
        assert not any(
            "_state_lock" in f.message for f in lockgraph.inversions()
        )

    def test_edge_direction_recorded_both_ways(self, lockdemo):
        _, _, lockgraph = lockdemo
        best = lockgraph.order_edges()
        assert ("lockdemo.alpha._registry_lock", "lockdemo.beta._audit_lock") in best
        assert ("lockdemo.beta._audit_lock", "lockdemo.alpha._registry_lock") in best


# -- per-rule corpus ----------------------------------------------------------

CORPUS = sorted((FIXTURES / "rules").glob("hsl*.py"))


def _expected(path: pathlib.Path) -> set[tuple[int, str]]:
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), 1):
        if "# expect:" in line:
            out.add((i, line.split("# expect:", 1)[1].strip()))
    return out


def _corpus_findings(path: pathlib.Path) -> set[tuple[int, str]]:
    """Run the full rule set (per-file lint + whole-program rules) over
    one corpus file, exactly as check.py composes them."""
    src = path.read_text()
    tree = ast.parse(src)
    findings = list(lint_source(src, str(path), tree=tree))
    name = _module_name(path)
    program = Program({name: _index_module(name, str(path), src, tree)})
    callgraph = CallGraph(program)
    findings += LockGraph(program, callgraph).inversions()
    findings += resource_findings(program)
    findings += config_key_findings(program, [])
    findings += fault_point_findings(program)
    effects = Effects(program, callgraph)
    findings += lockset_race_findings(program, effects)
    findings += atomicity_findings(program, effects)
    findings += jit_hygiene_findings(program)
    raises_obj = Raises(program, callgraph)
    contracts = declared_contracts(program)
    findings += error_contract_findings(program, raises_obj, contracts)
    findings += swallowed_findings(program, raises_obj)
    findings += unwind_findings(program, callgraph, raises_obj, contracts)[0]
    ddomains = DurabilityDomains(program, callgraph, raises_obj)
    # check.py's dedupe: a write site HSL027 claims reports once, under
    # the newer rule, never twice as HSL021+HSL027.
    findings += [
        f for f in ProcessDomains(program, callgraph, raises_obj).findings()
        if not (f.rule == "HSL021" and (f.path, f.line) in ddomains.claimed_sites)
    ]
    findings += TraceDomains(program, callgraph, raises_obj).findings()
    findings += ddomains.findings()
    return {(f.line, f.rule) for f in findings}


@pytest.mark.parametrize("path", CORPUS, ids=[p.stem for p in CORPUS])
def test_rule_corpus(path):
    """Each corpus file must produce exactly its `# expect:` annotations:
    flagged lines flag, clean lines stay clean, nothing extra fires."""
    assert _corpus_findings(path) == _expected(path)


def test_corpus_covers_every_rule():
    covered = {p.stem.upper() for p in CORPUS}
    declared = {r for r in RULES if r not in ("HSL000",)}
    assert covered == declared


# -- racedemo fixture package (effects + race rules) --------------------------

@pytest.fixture(scope="module")
def racedemo():
    program = Program.load([FIXTURES / "racedemo"])
    callgraph = CallGraph(program)
    return program, callgraph, Effects(program, callgraph)


class TestRacedemo:
    def test_effect_summaries_match_golden(self, racedemo):
        _, _, effects = racedemo
        golden = json.loads((FIXTURES / "goldens" / "racedemo_effects.json").read_text())
        assert json.loads(json.dumps(effects.to_json())) == golden

    def test_exactly_three_planted_findings(self, racedemo):
        program, _, effects = racedemo
        findings = (
            lockset_race_findings(program, effects)
            + atomicity_findings(program, effects)
            + jit_hygiene_findings(program)
        )
        assert sorted(f.rule for f in findings) == ["HSL013", "HSL014", "HSL015"]

    def test_hsl013_two_path_witness(self, racedemo):
        program, _, effects = racedemo
        (f,) = lockset_race_findings(program, effects)
        assert f.rule == "HSL013"
        # the witness names BOTH conflicting access paths with locksets
        assert "path 1" in f.message and "path 2" in f.message
        assert "racedemo.store.Store.put" in f.message
        assert "racedemo.store.Store.reset_unsafe" in f.message
        assert "holding racedemo.store.Store._lock" in f.message
        assert "holding no lock" in f.message
        assert "held at 5/6 accesses" in f.message

    def test_hsl014_names_both_critical_sections(self, racedemo):
        program, _, effects = racedemo
        (f,) = atomicity_findings(program, effects)
        assert f.rule == "HSL014"
        assert "bump_torn" in f.message
        assert "read under" in f.message and "re-acquired" in f.message

    def test_hsl015_flags_loop_lambda_only(self, racedemo):
        program, _, _ = racedemo
        (f,) = jit_hygiene_findings(program)
        assert f.rule == "HSL015"
        assert "fresh lambda" in f.message
        assert f.path.endswith("kernels.py")

    def test_guarded_state_stays_clean(self, racedemo):
        # _entries (consistently locked) and _FN_CACHE (memo under lock)
        # are tracked but not reported — the proof isn't vacuous.
        _, _, effects = racedemo
        assert "racedemo.store.Store._entries" in effects.by_state
        assert "racedemo.kernels._FN_CACHE" in effects.by_state

    def test_entry_lock_guarantee_credits_callers(self):
        # A helper only ever called under the lock is credited with it
        # (must-hold-on-entry fixpoint) — no false race on its accesses.
        src = (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "_reg = {}\n"
            "def public(k, v):\n"
            "    with _lock:\n"
            "        _helper(k, v)\n"
            "def other(k):\n"
            "    with _lock:\n"
            "        _helper(k, None)\n"
            "def _helper(k, v):\n"
            "    _reg[k] = v\n"
            "def reader():\n"
            "    with _lock:\n"
            "        return dict(_reg)\n"
        )
        program = Program({"entrymod": _index_module("entrymod", "entrymod.py", src, ast.parse(src))})
        effects = Effects(program, CallGraph(program))
        assert effects.entry_locks["entrymod._helper"] == {"entrymod._lock"}
        assert lockset_race_findings(program, effects) == []


# -- raisedemo fixture package (raises + exception-flow rules) ----------------

@pytest.fixture(scope="module")
def raisedemo():
    program = Program.load([FIXTURES / "raisedemo"])
    callgraph = CallGraph(program)
    return program, callgraph, Raises(program, callgraph)


class TestRaisedemo:
    def test_raise_summaries_match_golden(self, raisedemo):
        _, _, raises_obj = raisedemo
        golden = json.loads((FIXTURES / "goldens" / "raisedemo_raises.json").read_text())
        assert json.loads(json.dumps(raises_obj.to_json())) == golden

    def test_exactly_three_planted_findings(self, raisedemo):
        program, callgraph, raises_obj = raisedemo
        contracts = declared_contracts(program)
        findings = (
            error_contract_findings(program, raises_obj, contracts)
            + swallowed_findings(program, raises_obj)
            + unwind_findings(program, callgraph, raises_obj, contracts)[0]
        )
        assert sorted(f.rule for f in findings) == ["HSL016", "HSL017", "HSL018"]

    def test_hsl016_witness_names_escape_and_contract(self, raisedemo):
        program, _, raises_obj = raisedemo
        (f,) = error_contract_findings(program, raises_obj)
        assert f.rule == "HSL016"
        assert "drifting_persist" in f.message
        assert "KeyError escapes" in f.message
        assert "PipelineError" in f.message  # the declared-but-narrower surface

    def test_hierarchy_narrowed_subtraction(self, raisedemo):
        # persist: EmptyStoreError (⊆ PipelineError) and the raise-from
        # transformation both stay inside the declared contract.
        _, _, raises_obj = raisedemo
        esc = raises_obj.escapes["raisedemo.api.persist"]
        assert sorted(esc) == ["EmptyStoreError", "PipelineError"]
        assert raises_obj.covers("PipelineError", "EmptyStoreError")
        assert not raises_obj.covers("EmptyStoreError", "PipelineError")

    def test_hsl017_flags_only_the_bare_swallow(self, raisedemo):
        program, _, raises_obj = raisedemo
        (f,) = swallowed_findings(program, raises_obj)
        assert f.rule == "HSL017"
        assert f.path.endswith("worker.py")
        assert "bare `except:`" in f.message

    def test_hsl018_proof_and_hole(self, raisedemo):
        program, callgraph, raises_obj = raisedemo
        contracts = declared_contracts(program)
        findings, proof = unwind_findings(program, callgraph, raises_obj, contracts)
        assert proof["demo.persist"]["covered"] is True
        (site,) = proof["demo.persist"]["sites"]
        assert site["chain"] == ["raisedemo.api.persist"]
        assert "declared error contract" in site["via"]
        assert proof["demo.orphan"]["covered"] is False
        (f,) = findings
        assert "demo.orphan" in f.message and "scrub" in f.message

    def test_fixture_points_extracted_from_ast(self, raisedemo):
        program, _, _ = raisedemo
        points, path = known_fault_points(program)
        assert points == {"demo.persist", "demo.orphan"}
        assert path.endswith("raisedemo/faults.py")


# -- procdemo fixture package (process domains + HSL019-022) ------------------

@pytest.fixture(scope="module")
def procdemo():
    program = Program.load([FIXTURES / "procdemo"])
    callgraph = CallGraph(program)
    raises_obj = Raises(program, callgraph)
    return program, callgraph, ProcessDomains(program, callgraph, raises_obj)


class TestProcdemo:
    def test_domain_graph_matches_golden(self, procdemo):
        _, _, domains = procdemo
        golden = json.loads((FIXTURES / "goldens" / "procdemo_domains.json").read_text())
        assert json.loads(json.dumps(domains.to_json())) == golden

    def test_exactly_four_planted_findings(self, procdemo):
        _, _, domains = procdemo
        rules = sorted(f.rule for f in domains.findings())
        assert rules == ["HSL019", "HSL020", "HSL021", "HSL022"]

    def test_hsl019_witness_names_entry_and_import_chain(self, procdemo):
        _, _, domains = procdemo
        (f,) = domains.spawn_import_findings()
        assert f.path.endswith("devkit.py")  # the module whose import is banned
        assert "procdemo.workers.shard_body" in f.message  # the seeding entry
        assert "procdemo.workers imports procdemo.devkit" in f.message
        # the witness chain carries BOTH files — --changed keeps the
        # finding when either side of the chain is what was edited
        assert any(p.endswith("workers.py") for p in f.witness_paths)
        assert any(p.endswith("devkit.py") for p in f.witness_paths)

    def test_hsl020_names_the_banned_type_and_site(self, procdemo):
        _, _, domains = procdemo
        (f,) = domains.exchange_typing_findings()
        assert "ColumnTable instance" in f.message
        assert "submit site" in f.message
        assert f.path.endswith("coord.py")

    def test_hsl020_path_list_submit_stays_clean(self, procdemo):
        # Same pool, same body, paths instead of a table: no finding at
        # the first submit line (the proof is not vacuous).
        _, _, domains = procdemo
        (f,) = domains.exchange_typing_findings()
        first_submit = min(
            s.line for s in domains.boundary_sites if s.kind == "submit"
        )
        assert f.line > first_submit

    def test_hsl021_flags_bare_write_not_atomic_publish(self, procdemo):
        _, _, domains = procdemo
        (f,) = domains.shared_file_findings()
        assert f.path.endswith("workers.py")
        assert "bad_manifest" in f.message
        # _publish_atomic (mkstemp + fsync + os.replace) stayed clean

    def test_hsl022_flags_carrier_without_install_state(self, procdemo):
        _, _, domains = procdemo
        (f,) = domains.continuity_findings()
        assert "bare_entry" in f.message
        assert "install_state" in f.message

    def test_service_body_deferred_engine_is_legal(self, procdemo):
        # worker_main boots devkit (jax) behind a deferred import: the
        # service module is in the domain, devkit is NOT pulled in
        # through it, and no finding lands on service.py.
        _, _, domains = procdemo
        assert "procdemo.service" in domains.domain_modules
        assert not any(
            f.path.endswith("service.py") for f in domains.findings()
        )

    def test_task_closure_and_boundary_inventory(self, procdemo):
        _, _, domains = procdemo
        assert "procdemo.workers._publish_atomic" in domains.task_fns
        chain = domains.task_fns["procdemo.workers._publish_atomic"]
        assert chain[0] == "procdemo.workers.shard_body"
        kinds = sorted(s.kind for s in domains.boundary_sites)
        assert kinds == ["put", "put", "return", "submit", "submit"]
        # both submits resolved their task-body target (declared ⇒ no
        # undeclared-target finding rode along)
        assert all(
            s.target == "procdemo.workers.shard_body"
            for s in domains.boundary_sites if s.kind == "submit"
        )


# -- jitdemo fixture package (trace domains + HSL023-026) ---------------------

@pytest.fixture(scope="module")
def jitdemo():
    program = Program.load([FIXTURES / "jitdemo"])
    callgraph = CallGraph(program)
    raises_obj = Raises(program, callgraph)
    return program, callgraph, TraceDomains(program, callgraph, raises_obj)


class TestJitdemo:
    def test_trace_graph_matches_golden(self, jitdemo):
        _, _, tdomains = jitdemo
        golden = json.loads((FIXTURES / "goldens" / "jitdemo_trace.json").read_text())
        assert json.loads(json.dumps(tdomains.to_json())) == golden

    def test_exactly_four_planted_findings(self, jitdemo):
        _, _, tdomains = jitdemo
        rules = sorted(f.rule for f in tdomains.findings())
        assert rules == ["HSL023", "HSL024", "HSL025", "HSL026"]

    def test_hsl023_witness_follows_the_closure(self, jitdemo):
        # The effect is two hops from the entry: leaky_norm -> _total.
        # HSL002 (lexical) cannot see it; the closure walk must, and
        # the finding must carry the chain.
        _, _, tdomains = jitdemo
        (f,) = [f for f in tdomains.findings() if f.rule == "HSL023"]
        assert f.path.endswith("traced.py")
        assert "stats counter increment" in f.message
        assert "jitdemo.traced.leaky_norm -> jitdemo.traced._total" in f.message
        assert any(p.endswith("traced.py") for p in f.witness_paths)

    def test_hsl023_engage_counterpart_stays_clean(self, jitdemo):
        # norm/engage hoists the same counter bump to the engagement
        # site — the proof is not vacuous.
        _, _, tdomains = jitdemo
        assert "jitdemo.traced.norm" in tdomains.trace_fns
        hits = [f for f in tdomains.findings() if f.rule == "HSL023"]
        assert len(hits) == 1
        assert all("jitdemo.traced.engage" not in f.message for f in hits)

    def test_hsl024_names_the_undeclared_static(self, jitdemo):
        # "order" is undeclared; "reps" (declared) stays clean.
        _, _, tdomains = jitdemo
        (f,) = [f for f in tdomains.findings() if f.rule == "HSL024"]
        assert "'order'" in f.message and "jitdemo.traced.poly" in f.message
        assert "reps" not in f.message

    def test_hsl025_mutation_names_the_gateway(self, jitdemo):
        # read_aliased mutates the staged view; read_owned (through
        # own_arrays) stays clean.
        _, _, tdomains = jitdemo
        (f,) = [f for f in tdomains.findings() if f.rule == "HSL025"]
        assert f.path.endswith("staging.py")
        assert "read_aliased" in f.message and "own_arrays" in f.message
        assert "read_owned" not in f.message

    def test_hsl026_flags_only_the_ladder_hole(self, jitdemo):
        # rowmax is missing exactly the permanent fallback; everything
        # else on its ladder (gate, both counters) is present, and
        # tile_reduce's complete ladder is proven.
        _, _, tdomains = jitdemo
        (f,) = [f for f in tdomains.findings() if f.rule == "HSL026"]
        assert "'jitdemo.rowmax'" in f.message
        assert "permanent per-shape fallback" in f.message
        assert "gate" not in f.message.split("missing", 1)[1]
        by_kernel = {lad["kernel"]: lad for lad in tdomains._kernel_ladders}
        assert by_kernel["jitdemo.tile_reduce"]["proven"] is True
        assert by_kernel["jitdemo.rowmax"]["proven"] is False
        assert by_kernel["jitdemo.tile_reduce"]["witness"] == [
            "jitdemo.device.tile_reduce", "jitdemo.device._make_tile_reduce",
        ]

    def test_entry_forms_and_kind_merge(self, jitdemo):
        # All entry shapes detected: bare @jit, partial(jit, ...),
        # call-form jit in factories, the shard_map body (which is also
        # the jit call-form target: kinds merge), and Pallas kernels.
        _, _, tdomains = jitdemo
        entries = json.loads(json.dumps(tdomains.to_json()))["entries"]
        assert entries["jitdemo.traced.make_exchange.<locals>.fn"]["kinds"] == [
            "jit", "shard_map",
        ]
        assert entries["jitdemo.traced.make_exchange.<locals>.fn"]["key"] == (
            "jitdemo.exchange"
        )
        kinds = {k for e in entries.values() for k in e["kinds"]}
        assert kinds == {"jit", "shard_map", "pallas_kernel"}

    def test_donation_proof_records_the_gateway_witness(self, jitdemo):
        _, _, tdomains = jitdemo
        proof = json.loads(json.dumps(tdomains.to_json()))["donation_proof"]
        assert proof["donation_sites"] == []
        # the planted mutation flips the proof off for the fixture
        assert proof["proven"] is False
        owned = [p for p in proof["staged_view_producers"]
                 if p["fn"].endswith("read_owned")]
        assert owned[0]["ownership_witness"] == ["jitdemo.staging.read_owned"]

    def test_static_domain_registry_extracted(self, jitdemo):
        program, _, _ = jitdemo
        assert declared_static_domains(program) == {"reps", "n"}


# -- durademo fixture package (durability domains + HSL027-030) ---------------

@pytest.fixture(scope="module")
def durademo():
    program = Program.load([FIXTURES / "durademo"])
    callgraph = CallGraph(program)
    raises_obj = Raises(program, callgraph)
    return program, callgraph, DurabilityDomains(program, callgraph, raises_obj)


class TestDurademo:
    def test_durability_graph_matches_golden(self, durademo):
        _, _, ddomains = durademo
        golden = json.loads((FIXTURES / "goldens" / "durademo_dura.json").read_text())
        assert json.loads(json.dumps(ddomains.to_json())) == golden

    def test_exactly_four_planted_findings(self, durademo):
        _, _, ddomains = durademo
        rules = sorted(f.rule for f in ddomains.findings())
        assert rules == ["HSL027", "HSL028", "HSL029", "HSL030"]

    def test_hsl027_names_root_and_idiom(self, durademo):
        _, _, ddomains = durademo
        (f,) = [f for f in ddomains.findings() if f.rule == "HSL027"]
        assert "'ledger'" in f.message
        assert "durademo.store.publish_fast" in f.message
        assert "fsync" in f.message
        assert f.witness_paths and f.witness_paths[0].endswith("store.py")
        # the proven direct counterpart and the delegated-clean site
        # stay quiet but are inventoried with their witness chains
        sites = {(s.fn, s.kind): s for s in ddomains.sites}
        delegated = sites[("durademo.store.save_ledger", "delegated")]
        assert delegated.ok
        assert delegated.chain == ("durademo.store.publish_json",)

    def test_hsl028_unproven_window_names_the_missing_point(self, durademo):
        _, _, ddomains = durademo
        (f,) = [f for f in ddomains.findings() if f.rule == "HSL028"]
        assert "'durademo.commit_before_stamp'" in f.message
        assert "no armed faults.fault_point('durademo.stamp')" in f.message
        proofs = ddomains._window_proofs
        assert proofs["durademo.batch_before_cursor"]["proven"] is True
        assert proofs["durademo.batch_before_cursor"]["point"]["line"] is not None
        assert proofs["durademo.commit_before_stamp"]["ordered"] is True
        assert proofs["durademo.commit_before_stamp"]["proven"] is False

    def test_hsl029_witness_follows_the_replay_chain(self, durademo):
        _, _, ddomains = durademo
        (f,) = [f for f in ddomains.findings() if f.rule == "HSL029"]
        assert "'time.time'" in f.message
        assert (
            "durademo.tailer.Tailer.poll -> durademo.tailer.Tailer._write_batch"
            in f.message
        )
        # the seq-named cursor write on the same replay path stays clean
        assert "_save_cursor" not in f.message

    def test_hsl030_closure_walk_finds_the_hidden_read(self, durademo):
        _, _, ddomains = durademo
        (f,) = [f for f in ddomains.findings() if f.rule == "HSL030"]
        assert "get_latest_id() live version read" in f.message
        assert "durademo.control.Planner.resolve" in f.message
        assert "durademo.control._live_floor" in f.message
        # both sanctioned shapes stay clean: the snapshot-dispatch split
        # and the default-fill idiom
        assert "plan_key" not in f.message and "decide" not in f.message

    def test_registries_extracted_and_claimed_sites_cover_every_site(self, durademo):
        program, _, ddomains = durademo
        assert set(ddomains.roots) == {"ledger", "batches", "cursor"}
        assert set(ddomains.windows) == {
            "durademo.batch_before_cursor", "durademo.commit_before_stamp",
        }
        assert set(ddomains.replay_roots) == {"durademo.tailer.Tailer.poll"}
        assert ddomains.known_points == {"durademo.tail", "durademo.stamp"}
        for s in ddomains.sites:
            mod = program.modules[program.functions[s.fn].module]
            assert (mod.path, s.line) in ddomains.claimed_sites


# -- repo-wide guarantees (what the CI gate asserts) --------------------------

class TestRepoWideGuarantees:
    def test_lock_graph_is_cycle_free(self, repo_program):
        """The acceptance proof: the full lock-acquisition graph —
        session RLock, metadata cache, device cache, serve scheduler
        condvar, plan/result caches, module memo locks — has no cycle."""
        program, callgraph = repo_program
        lockgraph = LockGraph(program, callgraph)
        assert lockgraph.inversions() == []
        # and it actually covers the locks the serving PR added:
        for lock_id in (
            "hyperspace_tpu.hyperspace.HyperspaceSession._state_lock",
            "hyperspace_tpu.metadata.cache.CreationTimeBasedCache._lock",
            "hyperspace_tpu.execution.device_cache.RefCache._lock",
            "hyperspace_tpu.serve.scheduler.QueryServer._cv",
            "hyperspace_tpu.serve.plan_cache.PlanCache._lock",
            "hyperspace_tpu.serve.result_cache.ResultCache._lock",
            "hyperspace_tpu.ops.filter._MASK_FN_LOCK",
            "hyperspace_tpu.utils.jit_memory._limit_lock",
        ):
            assert lock_id in program.locks, lock_id

    def test_lock_holders_reach_only_leaf_metric_locks(self, repo_program):
        # The shape of the healthy graph: every order edge terminates in
        # a metrics-registry leaf lock (which never calls out).
        program, callgraph = repo_program
        lockgraph = LockGraph(program, callgraph)
        inner = {b for (_, b) in lockgraph.order_edges()}
        outer = {a for (a, _) in lockgraph.order_edges()}
        assert not any(lock.startswith("hyperspace_tpu.obs.metrics") for lock in outer)
        assert inner  # the graph is not trivially empty

    def test_zero_config_key_drift(self, repo_program):
        program, _ = repo_program
        assert config_key_findings(program, [TESTS_DIR]) == []

    def test_zero_fault_point_drift(self, repo_program):
        program, _ = repo_program
        assert fault_point_findings(program) == []

    def test_zero_resource_findings(self, repo_program):
        program, _ = repo_program
        assert resource_findings(program) == []

    def test_repo_is_race_free_under_hsl013(self, repo_program):
        """The HSL013 analog of the HSL009 cycle-free proof: every
        shared state in serve/, the session, and the caches is accessed
        under a consistent lockset (docs/serving.md)."""
        program, callgraph = repo_program
        effects = Effects(program, callgraph)
        assert lockset_race_findings(program, effects) == []
        # and the proof is about the state that matters — the serving
        # plane's mutable attributes are all tracked:
        for state in (
            "hyperspace_tpu.serve.scheduler.QueryServer._inflight",
            "hyperspace_tpu.serve.scheduler.QueryServer._fifo",
            "hyperspace_tpu.serve.plan_cache.PlanCache._entries",
            "hyperspace_tpu.serve.result_cache.ResultCache._entries",
            "hyperspace_tpu.hyperspace.HyperspaceSession._last_profile",
            "hyperspace_tpu.hyperspace.HyperspaceSession.index_health",
            "hyperspace_tpu.metadata.cache.CreationTimeBasedCache._entry",
            "hyperspace_tpu.execution.device_cache.RefCache._entries",
            "hyperspace_tpu.ops.filter._MASK_FN_CACHE",
        ):
            assert state in effects.by_state, state

    def test_repo_has_no_atomicity_violations(self, repo_program):
        program, callgraph = repo_program
        effects = Effects(program, callgraph)
        assert atomicity_findings(program, effects) == []

    def test_repo_jit_sites_are_cache_hygienic(self, repo_program):
        """Every jit-of-local-fn site in ops/ is behind an lru_cache
        factory or an explicit memo — no per-call cache keys (the
        recompile-storm pattern behind the map-count segfault)."""
        program, _ = repo_program
        assert jit_hygiene_findings(program) == []

    def test_race_allowlist_is_narrow_and_justified(self, repo_program):
        program, callgraph = repo_program
        effects = Effects(program, callgraph)
        for state, why in RACE_ALLOWLIST.items():
            assert why, state
            # a stale entry silently widens the exemption surface
            assert state in effects.by_state, f"stale RACE_ALLOWLIST entry: {state}"

    def test_unresolved_call_accounting_and_bound(self, repo_program, repo_check):
        """The unresolved-call ratio is recorded in the report summary,
        and resolution quality can't silently degrade: the deliberately
        under-approximate resolver leaves stdlib/numpy/jax calls
        unresolved (~3/4 of all sites today), but a jump past the bound
        means a resolver regression is hiding lock/effect edges."""
        report, _ = repo_check
        s = report["summary"]
        assert s["calls_unresolved"] > 0
        assert 0.0 < s["calls_unresolved_ratio"] < 0.85
        program, callgraph = repo_program
        total = len(callgraph.edges) + len(callgraph.unresolved)
        assert s["calls_unresolved_ratio"] == round(len(callgraph.unresolved) / total, 4)

    def test_entry_lock_fixpoint_on_repo(self, repo_program):
        # io._evict_locked is only ever called with the IO cache lock
        # held — the fixpoint must prove it (this is what keeps its
        # unlocked-looking mutations out of HSL013).
        program, callgraph = repo_program
        effects = Effects(program, callgraph)
        assert (
            "hyperspace_tpu.execution.io._cache_lock"
            in effects.entry_locks["hyperspace_tpu.execution.io._evict_locked"]
        )

    def test_validator_corpus_passes(self):
        report = validator_corpus()
        assert report["status"] == "ok", report

    def test_run_check_clean(self, repo_check):
        report, _ = repo_check
        assert report["_findings"] == []
        assert report["summary"]["allowlisted"] == len(report["allowlisted"])
        assert report["summary"]["locks"] >= 20

    def test_seeded_typo_counter_is_caught(self, repo_program):
        # Sanity that the repo-wide zero isn't vacuous: a typo'd key in a
        # scratch module next to the real program is flagged with a
        # did-you-mean naming the declared key.
        src = 'def f(conf):\n    return conf.get("hyperspace.serve.workerz")\n'
        name, path = "scratch_mod", "scratch_mod.py"
        program = Program({name: _index_module(name, path, src, ast.parse(src))})
        findings = config_key_findings(program, [])
        assert [f.rule for f in findings] == ["HSL010"]
        assert "hyperspace.serve.workers" in findings[0].message

    def test_seeded_unthreaded_fault_point_is_caught(self, repo_program, monkeypatch):
        from hyperspace_tpu import faults as faults_mod

        program, _ = repo_program
        monkeypatch.setattr(
            faults_mod, "KNOWN_POINTS", (*faults_mod.KNOWN_POINTS, "ghost.point")
        )
        findings = fault_point_findings(program)
        assert [f.rule for f in findings] == ["HSL012"]
        assert "ghost.point" in findings[0].message
        assert "never threaded" in findings[0].message

    def test_allowlist_is_narrow_and_justified(self):
        for (suffix, rule), why in TEST_ALLOWLIST.items():
            assert not suffix.startswith("hyperspace_tpu/"), (
                "the allowlist is for test/benchmark surfaces only — "
                "package findings get fixed"
            )
            assert why


# -- exception-flow guarantees (HSL016-HSL018 on the real repo) ---------------

@pytest.fixture(scope="module")
def repo_raises(repo_program):
    program, callgraph = repo_program
    return Raises(program, callgraph)


class TestRepoExceptionFlow:
    def test_every_contract_holds(self, repo_program, repo_raises):
        """The acceptance proof: each public API's statically observed
        escape set ⊆ its declared ERROR_CONTRACTS entry."""
        program, _ = repo_program
        assert error_contract_findings(program, repo_raises) == []

    def test_contracts_cover_the_serving_surface(self, repo_program):
        program, _ = repo_program
        contracts = declared_contracts(program)
        for q in (
            "hyperspace_tpu.hyperspace.HyperspaceSession.run",
            "hyperspace_tpu.hyperspace.HyperspaceSession.run_query",
            "hyperspace_tpu.serve.scheduler.QueryServer.submit",
            "hyperspace_tpu.serve.scheduler.QueryHandle.result",
            "hyperspace_tpu.hyperspace.Hyperspace.recover",
            "hyperspace_tpu.actions.base.Action.run",
        ):
            assert q in contracts, q
            assert q in program.functions, q  # no dead entries

    def test_crash_point_escapes_the_query_path(self, repo_raises):
        """CrashPoint must REACH the public APIs: a simulated dying
        writer that got absorbed below session.run would mean some
        handler 'survived' a process death."""
        for q in (
            "hyperspace_tpu.hyperspace.HyperspaceSession.run",
            "hyperspace_tpu.actions.base.Action.run",
        ):
            esc = repo_raises.escapes[q]
            assert "CrashPoint" in esc, q
            # and the witness chain bottoms out in the fault harness
            assert esc["CrashPoint"].chain[-1] == "hyperspace_tpu.faults._hit"

    def test_hierarchy_grafts_local_types_onto_builtins(self, repo_raises):
        assert repo_raises.ancestors["FaultError"][:2] == ("FaultError", "OSError")
        assert "Exception" in repo_raises.ancestors["FaultError"]
        assert repo_raises.ancestors["CrashPoint"] == ("CrashPoint", "BaseException")
        assert "HyperspaceError" in repo_raises.ancestors["IndexCorruptionError"]

    def test_repo_has_no_swallowed_crashes(self, repo_program, repo_raises):
        program, _ = repo_program
        flagged = [
            f for f in swallowed_findings(program, repo_raises)
            if not f.path.endswith("benchmarks/bench_serve.py")  # allowlisted
        ]
        assert flagged == []

    def test_unwind_proof_covers_every_known_point(self, repo_program, repo_raises):
        """HSL018 acceptance: every fault point in faults.KNOWN_POINTS
        has a static propagation path to a recovery construct."""
        from hyperspace_tpu import faults as faults_mod

        program, callgraph = repo_program
        findings, proof = unwind_findings(program, callgraph, repo_raises)
        assert findings == []
        assert set(proof) == set(faults_mod.KNOWN_POINTS)
        for point, entry in proof.items():
            assert entry["covered"], point
            assert entry["sites"], point  # HSL012 guarantees this too
            for site in entry["sites"]:
                assert site["chain"][-1] == site["fn"]

    def test_recovery_roots_include_the_rollback_handler(self, repo_program):
        program, _ = repo_program
        roots = recovery_roots(program)
        assert "hyperspace_tpu.actions.base.Action.run" in roots
        assert any(v == "recover()" for v in roots.values())
        assert any(v == "declared error contract" for v in roots.values())
        # the rollback-handler detection stands on its own (no contracts)
        bare = recovery_roots(program, contracts={})
        assert bare.get("hyperspace_tpu.actions.base.Action.run") == "rollback handler"

    def test_dynamic_raises_table_is_narrow_and_fresh(self, repo_program):
        program, _ = repo_program
        for q, (types, why) in DYNAMIC_RAISES.items():
            assert q in program.functions, f"stale DYNAMIC_RAISES entry: {q}"
            assert types and why

    def test_result_contract_mirrors_worker_surface(self, repo_raises):
        # QueryHandle.result's declared surface comes from the
        # DYNAMIC_RAISES augmentation (raise self.error) + QueryTimeout.
        esc = repo_raises.escapes["hyperspace_tpu.serve.scheduler.QueryHandle.result"]
        assert {"QueryTimeout", "HyperspaceError", "OSError", "CrashPoint"} <= set(esc)

    def test_dead_symbol_report_shape(self, repo_check):
        report, _ = repo_check
        dead = report["dead_symbols"]
        assert dead["count"] == len(dead["functions"])
        assert report["summary"]["dead_symbols"] == dead["count"]
        # informational, under-approximate — but it must not claim the
        # whole program dead, and public entry points are never listed
        assert dead["count"] < report["summary"]["functions"] // 4
        assert not any(q.rsplit(".", 1)[-1] == "run_query" for q in dead["functions"])

    def test_check_wall_time_is_bounded(self, repo_check):
        """The engine's own cost is regression-gated: a full
        analysis.check pass (parse + lint + program + callgraph +
        effects + races + raises + rules + domains) stays under a
        minute."""
        report, elapsed = repo_check
        assert report["summary"]["files"] > 100
        assert elapsed < 60.0, f"analysis.check took {elapsed:.1f}s"


# -- process-domain guarantees (HSL019-022 on the real repo) ------------------

@pytest.fixture(scope="module")
def repo_domains(repo_program, repo_raises):
    program, callgraph = repo_program
    return ProcessDomains(program, callgraph, repo_raises)


@pytest.fixture(scope="module")
def repo_tdomains(repo_program, repo_raises):
    program, callgraph = repo_program
    return TraceDomains(program, callgraph, repo_raises)


@pytest.fixture(scope="module")
def repo_ddomains(repo_program, repo_raises):
    program, callgraph = repo_program
    return DurabilityDomains(program, callgraph, repo_raises)


class TestRepoProcessDomains:
    def test_spawn_domain_is_jax_pure_at_module_level(self, repo_domains):
        """The acceptance proof: every module a spawned worker imports
        at start — build_exchange, procpool, the fleet worker shim, the
        bench fleet mains, and their whole module-level import closure
        (package __init__s included) — is jax-free at module load. The
        runtime mirror (tests/test_procpool.py) asserts the same fact
        inside a real spawned interpreter."""
        assert repo_domains.spawn_import_findings() == []
        for m in (
            "hyperspace_tpu.execution.build_exchange",
            "hyperspace_tpu.parallel.procpool",
            "hyperspace_tpu.parallel",  # the package __init__ that leaked jax
            "hyperspace_tpu.serve.fleet.supervisor",
            "hyperspace_tpu.execution.io",
            "hyperspace_tpu.ops.sortkeys",
            "benchmarks.bench_serve",
        ):
            assert m in repo_domains.domain_modules, m

    def test_registry_entries_are_live_and_kinded(self, repo_domains):
        for q, (kind, why) in SPAWN_ENTRY_POINTS.items():
            assert kind in ("task", "task_body", "service", "service_body"), q
            assert why, q
        assert set(repo_domains.live_entries) == set(SPAWN_ENTRY_POINTS)

    def test_task_closure_covers_the_worker_bodies(self, repo_domains):
        # p2 reads spill through io.read_parquet and sorts through the
        # deferred sortkeys import — the closure must see both.
        fns = repo_domains.task_fns
        assert "hyperspace_tpu.execution.build_exchange.p2_owner" in fns
        assert "hyperspace_tpu.execution.io.read_parquet" in fns
        assert "hyperspace_tpu.execution.build_exchange.host_sort_perm" in fns
        # and it must NOT leak into the device build plane (the
        # write_table fallback misresolution this PR blocklisted).
        assert not any(q.startswith("hyperspace_tpu.ops.bucketize") for q in fns)
        assert not any(q.startswith("hyperspace_tpu.parallel.mesh") for q in fns)

    def test_every_spawn_target_is_declared(self, repo_domains):
        # Both directions of the registry contract (the HSL012 shape):
        # every statically detected spawn target resolves to a declared
        # entry; zero continuity findings on the tree.
        targets = {
            s.target for s in repo_domains.boundary_sites
            if s.kind in ("submit", "spawn", "fleet_target", "mp_process")
            and s.target is not None
        }
        assert "hyperspace_tpu.execution.build_exchange.p1_shard" in targets
        assert "hyperspace_tpu.execution.build_exchange.p2_owner" in targets
        assert "hyperspace_tpu.parallel.procpool._task_entry" in targets
        assert "hyperspace_tpu.serve.fleet.supervisor._worker_entry" in targets
        assert targets <= set(SPAWN_ENTRY_POINTS)
        assert repo_domains.continuity_findings() == []

    def test_exchange_surface_is_clean_and_sites_found(self, repo_domains):
        assert repo_domains.exchange_typing_findings() == []
        kinds = {s.kind for s in repo_domains.boundary_sites}
        # submit (builder), spawn (procpool/supervisor), fleet target
        # (bench), worker put (procpool), task-body returns (p1/p2).
        assert {"submit", "spawn", "fleet_target", "put", "return"} <= kinds

    def test_every_lease_acquire_has_a_reap_proof(self, repo_domains):
        assert repo_domains.shared_file_findings() == []
        acquires = repo_domains.lease_acquires
        assert acquires, "the lease O_EXCL sites must be inventoried"
        for a in acquires:
            assert a["reap_via"], a
        fns = {a["fn"] for a in acquires}
        assert "hyperspace_tpu.serve.fleet.lease.FileLease.try_acquire" in fns
        assert "hyperspace_tpu.utils.file_utils._locked_rename" in fns

    def test_worker_span_vocabulary_is_declared_and_fresh(self, repo_program, repo_domains):
        """KNOWN_WORKER_SPANS covers exactly what the task domain can
        emit — an undeclared name is a finding (checked above); a
        declared name nothing emits is a stale registry entry."""
        import ast as _ast

        from hyperspace_tpu.obs.trace import KNOWN_WORKER_SPANS

        program, _ = repo_program
        emitted = set()
        for q in repo_domains.task_fns:
            fn = program.functions.get(q)
            if fn is None:
                continue
            for node in _ast.walk(fn.node):
                if (
                    isinstance(node, _ast.Call) and node.args
                    and isinstance(node.args[0], _ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    attr = getattr(node.func, "attr", getattr(node.func, "id", ""))
                    if attr in ("span", "trace"):
                        emitted.add(node.args[0].value)
        assert emitted == set(KNOWN_WORKER_SPANS)

    def test_trace_domain_is_pure(self, repo_tdomains):
        """The acceptance proof for the device plane: the dispatch-
        augmented closure of every jit/shard_map/Pallas entry in the
        repo is host-effect-free, signature-bounded, donation-safe, and
        ladder-complete — zero HSL023-026 findings."""
        assert repo_tdomains.findings() == []

    def test_traced_helper_closure_found(self, repo_tdomains):
        # The fused device paths are in the domain with entry-rooted
        # witness chains — the closure is not vacuous.
        fns = repo_tdomains.trace_fns
        for q in (
            "hyperspace_tpu.ops.aggregate._segment_reduce_many",
            "hyperspace_tpu.ops.join._fused_join",
            "hyperspace_tpu.ops.join_agg._fused_join_agg_bounds",
            "hyperspace_tpu.ops.kmeans._lloyd",
            "hyperspace_tpu.plan.expr.evaluate",
        ):
            assert q in fns, q
        # expression evaluation enters through the filter kernels
        chain = fns["hyperspace_tpu.plan.expr.evaluate"]
        assert chain[0].startswith("hyperspace_tpu.ops.filter.")

    def test_every_pallas_ladder_is_proven(self, repo_tdomains):
        """All three Pallas kernels carry the complete fallback ladder:
        eligibility gate, permanent per-shape *bad* set, and both
        device.kernel.* counters, with the engagement chain from the
        public op down to the factory."""
        ladders = {lad["kernel"]: lad for lad in repo_tdomains._kernel_ladders}
        assert set(ladders) == {
            "ops.aggregate.pallas_segment_reduce",
            "ops.sortkeys.pallas_run_bounds",
            "ops.topk.pallas_tile",
        }
        for name, lad in ladders.items():
            assert lad["proven"], name
            assert lad["gate"] and lad["bad_set"], name
            assert set(lad["counters"]) == {
                "device.kernel.fused", "device.kernel.fallbacks",
            }, name
        assert ladders["ops.topk.pallas_tile"]["witness"] == [
            "hyperspace_tpu.ops.topk.topk",
            "hyperspace_tpu.ops.topk._pallas_topk",
            "hyperspace_tpu.ops.topk._make_tile_kernel",
        ]
        assert ladders["ops.aggregate.pallas_segment_reduce"]["witness"][0] == (
            "hyperspace_tpu.ops.aggregate.aggregate_table"
        )

    def test_known_kernels_registry_is_fresh(self, repo_tdomains):
        # Same both-directions contract as faults.KNOWN_POINTS: every
        # engagement declared, every declared entry live.
        assert repo_tdomains.known_kernels == {
            lad["kernel"] for lad in repo_tdomains._kernel_ladders
        }

    def test_donation_proof_is_gated_not_vacuous(self, repo_tdomains):
        """No donation anywhere today (that IS the HSL025 proof the
        ROADMAP's donated-buffer plans will build on), while the staging
        producer and the own_arrays gateway are both found."""
        proof = json.loads(json.dumps(repo_tdomains.to_json()))["donation_proof"]
        assert proof["donation_sites"] == []
        assert proof["proven"] is True
        (producer,) = proof["staged_view_producers"]
        assert producer["fn"] == (
            "hyperspace_tpu.execution.table.ColumnTable.from_arrow"
        )
        assert any(
            g["fn"] == "hyperspace_tpu.execution.io.read_parquet_cached"
            for g in proof["own_arrays_gateways"]
        )

    def test_trace_unresolved_accounting_and_bound(self, repo_tdomains, repo_check):
        """trace_domain.unresolved_ratio is recorded in the summary and
        bounded: traced bodies call mostly jax APIs the grounded
        resolver deliberately rejects (~0.85 today), but a jump past
        the bound means closure edges are silently vanishing."""
        report, _ = repo_check
        s = report["summary"]
        assert s["trace_entry_points"] >= 25
        assert s["trace_domain_functions"] >= 15
        assert s["trace_kernels_proven"] == 3
        assert 0.0 < s["trace_domain_unresolved_ratio"] < 0.9
        assert s["trace_domain_unresolved_ratio"] == repo_tdomains.unresolved_ratio()
        assert repo_tdomains.unresolved_ratio() == round(
            repo_tdomains.trace_calls_unresolved / repo_tdomains.trace_calls_total, 4
        )

    def test_static_domains_cover_the_device_plane(self, repo_program, repo_tdomains):
        from hyperspace_tpu.analysis.tracedomain import _lru_bound

        program, _ = repo_program
        declared = declared_static_domains(program)
        assert declared is not None and {"fns", "num_segments"} <= declared
        # every static argument outside a bounded lru factory (whose
        # memo key already bounds it) comes from the declared registry
        for e in repo_tdomains.entries:
            if e.kind == "pallas_kernel" or not e.static_names:
                continue
            host = program.functions[e.host]
            if _lru_bound(host.node) == "bounded":
                continue
            for n in e.static_names:
                assert n in declared, (e.traced, n)

    def test_durability_domain_is_pure(self, repo_ddomains):
        """The acceptance proof for the durable plane: every declared
        root publishes through the fsync-before-rename idiom, every
        torn window is ordered with an in-window fault point, every
        replay-path file name is deterministic, and no pinned-snapshot
        closure reads the live version vector — zero HSL027-030
        findings, with ANALYSIS_BASELINE.json still empty."""
        assert repo_ddomains.findings() == []

    def test_every_durable_root_carries_sites(self, repo_ddomains):
        """The inference is not vacuous: all 13 declared planes are
        found writing, and every site proves (or delegates to) the
        atomic idiom."""
        from hyperspace_tpu.analysis.duradomain import DURABLE_ROOTS

        assert set(repo_ddomains.roots) == set(DURABLE_ROOTS)
        by_root = {marker: [] for marker in repo_ddomains.roots}
        for s in repo_ddomains.sites:
            by_root[s.root].append(s)
        for marker, sites in by_root.items():
            assert sites, f"durable root {marker!r} has no write sites"
            for s in sites:
                assert s.ok, (marker, s.fn, s.line)
        # the two-phase anchors write through delegation chains into
        # file_utils — the witness machinery is exercised on the tree
        assert any(s.kind == "delegated" and s.chain for s in repo_ddomains.sites)

    def test_every_torn_window_is_proven(self, repo_ddomains):
        """All four exactly-once protocols: statically ordered writes
        AND a declared in-window fault point the crash sweeps kill at
        (tests/test_ingest.py, test_journal.py, test_controller.py
        parametrize over this registry by name)."""
        from hyperspace_tpu.analysis.duradomain import TORN_WINDOWS

        proofs = repo_ddomains._window_proofs
        assert set(proofs) == set(TORN_WINDOWS)
        for name, proof in proofs.items():
            assert proof["live"], name
            assert proof["ordered"], name
            assert proof["point"]["line"] is not None, name
            assert proof["proven"], name
            point = TORN_WINDOWS[name][3]
            assert point in repo_ddomains.known_points, name

    def test_replay_closure_covers_the_recovery_paths(self, repo_ddomains):
        from hyperspace_tpu.analysis.duradomain import REPLAY_ROOTS

        assert set(repo_ddomains.replay_roots) == set(REPLAY_ROOTS)
        for q in REPLAY_ROOTS:
            assert q in repo_ddomains.replay_fns, q
        # the CDC re-poll path actually reaches its batch writer
        assert (
            "hyperspace_tpu.ingest.tailer.CdcTailer._write_batch"
            in repo_ddomains.replay_fns
        )

    def test_durable_unresolved_accounting_and_bound(self, repo_ddomains, repo_check):
        """durable_domain.unresolved_ratio is recorded in the summary
        and bounded — a jump past the bound means delegation proofs and
        the replay closure are silently losing edges."""
        report, _ = repo_check
        s = report["summary"]
        assert s["durable_roots"] == len(repo_ddomains.roots)
        assert s["durable_write_sites"] == len(repo_ddomains.sites) > 0
        assert s["durable_domain_functions"] >= 100
        assert s["torn_windows"] == 4
        assert s["torn_windows_proven"] == 4
        assert s["replay_roots"] == 3
        assert s["replay_closure_functions"] > 100
        assert 0.0 < s["durable_domain_unresolved_ratio"] < 0.9
        assert s["durable_domain_unresolved_ratio"] == repo_ddomains.unresolved_ratio()
        assert repo_ddomains.unresolved_ratio() == round(
            repo_ddomains.dura_calls_unresolved / repo_ddomains.dura_calls_total, 4
        )
        # the report section the CI job reads lists every root, every
        # window with its in-window point witness, every replay path
        dura = report["durable_domains"]
        assert set(dura["roots"]) == set(repo_ddomains.roots)
        assert all(w["proven"] for w in dura["windows"].values())
        assert set(dura["replay"]) == set(repo_ddomains.replay_roots)

    def test_every_torn_window_has_a_crash_sweep_home(self):
        """The dynamic sweeps (test_ingest / test_journal /
        test_controller) parametrize over TORN_WINDOWS filtered by
        these prefixes and KeyError on an unknown name — so a window
        whose name starts with a NEW prefix would silently escape every
        sweep. This pin makes that a loud failure instead."""
        from hyperspace_tpu.analysis.duradomain import TORN_WINDOWS

        swept = ("ingest.", "journal.", "controller.")
        for name in TORN_WINDOWS:
            assert name.startswith(swept), (
                f"torn window {name!r} matches no crash-sweep prefix "
                f"{swept}; add a driver before registering it"
            )

    def test_module_level_imports_skip_deferred_and_type_checking(self):
        src = (
            "import os\n"
            "try:\n"
            "    import fast_json\n"
            "except ImportError:\n"
            "    import json as fast_json\n"
            "from typing import TYPE_CHECKING\n"
            "if TYPE_CHECKING:\n"
            "    import jax\n"
            "def f():\n"
            "    import jax.numpy as jnp\n"
            "    return jnp\n"
        )
        mod = _index_module("m", "m.py", src, ast.parse(src))
        targets = {t for t, _ in module_level_imports(mod)}
        assert "os" in targets and "fast_json" in targets and "json" in targets
        assert not any(t.startswith("jax") for t in targets)

    def test_declared_entry_points_extraction(self):
        src = (
            'SPAWN_ENTRY_POINTS = {\n'
            '    "m.body": ("task_body", "why"),\n'
            '    "m.shim": "service",\n'
            '}\n'
        )
        program = Program({"m": _index_module("m", "m.py", src, ast.parse(src))})
        got = declared_entry_points(program)
        assert got == {"m.body": ("task_body", "why"), "m.shim": ("service", "")}


# -- check CLI ----------------------------------------------------------------

def _validate_sarif_required(sarif: dict) -> None:
    """Assert the SARIF 2.1.0 REQUIRED-property set: sarifLog needs
    `version` + `runs`; each run needs `tool.driver.name`; each
    reportingDescriptor needs `id`; each result needs `message` (with
    text) and — per the artifactLocation/region constraints the spec
    puts on physicalLocation — a uri and a 1-based startLine. Every
    result.ruleId must resolve against the driver's rules."""
    assert sarif["version"] == "2.1.0"
    assert isinstance(sarif["runs"], list) and sarif["runs"]
    for run in sarif["runs"]:
        driver = run["tool"]["driver"]
        assert isinstance(driver["name"], str) and driver["name"]
        rule_ids = set()
        for rule in driver.get("rules", []):
            assert isinstance(rule["id"], str) and rule["id"]
            assert rule["shortDescription"]["text"]
            rule_ids.add(rule["id"])
        assert len(rule_ids) == len(driver.get("rules", []))  # ids unique
        assert isinstance(run["results"], list)
        for res in run["results"]:
            assert res["ruleId"] in rule_ids
            assert isinstance(res["message"]["text"], str) and res["message"]["text"]
            assert res.get("level") in ("none", "note", "warning", "error")
            assert res.get("baselineState", "new") in (
                "new", "unchanged", "updated", "absent",
            )
            for loc in res["locations"]:
                phys = loc["physicalLocation"]
                assert isinstance(phys["artifactLocation"]["uri"], str)
                assert phys["region"]["startLine"] >= 1


class TestCheckCli:
    def test_exit_clean_on_repo(self):
        proc = subprocess.run(
            [sys.executable, "-m", "hyperspace_tpu.analysis.check"],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )
        assert proc.returncode == EXIT_CLEAN, proc.stdout + proc.stderr
        assert "cycle-free=True" in proc.stderr

    def test_exit_findings_without_baseline(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        assert check_main([str(bad), "--no-baseline"]) == EXIT_FINDINGS

    def test_exit_internal_error(self, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        monkeypatch.setattr(
            check_mod, "run_check",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        assert check_mod.main(["--no-baseline"]) == EXIT_INTERNAL_ERROR

    def test_baseline_masks_old_findings_only(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        baseline = tmp_path / "baseline.json"
        # 1. write the baseline: current findings become "known"
        assert check_main([str(bad), "--baseline", str(baseline),
                           "--write-baseline"]) == EXIT_CLEAN
        assert json.loads(baseline.read_text())["findings"]
        # 2. same findings, baseline present -> clean
        assert check_main([str(bad), "--baseline", str(baseline)]) == EXIT_CLEAN
        # 3. a NEW finding fails even with the baseline
        bad.write_text("from jax import shard_map\nimport numpy as np\nv = np.random.rand(3)\n")
        assert check_main([str(bad), "--baseline", str(baseline)]) == EXIT_FINDINGS

    def test_json_report_shape(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        out = tmp_path / "report.json"
        rc = check_main([str(bad), "--no-baseline", "--format", "json",
                         "--output", str(out)])
        assert rc == EXIT_FINDINGS
        report = json.loads(out.read_text())
        assert report["summary"]["new_findings"] == 1
        (finding,) = report["findings"]
        assert finding["rule"] == "HSL001"
        assert finding["slug"] == "fragile-jax-import"
        assert finding["new"] is True
        assert report["validator_corpus"]["status"] in ("ok", "skipped")
        assert "lock_graph" in report

    def test_docs_table_in_sync(self):
        # docs/configuration.md's key table is generated from
        # config.KNOWN_KEYS; this is the no-drift assertion.
        from hyperspace_tpu.analysis.check import docs_findings

        assert docs_findings(REPO_ROOT) == []

    def test_sarif_exit_codes_match_json(self, tmp_path):
        # the SARIF renderer changes the artifact, never the gate:
        # 0 = clean, 1 = new findings, 2 = internal error — same as json.
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        assert check_main([str(clean), "--no-baseline", "--format", "sarif"]) == EXIT_CLEAN
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        out = tmp_path / "report.sarif"
        rc = check_main([str(bad), "--no-baseline", "--format", "sarif",
                         "--output", str(out)])
        assert rc == EXIT_FINDINGS
        sarif = json.loads(out.read_text())
        assert sarif["version"] == "2.1.0"
        run = sarif["runs"][0]
        assert run["tool"]["driver"]["name"] == "hyperspace-analysis"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"HSL013", "HSL014", "HSL015"} <= rule_ids
        (result,) = run["results"]
        assert result["ruleId"] == "HSL001"
        assert result["baselineState"] == "new"
        assert result["locations"][0]["physicalLocation"]["region"]["startLine"] == 1

    def test_sarif_required_properties_across_all_rules(self, tmp_path):
        """Validate the SARIF 2.1.0 required-property set (runs/results/
        rules shape) over the full rule corpus — old and new rules alike
        — instead of spot-checking one finding."""
        out = tmp_path / "corpus.sarif"
        rc = check_main([str(FIXTURES / "rules"), "--no-baseline",
                         "--format", "sarif", "--output", str(out)])
        assert rc == EXIT_FINDINGS
        sarif = json.loads(out.read_text())
        _validate_sarif_required(sarif)
        fired = {r["ruleId"] for r in sarif["runs"][0]["results"]}
        # old rules, the exception-flow rules, the process-domain rules,
        # and the trace-domain rules all appear
        assert {"HSL001", "HSL011", "HSL013", "HSL016", "HSL017", "HSL018",
                "HSL019", "HSL020", "HSL021", "HSL022",
                "HSL023", "HSL024", "HSL025", "HSL026"} <= fired

    def test_sarif_required_properties_on_clean_run(self, tmp_path):
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        out = tmp_path / "clean.sarif"
        assert check_main([str(clean), "--no-baseline", "--format", "sarif",
                           "--output", str(out)]) == EXIT_CLEAN
        sarif = json.loads(out.read_text())
        _validate_sarif_required(sarif)
        assert sarif["runs"][0]["results"] == []

    def test_sarif_internal_error_exit(self, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        monkeypatch.setattr(
            check_mod, "run_check",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        assert check_mod.main(["--no-baseline", "--format", "sarif"]) == EXIT_INTERNAL_ERROR

    def test_sarif_baseline_state_unchanged(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        baseline = tmp_path / "baseline.json"
        assert check_main([str(bad), "--baseline", str(baseline),
                           "--write-baseline"]) == EXIT_CLEAN
        out = tmp_path / "report.sarif"
        rc = check_main([str(bad), "--baseline", str(baseline),
                         "--format", "sarif", "--output", str(out)])
        assert rc == EXIT_CLEAN  # known finding: gate passes...
        (result,) = json.loads(out.read_text())["runs"][0]["results"]
        assert result["baselineState"] == "unchanged"  # ...but SARIF keeps it

    def test_changed_mode_restricts_reporting(self, tmp_path, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        other = tmp_path / "other.py"
        other.write_text("import numpy as np\nv = np.random.rand(3)\n")
        # only other.py "changed": bad.py's finding must be masked
        monkeypatch.setattr(
            check_mod, "changed_files", lambda root: ("origin/main", {"other.py"})
        )
        monkeypatch.setattr(check_mod, "_repo_root", lambda: tmp_path)
        out = tmp_path / "report.json"
        rc = check_mod.main([str(bad), str(other), "--no-baseline", "--changed",
                             "--format", "json", "--output", str(out)])
        assert rc == EXIT_FINDINGS
        report = json.loads(out.read_text())
        assert report["changed"] == {"base": "origin/main", "files": ["other.py"]}
        assert [f["rule"] for f in report["findings"]] == ["HSL005"]
        # nothing changed -> clean exit even with the bad file on disk
        monkeypatch.setattr(check_mod, "changed_files", lambda root: ("origin/main", set()))
        assert check_mod.main([str(bad), "--no-baseline", "--changed"]) == EXIT_CLEAN

    def test_changed_mode_keeps_findings_whose_witness_changed(self, tmp_path, monkeypatch):
        """The --changed blind-spot fix: a finding whose PRIMARY file is
        unchanged but whose witness chain crosses a changed file must
        still be reported — editing host.py (the spawn-domain module)
        is what creates the HSL019 finding reported at impure.py."""
        import hyperspace_tpu.analysis.check as check_mod

        host = tmp_path / "host.py"
        host.write_text(
            'SPAWN_ENTRY_POINTS = {"host.body": ("task_body", "x")}\n'
            "import impure\n"
            "def body():\n"
            "    return impure.K\n"
        )
        impure = tmp_path / "impure.py"
        impure.write_text("import jax\nK = 1\n")
        monkeypatch.setattr(check_mod, "_repo_root", lambda: tmp_path)
        # only host.py "changed": the HSL019 finding (primary: impure.py)
        # must survive through its witness chain
        monkeypatch.setattr(
            check_mod, "changed_files", lambda root: ("origin/main", {"host.py"})
        )
        out = tmp_path / "report.json"
        rc = check_mod.main([str(host), str(impure), "--no-baseline", "--changed",
                             "--format", "json", "--output", str(out)])
        assert rc == EXIT_FINDINGS
        report = json.loads(out.read_text())
        assert [f["rule"] for f in report["findings"]] == ["HSL019"]
        assert report["findings"][0]["path"].endswith("impure.py")
        # an unrelated change set still drops it
        monkeypatch.setattr(
            check_mod, "changed_files", lambda root: ("origin/main", {"elsewhere.py"})
        )
        assert check_mod.main([str(host), str(impure), "--no-baseline",
                               "--changed"]) == EXIT_CLEAN

    def test_changed_mode_falls_back_without_git(self, tmp_path, monkeypatch):
        import hyperspace_tpu.analysis.check as check_mod

        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        monkeypatch.setattr(check_mod, "changed_files", lambda root: None)
        # git unavailable: full run, the finding still fails the gate
        assert check_mod.main([str(bad), "--no-baseline", "--changed"]) == EXIT_FINDINGS

    def test_changed_files_parses_git(self):
        # against the real repo: returns a base ref and a set of paths
        got = check_mod_changed_files(REPO_ROOT)
        if got is None:
            pytest.skip("git unavailable in this environment")
        base, files = got
        assert base in ("origin/main", "main", "HEAD")
        assert all(isinstance(p, str) for p in files)
