"""ROLLUP / CUBE / GROUPING SETS: two-phase re-aggregation (one finest
aggregate + partial re-folds per set), checked against pandas groupby
unions. Covers mean recomposition from sum+count partials, grouping()
flags, null group values vs subtotal nulls, and count semantics."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import HyperspaceSession, col
from hyperspace_tpu.plan.nodes import plan_from_json


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("gsdata")
    rng = np.random.default_rng(5)
    n = 4_000
    null_v = rng.random(n) < 0.1
    df = pd.DataFrame(
        {
            "state": np.array(["CA", "NY", "TX", "WA"], dtype=object)[rng.integers(0, 4, n)],
            "cat": np.array(["food", "toys", "tools"], dtype=object)[rng.integers(0, 3, n)],
            "q": pd.array(np.where(null_v, 0, rng.integers(1, 30, n)), dtype="Int64"),
            "amt": np.round(rng.normal(size=n) * 50 + 100, 2),
        }
    )
    df.loc[null_v, "q"] = pd.NA
    root = tmp_path / "t"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    ds = session.parquet(root)
    return session, ds, df


def rollup_oracle(df, levels, aggfn):
    parts = []
    for i in range(len(levels), 0, -1):
        keys = levels[:i]
        g = aggfn(df.groupby(keys)).reset_index()
        for c in levels[i:]:
            g[c] = None
        parts.append(g)
    total = aggfn(df.groupby(lambda _: 0)).reset_index(drop=True)
    for c in levels:
        total[c] = None
    parts.append(total)
    return pd.concat(parts, ignore_index=True)


def norm(frame, cols):
    rows = [
        tuple(None if pd.isna(v) else (round(v, 6) if isinstance(v, float) else v) for v in row)
        for row in frame[cols].itertuples(index=False)
    ]
    return sorted(rows, key=lambda r: tuple((v is None, str(v)) for v in r))


def test_rollup_matches_pandas(data):
    session, ds, df = data
    q = ds.rollup(
        ["state", "cat"],
        [("sum", "amt", "s"), ("count", None, "n"), ("mean", "q", "mq")],
    )
    got = session.to_pandas(q)
    exp = rollup_oracle(
        df,
        ["state", "cat"],
        lambda g: g.agg(s=("amt", "sum"), n=("amt", "size"), mq=("q", "mean")),
    )
    assert len(got) == len(exp)
    assert norm(got, ["state", "cat", "s", "n", "mq"]) == norm(
        exp, ["state", "cat", "s", "n", "mq"]
    )


def test_grouping_flags_and_min_max(data):
    session, ds, df = data
    q = ds.rollup(
        ["state", "cat"],
        [
            ("min", "amt", "lo"),
            ("max", "amt", "hi"),
            ("grouping", "cat", "g_cat"),
            ("grouping", "state", "g_state"),
        ],
    )
    got = session.to_pandas(q)
    # Finest rows: both flags 0; mid (cat rolled away): g_cat=1 g_state=0;
    # grand total: both 1.
    finest = got[(got.g_cat == 0) & (got.g_state == 0)]
    mid = got[(got.g_cat == 1) & (got.g_state == 0)]
    top = got[(got.g_cat == 1) & (got.g_state == 1)]
    assert len(finest) == df.groupby(["state", "cat"]).ngroups
    assert len(mid) == df.state.nunique()
    assert len(top) == 1
    assert np.isclose(top.lo.iloc[0], df.amt.min()) and np.isclose(top.hi.iloc[0], df.amt.max())
    m = mid.set_index("state")
    exp = df.groupby("state").amt.agg(["min", "max"])
    np.testing.assert_allclose(m.lo[exp.index].to_numpy(), exp["min"].to_numpy())
    np.testing.assert_allclose(m.hi[exp.index].to_numpy(), exp["max"].to_numpy())


def test_cube_set_count(data):
    session, ds, df = data
    q = ds.cube(["state", "cat"], [("count", None, "n")])
    got = session.to_pandas(q)
    expected_rows = (
        df.groupby(["state", "cat"]).ngroups + df.state.nunique() + df.cat.nunique() + 1
    )
    assert len(got) == expected_rows
    assert got.n.sum() == 4 * len(df)  # every row counted once per subset level


def test_explicit_grouping_sets_and_json(data):
    session, ds, df = data
    q = ds.aggregate(
        ["state", "cat"],
        [("sum", "amt", "s")],
        grouping_sets=[["state"], ["cat"]],
    )
    d = q.to_json()
    assert plan_from_json(d).to_json() == d
    got = session.to_pandas(q)
    assert len(got) == df.state.nunique() + df.cat.nunique()
    by_state = got[got.cat.isna()].set_index("state").s
    exp = df.groupby("state").amt.sum()
    np.testing.assert_allclose(by_state[exp.index].to_numpy(), exp.to_numpy(), rtol=1e-9)


def test_rollup_over_filter_and_validation(data):
    session, ds, df = data
    q = ds.filter(col("state") == "CA").rollup(["cat"], [("sum", "q", "sq")])
    got = session.to_pandas(q)
    dfx = df[df.state == "CA"]
    exp_total = dfx.q.sum()
    total_row = got[got.cat.isna()]
    assert len(total_row) == 1
    assert int(total_row.sq.iloc[0]) == int(exp_total)
    with pytest.raises(ValueError):
        ds.aggregate(["state"], [("sum", "amt", "s")], grouping_sets=[["cat"]])
    with pytest.raises(ValueError):
        ds.aggregate(["state"], [("grouping", "state", "g")])  # no sets
    # count_distinct under rollup executes (dedicated tests below).
    session.run(ds.rollup(["state"], [("count_distinct", "cat", "cd")]))


def test_rollup_count_distinct(data):
    session, ds, df = data
    q = ds.rollup(
        ["state"],
        [
            ("count_distinct", "cat", "dcat"),
            ("count_distinct", "q", "dq"),
            ("sum", "amt", "s"),
            ("grouping", "state", "g"),
        ],
    )
    got = session.to_pandas(q)

    def agg(g):
        return g.agg(
            dcat=("cat", "nunique"),
            dq=("q", "nunique"),
            s=("amt", "sum"),
        )

    exp = rollup_oracle(df, ["state"], agg)
    exp["g"] = [0] * (len(exp) - 1) + [1]
    assert norm(got, ["state", "dcat", "dq", "s", "g"]) == norm(
        exp, ["state", "dcat", "dq", "s", "g"]
    )
    assert "GroupingSetsDistinct" in repr(session.last_physical_plan)


def test_grouping_sets_count_distinct_with_null_group(data):
    session, ds, df = data
    # An explicit set list incl. the empty set; distinct counts at every
    # grain computed over the same child materialization.
    q = ds.aggregate(
        ["state", "cat"],
        [("count_distinct", "q", "dq"), ("count", None, "n")],
        grouping_sets=[["state", "cat"], ["cat"], []],
    )
    got = session.to_pandas(q)
    p1 = df.groupby(["state", "cat"]).agg(dq=("q", "nunique"), n=("q", "size")).reset_index()
    p2 = df.groupby(["cat"]).agg(dq=("q", "nunique"), n=("q", "size")).reset_index()
    p2["state"] = None
    p3 = pd.DataFrame(
        {"state": [None], "cat": [None], "dq": [df.q.nunique()], "n": [len(df)]}
    )
    exp = pd.concat([p1, p2, p3], ignore_index=True)
    assert norm(got, ["state", "cat", "dq", "n"]) == norm(exp, ["state", "cat", "dq", "n"])
