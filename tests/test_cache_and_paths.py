"""TTL cache + path resolver + name/config utilities."""

import pytest

from hyperspace_tpu.config import HyperspaceConf, INDEX_NUM_BUCKETS
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.metadata.cache import CreationTimeBasedCache
from hyperspace_tpu.metadata.path_resolver import PathResolver
from hyperspace_tpu.utils.name_utils import normalize_index_name


def test_cache_ttl(monkeypatch):
    import time as time_mod

    # The TTL clock is monotonic (clock-step hazard: an NTP step must
    # not expire fresh entries or immortalize stale ones).
    t = [1000.0]
    monkeypatch.setattr(time_mod, "monotonic", lambda: t[0])
    c = CreationTimeBasedCache(expiry_seconds=10)
    assert c.get() is None
    c.set([1, 2, 3])
    assert c.get() == [1, 2, 3]
    t[0] += 11
    assert c.get() is None  # expired
    c.set([4])
    assert c.get() == [4]
    c.clear()
    assert c.get() is None


def test_path_resolver_case_insensitive(tmp_path):
    conf = HyperspaceConf(system_path=str(tmp_path))
    r = PathResolver(conf)
    (tmp_path / "MyIndex").mkdir()
    assert r.get_index_path("myindex") == tmp_path / "MyIndex"
    assert r.get_index_path("MYINDEX") == tmp_path / "MyIndex"
    # Unknown names resolve to normalized child path.
    assert r.get_index_path("new idx") == tmp_path / "new_idx"
    assert r.list_index_paths() == [tmp_path / "MyIndex"]


def test_normalize_index_name():
    assert normalize_index_name("  my  index \t name ") == "my_index_name"


def test_conf_overrides():
    conf = HyperspaceConf(system_path="/x")
    conf.set(INDEX_NUM_BUCKETS, 16)
    assert conf.num_buckets == 16
    assert conf.get(INDEX_NUM_BUCKETS) == 16


def test_index_config_validation():
    with pytest.raises(HyperspaceError):
        IndexConfig("", ["a"])
    with pytest.raises(HyperspaceError):
        IndexConfig("i", [])
    with pytest.raises(HyperspaceError):
        IndexConfig("i", ["a", "A"])
    with pytest.raises(HyperspaceError):
        IndexConfig("i", ["a"], ["A"])
    cfg = IndexConfig.builder().index_name("i").indexed_columns("a").included_columns("b").create()
    assert cfg == IndexConfig("I", ["A"], ["B"])  # case-insensitive equality
    assert cfg.all_columns == ["a", "b"]


def test_index_config_builder_double_set():
    b = IndexConfig.builder().index_name("i")
    with pytest.raises(HyperspaceError):
        b.index_name("j")
