"""Fixture module: declared error contracts, one kept and one drifting."""

from raisedemo.faults import fault_point

ERROR_CONTRACTS = {
    "raisedemo.api.persist": ("PipelineError",),
    "raisedemo.api.drifting_persist": ("PipelineError",),
}


class PipelineError(Exception):
    """The fixture's typed surface."""


class EmptyStoreError(PipelineError):
    """Subclass: covered by a PipelineError contract entry."""


def persist(store):
    """Clean: everything that escapes is within the declared contract
    (EmptyStoreError is a PipelineError), and the fault point it
    threads is covered by this very contract entry (HSL018)."""
    fault_point("demo.persist")
    if not store:
        raise EmptyStoreError("nothing to persist")
    try:
        store.flush()
    except (ValueError, KeyError) as e:
        # raise-from transformation: the caught types are subtracted,
        # PipelineError is what escapes.
        raise PipelineError("flush failed") from e


def drifting_persist(store):
    # DELIBERATE HSL016: KeyError escapes but the declared contract
    # only covers PipelineError.
    if store is None:
        raise KeyError("no store bound")
    raise PipelineError("unreachable demo tail")
