"""Fixture mini-package for the exception-flow analysis tests.

NOT imported at runtime — the engine only parses it. Contains, on
purpose, exactly three planted findings (the HSL016–HSL018 seeded
regressions):

- ``api.drifting_persist`` lets a ``KeyError`` escape that its declared
  ``ERROR_CONTRACTS`` entry (``PipelineError`` only) does not cover —
  the HSL016 error-contract drift, reported with the raise-site witness
  chain.
- ``worker.drain`` swallows EVERYTHING with a bare ``except:`` and no
  re-raise — the HSL017 swallowed-crash shape.
- ``orphan.scrub`` threads the declared fault point ``demo.orphan``
  but is reachable from no recovery construct (no contract entry, no
  ``recover()``, no rollback handler) — the HSL018 unwind-safety hole.

Everything else is the clean counterpart of each pattern: a contract
entry whose escape set matches exactly, handlers that re-raise or
record before absorbing, and a fault point (``demo.persist``) proven
covered through the declared contract entry. The golden raise-summary
JSON lives in ../goldens/raisedemo_raises.json.
"""
