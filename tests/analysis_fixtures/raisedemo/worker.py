"""Fixture module: one swallow-everything handler next to the clean
store-or-reraise counterparts."""


def drain(queue):
    # DELIBERATE HSL017: a bare except with no re-raise absorbs
    # CrashPoint and KeyboardInterrupt along with everything else.
    try:
        queue.flush()
    except:
        return None


def careful_drain(queue, log):
    # Clean: broad catch, but the exception is re-raised after the log.
    try:
        queue.flush()
    except BaseException as e:
        log(e)
        raise


def recorded_drain(queue, log):
    # Clean: Exception-level catch that records instead of passing.
    try:
        queue.flush()
    except Exception as e:
        log(e)
