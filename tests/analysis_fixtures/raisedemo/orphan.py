"""Fixture module: a fault point threaded outside every recovery path."""

from raisedemo.faults import fault_point


def scrub(path):
    # DELIBERATE HSL018: `demo.orphan` is declared in KNOWN_POINTS and
    # threaded here, but no contract entry point, recover(), or rollback
    # handler reaches scrub() — an injected crash unwinds into nothing.
    fault_point("demo.orphan", path)
    path.unlink()
