"""Fixture fault-point registry (the shape analysis/raises.py extracts:
a top-level KNOWN_POINTS tuple plus a fault_point() entry point)."""

KNOWN_POINTS = (
    "demo.persist",
    "demo.orphan",
)


def fault_point(name, path=None):
    """Inert stand-in for hyperspace_tpu.faults.fault_point."""
