"""HSL023 traced-effect purity: host effects reachable through the
trace-domain closure. The effects live in helpers the jitted entry
points call — lexically outside any jit, so the per-file HSL002 check
cannot see them; only the whole-program closure walk does."""

import time

import jax.numpy as jnp

from hyperspace_tpu import stats
from hyperspace_tpu.compat import jit


def _tally(x):
    stats.increment("device.kernel.fused")  # expect: HSL023
    return jnp.sum(x)


def _stamp(x):
    t = time.time()  # expect: HSL023
    return x * t


def _scale(x):
    # Clean traced helper: pure array math only.
    return x * 2.0


@jit
def bad_norm(x):
    return _tally(x) / x.size


@jit
def bad_stamped(x):
    return _stamp(x)


@jit
def good_norm(x):
    return _scale(x) / x.size
