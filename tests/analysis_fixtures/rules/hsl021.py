"""HSL021 shared-file protocol corpus.

The module hosts a spawn task body, so it is domain-gated: writes under
shared exchange/lease paths must publish atomically, and every O_EXCL
lease claim must reach a TTL reaper. One bare write and one reap-less
claim are planted next to their clean counterparts.
"""

import os
import tempfile

SPAWN_ENTRY_POINTS = {
    "hsl021.publish_entry": ("task_body", "corpus task body"),
}


def publish_entry(exchange_dir, doc):
    path = exchange_dir + "/entry.json"
    with open(path, "w") as f:  # expect: HSL021
        f.write(doc)
    return path


def publish_atomic(exchange_dir, doc):
    # Clean counterpart: tmp + fsync + os.replace — a reader in another
    # process sees a whole entry or no entry.
    fd, tmp = tempfile.mkstemp(dir=exchange_dir)
    with os.fdopen(fd, "w") as f:
        f.write(doc)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, exchange_dir + "/entry.json")


def acquire_no_reap(lease_path):
    fd = os.open(lease_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)  # expect: HSL021
    os.close(fd)
    return True


class Lease:
    """Clean counterpart: the FileExistsError path reaps by TTL."""

    def __init__(self, path, ttl_s):
        self.path = path
        self.ttl_s = ttl_s

    def acquire(self, now_s):
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            self._reap(now_s)
            return None
        os.close(fd)
        return "token"

    def _reap(self, now_s):
        age_s = now_s - 0.0
        if age_s <= self.ttl_s:
            return False
        os.unlink(self.path)
        return True
