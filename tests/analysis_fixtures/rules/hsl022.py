"""HSL022 cross-boundary continuity corpus.

Two task carriers: the good one installs the shipped fault state and
the module merges observations back (join); the bad one spawns workers
that silently lose injected faults. The module declares its own
KNOWN_WORKER_SPANS / KNOWN_COUNTERS registries, so an undeclared worker
span name flags too.
"""

SPAWN_ENTRY_POINTS = {
    "hsl022.good_entry": ("task", "corpus carrier with full continuity"),
    "hsl022.bad_entry": ("task", "corpus carrier missing the fault plumbing"),
}

KNOWN_WORKER_SPANS = ("work.step",)
KNOWN_COUNTERS = ("work.items",)


def install_state(state):
    pass


def merge_observed(points):
    pass


def adopt_root(root):
    pass


def span(name):
    pass


def increment(name):
    pass


def good_entry(fn, env):
    install_state(env)
    with span("work.step"):
        increment("work.items")
        return fn()


def bad_entry(fn, env):  # expect: HSL022
    with span("work.stepz"):  # expect: HSL022
        return fn()


def join_side(results):
    merge_observed(())
    adopt_root(None)
    return results
