"""HSL027 atomic-publish completeness corpus.

The file declares its own ``DURABLE_ROOTS`` plane (the engine
AST-extracts the literal, so the rule arms without the real registry):
every write whose call text names the ``ledger`` root owes the
fsync-before-replace idiom — directly, or through a delegation chain
that proves it. One fsync-less publish is planted next to the proven
counterpart and a delegated-clean site.
"""

import os
import tempfile

DURABLE_ROOTS = {
    "ledger": "the corpus ledger plane (atomic JSON)",
}


def publish_fast(state_dir, doc):
    tmp = state_dir + "/.partial"
    with open(tmp, "w") as f:
        f.write(doc)
    os.replace(tmp, state_dir + "/ledger.json")  # expect: HSL027


def publish_atomic(state_dir, doc):
    # Clean counterpart: payload fsync strictly before the rename — a
    # crash can surface the old ledger or the new one, never a torn one.
    fd, tmp = tempfile.mkstemp(dir=state_dir)
    with os.fdopen(fd, "w") as f:
        f.write(doc)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, state_dir + "/ledger.json")


def save(state_dir, doc):
    # Delegated clean site: the chain down to publish_atomic proves the
    # idiom, so the caller owes nothing at this line.
    publish_atomic(state_dir + "/ledger", doc)
