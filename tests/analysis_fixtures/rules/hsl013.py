"""HSL013 lockset-race corpus: shared state under inconsistent locksets.

(The cross-class form with a two-path witness lives in the racedemo
fixture package; this file is the minimal per-state forms.)
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._label = "idle"

    def bump(self):
        with self._lock:
            self._count += 1

    def read(self):
        with self._lock:
            return self._count

    def clear_unsafe(self):
        self._count = 0  # expect: HSL013

    def relabel(self):
        with self._lock:
            self._label = "busy"

    def read_label_consistent(self):
        with self._lock:
            return self._label


class EventLike:
    """No lock anywhere — no locking discipline exists to violate, so
    the guarded-by inference stays silent (cross-thread safety here is
    somebody else's argument, e.g. an Event or a happens-before)."""

    def __init__(self):
        self.flag = False

    def set_flag(self):
        self.flag = True

    def get_flag(self):
        return self.flag


_g_lock = threading.Lock()
_g_version = 0


def g_bump(delta):
    global _g_version
    with _g_lock:
        _g_version += delta


def g_read():
    with _g_lock:
        return _g_version


def g_reset_unsafe():
    global _g_version
    _g_version = 0  # expect: HSL013


def g_reset_sanctioned():
    global _g_version
    _g_version = -1  # noqa: HSL013 — test-only reset before threads start
