"""HSL025 donation/aliasing safety: mutating a zero-copy staged view
without the own_arrays gateway, donating a staged view, and touching a
buffer after donating it — each next to its clean counterpart."""

import functools

import numpy as np

from hyperspace_tpu.compat import jit


def stage_column(buf):
    arr = np.frombuffer(buf, dtype=np.int64)
    arr.flags.writeable = False
    return arr


class ColumnTable:
    def __init__(self, columns):
        self.columns = columns

    @classmethod
    def from_arrow(cls, table, zero_copy_ok=False):
        cols = {}
        for name, buf in table.items():
            arr = stage_column(buf)
            cols[name] = arr
        return cls(cols)

    def own_arrays(self):
        self.columns = {n: np.array(a) for n, a in self.columns.items()}
        return self


@functools.partial(jit, donate_argnums=(0,))
def scrub(x):
    return x * 0


def mutate_aliased(table):
    t = ColumnTable.from_arrow(table, zero_copy_ok=True)
    t.columns["a"][0] = -1  # expect: HSL025
    return t


def mutate_owned(table):
    t = ColumnTable.from_arrow(table, zero_copy_ok=True)
    t.own_arrays()
    t.columns["a"][0] = -1
    return t


def donate_staged(buf):
    col = stage_column(buf)
    return scrub(col)  # expect: HSL025


def reuse_after_donate(buf):
    x = np.ascontiguousarray(buf)
    y = scrub(x)  # expect: HSL025
    return y, x


def donate_fresh(buf):
    x = np.ascontiguousarray(buf)
    return scrub(x)
