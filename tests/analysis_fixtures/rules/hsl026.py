"""HSL026 kernel fallback-ladder completeness: a complete (clean)
ladder, a ladder with no permanent per-shape fallback, an undeclared
engagement with an empty ladder, a stale registry entry, and a counter
missing from KNOWN_COUNTERS."""

import functools
import threading

import jax.numpy as jnp

from hyperspace_tpu import stats
from hyperspace_tpu.compat import jit, resolve_pallas

KNOWN_KERNELS = (  # expect: HSL026
    "corpus.reduce",
    "corpus.rowmax",
    "corpus.ghost",
)
# "device.kernel.fallbacks" is deliberately missing: both fallback
# increments below are flagged against this registry.
KNOWN_COUNTERS = ("device.kernel.fused",)

_TILE = 128
_MAX_LANES = 1024

_bad_shapes: set = set()
_bad_lock = threading.Lock()


@functools.lru_cache(maxsize=8)
def _make_reduce(n):
    pl = resolve_pallas()

    def kernel(x_ref, o_ref):
        o_ref[...] = jnp.sum(x_ref[...], axis=1)

    def run(x):
        return pl.pallas_call(kernel, grid=(n // _TILE,))(x)

    return jit(run, key="corpus.reduce")


def reduce_rows(x):
    n = x.shape[1]
    if n <= _MAX_LANES:
        try:
            run = _make_reduce(n)
            out = run(x)
            stats.increment("device.kernel.fused")
            return out
        except Exception:
            with _bad_lock:
                if (n,) not in _bad_shapes:
                    _bad_shapes.add((n,))
            stats.increment("device.kernel.fallbacks")  # expect: HSL026
    return jnp.sum(x, axis=1)


@functools.lru_cache(maxsize=8)
def _make_rowmax(n):
    pl = resolve_pallas()

    def kernel(x_ref, o_ref):
        o_ref[...] = jnp.max(x_ref[...], axis=1)

    def run(x):
        # Ladder has a gate and both counters but no *bad* set: a
        # lowering failure re-engages Pallas on the same shape forever.
        return pl.pallas_call(kernel, grid=(n // _TILE,))(x)  # expect: HSL026

    return jit(run, key="corpus.rowmax")


def rowmax(x):
    n = x.shape[1]
    if n <= _MAX_LANES:
        try:
            run = _make_rowmax(n)
            out = run(x)
            stats.increment("device.kernel.fused")
            return out
        except Exception:
            stats.increment("device.kernel.fallbacks")  # expect: HSL026
    return jnp.max(x, axis=1)


@functools.lru_cache(maxsize=4)
def _make_stray(n):
    pl = resolve_pallas()

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2

    def run(x):
        # Undeclared engagement AND an empty ladder: both findings
        # land on this pallas_call line.
        return pl.pallas_call(kernel, grid=(1,))(x)  # expect: HSL026

    return jit(run, key="corpus.stray")
