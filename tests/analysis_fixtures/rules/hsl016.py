"""HSL016 error-contract drift corpus."""

ERROR_CONTRACTS = {
    "hsl016.declared_ok": ("AppError",),
    "hsl016.drifting": ("AppError",),
    "hsl016.transforms": ("AppError",),
    "hsl016.ghost_entry": ("AppError",),  # expect: HSL016
    "hsl016.dead_type": ("AppError", "UnusedError"),  # expect: HSL016
}


class AppError(Exception):
    pass


class DetailError(AppError):
    pass


class UnusedError(AppError):
    pass


def declared_ok():
    # Subclass escape covered modulo hierarchy: DetailError ⊆ AppError.
    raise DetailError("fine")


def drifting(flag):  # expect: HSL016
    if flag:
        raise AppError("the declared half")
    raise ValueError("not in the contract")


def transforms(op):
    # raise-from transformation: ValueError/KeyError are subtracted by
    # the handler, AppError is what escapes — within the contract.
    try:
        op()
    except (ValueError, KeyError) as e:
        raise AppError("wrapped") from e


def dead_type():
    # UnusedError is declared above but covers no observed escape.
    raise AppError("only the base ever escapes")


def shielded(op):
    # Handler subtraction: nothing escapes, no contract needed.
    try:
        op()
    except Exception as e:
        return e
    return None
