"""HSL017 swallowed crash/fault corpus."""


class CrashPoint(BaseException):
    pass


class FaultError(OSError):
    pass


def bare_swallow(op):
    try:
        op()
    except:  # expect: HSL017
        return None


def crash_handled(op):
    try:
        op()
    except BaseException:  # expect: HSL017
        return None


def crash_reraised_is_fine(op, log):
    try:
        op()
    except BaseException as e:
        log(e)
        raise


def crash_noqa_is_suppressed(op):
    try:
        op()
    except BaseException:  # noqa: HSL017 — isolation harness by design
        return None


def fault_swallowed(op):
    try:
        op()
    except FaultError:  # expect: HSL017
        return -1


def except_pass(op):
    try:
        op()
    except Exception:  # expect: HSL017
        pass


def except_recorded_is_fine(op, log):
    try:
        op()
    except Exception as e:
        log(e)


def retry_bypass(op):
    for _attempt in range(3):
        try:
            return op()
        except OSError:  # expect: HSL017
            continue
    return None


def retry_classified_is_fine(op, is_retryable):
    for _attempt in range(3):
        try:
            return op()
        except OSError as e:
            if not is_retryable(e):
                raise
            continue
    return None


def skip_loop_is_fine(paths):
    # A for-each over work items skips a bad one — not a retry.
    out = []
    for p in paths:
        try:
            out.append(p.read_text())
        except OSError:
            continue
    return out
