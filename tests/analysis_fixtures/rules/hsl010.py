"""HSL010 config-key-drift corpus: get/set of undeclared keys."""


def typo_set(conf):
    conf.set("hyperspace.srve.workers", 2)  # expect: HSL010


def typo_get(conf):
    return conf.get("hyperspace.obs.enabld")  # expect: HSL010


def declared_keys_are_fine(conf):
    conf.set("hyperspace.serve.workers", 2)
    return conf.get("hyperspace.obs.enabled")


def non_hyperspace_namespace_is_fine(conf):
    return conf.get("myapp.custom.knob")
