"""HSL005 unseeded-randomness corpus."""

import random

import numpy as np

v = np.random.rand(3)  # expect: HSL005
r = np.random.default_rng()  # expect: HSL005
s = random.random()  # expect: HSL005

seeded = np.random.default_rng(0)
