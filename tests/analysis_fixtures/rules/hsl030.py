"""HSL030 snapshot-stamp discipline corpus.

A ``snapshot`` parameter marks the pinned context: the carrier and its
unguarded call closure must never read the live version vector. The
planted read hides one hop below the carrier — only the closure walk
sees it. The clean counterparts show both sanctioned shapes: a
conditional dispatching on the snapshot parameter (both branches
deliberate) and the default-fill idiom (the live read only fills an
ABSENT argument).
"""


def _live_floor(session):
    return session.get_latest_id()  # expect: HSL030


def plan_key(session, snapshot):
    return _live_floor(session)


def plan_key_pinned(session, snapshot):
    # Clean: dispatching on the snapshot parameter IS the sanctioned
    # pinned-vs-live split.
    if snapshot is not None:
        return snapshot.stamp
    else:
        return session.latest_log_id


def decide(session, snapshot, stamp=None):
    # Clean: default-fill — a pinned caller passes the snapshot-derived
    # stamp; the live read only runs when the argument is absent.
    stamp = _live_floor(session) if stamp is None else stamp
    return stamp
