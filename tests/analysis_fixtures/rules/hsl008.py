"""HSL008 unlocked-global-mutation corpus."""

import threading

_cache = {}
_seen = set()
_lock = threading.Lock()


def put_bad(key, value):
    _cache[key] = value  # expect: HSL008


def record_bad(x):
    _seen.add(x)  # expect: HSL008


def evict_bad(key, other):
    _cache.pop(key)  # expect: HSL008
    del _cache[other]  # expect: HSL008


def put_under_lock_is_fine(key, value):
    with _lock:
        _cache[key] = value


_cache["import-time-init"] = object()


def read_only_is_fine(key):
    return _cache.get(key)


def local_container_is_fine(items):
    out = []
    for i in items:
        out.append(i)
    return out
