"""HSL009 lock-order-inversion corpus: a direct two-lock inversion.

(The cross-module, call-graph-mediated form lives in the lockdemo
fixture package; this file is the minimal lexical form.)
"""

import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()
_lock_c = threading.Lock()


def a_then_b():
    with _lock_a:  # expect: HSL009
        with _lock_b:
            pass


def b_then_a():
    with _lock_b:
        with _lock_a:
            pass


def consistent_order_is_fine():
    with _lock_a:
        with _lock_c:
            pass
