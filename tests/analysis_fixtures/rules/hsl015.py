"""HSL015 jit-cache-hygiene corpus: call sites that manufacture a fresh
jit cache key per call (recompile storm / executable leak)."""

import functools
import threading

import jax


def per_call_lambda(columns, factor):
    out = []
    for arr in columns:
        fn = jax.jit(lambda x: x * factor)  # expect: HSL015
        out.append(fn(arr))
    return out


def per_call_partial(arr, factor):
    fn = jax.jit(functools.partial(_scale, factor))  # expect: HSL015
    return fn(arr)


def per_call_closure(arr, factor):
    def scale(x):
        return x * factor

    return jax.jit(scale)(arr)  # expect: HSL015


def _scale(factor, x):
    return factor * x


@functools.lru_cache(maxsize=32)
def cached_factory(factor):
    def scale(x):
        return x * factor

    return jax.jit(scale)  # clean: the factory is memoized


_FN_CACHE: dict = {}
_FN_LOCK = threading.Lock()


def memo_filled(offset):
    with _FN_LOCK:
        fn = _FN_CACHE.get(offset)
    if fn is None:
        fn = jax.jit(functools.partial(_scale, offset))  # clean: memo below
        with _FN_LOCK:
            _FN_CACHE[offset] = fn
    return fn


@jax.jit
def _kernel(x, mode):
    return x


def fstring_static(x, name):
    return _kernel(x, f"mode-{name}")  # expect: HSL015


def stable_static(x):
    return _kernel(x, "mode-fixed")
