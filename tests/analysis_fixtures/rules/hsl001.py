"""HSL001 fragile-jax-import corpus (flagged and clean forms)."""

from jax import shard_map  # expect: HSL001
from jax import enable_x64  # expect: HSL001
from jax.experimental import pallas  # expect: HSL001
from jax.experimental.shard_map import shard_map as sm  # expect: HSL001
import jax.experimental.pallas  # expect: HSL001

from jax import lax
import jax.numpy as jnp
from hyperspace_tpu.compat import shard_map as compat_shard_map
