"""HSL003 traced-control-flow corpus."""

import functools

import jax


@jax.jit
def value_branch(x):
    if x > 0:  # expect: HSL003
        return x
    return -x


@jax.jit
def value_loop(x):
    while x < 10:  # expect: HSL003
        x = x + 1
    return x


@jax.jit
def shape_branch_is_static(x):
    if x.shape[0] > 1:
        return x
    return -x


@functools.partial(jax.jit, static_argnames=("n",))
def static_param_is_fine(x, n):
    if n > 3:
        return x
    return -x
