"""HSL020 exchange-surface typing corpus.

A mini TaskPool (boundary methods are recognized by class+method name,
same as the real parallel/procpool.py) plus a ColumnTable stand-in: a
list of paths crosses the submit boundary legally; a ColumnTable
instance — typed through the same local-binding inference the call
graph uses for receivers — is a planted violation.
"""

SPAWN_ENTRY_POINTS = {
    "hsl020.task_entry": ("task_body", "corpus task body"),
}


class ColumnTable:
    def __init__(self):
        self.columns = {}


class TaskPool:
    def submit(self, task_id, fn, *args):
        pass

    def join(self):
        return {}


def task_entry(paths):
    return {"n": len(paths)}


def coordinator(files):
    pool = TaskPool()
    pool.submit(0, task_entry, [str(f) for f in files])  # clean: paths cross
    table = ColumnTable()
    pool.submit(1, task_entry, table)  # expect: HSL020
    return pool.join()
