"""HSL006 metadata-write-bypass corpus."""

import json


def write_manifest_bad(dest_dir, manifest, MANIFEST_NAME):
    (dest_dir / MANIFEST_NAME).write_text(json.dumps(manifest))  # expect: HSL006


def write_pointer_bad(log_dir, LATEST_STABLE_LOG_NAME, data):
    (log_dir / LATEST_STABLE_LOG_NAME).write_bytes(data)  # expect: HSL006


def write_version_dir_bad(root, payload):
    (root / "v__=0" / "part").write_text(payload)  # expect: HSL006


def unrelated_write_is_fine(report_path, text):
    report_path.write_text(text)


def read_mode_is_fine(log_dir, entry_id):
    return open(log_dir / str(entry_id)).read()
