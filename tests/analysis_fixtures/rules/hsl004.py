"""HSL004 unhashable-static corpus."""

import functools

import jax


def f(x, n):
    return x


g = jax.jit(f, static_argnums=[1])  # expect: HSL004


@functools.partial(jax.jit, static_argnames=("cap",))
def tuple_spelling_is_fine(x, cap):
    return x
