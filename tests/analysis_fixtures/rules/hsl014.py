"""HSL014 atomicity corpus: torn check-then-act across lock regions."""

import threading


class Budget:
    def __init__(self):
        self._lock = threading.Lock()
        self._remaining = 10
        self._cache = {}

    def spend_torn(self, cost):
        with self._lock:
            left = self._remaining
        if left >= cost:
            with self._lock:
                self._remaining = left - cost  # expect: HSL014
        return left

    def spend_atomic(self, cost):
        with self._lock:
            left = self._remaining
            if left >= cost:
                self._remaining = left - cost
            return left

    def memo_fill_is_fine(self, key):
        # Keyed read then keyed insert: duplicate idempotent work at
        # worst — the sanctioned cache idiom, not a torn update.
        with self._lock:
            value = self._cache.get(key)
        if value is None:
            value = _expensive(key)
            with self._lock:
                self._cache[key] = value
        return value

    def recheck_is_fine(self, cost):
        # Double-checked: the second region revalidates before writing.
        with self._lock:
            left = self._remaining
        if left >= cost:
            with self._lock:
                if self._remaining >= cost:
                    self._remaining = self._remaining - cost

    def torn_through_helper(self, cost):
        with self._lock:
            left = self._remaining
        if left >= cost:
            self._apply(left - cost)  # expect: HSL014
        return left

    def _apply(self, value):
        with self._lock:
            self._remaining = value


def _expensive(key):
    return key
