"""HSL029 replay-idempotence corpus.

``repoll`` is a declared replay root: every durable file name written
in its call-graph closure must derive from cursor/seq/generation
values, so a re-poll after a crash rewrites the SAME path.
``_write_wallclock`` names its batch from ``time.time()`` — a replay
would write a different path and orphan the first file.
"""

import os
import tempfile
import time

DURABLE_ROOTS = {
    "batches": "seq-named batch files the tailer republishes on re-poll",
}

REPLAY_ROOTS = {
    "hsl029.repoll": "re-poll after a crash must rewrite the same batch",
}


def _publish(path, doc):
    # The atomic idiom — both writers below delegate here, so HSL027
    # stays quiet and only the naming discipline is under test.
    fd, tmp = tempfile.mkstemp()
    with os.fdopen(fd, "w") as f:
        f.write(doc)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _write_wallclock(state_dir, rows):
    name = state_dir + "/batches/" + str(time.time())
    _publish(name, repr(rows))  # expect: HSL029


def _write_seq(state_dir, rows, seq):
    # Clean counterpart: the name derives from the cursor seq — the
    # replay rewrites the same file.
    name = state_dir + "/batches/" + str(seq)
    _publish(name, repr(rows))


def repoll(state_dir, rows, seq):
    _write_wallclock(state_dir, rows)
    _write_seq(state_dir, rows, seq)
