"""HSL019 spawn-import purity corpus.

The file declares its own SPAWN_ENTRY_POINTS (the registry is
AST-extracted per scanned module, like ERROR_CONTRACTS), making this
module a spawn-domain host: its module-level imports run in every
spawned worker before the task body does. `import jax` at module level
flags; the deferred function-level import is a runtime edge and stays
legal (the idiom the heavy modules use).
"""

SPAWN_ENTRY_POINTS = {
    "hsl019.worker_body": ("task_body", "corpus task body"),
}

import jax  # expect: HSL019
import numpy as np  # clean: numpy is part of the worker vocabulary


def worker_body(path):
    return {"path": str(path), "n": int(np.int64(3).item())}


def coordinator_only(xs):
    # Deferred import: executes at CALL time in whichever process runs
    # this (the coordinator) — not at worker module load. Legal.
    import jax.numpy as jnp

    return jnp.asarray(xs)
