"""HSL011 resource/exception-safety corpus."""

import threading

_lock = threading.Lock()


def acquire_bad():
    _lock.acquire()  # expect: HSL011
    do_work()
    _lock.release()


def acquire_with_finally_is_fine():
    _lock.acquire()
    try:
        do_work()
    finally:
        _lock.release()


def acquire_timeout_bad(sem):
    # Signature-form recognition: the receiver is not named "lock", but
    # .acquire(timeout=) is the threading API and the success branch
    # must conditionally release.
    if sem.acquire(timeout=2.0):  # expect: HSL011
        do_work()
        sem.release()


def acquire_timeout_with_finally_is_fine(sem):
    got = sem.acquire(timeout=2.0)
    try:
        do_work()
    finally:
        if got:
            sem.release()


def open_bad(path):
    f = open(path)  # expect: HSL011
    return f.read()


def open_with_is_fine(path):
    with open(path) as f:
        return f.read()


def fdopen_bad(os, fd):
    f = os.fdopen(fd, "wb")  # expect: HSL011
    f.write(b"x")


def fdopen_with_is_fine(os, fd):
    with os.fdopen(fd, "wb") as f:
        f.write(b"x")


def tempfile_bad(tempfile):
    t = tempfile.NamedTemporaryFile()  # expect: HSL011
    t.write(b"x")


def tempfile_closed_is_fine(tempfile):
    t = tempfile.NamedTemporaryFile()
    try:
        t.write(b"x")
    finally:
        t.close()


def span_bad(obs_trace):
    obs_trace.span("query.step")  # expect: HSL011
    do_work()


def span_entered_is_fine(obs_trace):
    with obs_trace.span("query.step"):
        do_work()


def do_work():
    pass
