"""HSL011 resource/exception-safety corpus."""

import threading

_lock = threading.Lock()


def acquire_bad():
    _lock.acquire()  # expect: HSL011
    do_work()
    _lock.release()


def acquire_with_finally_is_fine():
    _lock.acquire()
    try:
        do_work()
    finally:
        _lock.release()


def open_bad(path):
    f = open(path)  # expect: HSL011
    return f.read()


def open_with_is_fine(path):
    with open(path) as f:
        return f.read()


def span_bad(obs_trace):
    obs_trace.span("query.step")  # expect: HSL011
    do_work()


def span_entered_is_fine(obs_trace):
    with obs_trace.span("query.step"):
        do_work()


def do_work():
    pass
