"""HSL028 torn-window ordering corpus.

``TORN_WINDOWS`` declares two exactly-once protocols over this file's
own functions (the engine AST-extracts the literal, and the file's
``KNOWN_POINTS`` tuple stands in for the real fault registry).
``commit`` arms the in-window fault point strictly between the two
writes — proven. ``commit_unarmed`` orders the writes but arms its
point only AFTER the second write, so the crash sweep can never kill
inside the torn state — the window is unproven.
"""

from hyperspace_tpu import faults

KNOWN_POINTS = ("ingest.tail", "ingest.stamp")

TORN_WINDOWS = {
    "corpus.batch_before_cursor": (
        "hsl028.commit", "write_batch", "save_cursor", "ingest.tail",
        "the batch must land before the cursor advances"),
    "corpus.commit_before_stamp": (
        "hsl028.commit_unarmed", "write_batch", "save_cursor", "ingest.stamp",
        "the commit must land before the bookkeeping stamp"),
}


def write_batch(rows):
    return list(rows)


def save_cursor(seq):
    return seq


def commit(rows, seq):
    write_batch(rows)
    faults.fault_point("ingest.tail")
    return save_cursor(seq)


def commit_unarmed(rows, seq):  # expect: HSL028
    write_batch(rows)
    save_cursor(seq)
    faults.fault_point("ingest.stamp")


def recover(rows, seq):
    # The unwind root (HSL018): both committers are reachable from a
    # recovery construct, so the corpus stays single-rule.
    commit(rows, seq)
    commit_unarmed(rows, seq)
