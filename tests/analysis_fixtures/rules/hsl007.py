"""HSL007 wallclock-duration / undeclared-counter corpus."""

import time

from hyperspace_tpu import stats


def age_bad(stamp):
    return time.time() - stamp  # expect: HSL007


def count_bad():
    stats.increment("retyr.attempts")  # expect: HSL007


def count_ok():
    stats.increment("retry.attempts")


def age_ok(start):
    return time.monotonic() - start
