"""HSL024 signature-space boundedness: every leg of the rule — a
non-literal jit key, an unbounded jit factory, an undeclared static
argument, a stale registry entry, and an unrounded pad width — next to
its clean counterpart."""

import functools

import jax.numpy as jnp

from hyperspace_tpu.compat import jit

KNOWN_STATIC_DOMAINS = {  # expect: HSL024
    "m_pad": "tile-rounded pad target",
    "knob": "stale: no jit site uses it and no function takes it",
}


def _next_mult(n, m):
    return ((n + m - 1) // m) * m


@functools.partial(jit, static_argnames=("m_pad",))
def pad_to(x, m_pad):
    # Clean: "m_pad" is a declared bounded domain.
    return jnp.pad(x, (0, m_pad - x.shape[0]))


@functools.partial(jit, static_argnames=("order",))
def poly(x, order):  # expect: HSL024
    return x ** order


@functools.lru_cache(maxsize=8)
def make_scaler(c):
    def run(x):
        return x * c

    # Non-literal key: every c mints a key the storm detector cannot
    # group.
    return jit(run, key=f"scale.{c}")  # expect: HSL024


@functools.lru_cache(maxsize=None)
def make_shifter(s):
    def run(x):
        return x + s

    # The factory cache itself is unbounded, so the set of live jit
    # callables is too.
    return jit(run, key="corpus.shift")  # expect: HSL024


@functools.lru_cache(maxsize=16)
def make_clean(c):
    def run(x):
        return x - c

    return jit(run, key="corpus.clean")


def pad_raw(x):
    n = x.shape[0]
    return jnp.pad(x, (0, 2 * n))  # expect: HSL024


def pad_rounded(x):
    n = x.shape[0]
    m = _next_mult(n, 8)
    return jnp.pad(x, (0, m - n))
