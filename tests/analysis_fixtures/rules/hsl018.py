"""HSL018 unwind-safety corpus.

Uses REAL registry point names (bucket.write / footer.read) so the
HSL012 name check stays quiet when this file is scanned alone — the
KNOWN_POINTS tuple below is what the HSL018 proof extracts.
"""

KNOWN_POINTS = (
    "bucket.write",
    "footer.read",
)

ERROR_CONTRACTS = {
    "hsl018.public_entry": ("RuntimeError",),
}


def fault_point(name, path=None):
    pass


def public_entry():
    _persist()


def _persist():
    # Covered: public_entry is a declared contract entry and reaches us.
    fault_point("bucket.write")
    raise RuntimeError("boom")


def _orphan_helper():
    fault_point("footer.read")  # expect: HSL018
    return 0


def balanced_gauge(self_like, op):
    pass


class Gaugey:
    def __init__(self):
        self._inflight = 0
        self._lock = None

    def risky_unbalanced(self, op):
        self._inflight += 1  # expect: HSL018
        op()
        self._inflight -= 1

    def risky_balanced(self, op):
        self._inflight += 1
        try:
            op()
        finally:
            self._inflight -= 1
