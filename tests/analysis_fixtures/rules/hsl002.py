"""HSL002 host-sync-in-jit corpus."""

import jax
import numpy as np


@jax.jit
def item_sync(x):
    return x.item()  # expect: HSL002


def wrapped(x):
    return float(x)  # expect: HSL002


g = jax.jit(wrapped)


@jax.jit
def asarray_sync(x):
    return np.asarray(x)  # expect: HSL002


def host_side_is_fine(x):
    return float(x.item())
