"""HSL012 fault-point-coverage corpus: call sites naming undeclared points."""

from hyperspace_tpu.faults import fault_point


def write_log_entry_bad(path):
    fault_point("log.wriet", path)  # expect: HSL012


def write_log_entry_ok(path):
    fault_point("log.write", path)
