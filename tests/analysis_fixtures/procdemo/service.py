"""Fleet worker-main analog: a service body boots the heavy engine at
RUN time behind deferred imports — the clean counterpart of the HSL019
pattern (module-load purity holds; the runtime jax use is the worker's
whole job)."""


def worker_main(ctx):
    from procdemo import devkit  # deferred: a runtime edge, legal

    return devkit.device_sum([1, 2, 3])
