"""Task bodies: jax-free at module load — except the planted leak
through `devkit`, which imports jax at module level and is imported
HERE at module level (the chain HSL019 reports)."""

import numpy as np

from procdemo import devkit
from procdemo.pool import span


def shard_body(files, exchange_dir):
    with span("demo.shard"):
        out = {}
        for i, f in enumerate(files):
            out[str(i)] = _spill(str(f), exchange_dir)
        return {"spills": out, "n": int(np.int64(len(files)))}


def _spill(name, exchange_dir):
    path = exchange_dir + "/spill-" + name
    _publish_atomic(path, "data")
    return path


def _publish_atomic(path, data):
    # Clean counterpart (HSL021): tmp + fsync + os.replace.
    import os
    import tempfile

    fd, tmp = tempfile.mkstemp()
    with os.fdopen(fd, "w") as h:
        h.write(data)
        h.flush()
        os.fsync(h.fileno())
    os.replace(tmp, path)


def bad_manifest(exchange_dir, doc):
    with open(exchange_dir + "/manifest.json", "w") as h:  # planted HSL021
        h.write(doc)


def sum_on_device(xs):
    # Coordinator-side helper; the devkit use keeps the module-level
    # import live (the leak is the IMPORT, not this call).
    return devkit.device_sum(xs)
