"""Device kit: the module a worker must never pay at load. Imported at
module level by `workers` (a spawn-domain host), so the jax import
below is the planted HSL019 violation — the finding lands HERE, with
the workers → devkit chain and the seeding entry point as witness."""

import jax  # planted HSL019


def device_sum(xs):
    return jax.numpy.sum(jax.numpy.asarray(xs))
