"""Coordinator: submits task bodies into the pool. One submit ships
paths (legal); one ships a ColumnTable instance (planted HSL020)."""

from procdemo.pool import TaskPool
from procdemo.workers import shard_body


class ColumnTable:
    def __init__(self):
        self.columns = {}


def run_build(files, exchange_dir):
    with TaskPool() as pool:
        pool.submit(0, shard_body, [str(f) for f in files], str(exchange_dir))
        table = ColumnTable()
        pool.submit(1, shard_body, table)  # planted HSL020
        return pool.join()
