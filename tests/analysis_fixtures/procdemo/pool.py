"""Mini spawn plumbing: carriers, registry, and the worker-span
vocabulary (the procpool + obs.trace analog)."""

SPAWN_ENTRY_POINTS = {
    "procdemo.pool.task_entry": ("task", "carrier with full continuity"),
    "procdemo.pool.bare_entry": ("task", "carrier missing the fault plumbing"),
    "procdemo.workers.shard_body": ("task_body", "p1-shard analog"),
    "procdemo.service.worker_main": ("service_body", "fleet worker-main analog"),
}

KNOWN_WORKER_SPANS = ("demo.shard",)


def install_state(state):
    pass


def merge_observed(points):
    pass


def adopt_root(root):
    pass


class _Noop:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def span(name):
    return _Noop()


class TaskPool:
    def __init__(self):
        self._pending = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def submit(self, task_id, fn, *args):
        self._pending[task_id] = (fn, args)

    def join(self):
        merge_observed(())
        adopt_root(None)
        return {}


def task_entry(q, fn, args, env):
    install_state(env.get("faults"))
    q.put((0, fn(*args)))


def bare_entry(q, fn, args, env):  # planted HSL022: faults never ship in
    q.put((0, fn(*args)))
