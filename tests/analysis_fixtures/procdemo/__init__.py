"""Process-domain fixture package (HSL019-022).

A miniature of the real multi-process installation: `pool` is the
procpool analog (carriers + registry), `workers` the jax-free task
bodies, `devkit` the device module a worker must never pay at load,
`coord` the coordinator submitting across the boundary, and `service`
the fleet-worker-main analog whose engine hides behind deferred
imports. One planted violation per rule, each next to the clean
counterpart of its pattern; the golden domain-graph JSON pins the
inferred closure (tests/test_analysis_engine.py).
"""
