"""Fixture module: a lock-disciplined store with one deliberate
unguarded write (HSL013) and one torn check-then-act (HSL014)."""

import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._version = 0

    def put(self, key, value):
        with self._lock:
            self._entries[key] = value
            self._version += 1

    def get(self, key):
        with self._lock:
            return self._entries.get(key)

    def size(self):
        with self._lock:
            return len(self._entries)

    def reset_unsafe(self):
        # DELIBERATE HSL013: every other _version access holds _lock;
        # this write races the guarded increment in put().
        self._version = 0

    def bump_torn(self):
        # DELIBERATE HSL014: the value read under the lock is written
        # back under a RE-ACQUIRED lock — a concurrent put() between the
        # two critical sections is lost.
        with self._lock:
            v = self._version
        with self._lock:
            self._version = v + 1

    def bump_atomic(self):
        with self._lock:
            self._version = self._version + 1
