"""Fixture module: one per-call jit site (HSL015) next to the two
sanctioned bounded patterns (lru_cache factory, explicit memo)."""

import functools
import threading

import jax


def scale_columns(columns, factor):
    out = []
    for arr in columns:
        # DELIBERATE HSL015: a fresh lambda per iteration means a fresh
        # jit cache key per iteration — compile + executable leak each
        # time around the loop.
        fn = jax.jit(lambda x: x * factor)
        out.append(fn(arr))
    return out


@functools.lru_cache(maxsize=8)
def make_scaler(factor):
    def scale(x):
        return x * factor

    return jax.jit(scale)  # clean: the factory is memoized


_FN_CACHE: dict = {}
_FN_LOCK = threading.Lock()


def offset_kernel(offset):
    with _FN_LOCK:
        fn = _FN_CACHE.get(offset)
    if fn is None:
        fn = jax.jit(functools.partial(_shift, offset))  # clean: memo below
        with _FN_LOCK:
            _FN_CACHE[offset] = fn
    return fn


def _shift(offset, x):
    return x + offset
