"""Fixture mini-package for the effects/race analysis tests.

NOT imported at runtime — the engine only parses it. Contains, on
purpose, exactly three planted findings (the HSL013–HSL015 seeded
regressions):

- ``store.Store.reset_unsafe`` writes ``_version`` without the lock
  every other access holds — the HSL013 lockset race, reported with a
  two-path witness naming the guarded and unguarded access.
- ``store.Store.bump_torn`` reads ``_version`` under the lock, releases
  it, and writes the stale value back under a re-acquired lock — the
  HSL014 torn check-then-act.
- ``kernels.scale_columns`` jits a fresh lambda per loop iteration —
  the HSL015 recompile-storm / executable-leak pattern.

Everything else in the package is the clean counterpart of each pattern
(consistent locksets, atomic check-then-act, memoized jit factories).
The golden effect-summary JSON lives in ../goldens/.
"""
