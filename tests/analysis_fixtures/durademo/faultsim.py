"""Parse-only stand-in for the fault harness: the engine matches the
``fault_point`` call tail and AST-extracts the ``KNOWN_POINTS`` tuple —
the fixture is never imported, so no real machinery is needed."""

KNOWN_POINTS = (
    "durademo.tail",
    "durademo.stamp",
)


def fault_point(name, path=None):
    return name
