"""Pinned-snapshot carriers (HSL030): the planted live read hides one
hop below the carrier, and both sanctioned shapes — the
snapshot-dispatch conditional and the default-fill idiom — stay
clean."""


def _live_floor(session):
    # Planted HSL030 target: reached unguarded from Planner.resolve.
    return session.get_latest_id()


class Planner:
    def resolve(self, session, snapshot):
        return _live_floor(session)

    def plan_key(self, session, snapshot):
        # Clean: dispatching on the snapshot parameter IS the
        # sanctioned pinned-vs-live split.
        if snapshot is not None:
            return snapshot.stamp
        else:
            return session.latest_log_id

    def decide(self, session, snapshot, stamp=None):
        # Clean: default-fill — a pinned caller passes the
        # snapshot-derived stamp; the live read only fills an absence.
        stamp = _live_floor(session) if stamp is None else stamp
        return stamp
