"""durademo: durability-domain fixture package (duradomain.py, HSL027-030).

A miniature durable plane exercising every shape the durability-domain
inference handles — registry extraction (the package declares its own
``DURABLE_ROOTS``/``TORN_WINDOWS``/``REPLAY_ROOTS``/``KNOWN_POINTS``
literals), direct and delegated write sites with witness chains,
``self.<attr>`` path widening, torn-window proofs with in-window fault
points, the replay closure, and the pinned-snapshot carrier walk —
with exactly four planted violations, one per rule:

- HSL027: ``store.publish_fast`` renames the ledger into place with no
  fsync before the publish; ``publish_atomic``/``save_ledger`` are the
  proven direct and delegated counterparts.
- HSL028: ``tailer.Tailer.commit`` orders its two writes but arms the
  ``durademo.stamp`` point only AFTER the window, so the crash sweep
  can never kill inside the torn state; ``Tailer.poll`` is the proven
  window (point strictly between batch publish and cursor save).
- HSL029: ``tailer.Tailer._write_batch`` names its batch file from
  ``time.time()`` on the declared ``poll`` replay path; ``_save_cursor``
  writes a replay-stable name.
- HSL030: ``control._live_floor`` reads the live version vector one
  hop below the pinned carrier ``Planner.resolve``; ``plan_key`` (the
  snapshot-dispatch split) and ``decide`` (default-fill) are clean.

Like every analysis fixture, this package is parsed by the engine and
never imported — ``faultsim.py`` stands in for the fault harness.
"""
