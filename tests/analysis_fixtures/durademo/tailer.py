"""The replayed tail loop: the two declared torn windows and the
replay root. ``poll`` is the proven window (ordered writes, in-window
point); ``commit`` is the planted HSL028 (point armed after the
window); ``_write_batch`` is the planted HSL029 (wall-clock batch name
on the replay path)."""

import time

from durademo import faultsim
from durademo.store import publish_json

TORN_WINDOWS = {
    "durademo.batch_before_cursor": (
        "durademo.tailer.Tailer.poll",
        "_write_batch", "_save_cursor", "durademo.tail",
        "the batch must land before the cursor advances; the re-poll "
        "rewrites the same seq-named file"),
    "durademo.commit_before_stamp": (
        "durademo.tailer.Tailer.commit",
        "_append_log", "_stamp", "durademo.stamp",
        "the commit must land before the bookkeeping stamp"),
}

REPLAY_ROOTS = {
    "durademo.tailer.Tailer.poll":
        "re-poll after a crash must rewrite the same batch paths",
}


class Tailer:
    def __init__(self, state_dir):
        self.state_dir = state_dir
        self.seq = 0

    def poll(self, rows):
        self._write_batch(rows)
        faultsim.fault_point("durademo.tail")
        self._save_cursor()

    def _write_batch(self, rows):
        # Planted HSL029: the batch name derives from the wall clock —
        # a re-poll writes a DIFFERENT path and orphans this one.
        name = self.state_dir + "/batches/" + str(time.time())
        publish_json(name, repr(rows))

    def _save_cursor(self):
        # Clean counterpart: a fixed, replay-stable name.
        publish_json(self.state_dir + "/cursor.json", str(self.seq))

    def commit(self, rows):
        # Planted HSL028: the point arms only AFTER the stamp — the
        # sweep can never kill inside the window.
        self._append_log(rows)
        self._stamp()
        faultsim.fault_point("durademo.stamp")

    def _append_log(self, rows):
        return len(rows)

    def _stamp(self):
        self.seq = self.seq + 1
