"""The durable planes and the sanctioned publish idiom.

``DURABLE_ROOTS`` is the fixture's registry — the engine AST-extracts
the literal from any scanned module, the same way procdemo declares its
own ``SPAWN_ENTRY_POINTS``."""

import os
import tempfile

DURABLE_ROOTS = {
    "ledger": "the demo ledger (atomic JSON, the 2-phase anchor)",
    "batches": "seq-named delta batches the tailer republishes",
    "cursor": "the tail cursor the batches commit ahead of",
}


def publish_json(path, doc):
    """The proven idiom: payload fsync strictly before the rename."""
    fd, tmp = tempfile.mkstemp()
    with os.fdopen(fd, "w") as f:
        f.write(doc)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def save_ledger(state_dir, doc):
    # Delegated clean site: the chain down to publish_json proves it.
    publish_json(state_dir + "/ledger.json", doc)


def publish_fast(state_dir, doc):
    # Planted HSL027: rename with no fsync — the new name can be
    # durable before its bytes are.
    tmp = state_dir + "/.partial"
    with open(tmp, "w") as f:
        f.write(doc)
    os.replace(tmp, state_dir + "/ledger.json")
