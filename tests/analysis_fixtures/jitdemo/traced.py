"""Traced entry points: decorator-form jit (bare and partial), a
shard_map body inside a bounded factory, and the two signature/purity
plants — ``leaky_norm`` reaches a counter bump through its closure
(HSL023) and ``poly`` declares an undeclared static domain (HSL024)."""

import functools

import jax.numpy as jnp

from jitdemo.shims import Mesh, jit, shard_map, stats


@functools.partial(jit, static_argnames=("reps",))
def scale(x, reps):
    # "reps" is a declared bounded domain (shims.KNOWN_STATIC_DOMAINS).
    for _ in range(reps):
        x = x * 1.1
    return x


@functools.partial(jit, static_argnames=("order",))
def poly(x, order):
    # Planted HSL024: "order" is not a declared static domain — every
    # new order value mints a fresh compile signature.
    out = x
    for _ in range(order):
        out = out * x
    return out


def _total(x):
    # Planted HSL023: a host effect two hops inside the trace domain
    # (leaky_norm -> _total). The fix is `engage` below.
    stats.increment("device.kernel.fused")
    return jnp.sum(x)


@jit
def leaky_norm(x):
    return _total(x) / x.size


@jit
def norm(x):
    return x / jnp.sum(x)


def engage(x):
    # Clean counterpart: the effect lives at the engagement site, on
    # the host side of the trace boundary.
    out = norm(x)
    stats.increment("device.kernel.fused")
    return out


@functools.lru_cache(maxsize=4)
def make_exchange(axis):
    mesh = Mesh(("x",))

    @functools.partial(shard_map, mesh=mesh)
    def fn(block):
        return block - jnp.mean(block)

    return jit(fn, key="jitdemo.exchange")
