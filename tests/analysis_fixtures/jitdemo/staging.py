"""Zero-copy staging mini-plane: writeable=False staged views, the
``own_arrays`` ownership gateway, and one planted in-place mutation of
an aliased view (HSL025)."""

import numpy as np


def stage_column(buf):
    arr = np.frombuffer(buf, dtype=np.int64)
    arr.flags.writeable = False
    return arr


class ColumnTable:
    def __init__(self, columns):
        self.columns = columns

    @classmethod
    def from_arrow(cls, table, zero_copy_ok=False):
        cols = {}
        for name, buf in table.items():
            arr = stage_column(buf)
            cols[name] = arr
        return cls(cols)

    def own_arrays(self):
        self.columns = {n: np.array(a) for n, a in self.columns.items()}
        return self


def read_owned(table):
    t = ColumnTable.from_arrow(table, zero_copy_ok=True)
    t.own_arrays()
    t.columns["a"][0] = -1
    return t


def read_aliased(table):
    t = ColumnTable.from_arrow(table, zero_copy_ok=True)
    # Planted HSL025: the staged view still aliases the Arrow buffer.
    t.columns["a"][0] = -1
    return t
