"""compat/stats stand-ins: the fixture is parsed, never imported, so
these only need the right *names* — entry detection is tail-based
(``jit``/``shard_map``/``pallas_call``) and the registries are
AST-extracted, exactly like the real compat.py/stats.py."""

KNOWN_STATIC_DOMAINS = {
    "reps": "replication factor: small enumerated ints",
    "n": "lane count, tile-rounded by the factories' memo key",
}


def jit(fn=None, *, key=None, static_argnames=(), donate_argnums=()):
    return fn


def shard_map(fn=None, *, mesh=None):
    return fn


class Mesh:
    def __init__(self, axes):
        self.axes = axes


class _Pallas:
    def pallas_call(self, kernel, **kw):
        return kernel


def resolve_pallas():
    return _Pallas()


class stats:
    _counts: dict = {}

    @classmethod
    def increment(cls, name):
        cls._counts[name] = cls._counts.get(name, 0) + 1
