"""jitdemo: trace-domain fixture package (tracedomain.py, HSL023-026).

A miniature device plane exercising every shape the trace-domain
inference handles — decorator-form jit (bare and
``functools.partial(jit, static_argnames=...)``), call-form jit inside
lru_cache factories, a shard_map body, Pallas kernel bodies, zero-copy
staging, and two kernel fallback ladders — with exactly four planted
violations, one per rule:

- HSL023: ``traced._total`` (reached from ``@jit leaky_norm``) bumps a
  stats counter inside the trace domain; ``norm``/``engage`` is the
  clean hoisted counterpart.
- HSL024: ``traced.poly`` declares static argument ``order`` which is
  not in the fixture's KNOWN_STATIC_DOMAINS; ``scale`` uses the
  declared ``reps`` domain.
- HSL025: ``staging.read_aliased`` mutates a zero-copy staged view in
  place; ``read_owned`` goes through ``own_arrays()`` first.
- HSL026: ``device.rowmax``'s ladder has no permanent per-shape
  fallback set; ``tile_reduce``'s ladder is complete (the proven one).

Like every analysis fixture, this package is parsed by the engine and
never imported — ``shims.py`` stands in for compat/stats so the code
reads like the real device plane without needing jax.
"""
