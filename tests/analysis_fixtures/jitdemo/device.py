"""Two Pallas engagements with fallback ladders: ``tile_reduce`` is
complete (gate + permanent per-shape fallback + both counters — the
proven ladder), ``rowmax`` has no *bad* set, so a retryable lowering
failure re-engages Pallas forever (planted HSL026)."""

import functools
import threading

import jax.numpy as jnp

from jitdemo.shims import jit, resolve_pallas, stats

# Both engagements declared, both counters declared — the registries
# the HSL026 checks read (AST-extracted, like the real ops/stats ones).
KNOWN_KERNELS = (
    "jitdemo.tile_reduce",
    "jitdemo.rowmax",
)
KNOWN_COUNTERS = (
    "device.kernel.fused",
    "device.kernel.fallbacks",
)

_TILE = 128
_MAX_TILE = 4096

# (n,) shapes whose lowering failed: permanent fallback, lock-guarded.
_bad_shapes: set = set()
_bad_lock = threading.Lock()


def _next_mult(n, m):
    return ((n + m - 1) // m) * m


@functools.lru_cache(maxsize=8)
def _make_tile_reduce(n):
    pl = resolve_pallas()

    def kernel(x_ref, o_ref):
        o_ref[...] = jnp.sum(x_ref[...], axis=1)

    def run(x):
        return pl.pallas_call(kernel, grid=(n // _TILE,))(x)

    return jit(run, key="jitdemo.tile_reduce")


def tile_reduce(x):
    n = x.shape[1]
    m = _next_mult(n, _TILE)
    if n <= _MAX_TILE and (n,) not in _bad_shapes:
        try:
            run = _make_tile_reduce(m)
            out = run(jnp.pad(x, ((0, 0), (0, m - n))))
            stats.increment("device.kernel.fused")
            return out
        except Exception:
            with _bad_lock:
                _bad_shapes.add((n,))
            stats.increment("device.kernel.fallbacks")
    return jnp.sum(x, axis=1)


@functools.lru_cache(maxsize=8)
def _make_rowmax(n):
    pl = resolve_pallas()

    def kernel(x_ref, o_ref):
        o_ref[...] = jnp.max(x_ref[...], axis=1)

    def run(x):
        return pl.pallas_call(kernel, grid=(n // _TILE,))(x)

    return jit(run, key="jitdemo.rowmax")


def rowmax(x):
    n = x.shape[1]
    if n <= _MAX_TILE:
        try:
            run = _make_rowmax(n)
            out = run(x)
            stats.increment("device.kernel.fused")
            return out
        except Exception:
            stats.increment("device.kernel.fallbacks")
    return jnp.max(x, axis=1)
