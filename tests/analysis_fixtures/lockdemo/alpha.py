"""Fixture module A: module lock + registry, session/cache class locks."""

import threading

from lockdemo import beta

_registry_lock = threading.Lock()
_registry = {}


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def put_entry(self, key, value):
        with self._lock:
            self._entries[key] = value


class Session:
    def __init__(self):
        self._state_lock = threading.RLock()
        self.cache = Cache()

    def publish(self, key, value):
        # state lock held across a call into the typed-attribute cache:
        # the engine must produce the edge Session._state_lock -> Cache._lock.
        with self._state_lock:
            self.cache.put_entry(key, value)

    def refresh(self):
        # RLock re-entry on the same thread: NOT an HSL009 self-cycle.
        with self._state_lock:
            return self.snapshot()

    def snapshot(self):
        with self._state_lock:
            return dict(_registry)


def register(name, value):
    # One half of the seeded inversion: registry lock, then (via the
    # call chain) beta's audit lock.
    with _registry_lock:
        _registry[name] = value
        beta.audit(name)


def lookup(name):
    with _registry_lock:
        return _registry.get(name)
