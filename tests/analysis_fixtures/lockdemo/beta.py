"""Fixture module B: the other half of the seeded lock-order inversion."""

import threading

from lockdemo import alpha

_audit_lock = threading.Lock()
_audit = []


def audit(name):
    with _audit_lock:
        _audit.append(name)


def rollback(name):
    # The DELIBERATE inversion: audit lock held while calling back into
    # alpha.register, which takes the registry lock — the reverse of
    # register's registry->audit order. HSL009 must report this cycle
    # with both chains as witness.
    with _audit_lock:
        alpha.register(name, None)
