"""Fixture mini-package for the whole-program analysis engine tests.

NOT imported at runtime — the engine only parses it. Contains, on
purpose: a module-level lock + registry, a class-attribute lock pair
resolved through a typed attribute (``self.cache = Cache()``), a
cross-module call chain, and a DELIBERATE lock-order inversion between
``alpha._registry_lock`` and ``beta._audit_lock`` (the HSL009 seeded
regression: the engine must report the cycle with a two-chain witness).
Golden call-graph and lock-graph outputs live in ../goldens/.
"""
