"""Durable telemetry journal (obs/journal.py, docs/observability.md
"telemetry journal"): segment rotation through the atomic tmp+replace
publish, byte-budgeted eviction, the advisory IO contract, the
event/span/SLO taps, the fleet merge reader — and the crash-safety
story proven with a REAL ``kill -9``: a journaling child killed
mid-segment leaves sealed segments that merge cleanly, a torn
``.tmp-seg-*`` tail that merge skips and ``sweep()`` removes."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from hyperspace_tpu import faults, stats
from hyperspace_tpu.analysis.duradomain import TORN_WINDOWS
from hyperspace_tpu.faults import CrashPoint
from hyperspace_tpu.obs import events, journal, metrics, slo, trace
from hyperspace_tpu.obs import export as obs_export


def _enable(tmp_path, **kw):
    # Big enough that only an explicit seal() publishes (the first
    # record also carries an opportunistic full-registry metrics
    # snapshot, which alone overflows a tiny segment budget).
    kw.setdefault("segment_bytes", 1 << 20)
    journal.configure(enabled=True, root=str(tmp_path / "_obs"), **kw)
    return tmp_path / "_obs"


def _my_dir(root):
    return root / str(os.getpid())


# -- write path / rotation ---------------------------------------------------


def test_record_seal_merge_roundtrip(tmp_path):
    root = _enable(tmp_path)
    journal.record("event", event={"name": "x", "seq": 1})
    journal.record("span", trace={"name": "query", "trace_id": "1-1"})
    # Nothing is visible until the active segment is sealed: readers
    # only ever see whole segments.
    assert journal.segment_paths(_my_dir(root)) == []
    journal.seal()
    (seg,) = journal.segment_paths(_my_dir(root))
    kinds = [r["kind"] for r in journal.read_segment(seg)]
    assert "event" in kinds and "span" in kinds
    merged = journal.merge_dir(root)
    assert all(r["pid"] == os.getpid() for r in merged)
    assert [r.get("ts") for r in merged] == sorted(r.get("ts") for r in merged)
    assert stats.get("obs.journal.records") >= 2
    assert stats.get("obs.journal.segments_sealed") == 1


def test_segment_rotation_is_atomic_and_ordered(tmp_path):
    root = _enable(tmp_path, segment_bytes=1024)
    for i in range(200):
        journal.record("event", event={"name": "fill", "seq": i, "pad": "p" * 64})
    journal.seal()
    segs = journal.segment_paths(_my_dir(root))
    assert len(segs) >= 2  # rotated at the byte budget
    numbers = [int(p.name[len("segment-"):-len(".jsonl")]) for p in segs]
    assert numbers == sorted(numbers)
    # Every published segment is whole: each line parses.
    for seg in segs:
        with open(seg, encoding="utf-8") as f:
            for line in f:
                json.loads(line)
    # Replay preserves the emission order within this process.
    seqs = [r["event"]["seq"] for r in journal.merge_dir(root)
            if r["kind"] == "event" and r["event"].get("name") == "fill"]
    assert seqs == sorted(seqs)


def test_eviction_holds_byte_budget_keeping_newest(tmp_path):
    root = _enable(tmp_path, segment_bytes=1024, max_bytes=4096)
    for i in range(400):
        journal.record("event", event={"name": "fill", "seq": i, "pad": "p" * 64})
    journal.seal()
    segs = journal.segment_paths(_my_dir(root))
    assert stats.get("obs.journal.evictions") > 0
    total = sum(p.stat().st_size for p in segs)
    assert total <= 4096 + 2048  # budget + at most the newest overshoot
    # The newest records survived eviction; the oldest were dropped.
    seqs = [r["event"]["seq"] for r in journal.merge_dir(root)
            if r["kind"] == "event"]
    assert 399 in seqs and 0 not in seqs


def test_metrics_snapshots_ride_the_write_path(tmp_path):
    root = _enable(tmp_path, snapshot_s=0.1)
    metrics.counter("serve.completed").inc(7)
    journal.record("event", event={"name": "tick", "seq": 1})
    journal.seal()
    snaps = [r for r in journal.merge_dir(root) if r["kind"] == "metrics"]
    assert snaps and snaps[0]["metrics"]["serve.completed"] == 7


def test_disabled_journal_is_a_noop(tmp_path):
    journal.configure(enabled=False, root=str(tmp_path / "_obs"))
    journal.record("event", event={"name": "x"})
    journal.seal()
    assert not (tmp_path / "_obs").exists()
    assert stats.get("obs.journal.records") == 0


def test_io_failures_are_advisory_counted_not_raised(tmp_path):
    # Point the journal root AT A FILE: every open fails, nothing raises.
    blocker = tmp_path / "blocked"
    blocker.write_text("not a directory")
    journal.configure(enabled=True, root=str(blocker))
    journal.record("event", event={"name": "x"})
    assert stats.get("obs.journal.errors") >= 1
    assert stats.get("obs.journal.records") == 0


# -- taps ---------------------------------------------------------------------


def test_event_span_and_slo_taps_feed_the_journal(tmp_path):
    root = _enable(tmp_path)
    evt = events.declare("advisor.routing.demoted")  # any declared event
    evt.emit(detail="hello")
    with trace.trace("q"):
        pass
    # Walk the SLO sampler into a page: baseline traffic, then a hard
    # failure burst (the controller tests' _drive_page shape).
    completed = metrics.counter("serve.completed")
    failed = metrics.counter("serve.failed")
    metrics.counter("serve.timeouts")
    metrics.counter("serve.cancelled")
    metrics.histogram("serve.latency.seconds")
    completed.inc(10_000)
    slo.sample(0.0)
    slo.evaluate(0.0)
    slo.sample(4000.0)
    slo.evaluate(4000.0)
    failed.inc(3_000)
    slo.sample(4030.0)
    slo.evaluate(4030.0)
    journal.seal()
    merged = journal.merge_dir(root)
    tapped = [r["event"]["name"] for r in merged if r["kind"] == "event"]
    assert "advisor.routing.demoted" in tapped
    span_names = [r["trace"]["name"] for r in merged if r["kind"] == "span"]
    assert "q" in span_names
    transitions = [(r["objective"], r["previous"], r["verdict"])
                   for r in merged if r["kind"] == "slo"]
    assert ("serve.availability", "ok", "page") in transitions


def test_worker_state_shipping_roundtrip(tmp_path):
    _enable(tmp_path, segment_bytes=2048)
    state = journal.export_state()
    assert state["enabled"] and state["parent_pid"] == os.getpid()
    # install_state in THIS process is what a worker would run: it
    # reconfigures and stamps a process record.
    journal.install_state(dict(state, worker_id=3))
    journal.seal()
    merged = journal.merge_dir(journal.root())
    procs = [r for r in merged if r["kind"] == "process"]
    assert procs and procs[-1]["worker_id"] == 3
    assert procs[-1]["parent_pid"] == os.getpid()


# -- reader tolerance ---------------------------------------------------------


def test_merge_skips_torn_and_alien_lines(tmp_path):
    root = _enable(tmp_path)
    journal.record("event", event={"name": "good", "seq": 1})
    journal.seal()
    (seg,) = journal.segment_paths(_my_dir(root))
    with open(seg, "a", encoding="utf-8") as f:
        f.write('{"torn": tr')  # a torn JSON tail
    # An alien (non-journal) pid dir entry and a foreign file.
    (root / "notes.txt").write_text("not a pid dir")
    docs = journal.read_segment(seg)
    assert [d["event"]["seq"] for d in docs if d.get("kind") == "event"] == [1]
    assert journal.merge_dir(root)  # does not raise on the alien file


def test_sweep_removes_torn_tmp_but_not_the_live_tail(tmp_path):
    root = _enable(tmp_path)
    # A dead writer's torn tail in another pid's dir.
    dead = root / "99999"
    dead.mkdir(parents=True)
    torn = dead / ".tmp-seg-abc"
    torn.write_text('{"ts": 1.0, "kind": "event"')
    # Our own live active segment.
    journal.record("event", event={"name": "live", "seq": 1})
    live_tmp = [p for p in _my_dir(root).iterdir()
                if p.name.startswith(".tmp-seg-")]
    assert live_tmp
    removed = journal.sweep(root)
    assert str(torn) in removed and not torn.exists()
    assert all(p.exists() for p in live_tmp)  # the live tail is ours


# -- crash safety: a REAL kill -9 mid-rotation --------------------------------

_CHILD = r"""
import sys
from hyperspace_tpu.obs import journal
journal.configure(enabled=True, root=sys.argv[1], segment_bytes=1024)
i = 0
while True:  # journals forever, until killed
    journal.record("event", event={"name": "child", "seq": i, "pad": "p" * 64})
    i += 1
"""


def test_sigkill_mid_rotation_leaves_mergeable_segments(tmp_path):
    root = tmp_path / "_obs"
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(root)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        child_dir = root / str(proc.pid)
        deadline = time.monotonic() + 60.0
        # Wait until the child has sealed at least two segments AND has
        # an active tmp tail — then SIGKILL it mid-segment.
        while time.monotonic() < deadline:
            sealed = journal.segment_paths(child_dir)
            tmps = (
                [p for p in child_dir.iterdir()
                 if p.name.startswith(".tmp-seg-")]
                if child_dir.is_dir() else []
            )
            if len(sealed) >= 2 and tmps:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("child never sealed two segments")
    finally:
        proc.kill()  # SIGKILL: no cleanup handlers run
        proc.wait(timeout=30.0)
    assert proc.returncode == -signal.SIGKILL
    # The torn tail is invisible to readers and the sealed history
    # replays in order with no gaps.
    merged = journal.merge_dir(root)
    seqs = [r["event"]["seq"] for r in merged if r["kind"] == "event"]
    assert seqs == list(range(len(seqs))) and len(seqs) > 0
    # sweep() reaps the torn tmp tail the kill left behind.
    leftover = [p for p in (root / str(proc.pid)).iterdir()
                if p.name.startswith(".tmp-seg-")]
    assert leftover  # the kill really did tear an active segment
    journal.sweep(root)
    assert not [p for p in (root / str(proc.pid)).iterdir()
                if p.name.startswith(".tmp-seg-")]
    assert journal.merge_dir(root) == merged  # sweep changed no history


# -- fleet chrome export ------------------------------------------------------


def _write_member_journal(root, pid, spans):
    d = root / str(pid)
    d.mkdir(parents=True)
    with open(d / "segment-00000000.jsonl", "w", encoding="utf-8") as f:
        for i, sp in enumerate(spans):
            f.write(json.dumps(
                {"ts": float(i), "pid": pid, "kind": "span", "trace": sp}
            ) + "\n")


def test_fleet_chrome_lanes_are_pid_qualified(tmp_path):
    """Two members whose OS thread ids collide (tid=1 in both — every
    member's main thread) must land on separate per-pid track groups,
    not interleave on one lane."""
    root = tmp_path / "_obs"
    _write_member_journal(root, 101, [
        {"name": "qa", "trace_id": "101-1", "tid": 1, "t0_s": 0.0, "wall_s": 1.0}
    ])
    _write_member_journal(root, 202, [
        {"name": "qb", "trace_id": "202-1", "tid": 1, "t0_s": 0.5, "wall_s": 1.0}
    ])
    roots = obs_export.roots_from_fleet(str(root))
    assert {r["pid"] for r in roots} == {101, 202}
    doc = obs_export.chrome_trace(roots)
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {(e["pid"], e["name"]) for e in slices} == {(101, "qa"), (202, "qb")}
    # Same raw tid, different pids => distinct (pid, lane) tracks with
    # per-pid alias numbering starting at 1 in each group.
    assert {(e["pid"], e["tid"]) for e in slices} == {(101, 1), (202, 1)}
    names = [e for e in doc["traceEvents"] if e["ph"] == "M"
             and e["name"] == "process_name"]
    assert {m["args"]["name"] for m in names} == {
        "member pid 101", "member pid 202"
    }


# -- torn-window sweep, driven BY NAME from the static registry --------------


def _drive_seal_before_index(tmp_path, point):
    """Kill between the segment publish (replace + dir fsync) and the
    eviction/bookkeeping index: the sealed segment must be whole, the
    bookkeeping must be untouched, and a restarted journaler re-scans
    the directory and indexes PAST the orphan instead of over it."""
    root = _enable(tmp_path)
    journal.record("event", event={"name": "torn", "seq": 0})
    sealed_before = stats.get("obs.journal.segments_sealed")
    faults.inject(point, crash=True, at_call=1)
    try:
        with pytest.raises(CrashPoint):
            journal.seal()
    finally:
        faults.reset()
    # First half of the window held: the segment published whole …
    (seg,) = journal.segment_paths(_my_dir(root))
    seqs = [r["event"]["seq"] for r in journal.read_segment(seg)
            if r["kind"] == "event" and r["event"].get("name") == "torn"]
    assert seqs == [0]
    # … and the second half never ran: no seal counted, no eviction.
    assert stats.get("obs.journal.segments_sealed") == sealed_before
    # A real kill takes the process; model the restart with the
    # journal's own reset (fresh segment cursor -> directory re-scan).
    journal.reset()
    _enable(tmp_path)
    journal.record("event", event={"name": "torn", "seq": 1})
    journal.seal()
    segs = journal.segment_paths(_my_dir(root))
    assert len(segs) == 2  # the orphan was indexed past, not overwritten
    merged = [r["event"]["seq"] for r in journal.merge_dir(root)
              if r["kind"] == "event" and r["event"].get("name") == "torn"]
    assert merged == [0, 1]
    assert journal.sweep(root) == []  # sealed segments are never swept


_TORN_WINDOW_DRIVERS = {
    "journal.seal_before_index": _drive_seal_before_index,
}


@pytest.mark.parametrize(
    "window", sorted(k for k in TORN_WINDOWS if k.startswith("journal."))
)
def test_kill_inside_window_converges(window, tmp_path):
    """A journal window added to `analysis.duradomain.TORN_WINDOWS`
    without a driver here fails with a KeyError — the crash sweep can
    never silently drift from the statically proven protocol set."""
    _fn, _first, _second, point, why = TORN_WINDOWS[window]
    assert point in faults.KNOWN_POINTS, why
    _TORN_WINDOW_DRIVERS[window](tmp_path, point)
