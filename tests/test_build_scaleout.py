"""Scale-out pooled index build (docs/architecture.md "scale-out
build"): bucket-sharded worker-process pool + spill-file exchange must
be BYTE-identical to the serial streaming reference at every worker
count, and the exchange format itself round-trips."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import stats
from hyperspace_tpu.dataset import Dataset
from hyperspace_tpu.execution import build_exchange as bx
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.builder import DeviceIndexBuilder


def _gen_source(root, n=12_000, files=3, row_group_size=2_000, with_nulls=True):
    root.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(11)
    per = n // files
    for i in range(files):
        m = per if i < files - 1 else n - per * (files - 1)
        k = rng.integers(-(10**12), 10**12, m).astype(np.int64)
        nulls = (rng.random(m) < 0.08) if with_nulls else None
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(k, mask=nulls),
                    "s": pa.array([f"s{j % 41:02d}" for j in range(m)]),
                    "v": pa.array(rng.standard_normal(m)),
                }
            ),
            root / f"p{i}.parquet",
            row_group_size=row_group_size,
        )


def _assert_identical_index(d_ref, d_got, num_buckets):
    assert hio.read_manifest(d_ref) == hio.read_manifest(d_got)
    for b in range(num_buckets):
        rb = (d_ref / hio.bucket_file_name(b)).read_bytes()
        gb = (d_got / hio.bucket_file_name(b)).read_bytes()
        assert rb == gb, f"bucket {b} bytes differ from the serial reference"


# kw shared by reference and pooled builders: the tiny budget forces the
# serial builder down the streaming path (the pooled build's reference).
_KW = dict(memory_budget_bytes=50_000, chunk_bytes=80_000)


def test_pooled_build_matches_serial_byte_for_byte_across_worker_counts(tmp_path):
    """1, 2, and 4 workers — every pooled layout must reproduce the
    serial streaming reference exactly (manifest AND bucket bytes)."""
    _gen_source(tmp_path / "src")
    ds = Dataset.parquet(tmp_path / "src")
    num_buckets = 16
    serial = DeviceIndexBuilder(pipeline_enabled=False, **_KW)
    d_ref = tmp_path / "ref" / "v__=0"
    serial.write(ds.scan(), ["k", "s", "v"], ["k"], num_buckets, d_ref)
    assert serial.last_build_stats["path"] == "streaming"

    for w in (1, 2, 4):
        pooled = DeviceIndexBuilder(workers=w, **_KW)
        d = tmp_path / f"pool{w}" / "v__=0"
        pooled.write(ds.scan(), ["k", "s", "v"], ["k"], num_buckets, d)
        st = pooled.last_build_stats
        assert st["path"] == "pooled" and st["workers"] == w
        assert st["p1_shards"] <= w and st["p2_owners"] <= w
        assert st["rows"] == 12_000 and st["exchange_bytes"] > 0
        assert not (d.parent / "v__=0.exchange").exists(), "exchange dir must be swept"
        _assert_identical_index(d_ref, d, num_buckets)


def test_worker_count_exceeds_bucket_count(tmp_path):
    """More workers than buckets: owners clamp to the bucket count and
    the output stays identical."""
    _gen_source(tmp_path / "src", n=4_000, files=2, row_group_size=1_000)
    ds = Dataset.parquet(tmp_path / "src")
    serial = DeviceIndexBuilder(pipeline_enabled=False, memory_budget_bytes=20_000, chunk_bytes=30_000)
    d_ref = tmp_path / "ref" / "v__=0"
    serial.write(ds.scan(), ["k", "v"], ["k"], 2, d_ref)
    pooled = DeviceIndexBuilder(workers=4, memory_budget_bytes=20_000, chunk_bytes=30_000)
    d = tmp_path / "pool" / "v__=0"
    pooled.write(ds.scan(), ["k", "v"], ["k"], 2, d)
    assert pooled.last_build_stats["p2_owners"] == 2  # clamped to buckets
    assert pooled.last_build_stats["p1_shards"] == 2  # clamped to files
    _assert_identical_index(d_ref, d, 2)


def test_single_bucket_index(tmp_path):
    _gen_source(tmp_path / "src", n=3_000, files=2, row_group_size=1_000)
    ds = Dataset.parquet(tmp_path / "src")
    serial = DeviceIndexBuilder(pipeline_enabled=False, memory_budget_bytes=10_000, chunk_bytes=20_000)
    d_ref = tmp_path / "ref" / "v__=0"
    serial.write(ds.scan(), ["k", "v"], ["k"], 1, d_ref)
    pooled = DeviceIndexBuilder(workers=2, memory_budget_bytes=10_000, chunk_bytes=20_000)
    d = tmp_path / "pool" / "v__=0"
    pooled.write(ds.scan(), ["k", "v"], ["k"], 1, d)
    _assert_identical_index(d_ref, d, 1)


def test_zero_row_input(tmp_path):
    """Zero-row source files: every bucket lands empty, manifest all
    zeros, identical to the serial reference."""
    root = tmp_path / "src"
    root.mkdir(parents=True)
    empty = pa.table({"k": pa.array([], type=pa.int64()), "v": pa.array([], type=pa.float64())})
    pq.write_table(empty, root / "p0.parquet")
    pq.write_table(empty, root / "p1.parquet")
    ds = Dataset.parquet(root)
    serial = DeviceIndexBuilder(pipeline_enabled=False, memory_budget_bytes=1, chunk_bytes=1_000)
    d_ref = tmp_path / "ref" / "v__=0"
    serial.write(ds.scan(), ["k", "v"], ["k"], 4, d_ref)
    pooled = DeviceIndexBuilder(workers=2, memory_budget_bytes=1, chunk_bytes=1_000)
    d = tmp_path / "pool" / "v__=0"
    pooled.write(ds.scan(), ["k", "v"], ["k"], 4, d)
    assert pooled.last_build_stats["rows"] == 0
    assert hio.read_manifest(d)["bucketRows"] == [0, 0, 0, 0]
    _assert_identical_index(d_ref, d, 4)


# -- exchange-format unit tests ----------------------------------------------


def test_slice_files_contiguous_ordered_balanced():
    files = [f"f{i}" for i in range(10)]
    sizes = [100] * 10
    for w in (1, 2, 3, 4, 10, 16):
        slices = bx.slice_files(files, sizes, w)
        assert len(slices) == min(w, len(files))
        assert all(s for s in slices), "no empty slices"
        # Contiguity + order: concatenation reproduces the input exactly.
        assert [f for s in slices for f in s] == files
    # Byte balance: a huge first file takes a slice of its own.
    slices = bx.slice_files(files, [10_000] + [100] * 9, 3)
    assert slices[0] == ["f0"]
    assert bx.slice_files([], [], 4) == []


def test_owner_map_is_bucket_mod_owners():
    assert [bx.owner_of(b, 3) for b in range(7)] == [0, 1, 2, 0, 1, 2, 0]


def test_spill_path_layout_groups_by_owner(tmp_path):
    p = bx.spill_path(tmp_path, owner=2, shard=1, bucket=7)
    assert p.parent == tmp_path / "owner-00002"
    assert p.name == "shard-00001.bucket-00007.parquet"


def test_exchange_roundtrip_in_process(tmp_path):
    """p1_shard → p2_owner run in-process (no pool): the exchange format
    round-trips rows exactly, shard-order concatenation preserves the
    global row order, and the ledger matches what p2 budgets from."""
    _gen_source(tmp_path / "src", n=2_000, files=2, row_group_size=500, with_nulls=False)
    ds = Dataset.parquet(tmp_path / "src")
    schema = ds.scan().scan_schema
    files = sorted(str(p) for p in (tmp_path / "src").glob("*.parquet"))
    ex = tmp_path / "ex"
    num_buckets, num_owners = 4, 2
    ledgers = []
    for w, f in enumerate(files):
        res = bx.p1_shard(bx.P1Task(
            worker=w, files=[f], fmt="parquet", columns=["k", "s", "v"],
            schema=schema, indexed_columns=["k"], num_buckets=num_buckets,
            num_owners=num_owners, chunk_bytes=20_000, memory_budget_bytes=10_000,
            exchange_dir=str(ex),
        ))
        assert res["rows"] == 1_000 and res["chunks"] >= 1
        ledgers.append(res["spill_bytes"])
        for b, path in res["spill_files"].items():
            assert bx.owner_of(b, num_owners) == int(path.split("owner-")[1][:5])
    merged = {}
    for led in ledgers:
        for b, nb in led.items():
            merged[b] = merged.get(b, 0) + nb
    dest = tmp_path / "out"
    dest.mkdir()
    rows = {}
    for o in range(num_owners):
        res = bx.p2_owner(bx.P2Task(
            owner=o, num_owners=num_owners, n_shards=len(files),
            num_buckets=num_buckets, exchange_dir=str(ex), dest_dir=str(dest),
            columns=["k", "s", "v"], schema=schema, indexed_columns=["k"],
            spill_bytes={b: nb for b, nb in merged.items() if bx.owner_of(b, num_owners) == o},
            window_bytes=1,  # a window below any bucket still admits one at a time
        ))
        rows.update(res["bucket_rows"])
    assert sum(rows.values()) == 2_000
    # Row multiset survives the exchange + sort.
    got = pd.concat([
        pd.DataFrame(hio.read_parquet([str(dest / hio.bucket_file_name(b))]).decode())
        for b in range(num_buckets)
    ])
    exp = pd.concat([pd.read_parquet(f) for f in files])
    cols = ["k", "s", "v"]
    pd.testing.assert_frame_equal(
        got[cols].sort_values(cols).reset_index(drop=True),
        exp[cols].sort_values(cols).reset_index(drop=True),
        check_dtype=False,
    )


def test_host_sort_perm_matches_lexsort(tmp_path):
    from hyperspace_tpu.execution.table import ColumnTable
    from hyperspace_tpu.ops.sortkeys import key_lanes, lexsort_lanes

    rng = np.random.default_rng(3)
    t = ColumnTable.from_arrow(pa.table({
        "k": rng.integers(-100, 100, 500).astype(np.int64),
        "v": rng.standard_normal(500),
    }))
    perm = bx.host_sort_perm(t, ["k"])
    expected = lexsort_lanes(key_lanes(t, ["k"]))
    assert np.array_equal(np.asarray(perm), np.asarray(expected))


# -- end-to-end through the session/config surface ----------------------------


def test_create_index_with_workers_conf_serves_queries(tmp_path):
    """hyperspace.build.workers=2 end-to-end: CreateAction commits a
    pooled build through the unchanged 2-phase protocol and the index
    answers rewritten queries identically to the raw scan."""
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu.config import BUILD_WORKERS

    _gen_source(tmp_path / "src", n=6_000, files=2, with_nulls=False)
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    session.conf.set(BUILD_WORKERS, 2)
    hs = Hyperspace(session)
    df = session.parquet(tmp_path / "src")
    before = stats.get("build.exchange.bytes")
    hs.create_index(df, IndexConfig("sidx", ["k"], ["s", "v"]))
    assert session.last_build_stats["path"] == "pooled"
    assert stats.get("build.exchange.bytes") > before

    some_key = int(session.run(df.select("k")).columns["k"][7])
    q = df.filter(col("k") == some_key).select("k", "s", "v")
    session.disable_hyperspace()
    expected = session.to_pandas(q).sort_values(["s", "v"]).reset_index(drop=True)
    session.enable_hyperspace()
    got = session.to_pandas(q).sort_values(["s", "v"]).reset_index(drop=True)
    assert len(got) > 0
    pd.testing.assert_frame_equal(got, expected[got.columns.tolist()])


def test_configured_exchange_dir_is_used_and_swept(tmp_path):
    """hyperspace.build.exchange.dir: the exchange lands under the
    configured root (suffixed per build so concurrent builds never
    collide) and is swept either way."""
    _gen_source(tmp_path / "src", n=2_000, files=2, row_group_size=500, with_nulls=False)
    ds = Dataset.parquet(tmp_path / "src")
    ex_root = tmp_path / "scratch"
    b = DeviceIndexBuilder(workers=2, exchange_dir=str(ex_root),
                           memory_budget_bytes=10_000, chunk_bytes=20_000)
    dest = tmp_path / "i" / "v__=0"
    assert b._exchange_root(dest) == ex_root / "i-v__=0.exchange"
    b.write(ds.scan(), ["k", "v"], ["k"], 4, dest)
    assert b.last_build_stats["path"] == "pooled"
    assert not any(ex_root.glob("*")), "configured exchange dir not swept"
    assert hio.read_manifest(dest)["bucketRows"] and sum(
        hio.read_manifest(dest)["bucketRows"]) == 2_000


def test_pooled_build_adopts_worker_traces(tmp_path):
    """Each worker process's root span ships back and lands in this
    process's recent-root ring with the WORKER's pid-qualified trace id
    — the chrome exporter's one-lane-per-worker-process evidence."""
    import os

    from hyperspace_tpu.obs import trace as obs_trace

    _gen_source(tmp_path / "src", n=3_000, files=2, row_group_size=1_000, with_nulls=False)
    ds = Dataset.parquet(tmp_path / "src")
    obs_trace.reset()
    pooled = DeviceIndexBuilder(workers=2, memory_budget_bytes=20_000, chunk_bytes=30_000)
    with obs_trace.trace("test.build"):
        pooled.write(ds.scan(), ["k", "v"], ["k"], 4, tmp_path / "i" / "v__=0")
    roots = obs_trace.recent_roots()
    worker_roots = [r for r in roots if r.name in ("build.p1.worker", "build.p2.worker")]
    assert len(worker_roots) >= 3  # 2 p1 shards + >=1 p2 owner adopted
    my_pid = str(os.getpid())
    pids = {str(r.trace_id).split("-", 1)[0] for r in worker_roots}
    assert my_pid not in pids and len(pids) >= 2, pids
