"""Outer / semi / anti join types: equality vs SQL semantics computed in
pandas (with null keys handled the SQL way — NULL never matches, unlike
pandas' NaN-joins-NaN), on both venues, rewritten (bucket-aligned index
path) and raw. The reference inherits these join types from Spark's
SortMergeJoinExec over its rewritten bucketed relations — the rewrite
swaps only the relations inside whatever join node it matched
(JoinIndexRule.scala:124-153)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
from hyperspace_tpu import native
from hyperspace_tpu.config import JOIN_VENUE

HOWS = ["inner", "left", "right", "full", "semi", "anti"]


def _frames():
    rng = np.random.default_rng(7)
    n_l, n_r = 3_000, 800
    lk = rng.integers(0, 400, n_l).astype(np.float64)
    lk[rng.random(n_l) < 0.05] = np.nan  # null keys
    rk = rng.integers(300, 600, n_r).astype(np.float64)  # partial overlap
    rk[rng.random(n_r) < 0.05] = np.nan
    l = pd.DataFrame(
        {
            "k": pd.array(np.where(np.isnan(lk), None, lk), dtype="Int64"),
            "lv": rng.integers(0, 100, n_l).astype(np.int64),
            "ls": [f"L{int(i) % 11}" for i in rng.integers(0, 11, n_l)],
        }
    )
    r = pd.DataFrame(
        {
            "k2": pd.array(np.where(np.isnan(rk), None, rk), dtype="Int64"),
            "rv": rng.normal(size=n_r),
            "rs": [f"R{int(i) % 5}" for i in rng.integers(0, 5, n_r)],
        }
    )
    return l, r


def sql_join(l: pd.DataFrame, r: pd.DataFrame, how: str) -> pd.DataFrame:
    """SQL-semantics expected output (columns k, lv, ls[, rv, rs]):
    NULL keys never match; outer variants null-extend; the key column
    coalesces (right-unmatched rows carry the right key)."""
    ld = l[l.k.notna()]
    rd = r[r.k2.notna()]
    if how == "semi":
        return l[l.k.isin(set(rd.k2))]
    if how == "anti":
        return l[~l.k.isin(set(rd.k2))]
    inner = ld.merge(rd, left_on="k", right_on="k2", how="inner").drop(columns=["k2"])
    parts = [inner]
    if how in ("left", "full"):
        un = l[~l.k.isin(set(rd.k2))].copy()
        un["rv"] = np.nan
        un["rs"] = None
        parts.append(un)
    if how in ("right", "full"):
        un = r[~r.k2.isin(set(ld.k))].copy()
        un = un.rename(columns={"k2": "k"})
        un["lv"] = None
        un["ls"] = None
        parts.append(un)
    return pd.concat(parts, ignore_index=True)[["k", "lv", "ls", "rv", "rs"]]


def norm_rows(df: pd.DataFrame, cols: list[str]) -> list[str]:
    """Order-independent, null-normalized row multiset for comparison."""
    rows = []
    for t in df[cols].itertuples(index=False, name=None):
        row = []
        for v in t:
            if v is None or v is pd.NA or (isinstance(v, float) and np.isnan(v)):
                row.append(None)
            elif isinstance(v, (bool, np.bool_)):
                row.append(bool(v))
            elif isinstance(v, (int, np.integer, float, np.floating)):
                row.append(round(float(v), 9))
            else:
                row.append(str(v))
        rows.append(repr(tuple(row)))
    return sorted(rows)


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("join_types")
    l, r = _frames()
    (tmp_path / "l").mkdir()
    (tmp_path / "r").mkdir()
    pq.write_table(pa.Table.from_pandas(l, preserve_index=False), tmp_path / "l" / "p.parquet")
    pq.write_table(pa.Table.from_pandas(r, preserve_index=False), tmp_path / "r" / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    ls, rs = session.parquet(tmp_path / "l"), session.parquet(tmp_path / "r")
    hs.create_index(ls, IndexConfig("jt_l", ["k"], ["lv", "ls"]))
    hs.create_index(rs, IndexConfig("jt_r", ["k2"], ["rv", "rs"]))
    return session, ls, rs, l, r


@pytest.mark.parametrize("how", HOWS)
@pytest.mark.parametrize("venue", ["device", "host"])
@pytest.mark.parametrize("indexed", [False, True])
def test_join_types_match_sql_semantics(setup, how, venue, indexed):
    session, ls, rs, l, r = setup
    if venue == "host" and not native.available():
        pytest.skip("native library not built")
    if indexed:
        session.enable_hyperspace()
    else:
        session.disable_hyperspace()
    session.conf.set(JOIN_VENUE, venue)
    q = ls.join(rs, ["k"], ["k2"], how=how)
    got = session.to_pandas(q)
    exp = sql_join(l, r, how)
    out_cols = ["k", "lv", "ls"] if how in ("semi", "anti") else ["k", "lv", "ls", "rv", "rs"]
    assert list(got.columns) == out_cols
    assert norm_rows(got, out_cols) == norm_rows(exp, out_cols)
    if indexed:
        assert session.last_query_stats["join_path"] == "zero-exchange-aligned"
        assert session.last_query_stats["num_buckets"] == 4


@pytest.mark.parametrize("how", ["left", "semi", "anti", "full"])
def test_join_types_with_side_filter_and_pushdown(setup, how):
    """Filter above the join on LEFT columns: pushed below for left/semi/
    anti (semantics-preserving), kept residual for full — identical
    results either way vs filtering the SQL-expected frame."""
    from hyperspace_tpu import col

    session, ls, rs, l, r = setup
    session.enable_hyperspace()
    session.conf.set(JOIN_VENUE, "device")
    q = ls.join(rs, ["k"], ["k2"], how=how).filter(col("lv") < 50)
    got = session.to_pandas(q)
    exp = sql_join(l, r, how)
    exp = exp[exp.lv.notna() & (exp.lv < 50)]
    out_cols = ["k", "lv", "ls"] if how in ("semi", "anti") else ["k", "lv", "ls", "rv", "rs"]
    assert norm_rows(got, out_cols) == norm_rows(exp, out_cols)


def test_right_unmatched_coalesces_key_from_right(setup):
    """Full join rows unmatched on the left carry the RIGHT key value in
    the (left-named) key column."""
    session, ls, rs, l, r = setup
    session.disable_hyperspace()
    session.conf.set(JOIN_VENUE, "device")
    got = session.to_pandas(ls.join(rs, ["k"], ["k2"], how="full"))
    rd_only = set(r[r.k2.notna()].k2) - set(l[l.k.notna()].k)
    got_keys = set(got[got.lv.isna()].k.dropna())
    assert rd_only <= got_keys


def test_unknown_join_type_rejected():
    from hyperspace_tpu.plan.nodes import Join, Scan
    from hyperspace_tpu.schema import Field, Schema

    s = Scan("/tmp/x", "parquet", Schema((Field("k", "int64"),)))
    with pytest.raises(ValueError, match="unknown join type"):
        Join(s, s, ["k"], ["k"], "cross")


def test_semi_anti_schema_is_left_only(setup):
    _, ls, rs, _, _ = setup
    semi = ls.join(rs, ["k"], ["k2"], how="semi")
    assert [f.name for f in semi.schema.fields] == ["k", "lv", "ls"]
    full = ls.join(rs, ["k"], ["k2"], how="full")
    assert [f.name for f in full.schema.fields] == ["k", "lv", "ls", "rv", "rs"]


def test_non_equi_join_condition(tmp_path):
    """ON a.k = b.k AND <theta>: the non-equi residual evaluates over
    the matched rows with 3-valued semantics (inner joins only); the
    rewritten index path returns the same rows as raw."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu.plan.nodes import plan_from_json

    rng = np.random.default_rng(77)
    n = 12_000
    left = pd.DataFrame(
        {
            "k": rng.integers(0, 300, n).astype(np.int64),
            "lo": rng.integers(0, 50, n).astype(np.int64),
        }
    )
    right = pd.DataFrame(
        {
            "k2": np.arange(300, dtype=np.int64),
            "hi": rng.integers(10, 60, 300).astype(np.int64),
        }
    )
    for name, df in (("l", left), ("r", right)):
        (tmp_path / name).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    l = session.parquet(tmp_path / "l")
    r = session.parquet(tmp_path / "r")
    hs.create_index(l, IndexConfig("ne_l", ["k"], ["lo"]))
    hs.create_index(r, IndexConfig("ne_r", ["k2"], ["hi"]))

    q = l.join(r, ["k"], ["k2"], condition=col("lo") < col("hi")).aggregate(
        [], [AggSpec.of("count", None, "n")]
    )
    assert plan_from_json(q.to_json()).to_json() == q.to_json()
    session.enable_hyperspace()
    n_idx = int(session.to_pandas(q).loc[0, "n"])
    assert "residual_condition" in repr(session.last_physical_plan)
    session.disable_hyperspace()
    n_raw = int(session.to_pandas(q).loc[0, "n"])
    exp = len(left.merge(right, left_on="k", right_on="k2").query("lo < hi"))
    assert n_idx == n_raw == exp

    # Outer joins accept residuals too (matching semantics —
    # test_on_residual_alters_matching pins the behavior).
    l.join(r, ["k"], ["k2"], how="left", condition=col("lo") < col("hi"))
    with pytest.raises(ValueError, match="match schema"):
        l.join(r, ["k"], ["k2"], condition=col("nope") < col("hi"))


@pytest.mark.parametrize("how", ["left", "right", "full", "semi", "anti"])
def test_on_residual_alters_matching(tmp_path, how):
    """Outer/semi/anti ON residual: a pair failing the residual is NOT a
    match — left rows null-extend / flip existence, per SQL ON-clause
    semantics. Oracle: pandas inner merge + residual, then recompose."""
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col

    rng = np.random.default_rng(91)
    n = 6_000
    left = pd.DataFrame(
        {
            "k": rng.integers(0, 250, n).astype(np.int64),
            "lo": rng.integers(0, 50, n).astype(np.int64),
        }
    )
    right = pd.DataFrame(
        {
            "k2": rng.integers(100, 350, 900).astype(np.int64),
            "hi": rng.integers(10, 60, 900).astype(np.int64),
        }
    )
    for name, df in (("l", left), ("r", right)):
        (tmp_path / name).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    l = session.parquet(tmp_path / "l")
    r = session.parquet(tmp_path / "r")
    hs.create_index(l, IndexConfig("or_l", ["k"], ["lo"]))
    hs.create_index(r, IndexConfig("or_r", ["k2"], ["hi"]))

    q = l.join(r, ["k"], ["k2"], how=how, condition=col("lo") < col("hi"))

    surv = left.reset_index().merge(right.reset_index(), left_on="k", right_on="k2",
                                    suffixes=("_l", "_r")).query("lo < hi")
    if how in ("semi", "anti"):
        in_l = set(surv.index_l)
        keep = left.index.isin(in_l)
        exp = left[keep if how == "semi" else ~keep]
        cols = ["k", "lo"]
        exp = exp[cols]
    else:
        inner = surv[["k", "lo", "hi"]]
        parts = [inner]
        if how in ("left", "full"):
            lum = left[~left.index.isin(set(surv.index_l))].copy()
            lum["hi"] = np.nan
            parts.append(lum[["k", "lo", "hi"]])
        if how in ("right", "full"):
            rum = right[~right.index.isin(set(surv.index_r))].copy()
            rum["k"] = rum["k2"]
            rum["lo"] = np.nan
            parts.append(rum[["k", "lo", "hi"]])
        exp = pd.concat(parts, ignore_index=True)
        cols = ["k", "lo", "hi"]

    for enabled in (False, True):
        if enabled:
            session.enable_hyperspace()
        else:
            session.disable_hyperspace()
        got = session.to_pandas(q)
        assert norm_rows(got, cols) == norm_rows(exp, cols), (how, enabled)


def test_intersect_except_set_semantics(tmp_path):
    """INTERSECT/EXCEPT desugar to DISTINCT + semi/anti joins on all
    columns (the set-op nodes the reference round-trips,
    LogicalPlanSerDeUtils.scala:82-145)."""
    from hyperspace_tpu import HyperspaceSession

    a = pd.DataFrame({"x": [1, 1, 2, 3, 5], "y": ["a", "a", "b", "c", "e"]})
    b = pd.DataFrame({"u": [1, 3, 3, 4], "v": ["a", "c", "c", "d"]})
    for name, df in (("a", a), ("b", b)):
        (tmp_path / name).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    da, db = session.parquet(tmp_path / "a"), session.parquet(tmp_path / "b")

    inter = session.to_pandas(da.intersect(db)).sort_values("x")
    assert list(map(tuple, inter.to_numpy())) == [(1, "a"), (3, "c")]
    exc = session.to_pandas(da.except_(db)).sort_values("x")
    assert list(map(tuple, exc.to_numpy())) == [(2, "b"), (5, "e")]
    with pytest.raises(ValueError, match="equal width"):
        da.intersect(db.select("u"))
    with pytest.raises(ValueError, match="incompatible"):
        da.intersect(db.select("v", "u"))  # int vs string positionally
