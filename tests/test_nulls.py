"""Null handling end to end.

Spark columns are nullable by default and the reference indexes them
untouched (schema captured with nullability, index/IndexLogEntry.scala:39-47).
Here nulls ride validity masks through ColumnTable, predicates evaluate with
SQL 3-valued logic (filters keep only definitely-true rows), null keys never
equi-join, and parquet round-trips preserve the masks.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.ops.filter import eval_predicate_mask
from hyperspace_tpu.plan.expr import lit


@pytest.fixture
def session(tmp_system_path):
    return HyperspaceSession(system_path=tmp_system_path, num_buckets=8)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def _nullable_parquet(tmp_path, n=800, seed=11):
    """key + payload columns, every one carrying nulls."""
    rng = np.random.default_rng(seed)
    key = rng.integers(0, 60, n).astype(np.int64)
    val = rng.standard_normal(n)
    name = np.array([f"n{i % 23}" for i in range(n)], dtype=object)
    knull = rng.random(n) < 0.15
    vnull = rng.random(n) < 0.15
    snull = rng.random(n) < 0.15
    table = pa.table(
        {
            "key": pa.array([None if m else int(k) for k, m in zip(key, knull)], type=pa.int64()),
            "value": pa.array([None if m else float(v) for v, m in zip(val, vnull)], type=pa.float64()),
            "name": pa.array([None if m else s for s, m in zip(name, snull)], type=pa.string()),
        }
    )
    root = tmp_path / "nullable"
    root.mkdir()
    pq.write_table(table.slice(0, n // 2), root / "a.parquet")
    pq.write_table(table.slice(n // 2), root / "b.parquet")
    return str(root), table.to_pandas()


def frames_equal(a: pd.DataFrame, b: pd.DataFrame):
    cols = sorted(a.columns)
    assert sorted(b.columns) == cols

    def decat(df: pd.DataFrame) -> pd.DataFrame:
        # ColumnTable.to_arrow emits dictionary-coded string columns
        # (codes + dictionary — strings never inflate on host), which
        # pandas renders as Categorical; the VALUES are what this
        # comparison is about.
        out = df.copy()
        for c in out.columns:
            if isinstance(out[c].dtype, pd.CategoricalDtype):
                out[c] = out[c].astype(object)
        return out

    a2 = decat(a[cols]).sort_values(cols, na_position="last").reset_index(drop=True)
    b2 = decat(b[cols]).sort_values(cols, na_position="last").reset_index(drop=True)
    pd.testing.assert_frame_equal(a2, b2, check_dtype=False)


def _canon(df: pd.DataFrame) -> pd.DataFrame:
    """Normalize None→NaN so decode() output compares against pandas."""
    return df.fillna(np.nan) if len(df) else df


# -- container round-trip ----------------------------------------------------

def test_arrow_round_trip_preserves_nulls(tmp_path):
    root, pdf = _nullable_parquet(tmp_path)
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.dataset import list_data_files

    files = [fi.path for fi in list_data_files(root)]
    t = hio.read_parquet(files)
    assert set(t.validity) == {"key", "value", "name"}
    back = t.to_arrow().to_pandas()
    frames_equal(back, pdf)


# -- 3-valued predicate logic ------------------------------------------------

def _masked_table(n=400, seed=3):
    rng = np.random.default_rng(seed)
    from hyperspace_tpu.schema import Field, Schema

    schema = Schema.of(Field("a", "int64", nullable=True), Field("b", "float64", nullable=True))
    a = rng.integers(-50, 50, n).astype(np.int64)
    b = rng.standard_normal(n)
    va = rng.random(n) > 0.2
    vb = rng.random(n) > 0.2
    t = ColumnTable(schema, {"a": a, "b": b}, {}, {"a": va, "b": vb})
    return t, a, b, va, vb


def test_filter_comparison_null_is_not_true():
    t, a, b, va, vb = _masked_table()
    got = eval_predicate_mask(t, col("a") > lit(0))
    np.testing.assert_array_equal(got, (a > 0) & va)
    got = eval_predicate_mask(t, col("a") != lit(3))
    np.testing.assert_array_equal(got, (a != 3) & va)


def test_filter_kleene_and_or_not():
    t, a, b, va, vb = _masked_table()
    # OR: (false OR unknown) = unknown → dropped; (true OR unknown) = true.
    got = eval_predicate_mask(t, (col("a") > lit(0)) | (col("b") > lit(0)))
    want = ((a > 0) & va) | ((b > 0) & vb)
    np.testing.assert_array_equal(got, want)
    # AND with Kleene: true only when both definitely true.
    got = eval_predicate_mask(t, (col("a") > lit(0)) & (col("b") > lit(0)))
    want = (a > 0) & va & (b > 0) & vb
    np.testing.assert_array_equal(got, want)
    # NOT(unknown) = unknown → dropped either way.
    got = eval_predicate_mask(t, ~(col("a") > lit(0)))
    want = ~(a > 0) & va
    np.testing.assert_array_equal(got, want)


def test_filter_host_fallback_kleene():
    """Arithmetic on a nullable int64 column runs on host — same 3-valued
    result."""
    t, a, b, va, vb = _masked_table()
    got = eval_predicate_mask(t, (col("a") + lit(1)) > lit(0))
    np.testing.assert_array_equal(got, ((a + 1) > 0) & va)


def test_filter_64bit_pair_path_with_nulls():
    from hyperspace_tpu.schema import Field, Schema

    rng = np.random.default_rng(9)
    n = 300
    a = rng.integers(-(2**60), 2**60, n).astype(np.int64)
    va = rng.random(n) > 0.3
    schema = Schema.of(Field("a", "int64", nullable=True))
    t = ColumnTable(schema, {"a": a}, {}, {"a": va})
    got = eval_predicate_mask(t, col("a") >= lit(2**40))
    np.testing.assert_array_equal(got, (a >= 2**40) & va)


# -- index build + rewritten query equality ----------------------------------

def test_create_index_and_filter_equality_with_nulls(session, hs, tmp_path):
    root, _ = _nullable_parquet(tmp_path)
    df = session.parquet(root)
    hs.create_index(df, IndexConfig("nullidx", ["key"], ["value", "name"]))

    queries = [
        df.filter(col("key") == 17).select("key", "value"),
        df.filter((col("key") > 30) & (col("value") < 0.5)).select("key", "value", "name"),
        df.filter((col("name") == "n7") | (col("key") <= 5)).select("name", "key"),
    ]
    for q in queries:
        session.enable_hyperspace()
        opt = session.optimized_plan(q)
        assert any(s.bucket_spec is not None for s in opt.leaves()), "rewrite missed"
        got = _canon(session.to_pandas(q))
        session.disable_hyperspace()
        want = _canon(session.to_pandas(q))
        frames_equal(got, want)


def test_string_index_key_with_nulls(session, hs, tmp_path):
    root, _ = _nullable_parquet(tmp_path)
    df = session.parquet(root)
    hs.create_index(df, IndexConfig("sidx", ["name"], ["key"]))
    q = df.filter(col("name") == "n3").select("name", "key")
    session.enable_hyperspace()
    assert any(s.bucket_spec is not None for s in session.optimized_plan(q).leaves())
    got = _canon(session.to_pandas(q))
    session.disable_hyperspace()
    frames_equal(got, _canon(session.to_pandas(q)))


# -- joins: null keys never match -------------------------------------------

def test_join_null_keys_never_match(session, hs, tmp_path):
    rng = np.random.default_rng(21)
    n = 600
    lkey = [None if rng.random() < 0.2 else int(k) for k in rng.integers(0, 40, n)]
    lval = rng.standard_normal(n)
    left = pa.table({"k": pa.array(lkey, type=pa.int64()), "lv": pa.array(lval)})
    m = 200
    rkey = [None if rng.random() < 0.2 else int(k) for k in rng.integers(0, 40, m)]
    rpay = [f"p{i}" for i in range(m)]
    right = pa.table({"k": pa.array(rkey, type=pa.int64()), "rp": pa.array(rpay)})
    lroot = tmp_path / "jl"
    rroot = tmp_path / "jr"
    lroot.mkdir()
    rroot.mkdir()
    pq.write_table(left, lroot / "l.parquet")
    pq.write_table(right, rroot / "r.parquet")

    ldf = session.parquet(lroot)
    rdf = session.parquet(rroot)
    hs.create_index(ldf, IndexConfig("jln", ["k"], ["lv"]))
    hs.create_index(rdf, IndexConfig("jrn", ["k"], ["rp"]))

    q = ldf.select("k", "lv").join(rdf.select("k", "rp"), ["k"])
    session.enable_hyperspace()
    opt = session.optimized_plan(q)
    assert all(s.bucket_spec is not None for s in opt.leaves()), "join rewrite missed"
    got = _canon(session.to_pandas(q))
    session.disable_hyperspace()
    raw = _canon(session.to_pandas(q))
    frames_equal(got, raw)

    # SQL semantics: rows with null keys on either side never appear.
    lpd = left.to_pandas().dropna(subset=["k"])
    rpd = right.to_pandas().dropna(subset=["k"])
    want = lpd.merge(rpd, on="k")
    assert len(got) == len(want)
    frames_equal(got, want)


def test_join_payload_nulls_survive(session, hs, tmp_path):
    left = pa.table(
        {
            "k": pa.array([1, 2, 3], type=pa.int64()),
            "lv": pa.array([None, 1.5, None], type=pa.float64()),
        }
    )
    right = pa.table(
        {
            "k": pa.array([1, 2, 3], type=pa.int64()),
            "rp": pa.array(["x", None, "z"]),
        }
    )
    lroot = tmp_path / "pl"
    rroot = tmp_path / "pr"
    lroot.mkdir()
    rroot.mkdir()
    pq.write_table(left, lroot / "l.parquet")
    pq.write_table(right, rroot / "r.parquet")
    ldf = session.parquet(lroot)
    rdf = session.parquet(rroot)
    q = ldf.join(rdf, ["k"])
    got = _canon(session.to_pandas(q)).sort_values("k").reset_index(drop=True)
    assert got["lv"].isna().tolist() == [True, False, True]
    assert got["rp"].isna().tolist() == [False, True, False]


def test_nullable_bool_column_round_trip():
    t = pa.table({"b": pa.array([True, None, False]), "k": pa.array([1, 2, 3], type=pa.int64())})
    ct = ColumnTable.from_arrow(t)
    assert ct.validity["b"].tolist() == [True, False, True]
    back = ct.to_arrow().to_pandas()
    assert back["b"].tolist()[0] is True and pd.isna(back["b"].tolist()[1])
