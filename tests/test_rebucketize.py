"""Query-time re-bucketing exchange + bucket-preserving join outputs.

SURVEY §2.3's "single re-bucketing all-to-all when bucket counts don't
match" and the ranker's mismatched-pair case
(index/rankers/JoinIndexRanker.scala:31-34): one side bucketed on its
join keys pairs with an arbitrary materialized side via an on-the-fly
hash + counting-sort exchange (host) / device sort (device venue); an
inner join's bucket-major output reuses its grouping in a later join on
the same keys with no exchange at all.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col, lit
from hyperspace_tpu.config import JOIN_REBUCKETIZE, JOIN_VENUE

NB = 8


@pytest.fixture
def tables(tmp_path):
    rng = np.random.default_rng(23)
    n = 30_000
    fact = pd.DataFrame(
        {
            "k": rng.integers(0, 900, n).astype(np.int64),
            "v": rng.normal(size=n).round(4),
        }
    )
    dim = pd.DataFrame(
        {
            "k": np.arange(900, dtype=np.int64),
            "g": (np.arange(900) % 7).astype(np.int64),
            "tag": np.array(["a", "b", "c"], dtype=object)[np.arange(900) % 3],
        }
    )
    for name, df in (("fact", fact), ("dim", dim)):
        (tmp_path / name).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=NB)
    hs = Hyperspace(session)
    f = session.parquet(tmp_path / "fact")
    d = session.parquet(tmp_path / "dim")
    hs.create_index(f, IndexConfig("f_k", ["k"], ["v"]))
    session.enable_hyperspace()
    return session, f, d, fact, dim


@pytest.mark.parametrize("venue", ["host", "device"])
def test_rebucketize_one_indexed_side(tables, venue):
    """The dim side is NOT indexed (an aggregate output, so no scan to
    rewrite): forcing the exchange pairs it bucket-parallel against the
    fact index on both venues, results equal pandas."""
    session, f, d, fact, dim = tables
    session.conf.set(JOIN_REBUCKETIZE, "force")
    session.conf.set(JOIN_VENUE, venue)
    dim_agg = d.aggregate(["k"], [AggSpec.of("sum", "g", "sg")])  # non-scan side
    q = f.join(dim_agg, ["k"]).aggregate([], [
        AggSpec.of("sum", "v", "sv"), AggSpec.of("count", None, "n"),
        AggSpec.of("sum", "sg", "ssg"),
    ])
    got = session.to_pandas(q)
    stats = session.last_query_stats
    assert stats["join_path"] in ("rebucketized-aligned",), stats
    exp = fact.merge(dim.groupby("k").g.sum().rename("sg").reset_index(), on="k")
    assert int(got.loc[0, "n"]) == len(exp)
    np.testing.assert_allclose(got.loc[0, "sv"], exp.v.sum(), rtol=1e-9)
    np.testing.assert_allclose(got.loc[0, "ssg"], exp.sg.sum(), rtol=1e-9)
    kern = stats.get("exchange_kernel", "")
    if venue == "device":
        assert kern == "device-sort-exchange"
    else:
        assert kern.startswith("host-")


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_rebucketize_join_types_match_pandas(tables, how):
    session, f, d, fact, dim = tables
    session.conf.set(JOIN_REBUCKETIZE, "force")
    half = d.filter(col("k") < lit(450)).aggregate(
        ["k"], [AggSpec.of("count", None, "dn")]
    )
    q = f.join(half, ["k"], how=how)
    got = session.to_pandas(q)
    assert session.last_query_stats["join_path"] == "rebucketized-aligned"
    dk = set(range(450))
    if how == "semi":
        exp_n = int(fact.k.isin(dk).sum())
    elif how == "anti":
        exp_n = int((~fact.k.isin(dk)).sum())
    else:  # inner and left: dim keys unique, so inner = matched fact rows
        matched = int(fact.k.isin(dk).sum())
        exp_n = matched if how == "inner" else len(fact)
    assert len(got) == exp_n, (how, len(got), exp_n)


def test_bucket_preserved_chain_same_key(tables):
    """Join(Join(fact, dim1), dim2) on the SAME key: the inner aligned
    join's bucket-major output re-pairs against the second index side
    with NO exchange (preserved grouping)."""
    session, f, d, fact, dim = tables
    session.conf.set(JOIN_REBUCKETIZE, "force")
    d1 = d.select("k", "g").aggregate(["k"], [AggSpec.of("sum", "g", "sg")])
    inner = f.join(d1, ["k"])  # rebucketized-aligned, inner => preserved
    d2 = d.select("k", "tag").aggregate(["k"], [AggSpec.of("count", None, "c2")])
    q = inner.join(d2, ["k"]).aggregate([], [AggSpec.of("count", None, "n")])
    got = session.to_pandas(q)
    phys = repr(session.last_physical_plan)
    assert "preserved" in phys, phys
    assert int(got.loc[0, "n"]) == len(fact)  # dim keys cover all fact keys


def test_star_chain_every_join_bucket_parallel(tmp_path):
    """A 3-table star chain (the q27 shape) where every dimension is
    indexed: the innermost join rides the both-aligned zero-exchange
    path; the SECOND dimension join re-bucketizes the (differently
    keyed) join output into that dimension's bucket layout — no join
    falls back to single-partition."""
    rng = np.random.default_rng(41)
    n = 20_000
    fact = pd.DataFrame(
        {
            "k1": rng.integers(0, 400, n).astype(np.int64),
            "k2": rng.integers(0, 300, n).astype(np.int64),
            "v": rng.normal(size=n).round(4),
        }
    )
    dima = pd.DataFrame({"k1": np.arange(400, dtype=np.int64), "a": np.arange(400) % 5})
    dimb = pd.DataFrame({"k2": np.arange(300, dtype=np.int64), "b": np.arange(300) % 7})
    for name, df in (("fact", fact), ("dima", dima), ("dimb", dimb)):
        (tmp_path / name).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=NB)
    hs = Hyperspace(session)
    f = session.parquet(tmp_path / "fact")
    da = session.parquet(tmp_path / "dima")
    db = session.parquet(tmp_path / "dimb")
    hs.create_index(f, IndexConfig("f_k1", ["k1"], ["k2", "v"]))
    hs.create_index(da, IndexConfig("da_k1", ["k1"], ["a"]))
    hs.create_index(db, IndexConfig("db_k2", ["k2"], ["b"]))
    session.enable_hyperspace()
    session.conf.set(JOIN_REBUCKETIZE, "force")
    q = (
        f.join(da.filter(col("a") == lit(2)), ["k1"])
        .join(db, ["k2"])
        .aggregate(["b"], [AggSpec.of("sum", "v", "sv"), AggSpec.of("count", None, "n")])
    )
    got = session.to_pandas(q).sort_values("b").reset_index(drop=True)
    phys = repr(session.last_physical_plan)
    assert "zero-exchange-aligned" in phys, phys
    assert "rebucketized-aligned" in phys, phys
    assert "single-partition" not in phys, phys
    j = fact.merge(dima[dima.a == 2], on="k1").merge(dimb, on="k2")
    exp = j.groupby("b").agg(sv=("v", "sum"), n=("v", "size")).reset_index()
    np.testing.assert_allclose(got.sv.to_numpy(), exp.sv.to_numpy(), rtol=1e-9)
    np.testing.assert_array_equal(got.n.to_numpy(), exp.n.to_numpy())


def test_rebucketize_off_keeps_single_partition(tables):
    session, f, d, fact, dim = tables
    session.conf.set(JOIN_REBUCKETIZE, "off")
    session.conf.set("hyperspace.join.broadcast.maxRows", 0)
    dim_agg = d.aggregate(["k"], [AggSpec.of("sum", "g", "sg")])
    q = f.join(dim_agg, ["k"]).aggregate([], [AggSpec.of("count", None, "n")])
    got = session.to_pandas(q)
    assert session.last_query_stats["join_path"] == "single-partition"
    assert int(got.loc[0, "n"]) == len(fact)


def test_dtype_mismatched_indexes_fall_back_not_wrong(tmp_path):
    """Two indexes bucketed on int32 vs int64 key columns hash equal
    values into DIFFERENT buckets — the aligned path must refuse the
    pairing (correctness guard), falling back to a general join with
    identical results."""
    n = 5_000
    rng = np.random.default_rng(5)
    left = pd.DataFrame({"k": rng.integers(0, 300, n).astype(np.int32), "a": rng.normal(size=n)})
    right = pd.DataFrame({"k2": np.arange(300, dtype=np.int64), "b": np.arange(300) * 2.0})
    for name, df in (("l", left), ("r", right)):
        (tmp_path / name).mkdir()
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / name / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    l = session.parquet(tmp_path / "l")
    r = session.parquet(tmp_path / "r")
    hs.create_index(l, IndexConfig("l_k", ["k"], ["a"]))
    hs.create_index(r, IndexConfig("r_k", ["k2"], ["b"]))
    session.enable_hyperspace()
    q = l.join(r, ["k"], ["k2"]).aggregate([], [AggSpec.of("count", None, "n")])
    got = session.to_pandas(q)
    assert session.last_query_stats["join_path"] != "zero-exchange-aligned"
    assert int(got.loc[0, "n"]) == len(left)  # every key matches
