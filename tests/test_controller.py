"""Self-driving operations controller (serve/controller.py,
docs/fault_tolerance.md "self-driving operations"): every trigger→action
mapping, hysteresis across verdict flicker, per-actuation cooldown,
actuation-budget exhaustion degrading to observe-only, the kill switch
disarming mid-loop, and CrashPoint at the `controller.actuate` fault
point unwinding with zero partial state — all driven by an injectable
clock (no sleeps on the decision paths)."""

import json
import threading
import time
import urllib.request

import pytest

from hyperspace_tpu import faults, stats
from hyperspace_tpu.analysis.duradomain import TORN_WINDOWS
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.faults import CrashPoint
from hyperspace_tpu.obs import events, metrics, slo
from hyperspace_tpu.obs import http as obs_http
from hyperspace_tpu.serve.controller import OpsController
from hyperspace_tpu.serve.fleet.quota import TenantQuotas
from hyperspace_tpu.serve.scheduler import QueryServer


class FakeSession:
    """The session surface the controller reads: conf + the lock-guarded
    index_health map (the test_health_plane.FakeSession shape)."""

    def __init__(self, **conf_overrides):
        self.conf = HyperspaceConf()
        self.conf.set("hyperspace.controller.enabled", "true")
        for k, v in conf_overrides.items():
            self.conf.set(k, v)
        self._state_lock = threading.RLock()
        self.index_health = {}


class FakeLifecycle:
    def __init__(self, log):
        self._log = log

    def sweep(self):
        self._log.append(("sweep",))
        return {"applied": [], "skipped": [], "failed": []}


class FakeHyperspace:
    """The facade surface the controller actuates through; records every
    call so tests pin the trigger→protocol mapping."""

    def __init__(self, session):
        self.session = session
        self.calls = []
        self.fail_next = None  # exception type to raise on the next call

    def _maybe_fail(self):
        if self.fail_next is not None:
            exc, self.fail_next = self.fail_next, None
            raise exc("injected facade failure")

    def recover(self, name=None):
        self._maybe_fail()
        self.calls.append(("recover", name))
        with self.session._state_lock:
            for root in [r for r in self.session.index_health
                         if name is None or r.endswith(name)]:
                self.session.index_health.pop(root)
        return {}

    def refresh_index(self, name, mode="full"):
        self._maybe_fail()
        self.calls.append(("refresh", name, mode))

    def lifecycle(self):
        return FakeLifecycle(self.calls)


def _serve_counters():
    return (
        metrics.counter("serve.completed"),
        metrics.counter("serve.failed"),
        metrics.counter("serve.timeouts"),
        metrics.counter("serve.cancelled"),
        metrics.histogram("serve.latency.seconds"),
    )


def _controller(server=None, **conf_overrides):
    session = FakeSession(**conf_overrides)
    hs = FakeHyperspace(session)
    return hs, OpsController(hs, server=server, clock=lambda: 0.0)


def _drive_page(completed, failed, ctrl, t0=0.0):
    """Walk the controller's own sampling into a sustained availability
    page: baseline traffic, then a hard failure burst. Returns the time
    of the last (second consecutive page) step."""
    completed.inc(10_000)
    ctrl.step(now=t0)
    ctrl.step(now=t0 + 4000.0)
    failed.inc(3_000)
    ctrl.step(now=t0 + 4030.0)  # page tick 1: hysteresis holds
    ctrl.step(now=t0 + 4031.0)  # page tick 2: actuate
    return t0 + 4031.0


def _actuation_events(action=None):
    out = [e for e in events.recent() if e["name"] == "controller.actuation"]
    if action is not None:
        out = [e for e in out if e["fields"]["action"] == action]
    return out


@pytest.fixture
def shed_server():
    """A real QueryServer (DI run_fn) + real TenantQuotas — the overload
    actuation surface."""
    session = FakeSession()
    quotas = TenantQuotas(rate=10.0, burst=10.0)
    server = QueryServer(
        session, workers=1, max_queue_depth=32, run_fn=lambda p: p, quotas=quotas
    )
    try:
        yield server
    finally:
        server.shutdown()


# -- trigger -> action mappings --------------------------------------------


def test_slo_page_engages_shed_and_quota_tighten(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    assert shed_server.get_shed_depth() == 32
    _drive_page(completed, failed, ctrl)
    assert shed_server.get_shed_depth() == 16  # 0.5 x maxQueueDepth
    assert shed_server.quotas.throttle() == pytest.approx(0.5)
    snap = ctrl.snapshot()
    assert snap["engaged"] is True
    assert snap["verdicts"]["serve.availability"] == "page"
    assert stats.get("controller.actuations") == 1
    (evt,) = _actuation_events("shed.engage")
    assert evt["fields"]["trigger"] == "slo.page"
    assert evt["fields"]["outcome"] == "executed"
    assert metrics.REGISTRY.get("controller.engaged").value == 1


def test_recovery_releases_overrides_after_recovery_ticks(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    t = _drive_page(completed, failed, ctrl)
    # clean traffic pushes the burst out of the page windows
    completed.inc(80_000)
    ctrl.step(now=t + 70.0)  # non-page tick 1: still engaged
    assert ctrl.snapshot()["engaged"] is True
    ctrl.step(now=t + 71.0)  # non-page tick 2: release
    assert ctrl.snapshot()["engaged"] is False
    assert shed_server.get_shed_depth() == 32
    assert shed_server.quotas.throttle() == pytest.approx(1.0)
    (evt,) = _actuation_events("shed.release")
    assert evt["fields"]["trigger"] == "slo.recovered"
    assert metrics.REGISTRY.get("controller.engaged").value == 0


def test_quarantine_triggers_recover_then_gated_rebuild():
    _serve_counters()
    hs, ctrl = _controller()
    with hs.session._state_lock:
        hs.session.index_health["/idx/myidx"] = {"reason": "torn bucket"}
    ctrl.step(now=0.0)
    ctrl.step(now=1.0)
    assert hs.calls == [("recover", "myidx"), ("refresh", "myidx", "full")]
    assert hs.session.index_health == {}
    assert stats.get("controller.heals") == 1
    (evt,) = _actuation_events("heal.myidx")
    assert evt["fields"]["trigger"] == "index.quarantined"


def test_heal_rebuild_gate_off_limits_heal_to_recover():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.heal.rebuild": "false"})
    with hs.session._state_lock:
        hs.session.index_health["/idx/a"] = {"reason": "x"}
    ctrl.step(now=0.0)
    ctrl.step(now=1.0)
    assert hs.calls == [("recover", "a")]


def test_demotion_cluster_triggers_advisor_sweep():
    _serve_counters()
    hs, ctrl = _controller()
    demoted = events.declare("advisor.routing.demoted")
    for i in range(3):
        demoted.emit(signature=f"s{i}")
    ctrl.step(now=0.0)
    ctrl.step(now=1.0)
    assert ("sweep",) in hs.calls
    (evt,) = _actuation_events("advisor.sweep")
    assert evt["fields"]["trigger"] == "routing.demotion_cluster"
    assert evt["fields"]["demotions"] == 3
    # evidence consumed: no second sweep without fresh demotions
    ctrl.step(now=100.0)
    assert hs.calls.count(("sweep",)) == 1


def test_demotions_below_cluster_size_or_outside_window_never_sweep():
    _serve_counters()
    hs, ctrl = _controller()
    demoted = events.declare("advisor.routing.demoted")
    demoted.emit(signature="a")
    demoted.emit(signature="b")
    ctrl.step(now=0.0)  # 2 < clusterSize 3
    assert ("sweep",) not in hs.calls
    # the third arrives after the first two aged out of the window
    demoted.emit(signature="c")
    ctrl.step(now=1000.0)  # window 300s: earlier pair expired
    assert ("sweep",) not in hs.calls


# -- back off background work while SLOs burn -------------------------------


def test_heal_and_sweep_defer_while_burning(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    t = _drive_page(completed, failed, ctrl)
    assert ctrl.snapshot()["engaged"] is True
    # the quarantine lands MID-burn: rebuild-class work must wait
    with hs.session._state_lock:
        hs.session.index_health["/idx/hot"] = {"reason": "x"}
    ctrl.step(now=t + 1.0)  # still paging
    assert not any(c[0] in ("recover", "refresh") for c in hs.calls)
    assert not any(c[0] in ("recover", "refresh") for c in hs.calls)
    backoffs = [e for e in events.recent() if e["name"] == "controller.backoff"]
    assert {e["fields"]["action"] for e in backoffs} == {"heal"}
    assert stats.get("controller.deferred") >= 1
    # burn clears -> the held-back heal executes
    completed.inc(80_000)
    ctrl.step(now=t + 70.0)
    ctrl.step(now=t + 71.0)
    ctrl.step(now=t + 72.0)
    assert ("recover", "hot") in hs.calls


# -- hysteresis / cooldown (no flapping) ------------------------------------


def test_single_verdict_flicker_never_actuates(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    completed.inc(10_000)
    ctrl.step(now=0.0)
    ctrl.step(now=4000.0)
    failed.inc(3_000)
    ctrl.step(now=4030.0)  # page tick 1 of hysteresis 2
    assert ctrl.snapshot()["engaged"] is False
    assert shed_server.get_shed_depth() == 32
    # flicker back to ok: the page streak resets
    completed.inc(80_000)
    ctrl.step(now=4100.0)
    assert ctrl.snapshot()["page_ticks"] == 0
    assert ctrl.snapshot()["engaged"] is False
    assert _actuation_events() == []


def test_heal_failure_cools_down_before_retry():
    _serve_counters()
    hs, ctrl = _controller()
    with hs.session._state_lock:
        hs.session.index_health["/idx/bad"] = {"reason": "x"}
    hs.fail_next = RuntimeError
    ctrl.step(now=0.0)
    assert stats.get("controller.actuation_failures") == 1
    failed_events = [e for e in events.recent()
                     if e["name"] == "controller.actuation_failed"]
    assert failed_events and failed_events[0]["fields"]["action"] == "heal.bad"
    # still quarantined; inside the 30s cooldown nothing retries
    ctrl.step(now=5.0)
    assert hs.calls == []
    assert stats.get("controller.deferred") >= 1
    # past the cooldown the heal retries and succeeds
    ctrl.step(now=31.0)
    assert ("recover", "bad") in hs.calls


# -- actuation budget --------------------------------------------------------


def test_budget_exhaustion_degrades_to_observe_only(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(
        server=shed_server, **{"hyperspace.controller.actuationBudget": 1}
    )
    t = _drive_page(completed, failed, ctrl)  # spends the whole budget
    assert ctrl.snapshot()["budget_remaining"] == 0
    # release stays free: the system is always left as found
    completed.inc(80_000)
    ctrl.step(now=t + 70.0)
    ctrl.step(now=t + 71.0)
    assert shed_server.get_shed_depth() == 32
    # a new trigger is observed, audited, and NOT executed
    with hs.session._state_lock:
        hs.session.index_health["/idx/q"] = {"reason": "x"}
    ctrl.step(now=t + 72.0)
    assert not any(c[0] == "recover" for c in hs.calls)
    assert ctrl.snapshot()["mode"] == "observe_only"
    observe = [e for e in events.recent() if e["name"] == "controller.observe_only"]
    assert len(observe) == 1 and observe[0]["severity"] == "error"
    suppressed = _actuation_events("heal.q")
    assert suppressed and suppressed[0]["fields"]["outcome"] == "observe_only"
    # announced once, not per tick
    ctrl.step(now=t + 103.0)
    assert len([e for e in events.recent()
                if e["name"] == "controller.observe_only"]) == 1


# -- kill switch -------------------------------------------------------------


def test_kill_switch_disarms_mid_loop_and_releases(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    _drive_page(completed, failed, ctrl)
    assert shed_server.get_shed_depth() == 16
    ticks_before = stats.get("controller.ticks")
    hs.session.conf.set("hyperspace.controller.enabled", "false")
    with hs.session._state_lock:
        hs.session.index_health["/idx/x"] = {"reason": "x"}
    snap = ctrl.step(now=5000.0)
    # overrides released, nothing else observed or actuated
    assert shed_server.get_shed_depth() == 32
    assert shed_server.quotas.throttle() == pytest.approx(1.0)
    assert snap["mode"] == "disabled" and snap["engaged"] is False
    assert stats.get("controller.ticks") == ticks_before
    assert not any(c[0] == "recover" for c in hs.calls)
    (evt,) = _actuation_events("shed.release")
    assert evt["fields"]["trigger"] == "kill_switch"


def test_disabled_by_default_controller_never_acts():
    session = FakeSession()
    session.conf.set("hyperspace.controller.enabled", "false")
    hs = FakeHyperspace(session)
    ctrl = OpsController(hs, clock=lambda: 0.0)
    with session._state_lock:
        session.index_health["/idx/x"] = {"reason": "x"}
    snap = ctrl.step(now=0.0)
    assert snap["mode"] == "disabled"
    assert hs.calls == [] and stats.get("controller.ticks") == 0


# -- crash safety (controller.actuate fault point) ---------------------------


def test_crashpoint_at_actuate_unwinds_with_zero_partial_state(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    completed.inc(10_000)
    ctrl.step(now=0.0)
    ctrl.step(now=4000.0)
    failed.inc(3_000)
    ctrl.step(now=4030.0)
    with faults.injected("controller.actuate", crash=True):
        with pytest.raises(CrashPoint):
            ctrl.step(now=4031.0)  # the engage tick dies BEFORE mutating
    assert shed_server.get_shed_depth() == 32  # no partial actuation
    assert shed_server.quotas.throttle() == pytest.approx(1.0)
    assert ctrl.snapshot()["engaged"] is False
    assert stats.get("controller.actuations") == 0
    # the "next process": a clean retry actuates normally
    ctrl.step(now=4032.0)
    assert shed_server.get_shed_depth() == 16


def test_transient_fault_at_actuate_surfaces_typed():
    _serve_counters()
    hs, ctrl = _controller()
    with hs.session._state_lock:
        hs.session.index_health["/idx/t"] = {"reason": "x"}
    with faults.injected("controller.actuate", times=1):
        with pytest.raises(OSError):
            ctrl.step(now=0.0)
    assert hs.calls == []  # the fault fired before any mutation
    ctrl.step(now=1.0)
    assert ("recover", "t") in hs.calls


# -- loop + healthz surface --------------------------------------------------


def test_start_stop_loop_ticks_and_stops():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.intervalSeconds": 0.01})
    ctrl._clock = time.monotonic
    with ctrl.start():
        deadline = time.monotonic() + 5.0
        while stats.get("controller.ticks") < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert stats.get("controller.ticks") >= 3
    ticks = stats.get("controller.ticks")
    time.sleep(0.05)
    assert stats.get("controller.ticks") == ticks  # stopped means stopped


def test_loop_survives_a_failing_step():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.intervalSeconds": 0.01})
    ctrl._clock = time.monotonic
    boom = {"n": 0}

    real_step = ctrl.step

    def flaky_step(now=None):
        boom["n"] += 1
        if boom["n"] == 1:
            raise RuntimeError("transient controller bug")
        return real_step(now)

    ctrl.step = flaky_step
    with ctrl.start():
        deadline = time.monotonic() + 5.0
        while boom["n"] < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert boom["n"] >= 3  # the loop kept reconciling past the failure
    failed_events = [e for e in events.recent()
                     if e["name"] == "controller.actuation_failed"]
    assert any(e["fields"]["action"] == "step" for e in failed_events)


def test_healthz_surfaces_controller_verdict():
    _serve_counters()
    hs, ctrl = _controller()
    endpoint = obs_http.HealthServer().start()
    try:
        endpoint.attach_controller(ctrl)
        ctrl.step(now=0.0)
        with urllib.request.urlopen(endpoint.url("/healthz"), timeout=10) as r:
            doc = json.loads(r.read().decode())
        (view,) = doc["controller"]
        assert view["enabled"] is True
        assert view["mode"] == "actuate"
        assert view["budget_remaining"] == 32
        assert "verdicts" in view
    finally:
        endpoint.stop()


def test_start_registers_with_shared_health_endpoint():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.intervalSeconds": 0.05})
    endpoint = obs_http.acquire()
    try:
        ctrl._clock = time.monotonic
        with ctrl.start():
            with urllib.request.urlopen(endpoint.url("/healthz"), timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert len(doc["controller"]) == 1
    finally:
        obs_http.release()


# -- fleet coordination: lease-elected healing -------------------------------


def _fleet_controller(tmp_path, member_id, **conf_overrides):
    """A controller whose FakeSession points at a real (tmp) store dir,
    so `_fleet_root` discovers `<system.path>/_fleet` and heals go
    through the single-flight lease."""
    session = FakeSession(**conf_overrides)
    session.conf.set("hyperspace.system.path", str(tmp_path))
    hs = FakeHyperspace(session)
    return hs, OpsController(hs, clock=lambda: 0.0, member_id=member_id)


def _heal_lease_path(tmp_path, name="shared"):
    from hyperspace_tpu.serve.fleet.singleflight import key_name

    return tmp_path / "_fleet" / "heal" / f"{key_name(f'heal.{name}')}.lease"


def test_two_controllers_one_store_exactly_one_heal(tmp_path):
    _serve_counters()
    hs_a, ctrl_a = _fleet_controller(tmp_path, "member-a")
    hs_b, ctrl_b = _fleet_controller(tmp_path, "member-b")
    for hs in (hs_a, hs_b):
        with hs.session._state_lock:
            hs.session.index_health["/idx/shared"] = {"reason": "torn"}
    ctrl_a.step(now=0.0)
    ctrl_b.step(now=0.0)
    # exactly ONE member (the lease leader) ran recover + rebuild …
    assert hs_a.calls == [("recover", "shared"), ("refresh", "shared", "full")]
    # … the follower lifted its LOCAL quarantine via recover only
    assert hs_b.calls == [("recover", "shared")]
    assert hs_a.session.index_health == {} and hs_b.session.index_health == {}
    assert stats.get("controller.heals") == 1
    (led,) = [e for e in _actuation_events("heal.shared")
              if e["fields"]["outcome"] == "executed"]
    (obs,) = [e for e in _actuation_events("heal.shared")
              if e["fields"]["outcome"] == "observed"]
    assert led["fields"]["member"] == "member-a"
    assert obs["fields"]["member"] == "member-b"
    # the follower's observation spent no budget and no heal count
    assert ctrl_b.snapshot()["budget_remaining"] == 32
    assert ctrl_a.snapshot()["budget_remaining"] == 31
    # the published marker carries the leader + generation
    marker = json.loads((tmp_path / "_fleet" / "heal" / "shared.json").read_text())
    assert marker == {"index": "shared", "member": "member-a", "generation": 1}


def test_sigkilled_healer_lease_is_reaped_and_taken_over(tmp_path):
    _serve_counters()
    hs, ctrl = _fleet_controller(
        tmp_path, "survivor", **{"hyperspace.fleet.lease.seconds": 5.0}
    )
    with hs.session._state_lock:
        hs.session.index_health["/idx/shared"] = {"reason": "torn"}
    # a healer died (SIGKILL) holding the heal lease: its epoch is
    # beyond the TTL, so the surviving member must reap it and take over
    lease = _heal_lease_path(tmp_path)
    lease.parent.mkdir(parents=True, exist_ok=True)
    lease.write_text(f"{time.time() - 120.0:.6f}:999999:dead")
    takeovers0 = stats.get("fleet.singleflight.takeovers")
    ctrl.step(now=0.0)
    assert ("recover", "shared") in hs.calls
    assert stats.get("fleet.singleflight.takeovers") == takeovers0 + 1
    assert stats.get("controller.heals") == 1
    assert not lease.exists()
    takeover = [e for e in events.recent()
                if e["name"] == "fleet.singleflight.takeover"]
    assert takeover and takeover[0]["fields"]["key"] == "heal.shared"


def test_write_marker_publishes_atomically_or_not_at_all(tmp_path):
    """Atomic-publish completeness (HSL027 regression): a marker write
    that dies before the rename leaves NO marker and no tmp litter — a
    follower can never read a torn or empty heal document."""
    import os as _os

    heal_dir = tmp_path / "heal"
    heal_dir.mkdir()
    marker = heal_dir / "shared.json"

    def boom(fd):
        raise OSError("disk on fire")

    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(_os, "fsync", boom)
        with pytest.raises(OSError):
            OpsController._write_marker(marker, {"index": "shared",
                                                 "generation": 1})
    assert not marker.exists()
    assert list(heal_dir.iterdir()) == []  # the torn tmp was reclaimed
    OpsController._write_marker(marker, {"index": "shared", "generation": 1})
    assert json.loads(marker.read_text())["generation"] == 1
    assert [p.name for p in heal_dir.iterdir()] == ["shared.json"]


def _drive_marker_after_heal(tmp_path, point):
    """Kill between the shared-bytes heal and the generation-marker
    publish: the bytes are healed, no marker exists, and the next
    member to see the quarantine leads a full idempotent re-heal."""
    _serve_counters()
    hs_a, ctrl_a = _fleet_controller(tmp_path, "member-a")
    with hs_a.session._state_lock:
        hs_a.session.index_health["/idx/shared"] = {"reason": "torn"}
    faults.inject(point, crash=True, at_call=1)
    try:
        with pytest.raises(CrashPoint):
            ctrl_a.step(now=0.0)
    finally:
        faults.reset()
    # First half of the window held: the leader healed the shared bytes
    # (recover + gated rebuild ran, its local quarantine lifted) …
    assert hs_a.calls == [("recover", "shared"), ("refresh", "shared", "full")]
    # … and the second half never ran: no marker was published.
    marker = tmp_path / "_fleet" / "heal" / "shared.json"
    assert not marker.exists()
    # Convergence: a surviving member still quarantined sees NO fresh
    # marker, so it leads its own heal — recover() is idempotent over
    # the already-healed bytes — and publishes generation 1.
    hs_b, ctrl_b = _fleet_controller(tmp_path, "member-b")
    with hs_b.session._state_lock:
        hs_b.session.index_health["/idx/shared"] = {"reason": "torn"}
    ctrl_b.step(now=0.0)
    assert hs_b.calls == [("recover", "shared"), ("refresh", "shared", "full")]
    assert hs_b.session.index_health == {}
    doc = json.loads(marker.read_text())
    assert doc["member"] == "member-b" and doc["generation"] == 1


@pytest.mark.parametrize(
    "window", sorted(k for k in TORN_WINDOWS if k.startswith("controller."))
)
def test_kill_inside_torn_window_converges(window, tmp_path):
    """Driven BY NAME from `analysis.duradomain.TORN_WINDOWS`: a
    controller window added to the registry without a driver here fails
    with a KeyError, so the crash sweep tracks the proven protocols."""
    drivers = {"controller.marker_after_heal": _drive_marker_after_heal}
    _fn, _first, _second, point, why = TORN_WINDOWS[window]
    assert point in faults.KNOWN_POINTS, why
    drivers[window](tmp_path, point)


def test_restarted_member_observes_stale_marker_once_then_heals(tmp_path):
    """A fresh controller (restart: empty generation memory) observes a
    pre-existing marker at most ONCE; when the quarantine persists past
    the cooldown it leads a real heal and bumps the generation."""
    _serve_counters()
    hs, ctrl = _fleet_controller(tmp_path, "restarted")
    marker = tmp_path / "_fleet" / "heal" / "shared.json"
    marker.parent.mkdir(parents=True, exist_ok=True)
    marker.write_text(json.dumps(
        {"index": "shared", "member": "old-member", "generation": 3}
    ))
    with hs.session._state_lock:
        hs.session.index_health["/idx/shared"] = {"reason": "torn"}
    ctrl.step(now=0.0)
    assert hs.calls == [("recover", "shared")]  # observed, recover only
    # the corruption was NOT actually healed: it comes back
    with hs.session._state_lock:
        hs.session.index_health["/idx/shared"] = {"reason": "torn again"}
    ctrl.step(now=10.0)  # inside the heal cooldown: deferred
    assert hs.calls == [("recover", "shared")]
    ctrl.step(now=31.0)  # past cooldown: marker gen 3 already seen -> LEAD
    assert hs.calls == [
        ("recover", "shared"),
        ("recover", "shared"), ("refresh", "shared", "full"),
    ]
    assert json.loads(marker.read_text())["generation"] == 4
    assert json.loads(marker.read_text())["member"] == "restarted"


def test_stop_mid_heal_releases_the_held_lease(tmp_path):
    """stop() while an in-flight heal holds the single-flight lease must
    release it BEFORE joining — a controller stopped mid-heal never
    leaves a live lease wedging the fleet for TTL seconds."""
    _serve_counters()
    hs, ctrl = _fleet_controller(tmp_path, "stopping")
    entered = threading.Event()
    unblock = threading.Event()

    def slow_refresh(name, mode="full"):
        hs.calls.append(("refresh", name, mode))
        entered.set()
        assert unblock.wait(timeout=30.0)

    hs.refresh_index = slow_refresh
    with hs.session._state_lock:
        hs.session.index_health["/idx/shared"] = {"reason": "torn"}
    t = threading.Thread(target=lambda: ctrl.step(now=0.0))
    t.start()
    try:
        assert entered.wait(timeout=30.0)  # the heal is mid-build, lease held
        lease = _heal_lease_path(tmp_path)
        assert lease.exists()
        ctrl.stop(timeout=0.5)
        assert not lease.exists()  # released BEFORE the join, not after TTL
    finally:
        unblock.set()
        t.join(timeout=30.0)
    assert not t.is_alive()


def test_heal_coordination_gate_off_keeps_heals_local(tmp_path):
    _serve_counters()
    hs, ctrl = _fleet_controller(
        tmp_path, "solo", **{"hyperspace.controller.heal.coordinate": "false"}
    )
    with hs.session._state_lock:
        hs.session.index_health["/idx/shared"] = {"reason": "torn"}
    ctrl.step(now=0.0)
    assert hs.calls == [("recover", "shared"), ("refresh", "shared", "full")]
    # No coordination artifacts: no heal marker, no lease. (The incident
    # flight recorder may still create `_fleet/incidents` — it is not
    # gated by heal.coordinate.)
    assert not (tmp_path / "_fleet" / "heal").exists()


# -- fleet scaling: supervisor actuation -------------------------------------


class FakeSupervisor:
    """The FleetSupervisor surface the scale actuator drives."""

    def __init__(self, n=2):
        self.n = n
        self.calls = []
        self.saturation = {"queue_depth": 0, "max_queue_depth": 64}

    def set_target_workers(self, n, min_workers=1):
        self.calls.append(("scale", n, min_workers))
        self.n = max(min_workers, n)
        return self.n

    def fleet_health(self):
        return {"saturation": dict(self.saturation)}


def _scale_controller(sup, **conf_overrides):
    session = FakeSession(**conf_overrides)
    hs = FakeHyperspace(session)
    ctrl = OpsController(hs, clock=lambda: 0.0, member_id="scaler",
                         supervisor=sup)
    return hs, ctrl


def test_sustained_saturation_scales_up_and_recovery_scales_back():
    _serve_counters()
    sup = FakeSupervisor(n=2)
    hs, ctrl = _scale_controller(sup)
    sup.saturation["queue_depth"] = 60  # ratio 0.94 >= 0.75
    ctrl.step(now=0.0)  # saturated tick 1 of hysteresis 2
    assert sup.calls == []
    ctrl.step(now=1.0)  # saturated tick 2: scale up
    assert sup.calls == [("scale", 3, 1)]
    assert sup.n == 3
    assert stats.get("controller.scale") == 1
    (up,) = _actuation_events("fleet.scale.up")
    assert up["fields"]["trigger"] == "fleet.saturation"
    assert up["fields"]["workers"] == 3
    budget_after_up = ctrl.snapshot()["budget_remaining"]
    # calm ticks: the fleet drains, the episode releases to baseline
    sup.saturation["queue_depth"] = 0
    ctrl.step(now=2.0)  # calm tick 1 of recovery 2
    assert sup.n == 3
    ctrl.step(now=3.0)  # calm tick 2: scale back down
    assert sup.calls[-1] == ("scale", 2, 1)
    assert sup.n == 2
    (down,) = _actuation_events("fleet.scale.down")
    assert down["fields"]["trigger"] == "fleet.recovered"
    # the release is budget-free, like every release
    assert ctrl.snapshot()["budget_remaining"] == budget_after_up
    assert ctrl.snapshot()["scale_baseline"] is None
    assert stats.get("controller.scale") == 2


def test_scale_up_respects_max_workers_cap():
    _serve_counters()
    sup = FakeSupervisor(n=2)
    hs, ctrl = _scale_controller(
        sup, **{"hyperspace.controller.scale.maxWorkers": 3,
                "hyperspace.controller.cooldownSeconds": 1.0}
    )
    sup.saturation["queue_depth"] = 64
    for i in range(8):
        ctrl.step(now=float(i * 5))
    assert sup.n == 3  # grew one step, then pinned at the cap
    assert len(_actuation_events("fleet.scale.up")) == 1


def test_local_server_saturation_alone_drives_scale_up():
    _serve_counters()
    sup = FakeSupervisor(n=1)
    session = FakeSession()
    hs = FakeHyperspace(session)
    gate = threading.Event()
    server = QueryServer(
        session, workers=1, max_queue_depth=32,
        run_fn=lambda p: gate.wait(timeout=30.0),
    )
    try:
        ctrl = OpsController(hs, server=server, clock=lambda: 0.0,
                             supervisor=sup)
        # fleet aggregate is idle; the LOCAL queue ratio must still count
        for _ in range(30):
            server.submit(object())
        ctrl.step(now=0.0)
        ctrl.step(now=1.0)
        assert sup.calls and sup.calls[0][1] == 2
    finally:
        gate.set()
        server.shutdown()


# -- recompile-storm response ------------------------------------------------


class FakeLedger:
    def __init__(self):
        self.pins = []

    def pin(self, signature, mode="raw"):
        self.pins.append((signature, mode))


def test_recompile_storm_pins_raw_and_drops_jit_caches():
    _serve_counters()
    hs, ctrl = _controller()
    ledger = FakeLedger()
    hs.session.routing_ledger = lambda: ledger
    drops0 = stats.get("jit_memory.cache_drops")
    events.declare("jit.recompile_storm").emit(key="sig-hot", recompiles=9)
    ctrl.step(now=0.0)
    assert ledger.pins == [("sig-hot", "raw")]
    assert stats.get("jit_memory.cache_drops") == drops0 + 1
    (act,) = _actuation_events("storm.response.sig-hot")
    assert act["fields"]["trigger"] == "jit.recompile_storm"
    assert act["fields"]["outcome"] == "executed"
    storm = [e for e in events.recent()
             if e["name"] == "controller.storm_response"]
    assert storm and storm[0]["fields"]["key"] == "sig-hot"
    assert storm[0]["fields"]["route"] == "raw"
    # same key storming again inside the cooldown: deferred, one pin
    events.declare("jit.recompile_storm").emit(key="sig-hot", recompiles=9)
    ctrl.step(now=1.0)
    assert ledger.pins == [("sig-hot", "raw")]


def test_storm_response_gate_off_never_pins():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.stormResponse": "false"})
    ledger = FakeLedger()
    hs.session.routing_ledger = lambda: ledger
    events.declare("jit.recompile_storm").emit(key="sig-x", recompiles=9)
    ctrl.step(now=0.0)
    assert ledger.pins == []
    assert _actuation_events("storm.response.sig-x") == []


# -- incident flight recorder ------------------------------------------------


def _incident_controller(tmp_path, server=None, **conf_overrides):
    conf_overrides.setdefault(
        "hyperspace.controller.incident.dir", str(tmp_path / "incidents")
    )
    return _controller(server=server, **conf_overrides)


def test_page_episode_yields_one_finalized_bundle(tmp_path, shed_server):
    from hyperspace_tpu.obs import journal

    journal.configure(enabled=True, root=str(tmp_path / "_obs"))
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _incident_controller(tmp_path, server=shed_server)
    t = _drive_page(completed, failed, ctrl)
    # The overload response engaging opened the bundle, still unresolved.
    (inc,) = ctrl.list_incidents()
    assert inc["open"] is True and inc["trigger"] == "slo.page"
    assert ctrl.snapshot()["open_incident"] == inc["name"]
    # Recovery closes + finalizes it.
    completed.inc(80_000)
    ctrl.step(now=t + 70.0)
    ctrl.step(now=t + 71.0)
    (inc,) = ctrl.list_incidents()
    assert inc["open"] is False and inc["resolution"] == "slo.recovered"
    doc = ctrl.read_incident(inc["name"])
    # Content-complete: state snapshots at open, manifest at close,
    # this member's sealed journal segments copied in.
    for f in ("open.json", "events.json", "config.json", "jit.json",
              "routing.json", "manifest.json"):
        assert f in doc["files"]
    assert any(f.startswith("journal/") for f in doc["files"])
    assert doc["open"]["verdicts"]["serve.availability"] == "page"
    actions = [a["action"] for a in doc["manifest"]["actions"]]
    assert "shed.engage" in actions and "shed.release" in actions
    assert stats.get("controller.incidents") == 1
    assert ctrl.snapshot()["open_incident"] is None


def test_fresh_quarantine_opens_bundle_closed_as_healed(tmp_path):
    _serve_counters()
    hs, ctrl = _incident_controller(tmp_path)
    with hs.session._state_lock:
        hs.session.index_health["/idx/a"] = {"reason": "torn"}
    # One reconciliation pass: the fresh quarantine opens the bundle,
    # the heal executes, and the now-empty quarantine closes it — the
    # whole episode is recorded within the tick it resolved in.
    ctrl.step(now=0.0)
    (inc,) = ctrl.list_incidents()
    assert inc["trigger"] == "quarantine.a"
    assert inc["open"] is False and inc["resolution"] == "healed"
    manifest = ctrl.read_incident(inc["name"])["manifest"]
    assert "heal.a" in [a["action"] for a in manifest["actions"]]


def test_budget_exhaustion_snapshots_an_observe_only_bundle(tmp_path):
    _serve_counters()
    hs, ctrl = _incident_controller(
        tmp_path, **{"hyperspace.controller.actuationBudget": "0"}
    )
    demoted = events.declare("advisor.routing.demoted")
    for i in range(3):
        demoted.emit(signature=f"s{i}")
    ctrl.step(now=0.0)
    # Degrading to observe-only is itself an incident: opened and
    # finalized in one motion — there is no recovery to wait for.
    (inc,) = ctrl.list_incidents()
    assert inc["trigger"] == "observe_only"
    assert inc["open"] is False and inc["resolution"] == "observe_only"


def test_incident_cooldown_and_retention(tmp_path):
    _serve_counters()
    hs, ctrl = _incident_controller(
        tmp_path, **{"hyperspace.controller.cooldownSeconds": "10"}
    )
    # Three serial episodes on distinct indexes: three bundles...
    for i, (t_open, t_close) in enumerate([(0.0, 1.0), (20.0, 21.0), (40.0, 41.0)]):
        with hs.session._state_lock:
            hs.session.index_health[f"/idx/i{i}"] = {"reason": "torn"}
        ctrl.step(now=t_open)
        ctrl.step(now=t_close)
    # ...pruned to controller.incident.maxBundles (default 16 keeps all).
    assert len(ctrl.list_incidents()) == 3
    assert stats.get("controller.incidents") == 3
    # Re-quarantine INSIDE the cooldown window: no fourth bundle.
    with hs.session._state_lock:
        hs.session.index_health["/idx/i2"] = {"reason": "torn again"}
    ctrl.step(now=41.5)
    assert len(ctrl.list_incidents()) == 3


def test_incident_retention_prunes_oldest(tmp_path):
    _serve_counters()
    hs, ctrl = _incident_controller(
        tmp_path,
        **{
            "hyperspace.controller.incident.maxBundles": "2",
            "hyperspace.controller.cooldownSeconds": "1",
        },
    )
    for i in range(3):
        with hs.session._state_lock:
            hs.session.index_health[f"/idx/i{i}"] = {"reason": "torn"}
        ctrl.step(now=i * 10.0)
        ctrl.step(now=i * 10.0 + 1.0)
    incs = ctrl.list_incidents()
    assert len(incs) == 2
    assert {i["trigger"] for i in incs} == {"quarantine.i1", "quarantine.i2"}


def test_incident_recorder_disabled_writes_nothing(tmp_path):
    _serve_counters()
    hs, ctrl = _incident_controller(
        tmp_path, **{"hyperspace.controller.incident.enabled": "false"}
    )
    with hs.session._state_lock:
        hs.session.index_health["/idx/a"] = {"reason": "torn"}
    ctrl.step(now=0.0)
    ctrl.step(now=1.0)
    assert ctrl.list_incidents() == []
    assert not (tmp_path / "incidents").exists()
    assert stats.get("controller.incidents") == 0


def test_debug_incidents_endpoint_serves_bundles(tmp_path):
    import urllib.error

    _serve_counters()
    hs, ctrl = _incident_controller(tmp_path)
    with hs.session._state_lock:
        hs.session.index_health["/idx/a"] = {"reason": "torn"}
    ctrl.step(now=0.0)
    ctrl.step(now=1.0)
    endpoint = obs_http.HealthServer().start()
    try:
        endpoint.attach_controller(ctrl)
        with urllib.request.urlopen(
            endpoint.url("/debug/incidents"), timeout=10
        ) as r:
            (inc,) = json.loads(r.read())["incidents"]
        assert inc["resolution"] == "healed"
        with urllib.request.urlopen(
            endpoint.url(f"/debug/incidents?name={inc['name']}"), timeout=10
        ) as r:
            detail = json.loads(r.read())
        assert detail["manifest"]["trigger"] == "quarantine.a"
        assert "open.json" in detail["files"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                endpoint.url("/debug/incidents?name=nope"), timeout=10
            )
        assert ei.value.code == 404
    finally:
        endpoint.stop()
