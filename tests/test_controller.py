"""Self-driving operations controller (serve/controller.py,
docs/fault_tolerance.md "self-driving operations"): every trigger→action
mapping, hysteresis across verdict flicker, per-actuation cooldown,
actuation-budget exhaustion degrading to observe-only, the kill switch
disarming mid-loop, and CrashPoint at the `controller.actuate` fault
point unwinding with zero partial state — all driven by an injectable
clock (no sleeps on the decision paths)."""

import json
import threading
import time
import urllib.request

import pytest

from hyperspace_tpu import faults, stats
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.faults import CrashPoint
from hyperspace_tpu.obs import events, metrics, slo
from hyperspace_tpu.obs import http as obs_http
from hyperspace_tpu.serve.controller import OpsController
from hyperspace_tpu.serve.fleet.quota import TenantQuotas
from hyperspace_tpu.serve.scheduler import QueryServer


class FakeSession:
    """The session surface the controller reads: conf + the lock-guarded
    index_health map (the test_health_plane.FakeSession shape)."""

    def __init__(self, **conf_overrides):
        self.conf = HyperspaceConf()
        self.conf.set("hyperspace.controller.enabled", "true")
        for k, v in conf_overrides.items():
            self.conf.set(k, v)
        self._state_lock = threading.RLock()
        self.index_health = {}


class FakeLifecycle:
    def __init__(self, log):
        self._log = log

    def sweep(self):
        self._log.append(("sweep",))
        return {"applied": [], "skipped": [], "failed": []}


class FakeHyperspace:
    """The facade surface the controller actuates through; records every
    call so tests pin the trigger→protocol mapping."""

    def __init__(self, session):
        self.session = session
        self.calls = []
        self.fail_next = None  # exception type to raise on the next call

    def _maybe_fail(self):
        if self.fail_next is not None:
            exc, self.fail_next = self.fail_next, None
            raise exc("injected facade failure")

    def recover(self, name=None):
        self._maybe_fail()
        self.calls.append(("recover", name))
        with self.session._state_lock:
            for root in [r for r in self.session.index_health
                         if name is None or r.endswith(name)]:
                self.session.index_health.pop(root)
        return {}

    def refresh_index(self, name, mode="full"):
        self._maybe_fail()
        self.calls.append(("refresh", name, mode))

    def lifecycle(self):
        return FakeLifecycle(self.calls)


def _serve_counters():
    return (
        metrics.counter("serve.completed"),
        metrics.counter("serve.failed"),
        metrics.counter("serve.timeouts"),
        metrics.counter("serve.cancelled"),
        metrics.histogram("serve.latency.seconds"),
    )


def _controller(server=None, **conf_overrides):
    session = FakeSession(**conf_overrides)
    hs = FakeHyperspace(session)
    return hs, OpsController(hs, server=server, clock=lambda: 0.0)


def _drive_page(completed, failed, ctrl, t0=0.0):
    """Walk the controller's own sampling into a sustained availability
    page: baseline traffic, then a hard failure burst. Returns the time
    of the last (second consecutive page) step."""
    completed.inc(10_000)
    ctrl.step(now=t0)
    ctrl.step(now=t0 + 4000.0)
    failed.inc(3_000)
    ctrl.step(now=t0 + 4030.0)  # page tick 1: hysteresis holds
    ctrl.step(now=t0 + 4031.0)  # page tick 2: actuate
    return t0 + 4031.0


def _actuation_events(action=None):
    out = [e for e in events.recent() if e["name"] == "controller.actuation"]
    if action is not None:
        out = [e for e in out if e["fields"]["action"] == action]
    return out


@pytest.fixture
def shed_server():
    """A real QueryServer (DI run_fn) + real TenantQuotas — the overload
    actuation surface."""
    session = FakeSession()
    quotas = TenantQuotas(rate=10.0, burst=10.0)
    server = QueryServer(
        session, workers=1, max_queue_depth=32, run_fn=lambda p: p, quotas=quotas
    )
    try:
        yield server
    finally:
        server.shutdown()


# -- trigger -> action mappings --------------------------------------------


def test_slo_page_engages_shed_and_quota_tighten(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    assert shed_server.get_shed_depth() == 32
    _drive_page(completed, failed, ctrl)
    assert shed_server.get_shed_depth() == 16  # 0.5 x maxQueueDepth
    assert shed_server.quotas.throttle() == pytest.approx(0.5)
    snap = ctrl.snapshot()
    assert snap["engaged"] is True
    assert snap["verdicts"]["serve.availability"] == "page"
    assert stats.get("controller.actuations") == 1
    (evt,) = _actuation_events("shed.engage")
    assert evt["fields"]["trigger"] == "slo.page"
    assert evt["fields"]["outcome"] == "executed"
    assert metrics.REGISTRY.get("controller.engaged").value == 1


def test_recovery_releases_overrides_after_recovery_ticks(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    t = _drive_page(completed, failed, ctrl)
    # clean traffic pushes the burst out of the page windows
    completed.inc(80_000)
    ctrl.step(now=t + 70.0)  # non-page tick 1: still engaged
    assert ctrl.snapshot()["engaged"] is True
    ctrl.step(now=t + 71.0)  # non-page tick 2: release
    assert ctrl.snapshot()["engaged"] is False
    assert shed_server.get_shed_depth() == 32
    assert shed_server.quotas.throttle() == pytest.approx(1.0)
    (evt,) = _actuation_events("shed.release")
    assert evt["fields"]["trigger"] == "slo.recovered"
    assert metrics.REGISTRY.get("controller.engaged").value == 0


def test_quarantine_triggers_recover_then_gated_rebuild():
    _serve_counters()
    hs, ctrl = _controller()
    with hs.session._state_lock:
        hs.session.index_health["/idx/myidx"] = {"reason": "torn bucket"}
    ctrl.step(now=0.0)
    ctrl.step(now=1.0)
    assert hs.calls == [("recover", "myidx"), ("refresh", "myidx", "full")]
    assert hs.session.index_health == {}
    assert stats.get("controller.heals") == 1
    (evt,) = _actuation_events("heal.myidx")
    assert evt["fields"]["trigger"] == "index.quarantined"


def test_heal_rebuild_gate_off_limits_heal_to_recover():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.heal.rebuild": "false"})
    with hs.session._state_lock:
        hs.session.index_health["/idx/a"] = {"reason": "x"}
    ctrl.step(now=0.0)
    ctrl.step(now=1.0)
    assert hs.calls == [("recover", "a")]


def test_demotion_cluster_triggers_advisor_sweep():
    _serve_counters()
    hs, ctrl = _controller()
    demoted = events.declare("advisor.routing.demoted")
    for i in range(3):
        demoted.emit(signature=f"s{i}")
    ctrl.step(now=0.0)
    ctrl.step(now=1.0)
    assert ("sweep",) in hs.calls
    (evt,) = _actuation_events("advisor.sweep")
    assert evt["fields"]["trigger"] == "routing.demotion_cluster"
    assert evt["fields"]["demotions"] == 3
    # evidence consumed: no second sweep without fresh demotions
    ctrl.step(now=100.0)
    assert hs.calls.count(("sweep",)) == 1


def test_demotions_below_cluster_size_or_outside_window_never_sweep():
    _serve_counters()
    hs, ctrl = _controller()
    demoted = events.declare("advisor.routing.demoted")
    demoted.emit(signature="a")
    demoted.emit(signature="b")
    ctrl.step(now=0.0)  # 2 < clusterSize 3
    assert ("sweep",) not in hs.calls
    # the third arrives after the first two aged out of the window
    demoted.emit(signature="c")
    ctrl.step(now=1000.0)  # window 300s: earlier pair expired
    assert ("sweep",) not in hs.calls


# -- back off background work while SLOs burn -------------------------------


def test_heal_and_sweep_defer_while_burning(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    t = _drive_page(completed, failed, ctrl)
    assert ctrl.snapshot()["engaged"] is True
    # the quarantine lands MID-burn: rebuild-class work must wait
    with hs.session._state_lock:
        hs.session.index_health["/idx/hot"] = {"reason": "x"}
    ctrl.step(now=t + 1.0)  # still paging
    assert not any(c[0] in ("recover", "refresh") for c in hs.calls)
    assert not any(c[0] in ("recover", "refresh") for c in hs.calls)
    backoffs = [e for e in events.recent() if e["name"] == "controller.backoff"]
    assert {e["fields"]["action"] for e in backoffs} == {"heal"}
    assert stats.get("controller.deferred") >= 1
    # burn clears -> the held-back heal executes
    completed.inc(80_000)
    ctrl.step(now=t + 70.0)
    ctrl.step(now=t + 71.0)
    ctrl.step(now=t + 72.0)
    assert ("recover", "hot") in hs.calls


# -- hysteresis / cooldown (no flapping) ------------------------------------


def test_single_verdict_flicker_never_actuates(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    completed.inc(10_000)
    ctrl.step(now=0.0)
    ctrl.step(now=4000.0)
    failed.inc(3_000)
    ctrl.step(now=4030.0)  # page tick 1 of hysteresis 2
    assert ctrl.snapshot()["engaged"] is False
    assert shed_server.get_shed_depth() == 32
    # flicker back to ok: the page streak resets
    completed.inc(80_000)
    ctrl.step(now=4100.0)
    assert ctrl.snapshot()["page_ticks"] == 0
    assert ctrl.snapshot()["engaged"] is False
    assert _actuation_events() == []


def test_heal_failure_cools_down_before_retry():
    _serve_counters()
    hs, ctrl = _controller()
    with hs.session._state_lock:
        hs.session.index_health["/idx/bad"] = {"reason": "x"}
    hs.fail_next = RuntimeError
    ctrl.step(now=0.0)
    assert stats.get("controller.actuation_failures") == 1
    failed_events = [e for e in events.recent()
                     if e["name"] == "controller.actuation_failed"]
    assert failed_events and failed_events[0]["fields"]["action"] == "heal.bad"
    # still quarantined; inside the 30s cooldown nothing retries
    ctrl.step(now=5.0)
    assert hs.calls == []
    assert stats.get("controller.deferred") >= 1
    # past the cooldown the heal retries and succeeds
    ctrl.step(now=31.0)
    assert ("recover", "bad") in hs.calls


# -- actuation budget --------------------------------------------------------


def test_budget_exhaustion_degrades_to_observe_only(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(
        server=shed_server, **{"hyperspace.controller.actuationBudget": 1}
    )
    t = _drive_page(completed, failed, ctrl)  # spends the whole budget
    assert ctrl.snapshot()["budget_remaining"] == 0
    # release stays free: the system is always left as found
    completed.inc(80_000)
    ctrl.step(now=t + 70.0)
    ctrl.step(now=t + 71.0)
    assert shed_server.get_shed_depth() == 32
    # a new trigger is observed, audited, and NOT executed
    with hs.session._state_lock:
        hs.session.index_health["/idx/q"] = {"reason": "x"}
    ctrl.step(now=t + 72.0)
    assert not any(c[0] == "recover" for c in hs.calls)
    assert ctrl.snapshot()["mode"] == "observe_only"
    observe = [e for e in events.recent() if e["name"] == "controller.observe_only"]
    assert len(observe) == 1 and observe[0]["severity"] == "error"
    suppressed = _actuation_events("heal.q")
    assert suppressed and suppressed[0]["fields"]["outcome"] == "observe_only"
    # announced once, not per tick
    ctrl.step(now=t + 103.0)
    assert len([e for e in events.recent()
                if e["name"] == "controller.observe_only"]) == 1


# -- kill switch -------------------------------------------------------------


def test_kill_switch_disarms_mid_loop_and_releases(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    _drive_page(completed, failed, ctrl)
    assert shed_server.get_shed_depth() == 16
    ticks_before = stats.get("controller.ticks")
    hs.session.conf.set("hyperspace.controller.enabled", "false")
    with hs.session._state_lock:
        hs.session.index_health["/idx/x"] = {"reason": "x"}
    snap = ctrl.step(now=5000.0)
    # overrides released, nothing else observed or actuated
    assert shed_server.get_shed_depth() == 32
    assert shed_server.quotas.throttle() == pytest.approx(1.0)
    assert snap["mode"] == "disabled" and snap["engaged"] is False
    assert stats.get("controller.ticks") == ticks_before
    assert not any(c[0] == "recover" for c in hs.calls)
    (evt,) = _actuation_events("shed.release")
    assert evt["fields"]["trigger"] == "kill_switch"


def test_disabled_by_default_controller_never_acts():
    session = FakeSession()
    session.conf.set("hyperspace.controller.enabled", "false")
    hs = FakeHyperspace(session)
    ctrl = OpsController(hs, clock=lambda: 0.0)
    with session._state_lock:
        session.index_health["/idx/x"] = {"reason": "x"}
    snap = ctrl.step(now=0.0)
    assert snap["mode"] == "disabled"
    assert hs.calls == [] and stats.get("controller.ticks") == 0


# -- crash safety (controller.actuate fault point) ---------------------------


def test_crashpoint_at_actuate_unwinds_with_zero_partial_state(shed_server):
    completed, failed, *_ = _serve_counters()
    hs, ctrl = _controller(server=shed_server)
    completed.inc(10_000)
    ctrl.step(now=0.0)
    ctrl.step(now=4000.0)
    failed.inc(3_000)
    ctrl.step(now=4030.0)
    with faults.injected("controller.actuate", crash=True):
        with pytest.raises(CrashPoint):
            ctrl.step(now=4031.0)  # the engage tick dies BEFORE mutating
    assert shed_server.get_shed_depth() == 32  # no partial actuation
    assert shed_server.quotas.throttle() == pytest.approx(1.0)
    assert ctrl.snapshot()["engaged"] is False
    assert stats.get("controller.actuations") == 0
    # the "next process": a clean retry actuates normally
    ctrl.step(now=4032.0)
    assert shed_server.get_shed_depth() == 16


def test_transient_fault_at_actuate_surfaces_typed():
    _serve_counters()
    hs, ctrl = _controller()
    with hs.session._state_lock:
        hs.session.index_health["/idx/t"] = {"reason": "x"}
    with faults.injected("controller.actuate", times=1):
        with pytest.raises(OSError):
            ctrl.step(now=0.0)
    assert hs.calls == []  # the fault fired before any mutation
    ctrl.step(now=1.0)
    assert ("recover", "t") in hs.calls


# -- loop + healthz surface --------------------------------------------------


def test_start_stop_loop_ticks_and_stops():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.intervalSeconds": 0.01})
    ctrl._clock = time.monotonic
    with ctrl.start():
        deadline = time.monotonic() + 5.0
        while stats.get("controller.ticks") < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert stats.get("controller.ticks") >= 3
    ticks = stats.get("controller.ticks")
    time.sleep(0.05)
    assert stats.get("controller.ticks") == ticks  # stopped means stopped


def test_loop_survives_a_failing_step():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.intervalSeconds": 0.01})
    ctrl._clock = time.monotonic
    boom = {"n": 0}

    real_step = ctrl.step

    def flaky_step(now=None):
        boom["n"] += 1
        if boom["n"] == 1:
            raise RuntimeError("transient controller bug")
        return real_step(now)

    ctrl.step = flaky_step
    with ctrl.start():
        deadline = time.monotonic() + 5.0
        while boom["n"] < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert boom["n"] >= 3  # the loop kept reconciling past the failure
    failed_events = [e for e in events.recent()
                     if e["name"] == "controller.actuation_failed"]
    assert any(e["fields"]["action"] == "step" for e in failed_events)


def test_healthz_surfaces_controller_verdict():
    _serve_counters()
    hs, ctrl = _controller()
    endpoint = obs_http.HealthServer().start()
    try:
        endpoint.attach_controller(ctrl)
        ctrl.step(now=0.0)
        with urllib.request.urlopen(endpoint.url("/healthz"), timeout=10) as r:
            doc = json.loads(r.read().decode())
        (view,) = doc["controller"]
        assert view["enabled"] is True
        assert view["mode"] == "actuate"
        assert view["budget_remaining"] == 32
        assert "verdicts" in view
    finally:
        endpoint.stop()


def test_start_registers_with_shared_health_endpoint():
    _serve_counters()
    hs, ctrl = _controller(**{"hyperspace.controller.intervalSeconds": 0.05})
    endpoint = obs_http.acquire()
    try:
        ctrl._clock = time.monotonic
        with ctrl.start():
            with urllib.request.urlopen(endpoint.url("/healthz"), timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert len(doc["controller"]) == 1
    finally:
        obs_http.release()
