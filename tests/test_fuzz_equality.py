"""Seeded randomized equality harness: random predicate trees and join
plans over random tables (nulls, strings, dates, floats) checked against
an INDEPENDENT pandas-based 3-valued-logic evaluator written here (the
spec), raw and index-rewritten, on whatever venue auto picks. The
deterministic seeds make failures reproducible; the diversity catches
interactions the hand-written suites don't enumerate."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, lit
from hyperspace_tpu.plan import expr as E

MODES = ["AIR", "MAIL", "RAIL", "SHIP", "TRUCK"]


def make_frame(rng, n):
    null_a = rng.random(n) < 0.12
    null_s = rng.random(n) < 0.1
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 50, n).astype(np.int64),
            "a": pd.array(np.where(null_a, 0, rng.integers(-20, 80, n)), dtype="Int64"),
            "f": np.round(rng.normal(size=n) * 10, 3),
            "s": pd.array(
                np.where(null_s, None, np.array(MODES, dtype=object)[rng.integers(0, 5, n)]),
                dtype=object,
            ),
        }
    )
    df.loc[null_a, "a"] = pd.NA
    return df


def rand_pred(rng, depth=0):
    """A random predicate tree over columns k/a/f/s."""
    r = rng.random()
    if depth < 2 and r < 0.45:
        op = rng.choice(["and", "or", "not"])
        if op == "not":
            return ("not", rand_pred(rng, depth + 1))
        return (op, rand_pred(rng, depth + 1), rand_pred(rng, depth + 1))
    leaf = rng.choice(["cmp_int", "cmp_float", "cmp_str", "in_int", "in_str", "like", "isnull", "colcol"])
    if leaf == "cmp_int":
        return ("cmp", rng.choice(["eq", "ne", "lt", "le", "gt", "ge"]), "a", int(rng.integers(-25, 85)))
    if leaf == "cmp_float":
        return ("cmp", rng.choice(["lt", "ge"]), "f", float(np.round(rng.normal() * 10, 2)))
    if leaf == "cmp_str":
        return ("cmp", rng.choice(["eq", "ne", "lt", "ge"]), "s", str(rng.choice(MODES + ["ZEBRA"])))
    if leaf == "in_int":
        vals = sorted({int(v) for v in rng.integers(0, 50, rng.integers(1, 5))})
        return ("in", "k", vals)
    if leaf == "in_str":
        vals = list({str(v) for v in rng.choice(MODES, rng.integers(1, 3))})
        return ("in", "s", vals)
    if leaf == "like":
        pat = rng.choice(["MA%", "%IL", "%AI%", "SHIP", "Z%"])
        return ("like", "s", str(pat))
    if leaf == "isnull":
        return ("isnull", rng.choice(["a", "s"]))
    return ("colcol", rng.choice(["lt", "ge"]), "k", "a")


def to_expr(p):
    t = p[0]
    if t == "and":
        return to_expr(p[1]) & to_expr(p[2])
    if t == "or":
        return to_expr(p[1]) | to_expr(p[2])
    if t == "not":
        return ~to_expr(p[1])
    if t == "cmp":
        _, op, c, v = p
        return E.BinOp(op, col(c), lit(v))
    if t == "in":
        return col(p[1]).isin(p[2])
    if t == "like":
        return col(p[1]).like(p[2])
    if t == "isnull":
        return col(p[1]).is_null()
    _, op, c1, c2 = p
    return E.BinOp(op, col(c1), col(c2))


def pandas_tri(df, p):
    """Independent 3VL evaluator: (true mask, false mask); unknown =
    neither."""
    t = p[0]
    if t == "and":
        t1, f1 = pandas_tri(df, p[1])
        t2, f2 = pandas_tri(df, p[2])
        return t1 & t2, f1 | f2
    if t == "or":
        t1, f1 = pandas_tri(df, p[1])
        t2, f2 = pandas_tri(df, p[2])
        return t1 | t2, f1 & f2
    if t == "not":
        tt, ff = pandas_tri(df, p[1])
        return ff, tt
    if t == "isnull":
        isna = df[p[1]].isna().to_numpy()
        return isna, ~isna
    if t == "cmp":
        _, op, c, v = p
        s = df[c]
        known = s.notna().to_numpy()
        # pandas 3 infers the new ``str`` dtype for string columns while
        # pandas 2 keeps ``object`` (where is_string_dtype is False for
        # None-bearing columns) — pick the fill by the LITERAL's type,
        # which the fuzzer always matches to the column domain.
        sv = s.fillna("" if isinstance(v, str) else 0).to_numpy()
        fn = {"eq": np.equal, "ne": np.not_equal, "lt": np.less,
              "le": np.less_equal, "gt": np.greater, "ge": np.greater_equal}[op]
        with np.errstate(all="ignore"):
            val = fn(sv, v)
        return val & known, ~val & known
    if t == "in":
        _, c, vals = p
        s = df[c]
        known = s.notna().to_numpy()
        val = s.isin(vals).to_numpy()
        return val & known, ~val & known
    if t == "like":
        _, c, pat = p
        import re

        rx = re.compile("".join(".*" if ch == "%" else re.escape(ch) for ch in pat), re.DOTALL)
        s = df[c]
        known = s.notna().to_numpy()
        val = np.array([bool(rx.fullmatch(str(x))) if x is not None else False for x in s])
        return val & known, ~val & known
    _, op, c1, c2 = p
    s1, s2 = df[c1], df[c2]
    known = (s1.notna() & s2.notna()).to_numpy()
    fn = {"lt": np.less, "ge": np.greater_equal}[op]
    with np.errstate(all="ignore"):
        val = fn(s1.fillna(0).to_numpy().astype(np.float64), s2.fillna(0).to_numpy().astype(np.float64))
    return val & known, ~val & known


@pytest.mark.parametrize("seed", range(20))
def test_random_filters_match_pandas_3vl(tmp_path, seed):
    rng = np.random.default_rng(1000 + seed)
    df = make_frame(rng, int(rng.integers(500, 3_000)))
    root = tmp_path / "t"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    hs = Hyperspace(session)
    ds = session.parquet(root)
    hs.create_index(ds, IndexConfig("fz_k", ["k"], ["a", "f", "s"]))

    for case in range(6):
        p = rand_pred(rng)
        q = ds.filter(to_expr(p))
        tmask, _ = pandas_tri(df, p)
        exp_n = int(tmask.sum())
        session.disable_hyperspace()
        raw_n = session.run(q).num_rows
        session.enable_hyperspace()
        idx_n = session.run(q).num_rows
        assert raw_n == exp_n, (seed, case, p, raw_n, exp_n)
        assert idx_n == exp_n, (seed, case, p, idx_n, exp_n)


@pytest.mark.parametrize("seed", range(8))
def test_random_join_types_match_pandas(tmp_path, seed):
    from tests.test_join_types import norm_rows

    rng = np.random.default_rng(2000 + seed)
    n_l, n_r = int(rng.integers(400, 2_000)), int(rng.integers(50, 600))
    lk = rng.integers(0, 120, n_l).astype(np.float64)
    lk[rng.random(n_l) < 0.06] = np.nan
    rk = rng.integers(60, 200, n_r).astype(np.float64)
    rk[rng.random(n_r) < 0.06] = np.nan
    l = pd.DataFrame({"k": pd.array(np.where(np.isnan(lk), None, lk), dtype="Int64"),
                      "lv": rng.integers(0, 9, n_l).astype(np.int64)})
    r = pd.DataFrame({"k2": pd.array(np.where(np.isnan(rk), None, rk), dtype="Int64"),
                      "rv": np.round(rng.normal(size=n_r), 4)})
    for nm, fr in (("l", l), ("r", r)):
        (tmp_path / nm).mkdir()
        pq.write_table(pa.Table.from_pandas(fr, preserve_index=False), tmp_path / nm / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    ls, rs = session.parquet(tmp_path / "l"), session.parquet(tmp_path / "r")

    how = ["inner", "left", "right", "full", "semi", "anti"][seed % 6]
    got = session.to_pandas(ls.join(rs, ["k"], ["k2"], how=how))

    ld = l[l.k.notna()]
    rd = r[r.k2.notna()]
    if how == "semi":
        exp = l[l.k.isin(set(rd.k2))]
    elif how == "anti":
        exp = l[~l.k.isin(set(rd.k2))]
    else:
        inner = ld.merge(rd, left_on="k", right_on="k2").drop(columns=["k2"])
        parts = [inner]
        if how in ("left", "full"):
            un = l[~l.k.isin(set(rd.k2))].copy()
            un["rv"] = np.nan
            parts.append(un)
        if how in ("right", "full"):
            un = r[~r.k2.isin(set(ld.k))].rename(columns={"k2": "k"}).copy()
            un["lv"] = None
            parts.append(un)
        exp = pd.concat(parts, ignore_index=True)
    cols = ["k", "lv"] if how in ("semi", "anti") else ["k", "lv", "rv"]
    assert norm_rows(got, cols) == norm_rows(exp[cols], cols), (seed, how)
