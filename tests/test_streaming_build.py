"""Streaming out-of-core build: chunked spill + batched device sort must
produce exactly the same index as the in-memory path, under a host-memory
budget far below the source size (the analog of the reference scanning
arbitrary-size sources as a pipelined cluster job,
actions/CreateActionBase.scala:99-120)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.dataset import Dataset
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.builder import DeviceIndexBuilder
from hyperspace_tpu.ops.sortkeys import key_lanes, lexsort_lanes, value_lanes
from hyperspace_tpu.parallel.mesh import make_mesh


def _gen_source(root, n=20_000, files=3, row_group_size=2_000, with_nulls=True):
    root.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(11)
    per = n // files
    for i in range(files):
        m = per if i < files - 1 else n - per * (files - 1)
        k = rng.integers(-(10**12), 10**12, m).astype(np.int64)
        nulls = (rng.random(m) < 0.08) if with_nulls else None
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(k, mask=nulls),
                    "s": pa.array([f"s{j % 41:02d}" for j in range(m)]),
                    "v": pa.array(rng.standard_normal(m)),
                }
            ),
            root / f"p{i}.parquet",
            row_group_size=row_group_size,
        )


@pytest.mark.parametrize("key", [["k"], ["k", "s"]])
def test_streaming_build_matches_in_memory(tmp_path, key):
    _gen_source(tmp_path / "src")
    ds = Dataset.parquet(tmp_path / "src")
    num_buckets = 16
    mesh = make_mesh()

    mem = DeviceIndexBuilder(mesh=mesh)
    d_mem = tmp_path / "idx_mem" / "v__=0"
    mem.write(ds.scan(), ["k", "s", "v"], key, num_buckets, d_mem)
    assert mem.last_build_stats["path"] == "in-memory"

    # A budget far below the source forces the chunked spill pipeline.
    stream = DeviceIndexBuilder(mesh=mesh, memory_budget_bytes=50_000, chunk_bytes=80_000)
    d_str = tmp_path / "idx_str" / "v__=0"
    stream.write(ds.scan(), ["k", "s", "v"], key, num_buckets, d_str)
    assert stream.last_build_stats["path"] == "streaming"
    assert stream.last_build_stats["chunks"] > 3
    assert not (d_str.parent / "v__=0.spill").exists(), "spill dir must be cleaned up"

    m1, m2 = hio.read_manifest(d_mem), hio.read_manifest(d_str)
    assert m1["bucketRows"] == m2["bucketRows"]
    for b in range(num_buckets):
        t1 = hio.read_parquet([str(d_mem / hio.bucket_file_name(b))])
        t2 = hio.read_parquet([str(d_str / hio.bucket_file_name(b))])
        assert t1.num_rows == t2.num_rows
        if t1.num_rows == 0:
            continue
        # Both key-sorted (nulls first).
        for t in (t1, t2):
            lanes = key_lanes(t, key, force_validity=True)
            perm = lexsort_lanes(lanes)
            resorted = [l[perm] for l in lanes]
            assert all(np.array_equal(a, b) for a, b in zip(resorted, lanes)), (
                f"bucket {b} not key-sorted"
            )
        # Same row multiset.
        df1 = pd.DataFrame(t1.decode()).sort_values(["k", "s", "v"], na_position="first").reset_index(drop=True)
        df2 = pd.DataFrame(t2.decode()).sort_values(["k", "s", "v"], na_position="first").reset_index(drop=True)
        pd.testing.assert_frame_equal(df1, df2)


def test_pipelined_build_matches_serial_byte_for_byte(tmp_path):
    """The pipelined streaming build must be indistinguishable from the
    serial two-phase reference on disk: identical manifest AND identical
    bucket-file BYTES (same spill content, same per-bucket stable sort,
    same deterministic parquet encode) — the bench.py --smoke invariant,
    pinned here at test scale."""
    _gen_source(tmp_path / "src", n=24_000, files=3, row_group_size=2_000)
    ds = Dataset.parquet(tmp_path / "src")
    num_buckets = 16
    mesh = make_mesh()
    kw = dict(mesh=mesh, memory_budget_bytes=50_000, chunk_bytes=80_000)

    serial = DeviceIndexBuilder(pipeline_enabled=False, **kw)
    d_serial = tmp_path / "idx_serial" / "v__=0"
    serial.write(ds.scan(), ["k", "s", "v"], ["k"], num_buckets, d_serial)
    assert serial.last_build_stats["path"] == "streaming"
    assert "pipeline" not in serial.last_build_stats

    pipe = DeviceIndexBuilder(pipeline_enabled=True, **kw)
    d_pipe = tmp_path / "idx_pipe" / "v__=0"
    pipe.write(ds.scan(), ["k", "s", "v"], ["k"], num_buckets, d_pipe)
    assert pipe.last_build_stats["path"] == "streaming"
    pinfo = pipe.last_build_stats["pipeline"]
    assert pinfo["window_bytes"] > 0 and 0.0 <= pinfo["occupancy"] <= 1.0
    assert not (d_pipe.parent / "v__=0.spill").exists()

    assert hio.read_manifest(d_serial) == hio.read_manifest(d_pipe)
    for b in range(num_buckets):
        s_bytes = (d_serial / hio.bucket_file_name(b)).read_bytes()
        p_bytes = (d_pipe / hio.bucket_file_name(b)).read_bytes()
        assert s_bytes == p_bytes, f"bucket {b} bytes differ serial vs pipelined"


def test_pipeline_window_of_one_bucket_still_completes(tmp_path):
    """A window smaller than any single bucket must admit buckets one at
    a time (never deadlock) and still produce the identical index."""
    _gen_source(tmp_path / "src", n=6_000, files=2, row_group_size=1_000)
    ds = Dataset.parquet(tmp_path / "src")
    mesh = make_mesh()
    kw = dict(mesh=mesh, memory_budget_bytes=20_000, chunk_bytes=30_000)
    serial = DeviceIndexBuilder(pipeline_enabled=False, **kw)
    d1 = tmp_path / "i1" / "v__=0"
    serial.write(ds.scan(), ["k", "v"], ["k"], 4, d1)
    tiny = DeviceIndexBuilder(pipeline_enabled=True, pipeline_max_inflight_bytes=1, **kw)
    d2 = tmp_path / "i2" / "v__=0"
    tiny.write(ds.scan(), ["k", "v"], ["k"], 4, d2)
    assert hio.read_manifest(d1) == hio.read_manifest(d2)
    for b in range(4):
        assert (d1 / hio.bucket_file_name(b)).read_bytes() == (d2 / hio.bucket_file_name(b)).read_bytes()


def test_streamed_index_serves_queries(tmp_path):
    """End-to-end: an index built out-of-core answers rewritten queries
    identically to the raw scan."""
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu.config import INDEX_BUILD_MEMORY_BUDGET, INDEX_BUILD_CHUNK_BYTES

    _gen_source(tmp_path / "src", n=8_000, with_nulls=False)
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=8, mesh=make_mesh())
    session.conf.set(INDEX_BUILD_MEMORY_BUDGET, 30_000)
    session.conf.set(INDEX_BUILD_CHUNK_BYTES, 50_000)
    hs = Hyperspace(session)
    df = session.parquet(tmp_path / "src")
    hs.create_index(df, IndexConfig("sidx", ["k"], ["s", "v"]))

    some_key = int(session.run(df.select("k")).columns["k"][7])
    q = df.filter(col("k") == some_key).select("k", "s", "v")
    session.disable_hyperspace()
    expected = session.to_pandas(q).sort_values(["s", "v"]).reset_index(drop=True)
    session.enable_hyperspace()
    got = session.to_pandas(q).sort_values(["s", "v"]).reset_index(drop=True)
    assert len(got) > 0
    pd.testing.assert_frame_equal(got, expected[got.columns.tolist()])


def test_value_lanes_preserve_order():
    """Lane decomposition: lexicographic lane order == logical order for
    every supported dtype (the correctness contract of ops/sortkeys.py)."""
    rng = np.random.default_rng(5)
    cases = [
        rng.integers(-(2**60), 2**60, 500).astype(np.int64),
        rng.integers(0, 2**63, 500).astype(np.uint64),
        rng.integers(-(2**30), 2**30, 500).astype(np.int32),
        (rng.standard_normal(500) * 1e6).astype(np.float64),
        (rng.standard_normal(500) * 1e3).astype(np.float32),
        rng.integers(0, 2, 500).astype(np.bool_),
        rng.integers(0, 2**31, 500).astype(np.uint32),
        rng.integers(-100, 100, 500).astype(np.int16),
    ]
    for arr in cases:
        lanes = value_lanes(arr)
        got = lexsort_lanes(lanes)
        expected = np.argsort(arr, kind="stable")
        assert np.array_equal(arr[got], arr[expected]), arr.dtype


def test_chunk_planning_respects_budget(tmp_path):
    _gen_source(tmp_path / "src", n=10_000, files=2, row_group_size=1_000)
    files = sorted(str(p) for p in (tmp_path / "src").glob("*.parquet"))
    est = hio.estimate_uncompressed_bytes(files)
    assert est > 0
    chunks = hio.plan_row_group_chunks(files, chunk_bytes=est // 4)
    assert len(chunks) >= 4
    # Every row group appears exactly once.
    seen = [u for c in chunks for u in c]
    assert len(seen) == len(set(seen))
    total_rgs = sum(pq.ParquetFile(f).metadata.num_row_groups for f in files)
    assert len(seen) == total_rgs


@pytest.mark.parametrize("fmt", ["csv", "orc", "json"])
def test_streaming_build_non_parquet_sources(tmp_path, fmt):
    """Sources above the budget stream for every supported format: CSV
    by record batches, ORC by stripes, JSON per file — same index as the
    in-memory path."""
    import pyarrow.csv as pcsv
    import pyarrow.orc as porc
    import json as pyjson

    rng = np.random.default_rng(13)
    n, files = 12_000, 3
    root = tmp_path / "src"
    root.mkdir()
    per = n // files
    for i in range(files):
        t = pa.table(
            {
                "k": rng.integers(0, 5_000, per).astype(np.int64),
                "v": np.round(rng.standard_normal(per), 6),
            }
        )
        if fmt == "csv":
            pcsv.write_csv(t, root / f"p{i}.csv")
        elif fmt == "orc":
            porc.write_table(t, root / f"p{i}.orc", stripe_size=16 << 10)
        else:
            with open(root / f"p{i}.json", "w") as f:
                for r in range(per):
                    f.write(pyjson.dumps({"k": int(t["k"][r].as_py()), "v": float(t["v"][r].as_py())}) + "\n")

    ds = getattr(Dataset, fmt)(root)
    num_buckets = 8
    mesh = make_mesh()

    mem = DeviceIndexBuilder(mesh=mesh)
    d_mem = tmp_path / "idx_mem" / "v__=0"
    mem.write(ds.scan(), ["k", "v"], ["k"], num_buckets, d_mem)
    assert mem.last_build_stats["path"] == "in-memory"

    # JSON chunks at file granularity: each file must fit the budget
    # (a single over-budget JSON file raises), while the TOTAL stays
    # above it so the streaming path is still what runs.
    budget = 1_000_000 if fmt == "json" else 15_000
    stream = DeviceIndexBuilder(mesh=mesh, memory_budget_bytes=budget, chunk_bytes=15_000)
    d_str = tmp_path / "idx_str" / "v__=0"
    stream.write(ds.scan(), ["k", "v"], ["k"], num_buckets, d_str)
    assert stream.last_build_stats["path"] == "streaming"
    assert stream.last_build_stats["format"] == fmt
    if fmt != "json":
        # CSV record batches / ORC stripes split each file into several
        # bounded chunks (JSON is file-granular).
        assert stream.last_build_stats["chunks"] > files

    m1, m2 = hio.read_manifest(d_mem), hio.read_manifest(d_str)
    assert m1["bucketRows"] == m2["bucketRows"]
    for b in range(num_buckets):
        t1 = hio.read_parquet([str(d_mem / hio.bucket_file_name(b))])
        t2 = hio.read_parquet([str(d_str / hio.bucket_file_name(b))])
        df1 = pd.DataFrame(t1.decode()).sort_values(["k", "v"]).reset_index(drop=True)
        df2 = pd.DataFrame(t2.decode()).sort_values(["k", "v"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(df1, df2)
