"""Native host-kernel parity tests.

The C++ kernels (hyperspace_tpu/native) must be BIT-IDENTICAL to the numpy
reference implementations: bucket pruning recomputes hashes at query time
and on-disk indexes embed them, so any divergence silently corrupts
results. These tests pin the contract on every dtype the hash path takes.
The suite must pass whether or not the toolchain built the library
(available() False just exercises the fallbacks).
"""

import hashlib

import numpy as np
import pytest

from hyperspace_tpu import native
from hyperspace_tpu.ops.hashing import _mix32, combine_hashes, hash_int_column, string_dict_hashes


def _reference_mix_i64(arr):
    lo = (arr & 0xFFFFFFFF).astype(np.uint32)
    hi = ((arr >> 32) & 0xFFFFFFFF).astype(np.uint32)
    return _mix32(lo ^ (_mix32(hi, np) * np.uint32(0x9E3779B1)), np)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


def test_native_builds():
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain on this host — numpy fallbacks cover it")
    assert native.available()


def test_hash_i64_parity(rng):
    arr = rng.integers(-(2**62), 2**62, 100_000).astype(np.int64)
    arr[:4] = [0, -1, np.iinfo(np.int64).min, np.iinfo(np.int64).max]
    assert np.array_equal(hash_int_column(arr, np), _reference_mix_i64(arr))


def test_hash_i32_and_float_parity(rng):
    i32 = rng.integers(-(2**31), 2**31 - 1, 50_000).astype(np.int32)
    assert np.array_equal(hash_int_column(i32, np), _mix32(i32.astype(np.uint32), np))
    f32 = rng.standard_normal(50_000).astype(np.float32)
    assert np.array_equal(
        hash_int_column(f32, np), _mix32(f32.view(np.int32).astype(np.uint32), np)
    )
    f64 = rng.standard_normal(50_000)
    assert np.array_equal(hash_int_column(f64, np), _reference_mix_i64(f64.view(np.int64)))


def test_md5_prefix_parity():
    strs = np.array(
        ["", "a", "hello world", "x" * 55, "y" * 56, "z" * 64, "w" * 120, "ü–😀"],
        dtype=object,
    )
    expected = np.array(
        [
            int.from_bytes(hashlib.md5(str(s).encode("utf-8")).digest()[:4], "little")
            for s in strs
        ],
        dtype=np.uint32,
    )
    assert np.array_equal(string_dict_hashes(strs), expected)


def test_combine_parity(rng):
    a = rng.integers(0, 2**32, 10_000).astype(np.uint32)
    b = rng.integers(0, 2**32, 10_000).astype(np.uint32)
    c = rng.integers(0, 2**32, 10_000).astype(np.uint32)
    expected = _mix32(_mix32(a * np.uint32(31) + b, np) * np.uint32(31) + c, np)
    assert np.array_equal(combine_hashes([a, b, c], np), expected)


def test_take_rows_parity(rng):
    for arr in (
        rng.standard_normal((5_000, 3)),
        rng.integers(0, 100, 5_000).astype(np.int64),
        rng.standard_normal(5_000).astype(np.float32),
    ):
        idx = rng.permutation(len(arr))[:2_000]
        out = native.take_rows(arr, idx)
        if out is not None:
            assert np.array_equal(out, arr[idx])
