"""Aggregate / Sort / Limit: device kernels vs pandas ground truth, the
fused Aggregate(Join) path vs the materialized join, and rewrite rules
firing underneath aggregation (the engine-side operators the TPU build
owns, SURVEY.md §2.2)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.parallel.mesh import make_mesh


@pytest.fixture
def sales(tmp_path):
    rng = np.random.default_rng(21)
    n = 5_000
    nulls = rng.random(n) < 0.1
    t = pa.table(
        {
            "store": pa.array([f"s{int(i) % 7}" for i in rng.integers(0, 7, n)]),
            "item": rng.integers(0, 50, n).astype(np.int64),
            "qty": pa.array(rng.integers(1, 20, n).astype(np.int64), mask=nulls),
            "price": rng.random(n) * 100,
        }
    )
    root = tmp_path / "sales"
    root.mkdir()
    pq.write_table(t, root / "part-0.parquet")
    return root


def _session(tmp_path, **kw):
    return HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=8, **kw)


def test_grouped_aggregation_matches_pandas(tmp_path, sales):
    session = _session(tmp_path)
    df = session.parquet(sales)
    q = df.aggregate(
        ["store"],
        [
            AggSpec.of("sum", "qty", "total_qty"),
            AggSpec.of("count", None, "rows"),
            AggSpec.of("count", "qty", "qty_rows"),
            AggSpec.of("mean", "price", "avg_price"),
            AggSpec.of("min", "price", "min_price"),
            AggSpec.of("max", "item", "max_item"),
            AggSpec.of("sum", col("qty") * col("price"), "revenue"),
        ],
    )
    got = session.to_pandas(q).sort_values("store").reset_index(drop=True)

    pdf = pq.read_table(sales).to_pandas()
    exp = (
        pdf.groupby("store")
        .agg(
            total_qty=("qty", "sum"),
            rows=("store", "size"),
            qty_rows=("qty", "count"),
            avg_price=("price", "mean"),
            min_price=("price", "min"),
            max_item=("item", "max"),
        )
        .reset_index()
        .sort_values("store")
        .reset_index(drop=True)
    )
    exp["revenue"] = (
        (pdf["qty"] * pdf["price"]).groupby(pdf["store"]).sum().sort_index().values
    )
    assert list(got["store"]) == list(exp["store"])
    np.testing.assert_allclose(got["total_qty"].astype(float), exp["total_qty"].astype(float))
    np.testing.assert_array_equal(got["rows"], exp["rows"])
    np.testing.assert_array_equal(got["qty_rows"], exp["qty_rows"])
    np.testing.assert_allclose(got["avg_price"], exp["avg_price"])
    np.testing.assert_allclose(got["min_price"], exp["min_price"])
    np.testing.assert_array_equal(got["max_item"], exp["max_item"])
    np.testing.assert_allclose(got["revenue"], exp["revenue"])


def test_global_aggregate_and_string_minmax(tmp_path, sales):
    session = _session(tmp_path)
    df = session.parquet(sales)
    q = df.aggregate(
        [],
        [
            AggSpec.of("count", None, "n"),
            AggSpec.of("sum", "price", "sum_price"),
            AggSpec.of("min", "store", "min_store"),
            AggSpec.of("max", "store", "max_store"),
        ],
    )
    got = session.to_pandas(q)
    pdf = pq.read_table(sales).to_pandas()
    assert got["n"][0] == len(pdf)
    np.testing.assert_allclose(got["sum_price"][0], pdf["price"].sum())
    assert got["min_store"][0] == pdf["store"].min()
    assert got["max_store"][0] == pdf["store"].max()


@pytest.mark.parametrize("venue", ["device", "host"])
def test_null_group_key_and_all_null_group(tmp_path, venue):
    from hyperspace_tpu.config import AGG_VENUE

    t = pa.table(
        {
            "k": pa.array([1, 1, None, None, 2], type=pa.int64()),
            "v": pa.array([10.0, None, 5.0, 7.0, None]),
        }
    )
    root = tmp_path / "nulls"
    root.mkdir()
    pq.write_table(t, root / "p.parquet")
    session = _session(tmp_path)
    session.conf.set(AGG_VENUE, venue)
    df = session.parquet(root)
    q = df.aggregate(["k"], [AggSpec.of("sum", "v", "sv"), AggSpec.of("count", "v", "cv")])
    got = session.to_pandas(q)
    by_k = {row["k"]: row for _, row in got.iterrows()}
    assert by_k[1]["sv"] == 10.0 and by_k[1]["cv"] == 1
    # null key forms its own group
    null_rows = got[got["k"].isna()]
    assert len(null_rows) == 1 and null_rows["sv"].iloc[0] == 12.0
    # group 2 has only null inputs -> NULL sum, count 0
    g2 = got[got["k"] == 2]
    assert g2["cv"].iloc[0] == 0 and pd.isna(g2["sv"].iloc[0])


def test_sort_and_limit(tmp_path, sales):
    session = _session(tmp_path)
    df = session.parquet(sales)
    q = df.select("store", "item", "price").sort([("store", True), ("price", False)]).limit(100)
    got = session.to_pandas(q)
    pdf = pq.read_table(sales).to_pandas()
    exp = (
        pdf[["store", "item", "price"]]
        .sort_values(["store", "price"], ascending=[True, False], kind="stable")
        .head(100)
        .reset_index(drop=True)
    )
    np.testing.assert_array_equal(got["store"], exp["store"])
    np.testing.assert_allclose(got["price"], exp["price"])


def test_sort_desc_nulls_last(tmp_path):
    t = pa.table({"v": pa.array([3.0, None, 1.0, 2.0, None])})
    root = tmp_path / "sn"
    root.mkdir()
    pq.write_table(t, root / "p.parquet")
    session = _session(tmp_path)
    got = session.to_pandas(session.parquet(root).sort([("v", False)]))
    vals = list(got["v"])
    assert vals[:3] == [3.0, 2.0, 1.0]
    assert all(pd.isna(v) for v in vals[3:])


@pytest.fixture
def join_tables(tmp_path):
    rng = np.random.default_rng(5)
    n = 8_000
    fact_root = tmp_path / "fact"
    fact_root.mkdir()
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 300, n).astype(np.int64),
                "amount": rng.random(n) * 50,
                "units": rng.integers(1, 9, n).astype(np.int64),
            }
        ),
        fact_root / "f.parquet",
    )
    dim_root = tmp_path / "dim"
    dim_root.mkdir()
    pq.write_table(
        pa.table(
            {
                "k": np.arange(250, dtype=np.int64),  # keys 250..299 unmatched
                "cat": pa.array([f"c{i % 6}" for i in range(250)]),
                "weight": np.round(np.random.default_rng(6).random(250), 3),
            }
        ),
        dim_root / "d.parquet",
    )
    return fact_root, dim_root


def _expected_join_agg(fact_root, dim_root, group, aggs):
    f = pq.read_table(fact_root).to_pandas()
    d = pq.read_table(dim_root).to_pandas()
    j = f.merge(d, on="k")
    g = j.groupby(group) if group else None
    return j, g


@pytest.mark.parametrize("with_index", [False, True])
def test_fused_join_aggregate_matches_pandas(tmp_path, join_tables, with_index):
    fact_root, dim_root = join_tables
    session = _session(tmp_path, mesh=make_mesh())
    hs = Hyperspace(session)
    fact = session.parquet(fact_root)
    dim = session.parquet(dim_root)
    if with_index:
        hs.create_index(fact, IndexConfig("f_k", ["k"], ["amount", "units"]))
        hs.create_index(dim, IndexConfig("d_k", ["k"], ["cat", "weight"]))
        session.enable_hyperspace()
    q = fact.join(dim, ["k"]).aggregate(
        ["cat"],
        [
            AggSpec.of("sum", "amount", "sum_amount"),  # left measure
            AggSpec.of("sum", "weight", "sum_weight"),  # right measure
            AggSpec.of("count", None, "pairs"),
            AggSpec.of("mean", "amount", "avg_amount"),
            AggSpec.of("sum", col("amount") * col("units"), "revenue"),
        ],
    )
    got = session.to_pandas(q).sort_values("cat").reset_index(drop=True)
    assert session.last_query_stats["agg_path"] == "fused-join-agg"
    if with_index:
        assert session.last_query_stats["join_path"] == "zero-exchange-aligned"

    f = pq.read_table(fact_root).to_pandas()
    d = pq.read_table(dim_root).to_pandas()
    j = f.merge(d, on="k")
    exp = (
        j.groupby("cat")
        .agg(
            sum_amount=("amount", "sum"),
            sum_weight=("weight", "sum"),
            pairs=("cat", "size"),
            avg_amount=("amount", "mean"),
        )
        .reset_index()
        .sort_values("cat")
        .reset_index(drop=True)
    )
    exp["revenue"] = (j["amount"] * j["units"]).groupby(j["cat"]).sum().sort_index().values
    assert list(got["cat"]) == list(exp["cat"])
    np.testing.assert_allclose(got["sum_amount"], exp["sum_amount"])
    np.testing.assert_allclose(got["sum_weight"], exp["sum_weight"])
    np.testing.assert_array_equal(got["pairs"], exp["pairs"])
    np.testing.assert_allclose(got["avg_amount"], exp["avg_amount"])
    np.testing.assert_allclose(got["revenue"], exp["revenue"])


def test_fused_join_agg_group_by_left_side(tmp_path, join_tables):
    fact_root, dim_root = join_tables
    session = _session(tmp_path)
    fact = session.parquet(fact_root)
    dim = session.parquet(dim_root)
    q = fact.join(dim, ["k"]).aggregate(
        ["k"], [AggSpec.of("sum", "weight", "w"), AggSpec.of("count", None, "n")]
    )
    got = session.to_pandas(q).sort_values("k").reset_index(drop=True)
    f = pq.read_table(fact_root).to_pandas()
    d = pq.read_table(dim_root).to_pandas()
    j = f.merge(d, on="k")
    exp = (
        j.groupby("k").agg(w=("weight", "sum"), n=("k", "size")).reset_index()
    ).sort_values("k").reset_index(drop=True)
    np.testing.assert_array_equal(got["k"], exp["k"])
    np.testing.assert_allclose(got["w"], exp["w"])
    np.testing.assert_array_equal(got["n"], exp["n"])


def test_join_agg_minmax(tmp_path, join_tables):
    """min/max over a join fuse on BOTH venues: the host C++ pass walks
    per-key runs; the device kernel's run-extremum channels take the
    segmented prefix scan at each run end. Results identical either way,
    covering secondary-side (amount), primary-side (weight), and mixed
    sibling aggregates."""
    from hyperspace_tpu import native
    from hyperspace_tpu.config import JOIN_VENUE

    fact_root, dim_root = join_tables
    f = pq.read_table(fact_root).to_pandas()
    d = pq.read_table(dim_root).to_pandas()
    j = f.merge(d, on="k")
    exp = (
        j.groupby("cat")
        .agg(mx=("amount", "max"), mn=("amount", "min"), wmx=("weight", "max"),
             sa=("amount", "sum"), n=("cat", "size"))
        .reset_index()
        .sort_values("cat")
        .reset_index(drop=True)
    )
    outs = {}
    for venue in ("host", "device"):
        if venue == "host" and not native.available():
            continue
        session = _session(tmp_path)
        session.conf.set(JOIN_VENUE, venue)
        fact = session.parquet(fact_root)
        dim = session.parquet(dim_root)
        q = fact.join(dim, ["k"]).aggregate(
            ["cat"],
            [
                AggSpec.of("max", "amount", "mx"),
                AggSpec.of("min", "amount", "mn"),
                AggSpec.of("max", "weight", "wmx"),
                AggSpec.of("sum", "amount", "sa"),
                AggSpec.of("count", None, "n"),
            ],
        )
        got = session.to_pandas(q).sort_values("cat").reset_index(drop=True)
        assert session.last_query_stats["agg_path"] == "fused-join-agg"
        expected_kernel = (
            "host-native-merge-accumulate" if venue == "host" else "device-run-prefix"
        )
        assert session.last_query_stats["join_kernel"] == expected_kernel
        outs[venue] = got
        assert list(got["cat"]) == list(exp["cat"])
        for c in ("mx", "mn", "wmx", "sa"):
            np.testing.assert_allclose(got[c], exp[c], rtol=1e-9, err_msg=f"{venue}.{c}")
        np.testing.assert_array_equal(got["n"], exp["n"])
    if len(outs) == 2:
        pd.testing.assert_frame_equal(outs["host"], outs["device"])


@pytest.mark.parametrize("venue", ["host", "device"])
def test_fused_minmax_with_nulls_and_unmatched(tmp_path, venue):
    """Fused min/max null semantics on BOTH venues (the device venue
    runs the segmented-prefix-scan run-extremum channels): null measure
    values are ignored, a group whose matched rows are all-null yields
    NULL, multiplicity does not skew extrema (duplicate keys), results
    equal the materialized join."""
    from hyperspace_tpu import native
    from hyperspace_tpu.config import JOIN_VENUE

    if venue == "host" and not native.available():
        pytest.skip("native library not built")
    rng = np.random.default_rng(51)
    n = 4_000
    amount = rng.random(n) * 100
    nulls = rng.random(n) < 0.2
    fact = pa.table(
        {
            "k": rng.integers(0, 80, n).astype(np.int64),
            "amount": pa.array(np.where(nulls, 0.0, amount), mask=nulls),
        }
    )
    dim = pa.table(
        {
            "k": np.arange(60, dtype=np.int64),  # keys 60..79 unmatched
            "cat": pa.array([f"c{i % 5}" for i in range(60)]),
        }
    )
    (tmp_path / "f").mkdir()
    (tmp_path / "d").mkdir()
    pq.write_table(fact, tmp_path / "f" / "p.parquet")
    pq.write_table(dim, tmp_path / "d" / "p.parquet")
    session = _session(tmp_path)
    session.conf.set(JOIN_VENUE, venue)
    fs, ds = session.parquet(tmp_path / "f"), session.parquet(tmp_path / "d")
    q = fs.join(ds, ["k"]).aggregate(
        ["cat"],
        [
            AggSpec.of("min", "amount", "mn"),
            AggSpec.of("max", "amount", "mx"),
            AggSpec.of("sum", "amount", "sm"),
        ],
    )
    got = session.to_pandas(q).sort_values("cat").reset_index(drop=True)
    assert session.last_query_stats["agg_path"] == "fused-join-agg"
    expected_kernel = (
        "host-native-merge-accumulate" if venue == "host" else "device-run-prefix"
    )
    assert session.last_query_stats["join_kernel"] == expected_kernel
    fpd = fact.to_pandas()
    jm = fpd.merge(dim.to_pandas(), on="k")
    exp = (
        jm.groupby("cat")
        .agg(mn=("amount", "min"), mx=("amount", "max"), sm=("amount", "sum"))
        .reset_index()
    )
    np.testing.assert_allclose(got["mn"].astype(float), exp["mn"].astype(float), rtol=1e-9)
    np.testing.assert_allclose(got["mx"].astype(float), exp["mx"].astype(float), rtol=1e-9)
    np.testing.assert_allclose(got["sm"].astype(float), exp["sm"].astype(float), rtol=1e-9)


def test_aggregate_over_index_rewrite_and_explain(tmp_path, sales):
    """Rules must fire underneath an Aggregate, and explain must render
    the new nodes."""
    session = _session(tmp_path)
    hs = Hyperspace(session)
    df = session.parquet(sales)
    hs.create_index(df, IndexConfig("sidx", ["item"], ["qty", "price"]))
    session.enable_hyperspace()
    q = df.filter(col("item") == 7).aggregate([], [AggSpec.of("sum", "qty", "sq")])
    opt = session.optimized_plan(q)
    assert any(s.bucket_spec is not None for s in opt.leaves()), "rewrite under Aggregate missed"
    got = session.to_pandas(q)
    session.disable_hyperspace()
    exp = session.to_pandas(q)
    assert got["sq"][0] == exp["sq"][0]
    text = hs.explain(q)
    assert "Aggregate" in text


def test_aggregate_plan_roundtrips_json(tmp_path, sales):
    from hyperspace_tpu.plan.nodes import plan_from_json

    session = _session(tmp_path)
    df = session.parquet(sales)
    q = df.aggregate(["store"], [AggSpec.of("sum", col("qty") * col("price"), "rev")]).sort(
        [("rev", False)]
    ).limit(3)
    rt = plan_from_json(q.to_json())
    assert rt.to_json() == q.to_json()
    got = session.to_pandas(q)
    got2 = session.to_pandas(rt)
    pd.testing.assert_frame_equal(got, got2)


def test_count_star_only_prunes_to_one_column_not_zero(tmp_path, sales):
    """count(*) with no group_by references no columns; pruning must keep
    at least one scan column or num_rows collapses to 0."""
    session = _session(tmp_path)
    df = session.parquet(sales)
    got = session.to_pandas(df.aggregate([], [AggSpec.of("count", None, "n")]))
    assert got["n"][0] == pq.read_table(sales).num_rows


def test_fused_join_agg_empty_primary_side(tmp_path, join_tables):
    """Global aggregate over a join whose primary (left) side is empty:
    one row with count 0 and NULL sum, not an IndexError."""
    _, dim_root = join_tables
    empty_root = tmp_path / "empty_fact"
    empty_root.mkdir()
    pq.write_table(
        pa.table(
            {
                "k": np.zeros(0, np.int64),
                "amount": np.zeros(0, np.float64),
            }
        ),
        empty_root / "f.parquet",
    )
    session = _session(tmp_path)
    fact = session.parquet(empty_root)
    dim = session.parquet(dim_root)
    q = fact.join(dim, ["k"]).aggregate(
        [], [AggSpec.of("count", None, "n"), AggSpec.of("sum", "amount", "s")]
    )
    got = session.to_pandas(q)
    assert session.last_query_stats["agg_path"] == "fused-join-agg"
    assert len(got) == 1
    assert got["n"][0] == 0
    assert pd.isna(got["s"][0])

    # Grouped variant: no groups at all.
    q2 = fact.join(dim, ["k"]).aggregate(["k"], [AggSpec.of("count", None, "n")])
    assert len(session.to_pandas(q2)) == 0


def test_count_star_over_projected_table(tmp_path, sales):
    """Pruning must not collapse a Project to zero columns either."""
    session = _session(tmp_path)
    df = session.parquet(sales).select("price")
    got = session.to_pandas(df.aggregate([], [AggSpec.of("count", None, "n")]))
    assert got["n"][0] == pq.read_table(sales).num_rows


def test_sum_of_constant_expression(tmp_path, join_tables):
    """sum(lit(2)) == 2 * count(*): constant expressions broadcast instead
    of crashing, on both the plain and the join paths."""
    from hyperspace_tpu.plan.expr import lit

    fact_root, dim_root = join_tables
    session = _session(tmp_path)
    fact = session.parquet(fact_root)
    dim = session.parquet(dim_root)

    got = session.to_pandas(
        fact.aggregate([], [AggSpec.of("sum", lit(2), "s"), AggSpec.of("count", None, "n")])
    )
    assert got["s"][0] == 2 * got["n"][0] == 2 * pq.read_table(fact_root).num_rows

    got2 = session.to_pandas(
        fact.join(dim, ["k"]).aggregate(
            [], [AggSpec.of("sum", lit(2), "s"), AggSpec.of("count", None, "n")]
        )
    )
    f = pq.read_table(fact_root).to_pandas()
    d = pq.read_table(dim_root).to_pandas()
    pairs = len(f.merge(d, on="k"))
    assert got2["n"][0] == pairs and got2["s"][0] == 2 * pairs


def test_agg_host_venue_matches_device(tmp_path, sales):
    """The numpy host reduce must match the device segment-reduce on all
    fns incl. null inputs and string (dict-code) min/max."""
    from hyperspace_tpu.config import AGG_VENUE

    q_args = (
        ["item"],
        [
            AggSpec.of("sum", "qty", "s"),
            AggSpec.of("count", None, "n"),
            AggSpec.of("count", "qty", "nq"),
            AggSpec.of("mean", "price", "m"),
            AggSpec.of("min", "qty", "mn"),
            AggSpec.of("max", "price", "mx"),
            AggSpec.of("min", "store", "smn"),
            AggSpec.of("max", "store", "smx"),
        ],
    )
    outs = {}
    for venue in ("device", "host"):
        session = _session(tmp_path, **{})
        session.conf.set(AGG_VENUE, venue)
        df = session.parquet(sales)
        outs[venue] = (
            session.to_pandas(df.aggregate(*q_args)).sort_values("item").reset_index(drop=True)
        )
        assert session.last_query_stats["agg_path"] == f"segment-reduce-{venue}"
    d, h = outs["device"], outs["host"]
    assert list(d["item"]) == list(h["item"])
    for c in ("s", "n", "nq", "m", "mn", "mx"):
        np.testing.assert_allclose(d[c].astype(float), h[c].astype(float), rtol=1e-12)
    assert list(d["smn"]) == list(h["smn"])
    assert list(d["smx"]) == list(h["smx"])


def test_sort_host_venue_matches_device(tmp_path, sales):
    from hyperspace_tpu.config import SORT_VENUE

    outs = {}
    for venue in ("device", "host"):
        session = _session(tmp_path)
        session.conf.set(SORT_VENUE, venue)
        df = session.parquet(sales)
        q = df.select("store", "item", "price").sort([("store", True), ("price", False)]).limit(50)
        outs[venue] = session.to_pandas(q)
    pd.testing.assert_frame_equal(outs["device"], outs["host"])


def test_sort_requires_keys():
    from hyperspace_tpu.plan.nodes import Scan, Sort
    from hyperspace_tpu.schema import Field, Schema

    scan = Scan("/x", "parquet", Schema.of(Field("a", "int64")))
    with pytest.raises(ValueError, match="at least one"):
        Sort(scan, [])
    with pytest.raises(ValueError, match="at least one"):
        scan.sort([])


def test_mesh_sharded_aggregation_matches_single_device(tmp_path, sales):
    """With a multi-device mesh, the device segment-reduce shards the row
    dimension and combines [A, K] partials with one collective per
    channel; results must equal the single-device reduce."""
    from hyperspace_tpu.config import AGG_VENUE

    q_args = (
        ["item"],
        [
            AggSpec.of("sum", "qty", "s"),
            AggSpec.of("count", None, "n"),
            AggSpec.of("mean", "price", "m"),
            AggSpec.of("min", "price", "mn"),
            AggSpec.of("max", "qty", "mx"),
        ],
    )
    outs = {}
    for name, mesh in (("single", None), ("mesh", make_mesh())):
        session = _session(tmp_path, mesh=mesh)
        session.conf.set(AGG_VENUE, "device")
        df = session.parquet(sales)
        outs[name] = (
            session.to_pandas(df.aggregate(*q_args)).sort_values("item").reset_index(drop=True)
        )
        if name == "mesh":
            assert session.last_query_stats.get("agg_devices", 1) > 1
    pd.testing.assert_frame_equal(outs["single"], outs["mesh"])


@pytest.mark.parametrize("venue", ["device", "host"])
def test_case_when_conditional_aggregate(tmp_path, sales, venue):
    """SQL CASE WHEN inside aggregates (the TPC-H Q12/Q14 shape):
    string-literal conditions with 3-valued nulls, numeric value legs,
    identical across venues and vs pandas."""
    from hyperspace_tpu import when
    from hyperspace_tpu.config import AGG_VENUE
    from hyperspace_tpu.plan.expr import lit as L

    session = _session(tmp_path)
    session.conf.set(AGG_VENUE, venue)
    df = session.parquet(sales)
    is_s1 = (col("store") == L("s1")) | (col("store") == L("s2"))
    expr = when(is_s1, col("price")).otherwise(0.0)
    flag = when(col("qty") > L(10), 1.0).otherwise(0.0)  # qty has nulls
    q = df.aggregate(
        ["item"],
        [
            AggSpec.of("sum", expr, "s12_price"),
            AggSpec.of("sum", flag, "big_qty"),
        ],
    ).sort(["item"])
    got = session.to_pandas(q)

    pdf = pq.read_table(sales).to_pandas()
    exp_price = np.where(pdf.store.isin(["s1", "s2"]), pdf.price, 0.0)
    # null qty: condition is NULL -> branch not taken -> 0.0 (default leg)
    exp_flag = np.where(pdf.qty.fillna(-1) > 10, 1.0, 0.0)
    exp = (
        pd.DataFrame({"item": pdf.item, "p": exp_price, "f": exp_flag})
        .groupby("item")
        .sum()
        .reset_index()
        .sort_values("item")
        .reset_index(drop=True)
    )
    np.testing.assert_array_equal(got["item"], exp["item"])
    np.testing.assert_allclose(got["s12_price"], exp["p"])
    np.testing.assert_allclose(got["big_qty"], exp["f"])


def test_case_when_json_roundtrip():
    from hyperspace_tpu import when
    from hyperspace_tpu.plan.expr import expr_from_json, lit as L

    e = when(col("a") > L(1), col("b") * L(2.0)).when(col("a") < L(0), 0.0).otherwise(col("b"))
    j = e.to_json()
    e2 = expr_from_json(j)
    assert e2.to_json() == j
    assert e.references() == {"a", "b"}


def test_nested_case_in_arithmetic_aggregate(tmp_path, sales):
    """Case nested inside arithmetic keeps branch-following validity: a
    null condition takes the ELSE leg instead of poisoning the row, and
    string-literal conditions work at any depth."""
    from hyperspace_tpu import when
    from hyperspace_tpu.plan.expr import lit as L

    session = _session(tmp_path)
    df = session.parquet(sales)
    expr = when(col("qty") > L(10), 1.0).otherwise(2.0) * col("price")
    sexpr = when(col("store") == L("s1"), 1.0).otherwise(0.0) * col("price")
    got = session.to_pandas(
        df.aggregate([], [AggSpec.of("sum", expr, "s"), AggSpec.of("sum", sexpr, "sp")])
    )
    pdf = pq.read_table(sales).to_pandas()
    exp = (np.where(pdf.qty.fillna(-1) > 10, 1.0, 2.0) * pdf.price).sum()
    exp_sp = np.where(pdf.store == "s1", pdf.price, 0.0).sum()
    np.testing.assert_allclose(got["s"][0], exp)
    np.testing.assert_allclose(got["sp"][0], exp_sp)


def test_case_aggregate_takes_fused_join_path(tmp_path, join_tables):
    """A Case spec with string-literal conditions stays eligible for the
    fused Aggregate(Join) kernel (the TPC-H Q12 shape)."""
    from hyperspace_tpu import when
    from hyperspace_tpu.config import AGG_VENUE
    from hyperspace_tpu.plan.expr import lit as L

    fact_root, dim_root = join_tables
    session = _session(tmp_path)
    session.conf.set(AGG_VENUE, "device")
    fact = session.parquet(fact_root)
    dim = session.parquet(dim_root)
    # The condition reads the FACT side so the partial-agg pushdown
    # (which owns the dim-condition shape) stays out of the way.
    q = fact.join(dim, ["k"]).aggregate(
        [], [AggSpec.of("sum", when(col("units") >= L(5), 1.0).otherwise(0.0), "big")]
    )
    got = session.to_pandas(q)
    assert session.last_query_stats["agg_path"] == "fused-join-agg"
    f = pq.read_table(fact_root).to_pandas()
    d = pq.read_table(dim_root).to_pandas()
    j = f.merge(d, on="k")
    np.testing.assert_allclose(got["big"][0], float((j.units >= 5).sum()))


def test_partial_agg_pushdown_dim_case_matches_pandas(tmp_path, join_tables):
    """The q43/q59 shape — SUM(CASE WHEN <dim attr> THEN <fact measure>
    ELSE 0) grouped by dim attributes — pre-aggregates the fact side by
    the join key and re-folds (PartialAggPushdown), matching pandas."""
    from hyperspace_tpu import when
    from hyperspace_tpu.plan.expr import lit as L

    fact_root, dim_root = join_tables
    session = _session(tmp_path)
    fact = session.parquet(fact_root)
    dim = session.parquet(dim_root)
    q = fact.join(dim, ["k"]).aggregate(
        ["cat"],
        [
            AggSpec.of("sum", when(col("weight") > L(0.5), col("amount")).otherwise(0.0), "hv"),
            AggSpec.of("sum", "amount", "tot"),
            AggSpec.of("count", None, "n"),
            AggSpec.of("mean", "units", "mu"),
            AggSpec.of("min", "amount", "lo"),
        ],
    )
    got = session.to_pandas(q).sort_values("cat").reset_index(drop=True)
    assert "PartialAggPushdown" in repr(session.last_physical_plan)
    f = pq.read_table(fact_root).to_pandas()
    d = pq.read_table(dim_root).to_pandas()
    j = f.merge(d, on="k")
    j["hv"] = np.where(j.weight > 0.5, j.amount, 0.0)
    exp = (
        j.groupby("cat")
        .agg(hv=("hv", "sum"), tot=("amount", "sum"), n=("amount", "size"),
             mu=("units", "mean"), lo=("amount", "min"))
        .reset_index()
        .sort_values("cat")
        .reset_index(drop=True)
    )
    np.testing.assert_allclose(got.hv.to_numpy(), exp.hv.to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(got.tot.to_numpy(), exp.tot.to_numpy(), rtol=1e-9)
    np.testing.assert_array_equal(got.n.to_numpy(), exp.n.to_numpy())
    np.testing.assert_allclose(got.mu.to_numpy(), exp.mu.to_numpy(), rtol=1e-12)
    np.testing.assert_allclose(got.lo.to_numpy(), exp.lo.to_numpy(), rtol=1e-12)


def test_top_n_matches_full_sort(tmp_path):
    """ORDER BY + LIMIT takes the partition-select path and must equal
    the full sort exactly, incl. duplicate first keys and DESC order."""
    rng = np.random.default_rng(8)
    n = 60_000
    df_ = pd.DataFrame(
        {
            "r": np.round(rng.random(n), 3),  # many exact duplicates
            "id": rng.permutation(n).astype(np.int64),
        }
    )
    root = tmp_path / "top"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df_, preserve_index=False), root / "p.parquet")
    session = _session(tmp_path)
    scan = session.parquet(root)
    got = session.to_pandas(scan.sort([("r", False), ("id", True)]).limit(25))
    node = next(n_ for n_ in session.last_physical_plan.walk() if n_.op == "TopN")
    assert "partition-select" in node.detail["kernel"]
    exp = df_.sort_values(["r", "id"], ascending=[False, True]).head(25).reset_index(drop=True)
    np.testing.assert_allclose(got["r"], exp["r"])
    np.testing.assert_array_equal(got["id"], exp["id"])
    # limit 0 edge
    assert len(session.to_pandas(scan.sort(["r"]).limit(0))) == 0


@pytest.mark.parametrize("venue", ["device", "host"])
def test_distinct(tmp_path, venue):
    from hyperspace_tpu.config import AGG_VENUE

    df_ = pd.DataFrame(
        {
            "a": [1, 1, 2, 2, 2, None],
            "b": ["x", "x", "y", "y", "z", None],
        }
    )
    root = tmp_path / "d"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df_, preserve_index=False), root / "p.parquet")
    session = _session(tmp_path)
    session.conf.set(AGG_VENUE, venue)
    got = session.to_pandas(session.parquet(root).distinct())
    assert len(got) == 4
    tuples = {(None if pd.isna(a) else int(a), None if (b is None or (isinstance(b, float) and pd.isna(b))) else b)
              for a, b in zip(got["a"], got["b"])}
    assert tuples == {(1, "x"), (2, "y"), (2, "z"), (None, None)}


@pytest.mark.parametrize("with_index", [False, True])
def test_host_fused_join_aggregate_matches_device(tmp_path, join_tables, with_index):
    """The host C++ merge+accumulate fused path must match the device
    run-prefix kernel and pandas, with and without aligned indexes
    (covering both the sorted and permuted code layouts)."""
    from hyperspace_tpu import native
    from hyperspace_tpu.config import JOIN_VENUE

    if not native.available():
        pytest.skip("native library not built")
    fact_root, dim_root = join_tables
    outs = {}
    for venue in ("device", "host"):
        session = _session(tmp_path / venue)
        session.conf.set(JOIN_VENUE, venue)
        hs = Hyperspace(session)
        fact = session.parquet(fact_root)
        dim = session.parquet(dim_root)
        if with_index:
            hs.create_index(fact, IndexConfig("f_k", ["k"], ["amount", "units"]))
            hs.create_index(dim, IndexConfig("d_k", ["k"], ["cat", "weight"]))
            session.enable_hyperspace()
        q = fact.join(dim, ["k"]).aggregate(
            ["cat"],
            [
                AggSpec.of("sum", "amount", "sa"),     # secondary-side measure
                AggSpec.of("sum", "weight", "sw"),     # primary(group)-side measure
                AggSpec.of("count", None, "n"),
                AggSpec.of("mean", "amount", "ma"),
            ],
        )
        outs[venue] = session.to_pandas(q).sort_values("cat").reset_index(drop=True)
        assert session.last_query_stats["agg_path"] == "fused-join-agg"
        if venue == "host":
            assert session.last_query_stats["join_kernel"] == "host-native-merge-accumulate"
    d, h = outs["device"], outs["host"]
    assert list(d["cat"]) == list(h["cat"])
    for c in ("sa", "sw", "n", "ma"):
        np.testing.assert_allclose(d[c].astype(float), h[c].astype(float), rtol=1e-9)

    f = pq.read_table(fact_root).to_pandas()
    dd = pq.read_table(dim_root).to_pandas()
    j = f.merge(dd, on="k")
    exp = (
        j.groupby("cat")
        .agg(sa=("amount", "sum"), sw=("weight", "sum"), n=("cat", "size"), ma=("amount", "mean"))
        .reset_index().sort_values("cat").reset_index(drop=True)
    )
    np.testing.assert_allclose(h["sa"], exp["sa"])
    np.testing.assert_allclose(h["sw"], exp["sw"])
    np.testing.assert_array_equal(h["n"], exp["n"])
    np.testing.assert_allclose(h["ma"], exp["ma"])


@pytest.mark.parametrize("venue", ["device", "host"])
def test_non_finite_float_aggregates_pass_through(tmp_path, venue):
    """sum/min/max results that are legitimately NaN or inf (NaN/inf VALUES
    in a float column) come back as NaN/inf with the row still valid —
    not silently zeroed (round-2 advisor, medium). Matches Spark/numpy."""
    from hyperspace_tpu.config import AGG_VENUE

    t = pa.table(
        {
            "g": pa.array([0, 0, 1, 1, 2, 3], type=pa.int64()),
            "x": pa.array([1.0, np.nan, np.inf, 2.0, 3.0, -np.inf]),
        }
    )
    root = tmp_path / f"nf_{venue}"
    root.mkdir()
    pq.write_table(t, root / "p.parquet")
    session = _session(tmp_path)
    session.conf.set(AGG_VENUE, venue)
    df = session.parquet(root)
    q = df.aggregate(
        ["g"],
        [
            AggSpec.of("sum", "x", "s"),
            AggSpec.of("min", "x", "mn"),
            AggSpec.of("max", "x", "mx"),
        ],
    )
    got = session.to_pandas(q).sort_values("g").reset_index(drop=True)
    exp = (
        t.to_pandas()
        .groupby("g")
        .agg(s=("x", "sum"), mn=("x", "min"), mx=("x", "max"))
        .reset_index()
    )
    # pandas .sum skips NaN; SQL SUM over a NaN VALUE is NaN — pin SQL/
    # numpy semantics explicitly per group.
    assert np.isnan(got.loc[0, "s"]) and np.isnan(got.loc[0, "mn"]) and np.isnan(got.loc[0, "mx"])
    assert got.loc[1, "s"] == np.inf and got.loc[1, "mn"] == 2.0 and got.loc[1, "mx"] == np.inf
    assert got.loc[2, "s"] == 3.0
    assert got.loc[3, "s"] == -np.inf and got.loc[3, "mn"] == -np.inf
    assert not got[["s", "mn", "mx"]].isna().drop(index=0).any().any()
    np.testing.assert_array_equal(got["g"], exp["g"])


def test_host_reduceat_with_trailing_empty_groups():
    """aggregate_arrays_host called with num_groups > max(gid)+1: trailing
    empty groups must not corrupt the LAST non-empty group's min/max
    (round-2 advisor: clamped reduceat starts shrank the prior segment)."""
    from hyperspace_tpu.ops.aggregate import aggregate_arrays_host

    vals = np.array([5.0, 1.0, 9.0])
    gid = np.array([0, 0, 1])
    res, cnt = aggregate_arrays_host(
        [(vals, None, "min"), (vals, None, "max")], gid, num_groups=4
    )
    np.testing.assert_array_equal(res[0][:2], [1.0, 9.0])  # min includes sv[n-1]
    np.testing.assert_array_equal(res[1][:2], [5.0, 9.0])
    assert np.isinf(res[0][2]) and np.isinf(res[0][3])  # empty -> identity
    np.testing.assert_array_equal(cnt[0], [2, 1, 0, 0])


@pytest.mark.parametrize("venue", ["host", "device"])
def test_count_distinct(tmp_path, venue):
    """count(distinct col): two-phase re-aggregation, nulls excluded,
    combinable with plain aggregates (TPC-H Q16's shape)."""
    from hyperspace_tpu.config import AGG_VENUE

    rng = np.random.default_rng(29)
    n = 8_000
    nulls = rng.random(n) < 0.1
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 12, n).astype(np.int64),
            "supp": pd.array(np.where(nulls, 0, rng.integers(0, 300, n)), dtype="Int64"),
            "qty": rng.integers(1, 50, n).astype(np.int64),
        }
    )
    df.loc[nulls, "supp"] = pd.NA
    root = tmp_path / f"cd_{venue}"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = _session(tmp_path)
    session.conf.set(AGG_VENUE, venue)
    ds = session.parquet(root)

    q = ds.aggregate(
        ["g"],
        [
            AggSpec.of("count_distinct", "supp", "nsupp"),
            AggSpec.of("sum", "qty", "sq"),
            AggSpec.of("count", None, "rows"),
            AggSpec.of("min", "qty", "mn"),
        ],
    )
    got = session.to_pandas(q).sort_values("g").reset_index(drop=True)
    assert "CountDistinctReaggregate" in repr(session.last_physical_plan)
    exp = (
        df.groupby("g")
        .agg(
            nsupp=("supp", "nunique"),
            sq=("qty", "sum"),
            rows=("g", "size"),
            mn=("qty", "min"),
        )
        .reset_index()
    )
    np.testing.assert_array_equal(got["g"], exp["g"])
    np.testing.assert_array_equal(got["nsupp"], exp["nsupp"])
    np.testing.assert_array_equal(got["sq"], exp["sq"])
    np.testing.assert_array_equal(got["rows"], exp["rows"])
    np.testing.assert_array_equal(got["mn"], exp["mn"])

    # Global (no group) variant.
    got = session.to_pandas(ds.aggregate([], [AggSpec.of("count_distinct", "supp", "ns")]))
    assert int(got.loc[0, "ns"]) == int(df.supp.nunique())


def test_multi_distinct_and_mean_share_aggregate(tmp_path):
    """TPC-DS q38/q87 shapes: several distinct columns AND mean in ONE
    aggregate, via the distinct-expansion path (Spark's Expand analog):
    one child execution, one group factorization, pair-factorized
    distinct counts — no join, no re-execution."""
    rng = np.random.default_rng(31)
    n = 6_000
    null_a = rng.random(n) < 0.08
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 9, n).astype(np.int64),
            "a": pd.array(np.where(null_a, 0, rng.integers(0, 40, n)), dtype="Int64"),
            "b": rng.integers(0, 25, n).astype(np.int64),
            "v": np.round(rng.normal(size=n) * 10, 3),
        }
    )
    df.loc[null_a, "a"] = pd.NA
    root = tmp_path / "md"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = _session(tmp_path)
    ds = session.parquet(root)
    q = ds.aggregate(
        ["g"],
        [
            AggSpec.of("count_distinct", "a", "na"),
            AggSpec.of("count_distinct", "b", "nb"),
            AggSpec.of("mean", "v", "mv"),
            AggSpec.of("sum", "v", "sv"),
            AggSpec.of("count", None, "rows"),
        ],
    )
    got = session.to_pandas(q).sort_values("g").reset_index(drop=True)
    assert "DistinctExpandAggregate" in repr(session.last_physical_plan)
    exp = (
        df.groupby("g")
        .agg(
            na=("a", "nunique"),
            nb=("b", "nunique"),
            mv=("v", "mean"),
            sv=("v", "sum"),
            rows=("g", "size"),
        )
        .reset_index()
    )
    np.testing.assert_array_equal(got["g"], exp["g"])
    np.testing.assert_array_equal(got["na"], exp["na"])
    np.testing.assert_array_equal(got["nb"], exp["nb"])
    np.testing.assert_allclose(got["mv"], exp["mv"], rtol=1e-12)
    np.testing.assert_allclose(got["sv"], exp["sv"], rtol=1e-12)
    np.testing.assert_array_equal(got["rows"], exp["rows"])

    # Global multi-distinct (no groups).
    got = session.to_pandas(
        ds.aggregate(
            [],
            [
                AggSpec.of("count_distinct", "a", "na"),
                AggSpec.of("mean", "b", "mb"),
            ],
        )
    )
    assert int(got.loc[0, "na"]) == int(df.a.nunique())
    assert np.isclose(got.loc[0, "mb"], df.b.mean())


def test_count_distinct_empty_input_counts_are_zero(tmp_path):
    """count(*) / count(col) siblings of count_distinct stay 0 (never
    NULL) over empty input — SQL count is never NULL."""
    t = pa.table({"g": pa.array([], type=pa.int64()), "a": pa.array([], type=pa.int64())})
    root = tmp_path / "cde"
    root.mkdir()
    pq.write_table(t, root / "p.parquet")
    session = _session(tmp_path)
    ds = session.parquet(root)
    got = session.to_pandas(ds.aggregate([], [
        AggSpec.of("count_distinct", "a", "na"),
        AggSpec.of("count", None, "rows"),
    ]))
    assert int(got.loc[0, "na"]) == 0
    assert got.loc[0, "rows"] is not None and int(got.loc[0, "rows"]) == 0
