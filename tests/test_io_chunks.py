"""Chunked-read planning, footer cache, and prefetch (execution/io.py +
execution/prefetch.py): the row-group chunk planner and reader gained a
second caller (the query-tail prefetcher) and a third (the chunked cold
read), so their edge cases are pinned here directly instead of only
through the streaming build."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import stats
from hyperspace_tpu.execution import io as hio


def _write(path, n, cols=("a", "b"), row_group_size=None):
    data = {}
    rng = np.random.default_rng(n + 1)
    for c in cols:
        data[c] = rng.integers(0, 1000, n).astype(np.int64)
    t = pa.table(data)
    pq.write_table(t, path, row_group_size=row_group_size or max(n, 1))
    return str(path)


class TestChunkPlanning:
    def test_empty_file_list(self):
        assert hio.read_footers([]) == {}
        assert hio.plan_row_group_chunks([], chunk_bytes=1024) == []
        assert hio.estimate_uncompressed_bytes([]) == 0

    def test_single_row_group_larger_than_budget(self, tmp_path):
        """A row group above chunk_bytes still gets a chunk of its own
        (each chunk holds at least one row group — the planner never
        splits below row-group granularity)."""
        f = _write(tmp_path / "big.parquet", 10_000)
        chunks = hio.plan_row_group_chunks([f], chunk_bytes=16)
        assert chunks == [[(f, 0)]]
        got = hio.read_chunk(chunks[0])
        assert got.num_rows == 10_000

    def test_every_row_group_exactly_once(self, tmp_path):
        f1 = _write(tmp_path / "a.parquet", 8_000, row_group_size=1_000)
        f2 = _write(tmp_path / "b.parquet", 4_000, row_group_size=1_000)
        est = hio.estimate_uncompressed_bytes([f1, f2])
        chunks = hio.plan_row_group_chunks([f1, f2], chunk_bytes=est // 6)
        units = [u for c in chunks for u in c]
        assert len(units) == len(set(units)) == 12
        total = sum(hio.read_chunk(c).num_rows for c in chunks)
        assert total == 12_000

    def test_zero_row_file_contributes_nothing(self, tmp_path):
        fz = str(tmp_path / "zero.parquet")
        pq.write_table(pa.table({"a": pa.array([], type=pa.int64()),
                                 "b": pa.array([], type=pa.int64())}), fz)
        f = _write(tmp_path / "real.parquet", 500)
        chunks = hio.plan_row_group_chunks([fz, f], chunk_bytes=1 << 20)
        rows = sum(hio.read_chunk(c).num_rows for c in chunks)
        assert rows == 500

    def test_column_missing_from_one_file_null_fills(self, tmp_path):
        """Schema skew: a column absent from one file is skipped for
        that file and null-filled by the promoting concat — the contract
        the prefetcher relies on to probe any file without raising."""
        f1 = _write(tmp_path / "full.parquet", 100, cols=("a", "b"))
        f2 = _write(tmp_path / "narrow.parquet", 50, cols=("a",))
        chunks = hio.plan_row_group_chunks([f1, f2], chunk_bytes=1 << 30, columns=["a", "b"])
        assert len(chunks) == 1
        t = hio.read_chunk(chunks[0], columns=["a", "b"])
        assert t.num_rows == 150
        assert t.column("b").null_count == 50


class TestFooterCache:
    def test_hits_and_mtime_invalidation(self, tmp_path):
        f = _write(tmp_path / "x.parquet", 200)
        hio.clear_footer_cache()
        h0, m0 = stats.get("io.footer_cache.hits"), stats.get("io.footer_cache.misses")
        hio.read_footers([f])
        assert stats.get("io.footer_cache.misses") == m0 + 1
        md = hio.read_footers([f])[f]
        assert stats.get("io.footer_cache.hits") == h0 + 1
        assert md.num_rows == 200
        # Rewrite the file: the stale entry must not serve.
        import os

        _write(tmp_path / "x.parquet", 300)
        os.utime(f, ns=(1, 1))  # force a distinct mtime even on coarse clocks
        md = hio.read_footers([f])[f]
        assert md.num_rows == 300
        assert stats.get("io.footer_cache.misses") == m0 + 2

    def test_consumers_share_one_parse(self, tmp_path):
        f = _write(tmp_path / "y.parquet", 400, row_group_size=100)
        hio.clear_footer_cache()
        m0 = stats.get("io.footer_cache.misses")
        est = hio.estimate_uncompressed_bytes([f])
        hio.plan_row_group_chunks([f], chunk_bytes=est)
        hio.read_footers([f])
        assert stats.get("io.footer_cache.misses") == m0 + 1


class TestChunkedColdRead:
    def test_matches_per_file_read(self, tmp_path, monkeypatch):
        """The row-group-parallel cold read must return exactly what the
        serial per-file path returns (same rows, same order)."""
        f1 = _write(tmp_path / "p1.parquet", 6_000, row_group_size=500)
        f2 = _write(tmp_path / "p2.parquet", 3_000, row_group_size=500)
        expected = hio.read_parquet([f1, f2])  # below threshold: per-file path
        monkeypatch.setattr(hio, "_CHUNKED_READ_MIN_BYTES", 1)
        got = hio.read_parquet([f1, f2])
        assert got.num_rows == expected.num_rows
        for name in expected.columns:
            np.testing.assert_array_equal(got.columns[name], expected.columns[name])


class TestPrefetch:
    def test_issues_once_per_file_version(self, tmp_path):
        from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
        from hyperspace_tpu.execution import prefetch
        from hyperspace_tpu.obs import metrics as obs_metrics

        root = tmp_path / "src"
        root.mkdir()
        _write(root / "p0.parquet", 4_000, cols=("k", "v"))
        session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=4)
        hs = Hyperspace(session)
        df = session.parquet(root)
        hs.create_index(df, IndexConfig("i1", ["k"], ["v"]))
        prefetch.reset()
        session.enable_hyperspace()
        issued = obs_metrics.REGISTRY.get("io.prefetch.issued")
        base = issued.value
        q = df.filter(col("k") == 7).select("k", "v")
        session.run(q)
        prefetch.drain()
        first = issued.value - base
        assert first >= 1  # the pruned bucket file was prefetched
        session.run(q)
        prefetch.drain()
        assert issued.value - base == first  # dedup: unchanged files re-issue nothing

    def test_disabled_by_config(self, tmp_path):
        from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
        from hyperspace_tpu.config import SCAN_PREFETCH_ENABLED
        from hyperspace_tpu.execution import prefetch
        from hyperspace_tpu.obs import metrics as obs_metrics

        root = tmp_path / "src"
        root.mkdir()
        _write(root / "p0.parquet", 2_000, cols=("k", "v"))
        session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
        session.conf.set(SCAN_PREFETCH_ENABLED, False)
        hs = Hyperspace(session)
        df = session.parquet(root)
        hs.create_index(df, IndexConfig("i1", ["k"], ["v"]))
        prefetch.reset()
        session.enable_hyperspace()
        issued = obs_metrics.REGISTRY.get("io.prefetch.issued")
        base = issued.value
        session.run(df.filter(col("k") == 3).select("k", "v"))
        prefetch.drain()
        assert issued.value == base
