"""Computed projections: SELECT <expr> AS x with 3-valued null
semantics, typed via expr_dtype, JSON round-trip, and optimizer
integration (column pruning keeps only what the expressions reference;
index rules cover computed entries by their input references). The
reference gets all of this from Catalyst's Project for free — here the
IR owns it (plan/nodes.py Project, ops/project.py)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, lit, when
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.nodes import plan_from_json


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("projdata")
    rng = np.random.default_rng(7)
    n = 2_000
    null_a = rng.random(n) < 0.1
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 40, n).astype(np.int64),
            "a": pd.array(np.where(null_a, 0, rng.integers(1, 90, n)), dtype="Int64"),
            "f": np.round(rng.normal(size=n) * 5, 3),
            "s": np.array(["AIR", "MAIL", "RAIL", "SHIP"], dtype=object)[
                rng.integers(0, 4, n)
            ],
        }
    )
    df.loc[null_a, "a"] = pd.NA
    root = tmp_path / "t"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    ds = session.parquet(root)
    return session, ds, df


def test_arithmetic_projection_nulls(data):
    session, ds, df = data
    q = ds.select("k", ("x", col("a") * lit(2) + col("k")), ("r", col("f") / lit(2.0)))
    got = session.to_pandas(q).sort_values(["k", "x", "r"]).reset_index(drop=True)
    exp = pd.DataFrame(
        {
            "k": df.k,
            "x": df.a * 2 + df.k,  # null propagates
            "r": df.f / 2.0,
        }
    ).sort_values(["k", "x", "r"]).reset_index(drop=True)
    assert got.x.isna().sum() == exp.x.isna().sum() > 0
    np.testing.assert_allclose(
        got.x.fillna(-1).to_numpy(dtype=np.float64),
        exp.x.fillna(-1).to_numpy(dtype=np.float64),
    )
    np.testing.assert_allclose(got.r.to_numpy(), exp.r.to_numpy())


def test_case_and_bool_projection(data):
    session, ds, df = data
    q = ds.select(
        ("big", col("a") > 40),
        ("bucket", when(col("a") > 40, 1).otherwise(0)),
    )
    got = session.to_pandas(q)
    known = df.a.notna()
    # Boolean projection: NULL where the comparison is unknown.
    assert got.big.isna().sum() == int((~known).sum())
    exp_big = (df.a > 40)[known].to_numpy(dtype=bool)
    np.testing.assert_array_equal(got.big[known.to_numpy()].to_numpy(dtype=bool), exp_big)
    # CASE with a null condition takes the ELSE leg (never null here).
    assert got.bucket.isna().sum() == 0
    exp_bucket = np.where(df.a.fillna(0) > 40, 1, 0)
    np.testing.assert_array_equal(got.bucket.to_numpy(dtype=np.int64), exp_bucket)


def test_substr_projection_keeps_sorted_codes(data):
    session, ds, df = data
    q = ds.select(("pfx", col("s").substr(1, 2)), "s").filter(col("pfx") == "MA")
    got = session.to_pandas(q)
    assert set(got.s) == {"MAIL"}
    assert len(got) == int((df.s == "MAIL").sum())


def test_projection_json_roundtrip(data):
    _, ds, _ = data
    q = ds.select("k", ("x", (col("a") + lit(1)) * col("k")))
    d = q.to_json()
    back = plan_from_json(d)
    assert back.schema.names == q.schema.names
    assert back.to_json() == d


def test_projection_over_index_join(data, tmp_path):
    """Computed projection above an indexed join still answers correctly
    (the aligned path falls back when it cannot absorb the expression)."""
    session, ds, df = data
    hs = Hyperspace(session)
    hs.create_index(ds, IndexConfig("pj_k", ["k"], ["a"]))
    other = ds.select("k", "f").aggregate(["k"], [("sum", "f", "sf")])
    q = ds.join(other, ["k"]).select("k", ("score", col("a") + col("sf")))
    session.enable_hyperspace()
    got = session.to_pandas(q)
    merged = df.merge(df.groupby("k").f.sum().rename("sf").reset_index(), on="k")
    exp = (merged.a + merged.sf).astype(np.float64)
    assert len(got) == len(merged)
    assert got.score.isna().sum() == merged.a.isna().sum()
    np.testing.assert_allclose(
        np.sort(got.score.dropna().to_numpy(dtype=np.float64)),
        np.sort(exp.dropna().to_numpy()),
        rtol=1e-9,
    )


def test_with_column_and_pruning(data):
    session, ds, df = data
    q = ds.with_column("half", col("f") / lit(2.0)).select("half")
    got = session.to_pandas(q)
    np.testing.assert_allclose(np.sort(got.half.to_numpy()), np.sort(df.f.to_numpy() / 2))
    # Pruning: the executed scan read only f (the expression's input).
    phys = repr(session.last_physical_plan)
    assert "half" in phys


def test_aggregate_over_computed_projection(data):
    session, ds, df = data
    q = ds.select("k", ("ab", col("a") * col("f"))).aggregate(
        ["k"], [("sum", "ab", "s_ab"), ("count", None, "n")]
    )
    got = session.to_pandas(q).sort_values("k").reset_index(drop=True)
    dfx = df.assign(ab=df.a.astype("Float64") * df.f)
    exp = (
        dfx.groupby("k")
        .agg(s_ab=("ab", "sum"), n=("ab", "size"))
        .reset_index()
        .sort_values("k")
        .reset_index(drop=True)
    )
    np.testing.assert_allclose(
        got.s_ab.to_numpy(dtype=np.float64),
        exp.s_ab.to_numpy(dtype=np.float64),
        rtol=1e-9,
    )
    np.testing.assert_array_equal(got.n.to_numpy(), exp.n.to_numpy())
