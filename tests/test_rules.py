"""Fake-backend rule tests: synthetic plans + injectable signature provider.

Mirror of the reference's level-3 rule tests (rules/JoinIndexRuleTest.scala,
FilterIndexRuleTest.scala, RuleTestHelper.scala:193-202): plans are built by
hand over nonexistent paths, index entries are fabricated, and the
signature provider fingerprints the scan ROOT string — so rule logic is
exercised with zero file IO. 15+ positive/negative join-condition shapes
(JoinIndexRuleTest.scala:107-343 has the analogous matrix).
"""

import pytest

from hyperspace_tpu.metadata.log_entry import (
    Content,
    CoveringIndex,
    Fingerprint,
    IndexLogEntry,
    Source,
    VectorIndex,
)
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import Filter, Join, Project, Scan, Union
from hyperspace_tpu.rules import base as rules_base
from hyperspace_tpu.rules.base import apply_rules
from hyperspace_tpu.rules.filter_index_rule import FilterIndexRule
from hyperspace_tpu.rules.join_index_rule import JoinIndexRule
from hyperspace_tpu.rules.ranker import JoinIndexRanker
from hyperspace_tpu.schema import Field, Schema
from hyperspace_tpu.signature import SignatureProvider


class RootSignatureProvider(SignatureProvider):
    """Fingerprint = sorted scan roots — no IO (RuleTestHelper analog)."""

    name = "rootBased"

    def signature(self, plan):
        roots = sorted(s.root for s in plan.leaves())
        return Fingerprint(kind=self.name, value="|".join(roots))


@pytest.fixture(autouse=True)
def root_signatures(monkeypatch):
    monkeypatch.setattr(
        rules_base, "create_signature_provider", lambda name="rootBased": RootSignatureProvider()
    )


T1 = Schema.of(Field("a", "int64"), Field("b", "int64"), Field("v", "float64"))
T2 = Schema.of(Field("c", "int64"), Field("d", "int64"), Field("w", "float64"))


def scan1() -> Scan:
    return Scan("/nonexistent/t1", "parquet", T1)


def scan2() -> Scan:
    return Scan("/nonexistent/t2", "parquet", T2)


def entry(name, root, schema, indexed, included, buckets=8) -> IndexLogEntry:
    sel = schema.select(indexed + included)
    return IndexLogEntry(
        id=1,
        state="ACTIVE",
        name=name,
        derived_dataset=CoveringIndex(indexed, included, sel.to_json(), buckets),
        content=Content(root=f"/nonexistent/idx/{name}", directories=["v__=0"]),
        source=Source(
            plan=Scan(root, "parquet", schema).to_json(),
            fingerprint=Fingerprint(kind="rootBased", value=root),
            files=[],
        ),
    )


def vector_entry(name, root) -> IndexLogEntry:
    return IndexLogEntry(
        id=1,
        state="ACTIVE",
        name=name,
        derived_dataset=VectorIndex("emb", ["a"], [], 8, 16),
        content=Content(root=f"/nonexistent/idx/{name}", directories=["v__=0"]),
        source=Source(
            plan=Scan(root, "parquet", T1).to_json(),
            fingerprint=Fingerprint(kind="rootBased", value=root),
            files=[],
        ),
    )


def join_plan(left_on=("a",), right_on=("c",)):
    return Join(
        Project(scan1(), ["a", "v"]),
        Project(scan2(), ["c", "w"]),
        list(left_on),
        list(right_on),
    )


def rewritten_sides(plan):
    return [s for s in plan.leaves() if s.bucket_spec is not None]


class TestJoinIndexRule:
    def run(self, plan, entries):
        return JoinIndexRule().apply(plan, entries)

    def test_exact_pair_rewrites_both_sides(self):
        out = self.run(
            join_plan(),
            [
                entry("l", "/nonexistent/t1", T1, ["a"], ["v"]),
                entry("r", "/nonexistent/t2", T2, ["c"], ["w"]),
            ],
        )
        assert len(rewritten_sides(out)) == 2

    def test_lone_candidate_rewrites_one_side_for_the_exchange(self):
        # Only the left side has a usable index: the rule rewrites THAT
        # side alone — the executor's re-bucketing exchange pairs it
        # with the arbitrary right side (the ranker's mismatched-pair
        # fallback generalized, JoinIndexRanker.scala:31-34).
        out = self.run(join_plan(), [entry("l", "/nonexistent/t1", T1, ["a"], ["v"])])
        sides = rewritten_sides(out)
        assert len(sides) == 1
        assert sides[0].bucket_spec[1] == ["a"]

    def test_indexed_columns_must_be_set_equal_to_join_cols(self):
        # Index on (a, b) but join only on a — superset is NOT usable
        # (JoinIndexRule.scala:515-524).
        out = self.run(
            join_plan(),
            [
                entry("l", "/nonexistent/t1", T1, ["a", "b"], ["v"]),
                entry("r", "/nonexistent/t2", T2, ["c"], ["w"]),
            ],
        )
        # The (a, b) superset index is unusable; the right side still
        # rewrites one-sided for the exchange.
        sides = rewritten_sides(out)
        assert len(sides) == 1 and sides[0].bucket_spec[1] == ["c"]

    def test_index_must_cover_required_columns(self):
        out = self.run(
            join_plan(),
            [
                entry("l", "/nonexistent/t1", T1, ["a"], []),  # v not covered
                entry("r", "/nonexistent/t2", T2, ["c"], ["w"]),
            ],
        )
        sides = rewritten_sides(out)
        assert len(sides) == 1 and sides[0].bucket_spec[1] == ["c"]

    def test_signature_mismatch_blocks_side(self):
        out = self.run(
            join_plan(),
            [
                entry("l", "/other/root", T1, ["a"], ["v"]),  # wrong fingerprint
                entry("r", "/nonexistent/t2", T2, ["c"], ["w"]),
            ],
        )
        sides = rewritten_sides(out)
        assert len(sides) == 1 and sides[0].bucket_spec[1] == ["c"]

    def test_compound_keys_compatible_order_rewrites(self):
        plan = Join(scan1(), scan2(), ["a", "b"], ["c", "d"])
        out = self.run(
            plan,
            [
                entry("l", "/nonexistent/t1", T1, ["a", "b"], ["v"]),
                entry("r", "/nonexistent/t2", T2, ["c", "d"], ["w"]),
            ],
        )
        assert len(rewritten_sides(out)) == 2

    def test_compound_keys_order_mismatch_blocks(self):
        # Left lists (a, b); the mapped right order must be (c, d) — an
        # index on (d, c) is incompatible (JoinIndexRule.scala:547-594).
        plan = Join(scan1(), scan2(), ["a", "b"], ["c", "d"])
        out = self.run(
            plan,
            [
                entry("l", "/nonexistent/t1", T1, ["a", "b"], ["v"]),
                entry("r", "/nonexistent/t2", T2, ["d", "c"], ["w"]),
            ],
        )
        # No compatible PAIR — a one-sided rewrite still applies (the
        # executor re-buckets or falls back safely; ordered
        # compatibility only gates the paired zero-exchange claim).
        assert len(rewritten_sides(out)) == 1

    def test_repeated_join_column_blocks(self):
        plan = Join(scan1(), scan2(), ["a", "a"], ["c", "d"])
        out = self.run(
            plan,
            [
                entry("l", "/nonexistent/t1", T1, ["a"], ["v"]),
                entry("r", "/nonexistent/t2", T2, ["c", "d"], ["w"]),
            ],
        )
        assert not rewritten_sides(out)

    def test_self_join_same_relation_object_blocks(self):
        s = scan1()
        plan = Join(Project(s, ["a", "v"]), Project(s, ["a", "b"]), ["a"], ["a"])
        out = self.run(plan, [entry("l", "/nonexistent/t1", T1, ["a"], ["v", "b"])])
        assert not rewritten_sides(out)

    def test_filter_side_requires_predicate_columns_covered(self):
        plan = Join(
            Filter(scan1(), col("b") > 1),  # b required by the predicate
            Project(scan2(), ["c", "w"]),
            ["a"],
            ["c"],
        )
        out = self.run(
            plan,
            [
                entry("l", "/nonexistent/t1", T1, ["a"], ["v"]),  # b missing
                entry("r", "/nonexistent/t2", T2, ["c"], ["w"]),
            ],
        )
        sides = rewritten_sides(out)
        assert len(sides) == 1 and sides[0].bucket_spec[1] == ["c"]

    def test_ranker_prefers_equal_bucket_pair(self):
        e_l8 = entry("l8", "/nonexistent/t1", T1, ["a"], ["v", "b"], buckets=8)
        e_l16 = entry("l16", "/nonexistent/t1", T1, ["a"], ["v", "b"], buckets=16)
        e_r8 = entry("r8", "/nonexistent/t2", T2, ["c"], ["w", "d"], buckets=8)
        out = self.run(join_plan(), [e_l8, e_l16, e_r8])
        sides = rewritten_sides(out)
        assert len(sides) == 2
        assert all(s.bucket_spec[0] == 8 for s in sides), "equal-bucket pair must win"

    def test_vector_index_entries_are_skipped(self):
        out = self.run(join_plan(), [vector_entry("vl", "/nonexistent/t1")])
        assert not rewritten_sides(out)

    def test_inner_join_of_nested_plan_rewritten_via_recursion(self):
        inner = join_plan()
        outer = Project(inner, ["a", "v", "w"])
        out = JoinIndexRule().apply(
            outer,
            [
                entry("l", "/nonexistent/t1", T1, ["a"], ["v"]),
                entry("r", "/nonexistent/t2", T2, ["c"], ["w"]),
            ],
        )
        assert len(rewritten_sides(out)) == 2


class TestFilterIndexRule:
    def run(self, plan, entries):
        return FilterIndexRule().apply(plan, entries)

    def test_covering_filter_rewrites(self):
        plan = Project(Filter(scan1(), col("a") == 5), ["a", "v"])
        out = self.run(plan, [entry("f", "/nonexistent/t1", T1, ["a"], ["v"])])
        assert rewritten_sides(out)

    def test_filter_must_reference_first_indexed_column(self):
        plan = Project(Filter(scan1(), col("b") == 5), ["b", "v"])
        out = self.run(plan, [entry("f", "/nonexistent/t1", T1, ["a", "b"], ["v"])])
        assert not rewritten_sides(out)

    def test_coverage_required(self):
        plan = Project(Filter(scan1(), col("a") == 5), ["a", "v"])
        out = self.run(plan, [entry("f", "/nonexistent/t1", T1, ["a"], [])])
        assert not rewritten_sides(out)

    def test_bare_filter_requires_full_schema_coverage(self):
        plan = Filter(scan1(), col("a") == 5)  # output = all of T1
        out = self.run(plan, [entry("f", "/nonexistent/t1", T1, ["a"], ["v"])])  # b missing
        assert not rewritten_sides(out)
        out = self.run(plan, [entry("f2", "/nonexistent/t1", T1, ["a"], ["b", "v"])])
        assert rewritten_sides(out)

    def test_index_scan_never_rewritten_twice(self):
        idx_scan = Scan("/nonexistent/idx", "parquet", T1.select(["a", "v"]), bucket_spec=(8, ["a"]))
        plan = Project(Filter(idx_scan, col("a") == 5), ["a", "v"])
        out = self.run(plan, [entry("f", "/nonexistent/idx", T1, ["a"], ["v"])])
        assert out is plan or rewritten_sides(out) == [idx_scan]

    def test_signature_mismatch_blocks(self):
        plan = Project(Filter(scan1(), col("a") == 5), ["a", "v"])
        out = self.run(plan, [entry("f", "/other/root", T1, ["a"], ["v"])])
        assert not rewritten_sides(out)

    def test_vector_index_entries_are_skipped(self):
        plan = Project(Filter(scan1(), col("a") == 5), ["a", "v"])
        out = self.run(plan, [vector_entry("v", "/nonexistent/t1")])
        assert not rewritten_sides(out)


class TestRuleOrderingAndSafety:
    def test_join_rule_runs_before_filter_rule(self):
        # A filter-under-join side: the JOIN rewrite must win the relation
        # (ordering is load-bearing, package.scala:23-33).
        plan = Join(
            Filter(scan1(), col("a") > 0),
            Project(scan2(), ["c", "w"]),
            ["a"],
            ["c"],
        )
        entries = [
            entry("l", "/nonexistent/t1", T1, ["a"], ["v", "b"]),
            entry("r", "/nonexistent/t2", T2, ["c"], ["w", "d"]),
        ]
        out = apply_rules(plan, entries)
        sides = rewritten_sides(out)
        assert len(sides) == 2
        assert all(s.bucket_spec is not None for s in sides)

    def test_rule_exception_downgrades_to_noop(self):
        class ExplodingRule(FilterIndexRule):
            def apply(self, plan, indexes):
                raise RuntimeError("boom")

        plan = Project(Filter(scan1(), col("a") == 5), ["a", "v"])
        out = apply_rules(plan, [], rules=[ExplodingRule()])
        assert out is plan  # never breaks the query (FilterIndexRule.scala:76-80)


def test_ranker_ordering_matrix():
    def e(buckets):
        return entry(f"e{buckets}", "/r", T1, ["a"], [], buckets=buckets)

    p_eq_small = (e(8), e(8))
    p_eq_big = (e(16), e(16))
    p_uneq_big = (e(32), e(16))
    ranked = JoinIndexRanker.rank([p_uneq_big, p_eq_small, p_eq_big])
    # Equal-bucket pairs first, larger equal pair preferred
    # (JoinIndexRanker.scala:28-37).
    assert ranked[0] == p_eq_big
    assert ranked[1] == p_eq_small
    assert ranked[2] == p_uneq_big
