"""Operation log tests, incl. the CAS conflict contract.

Analog of index/IndexLogManagerImplTest.scala:94-150 ("writeLog pass if no
other file exists with same name").
"""

from hyperspace_tpu import states
from hyperspace_tpu.metadata.log_manager import IndexLogManager

from tests.test_log_entry import make_entry


def test_write_and_read(tmp_path):
    lm = IndexLogManager(tmp_path / "idx1")
    entry = make_entry()
    assert lm.write_log(0, entry)
    got = lm.get_log(0)
    assert got is not None and got.name == "idx1" and got.id == 0
    assert lm.get_log(5) is None


def test_write_log_cas_conflict(tmp_path):
    lm = IndexLogManager(tmp_path / "idx1")
    assert lm.write_log(0, make_entry()) is True
    # Second write to the same id loses the race.
    assert lm.write_log(0, make_entry()) is False


def test_latest_id_and_log(tmp_path):
    lm = IndexLogManager(tmp_path / "idx1")
    assert lm.get_latest_id() is None
    assert lm.get_latest_log() is None
    for i in range(3):
        e = make_entry()
        e.state = states.CREATING if i < 2 else states.ACTIVE
        assert lm.write_log(i, e)
    assert lm.get_latest_id() == 2
    assert lm.get_latest_log().state == states.ACTIVE


def test_latest_stable_pointer_and_fallback(tmp_path):
    lm = IndexLogManager(tmp_path / "idx1")
    e0 = make_entry()
    e0.state = states.CREATING
    lm.write_log(0, e0)
    e1 = make_entry()
    e1.state = states.ACTIVE
    lm.write_log(1, e1)

    # No pointer yet: backward-scan fallback finds id 1.
    got = lm.get_latest_stable_log()
    assert got is not None and got.id == 1 and got.state == states.ACTIVE

    # Create the pointer; it should now be preferred.
    assert lm.create_latest_stable_log(1)
    e2 = make_entry()
    e2.state = states.DELETING
    lm.write_log(2, e2)
    got = lm.get_latest_stable_log()
    assert got.id == 1 and got.state == states.ACTIVE

    # Pointer to a non-stable entry is refused.
    assert not lm.create_latest_stable_log(2)

    assert lm.delete_latest_stable_log()
    # Fallback still works after pointer deletion.
    assert lm.get_latest_stable_log().id == 1


def test_concurrent_writers_exactly_one_wins(tmp_path):
    """Optimistic concurrency under real thread contention: N threads race
    to commit the same log id; exactly one write_log returns True
    (IndexLogManager.scala:138-154 — rename loser gets false)."""
    import threading

    lm = IndexLogManager(tmp_path / "race")
    n_threads = 8
    barrier = threading.Barrier(n_threads)
    results = [None] * n_threads

    def contend(i):
        e = make_entry()
        e.state = states.CREATING
        barrier.wait()
        results[i] = lm.write_log(0, e)

    threads = [threading.Thread(target=contend, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(1 for r in results if r) == 1, results
    assert lm.get_latest_id() == 0


def test_concurrent_actions_second_aborts(tmp_path):
    """Two actions racing run(): the loser aborts with the reference's
    'Could not acquire proper state' error (Action.scala:75-80)."""
    import threading

    from hyperspace_tpu.actions.base import Action
    from hyperspace_tpu.exceptions import HyperspaceError

    lm = IndexLogManager(tmp_path / "race2")

    class SlowAction(Action):
        transient_state = states.CREATING
        final_state = states.ACTIVE

        def __init__(self, lm, gate):
            super().__init__(lm)
            self.gate = gate

        def build_log_entry(self):
            return make_entry()

        def op(self):
            # Both actions may reach op() (loser can fail later, in end());
            # a broken/aborted barrier just means the other thread already
            # errored out — proceed either way.
            try:
                self.gate.wait(timeout=5)
            except threading.BrokenBarrierError:
                pass

    gate = threading.Barrier(2, timeout=10)
    errors = []

    def run_action():
        try:
            SlowAction(lm, gate).run()
        except HyperspaceError as e:
            errors.append(str(e))
            gate.abort()  # release a winner still blocked in op()

    threads = [threading.Thread(target=run_action) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(errors) == 1 and "Could not acquire proper state" in errors[0]
    assert lm.get_latest_log().state == states.ACTIVE


def test_concurrent_writers_one_wins_without_hardlinks(tmp_path, monkeypatch):
    """The no-hardlink degraded path (O_EXCL lock file) admits exactly one
    winner under contention — the former check-then-rename fallback had a
    window where two writers could both pass the existence check."""
    import threading

    from hyperspace_tpu.utils.file_utils import atomic_write

    def no_link(src, dst, **kw):
        raise OSError("hard links unsupported")

    monkeypatch.setattr("os.link", no_link)

    target = tmp_path / "nolink" / "0"
    n_threads = 8
    barrier = threading.Barrier(n_threads)
    results = [None] * n_threads

    def contend(i):
        barrier.wait()
        results[i] = atomic_write(target, f"writer-{i}".encode())

    threads = [threading.Thread(target=contend, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(1 for r in results if r) == 1, results
    winner = results.index(True)
    assert target.read_bytes() == f"writer-{winner}".encode()
    # Late writer after the winner: lock is free again, but the CAS fails.
    assert atomic_write(target, b"late") is False
    assert not target.with_name("0.lock").exists()


def test_cached_index_tables_are_frozen(tmp_path):
    """Tables handed out by the decoded-table cache are read-only: an
    accidental in-place write raises instead of corrupting every later
    query that shares the cache entry."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.execution import io as hio

    p = tmp_path / "frozen.parquet"
    pq.write_table(pa.table({"k": [1, 2, 3], "s": ["a", "b", None]}), p)
    t = hio.read_parquet_cached([str(p)])
    import numpy as np
    import pytest as _pytest

    with _pytest.raises(ValueError):
        t.columns["k"][0] = 99
    with _pytest.raises(ValueError):
        t.validity["s"][0] = False
    # A second read returns the same (uncorrupted) object.
    assert hio.read_parquet_cached([str(p)]).columns["k"][0] == 1


def test_stale_lock_is_reaped_and_write_retried(tmp_path, monkeypatch):
    """A crashed writer's leaked lock does not wedge the no-hardlink path:
    the next writer claims the stale lock atomically and wins the CAS."""
    import os

    from hyperspace_tpu.utils.file_utils import atomic_write

    monkeypatch.setattr("os.link", lambda *a, **k: (_ for _ in ()).throw(OSError()))

    target = tmp_path / "staledir" / "0"
    target.parent.mkdir()
    lock = target.with_name("0.lock")
    # A real (token-bearing) lock whose creator epoch is ancient — mtime is
    # deliberately FRESH to prove staleness comes from the token, not the
    # filesystem clock.
    lock.write_text("1000000000.000000:deadbeef")

    assert atomic_write(target, b"payload") is True
    assert target.read_bytes() == b"payload"
    assert not lock.exists()
