"""Crash-consistency and fault-tolerance tests (docs/fault_tolerance.md).

The core property, swept mechanically: for EVERY fault point an action
passes through (discovered per action with `faults.recording()`), a hard
crash injected at that point must leave the index either fully present
or cleanly absent — `get_latest_stable_log()` still resolves,
`recover()` converges to a stable log with no orphan version dirs, and a
subsequent query answers correctly (through the index when it survived,
through the source otherwise). Plus: transparent retry of transient IO,
typed corruption errors, query-plane fallback on a truncated bucket
file, in-process rollback of failed op()s, and lazy recover-on-access.
"""

from pathlib import Path

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, faults, states, stats
from hyperspace_tpu.config import (
    HYPERSPACE_LOG_DIR,
    RECOVER_GRACE_SECONDS,
    DATA_VERSION_PREFIX,
)
from hyperspace_tpu.exceptions import IndexCorruptionError, is_retryable
from hyperspace_tpu.faults import CrashPoint, FaultError
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.utils import retry


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends with the harness disarmed and a fast
    retry schedule (no real sleeping)."""
    import time

    faults.reset()
    retry.configure(max_attempts=3, backoff_base=0.0, sleeper=lambda s: None)
    yield
    faults.reset()
    retry.configure(max_attempts=3, backoff_base=0.005, sleeper=time.sleep)


def _write_source(root: Path, n: int = 60) -> str:
    rng = np.random.default_rng(7)
    table = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "key": pa.array((np.arange(n, dtype=np.int64) * 13) % 10),
            "value": pa.array(rng.standard_normal(n)),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(table.slice(0, n // 2), root / "part-0.parquet")
    pq.write_table(table.slice(n // 2), root / "part-1.parquet")
    return str(root)


def _expected(source: str) -> pd.DataFrame:
    import pyarrow.dataset as pads

    df = pads.dataset(source, format="parquet").to_table().to_pandas()
    return df[df["key"] == 7][["key", "value"]]


def _query_matches(session, source: str) -> None:
    """The canonical correctness probe: filter on the indexed column,
    compare row-identically against pandas over the raw source."""
    q = session.parquet(source).filter(col("key") == 7).select("key", "value")
    got = session.to_pandas(q)
    exp = _expected(source)
    cols = ["key", "value"]
    pd.testing.assert_frame_equal(
        got[cols].sort_values(cols).reset_index(drop=True),
        exp[cols].sort_values(cols).reset_index(drop=True),
        check_dtype=False,
    )


# ---------------------------------------------------------------------------
# Fault-injection harness unit behavior
# ---------------------------------------------------------------------------


class TestFaultHarness:
    def test_disabled_harness_is_inert(self):
        faults.fault_point("log.write", "/nope")  # disarmed: must not raise

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faults.inject("not.a.point")

    def test_default_rule_raises_transient_fault_error(self):
        with faults.injected("log.write"):
            with pytest.raises(FaultError) as ei:
                faults.fault_point("log.write")
            assert is_retryable(ei.value)

    def test_fail_at_call_k(self):
        with faults.injected("bucket.read", at_call=3):
            faults.fault_point("bucket.read")
            faults.fault_point("bucket.read")
            with pytest.raises(FaultError):
                faults.fault_point("bucket.read")
            faults.fault_point("bucket.read")  # call 4: clean again

    def test_fail_n_then_succeed(self):
        with faults.injected("bucket.read", times=2):
            for _ in range(2):
                with pytest.raises(FaultError):
                    faults.fault_point("bucket.read")
            faults.fault_point("bucket.read")  # budget spent

    def test_truncate_schedule_mangles_file(self, tmp_path):
        p = tmp_path / "f"
        p.write_bytes(b"x" * 100)
        with faults.injected("bucket.written", truncate=10):
            faults.fault_point("bucket.written", p)
        assert p.stat().st_size == 10

    def test_kill_switch_disarms_registered_rules(self):
        faults.inject("log.write", crash=True)
        faults.set_enabled(False)
        try:
            faults.fault_point("log.write")  # inert despite the rule
        finally:
            faults.set_enabled(True)
            faults.reset()

    def test_crash_point_is_base_exception(self):
        assert not isinstance(CrashPoint("p"), Exception)

    def test_recording_observes_points(self):
        with faults.recording() as seen:
            faults.fault_point("log.write")
            faults.fault_point("manifest.read")
        assert {"log.write", "manifest.read"} <= seen


# ---------------------------------------------------------------------------
# Brownout (slow-path) injection
# ---------------------------------------------------------------------------


class TestBrownoutDelay:
    """`delay_s` rules: the point goes SLOW instead of failed — with a
    virtual sleeper the accounting is wall-clock-free, jitter is a
    deterministic function of the call counter, the kill switch disarms
    a delay already in flight, and delay composes before error."""

    def _virtual(self):
        slept = []
        faults.set_sleeper(slept.append)
        return slept

    def test_pure_delay_slows_then_proceeds(self):
        slept = self._virtual()
        delays0 = stats.get("faults.delays_injected")
        with faults.injected("bucket.read", delay_s=0.2):
            faults.fault_point("bucket.read")  # must NOT raise
        assert sum(slept) == pytest.approx(0.2)
        assert stats.get("faults.delays_injected") == delays0 + 1

    def test_jitter_is_deterministic_per_call(self):
        def schedule():
            slept = self._virtual()
            totals = []
            with faults.injected("bucket.read", delay_s=0.1, jitter_s=0.05):
                for _ in range(3):
                    slept.clear()
                    faults.fault_point("bucket.read")
                    totals.append(round(sum(slept), 6))
            return totals

        first, second = schedule(), schedule()
        assert first == second  # same schedule every run, no RNG
        assert len(set(first)) > 1  # the jitter actually varies per call
        for n, total in enumerate(first, start=1):
            expect = 0.1 + 0.05 * ((n * 2654435761) % 1000) / 1000.0
            assert total == pytest.approx(expect)

    def test_delay_composes_before_error(self):
        slept = self._virtual()
        with faults.injected("bucket.read", delay_s=0.3, error=FaultError):
            with pytest.raises(FaultError):
                faults.fault_point("bucket.read")
        assert sum(slept) == pytest.approx(0.3)  # slow FIRST, then failed

    def test_kill_switch_disarms_a_delay_in_flight(self):
        slept = []

        def sleeper(s):
            slept.append(s)
            faults.set_enabled(False)  # flipped mid-delay

        faults.set_sleeper(sleeper)
        faults.inject("bucket.read", delay_s=10.0)
        try:
            faults.fault_point("bucket.read")
        finally:
            faults.set_enabled(True)
            faults.reset()
        # one slice at most ran; the remaining ~10s were abandoned
        assert sum(slept) <= 0.1

    def test_delay_clamped_by_max_delay(self):
        slept = self._virtual()
        faults.set_max_delay(0.1)
        try:
            with faults.injected("bucket.read", delay_s=60.0, jitter_s=60.0):
                faults.fault_point("bucket.read")
        finally:
            faults.set_max_delay(30.0)
        assert sum(slept) == pytest.approx(0.1)

    def test_deadline_carrying_path_times_out_typed_under_delay(self):
        """A brownout under a deadline-carrying path surfaces a TYPED
        QueryTimeout — delayed queries must never hang their callers."""
        import threading

        from hyperspace_tpu.config import HyperspaceConf
        from hyperspace_tpu.exceptions import QueryTimeout
        from hyperspace_tpu.serve.scheduler import QueryServer

        class _Session:
            conf = HyperspaceConf()
            _state_lock = threading.RLock()
            index_health = {}

        faults.inject("bucket.read", delay_s=0.5)  # real sleeper: real slowness
        server = QueryServer(
            _Session(), workers=1, max_queue_depth=8,
            run_fn=lambda p: faults.fault_point("bucket.read"),
        )
        try:
            slow = server.submit(object())  # occupies the only worker
            queued = server.submit(object(), timeout=0.05)  # expires queued
            with pytest.raises(QueryTimeout):
                queued.result(timeout=10.0)
            slow.result(timeout=10.0)  # the delayed query itself completes
        finally:
            faults.reset()
            server.shutdown()


# ---------------------------------------------------------------------------
# Retry / backoff
# ---------------------------------------------------------------------------


class TestRetry:
    def test_transient_errors_retry_then_succeed(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise FaultError("transient")
            return "ok"

        assert retry.retry_call(flaky) == "ok"
        assert len(calls) == 3

    def test_non_retryable_surfaces_immediately(self):
        calls = []

        def missing():
            calls.append(1)
            raise FileNotFoundError("gone")

        with pytest.raises(FileNotFoundError):
            retry.retry_call(missing)
        assert len(calls) == 1

    def test_exhaustion_reraises_last_error(self):
        def always():
            raise FaultError("still down")

        with pytest.raises(FaultError):
            retry.retry_call(always, policy=retry.RetryPolicy(max_attempts=2))

    def test_backoff_schedule_is_deterministic(self):
        p = retry.RetryPolicy(backoff_base=0.01, backoff_multiplier=2.0, backoff_max=0.05)
        assert [p.delay(a) for a in range(4)] == [0.01, 0.02, 0.04, 0.05]

    def test_sleeper_receives_backoff(self):
        slept = []
        retry.configure(sleeper=slept.append, backoff_base=0.01)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise FaultError("x")

        retry.retry_call(flaky, policy=retry.RetryPolicy(backoff_base=0.01))
        assert slept == [0.01, 0.02]

    def test_create_index_survives_transient_log_write_faults(self, tmp_path):
        """fail-2-then-succeed on the log entry CAS write: the retry
        layer absorbs it and the create commits normally."""
        source = _write_source(tmp_path / "src")
        session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
        hs = Hyperspace(session)
        before = stats.get("retry.attempts")
        faults.inject("file.atomic_write", times=2)
        hs.create_index(session.parquet(source), IndexConfig("ridx", ["key"], ["value"]))
        faults.reset()
        lm = IndexLogManager(Path(tmp_path / "sys") / "ridx")
        assert lm.get_latest_log().state == states.ACTIVE
        assert stats.get("retry.attempts") - before >= 2


# ---------------------------------------------------------------------------
# Typed corruption + manifest atomicity
# ---------------------------------------------------------------------------


class TestCorruptionDetection:
    def test_garbage_manifest_raises_typed_error(self, tmp_path):
        from hyperspace_tpu.execution import io as hio

        vdir = tmp_path / "idx" / "v__=0"
        vdir.mkdir(parents=True)
        (vdir / hio.MANIFEST_NAME).write_text('{"numBuckets": 2, "bucketRo')
        with pytest.raises(IndexCorruptionError) as ei:
            hio.read_manifest(vdir)
        assert ei.value.index_root == str(tmp_path / "idx")

    def test_absent_manifest_is_none_not_error(self, tmp_path):
        from hyperspace_tpu.execution import io as hio

        vdir = tmp_path / "empty"
        vdir.mkdir()
        assert hio.read_manifest(vdir) is None

    def test_crash_during_manifest_write_never_tears_it(self, tmp_path):
        """write_manifest goes through the atomic temp+replace path: a
        crash mid-write leaves either no manifest or the previous one —
        never a parse error."""
        from hyperspace_tpu.execution import io as hio

        vdir = tmp_path / "v__=0"
        faults.inject("file.write_json", crash=True)
        with pytest.raises(CrashPoint):
            hio.write_manifest(vdir, 2, ["key"], [3, 4])
        faults.reset()
        assert hio.read_manifest(vdir) is None  # absent, not torn

    def test_torn_log_entry_still_resolves_stable(self, tmp_path):
        """A truncated trailing log entry must not break reads: the
        backward scan skips it, and recover() quarantines it."""
        source = _write_source(tmp_path / "src")
        session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
        hs = Hyperspace(session)
        hs.create_index(session.parquet(source), IndexConfig("tidx", ["key"], ["value"]))
        index_path = Path(tmp_path / "sys") / "tidx"
        lm = IndexLogManager(index_path)
        # Torn write of a would-be entry 2: half a JSON object.
        (index_path / HYPERSPACE_LOG_DIR / "2").write_text('{"id": 2, "state": "REFR')
        stable = lm.get_latest_stable_log()
        assert stable is not None and stable.state == states.ACTIVE
        report = hs.recover("tidx")
        assert report["quarantined_entries"] == 1
        assert lm.get_latest_id() == 1
        assert lm.get_latest_log().state == states.ACTIVE


# ---------------------------------------------------------------------------
# Graceful degradation: corrupt bucket file → source-scan fallback
# ---------------------------------------------------------------------------


class TestCorruptionFallback:
    def test_truncated_bucket_degrades_to_source_scan(self, tmp_path):
        source = _write_source(tmp_path / "src")
        session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
        hs = Hyperspace(session)
        df = session.parquet(source)
        hs.create_index(df, IndexConfig("cidx", ["key"], ["value"]))
        session.enable_hyperspace()
        _query_matches(session, source)  # index path works when healthy

        # Truncate EVERY bucket file (whichever bucket the predicate
        # prunes to, the read fails) and drop the decoded-table cache so
        # the corruption is actually read.
        from hyperspace_tpu.execution import io as hio

        vdir = Path(tmp_path / "sys") / "cidx" / f"{DATA_VERSION_PREFIX}0"
        for f in sorted(vdir.glob("bucket-*.parquet")):
            with open(f, "r+b") as fh:
                fh.truncate(7)
        hio.clear_table_cache()

        before = stats.get("fallback.queries")
        _query_matches(session, source)  # answers via source fallback
        assert stats.get("fallback.queries") > before
        assert session.index_health, "corrupt index not quarantined"
        assert session.last_query_stats.get("degraded_indexes")
        # Sticky: the next query plans straight past the broken index.
        _query_matches(session, source)

    def test_fallback_disabled_surfaces_typed_error(self, tmp_path):
        source = _write_source(tmp_path / "src")
        session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
        hs = Hyperspace(session)
        df = session.parquet(source)
        hs.create_index(df, IndexConfig("cidx2", ["key"], ["value"]))
        session.enable_hyperspace()
        session.conf.set("hyperspace.fallback.enabled", False)
        from hyperspace_tpu.execution import io as hio

        vdir = Path(tmp_path / "sys") / "cidx2" / f"{DATA_VERSION_PREFIX}0"
        for f in sorted(vdir.glob("bucket-*.parquet")):
            with open(f, "r+b") as fh:
                fh.truncate(7)
        hio.clear_table_cache()
        q = session.parquet(source).filter(col("key") == 7).select("key", "value")
        with pytest.raises(IndexCorruptionError):
            session.run(q)


# ---------------------------------------------------------------------------
# In-process rollback of a failed op()
# ---------------------------------------------------------------------------


class TestOpFailureRollback:
    def test_failed_build_rolls_back_and_quarantines(self, tmp_path):
        from hyperspace_tpu.actions.create import CreateAction
        from hyperspace_tpu.config import HyperspaceConf

        source = _write_source(tmp_path / "src")
        conf = HyperspaceConf(system_path=str(tmp_path / "sys"), num_buckets=2)
        index_path = Path(tmp_path / "sys") / "bidx"
        lm, dm = IndexLogManager(index_path), IndexDataManager(index_path)

        class PartialWriter:
            def write(self, plan, columns, indexed_columns, num_buckets, dest_path):
                Path(dest_path).mkdir(parents=True, exist_ok=True)
                (Path(dest_path) / "bucket-00000.parquet").write_bytes(b"partial")
                raise ValueError("builder blew up mid-carve")

        from hyperspace_tpu.dataset import Dataset

        plan = Dataset.parquet(source).scan()
        cfg = IndexConfig("bidx", ["key"], ["value"])
        with pytest.raises(ValueError, match="mid-carve"):
            CreateAction(plan, cfg, lm, dm, index_path, conf, PartialWriter()).run()
        # Log rolled back to a stable state; pointer resolves.
        assert lm.get_latest_log().state == states.DOESNOTEXIST
        assert lm.get_latest_stable_log().state == states.DOESNOTEXIST
        # Partial version dir quarantined, version id reusable.
        assert dm.get_version_ids() == []
        assert list(index_path.glob(".quarantine-*")), "partial dir not quarantined"


# ---------------------------------------------------------------------------
# recover(): explicit and lazy
# ---------------------------------------------------------------------------


def _make_index(tmp_path, name="idx1"):
    source = _write_source(tmp_path / "src")
    session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
    hs = Hyperspace(session)
    hs.create_index(session.parquet(source), IndexConfig(name, ["key"], ["value"]))
    return source, session, hs, Path(tmp_path / "sys") / name


class TestRecover:
    def test_recover_rolls_crashed_refresh_and_gcs_orphan(self, tmp_path):
        source, session, hs, index_path = _make_index(tmp_path)
        lm, dm = IndexLogManager(index_path), IndexDataManager(index_path)
        # Fake a refresh that died after begin() + a partial v__=1.
        dead = lm.get_latest_log().with_state(states.REFRESHING)
        assert lm.write_log(2, dead)
        orphan = index_path / f"{DATA_VERSION_PREFIX}1"
        orphan.mkdir()
        (orphan / "bucket-00000.parquet").write_bytes(b"junk")

        report = hs.recover("idx1")
        assert report["rolled"] and report["orphans_removed"] == 1
        latest = lm.get_latest_log()
        assert latest.state == states.ACTIVE
        assert dm.get_version_ids() == [0]
        assert lm.get_latest_stable_log().id == latest.id
        # Idempotent.
        again = hs.recover("idx1")
        assert not again["rolled"] and again["orphans_removed"] == 0
        # The index still serves queries.
        session.enable_hyperspace()
        _query_matches(session, source)

    def test_lazy_recover_on_first_access(self, tmp_path):
        source, session, hs, index_path = _make_index(tmp_path, "lazy1")
        lm = IndexLogManager(index_path)
        dead = lm.get_latest_log().with_state(states.REFRESHING)
        assert lm.write_log(2, dead)
        # Fresh session (fresh cache); grace 0 so staleness is immediate.
        s2 = HyperspaceSession(system_path=str(Path(tmp_path) / "sys"), num_buckets=2)
        s2.conf.set(RECOVER_GRACE_SECONDS, 0)
        entries = s2.manager.get_indexes()
        assert [e.state for e in entries] == [states.ACTIVE]
        assert lm.get_latest_log().state == states.ACTIVE  # healed on disk

    def test_lazy_recover_respects_grace_for_live_writers(self, tmp_path):
        source, session, hs, index_path = _make_index(tmp_path, "lazy2")
        lm = IndexLogManager(index_path)
        dead = lm.get_latest_log().with_state(states.REFRESHING)  # fresh timestamp
        assert lm.write_log(2, dead)
        s2 = HyperspaceSession(system_path=str(Path(tmp_path) / "sys"), num_buckets=2)
        # Default grace (300s): a just-written transient entry could be a
        # LIVE writer — listing must not cancel it.
        s2.manager.get_indexes()
        assert lm.get_latest_log().state == states.REFRESHING


# ---------------------------------------------------------------------------
# THE SWEEP: a crash at every fault point of every action
# ---------------------------------------------------------------------------

ACTIONS = ("create", "refresh", "optimize", "vacuum")


def _setup(tmp_path, action):
    """Fresh source + session; for non-create actions, a healthy ACTIVE
    index (and DELETED for vacuum) built with the harness disarmed."""
    source = _write_source(tmp_path / "src")
    session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
    hs = Hyperspace(session)
    if action != "create":
        hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    if action == "vacuum":
        hs.delete_index("idx1")
    return source, session, hs


def _drive(hs, session, source, action):
    if action == "create":
        hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    elif action == "refresh":
        hs.refresh_index("idx1")
    elif action == "optimize":
        hs.optimize_index("idx1")
    elif action == "vacuum":
        hs.vacuum_index("idx1")


def _assert_crash_consistent(tmp_path, source, action, point):
    """Post-crash invariants + recovery convergence + query correctness."""
    ctx = f"action={action} point={point}"
    index_path = Path(tmp_path / "sys") / "idx1"
    lm = IndexLogManager(index_path)
    dm = IndexDataManager(index_path)
    # 1. The last stable state still resolves (no exception), crash or not.
    lm.get_latest_stable_log()
    # 2. recover() converges: stable latest entry, refreshed pointer,
    #    no orphan version dirs.
    s2 = HyperspaceSession(system_path=str(Path(tmp_path) / "sys"), num_buckets=2)
    hs2 = Hyperspace(s2)
    hs2.recover("idx1")
    latest = lm.get_latest_log()
    if latest is not None:
        assert latest.state in states.STABLE_STATES, ctx
        stable = lm.get_latest_stable_log()
        assert stable is not None and stable.id == latest.id, ctx
        referenced = (
            set(stable.content.directories)
            if stable.state != states.DOESNOTEXIST and stable.content is not None
            else set()
        )
        on_disk = {f"{DATA_VERSION_PREFIX}{v}" for v in dm.get_version_ids()}
        assert on_disk <= referenced, f"{ctx}: orphan version dirs {on_disk - referenced}"
        # Index-is-never-half: if the log says ACTIVE, the data it points
        # to is complete enough to answer queries (checked below).
    # 3. recover is idempotent.
    again = hs2.recover("idx1")
    assert not again["rolled"] and again["orphans_removed"] == 0, ctx
    # 4. Queries answer correctly — via the index when it survived, via
    #    the source (or fallback) otherwise.
    s2.enable_hyperspace()
    _query_matches(s2, source)


@pytest.mark.parametrize("action", ACTIONS)
def test_crash_sweep_every_fault_point(tmp_path_factory, action):
    """For each fault point the action passes through, replay the action
    from scratch with a hard crash at that point's first firing, then
    require full crash consistency (see _assert_crash_consistent)."""
    # Discovery pass: which points does this action exercise?
    base = tmp_path_factory.mktemp(f"disc-{action}")
    source, session, hs = _setup(base, action)
    with faults.recording() as seen:
        _drive(hs, session, source, action)
    points = sorted(seen)
    assert points, f"no fault points observed for {action}"

    crashed_at = []
    for point in points:
        tmp = tmp_path_factory.mktemp(f"sweep-{action}")
        source, session, hs = _setup(tmp, action)
        faults.inject(point, crash=True, at_call=1)
        try:
            _drive(hs, session, source, action)
        except CrashPoint:
            crashed_at.append(point)
        finally:
            faults.reset()
        _assert_crash_consistent(tmp, source, action, point)
    # The sweep only proves something if crashes actually fired.
    assert crashed_at, f"no crash fired for {action} across {points}"


def test_crash_sweep_mid_schedule_calls(tmp_path_factory):
    """Crashes at LATER calls of high-frequency points (the 2nd bucket
    write, the 2nd log write) — the first-firing sweep above can miss
    states only reachable mid-sequence."""
    for point, k in (("log.write", 2), ("bucket.written", 2), ("file.write_json", 2)):
        tmp = tmp_path_factory.mktemp("sweepk")
        source, session, hs = _setup(tmp, "create")
        faults.inject(point, crash=True, at_call=k)
        try:
            _drive(hs, session, source, "create")
        except CrashPoint:
            pass
        finally:
            faults.reset()
        _assert_crash_consistent(tmp, source, "create", f"{point}@{k}")


# ---------------------------------------------------------------------------
# Streaming-build pipeline fault points (docs/architecture.md "build
# pipeline"): a hard crash anywhere inside the p2 pipeline — the spill
# read, the queue put, the queue get — must leave no spill scratch
# behind, a recoverable log, and correct query answers. These points
# only exist on the pipelined out-of-core path, so the generic sweep
# above (in-memory builds) cannot reach them.
# ---------------------------------------------------------------------------


def _streaming_session(tmp_path):
    from hyperspace_tpu.config import INDEX_BUILD_CHUNK_BYTES, INDEX_BUILD_MEMORY_BUDGET

    source = _write_source(tmp_path / "src", n=600)
    session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
    # A budget far below the source forces the streaming (and therefore
    # pipelined) build inside CreateAction.
    session.conf.set(INDEX_BUILD_MEMORY_BUDGET, 2_000)
    session.conf.set(INDEX_BUILD_CHUNK_BYTES, 4_000)
    return source, session, Hyperspace(session)


@pytest.mark.parametrize("point", ["spill.read", "pipeline.put", "pipeline.get"])
def test_crash_mid_pipeline_streaming_build(tmp_path, point):
    source, session, hs = _streaming_session(tmp_path)
    faults.inject(point, crash=True, at_call=1)
    crashed = False
    try:
        hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    except CrashPoint:
        crashed = True
    finally:
        faults.reset()
    assert crashed, f"crash at {point} never fired (pipeline not exercised?)"
    # The spill scratch dir must not survive the crash (the pipeline's
    # stop flag unblocks every stage so the builder's cleanup runs).
    leftovers = list((tmp_path / "sys").rglob("*.spill"))
    assert not leftovers, f"spill scratch survived the crash: {leftovers}"
    _assert_crash_consistent(tmp_path, source, "create", point)


def test_transient_spill_read_fault_rolls_back(tmp_path):
    """A persistent FaultError in the pipeline surfaces through the
    builder (reader → sort stage re-raise), Action.run rolls back, and a
    clean retry succeeds."""
    source, session, hs = _streaming_session(tmp_path)
    with faults.injected("spill.read"):
        with pytest.raises(OSError):
            hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    assert not list((tmp_path / "sys").rglob("*.spill"))
    hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    session.enable_hyperspace()
    _query_matches(session, source)


def test_prefetch_fault_is_advisory(tmp_path):
    """Injected failures at prefetch.issue must never fail a query — the
    prefetcher counts the error and the executor's own read path serves
    the data (the advisory contract of execution/prefetch.py)."""
    from hyperspace_tpu.execution import prefetch
    from hyperspace_tpu.obs import metrics as obs_metrics

    source = _write_source(tmp_path / "src")
    session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
    hs = Hyperspace(session)
    hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    prefetch.reset()  # forget any issue history from the build-time session
    session.enable_hyperspace()
    with faults.injected("prefetch.issue"):
        _query_matches(session, source)
        prefetch.drain()
    errors = obs_metrics.REGISTRY.get("io.prefetch.errors")
    assert errors is not None and errors.value >= 1


# ---------------------------------------------------------------------------
# Scale-out pooled build fault points (docs/architecture.md "scale-out
# build"): the crash sweep extended across the PROCESS boundary. The
# coordinator ships its registered rules into every spawned worker
# (faults.export_state / install_state via parallel/procpool.py), so a
# crash rule at a worker-side point (`build.exchange.write` in a p1
# shard, `build.exchange.read` in a p2 owner) kills the worker process
# for real — no result ever posts — and the coordinator's bounded join
# must convert that into a typed WorkerCrashed abort, sweep the
# exchange/spill scratch, roll the action back, and leave recover()
# convergent with queries still correct.
# ---------------------------------------------------------------------------


def _pooled_session(tmp_path):
    from hyperspace_tpu.config import BUILD_WORKERS

    source = _write_source(tmp_path / "src", n=600)
    session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
    session.conf.set(BUILD_WORKERS, 2)
    return source, session, Hyperspace(session)


def _assert_no_build_scratch(tmp_path):
    leftovers = [
        p for pat in ("*.exchange", "*.spill") for p in (tmp_path / "sys").rglob(pat)
    ]
    assert not leftovers, f"build scratch survived the abort: {leftovers}"


@pytest.mark.parametrize("point", ["build.exchange.write", "build.exchange.read"])
def test_worker_killed_mid_build_typed_abort(tmp_path, point):
    """Worker killed mid-p1 (exchange.write) / mid-p2 (exchange.read):
    the CrashPoint unwinds out of the WORKER process (a real process
    death — spawn workers get no cleanup), the coordinator aborts with
    the typed WorkerCrashed, and the build rolls back cleanly."""
    from hyperspace_tpu.exceptions import WorkerCrashed

    source, session, hs = _pooled_session(tmp_path)
    faults.inject(point, crash=True, at_call=1)
    try:
        with pytest.raises(WorkerCrashed):
            hs.create_index(
                session.parquet(source), IndexConfig("idx1", ["key"], ["value"])
            )
    finally:
        faults.reset()
    _assert_no_build_scratch(tmp_path)
    assert stats.get("build.worker.crashes") >= 1
    _assert_crash_consistent(tmp_path, source, "create", point)
    # A clean retry (next "process") succeeds end to end.
    hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    session.enable_hyperspace()
    _query_matches(session, source)


@pytest.mark.parametrize("point", ["build.worker.spawn", "build.manifest.merge"])
def test_coordinator_crash_mid_pooled_build(tmp_path, point):
    """Coordinator-side pooled points: a hard crash at worker spawn or
    at the manifest merge dies like any writer death — exchange swept by
    the builder's finally, recover() converges."""
    source, session, hs = _pooled_session(tmp_path)
    faults.inject(point, crash=True, at_call=1)
    crashed = False
    try:
        hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    except CrashPoint:
        crashed = True
    finally:
        faults.reset()
    assert crashed, f"crash at {point} never fired"
    _assert_no_build_scratch(tmp_path)
    _assert_crash_consistent(tmp_path, source, "create", point)


def test_transient_worker_fault_aborts_typed_then_retries_clean(tmp_path):
    """A transient FaultError inside a worker posts back through the
    result queue, the coordinator aborts with the typed WorkerFailed
    (Action.run rolls back), and a clean retry succeeds."""
    from hyperspace_tpu.exceptions import WorkerFailed

    source, session, hs = _pooled_session(tmp_path)
    with faults.injected("build.exchange.write"):
        with pytest.raises(WorkerFailed) as ei:
            hs.create_index(
                session.parquet(source), IndexConfig("idx1", ["key"], ["value"])
            )
        assert ei.value.error_type == "FaultError"
    _assert_no_build_scratch(tmp_path)
    hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    session.enable_hyperspace()
    _query_matches(session, source)


# ---------------------------------------------------------------------------
# Device staging fault point (docs/architecture.md "device data path"):
# `device.stage` fires before each zero-copy column view. A transient
# fault degrades THAT COLUMN to the copied host path (the query still
# answers, bytes land in device.stage.bytes_copied); a crash is a hard
# death like any other — the read path holds no partial state, so
# recover() is a convergent no-op and a clean retry serves correctly.
# ---------------------------------------------------------------------------


def _staged_query_session(tmp_path):
    from hyperspace_tpu import stats

    source = _write_source(tmp_path / "src")
    session = HyperspaceSession(system_path=str(tmp_path / "sys"), num_buckets=2)
    hs = Hyperspace(session)
    hs.create_index(session.parquet(source), IndexConfig("idx1", ["key"], ["value"]))
    session.enable_hyperspace()
    stats.reset()
    return source, session, hs


def test_transient_stage_fault_degrades_to_copied_host_path(tmp_path):
    from hyperspace_tpu import stats
    from hyperspace_tpu.execution import io as hio

    source, session, hs = _staged_query_session(tmp_path)
    hio.clear_table_cache()
    with faults.injected("device.stage"):
        _query_matches(session, source)
    # Every staging attempt faulted: nothing crossed zero-copy, the
    # copied path carried the whole read, and the answer was correct.
    assert stats.get("device.stage.bytes_zero_copy") == 0
    assert stats.get("device.stage.bytes_copied") > 0
    assert stats.get("faults.injected") >= 1
    # With the harness disarmed the same read stages zero-copy again.
    hio.clear_table_cache()
    _query_matches(session, source)
    assert stats.get("device.stage.bytes_zero_copy") > 0


def test_stage_crash_is_hard_death_then_clean_retry(tmp_path):
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.faults import CrashPoint

    source, session, hs = _staged_query_session(tmp_path)
    hio.clear_table_cache()
    with faults.injected("device.stage", crash=True):
        with pytest.raises(CrashPoint):
            _query_matches(session, source)
    # The read path holds no partial on-disk state: recovery converges
    # trivially and the next "process" serves the query correctly.
    hs.recover()
    hio.clear_table_cache()
    _query_matches(session, source)


def test_stage_fault_off_switch_disarms(tmp_path):
    """hyperspace.faults.enabled=false must make device.stage inert even
    with a rule registered (the production kill-switch contract)."""
    from hyperspace_tpu import stats
    from hyperspace_tpu.config import FAULTS_ENABLED
    from hyperspace_tpu.execution import io as hio

    source, session, hs = _staged_query_session(tmp_path)
    hio.clear_table_cache()
    session.conf.set(FAULTS_ENABLED, False)
    try:
        with faults.injected("device.stage"):
            _query_matches(session, source)
            assert stats.get("device.stage.bytes_zero_copy") > 0
    finally:
        session.conf.set(FAULTS_ENABLED, True)
