"""Static-analysis subsystem tests: trace-safety lint rules + the
pre-execution plan validator (analysis/)."""

from __future__ import annotations

import subprocess
import sys
import textwrap

import pytest

from hyperspace_tpu.analysis.lint import lint_source, lint_paths, main as lint_main
from hyperspace_tpu.analysis.validator import (
    check_plan,
    validate_plan,
    validate_rewrite,
)
from hyperspace_tpu.exceptions import (
    PlanDiagnostic,
    PlanRewriteError,
    PlanValidationError,
)
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    AggSpec,
    Filter,
    Join,
    Project,
    Scan,
    Sort,
    Union,
    Window,
    WindowSpec,
)
from hyperspace_tpu.schema import Field, Schema


# -- lint rule fixtures ------------------------------------------------------

def rules_of(src: str, path: str = "<fixture>.py") -> list[str]:
    return [f.rule for f in lint_source(textwrap.dedent(src), path)]


class TestLintFragileImports:
    def test_from_jax_import_shard_map_flagged(self):
        assert rules_of("from jax import shard_map\n") == ["HSL001"]

    def test_from_jax_import_enable_x64_flagged(self):
        assert rules_of("from jax import enable_x64\n") == ["HSL001"]

    def test_jax_experimental_from_import_flagged(self):
        assert rules_of("from jax.experimental import pallas\n") == ["HSL001"]

    def test_jax_experimental_submodule_import_flagged(self):
        assert rules_of("from jax.experimental.shard_map import shard_map\n") == ["HSL001"]
        assert rules_of("import jax.experimental.pallas\n") == ["HSL001"]

    def test_compat_module_is_sanctioned(self):
        src = "from jax.experimental.shard_map import shard_map\n"
        assert lint_source(src, "hyperspace_tpu/compat.py") == []

    def test_stable_jax_imports_clean(self):
        assert rules_of("from jax import lax\nimport jax.numpy as jnp\n") == []

    def test_noqa_suppresses(self):
        assert rules_of("from jax import shard_map  # noqa: HSL001\n") == []

    def test_noqa_other_rule_does_not_suppress(self):
        assert rules_of("from jax import shard_map  # noqa: HSL002\n") == ["HSL001"]


class TestLintHostSync:
    def test_item_in_jitted_function(self):
        src = """
        import jax
        @jax.jit
        def f(x):
            return x.item()
        """
        assert rules_of(src) == ["HSL002"]

    def test_float_cast_in_wrapped_function(self):
        # jax.jit(fn) wrapping marks fn as traced even without a decorator.
        src = """
        import jax
        def make():
            def fn(x):
                return float(x)
            return jax.jit(fn)
        """
        assert rules_of(src) == ["HSL002"]

    def test_np_asarray_under_shard_map(self):
        src = """
        import functools, numpy as np
        from hyperspace_tpu.compat import shard_map
        @functools.partial(shard_map, mesh=None, in_specs=(), out_specs=())
        def f(x):
            return np.asarray(x)
        """
        assert rules_of(src) == ["HSL002"]

    def test_host_sync_outside_jit_is_fine(self):
        src = """
        def f(x):
            return float(x.item())
        """
        assert rules_of(src) == []


class TestLintTracedControlFlow:
    def test_if_on_traced_param(self):
        src = """
        import jax
        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """
        assert rules_of(src) == ["HSL003"]

    def test_while_on_traced_param(self):
        src = """
        import jax
        @jax.jit
        def f(x):
            while x < 10:
                x = x + 1
            return x
        """
        assert rules_of(src) == ["HSL003"]

    def test_shape_attribute_is_static(self):
        src = """
        import jax
        @jax.jit
        def f(x):
            if x.shape[0] > 1:
                return x
            return -x
        """
        assert rules_of(src) == []

    def test_static_argnames_param_is_exempt(self):
        src = """
        import functools, jax
        @functools.partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            if n > 3:
                return x
            return -x
        """
        assert rules_of(src) == []


class TestLintStaticArgsAndRandomness:
    def test_list_static_argnums_flagged(self):
        src = """
        import jax
        def f(x, n):
            return x
        g = jax.jit(f, static_argnums=[1])
        """
        assert rules_of(src) == ["HSL004"]

    def test_tuple_static_argnames_clean(self):
        src = """
        import functools, jax
        @functools.partial(jax.jit, static_argnames=("cap",))
        def f(x, cap):
            return x
        """
        assert rules_of(src) == []

    def test_global_numpy_rng_flagged(self):
        assert rules_of("import numpy as np\nv = np.random.rand(3)\n") == ["HSL005"]

    def test_unseeded_default_rng_flagged(self):
        assert rules_of("import numpy as np\nr = np.random.default_rng()\n") == ["HSL005"]

    def test_seeded_default_rng_clean(self):
        assert rules_of("import numpy as np\nr = np.random.default_rng(0)\n") == []

    def test_stdlib_random_flagged(self):
        assert rules_of("import random\nv = random.random()\n") == ["HSL005"]


class TestMetadataWriteBypass:
    """HSL006: bare writes to metadata-plane paths (the operation log,
    latestStable, the index manifest, version dirs) are torn writes
    waiting for a crash — only file_utils.py may open them for writing."""

    def test_manifest_write_text_flagged(self):
        # The exact seed bug shape (execution/io.py write_manifest).
        src = "(dest_dir / MANIFEST_NAME).write_text(json.dumps(m))\n"
        assert rules_of(src) == ["HSL006"]

    def test_log_dir_open_write_flagged(self):
        src = "f = open(self.log_dir / str(id), 'w')\n"
        assert rules_of(src) == ["HSL006"]

    def test_latest_stable_write_bytes_flagged(self):
        src = "(log_dir / LATEST_STABLE_LOG_NAME).write_bytes(data)\n"
        assert rules_of(src) == ["HSL006"]

    def test_version_dir_write_flagged(self):
        src = "(root / 'v__=0' / name).write_text(payload)\n"
        assert rules_of(src) == ["HSL006"]

    def test_unrelated_write_text_clean(self):
        assert rules_of("report_path.write_text(text)\n") == []

    def test_read_mode_open_clean(self):
        assert rules_of("open(self.log_dir / str(id)).read()\n") == []

    def test_file_utils_is_sanctioned(self):
        src = "open(log_dir / 'latestStable', 'w').write(data)\n"
        from hyperspace_tpu.analysis.lint import lint_source

        assert lint_source(src, "hyperspace_tpu/utils/file_utils.py") == []

    def test_noqa_suppresses(self):
        src = "(dest_dir / MANIFEST_NAME).write_text(m)  # noqa: HSL006\n"
        assert rules_of(src) == []


class TestLintUnlockedGlobalMutation:
    def test_unlocked_function_mutation_flagged(self):
        src = """
        _cache = {}
        def put(k, v):
            _cache[k] = v
        """
        assert rules_of(src) == ["HSL008"]

    def test_method_call_mutators_flagged(self):
        src = """
        _seen: set = set()
        def record(x):
            _seen.add(x)
        """
        assert rules_of(src) == ["HSL008"]

    def test_pop_and_del_flagged(self):
        src = """
        _cache = dict()
        def evict(k, j):
            _cache.pop(k)
            del _cache[j]
        """
        assert rules_of(src) == ["HSL008", "HSL008"]

    def test_mutation_under_lock_clean(self):
        src = """
        import threading
        _cache = {}
        _lock = threading.Lock()
        def put(k, v):
            with _lock:
                _cache[k] = v
        """
        assert rules_of(src) == []

    def test_module_level_mutation_clean(self):
        # Import-time initialization is single-threaded by construction.
        src = """
        _registry = {}
        _registry["default"] = object()
        """
        assert rules_of(src) == []

    def test_local_container_clean(self):
        src = """
        def collect(items):
            out = []
            for i in items:
                out.append(i)
            return out
        """
        assert rules_of(src) == []

    def test_read_only_use_clean(self):
        src = """
        _cache = {}
        def get(k):
            return _cache.get(k)
        """
        assert rules_of(src) == []

    def test_allowlisted_obs_singletons_clean(self):
        # The allowlist is keyed on (basename, name): trace.py's
        # singleton plumbing mutates by design.
        src = """
        NOOP = {}
        def poke():
            NOOP["x"] = 1
        """
        from hyperspace_tpu.analysis.lint import lint_source

        assert lint_source(textwrap.dedent(src), "hyperspace_tpu/obs/trace.py") == []

    def test_noqa_suppresses(self):
        src = """
        _cache = {}
        def put(k, v):
            _cache[k] = v  # noqa: HSL008
        """
        assert rules_of(src) == []


class TestLintCli:
    def test_repo_package_is_clean(self):
        # The permanent guarantee behind the compat satellite: the whole
        # package passes its own linter (CI runs this as a gate).
        import hyperspace_tpu

        pkg_dir = hyperspace_tpu.__path__[0]
        assert lint_paths([pkg_dir]) == []

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        good = tmp_path / "good.py"
        good.write_text("from jax import lax\n")
        assert lint_main([str(bad)]) == 1
        assert lint_main([str(good)]) == 0

    def test_module_invocation(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import numpy as np\nv = np.random.rand(3)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "hyperspace_tpu.analysis.lint", str(bad)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "HSL005" in proc.stdout

    def test_syntax_error_is_a_finding(self, tmp_path):
        f = tmp_path / "broken.py"
        f.write_text("def f(:\n")
        findings = lint_paths([str(f)])
        assert [x.rule for x in findings] == ["HSL000"]


# -- plan validator ----------------------------------------------------------

SCHEMA = Schema.of(
    Field("k", "int32"),
    Field("v", "float64"),
    Field("s", "string"),
    Field("d", "date"),
    Field("emb", "vector", dim=4),
)


def scan(schema=SCHEMA, **kw) -> Scan:
    return Scan("/data/t", "parquet", schema, **kw)


def rules(plan) -> list[str]:
    return [d.rule for d in validate_plan(plan)]


class TestValidatorMalformedPlans:
    """The >=5 malformed-plan classes from the issue, each rejected with
    a diagnostic naming the offending node."""

    def test_clean_plan_validates(self):
        plan = Filter(scan(), (col("k") > 5) & (col("v") <= 2.5)).select("k", "v")
        assert validate_plan(plan) == []
        check_plan(plan)  # must not raise

    def test_mismatched_join_bucket_specs(self):
        left = scan(bucket_spec=(8, ["k"]))
        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int32"), Field("w", "float32")),
                     bucket_spec=(16, ["k"]))
        plan = Join(left, right, ["k"], ["k"])
        diags = validate_plan(plan)
        assert [d.rule for d in diags] == ["join-bucket-mismatch"]
        assert diags[0].node == "Join"
        assert "8" in diags[0].message and "16" in diags[0].message
        # Warning severity: executable (falls back to a re-shuffle), but
        # check_plan promotes it on request.
        check_plan(plan)
        with pytest.raises(PlanValidationError) as ei:
            check_plan(plan, fail_on="warning")
        assert "join-bucket-mismatch" in str(ei.value)

    def test_mismatched_bucket_hash_domains(self):
        # Equal counts, equal key names — but int32 vs int64 key dtypes
        # hash differently, so the "aligned" pair can never align.
        left = scan(bucket_spec=(8, ["k"]))
        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int64"), Field("w", "float32")),
                     bucket_spec=(8, ["k"]))
        diags = validate_plan(Join(left, right, ["k"], ["k"]))
        assert [d.rule for d in diags] == ["join-bucket-mismatch"]
        assert "dtype domain" in diags[0].message

    def test_unresolved_column(self):
        diags = validate_plan(Filter(scan(), col("missing") > 5))
        assert [d.rule for d in diags] == ["unresolved-column"]
        assert diags[0].node == "Filter"
        assert "'missing'" in diags[0].message
        with pytest.raises(PlanValidationError):
            check_plan(Filter(scan(), col("missing") > 5))

    def test_unresolved_join_key(self):
        right = Scan("/data/u", "parquet", Schema.of(Field("k", "int32")))
        diags = validate_plan(Join(scan(), right, ["k"], ["nope"]))
        assert [d.rule for d in diags] == ["unresolved-column"]
        assert diags[0].node == "Join"

    def test_dtype_incompatible_predicate(self):
        diags = validate_plan(Filter(scan(), col("s") > 5))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]
        assert "string" in diags[0].message

    def test_non_boolean_predicate(self):
        diags = validate_plan(Filter(scan(), col("k") + 1))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]
        assert "expected bool" in diags[0].message

    def test_string_arithmetic(self):
        diags = validate_plan(Project(scan(), [("x", col("s") * 2)]))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]
        assert "arithmetic" in diags[0].message

    def test_bad_sort_key(self):
        diags = validate_plan(Sort(scan(), [("emb", True)]))
        assert [d.rule for d in diags] == ["unsortable-key"]
        assert diags[0].node == "Sort"
        with pytest.raises(PlanValidationError):
            check_plan(Sort(scan(), [("emb", True)]))

    def test_illegal_pushdown(self):
        # A left outer join: filtering the RIGHT side before the join
        # changes null-extension semantics. The rewrite guard catches a
        # pushed conjunct the original never had below that side.
        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int32"), Field("w", "float32")))
        pred = col("w") > 1.0
        original = Filter(Join(scan(), right, ["k"], ["k"], how="left"), pred)
        bad_rewrite = Join(scan(), Filter(right, pred), ["k"], ["k"], how="left")
        with pytest.raises(PlanRewriteError) as ei:
            validate_rewrite(original, bad_rewrite)
        assert ei.value.diagnostics[0].rule == "illegal-pushdown"
        assert "right" in ei.value.diagnostics[0].path

    def test_illegal_prune(self):
        # A rewrite that narrowed a scan below a filter still referencing
        # the pruned column must be rejected.
        import dataclasses

        base = scan(Schema.of(Field("k", "int32"), Field("v", "float64")))
        original = Filter(base, col("v") > 1.0).select("k", "v")
        pruned = dataclasses.replace(base, scan_schema=base.scan_schema.select(["k"]))
        bad_rewrite = Filter(pruned, col("v") > 1.0).select("k")
        with pytest.raises(PlanRewriteError) as ei:
            validate_rewrite(original, bad_rewrite)
        assert any(d.rule == "unresolved-column" for d in ei.value.diagnostics)

    def test_rewrite_schema_change(self):
        original = scan().select("k", "v")
        bad_rewrite = scan().select("k")
        with pytest.raises(PlanRewriteError) as ei:
            validate_rewrite(original, bad_rewrite)
        assert ei.value.diagnostics[0].rule == "rewrite-schema-change"

    def test_legal_rewrite_passes(self):
        from hyperspace_tpu.plan.prune import prune_columns
        from hyperspace_tpu.plan.pushdown import push_down_filters

        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int32"), Field("w", "float32")))
        plan = Filter(
            Join(scan(), right, ["k"], ["k"]), (col("v") > 0.5) & (col("w") > 1.0)
        ).select("k", "v", "w")
        validate_rewrite(plan, prune_columns(push_down_filters(plan)))


class TestValidatorMoreRules:
    def test_bad_bucket_spec_count(self):
        diags = validate_plan(scan(bucket_spec=(0, ["k"])))
        assert [d.rule for d in diags] == ["bad-bucket-spec"]

    def test_bucket_column_missing(self):
        diags = validate_plan(scan(bucket_spec=(8, ["zz"])))
        assert [d.rule for d in diags] == ["unresolved-column"]
        assert diags[0].node == "Scan"

    def test_join_key_domain_mismatch(self):
        right = Scan("/data/u", "parquet", Schema.of(Field("name", "string")))
        diags = validate_plan(Join(scan(), right, ["k"], ["name"]))
        assert [d.rule for d in diags] == ["join-key-type-mismatch"]

    def test_outer_join_vector_null_extension_warns(self):
        right = Scan(
            "/data/u", "parquet",
            Schema.of(Field("k", "int32"), Field("e2", "vector", dim=8)),
        )
        diags = validate_plan(Join(scan(), right, ["k"], ["k"], how="left"))
        assert [(d.rule, d.severity) for d in diags] == [
            ("null-extension-vector", "warning")
        ]

    def test_aggregate_sum_over_string(self):
        plan = Aggregate(scan(), ["k"], [AggSpec.of("sum", "s", "bad")])
        diags = validate_plan(plan)
        assert [d.rule for d in diags] == ["dtype-incompatible-aggregate"]

    def test_aggregate_unresolved_group_by(self):
        plan = Aggregate(scan(), ["zz"], [AggSpec.of("count", None, "n")])
        rules_found = rules(plan)
        assert "unresolved-column" in rules_found

    def test_window_order_by_vector(self):
        plan = Window(scan(), ["k"], [("emb", True)],
                      [WindowSpec.of("row_number", None, "rn")], "partition")
        assert "unsortable-key" in rules(plan)

    def test_in_list_domain_mismatch(self):
        diags = validate_plan(Filter(scan(), col("k").isin(["a", "b"])))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]
        diags = validate_plan(Filter(scan(), col("s").isin([1, 2])))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]

    def test_like_over_non_string(self):
        diags = validate_plan(Filter(scan(), col("k").like("a%")))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]

    def test_datepart_over_non_date(self):
        from hyperspace_tpu.plan.expr import year

        diags = validate_plan(Filter(scan(), year(col("k")) == 1998))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]

    def test_diagnostics_carry_provenance_path(self):
        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int32"), Field("w", "float32")))
        plan = Join(scan(), Filter(right, col("nope") > 1), ["k"], ["k"])
        diags = validate_plan(plan)
        assert len(diags) == 1
        assert diags[0].path == "Join/right:Filter"

    def test_all_diagnostics_reported_at_once(self):
        plan = Filter(
            Sort(scan(), [("emb", True)]), col("missing").isin([1])
        )
        found = rules(plan)
        assert set(found) == {"unresolved-column", "unsortable-key"}


class TestExecutorIntegration:
    """The executor refuses malformed plans before any device work."""

    def test_execute_rejects_unresolved_column(self, tmp_path):
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        from hyperspace_tpu.execution.executor import Executor

        root = tmp_path / "t"
        root.mkdir()
        pq.write_table(
            pa.table({"k": pa.array(np.arange(4, dtype=np.int32))}),
            root / "part-0.parquet",
        )
        plan = Filter(
            Scan(str(root), "parquet", Schema.of(Field("k", "int32"))),
            col("missing") > 1,
        )
        with pytest.raises(PlanValidationError) as ei:
            Executor().execute(plan)
        assert ei.value.diagnostics[0].rule == "unresolved-column"

    def test_validation_can_be_disabled(self, tmp_path):
        from hyperspace_tpu.config import ANALYSIS_VALIDATE, HyperspaceConf
        from hyperspace_tpu.execution.executor import Executor

        conf = HyperspaceConf()
        conf.set(ANALYSIS_VALIDATE, "false")
        assert conf.validate_plans is False
        plan = Filter(scan(), col("missing") > 1)
        # With validation off the malformed plan is NOT rejected up front
        # (the empty scan root makes execution itself a no-op here).
        try:
            Executor(conf=conf).execute(plan)
        except PlanValidationError:  # pragma: no cover - the regression
            pytest.fail("validator ran despite hyperspace.analysis.validate=false")
        except Exception:
            pass  # any later failure mode is fine; only the bypass matters

    def test_diagnostic_str_format(self):
        d = PlanDiagnostic("unresolved-column", "Filter", "Join/left:Filter", "msg")
        assert "[unresolved-column]" in str(d)
        assert "Join/left:Filter" in str(d)
