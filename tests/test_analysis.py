"""Static-analysis subsystem tests: trace-safety lint rules + the
pre-execution plan validator (analysis/)."""

from __future__ import annotations

import subprocess
import sys
import textwrap

import pytest

from hyperspace_tpu.analysis.lint import lint_source, lint_paths, main as lint_main
from hyperspace_tpu.analysis.validator import (
    check_plan,
    validate_plan,
    validate_rewrite,
)
from hyperspace_tpu.exceptions import (
    PlanDiagnostic,
    PlanRewriteError,
    PlanValidationError,
)
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    AggSpec,
    Filter,
    Join,
    Project,
    Scan,
    Sort,
    Union,
    Window,
    WindowSpec,
)
from hyperspace_tpu.schema import Field, Schema


# -- lint behaviors ----------------------------------------------------------
#
# Rule-by-rule flagged/clean cases moved to the corpus fixtures — one
# annotated file per rule under tests/analysis_fixtures/rules/, executed
# by tests/test_analysis_engine.py::test_rule_corpus. What stays inline
# here is rule-independent BEHAVIOR: suppression, sanctioned modules,
# the HSL008 allowlist, jit-wrapping detection, and the CLI contract.

def rules_of(src: str, path: str = "<fixture>.py") -> list[str]:
    return [f.rule for f in lint_source(textwrap.dedent(src), path)]


class TestLintBehaviors:
    def test_noqa_suppresses(self):
        assert rules_of("from jax import shard_map  # noqa: HSL001\n") == []

    def test_noqa_other_rule_does_not_suppress(self):
        assert rules_of("from jax import shard_map  # noqa: HSL002\n") == ["HSL001"]

    def test_bare_noqa_suppresses_any_rule(self):
        assert rules_of("import numpy as np\nv = np.random.rand(3)  # noqa\n") == []

    def test_compat_module_is_sanctioned(self):
        src = "from jax.experimental.shard_map import shard_map\n"
        assert lint_source(src, "hyperspace_tpu/compat.py") == []

    def test_file_utils_is_sanctioned_for_metadata_writes(self):
        src = "open(log_dir / 'latestStable', 'w').write(data)\n"
        assert lint_source(src, "hyperspace_tpu/utils/file_utils.py") == []

    def test_hsl008_allowlisted_obs_singletons(self):
        # The allowlist is keyed on (basename, name): trace.py's
        # singleton plumbing mutates by design.
        src = """
        NOOP = {}
        def poke():
            NOOP["x"] = 1
        """
        assert lint_source(textwrap.dedent(src), "hyperspace_tpu/obs/trace.py") == []

    def test_jit_wrapping_without_decorator_detected(self):
        # jax.jit(fn) marks fn as traced even without a decorator — the
        # wrapping-collection half of the HSL002/003 machinery.
        src = """
        import jax
        def make():
            def fn(x):
                return float(x)
            return jax.jit(fn)
        """
        assert rules_of(src) == ["HSL002"]

    def test_shard_map_counts_as_jit_context(self):
        src = """
        import functools, numpy as np
        from hyperspace_tpu.compat import shard_map
        @functools.partial(shard_map, mesh=None, in_specs=(), out_specs=())
        def f(x):
            return np.asarray(x)
        """
        assert rules_of(src) == ["HSL002"]

    def test_lint_source_accepts_shared_tree(self):
        # The unified check driver parses once and hands the tree in.
        import ast

        src = "from jax import shard_map\n"
        tree = ast.parse(src)
        assert [f.rule for f in lint_source(src, "x.py", tree=tree)] == ["HSL001"]

    def test_rules_registry_covers_all_ids(self):
        from hyperspace_tpu.analysis.lint import RULES

        assert sorted(RULES) == [f"HSL{i:03d}" for i in range(31)]
        assert RULES["HSL009"].scope == "program"
        assert RULES["HSL013"].scope == "program"
        assert RULES["HSL016"].scope == "program"
        assert RULES["HSL018"].scope == "program"
        assert RULES["HSL019"].scope == "program"
        assert RULES["HSL022"].scope == "program"
        assert RULES["HSL001"].scope == "file"


class TestLintCli:
    def test_repo_package_is_clean(self):
        # The permanent guarantee behind the compat satellite: the whole
        # package passes its own linter (CI runs this as a gate).
        import hyperspace_tpu

        pkg_dir = hyperspace_tpu.__path__[0]
        assert lint_paths([pkg_dir]) == []

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax import shard_map\n")
        good = tmp_path / "good.py"
        good.write_text("from jax import lax\n")
        assert lint_main([str(bad)]) == 1
        assert lint_main([str(good)]) == 0

    def test_module_invocation(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import numpy as np\nv = np.random.rand(3)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "hyperspace_tpu.analysis.lint", str(bad)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "HSL005" in proc.stdout

    def test_syntax_error_is_a_finding(self, tmp_path):
        # An unparseable TARGET is a finding (HSL000 -> exit 1), not an
        # analyzer crash (exit 2).
        f = tmp_path / "broken.py"
        f.write_text("def f(:\n")
        findings = lint_paths([str(f)])
        assert [x.rule for x in findings] == ["HSL000"]
        assert lint_main([str(f)]) == 1

    def test_internal_error_exits_2(self, monkeypatch):
        # 0 = clean, 1 = findings, 2 = the linter itself crashed — CI
        # must never read an analyzer crash as "findings present".
        import hyperspace_tpu.analysis.lint as lint_mod

        def boom(paths):
            raise RuntimeError("boom")

        monkeypatch.setattr(lint_mod, "lint_paths", boom)
        assert lint_mod.main(["anything.py"]) == 2


# -- plan validator ----------------------------------------------------------

SCHEMA = Schema.of(
    Field("k", "int32"),
    Field("v", "float64"),
    Field("s", "string"),
    Field("d", "date"),
    Field("emb", "vector", dim=4),
)


def scan(schema=SCHEMA, **kw) -> Scan:
    return Scan("/data/t", "parquet", schema, **kw)


def rules(plan) -> list[str]:
    return [d.rule for d in validate_plan(plan)]


class TestValidatorMalformedPlans:
    """The >=5 malformed-plan classes from the issue, each rejected with
    a diagnostic naming the offending node."""

    def test_clean_plan_validates(self):
        plan = Filter(scan(), (col("k") > 5) & (col("v") <= 2.5)).select("k", "v")
        assert validate_plan(plan) == []
        check_plan(plan)  # must not raise

    def test_mismatched_join_bucket_specs(self):
        left = scan(bucket_spec=(8, ["k"]))
        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int32"), Field("w", "float32")),
                     bucket_spec=(16, ["k"]))
        plan = Join(left, right, ["k"], ["k"])
        diags = validate_plan(plan)
        assert [d.rule for d in diags] == ["join-bucket-mismatch"]
        assert diags[0].node == "Join"
        assert "8" in diags[0].message and "16" in diags[0].message
        # Warning severity: executable (falls back to a re-shuffle), but
        # check_plan promotes it on request.
        check_plan(plan)
        with pytest.raises(PlanValidationError) as ei:
            check_plan(plan, fail_on="warning")
        assert "join-bucket-mismatch" in str(ei.value)

    def test_mismatched_bucket_hash_domains(self):
        # Equal counts, equal key names — but int32 vs int64 key dtypes
        # hash differently, so the "aligned" pair can never align.
        left = scan(bucket_spec=(8, ["k"]))
        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int64"), Field("w", "float32")),
                     bucket_spec=(8, ["k"]))
        diags = validate_plan(Join(left, right, ["k"], ["k"]))
        assert [d.rule for d in diags] == ["join-bucket-mismatch"]
        assert "dtype domain" in diags[0].message

    def test_unresolved_column(self):
        diags = validate_plan(Filter(scan(), col("missing") > 5))
        assert [d.rule for d in diags] == ["unresolved-column"]
        assert diags[0].node == "Filter"
        assert "'missing'" in diags[0].message
        with pytest.raises(PlanValidationError):
            check_plan(Filter(scan(), col("missing") > 5))

    def test_unresolved_join_key(self):
        right = Scan("/data/u", "parquet", Schema.of(Field("k", "int32")))
        diags = validate_plan(Join(scan(), right, ["k"], ["nope"]))
        assert [d.rule for d in diags] == ["unresolved-column"]
        assert diags[0].node == "Join"

    def test_dtype_incompatible_predicate(self):
        diags = validate_plan(Filter(scan(), col("s") > 5))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]
        assert "string" in diags[0].message

    def test_non_boolean_predicate(self):
        diags = validate_plan(Filter(scan(), col("k") + 1))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]
        assert "expected bool" in diags[0].message

    def test_string_arithmetic(self):
        diags = validate_plan(Project(scan(), [("x", col("s") * 2)]))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]
        assert "arithmetic" in diags[0].message

    def test_bad_sort_key(self):
        diags = validate_plan(Sort(scan(), [("emb", True)]))
        assert [d.rule for d in diags] == ["unsortable-key"]
        assert diags[0].node == "Sort"
        with pytest.raises(PlanValidationError):
            check_plan(Sort(scan(), [("emb", True)]))

    def test_illegal_pushdown(self):
        # A left outer join: filtering the RIGHT side before the join
        # changes null-extension semantics. The rewrite guard catches a
        # pushed conjunct the original never had below that side.
        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int32"), Field("w", "float32")))
        pred = col("w") > 1.0
        original = Filter(Join(scan(), right, ["k"], ["k"], how="left"), pred)
        bad_rewrite = Join(scan(), Filter(right, pred), ["k"], ["k"], how="left")
        with pytest.raises(PlanRewriteError) as ei:
            validate_rewrite(original, bad_rewrite)
        assert ei.value.diagnostics[0].rule == "illegal-pushdown"
        assert "right" in ei.value.diagnostics[0].path

    def test_illegal_prune(self):
        # A rewrite that narrowed a scan below a filter still referencing
        # the pruned column must be rejected.
        import dataclasses

        base = scan(Schema.of(Field("k", "int32"), Field("v", "float64")))
        original = Filter(base, col("v") > 1.0).select("k", "v")
        pruned = dataclasses.replace(base, scan_schema=base.scan_schema.select(["k"]))
        bad_rewrite = Filter(pruned, col("v") > 1.0).select("k")
        with pytest.raises(PlanRewriteError) as ei:
            validate_rewrite(original, bad_rewrite)
        assert any(d.rule == "unresolved-column" for d in ei.value.diagnostics)

    def test_rewrite_schema_change(self):
        original = scan().select("k", "v")
        bad_rewrite = scan().select("k")
        with pytest.raises(PlanRewriteError) as ei:
            validate_rewrite(original, bad_rewrite)
        assert ei.value.diagnostics[0].rule == "rewrite-schema-change"

    def test_legal_rewrite_passes(self):
        from hyperspace_tpu.plan.prune import prune_columns
        from hyperspace_tpu.plan.pushdown import push_down_filters

        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int32"), Field("w", "float32")))
        plan = Filter(
            Join(scan(), right, ["k"], ["k"]), (col("v") > 0.5) & (col("w") > 1.0)
        ).select("k", "v", "w")
        validate_rewrite(plan, prune_columns(push_down_filters(plan)))


class TestValidatorMoreRules:
    def test_bad_bucket_spec_count(self):
        diags = validate_plan(scan(bucket_spec=(0, ["k"])))
        assert [d.rule for d in diags] == ["bad-bucket-spec"]

    def test_bucket_column_missing(self):
        diags = validate_plan(scan(bucket_spec=(8, ["zz"])))
        assert [d.rule for d in diags] == ["unresolved-column"]
        assert diags[0].node == "Scan"

    def test_join_key_domain_mismatch(self):
        right = Scan("/data/u", "parquet", Schema.of(Field("name", "string")))
        diags = validate_plan(Join(scan(), right, ["k"], ["name"]))
        assert [d.rule for d in diags] == ["join-key-type-mismatch"]

    def test_outer_join_vector_null_extension_warns(self):
        right = Scan(
            "/data/u", "parquet",
            Schema.of(Field("k", "int32"), Field("e2", "vector", dim=8)),
        )
        diags = validate_plan(Join(scan(), right, ["k"], ["k"], how="left"))
        assert [(d.rule, d.severity) for d in diags] == [
            ("null-extension-vector", "warning")
        ]

    def test_aggregate_sum_over_string(self):
        plan = Aggregate(scan(), ["k"], [AggSpec.of("sum", "s", "bad")])
        diags = validate_plan(plan)
        assert [d.rule for d in diags] == ["dtype-incompatible-aggregate"]

    def test_aggregate_unresolved_group_by(self):
        plan = Aggregate(scan(), ["zz"], [AggSpec.of("count", None, "n")])
        rules_found = rules(plan)
        assert "unresolved-column" in rules_found

    def test_window_order_by_vector(self):
        plan = Window(scan(), ["k"], [("emb", True)],
                      [WindowSpec.of("row_number", None, "rn")], "partition")
        assert "unsortable-key" in rules(plan)

    def test_in_list_domain_mismatch(self):
        diags = validate_plan(Filter(scan(), col("k").isin(["a", "b"])))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]
        diags = validate_plan(Filter(scan(), col("s").isin([1, 2])))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]

    def test_like_over_non_string(self):
        diags = validate_plan(Filter(scan(), col("k").like("a%")))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]

    def test_datepart_over_non_date(self):
        from hyperspace_tpu.plan.expr import year

        diags = validate_plan(Filter(scan(), year(col("k")) == 1998))
        assert [d.rule for d in diags] == ["dtype-incompatible-predicate"]

    def test_diagnostics_carry_provenance_path(self):
        right = Scan("/data/u", "parquet",
                     Schema.of(Field("k", "int32"), Field("w", "float32")))
        plan = Join(scan(), Filter(right, col("nope") > 1), ["k"], ["k"])
        diags = validate_plan(plan)
        assert len(diags) == 1
        assert diags[0].path == "Join/right:Filter"

    def test_all_diagnostics_reported_at_once(self):
        plan = Filter(
            Sort(scan(), [("emb", True)]), col("missing").isin([1])
        )
        found = rules(plan)
        assert set(found) == {"unresolved-column", "unsortable-key"}


class TestExecutorIntegration:
    """The executor refuses malformed plans before any device work."""

    def test_execute_rejects_unresolved_column(self, tmp_path):
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        from hyperspace_tpu.execution.executor import Executor

        root = tmp_path / "t"
        root.mkdir()
        pq.write_table(
            pa.table({"k": pa.array(np.arange(4, dtype=np.int32))}),
            root / "part-0.parquet",
        )
        plan = Filter(
            Scan(str(root), "parquet", Schema.of(Field("k", "int32"))),
            col("missing") > 1,
        )
        with pytest.raises(PlanValidationError) as ei:
            Executor().execute(plan)
        assert ei.value.diagnostics[0].rule == "unresolved-column"

    def test_validation_can_be_disabled(self, tmp_path):
        from hyperspace_tpu.config import ANALYSIS_VALIDATE, HyperspaceConf
        from hyperspace_tpu.execution.executor import Executor

        conf = HyperspaceConf()
        conf.set(ANALYSIS_VALIDATE, "false")
        assert conf.validate_plans is False
        plan = Filter(scan(), col("missing") > 1)
        # With validation off the malformed plan is NOT rejected up front
        # (the empty scan root makes execution itself a no-op here).
        try:
            Executor(conf=conf).execute(plan)
        except PlanValidationError:  # pragma: no cover - the regression
            pytest.fail("validator ran despite hyperspace.analysis.validate=false")
        except Exception:
            pass  # any later failure mode is fine; only the bypass matters

    def test_diagnostic_str_format(self):
        d = PlanDiagnostic("unresolved-column", "Filter", "Join/left:Filter", "msg")
        assert "[unresolved-column]" in str(d)
        assert "Join/left:Filter" in str(d)
