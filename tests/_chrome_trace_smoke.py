"""Chrome-trace export smoke — the CI observability artifact.

Run as ``python tests/_chrome_trace_smoke.py [out.json]``: builds one
real (smoke-scale) index with the streaming pipeline on and runs one
TPC-DS query, both under a JSON-lines sink, then exports the span trees
with ``obs.export --format chrome`` and asserts the document is a valid
Chrome Trace Event file whose build-pipeline stages *visibly overlap*
(≥2 stage slices concurrent in time) — the property Perfetto renders as
parallel lanes. Also rebuilds one index with the POOLED scale-out build
(``hyperspace.build.workers=2``) and asserts the adopted worker-process
traces land on ≥2 distinct pid lanes that overlap in time — one lane
per worker process. Kept out of pytest collection (leading underscore):
tier-1 covers the exporter's unit semantics; this is the end-to-end
"a real build's timeline renders and shows the overlap" check."""

import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from benchmarks.tpcds import cached_tpcds, tpcds_indexes, tpcds_queries
    from hyperspace_tpu import Hyperspace, HyperspaceSession
    from hyperspace_tpu.obs import export

    out_path = sys.argv[1] if len(sys.argv) > 1 else "chrome-trace.json"
    base = Path(tempfile.mkdtemp(prefix="hs_chrome_smoke_"))
    sink = base / "events.jsonl"
    roots = cached_tpcds(sf=0.01, cache_root=base)
    session = HyperspaceSession(system_path=str(base / "idx"), num_buckets=8)
    session.conf.set("hyperspace.obs.sink", str(sink))
    # Smoke-scale data fits in memory, which would take the in-memory
    # build path; a tiny budget forces the streaming pipeline whose
    # overlapped stages are exactly what this artifact must show.
    session.conf.set("hyperspace.index.build.memoryBudgetBytes", 1 << 20)
    session.conf.set("hyperspace.index.build.chunkBytes", 256 << 10)
    hs = Hyperspace(session)
    scans = {name: session.parquet(root) for name, root in roots.items()}
    tpcds_indexes(hs, scans)  # smoke build(s): action traces land in the sink
    session.enable_hyperspace()
    name, plan = sorted(tpcds_queries(scans).items())[0]
    session.run(plan)  # one TPC-DS query trace

    # One POOLED rebuild: worker-process traces are adopted back into
    # the coordinator (pid-qualified trace ids), so the chrome export
    # shows one lane per worker process.
    session.conf.set("hyperspace.build.workers", 2)
    first = sorted(hs.indexes()["name"])[0]
    hs.refresh_index(first)

    rc = export.main(["--format", "chrome", "--sink", str(sink), "--output", out_path])
    assert rc == 0
    doc = json.loads(Path(out_path).read_text())
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert xs, "no complete events exported"
    for e in xs:  # well-formed: Perfetto rejects malformed events
        assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] >= 0

    build = [e for e in xs if e["name"].startswith("build.")]
    assert build, "no build-pipeline stage spans in the trace"
    intervals = [(e["ts"], e["ts"] + e["dur"], e["name"]) for e in build]
    overlaps = [
        (a[2], b[2])
        for i, a in enumerate(intervals)
        for b in intervals[i + 1:]
        if a[0] < b[1] and b[0] < a[1]
    ]
    assert overlaps, f"no overlapping build stages among {len(build)} spans"
    query = [e for e in xs if e["name"].startswith("execute.")]
    assert query, "no executed-operator spans from the TPC-DS query"

    # Scale-out build lanes: the pooled rebuild's worker-process roots
    # carry their own pid (trace_id "<pid>-<seq>"), so they land on
    # distinct pid tracks — and, as genuinely concurrent processes,
    # their slices must overlap in time (perf_counter is the shared
    # CLOCK_MONOTONIC on Linux, comparable across processes).
    workers = [
        e for e in xs if e["name"] in ("build.p1.worker", "build.p2.worker")
    ]
    assert workers, "no pooled worker-process spans in the trace"
    lanes = {e["pid"] for e in workers}
    assert len(lanes) >= 2, f"expected >=2 worker pid lanes, got {lanes}"
    w_intervals = [(e["ts"], e["ts"] + e["dur"], e["pid"]) for e in workers]
    w_overlaps = [
        (a[2], b[2])
        for i, a in enumerate(w_intervals)
        for b in w_intervals[i + 1:]
        if a[2] != b[2] and a[0] < b[1] and b[0] < a[1]
    ]
    assert w_overlaps, f"no cross-process overlap among {len(workers)} worker spans"

    # Device data path lanes: every Arrow→ColumnTable decode emits a
    # `device.stage` span (the staging pass the zero-copy layer
    # accounts), so the query timeline shows staging riding the pooled
    # IO lanes rather than serializing on the critical path.
    stage = [e for e in xs if e["name"] == "device.stage"]
    assert stage, "no device.stage spans in the trace"
    print(
        f"OK: {len(xs)} spans -> {out_path}; {len(build)} build-stage slices, "
        f"{len(overlaps)} overlapping pairs (e.g. {overlaps[0][0]} ~ {overlaps[0][1]}); "
        f"{len(query)} query operator slices; {len(workers)} worker slices on "
        f"{len(lanes)} pid lanes, {len(w_overlaps)} cross-process overlaps; "
        f"{len(stage)} device.stage slices"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
