"""Versioned data directory discovery (analog of IndexDataManager tests)."""

from hyperspace_tpu.metadata.data_manager import IndexDataManager


def test_version_discovery(tmp_path):
    dm = IndexDataManager(tmp_path / "idx1")
    assert dm.get_latest_version_id() is None
    for v in (0, 1, 3):
        dm.get_path(v).mkdir(parents=True)
    # Non-version dirs/files are ignored.
    (tmp_path / "idx1" / "_hyperspace_log").mkdir()
    (tmp_path / "idx1" / "v__=bad").mkdir()
    assert dm.get_version_ids() == [0, 1, 3]
    assert dm.get_latest_version_id() == 3
    assert dm.get_path(3).name == "v__=3"


def test_delete(tmp_path):
    dm = IndexDataManager(tmp_path / "idx1")
    p = dm.get_path(0)
    p.mkdir(parents=True)
    (p / "bucket-0.parquet").write_bytes(b"x")
    dm.delete(0)
    assert not p.exists()
    assert dm.get_version_ids() == []
