"""64-bit predicate evaluation without the global x64 flag.

Device lanes stay 32-bit native; comparisons against int64/float64 columns
are lowered to hi/lo uint32 pair comparisons (ops/filter.py). These tests
pin numpy-equality of the masks across dtypes, literal shapes, and both
orders of first use — and that `jax_enable_x64` is never flipped.
"""

import numpy as np
import pytest

import jax

from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.ops.filter import eval_predicate_mask
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.schema import Field, Schema


def _table():
    rng = np.random.default_rng(0)
    n = 500
    big = rng.integers(-(2**62), 2**62, n).astype(np.int64)
    big[:3] = [0, np.iinfo(np.int64).min, np.iinfo(np.int64).max]
    f64 = rng.standard_normal(n) * 1e12
    f64[:4] = [0.0, -0.0, np.inf, -np.inf]
    f64[4] = np.nan
    schema = Schema.of(
        Field("i64", "int64"),
        Field("i32", "int32"),
        Field("f64", "float64"),
        Field("f32", "float32"),
    )
    return ColumnTable(
        schema,
        {
            "i64": big,
            "i32": rng.integers(-1000, 1000, n).astype(np.int32),
            "f64": f64,
            "f32": rng.standard_normal(n).astype(np.float32),
        },
        {},
    )


def _np_mask(t, fn):
    with np.errstate(all="ignore"):
        return np.broadcast_to(np.asarray(fn(t.columns), dtype=bool), (t.num_rows,))


OPS = [
    ("eq", lambda a, b: a == b),
    ("ne", lambda a, b: a != b),
    ("lt", lambda a, b: a < b),
    ("le", lambda a, b: a <= b),
    ("gt", lambda a, b: a > b),
    ("ge", lambda a, b: a >= b),
]


def test_x64_flag_never_flips():
    t = _table()
    for _, f in OPS:
        eval_predicate_mask(t, f(col("i64"), lit(2**40 + 7)))
        eval_predicate_mask(t, f(col("f64"), lit(1.2345678901234e11)))
    assert jax.config.jax_enable_x64 is False


@pytest.mark.parametrize("opname,f", OPS)
def test_int64_literal_beyond_int32(opname, f):
    t = _table()
    v = t.columns["i64"][10]  # an actual huge value: exact-match matters
    for litval in (int(v), 2**40 + 7, -(2**50) + 3, 0):
        got = eval_predicate_mask(t, f(col("i64"), lit(litval)))
        want = _np_mask(t, lambda c: f(c["i64"], litval))
        np.testing.assert_array_equal(got, want, err_msg=f"{opname} {litval}")


@pytest.mark.parametrize("opname,f", OPS)
def test_int64_extremes_and_float_literals(opname, f):
    t = _table()
    for litval in (np.iinfo(np.int64).max, np.iinfo(np.int64).min, 10.5, -0.5, 2.0**70, float("inf")):
        got = eval_predicate_mask(t, f(col("i64"), lit(litval)))
        want = _np_mask(t, lambda c: f(c["i64"].astype(np.float64) if isinstance(litval, float) else c["i64"], litval))
        np.testing.assert_array_equal(got, want, err_msg=f"{opname} {litval}")


@pytest.mark.parametrize("opname,f", OPS)
def test_float64_literals(opname, f):
    t = _table()
    v = float(t.columns["f64"][20])
    for litval in (v, 0.0, -0.0, 1.2345678901234e11, float("inf"), float("-inf"), float("nan")):
        got = eval_predicate_mask(t, f(col("f64"), lit(litval)))
        want = _np_mask(t, lambda c: f(c["f64"], litval))
        np.testing.assert_array_equal(got, want, err_msg=f"{opname} {litval}")


@pytest.mark.parametrize("opname,f", OPS)
def test_float32_column_with_inexact_literal(opname, f):
    """Weak python-float literals against a float32 column follow numpy's
    NEP-50 promotion: the comparison runs IN float32 (literal rounded)."""
    t = _table()
    for litval in (0.1234567890123456789, 16777217.0):
        got = eval_predicate_mask(t, f(col("f32"), lit(litval)))
        want = _np_mask(t, lambda c: f(c["f32"], litval))
        np.testing.assert_array_equal(got, want, err_msg=f"{opname} {litval}")
    # Strong np.float64 scalars promote the comparison to float64 instead.
    litval = np.float64(16777217.0)
    got = eval_predicate_mask(t, f(col("f32"), lit(litval)))
    want = _np_mask(t, lambda c: f(c["f32"], litval))
    np.testing.assert_array_equal(got, want, err_msg=f"{opname} strong {litval}")


def test_int64_vs_float_literal_rounds_like_numpy():
    """numpy compares int64 arrays with float scalars in float64, rounding
    the column above 2^53 — the device pair path must match."""
    schema = Schema.of(Field("x", "int64"))
    arr = np.array([2**62 + 1, 2**62, 5, -(2**62) - 1], dtype=np.int64)
    t = ColumnTable(schema, {"x": arr}, {})
    for _, f in OPS:
        for litval in (float(2**62), 5.0, 5.5):
            got = eval_predicate_mask(t, f(col("x"), lit(litval)))
            want = np.asarray(f(arr, litval))
            np.testing.assert_array_equal(got, want, err_msg=f"{litval}")


def test_mixed_kind_arithmetic_falls_back():
    """int ⊕ float arithmetic promotes to float64 under numpy but would be
    float32 on device — must fall back to host above 2^24."""
    schema = Schema.of(Field("x", "int32"))
    arr = np.array([33554433, 5], dtype=np.int32)
    t = ColumnTable(schema, {"x": arr}, {})
    got = eval_predicate_mask(t, (col("x") * lit(2.0)) > lit(67108864.0))
    want = (arr * 2.0) > 67108864.0
    np.testing.assert_array_equal(got, want)
    # Mixed-kind comparison of compound sides, too.
    got = eval_predicate_mask(t, (col("x") + lit(1)) > lit(33554432.7))
    want = (arr + 1) > 33554432.7
    np.testing.assert_array_equal(got, want)


def test_int32_out_of_range_literal_folds():
    t = _table()
    got = eval_predicate_mask(t, col("i32") < lit(2**40))
    assert got.all()
    got = eval_predicate_mask(t, col("i32") > lit(2**40))
    assert not got.any()
    got = eval_predicate_mask(t, col("i32") == lit(-(2**40)))
    assert not got.any()


def test_col_col_64bit_pairs():
    t = _table()
    got = eval_predicate_mask(t, col("i64") < col("i64"))
    assert not got.any()
    # float64 vs float32: widened to float64 domain on both sides.
    got = eval_predicate_mask(t, col("f64") < col("f32"))
    want = _np_mask(t, lambda c: c["f64"] < c["f32"].astype(np.float64))
    np.testing.assert_array_equal(got, want)
    # int64 vs int32 compares in int64 order.
    got = eval_predicate_mask(t, col("i64") >= col("i32"))
    want = _np_mask(t, lambda c: c["i64"] >= c["i32"].astype(np.int64))
    np.testing.assert_array_equal(got, want)


def test_conjunction_mixing_widths():
    t = _table()
    pred = (col("i64") > lit(0)) & (col("i32") < lit(100)) & (col("f64") <= lit(1e11))
    got = eval_predicate_mask(t, pred)
    want = _np_mask(
        t, lambda c: (c["i64"] > 0) & (c["i32"] < 100) & (c["f64"] <= 1e11)
    )
    np.testing.assert_array_equal(got, want)


def test_arithmetic_on_int64_falls_back_to_host():
    """64-bit arithmetic can't run in 32-bit lanes — host numpy fallback
    must produce exact results."""
    t = _table()
    pred = (col("i64") + lit(1)) > lit(0)
    got = eval_predicate_mask(t, pred)
    want = _np_mask(t, lambda c: (c["i64"] + 1) > 0)
    np.testing.assert_array_equal(got, want)
    assert jax.config.jax_enable_x64 is False


def test_both_orders_of_first_use():
    """int64 predicates before AND after int32 predicates — no global
    state leaks between them (the old ensure_x64 hazard)."""
    t = _table()
    m64 = eval_predicate_mask(t, col("i64") > lit(0))
    m32 = eval_predicate_mask(t, col("i32") > lit(0))
    m64b = eval_predicate_mask(t, col("i64") > lit(0))
    m32b = eval_predicate_mask(t, col("i32") > lit(0))
    np.testing.assert_array_equal(m64, m64b)
    np.testing.assert_array_equal(m32, m32b)
    np.testing.assert_array_equal(m64, _np_mask(t, lambda c: c["i64"] > 0))
    np.testing.assert_array_equal(m32, _np_mask(t, lambda c: c["i32"] > 0))


def test_negative_nan_canonicalized():
    """Negative-sign NaNs must behave exactly like positive NaNs (IEEE:
    every comparison false, != true)."""
    neg_nan = np.frombuffer(np.uint64(0xFFF8000000000000).tobytes(), dtype=np.float64)[0]
    assert np.isnan(neg_nan)
    schema = Schema.of(Field("x", "float64"))
    arr = np.array([1.0, neg_nan, np.nan, -np.inf, 5.0])
    t = ColumnTable(schema, {"x": arr}, {})
    for _, f in OPS:
        got = eval_predicate_mask(t, f(col("x"), lit(2.0)))
        with np.errstate(all="ignore"):
            want = np.asarray(f(arr, 2.0))
        np.testing.assert_array_equal(got, want)


def test_col_col_nan_eq_ne():
    """NaN == NaN must be False and NaN != NaN True on the device pair path."""
    schema = Schema.of(Field("a", "float64"), Field("b", "float64"))
    a = np.array([1.0, np.nan, 3.0, np.nan])
    b = np.array([1.0, np.nan, 4.0, 2.0])
    t = ColumnTable(schema, {"a": a, "b": b}, {})
    np.testing.assert_array_equal(
        eval_predicate_mask(t, col("a") == col("b")), np.array([True, False, False, False])
    )
    np.testing.assert_array_equal(
        eval_predicate_mask(t, col("a") != col("b")), np.array([False, True, True, True])
    )


def test_int_division_matches_numpy_float64():
    """numpy divides ints in float64; the device's float32 would round
    67108863/67108864 to exactly 1.0 — must fall back to host."""
    schema = Schema.of(Field("x", "int32"))
    arr = np.array([67108863, 67108864, 1], dtype=np.int32)
    t = ColumnTable(schema, {"x": arr}, {})
    got = eval_predicate_mask(t, (col("x") / lit(67108864)) < lit(1.0))
    want = (arr / 67108864) < 1.0
    np.testing.assert_array_equal(got, want)


def test_bool_column_vs_numeric_literal():
    schema = Schema.of(Field("flag", "bool"))
    arr = np.array([True, False, True])
    t = ColumnTable(schema, {"flag": arr}, {})
    np.testing.assert_array_equal(
        eval_predicate_mask(t, col("flag") == lit(5)), np.asarray(arr == 5)
    )
    np.testing.assert_array_equal(
        eval_predicate_mask(t, col("flag") == lit(True)), arr
    )


def test_merge_join_mixed_dtype_sentinels():
    """int64 keys on one side, int32 on the other: each side's pads use its
    own dtype's max and must not collide with real keys."""
    from hyperspace_tpu.ops import join as join_ops

    i32max = np.iinfo(np.int32).max
    # Left int64 holds a REAL key equal to int32 max; right int32 pads with it.
    lk = np.array([[5, i32max, np.iinfo(np.int64).max]], dtype=np.int64)
    rk = np.array([[5, 5, i32max]], dtype=np.int32)  # last slot is a pad
    li, ri, totals = join_ops.merge_join(lk, rk)
    # Only the key 5 matches (twice); the real int32max key must NOT match
    # the right side's pad slot.
    assert totals.tolist() == [2]
    assert sorted(zip(li.tolist(), ri.tolist())) == [(0, 0), (0, 1)]
