"""Advisor subsystem tests (docs/advisor.md).

Covers the three layers end to end: what-if recommendation correctness
on a synthetic workload (hot predicate => create, never-hit index =>
drop, fragmentation => optimize, mismatched join buckets => rebucket),
adaptive-routing demotion / structural re-promotion on index mutation,
the lifecycle crash sweep through the new ``advisor.recommend`` /
``advisor.apply`` fault points, and cost-model monotonicity — plus the
round-5 satellite regressions: null-safe set-op semantics and the Arrow
dictionary-entry-null round trip.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, faults
from hyperspace_tpu.advisor.cost import CostModel
from hyperspace_tpu.advisor.lifecycle import LifecyclePolicy
from hyperspace_tpu.advisor.whatif import WhatIfAnalyzer
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.signature import plan_signature


@pytest.fixture
def session(tmp_system_path):
    return HyperspaceSession(system_path=tmp_system_path, num_buckets=8)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def _write(tmp_path, name, table: pa.Table, parts: int = 1):
    root = tmp_path / name
    root.mkdir()
    n = len(table)
    step = max(1, n // parts)
    for i in range(parts):
        pq.write_table(table.slice(i * step, step), root / f"p{i}.parquet")
    return root


def _hot_table(tmp_path, n=20_000, seed=7):
    rng = np.random.default_rng(seed)
    return _write(tmp_path, "hot", pa.table({
        "k": rng.integers(0, 500, n),
        "v": rng.standard_normal(n),
        "tag": pa.array([f"t{i % 37}" for i in range(n)]),
    }), parts=2)


def _cold_index(session, hs, tmp_path, name="coldidx"):
    rng = np.random.default_rng(3)
    root = _write(tmp_path, f"cold_{name}", pa.table({
        "x": rng.integers(0, 9, 1000),
        "y": rng.standard_normal(1000),
    }))
    hs.create_index(session.parquet(root), IndexConfig(name, ["x"], ["y"]))
    return root


# -- what-if -----------------------------------------------------------------

class TestWhatIf:
    def test_hot_predicate_earns_create_rec(self, session, hs, tmp_path):
        root = _hot_table(tmp_path)
        df = session.parquet(root)
        session.enable_hyperspace()
        for i in range(6):
            session.run(df.filter(col("k") == (i * 17) % 500).select("k", "v"))
        recs = hs.recommend()
        creates = [r for r in recs if r.kind == "create"]
        assert creates, [r.to_json() for r in recs]
        rec = creates[0]
        assert rec.source_root == str(root)
        assert [c.lower() for c in rec.index_config.indexed_columns] == ["k"]
        assert "v" in [c.lower() for c in rec.index_config.included_columns]
        assert rec.estimated_benefit_s > 0
        assert 0.0 < rec.confidence <= 1.0
        assert rec.queries_matched == 6

    def test_never_hit_index_earns_drop_rec(self, session, hs, tmp_path):
        _cold_index(session, hs, tmp_path)
        root = _hot_table(tmp_path)
        df = session.parquet(root)
        session.enable_hyperspace()
        for i in range(4):
            session.run(df.filter(col("k") == i).select("k", "v"))
        recs = hs.recommend()
        drops = [r for r in recs if r.kind == "drop"]
        assert [r.index_name for r in drops] == ["coldidx"]
        assert drops[0].estimated_benefit_s > 0

    def test_empty_workload_never_recommends_drops(self, session, hs, tmp_path):
        """With zero observed queries, "unused" is vacuous — a drop
        recommendation would be destructive guesswork."""
        _cold_index(session, hs, tmp_path)
        assert hs.recommend() == []

    def test_covered_predicate_earns_no_create_rec(self, session, hs, tmp_path):
        """A predicate an existing index already serves must not yield a
        duplicate create recommendation (the replay consults the real
        catalog first)."""
        root = _hot_table(tmp_path)
        df = session.parquet(root)
        hs.create_index(df, IndexConfig("kidx", ["k"], ["v"]))
        session.enable_hyperspace()
        for i in range(5):
            session.run(df.filter(col("k") == i).select("k", "v"))
        recs = hs.recommend()
        assert not [r for r in recs if r.kind == "create"], [r.to_json() for r in recs]
        # ... and the index that served the queries is not a drop target.
        assert not [r for r in recs if r.kind == "drop"]

    def test_fragmented_index_earns_optimize_rec(self, session, hs, tmp_path):
        root = _hot_table(tmp_path, n=4000)
        df = session.parquet(root)
        hs.create_index(df, IndexConfig("fragidx", ["k"], ["v"]))
        rng = np.random.default_rng(11)
        for i in range(session.conf.advisor_lifecycle_max_deltas + 1):
            pq.write_table(pa.table({
                "k": rng.integers(0, 500, 200),
                "v": rng.standard_normal(200),
                "tag": pa.array([f"d{i}"] * 200),
            }), root / f"delta{i}.parquet")
            hs.refresh_index("fragidx", mode="incremental")
        session.enable_hyperspace()
        session.run(df.filter(col("k") == 1).select("k", "v"))
        recs = hs.recommend()
        opts = [r for r in recs if r.kind == "optimize"]
        assert [r.index_name for r in opts] == ["fragidx"]

    def test_mismatched_join_buckets_earn_rebucket_rec(self, session, hs, tmp_path):
        rng = np.random.default_rng(13)
        lroot = _write(tmp_path, "facts", pa.table({
            "fk": rng.integers(0, 200, 8000),
            "amt": rng.standard_normal(8000),
        }))
        rroot = _write(tmp_path, "dims", pa.table({
            "dk": np.arange(200, dtype=np.int64),
            "label": pa.array([f"d{i}" for i in range(200)]),
        }))
        facts, dims = session.parquet(lroot), session.parquet(rroot)
        hs.create_index(facts, IndexConfig("fact_by_fk", ["fk"], ["amt"]))
        session.conf.num_buckets = 4  # second index lands at a different count
        hs.create_index(dims, IndexConfig("dim_by_dk", ["dk"], ["label"]))
        session.conf.num_buckets = 8
        session.enable_hyperspace()
        for _ in range(3):
            session.run(facts.join(dims, ["fk"], ["dk"]))
        recs = hs.recommend()
        rb = [r for r in recs if r.kind == "rebucket"]
        assert rb, [r.to_json() for r in recs]
        assert rb[0].index_name == "dim_by_dk"  # the smaller one re-buckets
        assert rb[0].num_buckets == 8

    def test_recommend_fault_point_fires(self, session, hs, tmp_path):
        root = _hot_table(tmp_path, n=2000)
        df = session.parquet(root)
        session.run(df.filter(col("k") == 1).select("k", "v"))
        with faults.injected("advisor.recommend"):
            with pytest.raises(OSError):
                hs.recommend()
        # Disarmed again: the pass succeeds.
        assert isinstance(hs.recommend(), list)


# -- cost model --------------------------------------------------------------

class TestCostModel:
    def test_estimates_monotonic_in_bytes(self):
        m = CostModel()
        sizes = [0, 1, 10**3, 10**6, 10**9, 10**12]
        scans = [m.estimate_scan_s(b) for b in sizes]
        assert scans == sorted(scans)
        queries = [m.estimate_query_s(b, 3) for b in sizes]
        assert queries == sorted(queries)
        assert all(b >= 0 for b in scans + queries)

    def test_indexed_benefit_positive_and_monotonic(self):
        m = CostModel()
        benefits = [m.indexed_benefit_s(b, 8) for b in (10**6, 10**8, 10**10)]
        assert benefits == sorted(benefits)
        assert benefits[-1] > 0
        # More buckets prune more -> at least as much benefit.
        assert m.indexed_benefit_s(10**9, 64) >= m.indexed_benefit_s(10**9, 8)

    def test_fit_from_measured_profiles(self, session, tmp_path):
        root = _hot_table(tmp_path, n=8000)
        df = session.parquet(root)
        for i in range(3):
            session.run(df.filter(col("k") == i).select("k", "v"))
        profiles = [r.profile for r in session.workload.snapshot()]
        m = CostModel.fit(profiles)
        assert m.samples >= 1
        assert m.scan_seconds_per_byte > 0
        # Still monotonic after fitting (the invariant the advisor rides on).
        assert m.estimate_scan_s(2e9) > m.estimate_scan_s(1e6)


# -- adaptive routing --------------------------------------------------------

class TestRouting:
    def _setup(self, session, hs, tmp_path):
        root = _hot_table(tmp_path, n=5000)
        df = session.parquet(root)
        hs.create_index(df, IndexConfig("kidx", ["k"], ["v"]))
        session.conf.set("hyperspace.advisor.routing.enabled", True)
        return df.filter(col("k") == 3).select("k", "v")

    def test_demotion_and_repromotion_on_mutation(self, session, hs, tmp_path):
        q = self._setup(session, hs, tmp_path)
        sig = plan_signature(q)
        led = session.routing_ledger()
        session.disable_hyperspace()
        r_raw = session.run(q)
        session.enable_hyperspace()
        led.record(sig, "indexed", 10.0)  # indexed path "measured" slower
        assert led.decide(sig) == "raw"
        r_routed = session.run(q)
        st = dict(session.last_query_stats)
        assert st["advisor_routing"] == {"decision": "raw", "demoted": True}
        np.testing.assert_allclose(
            np.sort(r_routed.decode()["v"]), np.sort(r_raw.decode()["v"])
        )
        # Structural re-promotion: any index mutation bumps the log
        # versions, the stamp mismatches, the ledger wipes.
        hs.refresh_index("kidx")
        assert led.decide(sig) == "indexed"
        session.run(q)
        st = dict(session.last_query_stats)
        assert st["advisor_routing"]["decision"] == "indexed"
        assert st["advisor_routing"]["demoted"] is False

    def test_fast_indexed_path_keeps_its_plan(self, session, hs, tmp_path):
        q = self._setup(session, hs, tmp_path)
        sig = plan_signature(q)
        led = session.routing_ledger()
        led.record(sig, "raw", 1.0)
        led.record(sig, "indexed", 0.2)
        assert led.decide(sig) == "indexed"

    def test_ledger_persists_and_reloads(self, session, hs, tmp_path):
        q = self._setup(session, hs, tmp_path)
        sig = plan_signature(q)
        led = session.routing_ledger()
        led.record(sig, "raw", 1.0)
        led.record(sig, "indexed", 5.0)  # verdict flip persists immediately
        assert led.path.exists()
        # A fresh session over the same system path reloads the verdict.
        s2 = HyperspaceSession(system_path=session.conf.system_path)
        s2.conf.set("hyperspace.advisor.routing.enabled", True)
        assert s2.routing_ledger().decide(sig) == "raw"

    def test_persist_failure_is_advisory(self, session, hs, tmp_path, monkeypatch):
        q = self._setup(session, hs, tmp_path)
        led = session.routing_ledger()
        from hyperspace_tpu.utils import file_utils

        def boom(path, obj, **kw):
            raise OSError("disk on fire")

        monkeypatch.setattr(file_utils, "write_json", boom)
        before = obs_metrics.counter("advisor.routing.persist_failed").value
        led.record(plan_signature(q), "raw", 1.0)
        led.flush()  # both writes fail, neither raises
        assert obs_metrics.counter("advisor.routing.persist_failed").value > before

    def test_explain_shows_routing_decision(self, session, hs, tmp_path):
        q = self._setup(session, hs, tmp_path)
        sig = plan_signature(q)
        led = session.routing_ledger()
        session.enable_hyperspace()
        assert "Adaptive routing: indexed" in hs.explain(q)
        led.record(sig, "raw", 0.01)
        led.record(sig, "indexed", 10.0)
        assert "Adaptive routing: raw" in hs.explain(q)

    def test_pinned_reader_keys_ledger_on_snapshot_stamp(self, session, hs,
                                                         tmp_path):
        """Snapshot-stamp discipline (HSL030 regression): a pinned query
        keys the routing ledger on the snapshot's OWN read point. A
        concurrent commit moves the LIVE collection stamp — which wipes
        the ledger for live readers — but must not wipe (or be wiped
        by) evidence recorded under a pinned view that cannot even see
        the commit."""
        from hyperspace_tpu.advisor.routing import (
            collection_stamp,
            snapshot_stamp,
        )

        q = self._setup(session, hs, tmp_path)
        led = session.routing_ledger()
        session.enable_hyperspace()
        with session.pin_snapshot() as snap:
            pinned = snapshot_stamp(snap)
            assert pinned == collection_stamp(session)  # same world at pin
            # A pinned run keys the ledger on the PINNED plan's
            # signature (run_query pins the plan before signing it).
            sig = plan_signature(snap.pin_plan(q))
            # both paths measured under the pinned key: demoted
            led.record(sig, "raw", 0.01, stamp=pinned)
            led.record(sig, "indexed", 10.0, stamp=pinned)
            assert led.decide(sig, stamp=pinned) == "raw"
            # a concurrent commit moves the live stamp under the reader …
            hs.refresh_index("kidx")
            assert collection_stamp(session) != pinned
            assert snapshot_stamp(snap) == pinned  # the pin does not move
            # … but the pinned run still routes on its own evidence
            session.run(q, snapshot=snap)
            st = dict(session.last_query_stats)
            assert st["advisor_routing"] == {"decision": "raw", "demoted": True}
            assert led.decide(sig, stamp=pinned) == "raw"  # and kept it
        # a LIVE reader sees the moved stamp: structural re-promotion
        session.run(q)
        st = dict(session.last_query_stats)
        assert st["advisor_routing"]["decision"] == "indexed"

    def test_underscore_dirs_invisible_to_catalog(self, session, hs, tmp_path):
        """The ledger dir lives under the system path but must never be
        listed as an index (or lazy recovery would poke at it forever)."""
        self._setup(session, hs, tmp_path)
        session.routing_ledger().flush()
        names = [p.name for p in session.manager.path_resolver.list_index_paths()]
        assert "_advisor" not in names
        assert "kidx" in names
        with pytest.raises(Exception):
            IndexConfig("_sneaky", ["k"])  # reserved namespace


# -- lifecycle ---------------------------------------------------------------

class TestLifecycle:
    def _workload(self, session, hs, tmp_path, queries=6):
        root = _hot_table(tmp_path)
        df = session.parquet(root)
        session.enable_hyperspace()
        for i in range(queries):
            session.run(df.filter(col("k") == (i * 17) % 500).select("k", "v"))
        return df

    def test_gates_off_sweep_applies_nothing(self, session, hs, tmp_path):
        _cold_index(session, hs, tmp_path)
        self._workload(session, hs, tmp_path)
        report = hs.lifecycle().sweep()
        assert report["applied"] == []
        assert report["failed"] == []
        assert len(report["skipped"]) >= 2  # create + drop both gated off

    def test_auto_create_and_auto_vacuum(self, session, hs, tmp_path):
        cold_root = _cold_index(session, hs, tmp_path)
        df = self._workload(session, hs, tmp_path)
        session.conf.set("hyperspace.advisor.lifecycle.autoCreate", True)
        session.conf.set("hyperspace.advisor.lifecycle.autoVacuum", True)
        session.conf.set("hyperspace.advisor.minConfidence", 0.1)
        report = hs.lifecycle().sweep()
        kinds = [a["kind"] for a in report["applied"]]
        assert "create" in kinds and "drop" in kinds, report
        # The auto-created index now serves the hot query...
        session.run(df.filter(col("k") == 17).select("k", "v"))
        assert session.workload.snapshot()[-1].used_indexes
        assert session.workload.snapshot()[-1].index_names
        # ...and the cold index is physically gone (vacuumed).
        from hyperspace_tpu import states

        active = session.manager.get_indexes(states_filter=tuple(states.ALL_STATES))
        cold = [e for e in active if e.name == "coldidx"]
        assert not cold or cold[0].state == states.DOESNOTEXIST

    def test_auto_optimize_compacts_fragmented(self, session, hs, tmp_path):
        root = _hot_table(tmp_path, n=4000)
        df = session.parquet(root)
        hs.create_index(df, IndexConfig("fragidx", ["k"], ["v"]))
        rng = np.random.default_rng(11)
        for i in range(session.conf.advisor_lifecycle_max_deltas + 1):
            pq.write_table(pa.table({
                "k": rng.integers(0, 500, 200),
                "v": rng.standard_normal(200),
                "tag": pa.array([f"d{i}"] * 200),
            }), root / f"delta{i}.parquet")
            hs.refresh_index("fragidx", mode="incremental")
        session.enable_hyperspace()
        session.run(df.filter(col("k") == 1).select("k", "v"))
        session.conf.set("hyperspace.advisor.lifecycle.autoOptimize", True)
        report = hs.lifecycle().sweep()
        assert "optimize" in [a["kind"] for a in report["applied"]], report
        entry = next(e for e in session.manager.get_indexes() if e.name == "fragidx")
        assert len(entry.content.directories) == 1  # compacted

    def test_apply_crash_is_crash_safe(self, session, hs, tmp_path):
        """CrashPoint at advisor.apply: the sweep dies BEFORE mutating
        (nothing to repair), the process-level recover() converges, and
        a later sweep completes the work."""
        _cold_index(session, hs, tmp_path)
        df = self._workload(session, hs, tmp_path)
        session.conf.set("hyperspace.advisor.lifecycle.autoCreate", True)
        session.conf.set("hyperspace.advisor.lifecycle.autoVacuum", True)
        session.conf.set("hyperspace.advisor.minConfidence", 0.1)
        with faults.injected("advisor.apply", crash=True):
            with pytest.raises(faults.CrashPoint):
                hs.lifecycle().sweep()
        # Nothing mutated mid-sweep: recover() is a no-op repair and the
        # catalog still answers.
        reports = hs.recover()
        assert all(not r["rolled"] for r in reports.values())
        report = hs.lifecycle().sweep()
        assert report["applied"], report
        session.run(df.filter(col("k") == 17).select("k", "v"))
        assert session.workload.snapshot()[-1].used_indexes

    def test_apply_crash_mid_create_recovers(self, session, hs, tmp_path):
        """CrashPoint INSIDE the auto-created index's build (log.written):
        the advisor inherits the Action machine's crash safety — the
        transient entry rolls back via recover() and queries still run."""
        df = self._workload(session, hs, tmp_path)
        session.conf.set("hyperspace.advisor.lifecycle.autoCreate", True)
        session.conf.set("hyperspace.advisor.minConfidence", 0.1)
        with faults.injected("log.write", crash=True, at_call=1):
            with pytest.raises(faults.CrashPoint):
                hs.lifecycle().sweep()
        hs.recover()
        r = session.run(df.filter(col("k") == 17).select("k", "v"))
        assert r.num_rows >= 0  # query plane healthy post-recovery

    def test_apply_transient_fault_recorded_not_fatal(self, session, hs, tmp_path):
        """A transient FaultError at advisor.apply surfaces through the
        declared sweep contract (OSError)."""
        self._workload(session, hs, tmp_path)
        session.conf.set("hyperspace.advisor.lifecycle.autoCreate", True)
        session.conf.set("hyperspace.advisor.minConfidence", 0.1)
        with faults.injected("advisor.apply"):
            with pytest.raises(OSError):
                hs.lifecycle().sweep()

    def test_rebucket_is_report_only(self, session, hs, tmp_path):
        from hyperspace_tpu.advisor.whatif import Recommendation

        session.conf.set("hyperspace.advisor.lifecycle.autoCreate", True)
        session.conf.set("hyperspace.advisor.lifecycle.autoVacuum", True)
        session.conf.set("hyperspace.advisor.lifecycle.autoOptimize", True)
        rec = Recommendation(
            kind="rebucket", estimated_benefit_s=99.0, confidence=1.0,
            reason="test", index_name="whatever", num_buckets=64,
        )
        report = hs.lifecycle().sweep([rec])
        assert report["applied"] == [] and len(report["skipped"]) == 1


# -- workload log ------------------------------------------------------------

class TestWorkload:
    def test_records_are_bounded_and_accurate(self, session, hs, tmp_path):
        root = _hot_table(tmp_path, n=3000)
        df = session.parquet(root)
        hs.create_index(df, IndexConfig("kidx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(col("k") == 3).select("k", "v")
        session.run(q)
        rec = session.workload.snapshot()[-1]
        assert rec.signature == plan_signature(q)
        assert rec.used_indexes and rec.index_names == ("kidx",)
        assert rec.total_s > 0 and rec.bytes_scanned >= 0
        session.disable_hyperspace()
        session.run(q)
        rec = session.workload.snapshot()[-1]
        assert not rec.used_indexes and rec.index_names == ()

    def test_ring_is_bounded(self, tmp_system_path):
        s = HyperspaceSession(system_path=tmp_system_path)
        s.conf.set("hyperspace.advisor.workload.maxRecords", 4)
        assert s.workload._records.maxlen == 4


# -- satellite regressions ---------------------------------------------------

class TestNullSafeSetOps:
    """plan/nodes.py round-5 fix: INTERSECT/EXCEPT follow SQL set
    semantics on NULLs (NULL-safe positional equality) instead of the
    engine's join semantics (NULL never equal)."""

    def _tables(self, session, tmp_path):
        l = _write(tmp_path, "setl", pa.table({
            "k": pa.array([1, 1, None, None, 2], type=pa.int64()),
            "s": pa.array(["a", None, "b", None, "c"]),
        }))
        r = _write(tmp_path, "setr", pa.table({
            "k": pa.array([1, None, None, 3], type=pa.int64()),
            "s": pa.array([None, "b", None, "z"]),
        }))
        return session.parquet(l), session.parquet(r)

    @staticmethod
    def _rows(res):
        d = res.decode()
        return sorted(zip(*(d[c] for c in d)), key=repr)

    def test_intersect_keeps_null_bearing_matches(self, session, tmp_path):
        L, R = self._tables(session, tmp_path)
        got = self._rows(session.run(L.intersect(R)))
        assert got == sorted([(1, None), (None, "b"), (None, None)], key=repr)

    def test_except_removes_null_bearing_matches(self, session, tmp_path):
        L, R = self._tables(session, tmp_path)
        got = self._rows(session.run(L.except_(R)))
        assert got == sorted([(1, "a"), (2, "c")], key=repr)

    def test_null_safe_survives_json_round_trip(self, session, tmp_path):
        from hyperspace_tpu.plan.nodes import plan_from_json

        L, R = self._tables(session, tmp_path)
        p = L.intersect(R)
        assert p.null_safe is True
        rt = plan_from_json(p.to_json())
        assert rt.null_safe is True
        # Ordinary joins stay null-UNSAFE and serialize without the flag.
        j = L.join(R, ["k"])
        assert j.null_safe is False and "nullSafe" not in j.to_json()

    def test_ordinary_join_null_semantics_unchanged(self, session, tmp_path):
        L, R = self._tables(session, tmp_path)
        out = session.run(L.select("k").join(R.select("k"), ["k"]))
        assert not any(v is None for v in out.decode()["k"])

    def test_null_never_matches_physical_zero(self, session, tmp_path):
        """The null-safe lane must not let NULL alias the deterministic
        0 a null slot physically holds."""
        l = _write(tmp_path, "zl", pa.table({"k": pa.array([0, None], type=pa.int64())}))
        r = _write(tmp_path, "zr", pa.table({"k": pa.array([0], type=pa.int64())}))
        L, R = session.parquet(l), session.parquet(r)
        got = self._rows(session.run(L.intersect(R)))
        assert got == [(0,)]  # NULL does not intersect with 0


class TestDictionaryNullRoundTrip:
    """execution/table.py round-5 fix: a null Arrow dictionary ENTRY must
    decode as a null row, not the literal string 'None'."""

    def test_dictionary_entry_null_round_trip(self):
        from hyperspace_tpu.execution.table import ColumnTable
        from hyperspace_tpu.schema import Schema

        ind = pa.array([0, 1, 2, 0, 1], type=pa.int32())
        dic = pa.array(["a", None, "b"])
        arr = pa.DictionaryArray.from_arrays(ind, dic)
        t = pa.table({"s": arr})
        ct = ColumnTable.from_arrow(t, Schema.from_arrow(t.schema))
        got = list(ct.decode()["s"])
        assert got == ["a", None, "b", "a", None]
        assert "None" not in set(ct.dictionaries["s"])
        back = ct.to_arrow()
        assert back.column("s").null_count == 2

    def test_dictionary_and_index_nulls_compose(self):
        from hyperspace_tpu.execution.table import ColumnTable
        from hyperspace_tpu.schema import Schema

        ind = pa.array([0, None, 1, 0], type=pa.int32())
        dic = pa.array(["x", None])
        arr = pa.DictionaryArray.from_arrays(ind, dic)
        t = pa.table({"s": arr})
        ct = ColumnTable.from_arrow(t, Schema.from_arrow(t.schema))
        assert list(ct.decode()["s"]) == ["x", None, None, "x"]

    def test_parquet_round_trip_with_dictionary_nulls(self, session, tmp_path):
        root = _write(tmp_path, "dictnull", pa.table({
            "s": pa.array(["a", None, "b", "a", None]),
            "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0]),
        }))
        out = session.run(session.parquet(root).filter(col("v") > 0).select("s", "v"))
        assert list(out.decode()["s"]) == ["a", None, "b", "a", None]
