"""Window functions: ranking family, partition/rows/range frames,
null handling, JSON round-trip, optimizer integration — all checked
against an independent pandas oracle. The sorted-segment formulation
(ops/window.py) is the TPU analog of Spark's WindowExec, which the
reference's environment provides (SURVEY.md §2.2)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import HyperspaceSession
from hyperspace_tpu.plan.nodes import plan_from_json


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("windata")
    rng = np.random.default_rng(3)
    n = 3_000
    null_v = rng.random(n) < 0.1
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 25, n).astype(np.int64),
            "o": rng.integers(0, 500, n).astype(np.int64),
            "v": pd.array(np.where(null_v, 0, rng.integers(1, 100, n)), dtype="Int64"),
            "f": np.round(rng.normal(size=n) * 7, 3),
        }
    )
    df.loc[null_v, "v"] = pd.NA
    root = tmp_path / "t"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    ds = session.parquet(root)
    return session, ds, df


def test_row_number_rank_dense_rank(data):
    session, ds, df = data
    q = ds.window(
        ["g"],
        order_by=[("o", True)],
        funcs=[
            ("row_number", None, "rn"),
            ("rank", None, "rk"),
            ("dense_rank", None, "dr"),
        ],
    )
    got = session.to_pandas(q)
    gs = df.groupby("g").o
    exp_rk = gs.rank(method="min").astype(np.int64)
    exp_dr = gs.rank(method="dense").astype(np.int64)
    # got rows come back in input order (scatter by inverse perm).
    np.testing.assert_array_equal(got.rk.to_numpy(), exp_rk.to_numpy())
    np.testing.assert_array_equal(got.dr.to_numpy(), exp_dr.to_numpy())
    # row_number: a permutation within ties of rank.
    assert got.rn.min() == 1
    chk = got.groupby("g").rn.apply(lambda s: sorted(s) == list(range(1, len(s) + 1)))
    assert chk.all()


def test_partition_frame_aggregates(data):
    session, ds, df = data
    q = ds.window(
        ["g"],
        funcs=[
            ("sum", "v", "sv"),
            ("mean", "f", "mf"),
            ("count", None, "n"),
            ("max", "f", "xf"),
            ("min", "v", "nv"),
        ],
    )
    got = session.to_pandas(q)
    grp = df.groupby("g")
    np.testing.assert_array_equal(
        got.sv.to_numpy(dtype=np.float64),
        grp.v.transform("sum").to_numpy(dtype=np.float64),
    )
    np.testing.assert_allclose(got.mf.to_numpy(), grp.f.transform("mean").to_numpy(), rtol=1e-12)
    np.testing.assert_array_equal(got.n.to_numpy(), grp.g.transform("size").to_numpy())
    np.testing.assert_allclose(got.xf.to_numpy(), grp.f.transform("max").to_numpy())
    np.testing.assert_array_equal(
        got.nv.to_numpy(dtype=np.float64),
        grp.v.transform("min").to_numpy(dtype=np.float64),
    )


def test_rows_frame_running_sum_and_minmax(data):
    session, ds, df = data
    q = ds.window(
        ["g"],
        order_by=[("o", True)],
        funcs=[("sum", "f", "rs"), ("min", "f", "rmin"), ("count", None, "rc")],
        frame="rows",
    )
    got = session.to_pandas(q)
    # The engine's ROWS frame breaks o-ties by input order (stable sort),
    # which matches pandas groupby cumsum after a stable sort by o.
    d = df.assign(_i=np.arange(len(df))).sort_values(["g", "o", "_i"], kind="stable")
    d["rs"] = d.groupby("g").f.cumsum()
    d["rmin"] = d.groupby("g").f.cummin()
    d["rc"] = d.groupby("g").cumcount() + 1
    d = d.sort_values("_i")
    np.testing.assert_allclose(got.rs.to_numpy(), d.rs.to_numpy(), rtol=1e-12)
    np.testing.assert_allclose(got.rmin.to_numpy(), d.rmin.to_numpy())
    np.testing.assert_array_equal(got.rc.to_numpy(), d.rc.to_numpy())


def test_range_frame_peers_share(data):
    session, ds, df = data
    q = ds.window(["g"], order_by=[("o", True)], funcs=[("sum", "f", "rs")], frame="range")
    got = session.to_pandas(q)
    # Oracle: cumulative sum up to and including ALL peers with the same o.
    d = df.assign(_i=np.arange(len(df)))
    peer_sum = d.groupby(["g", "o"]).f.transform("sum")
    d2 = d.sort_values(["g", "o"], kind="stable")
    cum = d2.groupby("g").f.cumsum()
    peer_last = ~d2.duplicated(["g", "o"], keep="last")
    # value at last peer row, shared back
    d2["rs"] = np.where(peer_last, cum, np.nan)
    d2["rs"] = d2.iloc[::-1].groupby(["g", "o"]).rs.transform("max")
    d2 = d2.sort_values("_i")
    np.testing.assert_allclose(got.rs.to_numpy(), d2.rs.to_numpy(), rtol=1e-12)
    # Peers must share identical values.
    q2 = session.to_pandas(
        ds.window(["g"], order_by=[("o", True)], funcs=[("count", None, "rc")], frame="range")
    )
    chk = pd.DataFrame({"g": df.g, "o": df.o, "rc": q2.rc}).groupby(["g", "o"]).rc.nunique()
    assert (chk == 1).all()


def test_null_only_partition_gives_null_sum(tmp_path):
    df = pd.DataFrame(
        {
            "g": [0, 0, 1, 1],
            "v": pd.array([None, None, 5, None], dtype="Int64"),
        }
    )
    root = tmp_path / "t"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    ds = session.parquet(root)
    got = session.to_pandas(ds.window(["g"], funcs=[("sum", "v", "sv"), ("count", None, "n")]))
    assert got[got.g == 0].sv.isna().all()
    assert (got[got.g == 1].sv == 5).all()
    assert (got.n == 2).all()


def test_int_running_minmax_with_leading_null_is_silent(tmp_path):
    """A rows-frame min/max over an int column whose partition starts
    with NULLs must mask those prefix rows NULL — and cast silently (no
    RuntimeWarning from ±inf identities)."""
    import warnings

    df = pd.DataFrame(
        {
            "g": [0, 0, 0, 1, 1],
            "v": pd.array([None, 7, 3, None, None], dtype="Int64"),
        }
    )
    root = tmp_path / "t"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    ds = session.parquet(root)
    q = ds.window(
        ["g"], order_by=[("v", True)], funcs=[("min", "v", "rmin"), ("max", "v", "rmax")],
        frame="rows",
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        got = session.to_pandas(q)
    g1 = got[got.g == 1]
    assert g1.rmin.isna().all() and g1.rmax.isna().all()
    g0 = got[(got.g == 0) & got.v.notna()]
    assert set(g0.rmax.dropna().astype(int)) <= {3, 7}


def test_with_column_replaces_existing(data):
    session, ds, df = data
    from hyperspace_tpu.plan.expr import col, lit

    q = ds.with_column("f", col("f") * lit(2.0)).select("f")
    got = session.to_pandas(q)
    np.testing.assert_allclose(np.sort(got.f.to_numpy()), np.sort(df.f.to_numpy() * 2))
    assert q.schema.names.count("f") == 1


def test_window_json_roundtrip_and_explain(data):
    session, ds, _ = data
    q = ds.window(["g"], order_by=[("o", False)], funcs=[("rank", None, "rk")])
    d = q.to_json()
    back = plan_from_json(d)
    assert back.to_json() == d
    assert back.schema.names == q.schema.names
    session.to_pandas(q.limit(5))
    assert "WindowSortedSegments" in repr(session.last_physical_plan)


def test_window_validation(data):
    _, ds, _ = data
    with pytest.raises(ValueError):
        ds.window(["g"], funcs=[("rank", None, "rk")])  # rank needs order
    with pytest.raises(ValueError):
        ds.window(["g"], funcs=[("sum", "v", "g")])  # collides with child col
    with pytest.raises(ValueError):
        ds.window(["g"], order_by=["o"], funcs=[("sum", "v", "s")], frame="bogus")


def test_lag_lead_against_pandas_shift(data):
    session, ds, df = data
    q = ds.window(
        ["g"],
        order_by=[("o", True)],
        funcs=[
            ("lag", "v", "lag_v"),
            ("lead", "f", "lead_f", 2),
            ("lag", "o", "lag3_o", 3),
        ],
    )
    got = session.to_pandas(q)
    # Stable sort by o then partition-shift mirrors the engine's stable
    # lexsort with input-order tie-break; shift keeps the index so the
    # oracle lands back in input order automatically.
    sdf = df.sort_values("o", kind="stable")
    exp_lag = sdf.groupby("g").v.shift(1).astype("Float64").sort_index()
    exp_lead = sdf.groupby("g").f.shift(-2).sort_index()
    exp_lag3 = sdf.groupby("g").o.shift(3).sort_index()
    pd.testing.assert_series_equal(
        got.lag_v.astype("Float64"), exp_lag, check_names=False
    )
    np.testing.assert_allclose(
        got.lead_f.to_numpy(dtype=np.float64),
        exp_lead.to_numpy(dtype=np.float64),
        equal_nan=True,
    )
    np.testing.assert_allclose(
        got.lag3_o.astype("Float64").to_numpy(dtype=np.float64, na_value=np.nan),
        exp_lag3.to_numpy(dtype=np.float64, na_value=np.nan),
        equal_nan=True,
    )


def test_lag_lead_strings_and_json(tmp_path):
    df = pd.DataFrame(
        {
            "g": [0, 0, 0, 1, 1],
            "o": [1, 2, 3, 1, 2],
            "s": ["a", "b", "c", "x", "y"],
        }
    )
    root = tmp_path / "t"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    ds = session.parquet(root)
    q = ds.window(
        ["g"], order_by=[("o", True)],
        funcs=[("lag", "s", "prev_s"), ("lead", "s", "next_s")],
    )
    d = q.to_json()
    assert plan_from_json(d).to_json() == d  # offset round-trips
    got = session.to_pandas(q).sort_values(["g", "o"])
    assert list(got.prev_s.fillna("-")) == ["-", "a", "b", "-", "x"]
    assert list(got.next_s.fillna("-")) == ["b", "c", "-", "y", "-"]


def test_lag_lead_validation(data):
    _, ds, _ = data
    with pytest.raises(ValueError):
        ds.window(["g"], funcs=[("lag", "v", "lv")])  # needs ORDER BY
    with pytest.raises(ValueError):
        ds.window(["g"], order_by=["o"], funcs=[("lag", "v", "lv", 0)])  # offset >= 1
