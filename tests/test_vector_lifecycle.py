"""Vector-index refresh (full + incremental) and optimize.

Round-1 verdict weak #6: the ANN index rotted on append (refresh and
optimize raised). The contract here mirrors the covering index: after an
append + incremental refresh, a full-probe search must EXACTLY equal
brute force over the grown dataset; optimize compacts back to one
version dir and retrains, preserving the equality gate.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, VectorIndexConfig
from hyperspace_tpu.exceptions import HyperspaceError

NP = 8  # partitions


def _write_emb(root, emb, ids, name):
    d = emb.shape[1]
    table = pa.table(
        {
            "id": pa.array(ids.astype(np.int64)),
            "emb": pa.FixedSizeListArray.from_arrays(
                pa.array(emb.reshape(-1), type=pa.float32()), d
            ),
        }
    )
    pq.write_table(table, root / name)


@pytest.fixture
def grown(tmp_path):
    """(session, hs, scan, emb_all): an index built on 3000 rows, then 800
    appended rows NOT yet indexed."""
    rng = np.random.default_rng(7)
    d, c = 16, 8
    centers = rng.standard_normal((c, d)).astype(np.float32) * 4
    e1 = (centers[rng.integers(0, c, 3000)] + rng.standard_normal((3000, d))).astype(np.float32)
    root = tmp_path / "vsrc"
    root.mkdir()
    _write_emb(root, e1, np.arange(3000), "a.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=NP)
    hs = Hyperspace(session)
    scan = session.parquet(root)
    hs.create_vector_index(scan, VectorIndexConfig("vl", "emb", ["id"], num_partitions=NP))
    e2 = (centers[rng.integers(0, c, 800)] + rng.standard_normal((800, d))).astype(np.float32)
    _write_emb(root, e2, np.arange(3000, 3800), "b.parquet")
    return session, hs, scan, np.concatenate([e1, e2])


def _full_probe_equality(session, hs, scan, emb_all, q=5, k=10):
    rng = np.random.default_rng(3)
    queries = emb_all[rng.choice(len(emb_all), q, replace=False)] + 0.01
    session.disable_hyperspace()
    exact = hs.ann_search(scan, queries, k=k)
    session.enable_hyperspace()
    approx = hs.ann_search(scan, queries, k=k, nprobe=NP)
    np.testing.assert_allclose(
        np.sort(exact.scores, axis=1), np.sort(approx.scores, axis=1), rtol=1e-4
    )
    eids = exact.rows.columns["id"].reshape(q, -1)
    aids = approx.rows.columns["id"].reshape(q, -1)
    for i in range(q):
        assert set(eids[i]) == set(aids[i])


def test_incremental_refresh_restores_equality(grown, tmp_path):
    session, hs, scan, emb_all = grown
    # Stale index: search falls back to brute force (index unused).
    session.enable_hyperspace()
    hs.refresh_index("vl", mode="incremental")
    entry = session.manager.get_indexes()[0]
    assert entry.content.directories == ["v__=0", "v__=1"]
    # Delta dir has its own centroids copy + manifest.
    vdir = tmp_path / "idx" / "vl" / "v__=1"
    assert (vdir / "_centroids.npy").exists()
    _full_probe_equality(session, hs, scan, emb_all)
    # Appended rows are actually findable: query AT an appended point.
    q = emb_all[3500][None, :]
    res = hs.ann_search(scan, q, k=1, nprobe=NP)
    assert res.rows.columns["id"][0] == 3500


def test_incremental_refresh_partial_probe_recall(grown):
    session, hs, scan, emb_all = grown
    hs.refresh_index("vl", mode="incremental")
    session.enable_hyperspace()
    rng = np.random.default_rng(4)
    queries = emb_all[rng.choice(len(emb_all), 20, replace=False)] + 0.01
    session.disable_hyperspace()
    exact = hs.ann_search(scan, queries, k=10)
    session.enable_hyperspace()
    approx = hs.ann_search(scan, queries, k=10, nprobe=3)
    eids = exact.rows.columns["id"].reshape(20, -1)
    aids = approx.rows.columns["id"].reshape(20, -1)
    recall = np.mean([len(set(eids[i]) & set(aids[i])) / 10 for i in range(20)])
    assert recall >= 0.9, f"recall {recall:.2f} after incremental refresh"


def test_full_refresh_retrains_single_dir(grown):
    session, hs, scan, emb_all = grown
    hs.refresh_index("vl", mode="full")
    entry = session.manager.get_indexes()[0]
    assert entry.content.directories == ["v__=1"]
    _full_probe_equality(session, hs, scan, emb_all)


def test_optimize_compacts_and_retrains(grown, tmp_path):
    session, hs, scan, emb_all = grown
    hs.refresh_index("vl", mode="incremental")
    hs.optimize_index("vl")
    entry = session.manager.get_indexes()[0]
    assert entry.content.directories == ["v__=2"]
    # One file per partition, all rows present.
    vdir = tmp_path / "idx" / "vl" / "v__=2"
    total = sum(
        pq.read_metadata(vdir / f"bucket-{p:05d}.parquet").num_rows for p in range(NP)
    )
    assert total == len(emb_all)
    assert (vdir / "_centroids.npy").exists()
    _full_probe_equality(session, hs, scan, emb_all)


def test_incremental_refresh_requires_appends(grown):
    session, hs, scan, emb_all = grown
    hs.refresh_index("vl", mode="incremental")
    with pytest.raises(HyperspaceError, match="no appended"):
        hs.refresh_index("vl", mode="incremental")


def test_optimize_vector_requires_active(grown):
    session, hs, scan, _ = grown
    hs.delete_index("vl")
    with pytest.raises(HyperspaceError, match="ACTIVE"):
        hs.optimize_index("vl")
