"""Host-native join venue: the C++ bucket-parallel merge join must be
result-identical to the device kernel, and the venue choice must obey
the config override. On tunneled TPU deployments the device→host
readback of the match pairs dominates a materialized join, so the
executor picks the host kernel when measured bandwidth is low
(parallel/bandwidth.py); both venues share every other stage."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col, lit
from hyperspace_tpu.config import JOIN_VENUE
from hyperspace_tpu import native


@pytest.fixture
def joined(tmp_path):
    rng = np.random.default_rng(0)
    f = pd.DataFrame(
        {
            "k": rng.integers(0, 500, 20_000).astype(np.int64),
            "a": rng.normal(size=20_000),
        }
    )
    d = pd.DataFrame({"k": np.arange(400, dtype=np.int64), "b": rng.normal(size=400)})
    (tmp_path / "f").mkdir()
    (tmp_path / "d").mkdir()
    pq.write_table(pa.Table.from_pandas(f, preserve_index=False), tmp_path / "f" / "p.parquet")
    pq.write_table(pa.Table.from_pandas(d, preserve_index=False), tmp_path / "d" / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=8)
    hs = Hyperspace(session)
    fs, ds = session.parquet(tmp_path / "f"), session.parquet(tmp_path / "d")
    hs.create_index(fs, IndexConfig("fk", ["k"], ["a"]))
    hs.create_index(ds, IndexConfig("dk", ["k"], ["b"]))
    session.enable_hyperspace()
    return session, fs, ds, f, d


needs_native = pytest.mark.skipif(not native.available(), reason="native library not built")


@needs_native
def test_host_venue_matches_device_venue(joined):
    session, fs, ds, f, d = joined
    q = fs.join(ds, ["k"])
    session.conf.set(JOIN_VENUE, "device")
    r_dev = session.to_pandas(q).sort_values(["k", "a"]).reset_index(drop=True)
    assert session.last_query_stats["join_kernel"] == "device-searchsorted"
    session.conf.set(JOIN_VENUE, "host")
    r_host = session.to_pandas(q).sort_values(["k", "a"]).reset_index(drop=True)
    assert session.last_query_stats["join_kernel"] == "host-native-merge"
    assert session.last_query_stats["join_path"] == "zero-exchange-aligned"
    pd.testing.assert_frame_equal(r_dev, r_host)
    exp = f.merge(d, on="k").sort_values(["k", "a"]).reset_index(drop=True)
    np.testing.assert_allclose(r_host["a"], exp["a"])
    np.testing.assert_allclose(r_host["b"], exp["b"])


@needs_native
def test_host_venue_null_keys_do_not_join(tmp_path):
    t1 = pa.table(
        {
            "k": pa.array([1, None, 2, None, 3], type=pa.int64()),
            "a": np.arange(5, dtype=np.float64),
        }
    )
    t2 = pa.table(
        {
            "k": pa.array([1, 2, None], type=pa.int64()),
            "b": np.arange(3, dtype=np.float64),
        }
    )
    (tmp_path / "l").mkdir()
    (tmp_path / "r").mkdir()
    pq.write_table(t1, tmp_path / "l" / "p.parquet")
    pq.write_table(t2, tmp_path / "r" / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    session.conf.set(JOIN_VENUE, "host")
    ls, rs = session.parquet(tmp_path / "l"), session.parquet(tmp_path / "r")
    got = session.to_pandas(ls.join(rs, ["k"]))
    assert sorted(got["k"]) == [1, 2]  # SQL: NULL = NULL is not true


@needs_native
def test_host_venue_multi_key_and_strings(tmp_path):
    rng = np.random.default_rng(2)
    n = 3000
    f = pd.DataFrame(
        {
            "g": rng.choice(["x", "y", "z"], n),
            "k": rng.integers(0, 50, n).astype(np.int64),
            "a": rng.normal(size=n),
        }
    )
    d = pd.DataFrame(
        {
            "g": np.repeat(["x", "y", "z"], 50),
            "k": np.tile(np.arange(50, dtype=np.int64), 3),
            "b": rng.normal(size=150),
        }
    )
    (tmp_path / "f").mkdir()
    (tmp_path / "d").mkdir()
    pq.write_table(pa.Table.from_pandas(f, preserve_index=False), tmp_path / "f" / "p.parquet")
    pq.write_table(pa.Table.from_pandas(d, preserve_index=False), tmp_path / "d" / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    session.conf.set(JOIN_VENUE, "host")
    fs, ds = session.parquet(tmp_path / "f"), session.parquet(tmp_path / "d")
    got = (
        session.to_pandas(fs.join(ds, ["g", "k"]))
        .sort_values(["g", "k", "a"])
        .reset_index(drop=True)
    )
    exp = (
        f.merge(d, on=["g", "k"])
        .sort_values(["g", "k", "a"])
        .reset_index(drop=True)
    )
    assert len(got) == len(exp)
    np.testing.assert_allclose(got["a"], exp["a"])
    np.testing.assert_allclose(got["b"], exp["b"])


@needs_native
def test_native_merge_join_kernel_direct():
    """Kernel-level: matches numpy reference on adversarial runs
    (duplicates straddling bucket edges, empty buckets, all-equal runs)."""
    rng = np.random.default_rng(7)
    for trial in range(5):
        nb = 6
        lparts = [np.sort(rng.integers(0, 12, rng.integers(0, 40))).astype(np.int32) for _ in range(nb)]
        rparts = [np.sort(rng.integers(0, 12, rng.integers(0, 40))).astype(np.int32) for _ in range(nb)]
        lk = np.concatenate(lparts) if lparts else np.zeros(0, np.int32)
        rk = np.concatenate(rparts) if rparts else np.zeros(0, np.int32)
        lofs = np.concatenate([[0], np.cumsum([len(p) for p in lparts])]).astype(np.int64)
        rofs = np.concatenate([[0], np.cumsum([len(p) for p in rparts])]).astype(np.int64)
        li, ri, totals = native.merge_join_sorted(lk, lofs, rk, rofs)
        # Reference: per-bucket nested equality.
        exp_pairs = []
        for b in range(nb):
            for i in range(lofs[b], lofs[b + 1]):
                for j in range(rofs[b], rofs[b + 1]):
                    if lk[i] == rk[j]:
                        exp_pairs.append((i, j))
        got_pairs = sorted(zip(li.tolist(), ri.tolist()))
        assert got_pairs == sorted(exp_pairs), f"trial {trial}"
        assert int(totals.sum()) == len(exp_pairs)


def test_unknown_venue_raises(joined):
    from hyperspace_tpu.exceptions import HyperspaceError

    session, fs, ds, _, _ = joined
    session.conf.set(JOIN_VENUE, "hsot")
    with pytest.raises(HyperspaceError, match="join.venue"):
        session.run(fs.join(ds, ["k"]))


@needs_native
def test_forced_host_venue_wins_over_mesh(joined):
    from hyperspace_tpu.parallel.mesh import make_mesh

    session, fs, ds, f, d = joined
    session.mesh = make_mesh()
    session.conf.set(JOIN_VENUE, "host")
    got = session.to_pandas(fs.join(ds, ["k"]))
    assert session.last_query_stats["join_kernel"] == "host-native-merge"
    assert len(got) == len(f.merge(d, on="k"))


@needs_native
def test_build_venue_host_produces_identical_index(tmp_path):
    """Host and device build venues must write byte-identical bucket
    files and manifests (null/string/float32/int64 keys covered)."""
    import json

    from hyperspace_tpu.config import BUILD_VENUE

    rng = np.random.default_rng(0)
    n = 20_000
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 5_000, n).astype(np.int64),
            "s": rng.choice(["aa", "bb", None, "cc"], n),
            "v": rng.normal(size=n).astype(np.float32),
            "d": rng.normal(size=n),
        }
    )
    (tmp_path / "src").mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), tmp_path / "src" / "p.parquet")

    dirs = {}
    for venue in ("device", "host"):
        session = HyperspaceSession(system_path=str(tmp_path / f"idx_{venue}"), num_buckets=8)
        session.conf.set(BUILD_VENUE, venue)
        hs = Hyperspace(session)
        scan = session.parquet(tmp_path / "src")
        hs.create_index(scan, IndexConfig("ix", ["k", "s"], ["v", "d"]))
        dirs[venue] = tmp_path / f"idx_{venue}" / "ix" / "v__=0"
    for b in range(8):
        f = f"bucket-{b:05d}.parquet"
        pd.testing.assert_frame_equal(
            pq.read_table(dirs["device"] / f).to_pandas(),
            pq.read_table(dirs["host"] / f).to_pandas(),
        )
    m1 = json.loads((dirs["device"] / "_index_manifest.json").read_text())
    m2 = json.loads((dirs["host"] / "_index_manifest.json").read_text())
    assert m1 == m2


@pytest.mark.parametrize("venue", ["device", "host"])
def test_filtered_sides_keep_zero_exchange_join(joined, venue):
    """JoinIndexRule keeps linear sides with filters; the executor must
    apply side-local predicates per bucket and STILL take the
    bucket-aligned zero-exchange path (round-1 weak #7: such shapes
    silently fell back to the single-partition join)."""
    if venue == "host" and not native.available():
        pytest.skip("native library not built")
    session, fs, ds, f, d = joined
    session.conf.set(JOIN_VENUE, venue)
    q = fs.filter(col("a") > lit(0.0)).join(ds.filter(col("b") < lit(0.5)), ["k"])
    got = session.to_pandas(q).sort_values(["k", "a"]).reset_index(drop=True)
    assert session.last_query_stats["join_path"] == "zero-exchange-aligned"
    exp = (
        f[f.a > 0.0]
        .merge(d[d.b < 0.5], on="k")
        .sort_values(["k", "a"])
        .reset_index(drop=True)
    )
    assert len(got) == len(exp)
    np.testing.assert_allclose(got["a"], exp["a"])
    np.testing.assert_allclose(got["b"], exp["b"])


def test_env_venue_override_precedence(joined, monkeypatch):
    """HYPERSPACE_VENUE overrides auto decisions; explicit per-operator
    conf still wins; invalid values raise."""
    from hyperspace_tpu.exceptions import HyperspaceError
    from hyperspace_tpu.parallel.bandwidth import pick_venue

    monkeypatch.setenv("HYPERSPACE_VENUE", "device")
    assert pick_venue("auto", 200.0, False, "x", needs_native=False) == "device"
    # Explicit request wins over the env var.
    assert pick_venue("host", 200.0, False, "x", needs_native=False) == "host"
    monkeypatch.setenv("HYPERSPACE_VENUE", "hOst")
    with pytest.raises(HyperspaceError, match="HYPERSPACE_VENUE"):
        pick_venue("auto", 200.0, False, "x", needs_native=False)
    # End-to-end: forced device via env on an auto session.
    monkeypatch.setenv("HYPERSPACE_VENUE", "device")
    session, fs, ds, f, d = joined
    session.to_pandas(fs.join(ds, ["k"]))
    assert session.last_query_stats["join_kernel"] == "device-searchsorted"
