"""Runtime counterpart of HSL010: the declared config-key registry
(config.KNOWN_KEYS) rejects undeclared hyperspace.* keys with a
did-you-mean suggestion, and the generated docs table stays in sync."""

from __future__ import annotations

import pytest

from hyperspace_tpu import config
from hyperspace_tpu.exceptions import UnknownConfigKeyError


@pytest.fixture()
def conf():
    return config.HyperspaceConf()


class TestKnownKeysRegistry:
    def test_every_constant_key_is_declared(self):
        # Every hyperspace.* string constant in config.py is in the
        # registry (the module can't grow a key outside it).
        consts = [
            v for v in vars(config).values()
            if isinstance(v, str) and v.startswith("hyperspace.")
        ]
        assert consts
        for key in consts:
            assert key in config.KNOWN_KEYS, key

    def test_registry_entries_are_documented(self):
        for key, spec in config.KNOWN_KEYS.items():
            assert spec.doc.strip(), key
            assert spec.default.strip(), key

    def test_docs_table_lists_every_key(self):
        table = config.docs_table()
        for key in config.KNOWN_KEYS:
            assert f"`{key}`" in table

    def test_set_unknown_key_raises_with_suggestion(self, conf):
        with pytest.raises(UnknownConfigKeyError) as ei:
            conf.set("hyperspace.srve.workers", 2)
        assert ei.value.suggestion == "hyperspace.serve.workers"
        assert "did you mean" in str(ei.value)

    def test_get_unknown_key_raises(self, conf):
        with pytest.raises(UnknownConfigKeyError):
            conf.get("hyperspace.obs.enabld")

    def test_unknown_key_without_close_match_has_no_suggestion(self, conf):
        with pytest.raises(UnknownConfigKeyError) as ei:
            conf.set("hyperspace.zzzz.qqqq.wwww", 1)
        assert ei.value.suggestion is None

    def test_declared_keys_still_work(self, conf):
        conf.set(config.SERVE_WORKERS, 2)
        assert conf.get(config.SERVE_WORKERS) == 2
        conf.set("hyperspace.index.num.buckets", 16)
        assert conf.num_buckets == 16

    def test_non_hyperspace_namespace_passes_through(self, conf):
        # The overrides map stays usable as an app scratch space.
        conf.set("myapp.custom.knob", "x")
        assert conf.get("myapp.custom.knob") == "x"

    def test_explain_keys_live_in_config(self, conf):
        # Moved out of display_mode.py so the registry is the single
        # declaration point; the re-export keeps old imports working.
        from hyperspace_tpu.explain.display_mode import EXPLAIN_DISPLAY_MODE

        assert EXPLAIN_DISPLAY_MODE == config.EXPLAIN_DISPLAY_MODE
        conf.set(EXPLAIN_DISPLAY_MODE, "console")
        assert conf.get(EXPLAIN_DISPLAY_MODE) == "console"
