"""Concurrent query-serving plane tests (docs/serving.md).

Covers the QueryServer scheduler (admission control, FIFO + priority
lanes, per-query timeouts, drain/shutdown), the versioned plan cache
(hit counters, invalidation on refresh), the opt-in result cache (never
serves pre-refresh rows), the per-query handle state, and the
thread-safe metadata TTL cache counters. The hammer test is the
acceptance gate: N client threads against one session must produce
results identical to serial execution.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.exceptions import AdmissionRejected, QueryTimeout
from hyperspace_tpu.serve import PlanCache, QueryServer, ResultCache


def _session(tmp_system_path) -> HyperspaceSession:
    return HyperspaceSession(system_path=tmp_system_path)


def _assert_same(a, b, label=""):
    """Decoded result dicts must match exactly (floats to 1e-9)."""
    da, db = a.decode(), b.decode()
    assert set(da) == set(db), (label, set(da), set(db))
    for c in da:
        av, bv = np.asarray(da[c]), np.asarray(db[c])
        assert len(av) == len(bv), (label, c, len(av), len(bv))
        if av.dtype.kind in "fc":
            np.testing.assert_allclose(av, bv, rtol=1e-9, err_msg=f"{label}.{c}")
        else:
            assert (av == bv).all(), (label, c)


def _query_set(df):
    """Distinct plan shapes a serving workload mixes: point lookup,
    range scan, aggregation, order/limit."""
    return [
        df.filter(col("key") == 7).select("key", "value"),
        df.filter(col("key") == 23).select("key", "value"),
        df.filter((col("key") >= 10) & (col("key") < 20)).select("key", "value", "id"),
        df.aggregate(["key"], [("sum", "value", "s"), ("count", None, "n")]).sort(["key"]),
        df.select("id", "key").sort([("id", False)]).limit(50),
    ]


# -- the hammer: N concurrent clients == serial results ----------------------

class TestHammer:
    def test_16_clients_match_serial(self, sample_parquet, tmp_system_path):
        session = _session(tmp_system_path)
        hs = Hyperspace(session)
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("serve_idx", ["key"], ["value", "id"]))
        session.enable_hyperspace()
        queries = _query_set(df)
        serial = [session.run(q) for q in queries]

        n_clients = 16
        errors: list[BaseException] = []
        with session.serve(workers=4, max_queue_depth=256) as server:
            def client(cid: int):
                try:
                    # Each client walks the query set at its own phase, so
                    # distinct plans interleave across the worker pool.
                    for j in range(len(queries)):
                        qi = (cid + j) % len(queries)
                        out = server.submit(queries[qi]).result(timeout=300)
                        _assert_same(serial[qi], out, label=f"client{cid}/q{qi}")
                except BaseException as e:  # surfaced after join
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(i,)) for i in range(n_clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
        assert not errors, errors

    def test_hammer_with_result_cache(self, sample_parquet, tmp_system_path):
        session = _session(tmp_system_path)
        hs = Hyperspace(session)
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("serve_idx2", ["key"], ["value", "id"]))
        session.enable_hyperspace()
        q = df.filter(col("key") == 5).select("key", "value")
        serial = session.run(q)
        with session.serve(workers=4, result_cache=True) as server:
            handles = [server.submit(q) for _ in range(24)]
            for h in handles:
                _assert_same(serial, h.result(timeout=300))
            rc = server.result_cache.stats()
        assert rc["hits"] > 0  # repeats served without re-execution


# -- admission control / scheduling (deterministic via the run_fn seam) ------

class TestAdmission:
    def test_rejects_at_max_queue_depth(self, tmp_system_path):
        session = _session(tmp_system_path)
        started, release = threading.Event(), threading.Event()

        def blocking_run(plan):
            started.set()
            assert release.wait(30)
            return plan

        server = QueryServer(
            session, workers=1, max_queue_depth=2, plan_cache=False, run_fn=blocking_run
        )
        try:
            h1 = server.submit("q1")
            assert started.wait(10)  # worker busy; queue now empty
            h2 = server.submit("q2")
            h3 = server.submit("q3")
            with pytest.raises(AdmissionRejected) as ei:
                server.submit("q4")
            assert ei.value.depth == 2 and ei.value.max_depth == 2
            release.set()
            assert h1.result(timeout=30) == "q1"
            assert h2.result(timeout=30) == "q2"
            assert h3.result(timeout=30) == "q3"
        finally:
            release.set()
            server.shutdown()

    def test_priority_lane_dequeues_first(self, tmp_system_path):
        session = _session(tmp_system_path)
        order: list[str] = []
        release = threading.Event()
        started = threading.Event()

        def run_fn(plan):
            started.set()
            assert release.wait(30)
            order.append(plan)
            return plan

        server = QueryServer(session, workers=1, max_queue_depth=16,
                             plan_cache=False, run_fn=run_fn)
        try:
            server.submit("head")  # occupies the worker
            assert started.wait(10)
            ha = server.submit("a")
            hb = server.submit("b")
            hp = server.submit("p", priority=True)
            release.set()
            for h in (ha, hb, hp):
                h.result(timeout=30)
            assert order == ["head", "p", "a", "b"]
        finally:
            release.set()
            server.shutdown()

    def test_queue_timeout_discards_unexecuted(self, tmp_system_path):
        session = _session(tmp_system_path)
        release = threading.Event()
        started = threading.Event()
        ran: list[str] = []

        def run_fn(plan):
            started.set()
            ran.append(plan)
            assert release.wait(30)
            return plan

        server = QueryServer(session, workers=1, max_queue_depth=16,
                             plan_cache=False, run_fn=run_fn)
        try:
            server.submit("slow")
            assert started.wait(10)
            h = server.submit("expires", timeout=0.05)
            time.sleep(0.2)  # let the deadline lapse while queued
            release.set()
            with pytest.raises(QueryTimeout):
                h.result(timeout=30)
            assert h.timed_out and "expires" not in ran
        finally:
            release.set()
            server.shutdown()

    def test_result_wait_timeout_leaves_query_running(self, tmp_system_path):
        session = _session(tmp_system_path)
        release = threading.Event()

        def run_fn(plan):
            assert release.wait(30)
            return plan

        server = QueryServer(session, workers=1, max_queue_depth=4,
                             plan_cache=False, run_fn=run_fn)
        try:
            h = server.submit("slow")
            with pytest.raises(QueryTimeout):
                h.result(timeout=0.05)
            assert not h.done()  # gave up waiting; query not cancelled
            release.set()
            assert h.result(timeout=30) == "slow"
        finally:
            release.set()
            server.shutdown()

    def test_drain_waits_then_resumes_admission(self, tmp_system_path):
        session = _session(tmp_system_path)
        server = QueryServer(session, workers=2, max_queue_depth=16,
                             plan_cache=False, run_fn=lambda p: p)
        try:
            handles = [server.submit(i) for i in range(8)]
            assert server.drain(timeout=30)
            assert all(h.done() for h in handles)
            assert server.submit("after").result(timeout=30) == "after"
        finally:
            server.shutdown()

    def test_shutdown_nowait_cancels_queued(self, tmp_system_path):
        session = _session(tmp_system_path)
        release = threading.Event()
        started = threading.Event()

        def run_fn(plan):
            started.set()
            assert release.wait(30)
            return plan

        server = QueryServer(session, workers=1, max_queue_depth=16,
                             plan_cache=False, run_fn=run_fn)
        server.submit("running")
        assert started.wait(10)
        queued = server.submit("queued")
        release.set()
        server.shutdown(wait=False)
        with pytest.raises(AdmissionRejected):
            queued.result(timeout=30)
        assert queued.cancelled
        with pytest.raises(AdmissionRejected):
            server.submit("late")

    def test_errors_surface_on_handle_not_worker(self, tmp_system_path):
        session = _session(tmp_system_path)

        def run_fn(plan):
            raise ValueError(f"boom:{plan}")

        server = QueryServer(session, workers=1, max_queue_depth=4,
                             plan_cache=False, run_fn=run_fn)
        try:
            h = server.submit("x")
            with pytest.raises(ValueError, match="boom:x"):
                h.result(timeout=30)
            # The worker survived the failure and serves the next query.
            h2 = server.submit("y")
            with pytest.raises(ValueError, match="boom:y"):
                h2.result(timeout=30)
        finally:
            server.shutdown()

    def test_result_preserves_original_traceback(self, tmp_system_path):
        """The HSL017 audit contract for the worker error path: the
        exception result() re-raises carries the ORIGINAL raising frames
        (the worker's except BaseException stores the object, traceback
        intact — preserved, not swallowed)."""
        import traceback

        session = _session(tmp_system_path)

        def deep_failure():
            raise ValueError("boom:traceback")

        def run_fn(plan):
            deep_failure()

        server = QueryServer(session, workers=1, max_queue_depth=4,
                             plan_cache=False, run_fn=run_fn)
        try:
            h = server.submit("x")
            with pytest.raises(ValueError) as excinfo:
                h.result(timeout=30)
            frames = [f.name for f in traceback.extract_tb(excinfo.value.__traceback__)]
            assert "deep_failure" in frames  # origin frame survives
            assert "run_fn" in frames        # ...with its caller chain
        finally:
            server.shutdown()


# -- plan cache ---------------------------------------------------------------

class TestPlanCache:
    def test_repeat_query_hits_and_refresh_invalidates(self, sample_parquet, tmp_system_path):
        session = _session(tmp_system_path)
        hs = Hyperspace(session)
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("pc_idx", ["key"], ["value"]))
        session.enable_hyperspace()
        q = df.filter(col("key") == 3).select("key", "value")
        cache = PlanCache(max_entries=8)
        with session.serve(workers=1, plan_cache=cache) as server:
            first = server.submit(q).result(timeout=300)
            s0 = cache.stats()
            assert s0["misses"] >= 1 and s0["entries"] == 1
            second = server.submit(q).result(timeout=300)
            s1 = cache.stats()
            assert s1["hits"] == s0["hits"] + 1  # optimized_plan skipped
            _assert_same(first, second)
            # refresh commits a new log entry -> version stamp bumps ->
            # the old key can never hit again.
            hs.refresh_index("pc_idx")
            third = server.submit(q).result(timeout=300)
            s2 = cache.stats()
            assert s2["misses"] == s1["misses"] + 1
            assert s2["hits"] == s1["hits"]
            _assert_same(first, third)

    def test_distinct_plans_get_distinct_entries(self, sample_parquet, tmp_system_path):
        session = _session(tmp_system_path)
        hs = Hyperspace(session)
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("pc_idx2", ["key"], ["value"]))
        session.enable_hyperspace()
        cache = PlanCache(max_entries=8)
        q1 = df.filter(col("key") == 1).select("key", "value")
        q2 = df.filter(col("key") == 2).select("key", "value")
        with session.serve(workers=1, plan_cache=cache) as server:
            server.submit(q1).result(timeout=300)
            server.submit(q2).result(timeout=300)
        assert cache.stats()["entries"] == 2


# -- result cache -------------------------------------------------------------

class TestResultCache:
    def test_refresh_mid_flight_never_serves_stale_rows(
        self, sample_parquet, tmp_system_path, tmp_path
    ):
        import pyarrow as pa
        import pyarrow.parquet as pq

        session = _session(tmp_system_path)
        hs = Hyperspace(session)
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("rc_idx", ["key"], ["value", "id"]))
        session.enable_hyperspace()
        q = df.filter(col("key") == 77).select("id", "key", "value")
        rc = ResultCache(max_bytes=64 << 20)
        with session.serve(workers=2, result_cache=rc) as server:
            before = server.submit(q).result(timeout=300)
            again = server.submit(q).result(timeout=300)
            _assert_same(before, again)
            assert rc.stats()["hits"] >= 1
            n_before = len(before.decode()["id"])

            # Mid-flight world change: append rows with key=77, refresh.
            extra = pa.table({
                "id": np.arange(10_000, 10_008, dtype=np.int64),
                "key": np.full(8, 77, dtype=np.int64),
                "value": np.linspace(0.0, 1.0, 8),
                "name": [f"late_{i}" for i in range(8)],
            })
            pq.write_table(extra, f"{sample_parquet}/part-2.parquet")
            hs.refresh_index("rc_idx")

            after = server.submit(q).result(timeout=300)
            ids = set(np.asarray(after.decode()["id"]).tolist())
            assert len(after.decode()["id"]) == n_before + 8
            assert {10_000, 10_007} <= ids  # post-refresh rows present
            # and the pre-refresh entry was unreachable, not "lucky":
            # its key embeds the old fingerprint + log versions.
            hits_before = rc.stats()["hits"]
            once_more = server.submit(q).result(timeout=300)
            _assert_same(after, once_more)
            assert rc.stats()["hits"] == hits_before + 1

    def test_byte_budget_evicts_lru(self, sample_parquet, tmp_system_path):
        session = _session(tmp_system_path)
        df = session.parquet(sample_parquet)
        session.enable_hyperspace()
        rc = ResultCache(max_bytes=1)  # everything is "too large"
        with session.serve(workers=1, result_cache=rc) as server:
            q = df.filter(col("key") == 1).select("key")
            server.submit(q).result(timeout=300)
            server.submit(q).result(timeout=300)
        st = rc.stats()
        assert st["entries"] == 0 and st["hits"] == 0  # nothing admitted


# -- per-query handle state / session view ------------------------------------

class TestPerQueryState:
    def test_handle_carries_profile_and_stats(self, sample_parquet, tmp_system_path):
        session = _session(tmp_system_path)
        df = session.parquet(sample_parquet)
        q = df.filter(col("key") == 9).select("key", "value")
        with session.serve(workers=1) as server:
            h = server.submit(q)
            h.result(timeout=300)
        assert h.profile is not None and h.stats is not None
        assert h.stats.get("files_read", 0) >= 1
        # The session view tracks the most recent completed query.
        assert session.last_profile() is not None

    def test_run_query_does_not_touch_session_view(self, sample_parquet, tmp_system_path):
        session = _session(tmp_system_path)
        df = session.parquet(sample_parquet)
        q = df.filter(col("key") == 4).select("key")
        outcome = session.run_query(q)
        assert outcome.result is not None and outcome.profile is not None
        assert session.last_profile() is None  # only _publish installs it
        session._publish(outcome)
        assert session.last_profile() is outcome.profile

    def test_concurrent_direct_runs_keep_view_consistent(
        self, sample_parquet, tmp_system_path
    ):
        """Two threads calling plain session.run(): the lock-guarded view
        must always pair stats with the matching physical plan (the
        pre-hardening code could interleave them)."""
        session = _session(tmp_system_path)
        df = session.parquet(sample_parquet)
        q1 = df.filter(col("key") == 1).select("key")
        q2 = df.aggregate(["key"], [("count", None, "n")])
        errs: list[BaseException] = []

        def run_many(q):
            try:
                for _ in range(5):
                    session.run(q)
            except BaseException as e:
                errs.append(e)

        ts = [threading.Thread(target=run_many, args=(q,)) for q in (q1, q2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=600)
        assert not errs, errs
        assert session.last_profile() is not None


# -- metadata TTL cache thread-safety -----------------------------------------

class TestMetadataCache:
    def test_hit_miss_counters(self):
        from hyperspace_tpu import stats
        from hyperspace_tpu.metadata.cache import CreationTimeBasedCache

        c = CreationTimeBasedCache(expiry_seconds=60)
        h0, m0 = stats.get("metadata.cache.hits"), stats.get("metadata.cache.misses")
        assert c.get() is None
        c.set([1, 2])
        assert c.get() == [1, 2]
        assert stats.get("metadata.cache.hits") == h0 + 1
        assert stats.get("metadata.cache.misses") == m0 + 1

    def test_expiry_counts_as_miss(self):
        from hyperspace_tpu import stats
        from hyperspace_tpu.metadata.cache import CreationTimeBasedCache

        c = CreationTimeBasedCache(expiry_seconds=0.0)
        c.set("entry")
        time.sleep(0.01)
        m0 = stats.get("metadata.cache.misses")
        assert c.get() is None
        assert stats.get("metadata.cache.misses") == m0 + 1

    def test_concurrent_get_set_clear_no_torn_state(self):
        """Hammer one cache from reader/writer/clearer threads: every
        get() returns either None or a fully consistent entry (the torn
        read between stamp check and eviction is what the single lock
        closed)."""
        from hyperspace_tpu.metadata.cache import CreationTimeBasedCache

        c = CreationTimeBasedCache(expiry_seconds=0.005)
        stop = time.monotonic() + 0.5
        errs: list[BaseException] = []

        def reader():
            try:
                while time.monotonic() < stop:
                    got = c.get()
                    assert got is None or got == ("payload", 123)
            except BaseException as e:
                errs.append(e)

        def writer():
            while time.monotonic() < stop:
                c.set(("payload", 123))

        def clearer():
            while time.monotonic() < stop:
                c.clear()

        threads = [threading.Thread(target=f) for f in (reader, reader, writer, clearer)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
