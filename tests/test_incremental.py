"""Incremental refresh + Hybrid Scan contract tests.

The reference v0.2 only has full-rebuild refresh; these cover the
incremental/delta machinery the BASELINE configs require (TPC-DS Hybrid
Scan; NYC-Taxi incremental refresh + compaction loop). The contract mirrors
the E2E equality gate: with-index results must be row-identical to
no-index results after every mutation.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.config import (
    INDEX_HYBRID_SCAN_ENABLED,
    INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO,
)
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.plan.nodes import Union


@pytest.fixture
def session(tmp_system_path):
    return HyperspaceSession(system_path=tmp_system_path, num_buckets=8)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def frames_equal(a: pd.DataFrame, b: pd.DataFrame):
    assert sorted(a.columns) == sorted(b.columns)
    cols = sorted(a.columns)
    a2 = a[cols].sort_values(cols).reset_index(drop=True)
    b2 = b[cols].sort_values(cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(a2, b2, check_dtype=False)


def index_used(plan) -> bool:
    return any(s.bucket_spec is not None for s in plan.leaves())


def has_union(plan) -> bool:
    if isinstance(plan, Union):
        return True
    return any(has_union(c) for c in plan.children())


def append_rows(root, n=300, seed=7, fname="part-appended.parquet"):
    rng = np.random.default_rng(seed)
    table = pa.table(
        {
            "id": pa.array(np.arange(100_000, 100_000 + n, dtype=np.int64)),
            "key": pa.array(rng.integers(0, 100, size=n, dtype=np.int64)),
            "value": pa.array(rng.standard_normal(n).astype(np.float64)),
            "name": pa.array([f"name_{i % 37}" for i in range(n)]),
        }
    )
    import pathlib

    pq.write_table(table, pathlib.Path(root) / fname)


class TestIncrementalRefresh:
    def test_incremental_refresh_filter_equality(self, session, hs, sample_parquet):
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("inc1", ["key"], ["value", "id"]))
        append_rows(sample_parquet)

        hs.refresh_index("inc1", mode="incremental")

        entry = session.manager.get_indexes()[0]
        assert entry.content.directories == ["v__=0", "v__=1"]

        q = df.filter(col("key") == 42).select("key", "value")
        session.enable_hyperspace()
        opt = session.optimized_plan(q)
        assert index_used(opt), "index must match again after incremental refresh"
        got = session.to_pandas(q)
        session.disable_hyperspace()
        frames_equal(got, session.to_pandas(q))

    def test_incremental_refresh_join_equality(self, session, hs, sample_parquet, tmp_path):
        rng = np.random.default_rng(3)
        n = 400
        other_root = tmp_path / "dim"
        other_root.mkdir()
        pq.write_table(
            pa.table(
                {
                    "key": pa.array(np.arange(100, dtype=np.int64)),
                    "label": pa.array([f"l{i}" for i in range(100)]),
                }
            ),
            other_root / "dim-0.parquet",
        )
        fact = session.parquet(sample_parquet)
        dim = session.parquet(other_root)
        hs.create_index(fact, IndexConfig("factidx", ["key"], ["value"]))
        hs.create_index(dim, IndexConfig("dimidx", ["key"], ["label"]))

        append_rows(sample_parquet)
        hs.refresh_index("factidx", mode="incremental")

        q = fact.select("key", "value").join(dim.select("key", "label"), ["key"])
        session.enable_hyperspace()
        opt = session.optimized_plan(q)
        assert index_used(opt)
        got = session.to_pandas(q)
        session.disable_hyperspace()
        frames_equal(got, session.to_pandas(q))

    def test_optimize_compacts_delta_versions(self, session, hs, sample_parquet):
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("inc2", ["key"], ["value"]))
        append_rows(sample_parquet, seed=11, fname="a1.parquet")
        hs.refresh_index("inc2", mode="incremental")
        append_rows(sample_parquet, seed=12, fname="a2.parquet")
        hs.refresh_index("inc2", mode="incremental")

        entry = session.manager.get_indexes()[0]
        assert len(entry.content.directories) == 3

        hs.optimize_index("inc2")
        entry = session.manager.get_indexes()[0]
        assert entry.content.directories == ["v__=3"]

        q = df.filter(col("key") == 5).select("key", "value")
        session.enable_hyperspace()
        assert index_used(session.optimized_plan(q))
        got = session.to_pandas(q)
        session.disable_hyperspace()
        frames_equal(got, session.to_pandas(q))

    def test_incremental_refresh_without_new_files_fails(self, session, hs, sample_parquet):
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("inc3", ["key"], ["value"]))
        with pytest.raises(HyperspaceError, match="no appended"):
            hs.refresh_index("inc3", mode="incremental")

    def test_incremental_refresh_with_deleted_file_fails(self, session, hs, sample_parquet):
        import pathlib

        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("inc4", ["key"], ["value"]))
        pathlib.Path(sample_parquet, "part-1.parquet").unlink()
        with pytest.raises(HyperspaceError, match="deleted or modified"):
            hs.refresh_index("inc4", mode="incremental")

    def test_unknown_refresh_mode_rejected(self, session, hs, sample_parquet):
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("inc5", ["key"], ["value"]))
        with pytest.raises(HyperspaceError, match="unknown refresh mode"):
            hs.refresh_index("inc5", mode="sideways")


class TestHybridScan:
    def enable_hybrid(self, session, ratio=10.0):
        session.conf.set(INDEX_HYBRID_SCAN_ENABLED, True)
        session.conf.set(INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO, ratio)

    def test_filter_hybrid_scan_equality(self, session, hs, sample_parquet):
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("h1", ["key"], ["value", "id"]))
        append_rows(sample_parquet)

        q = df.filter(col("key") == 42).select("key", "value")
        session.enable_hyperspace()
        # Without hybrid scan: stale signature ⇒ no rewrite.
        assert not index_used(session.optimized_plan(q))

        self.enable_hybrid(session)
        opt = session.optimized_plan(q)
        assert index_used(opt) and has_union(opt), "hybrid scan union expected"
        got = session.to_pandas(q)
        session.disable_hyperspace()
        frames_equal(got, session.to_pandas(q))

    def test_join_hybrid_scan_equality(self, session, hs, sample_parquet, tmp_path):
        other_root = tmp_path / "dim"
        other_root.mkdir()
        pq.write_table(
            pa.table(
                {
                    "key": pa.array(np.arange(100, dtype=np.int64)),
                    "label": pa.array([f"l{i}" for i in range(100)]),
                }
            ),
            other_root / "dim-0.parquet",
        )
        fact = session.parquet(sample_parquet)
        dim = session.parquet(other_root)
        hs.create_index(fact, IndexConfig("hf", ["key"], ["value"]))
        hs.create_index(dim, IndexConfig("hd", ["key"], ["label"]))
        append_rows(sample_parquet)

        q = fact.select("key", "value").join(dim.select("key", "label"), ["key"])
        session.enable_hyperspace()
        self.enable_hybrid(session)
        opt = session.optimized_plan(q)
        assert index_used(opt) and has_union(opt)
        got = session.to_pandas(q)
        session.disable_hyperspace()
        frames_equal(got, session.to_pandas(q))

    def test_hybrid_scan_respects_appended_ratio(self, session, hs, sample_parquet):
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("h2", ["key"], ["value"]))
        append_rows(sample_parquet)

        session.enable_hyperspace()
        self.enable_hybrid(session, ratio=1e-9)  # appended bytes exceed this
        q = df.filter(col("key") == 42).select("key", "value")
        assert not index_used(session.optimized_plan(q))

    def test_hybrid_scan_not_used_for_deletes(self, session, hs, sample_parquet):
        import pathlib

        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("h3", ["key"], ["value"]))
        pathlib.Path(sample_parquet, "part-1.parquet").unlink()

        session.enable_hyperspace()
        self.enable_hybrid(session)
        q = df.filter(col("key") == 42).select("key", "value")
        assert not index_used(session.optimized_plan(q))

    def test_hybrid_point_lookup_prunes_buckets(self, session, hs, sample_parquet):
        """The union's index input still bucket-prunes on point predicates."""
        df = session.parquet(sample_parquet)
        hs.create_index(df, IndexConfig("h4", ["key"], ["value"]))
        append_rows(sample_parquet)
        session.enable_hyperspace()
        self.enable_hybrid(session)
        q = df.filter(col("key") == 7).select("key", "value")
        got = session.to_pandas(q)
        session.disable_hyperspace()
        frames_equal(got, session.to_pandas(q))
