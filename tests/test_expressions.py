"""SQL expression surface: IS NULL, IN, LIKE, BETWEEN, SUBSTRING, date
part extraction — 3-valued null semantics, device lowering via the
translation layer (code-range desugaring over sorted dictionaries, day
ranges for year()), and integration with bucket/range pruning. These are
the Catalyst predicate shapes the reference's rules read for free
(FilterIndexRule.scala:203-215); here the engine owns them."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (
    AggSpec,
    Hyperspace,
    HyperspaceSession,
    IndexConfig,
    col,
    date_lit,
    lit,
    month,
    when,
    year,
)
from hyperspace_tpu.config import FILTER_VENUE


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("exprdata")
    rng = np.random.default_rng(11)
    n = 4_000
    null_q = rng.random(n) < 0.08
    null_m = rng.random(n) < 0.08
    modes = np.array(["AIR", "MAIL", "RAIL", "SHIP", "TRUCK", "FOB"], dtype=object)
    types = np.array(
        ["PROMO BRUSHED", "PROMO POLISHED", "STANDARD BRUSHED", "ECONOMY ANODIZED", "MEDIUM PLATED"],
        dtype=object,
    )
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 300, n).astype(np.int64),
            "qty": pd.array(
                np.where(null_q, 0, rng.integers(1, 50, n)), dtype="Int64"
            ),
            "mode": pd.array(
                np.where(null_m, None, modes[rng.integers(0, len(modes), n)]), dtype=object
            ),
            "ptype": types[rng.integers(0, len(types), n)],
            "phone": [f"{int(c):02d}-555-{int(x):04d}" for c, x in zip(rng.integers(10, 35, n), rng.integers(0, 10000, n))],
            "d": pd.array(
                [pd.Timestamp("1993-01-01") + pd.Timedelta(days=int(x)) for x in rng.integers(0, 1500, n)]
            ).date,
        }
    )
    df.loc[null_q, "qty"] = pd.NA
    root = tmp_path / "t"
    root.mkdir()
    t = pa.Table.from_pandas(df, preserve_index=False)
    t = t.set_column(t.schema.get_field_index("d"), "d", pa.array(df["d"], type=pa.date32()))
    pq.write_table(t, root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=8)
    ds = session.parquet(root)
    return session, ds, df


def run_both_venues(session, q):
    outs = []
    for venue in ("host", "device"):
        session.conf.set(FILTER_VENUE, venue)
        outs.append(session.to_pandas(q))
    a, b = outs
    assert len(a) == len(b)
    pd.testing.assert_frame_equal(
        a.sort_values(list(a.columns)).reset_index(drop=True),
        b.sort_values(list(b.columns)).reset_index(drop=True),
    )
    return a


def test_isin_int_and_string(data):
    session, ds, df = data
    got = run_both_venues(session, ds.filter(col("k").isin([5, 17, 250, 9999])))
    exp = df[df.k.isin([5, 17, 250, 9999])]
    assert len(got) == len(exp)

    got = run_both_venues(session, ds.filter(col("mode").isin(["MAIL", "SHIP", "ZEPPELIN"])))
    exp = df[df["mode"].isin(["MAIL", "SHIP"])]
    assert len(got) == len(exp)
    assert set(got["mode"]) <= {"MAIL", "SHIP"}


def test_not_in_drops_null_rows(data):
    """NOT (x IN (...)) is UNKNOWN for null x — the row is dropped, not
    kept (the 3-valued trap a boolean-logic engine gets wrong)."""
    session, ds, df = data
    got = run_both_venues(session, ds.filter(~col("mode").isin(["MAIL", "SHIP"])))
    exp = df[df["mode"].notna() & ~df["mode"].isin(["MAIL", "SHIP"])]
    assert len(got) == len(exp)
    assert got["mode"].notna().all()


def test_is_null_and_is_not_null(data):
    session, ds, df = data
    got = run_both_venues(session, ds.filter(col("qty").is_null()))
    assert len(got) == int(df.qty.isna().sum())
    assert got["qty"].isna().all()

    got = run_both_venues(session, ds.filter(col("qty").is_not_null() & (col("qty") > 25)))
    exp = df[df.qty.notna() & (df.qty > 25)]
    assert len(got) == len(exp)


@pytest.mark.parametrize(
    "pattern,matcher",
    [
        ("PROMO%", lambda s: s.str.startswith("PROMO")),
        ("%BRUSHED", lambda s: s.str.endswith("BRUSHED")),
        ("%O%", lambda s: s.str.contains("O")),
        ("PROMO B_USHED", lambda s: s == "PROMO BRUSHED"),
        ("STANDARD BRUSHED", lambda s: s == "STANDARD BRUSHED"),
    ],
)
def test_like_patterns(data, pattern, matcher):
    session, ds, df = data
    got = run_both_venues(session, ds.filter(col("ptype").like(pattern)))
    exp = df[matcher(df.ptype)]
    assert len(got) == len(exp), pattern


def test_not_like(data):
    session, ds, df = data
    got = run_both_venues(session, ds.filter(~col("ptype").like("PROMO%")))
    exp = df[~df.ptype.str.startswith("PROMO")]
    assert len(got) == len(exp)


def test_between(data):
    session, ds, df = data
    got = run_both_venues(session, ds.filter(col("k").between(100, 110)))
    exp = df[(df.k >= 100) & (df.k <= 110)]
    assert len(got) == len(exp)


def test_substr_comparisons_and_in(data):
    session, ds, df = data
    got = run_both_venues(session, ds.filter(col("phone").substr(1, 2).isin(["13", "31", "29"])))
    exp = df[df.phone.str[:2].isin(["13", "31", "29"])]
    assert len(got) == len(exp)

    got = run_both_venues(session, ds.filter(col("phone").substr(1, 2) == lit("20")))
    exp = df[df.phone.str[:2] == "20"]
    assert len(got) == len(exp)


def test_year_month_extraction(data):
    session, ds, df = data
    years = pd.to_datetime(df.d).dt.year  # df.d is object of date
    months = pd.to_datetime(df.d).dt.month

    got = run_both_venues(session, ds.filter(year(col("d")) == 1995))
    assert len(got) == int((years == 1995).sum())

    got = run_both_venues(session, ds.filter(year(col("d")) >= 1996))
    assert len(got) == int((years >= 1996).sum())

    # month() is not interval-shaped over days: exercises the host path.
    got = run_both_venues(session, ds.filter(month(col("d")) == 7))
    assert len(got) == int((months == 7).sum())


def test_date_lit_range(data):
    session, ds, df = data
    q = ds.filter((col("d") >= date_lit("1994-06-01")) & (col("d") < date_lit("1994-09-01")))
    got = run_both_venues(session, q)
    dd = pd.to_datetime(df.d)
    exp = df[(dd >= "1994-06-01") & (dd < "1994-09-01")]
    assert len(got) == len(exp)


def test_like_in_case_when_aggregate(data):
    """The TPC-H Q14 shape: a LIKE inside a conditional aggregate."""
    session, ds, df = data
    q = ds.aggregate(
        [],
        [
            AggSpec.of(
                "sum",
                when(col("ptype").like("PROMO%"), col("k")).otherwise(lit(0)),
                "promo",
            ),
            AggSpec.of("sum", "k", "total"),
        ],
    )
    got = session.to_pandas(q)
    exp_promo = int(df.k[df.ptype.str.startswith("PROMO")].sum())
    assert int(got.loc[0, "promo"]) == exp_promo
    assert int(got.loc[0, "total"]) == int(df.k.sum())


@pytest.fixture()
def indexed(tmp_path):
    rng = np.random.default_rng(3)
    n = 20_000
    df = pd.DataFrame(
        {
            "store": [f"s{int(i):03d}" for i in rng.integers(0, 64, n)],
            "v": rng.normal(size=n),
        }
    )
    root = tmp_path / "pts"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=16)
    hs = Hyperspace(session)
    ds = session.parquet(root)
    hs.create_index(ds, IndexConfig("store_ix", ["store"], ["v"]))
    session.enable_hyperspace()
    return session, ds, df


def test_in_multi_point_bucket_pruning(indexed):
    """IN on the bucket column prunes to the owning buckets' files only
    (multi-point analog of the point-lookup prune)."""
    session, ds, df = indexed
    vals = ["s001", "s017", "s040"]
    got = session.to_pandas(ds.filter(col("store").isin(vals)))
    exp = df[df.store.isin(vals)]
    assert len(got) == len(exp)
    st = session.last_query_stats
    assert st["files_pruned"] > 0
    assert st["files_read"] <= len(vals)
    plan = session.last_physical_plan
    assert "IndexPointLookup" in repr(plan)


def test_like_prefix_range_pruning(tmp_path):
    """A prefix LIKE on the leading indexed column feeds the manifest
    min/max stats as a [prefix, next-prefix) string range: out-of-range
    prefixes prune every file (hash buckets all span the in-range keys);
    in-range prefixes stay exact through the mask."""
    rng = np.random.default_rng(4)
    n = 20_000
    # First letters A..M only — 'Q%' is beyond every bucket's max.
    df = pd.DataFrame(
        {
            "name": np.array(
                [f"{chr(65 + int(i) % 13)}x{int(j):05d}" for i, j in zip(rng.integers(0, 13, n), rng.integers(0, 99999, n))],
                dtype=object,
            ),
            "v": rng.normal(size=n),
        }
    )
    root = tmp_path / "pref"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=8)
    hs = Hyperspace(session)
    ds = session.parquet(root)
    hs.create_index(ds, IndexConfig("name_ix", ["name"], ["v"]))
    session.enable_hyperspace()

    got = session.to_pandas(ds.filter(col("name").like("Q%")))
    assert len(got) == 0
    assert session.last_query_stats["files_pruned"] == 8
    assert session.last_query_stats["files_read"] == 0

    got = session.to_pandas(ds.filter(col("name").like("Dx%")))
    exp = df[df.name.str.startswith("Dx")]
    assert len(got) == len(exp)


def test_expr_json_roundtrip_in_plan(data):
    import json

    from hyperspace_tpu.plan.nodes import plan_from_json

    _, ds, _ = data
    q = ds.filter(
        col("mode").isin(["MAIL", "SHIP"])
        & col("ptype").like("PROMO%")
        & col("qty").is_not_null()
        & (year(col("d")) == 1995)
        & col("phone").substr(1, 2).isin(["13"])
    )
    j = json.dumps(q.to_json())
    assert plan_from_json(json.loads(j)).to_json() == q.to_json()


def test_in_rejects_empty_and_null(data):
    with pytest.raises(ValueError):
        col("k").isin([])
    with pytest.raises(ValueError):
        col("k").isin([1, None])


def test_year_comparison_feeds_range_pruning(tmp_path):
    """year(d) == Y must prune like the equivalent explicit day range
    (the DatePart conjunct feeds key_bounds through the same day-range
    translation the filter lowering uses)."""
    rng = np.random.default_rng(9)
    n = 50_000
    df = pd.DataFrame(
        {
            "d": (8035 + rng.integers(0, 2525, n)).astype(np.int32),
            "v": rng.normal(size=n),
        }
    )
    root = tmp_path / "dsrc"
    root.mkdir()
    t = pa.table({"d": pa.array(df.d.values, type=pa.date32()), "v": df.v.values})
    pq.write_table(t, root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=8)
    hs = Hyperspace(session)
    ds = session.parquet(root)
    hs.create_index(ds, IndexConfig("d_ix", ["d"], ["v"]))
    session.enable_hyperspace()

    got = session.to_pandas(ds.filter(year(col("d")) == 1997))
    yrs = (pd.Timestamp("1970-01-01") + pd.to_timedelta(df.d, unit="D")).dt.year
    assert len(got) == int((yrs == 1997).sum())
    assert session.last_query_stats["rows_pruned"] > 0

    got = session.to_pandas(ds.filter(year(col("d")) > 2000))
    assert len(got) == 0
    assert session.last_query_stats["files_pruned"] == 8


def test_scattered_like_over_large_dictionary(tmp_path):
    """NOT LIKE over a near-unique string column (TPC-H Q13's o_comment
    shape): thousands of scattered match runs must neither overflow the
    recursive walkers nor mis-evaluate — the translation switches to a
    dictionary lookup table."""
    rng = np.random.default_rng(5)
    n = 20_000
    body = np.array([f"word{int(i):06d} text" for i in rng.integers(0, 10**6, n)], dtype=object)
    special = rng.random(n) < 0.01
    vals = np.where(special, "the special handling of requests", body).astype(object)
    df = pd.DataFrame({"c": vals, "v": np.arange(n, dtype=np.int64)})
    root = tmp_path / "lut"
    root.mkdir()
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), root / "p.parquet")
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=4)
    ds = session.parquet(root)

    got = run_both_venues(session, ds.filter(~col("c").like("%special%requests%")))
    exp = df[~df.c.str.contains("special.*requests")]
    assert len(got) == len(exp)

    # Scattered positive match: every comment ending in '1 text'.
    got = run_both_venues(session, ds.filter(col("c").like("%1 text")))
    exp = df[df.c.str.endswith("1 text")]
    assert len(got) == len(exp)


def test_mathfn_roundtrip_and_eval():
    import numpy as np

    from hyperspace_tpu import abs_, col, floor, sqrt
    from hyperspace_tpu.plan.expr import evaluate, expr_from_json

    e = sqrt((col("x") * col("x") - col("x")) / (col("n") - 1))
    assert expr_from_json(e.to_json()).to_json() == e.to_json()
    vals = {"x": np.array([3.0, 5.0]), "n": np.array([3.0, 2.0])}
    out = evaluate(e, lambda n: vals[n], np)
    np.testing.assert_allclose(out, np.sqrt([(9 - 3) / 2, (25 - 5) / 1]))
    assert evaluate(floor(col("x") / 2), lambda n: vals[n], np).dtype == np.int64
    np.testing.assert_array_equal(
        evaluate(abs_(col("x") - 4), lambda n: vals[n], np), [1.0, 1.0]
    )
