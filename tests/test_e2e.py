"""End-to-end contract tests.

Analog of index/E2EHyperspaceRulesTests.scala: write sample parquet, create
indexes, then for each query shape assert (a) the optimized plan scans the
index location and (b) results with hyperspace enabled are row-identical to
disabled (verifyIndexUsage, E2EHyperspaceRulesTests.scala:324-340).
"""

import numpy as np
import pandas as pd
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
from hyperspace_tpu.plan.nodes import Scan


@pytest.fixture
def session(tmp_system_path):
    return HyperspaceSession(system_path=tmp_system_path, num_buckets=8)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def frames_equal(a: pd.DataFrame, b: pd.DataFrame):
    """Row-identical regardless of order."""
    assert sorted(a.columns) == sorted(b.columns)
    cols = sorted(a.columns)
    a2 = a[cols].sort_values(cols).reset_index(drop=True)
    b2 = b[cols].sort_values(cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(a2, b2, check_dtype=False)


def index_used(plan) -> bool:
    return any(s.bucket_spec is not None for s in plan.leaves())


def test_filter_query_uses_index_and_matches(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("fidx", ["key"], ["value", "id"]))

    query = df.filter(col("key") == 42).select("key", "value")

    session.disable_hyperspace()
    expected = session.to_pandas(query)
    assert not index_used(session.optimized_plan(query))

    session.enable_hyperspace()
    opt = session.optimized_plan(query)
    assert index_used(opt), "filter rewrite did not engage"
    got = session.to_pandas(query)
    frames_equal(got, expected)


def test_filter_range_and_string_queries(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("fidx2", ["name"], ["key"]))
    session.enable_hyperspace()

    q = df.filter((col("name") == "name_7") | (col("name") > "name_30")).select("name", "key")
    opt = session.optimized_plan(q)
    assert index_used(opt)
    got = session.to_pandas(q)
    session.disable_hyperspace()
    frames_equal(got, session.to_pandas(q))


def test_filter_not_rewritten_when_not_covering(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("smallidx", ["key"]))  # covers only 'key'
    session.enable_hyperspace()
    q = df.filter(col("key") == 1).select("key", "value")  # needs 'value' too
    assert not index_used(session.optimized_plan(q))


def test_filter_requires_first_indexed_column(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("kv", ["key", "id"], ["value"]))
    session.enable_hyperspace()
    # Filter on 'id' (second indexed col) only: rule must not engage.
    q = df.filter(col("id") == 5).select("id", "value")
    assert not index_used(session.optimized_plan(q))
    # Filter on 'key' (first indexed col): engages.
    q2 = df.filter(col("key") == 5).select("key", "value")
    assert index_used(session.optimized_plan(q2))


def test_join_query_zero_exchange(session, hs, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(3)
    n1, n2 = 800, 600
    left_root = tmp_path / "left"
    right_root = tmp_path / "right"
    left_root.mkdir()
    right_root.mkdir()
    pq.write_table(
        pa.table({"k": rng.integers(0, 200, n1).astype(np.int64), "lv": rng.standard_normal(n1)}),
        left_root / "l.parquet",
    )
    pq.write_table(
        pa.table({"k": rng.integers(0, 200, n2).astype(np.int64), "rv": rng.standard_normal(n2)}),
        right_root / "r.parquet",
    )
    ldf = session.parquet(left_root)
    rdf = session.parquet(right_root)
    hs.create_index(ldf, IndexConfig("jl", ["k"], ["lv"]))
    hs.create_index(rdf, IndexConfig("jr", ["k"], ["rv"]))

    q = ldf.join(rdf, ["k"])

    session.disable_hyperspace()
    expected = session.to_pandas(q)

    session.enable_hyperspace()
    opt = session.optimized_plan(q)
    scans = [s for s in opt.leaves() if s.bucket_spec is not None]
    assert len(scans) == 2, "join rewrite must replace both sides"
    assert scans[0].bucket_spec[0] == scans[1].bucket_spec[0]
    got = session.to_pandas(q)
    frames_equal(got, expected)


def test_enable_disable_toggling(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("tidx", ["key"], ["value"]))
    q = df.filter(col("key") == 7).select("key", "value")
    assert not index_used(session.optimized_plan(q))
    session.enable_hyperspace()
    assert index_used(session.optimized_plan(q))
    session.disable_hyperspace()
    assert not index_used(session.optimized_plan(q))


def test_stale_index_not_used_until_refresh(session, hs, sample_parquet):
    import pyarrow as pa
    import pyarrow.parquet as pq
    from pathlib import Path

    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("sidx", ["key"], ["value"]))
    session.enable_hyperspace()
    q = df.filter(col("key") == 3).select("key", "value")
    assert index_used(session.optimized_plan(q))

    # Append data: signature mismatch ⇒ rule must stop engaging.
    pq.write_table(
        pa.table(
            {
                "id": np.arange(4, dtype=np.int64),
                "key": np.array([3, 3, 3, 3], dtype=np.int64),
                "value": np.ones(4),
                "name": pa.array(["x"] * 4),
            }
        ),
        Path(sample_parquet) / "extra.parquet",
    )
    session.manager.clear_cache()
    assert not index_used(session.optimized_plan(q))

    # Refresh rebuilds from lineage; rule engages again and sees new rows.
    hs.refresh_index("sidx")
    opt = session.optimized_plan(q)
    assert index_used(opt)
    got = session.to_pandas(q)
    session.disable_hyperspace()
    expected = session.to_pandas(q)
    frames_equal(got, expected)
    assert (got.key == 3).sum() >= 4


def test_lifecycle_via_facade(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("lidx", ["key"], ["value"]))
    assert hs.indexes().iloc[0]["state"] == "ACTIVE"
    hs.delete_index("lidx")
    assert hs.indexes().iloc[0]["state"] == "DELETED"
    session.enable_hyperspace()
    q = df.filter(col("key") == 1).select("key", "value")
    assert not index_used(session.optimized_plan(q)), "DELETED index must not be used"
    hs.restore_index("lidx")
    assert index_used(session.optimized_plan(q))
    hs.delete_index("lidx")
    hs.vacuum_index("lidx")
    assert hs.indexes().iloc[0]["state"] == "DOESNOTEXIST"


def test_optimize_index_compaction(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("oidx", ["key"], ["value"]))
    hs.optimize_index("oidx")
    entry = session.manager.get_indexes()[0]
    assert entry.content.directories == ["v__=1"]
    session.enable_hyperspace()
    q = df.filter(col("key") == 11).select("key", "value")
    got = session.to_pandas(q)
    session.disable_hyperspace()
    frames_equal(got, session.to_pandas(q))


def test_explain_output(session, hs, sample_parquet):
    df = session.parquet(sample_parquet)
    hs.create_index(df, IndexConfig("eidx", ["key"], ["value"]))
    q = df.filter(col("key") == 5).select("key", "value")
    text = hs.explain(q, verbose=True)
    assert "eidx" in text
    assert "IndexScan" in text
    assert "ShuffleExchange-equivalents eliminated: 1" in text
    # explain must not leave the session toggled on
    assert not session.is_hyperspace_enabled()


def test_limit_early_out_stops_scanning(tmp_path):
    """LIMIT over an unordered multi-file scan stops reading once n rows
    survive instead of materializing the whole table."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import HyperspaceSession, col

    root = tmp_path / "many"
    root.mkdir()
    for i in range(10):
        pq.write_table(
            pa.table({"k": np.arange(i * 100, (i + 1) * 100, dtype=np.int64)}),
            root / f"part-{i}.parquet",
        )
    session = HyperspaceSession(system_path=str(tmp_path / "idx"), num_buckets=2)
    ds = session.parquet(root)

    out = session.to_pandas(ds.limit(5))
    assert len(out) == 5
    plan = repr(session.last_physical_plan)
    assert "LimitEarlyOut" in plan
    assert "'files_scanned': 1" in plan, plan

    # With a filter that only later files satisfy, scanning continues
    # exactly until enough rows survive.
    out = session.to_pandas(ds.filter(col("k") >= 750).limit(5))
    assert len(out) == 5
    assert (out.k >= 750).all()
    plan = repr(session.last_physical_plan)
    assert "'files_scanned': 8" in plan, plan

    # Fewer matches than n: every file scanned, all matches returned.
    out = session.to_pandas(ds.filter(col("k") >= 997).limit(10))
    assert len(out) == 3
    assert "'files_total': 10" in repr(session.last_physical_plan)
