"""Test harness configuration.

The analog of the reference's `local[4]` SparkSession
(SparkInvolvedSuite.scala:99-119): multi-device is simulated with 8 virtual
CPU devices via XLA_FLAGS, set before jax is first imported. Tests must not
assume real TPU hardware.
"""

import os

# XLA:CPU compiles on the calling thread; LLVM's recursive passes can
# overflow the default 8 MB main-thread stack on the largest fused
# programs (observed as a SIGSEGV inside backend_compile deep into the
# suite). The hard limit is unlimited here — raise the soft limit so the
# main thread's stack can grow past 8 MB.
try:
    import resource

    _soft, _hard = resource.getrlimit(resource.RLIMIT_STACK)
    if _hard in (resource.RLIM_INFINITY, -1) or (_hard > _soft >= 0):
        resource.setrlimit(resource.RLIMIT_STACK, (_hard, _hard))
except (ImportError, ValueError, OSError):
    pass

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The deployment's sitecustomize imports jax at interpreter startup with the
# TPU plugin selected, so the env var alone is too late — override via config.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def _map_count() -> int:
    """Memory mappings of this process (Linux); 0 where unreadable."""
    try:
        with open("/proc/self/maps", "rb") as f:
            return sum(1 for _ in f)
    except OSError:
        return 0


@pytest.fixture(autouse=True)
def _jit_map_guard():
    """Keep the process under vm.max_map_count (default 65530).

    Every XLA:CPU executable pins LLVM-JIT'd code/rodata/data mappings
    for the life of the jit cache; a full-suite run compiles enough
    programs (~18k live sections near the end) to exhaust the kernel's
    mapping limit, after which mmap fails inside LLVM and the compiler
    SIGSEGVs. Dropping jax's caches releases the executables; the
    occasional recompile is far cheaper than a dead process."""
    yield
    if _map_count() > 40_000:
        jax.clear_caches()


@pytest.fixture(autouse=True)
def _obs_reset():
    """Observability isolation: counters/metrics and the tracer's
    process-global state (last trace, sink path) are zeroed before each
    test, so cross-test counter drift can't leak into assertions and a
    test that configures a sink can't make a later test write to it."""
    from hyperspace_tpu import stats
    from hyperspace_tpu.obs import events, journal, metrics, runtime, slo, trace

    stats.reset()
    metrics.REGISTRY.reset()
    trace.reset()
    trace.set_enabled(True)
    events.reset()
    slo.reset()
    runtime.reset()
    journal.reset()
    yield
    journal.reset()


@pytest.fixture
def tmp_system_path(tmp_path):
    """Per-test index system path isolation (analog of HyperspaceSuite's
    systemPath handling, HyperspaceSuite.scala:25-75)."""
    p = tmp_path / "indexes"
    p.mkdir(parents=True, exist_ok=True)
    return str(p)


@pytest.fixture
def sample_parquet(tmp_path):
    """Small deterministic sample dataset (analog of SampleData.scala:141-153)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    n = 1000
    table = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "key": pa.array(rng.integers(0, 100, size=n, dtype=np.int64)),
            "value": pa.array(rng.standard_normal(n).astype(np.float64)),
            "name": pa.array([f"name_{i % 37}" for i in range(n)]),
        }
    )
    root = tmp_path / "sample_data"
    root.mkdir()
    # Two files so signatures cover multi-file listing.
    pq.write_table(table.slice(0, n // 2), root / "part-0.parquet")
    pq.write_table(table.slice(n // 2), root / "part-1.parquet")
    return str(root)
