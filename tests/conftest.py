"""Test harness configuration.

The analog of the reference's `local[4]` SparkSession
(SparkInvolvedSuite.scala:99-119): multi-device is simulated with 8 virtual
CPU devices via XLA_FLAGS, set before jax is first imported. Tests must not
assume real TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The deployment's sitecustomize imports jax at interpreter startup with the
# TPU plugin selected, so the env var alone is too late — override via config.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def tmp_system_path(tmp_path):
    """Per-test index system path isolation (analog of HyperspaceSuite's
    systemPath handling, HyperspaceSuite.scala:25-75)."""
    p = tmp_path / "indexes"
    p.mkdir(parents=True, exist_ok=True)
    return str(p)


@pytest.fixture
def sample_parquet(tmp_path):
    """Small deterministic sample dataset (analog of SampleData.scala:141-153)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    n = 1000
    table = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "key": pa.array(rng.integers(0, 100, size=n, dtype=np.int64)),
            "value": pa.array(rng.standard_normal(n).astype(np.float64)),
            "name": pa.array([f"name_{i % 37}" for i in range(n)]),
        }
    )
    root = tmp_path / "sample_data"
    root.mkdir()
    # Two files so signatures cover multi-file listing.
    pq.write_table(table.slice(0, n // 2), root / "part-0.parquet")
    pq.write_table(table.slice(n // 2), root / "part-1.parquet")
    return str(root)
