"""Benchmark: TPC-H-style lineitem point-lookup, indexed vs un-indexed.

The BASELINE.json config 1 analog ("TPC-H SF1 lineitem single-column
CoveringIndex + FilterIndexRule point-lookup"): generate a lineitem-like
table, build a covering index on the lookup key, then time point-lookup
queries with hyperspace enabled (bucket-pruned sorted index scan) vs
disabled (full scan + device filter). Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline normalizes against the driver's ≥5× query-speedup target
(BASELINE.md). Auxiliary numbers (build GB/s/chip) go to stderr.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def main():
    import pyarrow as pa
    import pyarrow.parquet as pq

    import jax

    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col

    devices = jax.devices()
    log(f"devices: {devices}")

    tmp = Path(tempfile.mkdtemp(prefix="hs_bench_"))
    try:
        # ---- data: lineitem-ish, ~2M rows ------------------------------
        n = 2_000_000
        rng = np.random.default_rng(42)
        orderkey = rng.integers(0, n // 4, n).astype(np.int64)
        table = pa.table(
            {
                "l_orderkey": orderkey,
                "l_partkey": rng.integers(0, 200_000, n).astype(np.int64),
                "l_quantity": rng.integers(1, 51, n).astype(np.int64),
                "l_extendedprice": (rng.random(n) * 100_000).astype(np.float64),
                "l_discount": (rng.random(n) * 0.1).astype(np.float64),
            }
        )
        data_root = tmp / "lineitem"
        data_root.mkdir()
        pq.write_table(table, data_root / "part-0.parquet")
        input_bytes = table.nbytes
        log(f"rows={n} input={input_bytes/1e9:.3f} GB")

        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=64)
        hs = Hyperspace(session)
        df = session.parquet(data_root)

        # ---- index build (report GB/s/chip to stderr) ------------------
        t0 = time.perf_counter()
        hs.create_index(
            df,
            IndexConfig(
                "lineitem_orderkey",
                ["l_orderkey"],
                ["l_partkey", "l_quantity", "l_extendedprice", "l_discount"],
            ),
        )
        build_s = time.perf_counter() - t0
        gbps = input_bytes / 1e9 / build_s
        log(f"index build: {build_s:.2f}s -> {gbps:.3f} GB/s/chip")

        # ---- point lookups ---------------------------------------------
        keys = rng.integers(0, n // 4, 12).astype(np.int64)

        def run_lookups():
            total = 0
            for k in keys:
                q = df.filter(col("l_orderkey") == int(k)).select(
                    "l_orderkey", "l_partkey", "l_extendedprice"
                )
                total += len(session.run(q).columns["l_orderkey"])
            return total

        session.enable_hyperspace()
        run_lookups()  # warmup (compile)
        t0 = time.perf_counter()
        rows_idx = run_lookups()
        t_indexed = time.perf_counter() - t0

        session.disable_hyperspace()
        run_lookups()  # warmup
        t0 = time.perf_counter()
        rows_no = run_lookups()
        t_noindex = time.perf_counter() - t0

        assert rows_idx == rows_no, f"result mismatch: {rows_idx} vs {rows_no}"
        speedup = t_noindex / t_indexed
        log(f"indexed: {t_indexed:.3f}s  no-index: {t_noindex:.3f}s  speedup: {speedup:.2f}x")

        print(
            json.dumps(
                {
                    "metric": "tpch_sf1_point_lookup_speedup",
                    "value": round(speedup, 3),
                    "unit": "x",
                    "vs_baseline": round(speedup / 5.0, 3),
                }
            )
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
