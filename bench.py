"""Benchmark: TPC-H SF1 lineitem point-lookup, indexed vs un-indexed.

The BASELINE.json config 1 analog ("TPC-H SF1 lineitem single-column
CoveringIndex + FilterIndexRule point-lookup") on the REAL SF1 scale:
6,001,215-row lineitem with the full 16-column TPC-H schema (strings,
dates, decimals), generated deterministically and cached under the system
tmp dir. Builds a covering index on l_orderkey, then times point-lookup
queries with hyperspace enabled (bucket-pruned sorted index scan) vs
disabled (full scan + device filter). Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline normalizes against the driver's ≥5× query-speedup target
(BASELINE.md). Auxiliary numbers (build GB/s/chip at two scales — the
throughput curve) go to stderr.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np


def log(*args):
    print(*args, file=sys.stderr, flush=True)


INDEXED = ["l_orderkey"]
INCLUDED = ["l_partkey", "l_quantity", "l_extendedprice", "l_discount"]


def build_once(session_path: Path, data_root: Path, num_buckets: int):
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.dataset import list_data_files

    session = HyperspaceSession(system_path=str(session_path), num_buckets=num_buckets)
    hs = Hyperspace(session)
    df = session.parquet(data_root)
    files = [fi.path for fi in list_data_files(data_root)]
    sel_bytes = hio.estimate_uncompressed_bytes(files, INDEXED + INCLUDED)
    t0 = time.perf_counter()
    hs.create_index(df, IndexConfig("lineitem_orderkey", INDEXED, INCLUDED))
    build_s = time.perf_counter() - t0
    phases = session.last_build_stats.get("phases_s")
    if phases:
        log(f"  build phases (s): {phases}")
    return session, hs, df, sel_bytes, build_s


def main():
    import jax

    from hyperspace_tpu import col
    from benchmarks.datagen import cached_tpch, gen_tpch_lineitem, TPCH_SF1_ORDERS_ROWS

    devices = jax.devices()
    log(f"devices: {devices}")

    li_root, _orders_root = cached_tpch(sf=1.0)
    tmp = Path(tempfile.mkdtemp(prefix="hs_bench_"))
    try:
        # ---- GB/s curve point at SF0.1 (amortization evidence) ---------
        small = tmp / "li_small"
        gen_tpch_lineitem(small, sf=0.1)
        _, _, _, sb, bs = build_once(tmp / "idx_small", small, 64)
        log(f"build sf=0.1: {bs:.2f}s -> {sb/1e9/bs:.3f} GB/s/chip (selected cols)")

        # ---- SF1 build --------------------------------------------------
        session, hs, df, sel_bytes, build_s = build_once(tmp / "indexes", li_root, 200)
        gbps = sel_bytes / 1e9 / build_s
        log(f"build sf=1:   {build_s:.2f}s -> {gbps:.3f} GB/s/chip (selected cols, ~6.0M rows)")

        # ---- point lookups ---------------------------------------------
        rng = np.random.default_rng(7)
        keys = rng.integers(0, TPCH_SF1_ORDERS_ROWS, 12).astype(np.int64)

        def run_lookups():
            total = 0
            for k in keys:
                q = df.filter(col("l_orderkey") == int(k)).select(
                    "l_orderkey", "l_partkey", "l_extendedprice"
                )
                total += len(session.run(q).columns["l_orderkey"])
            return total

        session.enable_hyperspace()
        run_lookups()  # warmup (compile)
        t0 = time.perf_counter()
        rows_idx = run_lookups()
        t_indexed = time.perf_counter() - t0

        session.disable_hyperspace()
        run_lookups()  # warmup
        t0 = time.perf_counter()
        rows_no = run_lookups()
        t_noindex = time.perf_counter() - t0

        assert rows_idx == rows_no, f"result mismatch: {rows_idx} vs {rows_no}"
        assert rows_idx > 0, "lookups matched nothing"
        speedup = t_noindex / t_indexed
        log(f"indexed: {t_indexed:.3f}s  no-index: {t_noindex:.3f}s  speedup: {speedup:.2f}x")

        # Real per-query profiles (docs/observability.md): one
        # representative lookup per mode, written alongside the headline
        # metric so the perf trajectory carries measured operator
        # evidence (wall per operator, files/bytes, cache outcomes)
        # rather than a single number.
        q = df.filter(col("l_orderkey") == int(keys[0])).select(
            "l_orderkey", "l_partkey", "l_extendedprice"
        )
        session.enable_hyperspace()
        session.run(q)
        profile_indexed = session.last_profile().to_json()
        session.disable_hyperspace()
        session.run(q)
        profile_noindex = session.last_profile().to_json()

        headline = {
            "metric": "tpch_sf1_point_lookup_speedup",
            "value": round(speedup, 3),
            "unit": "x",
            "vs_baseline": round(speedup / 5.0, 3),
        }
        Path("BENCH_PROFILES.json").write_text(
            json.dumps(
                {
                    **headline,
                    "indexed_s": round(t_indexed, 4),
                    "no_index_s": round(t_noindex, 4),
                    "profiles": {
                        "point_lookup_indexed": profile_indexed,
                        "point_lookup_no_index": profile_noindex,
                    },
                },
                indent=1,
                default=str,
            )
        )
        log("wrote BENCH_PROFILES.json (per-operator profiles, both modes)")

        print(json.dumps(headline))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
