"""Benchmark: TPC-H SF1 lineitem point-lookup, indexed vs un-indexed.

The BASELINE.json config 1 analog ("TPC-H SF1 lineitem single-column
CoveringIndex + FilterIndexRule point-lookup") on the REAL SF1 scale:
6,001,215-row lineitem with the full 16-column TPC-H schema (strings,
dates, decimals), generated deterministically and cached under the system
tmp dir. Builds a covering index on l_orderkey, then times point-lookup
queries with hyperspace enabled (bucket-pruned sorted index scan) vs
disabled (full scan + device filter). Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline normalizes against the driver's ≥5× query-speedup target
(BASELINE.md). Auxiliary numbers (build GB/s/chip at two scales — the
throughput curve) go to stderr.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np


def log(*args):
    print(*args, file=sys.stderr, flush=True)


INDEXED = ["l_orderkey"]
INCLUDED = ["l_partkey", "l_quantity", "l_extendedprice", "l_discount"]


def build_once(session_path: Path, data_root: Path, num_buckets: int):
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.dataset import list_data_files

    session = HyperspaceSession(system_path=str(session_path), num_buckets=num_buckets)
    hs = Hyperspace(session)
    df = session.parquet(data_root)
    files = [fi.path for fi in list_data_files(data_root)]
    sel_bytes = hio.estimate_uncompressed_bytes(files, INDEXED + INCLUDED)
    t0 = time.perf_counter()
    hs.create_index(df, IndexConfig("lineitem_orderkey", INDEXED, INCLUDED))
    build_s = time.perf_counter() - t0
    phases = session.last_build_stats.get("phases_s")
    if phases:
        log(f"  build phases (s): {phases}")
    return session, hs, df, sel_bytes, build_s


def main():
    import jax

    from hyperspace_tpu import col
    from benchmarks.datagen import cached_tpch, gen_tpch_lineitem, TPCH_SF1_ORDERS_ROWS

    devices = jax.devices()
    log(f"devices: {devices}")

    li_root, _orders_root = cached_tpch(sf=1.0)
    tmp = Path(tempfile.mkdtemp(prefix="hs_bench_"))
    try:
        # ---- GB/s curve point at SF0.1 (amortization evidence) ---------
        small = tmp / "li_small"
        gen_tpch_lineitem(small, sf=0.1)
        _, _, _, sb, bs = build_once(tmp / "idx_small", small, 64)
        log(f"build sf=0.1: {bs:.2f}s -> {sb/1e9/bs:.3f} GB/s/chip (selected cols)")

        # ---- SF1 build --------------------------------------------------
        session, hs, df, sel_bytes, build_s = build_once(tmp / "indexes", li_root, 200)
        gbps = sel_bytes / 1e9 / build_s
        log(f"build sf=1:   {build_s:.2f}s -> {gbps:.3f} GB/s/chip (selected cols, ~6.0M rows)")

        # ---- point lookups ---------------------------------------------
        rng = np.random.default_rng(7)
        keys = rng.integers(0, TPCH_SF1_ORDERS_ROWS, 12).astype(np.int64)

        def run_lookups():
            total = 0
            for k in keys:
                q = df.filter(col("l_orderkey") == int(k)).select(
                    "l_orderkey", "l_partkey", "l_extendedprice"
                )
                total += len(session.run(q).columns["l_orderkey"])
            return total

        session.enable_hyperspace()
        run_lookups()  # warmup (compile)
        t0 = time.perf_counter()
        rows_idx = run_lookups()
        t_indexed = time.perf_counter() - t0

        session.disable_hyperspace()
        run_lookups()  # warmup
        t0 = time.perf_counter()
        rows_no = run_lookups()
        t_noindex = time.perf_counter() - t0

        assert rows_idx == rows_no, f"result mismatch: {rows_idx} vs {rows_no}"
        assert rows_idx > 0, "lookups matched nothing"
        speedup = t_noindex / t_indexed
        log(f"indexed: {t_indexed:.3f}s  no-index: {t_noindex:.3f}s  speedup: {speedup:.2f}x")

        # Real per-query profiles (docs/observability.md): one
        # representative lookup per mode, written alongside the headline
        # metric so the perf trajectory carries measured operator
        # evidence (wall per operator, files/bytes, cache outcomes)
        # rather than a single number.
        q = df.filter(col("l_orderkey") == int(keys[0])).select(
            "l_orderkey", "l_partkey", "l_extendedprice"
        )
        session.enable_hyperspace()
        session.run(q)
        profile_indexed = session.last_profile().to_json()
        session.disable_hyperspace()
        session.run(q)
        profile_noindex = session.last_profile().to_json()

        headline = {
            "metric": "tpch_sf1_point_lookup_speedup",
            "value": round(speedup, 3),
            "unit": "x",
            "vs_baseline": round(speedup / 5.0, 3),
        }
        Path("BENCH_PROFILES.json").write_text(
            json.dumps(
                {
                    **headline,
                    "indexed_s": round(t_indexed, 4),
                    "no_index_s": round(t_noindex, 4),
                    "profiles": {
                        "point_lookup_indexed": profile_indexed,
                        "point_lookup_no_index": profile_noindex,
                    },
                },
                indent=1,
                default=str,
            )
        )
        log("wrote BENCH_PROFILES.json (per-operator profiles, both modes)")

        print(json.dumps(headline))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def smoke(out_path: str = "BENCH_PIPELINE.json") -> int:
    """Build-pipeline smoke (the CI `build-pipeline` job): build a small
    synthetic table through the streaming path twice — serial
    (`pipeline_enabled=False`, the phase-accounting reference) and
    pipelined — assert the index is byte-for-byte identical, and gate
    the pipelined wall against 0.9 x (p1 + p2) of the serial run.

    The wall gate only binds on hosts with >= 2 schedulable CPUs: on a
    single CPU every stage timeshares one core, both paths saturate it,
    and wall ratios measure the box, not the pipeline — there the
    overlap evidence is the recorded per-stage busy sum vs the p2 wall
    (overlap_factor > 1 means stages genuinely ran concurrently)."""
    import os

    from hyperspace_tpu import native
    from hyperspace_tpu.dataset import Dataset
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.execution.builder import DeviceIndexBuilder
    from hyperspace_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(11)
    num_buckets = 32
    n, files = 600_000, 3
    tmp = Path(tempfile.mkdtemp(prefix="hs_pipe_"))
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        root = tmp / "src"
        root.mkdir()
        per = n // files
        for i in range(files):
            k = rng.integers(0, 10**9, per).astype(np.int64)
            pq.write_table(
                pa.table(
                    {
                        "k": k,
                        "s": pa.array([f"s{j % 37:02d}" for j in range(per)]),
                        "v": rng.standard_normal(per),
                    }
                ),
                root / f"p{i}.parquet",
                row_group_size=20_000,
            )
        ds = Dataset.parquet(root)
        mesh = make_mesh()
        # Pin the host sort venue when the native kernel is available so
        # the run is deterministic across probe outcomes (identical
        # permutations either venue — the comparison is venue-neutral).
        venue = "host" if native.available() else "auto"
        kw = dict(
            mesh=mesh, memory_budget_bytes=400_000, chunk_bytes=600_000, venue=venue
        )

        # Best-of-2 per path: shared-runner noise easily exceeds the
        # margin under test; the min is the honest "what the code costs"
        # number for both sides of the ratio.
        serial = DeviceIndexBuilder(pipeline_enabled=False, **kw)
        d_serial = tmp / "idx_serial" / "v__=0"
        serial_wall, phases = None, None
        for _ in range(2):
            t0 = time.perf_counter()
            serial.write(ds.scan(), ["k", "s", "v"], ["k"], num_buckets, d_serial)
            w = time.perf_counter() - t0
            if serial_wall is None or w < serial_wall:
                serial_wall, phases = w, serial.last_build_stats["phases_s"]
        p1, p2 = phases["p1_decode_hash_spill"], phases["p2_sort_encode_write"]
        assert serial.last_build_stats["path"] == "streaming"

        pipe = DeviceIndexBuilder(pipeline_enabled=True, **kw)
        d_pipe = tmp / "idx_pipe" / "v__=0"
        pipe_wall, pipe_stats = None, None
        for _ in range(2):
            t0 = time.perf_counter()
            pipe.write(ds.scan(), ["k", "s", "v"], ["k"], num_buckets, d_pipe)
            w = time.perf_counter() - t0
            if pipe_wall is None or w < pipe_wall:
                pipe_wall, pipe_stats = w, dict(pipe.last_build_stats)
        pinfo = pipe_stats.get("pipeline", {})

        identical = hio.read_manifest(d_serial) == hio.read_manifest(d_pipe) and all(
            (d_serial / hio.bucket_file_name(b)).read_bytes()
            == (d_pipe / hio.bucket_file_name(b)).read_bytes()
            for b in range(num_buckets)
        )
        assert identical, "pipelined index differs from the serial reference"

        busy = pinfo.get("stage_busy_s", {})
        p2_pipe = pipe_stats["phases_s"]["p2_sort_encode_write"]
        overlap_factor = round(sum(busy.values()) / p2_pipe, 3) if p2_pipe else None
        cpus = len(os.sched_getaffinity(0))
        ratio = round(pipe_wall / (p1 + p2), 3)
        gate = "enforced" if cpus >= 2 else "skipped-single-cpu"
        result = {
            "metric": "build_pipeline_overlap_ratio",
            "value": ratio,
            "unit": "x (pipelined wall / serial p1+p2; < 1 is overlap)",
            "serial": {"wall_s": round(serial_wall, 4), "p1_s": p1, "p2_s": p2},
            "pipelined": {
                "wall_s": round(pipe_wall, 4),
                "phases_s": pipe_stats["phases_s"],
                "pipeline": pinfo,
                "overlap_factor": overlap_factor,
            },
            "identical_index_bytes": identical,
            "rows": n,
            "num_buckets": num_buckets,
            "venue": venue,
            "cpus": cpus,
            "gate": gate,
        }
        Path(out_path).write_text(json.dumps(result, indent=1) + "\n")
        log(f"wrote {out_path}: ratio={ratio} (p1={p1}s p2={p2}s pipe={pipe_wall:.3f}s "
            f"overlap_factor={overlap_factor} cpus={cpus} gate={gate})")
        print(json.dumps({k: result[k] for k in ("metric", "value", "unit", "gate")}))
        if gate == "enforced" and ratio >= 0.9:
            log(f"FAIL: pipelined wall {pipe_wall:.3f}s >= 0.9 x (p1+p2) = {0.9*(p1+p2):.3f}s")
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def scaleout_smoke(out_path: str = "BENCH_SCALEOUT.json", workers: int = 2) -> int:
    """Scale-out build smoke (the CI `build-scaleout` job): build a small
    synthetic table three ways — serial streaming reference
    (`pipeline_enabled=False`), pooled with ONE worker process, pooled
    with `workers` processes — assert all three indexes are byte-for-byte
    identical, and gate the N-worker wall against the 1-worker wall.

    Like BENCH_PIPELINE, the wall/GB/s scaling gate only binds on hosts
    with >= 2 schedulable CPUs: on one CPU, N worker processes timeshare
    one core and the wall ratio measures the box, not the sharding —
    there the run is recorded informational (`cpus` field) while the
    identical-bytes gate is ALWAYS enforced."""
    import os

    from hyperspace_tpu.dataset import Dataset
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.execution.builder import DeviceIndexBuilder

    rng = np.random.default_rng(11)
    num_buckets = 32
    n, files = 600_000, 4
    tmp = Path(tempfile.mkdtemp(prefix="hs_scaleout_"))
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        root = tmp / "src"
        root.mkdir()
        per = n // files
        for i in range(files):
            k = rng.integers(0, 10**9, per).astype(np.int64)
            pq.write_table(
                pa.table(
                    {
                        "k": k,
                        "s": pa.array([f"s{j % 37:02d}" for j in range(per)]),
                        "v": rng.standard_normal(per),
                    }
                ),
                root / f"p{i}.parquet",
                row_group_size=20_000,
            )
        ds = Dataset.parquet(root)
        sel_bytes = hio.estimate_uncompressed_bytes(
            sorted(str(p) for p in root.glob("*.parquet")), ["k", "s", "v"]
        )
        kw = dict(memory_budget_bytes=400_000, chunk_bytes=600_000)

        serial = DeviceIndexBuilder(pipeline_enabled=False, **kw)
        d_serial = tmp / "idx_serial" / "v__=0"
        serial.write(ds.scan(), ["k", "s", "v"], ["k"], num_buckets, d_serial)
        assert serial.last_build_stats["path"] == "streaming"

        def pooled_build(w: int, dest: Path):
            """Best-of-2 wall (shared-runner noise exceeds the margin)."""
            wall, stats_ = None, None
            b = DeviceIndexBuilder(workers=w, **kw)
            for _ in range(2):
                t0 = time.perf_counter()
                b.write(ds.scan(), ["k", "s", "v"], ["k"], num_buckets, dest)
                e = time.perf_counter() - t0
                if wall is None or e < wall:
                    wall, stats_ = e, dict(b.last_build_stats)
            return wall, stats_

        d_one = tmp / "idx_w1" / "v__=0"
        wall_one, stats_one = pooled_build(1, d_one)
        d_n = tmp / f"idx_w{workers}" / "v__=0"
        wall_n, stats_n = pooled_build(workers, d_n)

        def identical(d_got):
            return hio.read_manifest(d_serial) == hio.read_manifest(d_got) and all(
                (d_serial / hio.bucket_file_name(b)).read_bytes()
                == (d_got / hio.bucket_file_name(b)).read_bytes()
                for b in range(num_buckets)
            )

        same = identical(d_one) and identical(d_n)
        assert same, "pooled index differs from the serial reference"

        cpus = len(os.sched_getaffinity(0))
        speedup = round(wall_one / wall_n, 3)
        gate = "enforced" if cpus >= 2 else "skipped-single-cpu"
        result = {
            "metric": "build_scaleout_speedup",
            "value": speedup,
            "unit": f"x (1-worker wall / {workers}-worker wall; > 1 is scaling)",
            "workers": workers,
            "serial": {
                "wall_phases_s": serial.last_build_stats["phases_s"],
            },
            "one_worker": {
                "wall_s": round(wall_one, 4),
                "gbps": round(sel_bytes / 1e9 / wall_one, 4),
                "phases_s": stats_one["phases_s"],
            },
            "n_workers": {
                "wall_s": round(wall_n, 4),
                "gbps": round(sel_bytes / 1e9 / wall_n, 4),
                "phases_s": stats_n["phases_s"],
                "p1_shards": stats_n["p1_shards"],
                "p2_owners": stats_n["p2_owners"],
                "exchange_bytes": stats_n["exchange_bytes"],
            },
            "identical_index_bytes": same,
            "rows": n,
            "num_buckets": num_buckets,
            "cpus": cpus,
            "gate": gate,
        }
        Path(out_path).write_text(json.dumps(result, indent=1) + "\n")
        log(f"wrote {out_path}: speedup={speedup}x (w1={wall_one:.3f}s "
            f"w{workers}={wall_n:.3f}s cpus={cpus} gate={gate})")
        print(json.dumps({k: result[k] for k in ("metric", "value", "unit", "gate")}))
        if gate == "enforced" and speedup < 1.1:
            log(f"FAIL: {workers}-worker wall {wall_n:.3f}s shows no scaling over "
                f"1-worker {wall_one:.3f}s on a {cpus}-CPU host")
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="build-pipeline smoke: serial vs pipelined streaming build "
                         "(with --workers: serial vs pooled scale-out build)")
    ap.add_argument("--workers", type=int, default=0,
                    help="with --smoke: run the scale-out smoke comparing a "
                         "1-worker pool against this many worker processes")
    ap.add_argument("--out", default=None,
                    help="artifact path for --smoke (default BENCH_PIPELINE.json, "
                         "or BENCH_SCALEOUT.json with --workers)")
    args = ap.parse_args()
    if args.smoke and args.workers > 0:
        sys.exit(scaleout_smoke(args.out or "BENCH_SCALEOUT.json", args.workers))
    if args.smoke:
        sys.exit(smoke(args.out or "BENCH_PIPELINE.json"))
    main()
