"""TPC-DS round-5 query expansion: the multi-channel / returns /
inventory / shipping slices of the published 99, expressed in the plan
IR. Continues benchmarks/tpcds.py (same dataset, same conventions:
qgen-style parameter substitutions for this dataset's domains;
IR-forced reformulations noted per query — scalar subqueries as
explicit sub-plans joined on a literal key, deterministic-calendar
constants folded, lag/lead windows replacing the published rn self
joins). The reference claims serde coverage of all 99
(index/serde/package.scala:47-50); BASELINE config 3 is the SF1000
99-query geomean this slice builds toward.
"""

from __future__ import annotations


def _deviation_gt(sum_col, avg_col, frac):
    """abs(sum-avg)/avg > frac, spelled as a sign CASE (no abs() in the
    IR) — the q47/q53/q57 family's deviation predicate."""
    from hyperspace_tpu import col, lit, when

    dev = when(
        col(sum_col) >= col(avg_col),
        (col(sum_col) - col(avg_col)) / col(avg_col),
    ).otherwise((col(avg_col) - col(sum_col)) / col(avg_col))
    return (col(avg_col) > lit(0.0)) & (dev > lit(frac))


def tpcds_extra_queries(t: dict) -> dict:
    from hyperspace_tpu import AggSpec, col, date_lit, lit, when
    from hyperspace_tpu.plan.nodes import Union

    ss, dd, item, store = t["store_sales"], t["date_dim"], t["item"], t["store"]
    cs, ws = t["catalog_sales"], t["web_sales"]
    sr, cr, wr = t["store_returns"], t["catalog_returns"], t["web_returns"]
    inv, wh = t["inventory"], t["warehouse"]
    cd, hd, td, ca = (
        t["customer_demographics"],
        t["household_demographics"],
        t["time_dim"],
        t["customer_address"],
    )
    cust, promo, reason = t["customer"], t["promotion"], t["reason"]
    cc, web_site, wp, sm = (
        t["call_center"], t["web_site"], t["web_page"], t["ship_mode"],
    )
    ib = t["income_band"]

    one = lit(1)

    def scalar_join(left, right, lcols, rcols):
        """Cross join of two single-row scalar sub-plans via a literal
        key (the IR's two-step scalar-subquery evaluation)."""
        lp = left.select(("__k", one), *lcols)
        rp = right.select(("__k2", one), *rcols)
        return lp.join(rp, ["__k"], ["__k2"])

    # ---- q2: week-over-year day-of-week ratios, catalog+web union.
    wscs = Union([
        ws.select(("sold_date_sk", col("ws_sold_date_sk")),
                  ("sales_price", col("ws_ext_sales_price"))),
        cs.select(("sold_date_sk", col("cs_sold_date_sk")),
                  ("sales_price", col("cs_ext_sales_price"))),
    ])

    def day_sum2(name, alias):
        return AggSpec.of(
            "sum",
            when(col("d_day_name") == lit(name), col("sales_price")).otherwise(0.0),
            alias,
        )

    wswscs = (
        wscs.join(dd.select("d_date_sk", "d_week_seq", "d_day_name"),
                  ["sold_date_sk"], ["d_date_sk"])
        .aggregate(
            ["d_week_seq"],
            [day_sum2(n, a) for n, a in [
                ("Sunday", "sun_sales"), ("Monday", "mon_sales"),
                ("Tuesday", "tue_sales"), ("Wednesday", "wed_sales"),
                ("Thursday", "thu_sales"), ("Friday", "fri_sales"),
                ("Saturday", "sat_sales")]],
        )
    )
    # Week-grain year pick (the published day-grain date_dim join
    # multiplies each week x7; the week-grain join preserves the
    # distinct result rows — same adaptation as q59).
    dyears = dd.select("d_week_seq", "d_year").aggregate(
        ["d_week_seq"], [AggSpec.of("min", "d_year", "yr")]
    )

    def year_weeks(y, suffix):
        names = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
        ren = [(n + suffix, col(n + "_sales")) for n in names]
        out = wswscs.join(dyears.filter(col("yr") == lit(y)), ["d_week_seq"])
        if suffix == "1":
            return out.select("d_week_seq", *ren)
        return out.select(("wk_join", col("d_week_seq") - lit(53)), *ren)

    y1 = year_weeks(2001, "1")
    y2 = year_weeks(2002, "2")
    q2 = (
        y1.join(y2, ["d_week_seq"], ["wk_join"])
        .select(
            "d_week_seq",
            ("r_sun", col("sun1") / col("sun2")), ("r_mon", col("mon1") / col("mon2")),
            ("r_tue", col("tue1") / col("tue2")), ("r_wed", col("wed1") / col("wed2")),
            ("r_thu", col("thu1") / col("thu2")), ("r_fri", col("fri1") / col("fri2")),
            ("r_sat", col("sat1") / col("sat2")),
        )
        .sort([("d_week_seq", True)])
    )

    # ---- q12 / q20: item revenue share within class over a 30-day
    # window — the q98 shape on the web / catalog channels.
    def revenue_share(fact, dk, ik, price, cats, d_lo, d_hi):
        return (
            fact.select(dk, ik, price)
            .join(
                dd.select("d_date_sk", "d_date").filter(
                    (col("d_date") >= date_lit(d_lo)) & (col("d_date") <= date_lit(d_hi))
                ),
                [dk], ["d_date_sk"],
            )
            .join(
                item.select(
                    "i_item_sk", "i_item_id", "i_item_desc", "i_category",
                    "i_class", "i_current_price",
                ).filter(col("i_category").isin(cats)),
                [ik], ["i_item_sk"],
            )
            .aggregate(
                ["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
                [AggSpec.of("sum", price, "itemrevenue")],
            )
            .window(["i_class"], funcs=[("sum", "itemrevenue", "class_revenue")])
            .select(
                "i_item_id", "i_item_desc", "i_category", "i_class",
                "i_current_price", "itemrevenue",
                ("revenueratio", col("itemrevenue") * lit(100.0) / col("class_revenue")),
            )
            .sort([("i_category", True), ("i_class", True), ("i_item_id", True),
                   ("i_item_desc", True), ("revenueratio", True)])
            .limit(100)
        )

    q12 = revenue_share(ws, "ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price",
                        ["Sports", "Books", "Home"], "1999-02-22", "1999-03-24")
    q20 = revenue_share(cs, "cs_sold_date_sk", "cs_item_sk", "cs_ext_sales_price",
                        ["Sports", "Books", "Home"], "1999-02-22", "1999-03-24")

    # ---- q15: catalog sales by customer zip, one quarter.
    q15 = (
        cs.select("cs_sold_date_sk", "cs_bill_customer_sk", "cs_sales_price")
        .join(
            dd.select("d_date_sk", "d_qoy", "d_year").filter(
                (col("d_qoy") == lit(2)) & (col("d_year") == lit(2001))
            ),
            ["cs_sold_date_sk"], ["d_date_sk"],
        )
        .join(cust.select("c_customer_sk", "c_current_addr_sk"),
              ["cs_bill_customer_sk"], ["c_customer_sk"])
        .join(ca.select("ca_address_sk", "ca_zip", "ca_state"),
              ["c_current_addr_sk"], ["ca_address_sk"])
        .filter(
            col("ca_zip").substr(1, 5).isin(
                ["85669", "86197", "88274", "83405", "86475",
                 "85392", "85460", "80348", "81792"]
            )
            | col("ca_state").isin(["CA", "WA", "GA"])
            | (col("cs_sales_price") > lit(500.0))
        )
        .aggregate(["ca_zip"], [AggSpec.of("sum", "cs_sales_price", "sum_sales")])
        .sort([("ca_zip", True)])
        .limit(100)
    )

    # ---- q38 / q87: customers present in all three channels
    # (INTERSECT) / store customers absent from the other channels
    # (EXCEPT) over one year of months.
    def channel_customers(fact, dk, ck):
        return (
            fact.select(dk, ck)
            .join(
                dd.select("d_date_sk", "d_date", "d_month_seq").filter(
                    col("d_month_seq").between(1200, 1211)
                ),
                [dk], ["d_date_sk"],
            )
            .join(cust.select("c_customer_sk", "c_last_name", "c_first_name"),
                  [ck], ["c_customer_sk"])
            .select("c_last_name", "c_first_name", "d_date")
        )

    ss_cust = channel_customers(ss, "ss_sold_date_sk", "ss_customer_sk")
    cs_cust = channel_customers(cs, "cs_sold_date_sk", "cs_bill_customer_sk")
    ws_cust = channel_customers(ws, "ws_sold_date_sk", "ws_bill_customer_sk")
    q38 = (
        ss_cust.intersect(cs_cust).intersect(ws_cust)
        .aggregate([], [AggSpec.of("count", None, "cnt")])
    )
    q87 = (
        ss_cust.except_(cs_cust).except_(ws_cust)
        .aggregate([], [AggSpec.of("count", None, "cnt")])
    )

    # ---- q47 / q57: monthly sums vs the yearly window average with the
    # previous/next month's sums — lag/lead windows standing in for the
    # published rn-offset self joins (identical result: the partitions
    # and ORDER BY are the published ones, NULL-edged rows dropped).
    def monthly_deviation(fact, dk, ik, price, dim_join, group_extra, year):
        base = (
            fact
            .join(
                dd.select("d_date_sk", "d_year", "d_moy").filter(
                    (col("d_year") == lit(year))
                    | ((col("d_year") == lit(year - 1)) & (col("d_moy") == lit(12)))
                    | ((col("d_year") == lit(year + 1)) & (col("d_moy") == lit(1)))
                ),
                [dk], ["d_date_sk"],
            )
            .join(item.select("i_item_sk", "i_category", "i_brand"), [ik], ["i_item_sk"])
        )
        base = dim_join(base)
        part = ["i_category", "i_brand", *group_extra]
        v1 = (
            base.aggregate(
                [*part, "d_year", "d_moy"],
                [AggSpec.of("sum", price, "sum_sales")],
            )
            .window([*part, "d_year"], funcs=[("mean", "sum_sales", "avg_monthly_sales")])
            .window(
                part,
                order_by=[("d_year", True), ("d_moy", True)],
                funcs=[("lag", "sum_sales", "psum"), ("lead", "sum_sales", "nsum")],
            )
        )
        return (
            v1.filter(
                (col("d_year") == lit(year))
                & col("psum").is_not_null() & col("nsum").is_not_null()
                & _deviation_gt("sum_sales", "avg_monthly_sales", 0.1)
            )
            .select(
                *part, "d_year", "d_moy", "sum_sales", "avg_monthly_sales",
                "psum", "nsum",
                ("diff", col("sum_sales") - col("avg_monthly_sales")),
            )
            .sort([("diff", True), (part[0], True), ("d_moy", True)])
            .limit(100)
        )

    q47 = monthly_deviation(
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_sales_price"),
        "ss_sold_date_sk", "ss_item_sk", "ss_sales_price",
        lambda p: p.join(
            store.select("s_store_sk", "s_store_name", "s_company_name"),
            ["ss_store_sk"], ["s_store_sk"],
        ),
        ["s_store_name", "s_company_name"], 1999,
    )
    q57 = monthly_deviation(
        cs.select("cs_sold_date_sk", "cs_item_sk", "cs_call_center_sk", "cs_sales_price"),
        "cs_sold_date_sk", "cs_item_sk", "cs_sales_price",
        lambda p: p.join(cc.select("cc_call_center_sk", "cc_name"),
                         ["cs_call_center_sk"], ["cc_call_center_sk"]),
        ["cc_name"], 1999,
    )

    # ---- q51: web-vs-store cumulative daily revenue per item, FULL
    # OUTER joined at (item, day) with running-max forward fill.
    def daily_cume(fact, dk, ik, price, out_item, out_date, out_sales, out_cume):
        return (
            fact.select(dk, ik, price)
            .join(
                dd.select("d_date_sk", "d_date", "d_month_seq").filter(
                    col("d_month_seq").between(1200, 1211)
                ),
                [dk], ["d_date_sk"],
            )
            .aggregate([ik, "d_date"], [AggSpec.of("sum", price, "sales")])
            .window([ik], order_by=[("d_date", True)], funcs=[("sum", "sales", "cume")],
                    frame="rows")
            .select((out_item, col(ik)), (out_date, col("d_date")),
                    (out_sales, col("sales")), (out_cume, col("cume")))
        )

    web_d = daily_cume(ws, "ws_sold_date_sk", "ws_item_sk", "ws_sales_price",
                       "item_sk", "d_date", "web_sales", "web_cume")
    store_d = daily_cume(ss, "ss_sold_date_sk", "ss_item_sk", "ss_sales_price",
                         "item_sk_s", "d_date_s", "store_sales", "store_cume")
    q51 = (
        web_d.join(store_d, ["item_sk", "d_date"], ["item_sk_s", "d_date_s"], how="full")
        .window(
            ["item_sk"], order_by=[("d_date", True)],
            funcs=[("max", "web_cume", "web_cumulative"),
                   ("max", "store_cume", "store_cumulative")],
            frame="rows",
        )
        .filter(col("web_cumulative") > col("store_cumulative"))
        .select("item_sk", "d_date", "web_sales", "store_sales",
                "web_cumulative", "store_cumulative")
        .sort([("item_sk", True), ("d_date", True)])
        .limit(100)
    )

    # ---- q61: promotional vs total sales ratio, one month/category/GMT
    # band — the published cross join of two scalar subqueries.
    def q61_base(with_promo):
        p = (
            ss.select("ss_sold_date_sk", "ss_item_sk", "ss_promo_sk", "ss_store_sk",
                      "ss_customer_sk", "ss_ext_sales_price")
            .join(
                dd.select("d_date_sk", "d_year", "d_moy").filter(
                    (col("d_year") == lit(1998)) & (col("d_moy") == lit(11))
                ),
                ["ss_sold_date_sk"], ["d_date_sk"],
            )
            .join(store.select("s_store_sk", "s_gmt_offset").filter(
                col("s_gmt_offset") == lit(-5.0)), ["ss_store_sk"], ["s_store_sk"])
            .join(item.select("i_item_sk", "i_category").filter(
                col("i_category") == lit("Jewelry")), ["ss_item_sk"], ["i_item_sk"])
            .join(cust.select("c_customer_sk", "c_current_addr_sk"),
                  ["ss_customer_sk"], ["c_customer_sk"])
            .join(ca.select("ca_address_sk", "ca_gmt_offset").filter(
                col("ca_gmt_offset") == lit(-5.0)), ["c_current_addr_sk"], ["ca_address_sk"])
        )
        if with_promo:
            p = p.join(
                promo.select("p_promo_sk", "p_channel_dmail", "p_channel_email",
                             "p_channel_tv").filter(
                    (col("p_channel_dmail") == lit("Y"))
                    | (col("p_channel_email") == lit("Y"))
                    | (col("p_channel_tv") == lit("Y"))
                ),
                ["ss_promo_sk"], ["p_promo_sk"],
            )
        return p.aggregate([], [AggSpec.of("sum", "ss_ext_sales_price", "total")])

    q61 = scalar_join(
        q61_base(True).select(("promotions", col("total"))),
        q61_base(False).select(("total", col("total"))),
        ["promotions"], ["total"],
    ).select("promotions", "total",
             ("ratio", col("promotions") / col("total") * lit(100.0)))

    # ---- q69: demographics of customers with a store purchase but no
    # web/catalog purchase in the window (EXISTS / NOT EXISTS as
    # semi/anti joins).
    dd_q69 = dd.select("d_date_sk", "d_year", "d_moy").filter(
        (col("d_year") == lit(2001)) & col("d_moy").between(4, 6)
    )

    def purchased(fact, dk, ck):
        return fact.select(dk, ck).join(dd_q69, [dk], ["d_date_sk"]).select(ck)

    q69 = (
        cust.select("c_customer_sk", "c_current_addr_sk", "c_current_cdemo_sk")
        .join(ca.select("ca_address_sk", "ca_state").filter(
            col("ca_state").isin(["KY", "GA", "NM"])),
            ["c_current_addr_sk"], ["ca_address_sk"])
        .join(purchased(ss, "ss_sold_date_sk", "ss_customer_sk"),
              ["c_customer_sk"], ["ss_customer_sk"], how="semi")
        .join(purchased(ws, "ws_sold_date_sk", "ws_bill_customer_sk"),
              ["c_customer_sk"], ["ws_bill_customer_sk"], how="anti")
        .join(purchased(cs, "cs_sold_date_sk", "cs_bill_customer_sk"),
              ["c_customer_sk"], ["cs_bill_customer_sk"], how="anti")
        .join(cd.select("cd_demo_sk", "cd_gender", "cd_marital_status",
                        "cd_education_status", "cd_purchase_estimate",
                        "cd_credit_rating"),
              ["c_current_cdemo_sk"], ["cd_demo_sk"])
        .aggregate(
            ["cd_gender", "cd_marital_status", "cd_education_status",
             "cd_purchase_estimate", "cd_credit_rating"],
            [AggSpec.of("count", None, "cnt1")],
        )
        .sort([("cd_gender", True), ("cd_marital_status", True),
               ("cd_education_status", True), ("cd_purchase_estimate", True),
               ("cd_credit_rating", True)])
        .limit(100)
    )

    # ---- q74: web-vs-store year-over-year growth per customer
    # (ss_ext_sales_price stands in for the ungenerated ss_net_paid).
    def year_total(fact, dk, ck, price, year, id_alias, tot_alias, keep_name=False):
        p = (
            fact.select(dk, ck, price)
            .join(dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(year)),
                  [dk], ["d_date_sk"])
            .join(cust.select("c_customer_sk", "c_customer_id", "c_first_name",
                              "c_last_name"),
                  [ck], ["c_customer_sk"])
            .aggregate(
                ["c_customer_id", "c_first_name", "c_last_name"],
                [AggSpec.of("sum", price, tot_alias)],
            )
        )
        cols = [(id_alias, col("c_customer_id")), tot_alias]
        if keep_name:
            cols = [(id_alias, col("c_customer_id")), "c_first_name",
                    "c_last_name", tot_alias]
        return p.select(*cols)

    s1 = year_total(ss, "ss_sold_date_sk", "ss_customer_sk", "ss_ext_sales_price",
                    1999, "cid_s1", "total_s1", keep_name=True).filter(
        col("total_s1") > lit(0.0))
    s2 = year_total(ss, "ss_sold_date_sk", "ss_customer_sk", "ss_ext_sales_price",
                    2000, "cid_s2", "total_s2")
    w1 = year_total(ws, "ws_sold_date_sk", "ws_bill_customer_sk", "ws_net_paid",
                    1999, "cid_w1", "total_w1").filter(col("total_w1") > lit(0.0))
    w2 = year_total(ws, "ws_sold_date_sk", "ws_bill_customer_sk", "ws_net_paid",
                    2000, "cid_w2", "total_w2")
    q74 = (
        s1.join(s2, ["cid_s1"], ["cid_s2"])
        .join(w1, ["cid_s1"], ["cid_w1"])
        .join(w2, ["cid_s1"], ["cid_w2"])
        .filter(
            (col("total_w2") / col("total_w1")) > (col("total_s2") / col("total_s1"))
        )
        .select("cid_s1", "c_first_name", "c_last_name")
        .sort([("cid_s1", True), ("c_first_name", True), ("c_last_name", True)])
        .limit(100)
    )

    # ---- q86: web net-paid ROLLUP over (category, class) with the
    # rank-within-parent window (the q36/q70 shape on the web channel).
    q86 = (
        ws.select("ws_sold_date_sk", "ws_item_sk", "ws_net_paid")
        .join(dd.select("d_date_sk", "d_month_seq").filter(
            col("d_month_seq").between(1200, 1211)),
            ["ws_sold_date_sk"], ["d_date_sk"])
        .join(item.select("i_item_sk", "i_category", "i_class"),
              ["ws_item_sk"], ["i_item_sk"])
        .rollup(
            ["i_category", "i_class"],
            [
                AggSpec.of("sum", "ws_net_paid", "total_sum"),
                AggSpec.of("grouping", "i_category", "g_cat"),
                AggSpec.of("grouping", "i_class", "g_class"),
            ],
        )
        .select(
            "total_sum", "i_category", "i_class",
            ("lochierarchy", col("g_cat") + col("g_class")),
            ("parent_cat", when(col("g_class") == lit(0), col("i_category")).otherwise(lit(""))),
        )
        .window(
            ["lochierarchy", "parent_cat"],
            order_by=[("total_sum", False)],
            funcs=[("rank", None, "rank_within_parent")],
        )
        .select("total_sum", "i_category", "i_class", "lochierarchy",
                "rank_within_parent")
        .sort([("lochierarchy", False), ("i_category", True),
               ("rank_within_parent", True)])
        .limit(100)
    )

    # ---- q90: web AM-to-PM order count ratio.
    q90_base = (
        ws.select("ws_sold_time_sk", "ws_ship_hdemo_sk", "ws_web_page_sk")
        .join(hd.select("hd_demo_sk", "hd_dep_count").filter(
            col("hd_dep_count") == lit(6)), ["ws_ship_hdemo_sk"], ["hd_demo_sk"])
        .join(wp.select("wp_web_page_sk", "wp_char_count").filter(
            col("wp_char_count").between(5000, 5200)),
            ["ws_web_page_sk"], ["wp_web_page_sk"])
    )

    def hour_count(lo, hi, alias):
        return (
            q90_base.join(
                td.select("t_time_sk", "t_hour").filter(col("t_hour").between(lo, hi)),
                ["ws_sold_time_sk"], ["t_time_sk"],
            )
            .aggregate([], [AggSpec.of("count", None, alias)])
        )

    q90 = scalar_join(
        hour_count(8, 9, "amc"), hour_count(19, 20, "pmc"), ["amc"], ["pmc"]
    ).select(("am_pm_ratio", col("amc") / col("pmc")))

    # ---- q97: store/catalog customer-item overlap via FULL OUTER join
    # of the two distinct (customer, item) sets, counted by flag
    # validity.
    def cust_item(fact, dk, ck, ik, c_out, i_out, flag):
        return (
            fact.select(dk, ck, ik)
            .join(dd.select("d_date_sk", "d_month_seq").filter(
                col("d_month_seq").between(1200, 1211)), [dk], ["d_date_sk"])
            .select(ck, ik)
            .distinct()
            .select((c_out, col(ck)), (i_out, col(ik)), (flag, one))
        )

    ssci = cust_item(ss, "ss_sold_date_sk", "ss_customer_sk", "ss_item_sk",
                     "customer_sk", "item_sk", "s_flag")
    csci = cust_item(cs, "cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk",
                     "customer_sk_c", "item_sk_c", "c_flag")
    q97 = (
        ssci.join(csci, ["customer_sk", "item_sk"], ["customer_sk_c", "item_sk_c"],
                  how="full")
        .aggregate(
            [],
            [
                AggSpec.of(
                    "sum",
                    when(col("s_flag").is_not_null() & col("c_flag").is_null(), 1).otherwise(0),
                    "store_only",
                ),
                AggSpec.of(
                    "sum",
                    when(col("s_flag").is_null() & col("c_flag").is_not_null(), 1).otherwise(0),
                    "catalog_only",
                ),
                AggSpec.of(
                    "sum",
                    when(col("s_flag").is_not_null() & col("c_flag").is_not_null(), 1).otherwise(0),
                    "store_and_catalog",
                ),
            ],
        )
    )

    # ---- q1 / q30 / q81: customers whose channel returns exceed 1.2x
    # their store's / state's average (the per-group avg subquery as an
    # explicit aggregate joined back).
    def returns_over_avg(ctr, group_col, group_out):
        avg_side = ctr.select((group_out, col(group_col)), "ctr_total_return").aggregate(
            [group_out], [AggSpec.of("mean", "ctr_total_return", "avg_return")]
        )
        return (
            ctr.join(avg_side, [group_col], [group_out])
            .filter(col("ctr_total_return") > col("avg_return") * lit(1.2))
        )

    sr_ctr = (
        sr.select("sr_returned_date_sk", "sr_customer_sk", "sr_store_sk", "sr_return_amt")
        .join(dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2000)),
              ["sr_returned_date_sk"], ["d_date_sk"])
        .aggregate(["sr_customer_sk", "sr_store_sk"],
                   [AggSpec.of("sum", "sr_return_amt", "ctr_total_return")])
    )
    q1 = (
        returns_over_avg(sr_ctr, "sr_store_sk", "store2")
        .join(store.select("s_store_sk", "s_state").filter(col("s_state") == lit("TX")),
              ["sr_store_sk"], ["s_store_sk"])
        .join(cust.select("c_customer_sk", "c_customer_id"),
              ["sr_customer_sk"], ["c_customer_sk"])
        .select("c_customer_id")
        .sort([("c_customer_id", True)])
        .limit(100)
    )

    def state_returns_report(rt, dk, ck, ak, amt, year, home_state):
        ctr = (
            rt.select(dk, ck, ak, amt)
            .join(dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(year)),
                  [dk], ["d_date_sk"])
            .join(ca.select("ca_address_sk", "ca_state"), [ak], ["ca_address_sk"])
            .aggregate([ck, "ca_state"], [AggSpec.of("sum", amt, "ctr_total_return")])
        )
        return (
            returns_over_avg(ctr, "ca_state", "state2")
            .join(
                cust.select("c_customer_sk", "c_customer_id", "c_salutation",
                            "c_first_name", "c_last_name", "c_preferred_cust_flag",
                            "c_birth_day", "c_birth_month", "c_birth_year",
                            "c_birth_country", "c_current_addr_sk"),
                [ck], ["c_customer_sk"],
            )
            .join(
                ca.select(("ca2_sk", col("ca_address_sk")), ("ca2_state", col("ca_state")))
                .filter(col("ca2_state") == lit(home_state)),
                ["c_current_addr_sk"], ["ca2_sk"],
            )
            .select("c_customer_id", "c_salutation", "c_first_name", "c_last_name",
                    "c_preferred_cust_flag", "c_birth_day", "c_birth_month",
                    "c_birth_year", "c_birth_country", "ctr_total_return")
            .sort([("c_customer_id", True), ("c_salutation", True),
                   ("c_first_name", True), ("ctr_total_return", True)])
            .limit(100)
        )

    q30 = state_returns_report(wr, "wr_returned_date_sk", "wr_returning_customer_sk",
                               "wr_returning_addr_sk", "wr_return_amt", 2002, "GA")
    q81 = state_returns_report(cr, "cr_returned_date_sk", "cr_returning_customer_sk",
                               "cr_returning_addr_sk", "cr_return_amt", 2000, "GA")

    # ---- q93: actual sales after returns for one return reason (the
    # published ss LEFT JOIN sr, then the reason equi-join drops the
    # null-extended rows exactly as the comma join does).
    q93 = (
        ss.select("ss_item_sk", "ss_ticket_number", "ss_customer_sk",
                  "ss_quantity", "ss_sales_price")
        .join(
            sr.select("sr_item_sk", "sr_ticket_number", "sr_reason_sk",
                      "sr_return_quantity"),
            # (ticket, item) order matches the ticket+item bucket layout.
            ["ss_ticket_number", "ss_item_sk"], ["sr_ticket_number", "sr_item_sk"],
            how="left",
        )
        .join(reason.select("r_reason_sk", "r_reason_desc").filter(
            col("r_reason_desc") == lit("reason 28")),
            ["sr_reason_sk"], ["r_reason_sk"])
        .select(
            "ss_customer_sk",
            ("act_sales",
             when(col("sr_return_quantity").is_not_null(),
                  (col("ss_quantity") - col("sr_return_quantity")) * col("ss_sales_price"))
             .otherwise(col("ss_quantity") * col("ss_sales_price"))),
        )
        .aggregate(["ss_customer_sk"], [AggSpec.of("sum", "act_sales", "sumsales")])
        .sort([("sumsales", True), ("ss_customer_sk", True)])
        .limit(100)
    )

    # ---- q50: store return latency buckets per store, one return month.
    q50 = (
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_ticket_number",
                  "ss_customer_sk", "ss_store_sk")
        .join(
            sr.select("sr_item_sk", "sr_ticket_number", "sr_customer_sk",
                      "sr_returned_date_sk"),
            # Same (ticket, item) + customer-residual shape as q17.
            ["ss_ticket_number", "ss_item_sk"],
            ["sr_ticket_number", "sr_item_sk"],
            condition=col("ss_customer_sk") == col("sr_customer_sk"),
        )
        .join(
            dd.select("d_date_sk", "d_year", "d_moy").filter(
                (col("d_year") == lit(2001)) & (col("d_moy") == lit(8))
            ),
            ["sr_returned_date_sk"], ["d_date_sk"],
        )
        .join(store.select("s_store_sk", "s_store_name", "s_store_id", "s_county",
                           "s_city"), ["ss_store_sk"], ["s_store_sk"])
        .select(
            "s_store_name", "s_store_id", "s_county", "s_city",
            ("lag_days", col("sr_returned_date_sk") - col("ss_sold_date_sk")),
        )
        .aggregate(
            ["s_store_name", "s_store_id", "s_county", "s_city"],
            [
                AggSpec.of("sum", when(col("lag_days") <= lit(30), 1).otherwise(0), "d30"),
                AggSpec.of("sum", when((col("lag_days") > lit(30)) & (col("lag_days") <= lit(60)), 1).otherwise(0), "d31_60"),
                AggSpec.of("sum", when((col("lag_days") > lit(60)) & (col("lag_days") <= lit(90)), 1).otherwise(0), "d61_90"),
                AggSpec.of("sum", when((col("lag_days") > lit(90)) & (col("lag_days") <= lit(120)), 1).otherwise(0), "d91_120"),
                AggSpec.of("sum", when(col("lag_days") > lit(120), 1).otherwise(0), "d120_plus"),
            ],
        )
        .sort([("s_store_name", True), ("s_store_id", True)])
        .limit(100)
    )

    # ---- q17 / q25 / q29: the buy-return-rebuy triangle (ss -> sr by
    # ticket+item+customer -> cs by customer+item) across quarter
    # windows. STDDEV recomposes from sum/sumsq/count via sqrt() —
    # the IR's explicit two-phase stddev.
    from hyperspace_tpu import sqrt

    def triangle(d1_pred, d2_pred, d3_pred, store_cols, measures, sort_keys):
        base = (
            ss.select("ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                      "ss_ticket_number", "ss_quantity", "ss_store_sk",
                      "ss_net_profit")
            .join(dd.select("d_date_sk", "d_year", "d_qoy", "d_moy").filter(d1_pred),
                  ["ss_sold_date_sk"], ["d_date_sk"])
            .join(
                sr.select("sr_item_sk", "sr_ticket_number", "sr_customer_sk",
                          "sr_returned_date_sk", "sr_return_quantity", "sr_net_loss"),
                # (ticket, item) rides the bucketed ticket+item indexes;
                # the published third equi-key (customer) stays an ON
                # residual — same matches, aligned execution.
                ["ss_ticket_number", "ss_item_sk"],
                ["sr_ticket_number", "sr_item_sk"],
                condition=col("ss_customer_sk") == col("sr_customer_sk"),
            )
            .join(
                dd.select(("d2_sk", col("d_date_sk")), ("d2_year", col("d_year")),
                          ("d2_qoy", col("d_qoy")), ("d2_moy", col("d_moy")))
                .filter(d2_pred),
                ["sr_returned_date_sk"], ["d2_sk"],
            )
            .join(
                cs.select("cs_bill_customer_sk", "cs_item_sk", "cs_sold_date_sk",
                          "cs_quantity", "cs_net_profit"),
                ["ss_customer_sk", "ss_item_sk"],
                ["cs_bill_customer_sk", "cs_item_sk"],
            )
            .join(
                dd.select(("d3_sk", col("d_date_sk")), ("d3_year", col("d_year")),
                          ("d3_qoy", col("d_qoy")), ("d3_moy", col("d_moy")))
                .filter(d3_pred),
                ["cs_sold_date_sk"], ["d3_sk"],
            )
            .join(store.select("s_store_sk", *store_cols), ["ss_store_sk"], ["s_store_sk"])
            .join(item.select("i_item_sk", "i_item_id", "i_item_desc"),
                  ["ss_item_sk"], ["i_item_sk"])
        )
        return (
            base.aggregate(["i_item_id", "i_item_desc", *store_cols], measures)
            .sort(sort_keys)
            .limit(100)
        )

    def qty_stats(qcol, prefix):
        return [
            AggSpec.of("count", qcol, f"{prefix}_count"),
            AggSpec.of("mean", qcol, f"{prefix}_ave"),
            AggSpec.of("sum", col(qcol) * col(qcol), f"__{prefix}_sq"),
            AggSpec.of("sum", qcol, f"__{prefix}_sum"),
        ]

    def with_stdev(plan, prefixes, keep):
        outs = list(keep)
        for p in prefixes:
            n, s, sq = col(f"{p}_count"), col(f"__{p}_sum"), col(f"__{p}_sq")
            var = (sq - s * s / n) / (n - lit(1))
            outs.append((f"{p}_stdev", sqrt(var)))
            outs.append((f"{p}_cov", sqrt(var) / col(f"{p}_ave")))
        return plan.select(*outs)

    q17_agg = triangle(
        (col("d_year") == lit(2001)) & (col("d_qoy") == lit(1)),
        (col("d2_year") == lit(2001)) & col("d2_qoy").between(1, 3),
        (col("d3_year") == lit(2001)) & col("d3_qoy").between(1, 3),
        ["s_state"],
        [*qty_stats("ss_quantity", "store_sales"),
         *qty_stats("sr_return_quantity", "store_returns"),
         *qty_stats("cs_quantity", "catalog_sales")],
        [("i_item_id", True), ("i_item_desc", True), ("s_state", True)],
    )
    q17 = with_stdev(
        q17_agg,
        ["store_sales", "store_returns", "catalog_sales"],
        ["i_item_id", "i_item_desc", "s_state",
         "store_sales_count", "store_sales_ave",
         "store_returns_count", "store_returns_ave",
         "catalog_sales_count", "catalog_sales_ave"],
    )

    q25 = triangle(
        (col("d_year") == lit(2001)) & (col("d_moy") == lit(4)),
        (col("d2_year") == lit(2001)) & col("d2_moy").between(4, 10),
        (col("d3_year") == lit(2001)) & col("d3_moy").between(4, 10),
        ["s_store_id", "s_store_name"],
        [
            AggSpec.of("sum", "ss_net_profit", "store_sales_profit"),
            AggSpec.of("sum", "sr_net_loss", "store_returns_loss"),
            AggSpec.of("sum", "cs_net_profit", "catalog_sales_profit"),
        ],
        [("i_item_id", True), ("i_item_desc", True), ("s_store_id", True),
         ("s_store_name", True)],
    )

    q29 = triangle(
        (col("d_year") == lit(1999)) & (col("d_moy") == lit(9)),
        (col("d2_year") == lit(1999)) & col("d2_moy").between(9, 12),
        col("d3_year").isin([1999, 2000, 2001]),
        ["s_store_id", "s_store_name"],
        [
            AggSpec.of("sum", "ss_quantity", "store_sales_quantity"),
            AggSpec.of("sum", "sr_return_quantity", "store_returns_quantity"),
            AggSpec.of("sum", "cs_quantity", "catalog_sales_quantity"),
        ],
        [("i_item_id", True), ("i_item_desc", True), ("s_store_id", True),
         ("s_store_name", True)],
    )

    # ---- q40: catalog sales net of returns around a price-band window,
    # split before/after one date, by warehouse state.
    q40 = (
        cs.select("cs_order_number", "cs_item_sk", "cs_sold_date_sk",
                  "cs_warehouse_sk", "cs_sales_price")
        .join(
            cr.select("cr_order_number", "cr_item_sk", "cr_return_amt"),
            ["cs_order_number", "cs_item_sk"], ["cr_order_number", "cr_item_sk"],
            how="left",
        )
        .join(wh.select("w_warehouse_sk", "w_state"),
              ["cs_warehouse_sk"], ["w_warehouse_sk"])
        .join(
            item.select("i_item_sk", "i_item_id", "i_current_price").filter(
                col("i_current_price").between(0.99, 1.49)
            ),
            ["cs_item_sk"], ["i_item_sk"],
        )
        .join(
            dd.select("d_date_sk", "d_date").filter(
                (col("d_date") >= date_lit("2000-02-10"))
                & (col("d_date") <= date_lit("2000-04-10"))
            ),
            ["cs_sold_date_sk"], ["d_date_sk"],
        )
        .select(
            "w_state", "i_item_id",
            ("net_val",
             when(col("cr_return_amt").is_not_null(),
                  col("cs_sales_price") - col("cr_return_amt"))
             .otherwise(col("cs_sales_price"))),
            ("is_before", when(col("d_date") < date_lit("2000-03-11"), 1).otherwise(0)),
        )
        .aggregate(
            ["w_state", "i_item_id"],
            [
                AggSpec.of("sum", when(col("is_before") == lit(1), col("net_val")).otherwise(0.0), "sales_before"),
                AggSpec.of("sum", when(col("is_before") == lit(0), col("net_val")).otherwise(0.0), "sales_after"),
            ],
        )
        .sort([("w_state", True), ("i_item_id", True)])
        .limit(100)
    )

    # ---- q83: same-week return quantities across the three channels,
    # joined per item (the d_week_seq subquery folded through the
    # deterministic calendar via a semi join).
    probe_dates = (
        (col("d_date") == date_lit("2000-06-30"))
        | (col("d_date") == date_lit("2000-09-27"))
        | (col("d_date") == date_lit("2000-11-17"))
    )
    wk = dd.select("d_week_seq", "d_date").filter(probe_dates).select("d_week_seq")
    valid_dates = (
        dd.select("d_date_sk", "d_week_seq")
        .join(wk, ["d_week_seq"], ["d_week_seq"], how="semi")
        .select("d_date_sk")
    )

    def channel_return_qty(rt, dk, ik, qty, id_out, qty_out):
        return (
            rt.select(dk, ik, qty)
            .join(valid_dates, [dk], ["d_date_sk"], how="semi")
            .join(item.select("i_item_sk", "i_item_id"), [ik], ["i_item_sk"])
            .aggregate(["i_item_id"], [AggSpec.of("sum", qty, qty_out)])
            .select((id_out, col("i_item_id")), qty_out)
        )

    sr_q = channel_return_qty(sr, "sr_returned_date_sk", "sr_item_sk",
                              "sr_return_quantity", "item_id", "sr_item_qty")
    cr_q = channel_return_qty(cr, "cr_returned_date_sk", "cr_item_sk",
                              "cr_return_quantity", "item_id_c", "cr_item_qty")
    wr_q = channel_return_qty(wr, "wr_returned_date_sk", "wr_item_sk",
                              "wr_return_quantity", "item_id_w", "wr_item_qty")
    q83_total = (col("sr_item_qty") + col("cr_item_qty") + col("wr_item_qty"))
    q83 = (
        sr_q.join(cr_q, ["item_id"], ["item_id_c"])
        .join(wr_q, ["item_id"], ["item_id_w"])
        .select(
            "item_id", "sr_item_qty",
            ("sr_dev", col("sr_item_qty") / q83_total * lit(100.0) / lit(3.0)),
            "cr_item_qty",
            ("cr_dev", col("cr_item_qty") / q83_total * lit(100.0) / lit(3.0)),
            "wr_item_qty",
            ("wr_dev", col("wr_item_qty") / q83_total * lit(100.0) / lit(3.0)),
            ("average", q83_total / lit(3.0)),
        )
        .sort([("item_id", True), ("sr_item_qty", True)])
        .limit(100)
    )

    # ---- q84: customers in one city within an income band who have a
    # store return under their demographics (inner to store_returns, as
    # the published comma join multiplies).
    q84 = (
        cust.select("c_customer_sk", "c_customer_id", "c_first_name", "c_last_name",
                    "c_current_addr_sk", "c_current_cdemo_sk", "c_current_hdemo_sk")
        .join(ca.select("ca_address_sk", "ca_city").filter(
            col("ca_city") == lit("Fairview")),
            ["c_current_addr_sk"], ["ca_address_sk"])
        .join(hd.select("hd_demo_sk", "hd_income_band_sk"),
              ["c_current_hdemo_sk"], ["hd_demo_sk"])
        .join(
            ib.select("ib_income_band_sk", "ib_lower_bound", "ib_upper_bound").filter(
                (col("ib_lower_bound") >= lit(30_001))
                & (col("ib_upper_bound") <= lit(80_000))
            ),
            ["hd_income_band_sk"], ["ib_income_band_sk"],
        )
        .join(sr.select("sr_cdemo_sk"), ["c_current_cdemo_sk"], ["sr_cdemo_sk"])
        .select("c_customer_id", "c_last_name", "c_first_name")
        .sort([("c_customer_id", True)])
        .limit(100)
    )

    # ---- q85: web return reasons with buyer/returner demographic
    # agreement (the cd1=cd2 attribute equality rides the ON residual;
    # string col<>col equality crosses the two dictionaries).
    cd2 = cd.select(("cd2_sk", col("cd_demo_sk")),
                    ("cd2_marital", col("cd_marital_status")),
                    ("cd2_edu", col("cd_education_status")))
    q85 = (
        ws.select("ws_item_sk", "ws_order_number", "ws_web_page_sk",
                  "ws_sold_date_sk", "ws_quantity", "ws_sales_price", "ws_net_profit")
        .join(
            wr.select("wr_item_sk", "wr_order_number", "wr_refunded_cdemo_sk",
                      "wr_returning_cdemo_sk", "wr_reason_sk", "wr_refunded_addr_sk",
                      "wr_return_amt", "wr_fee"),
            ["ws_order_number", "ws_item_sk"], ["wr_order_number", "wr_item_sk"],
        )
        .join(dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2000)),
              ["ws_sold_date_sk"], ["d_date_sk"])
        .join(wp.select("wp_web_page_sk"), ["ws_web_page_sk"], ["wp_web_page_sk"])
        .join(cd.select("cd_demo_sk", "cd_marital_status", "cd_education_status"),
              ["wr_refunded_cdemo_sk"], ["cd_demo_sk"])
        .join(
            cd2, ["wr_returning_cdemo_sk"], ["cd2_sk"],
            condition=(col("cd_marital_status") == col("cd2_marital"))
            & (col("cd_education_status") == col("cd2_edu")),
        )
        .join(ca.select("ca_address_sk", "ca_country", "ca_state"),
              ["wr_refunded_addr_sk"], ["ca_address_sk"])
        .join(reason.select("r_reason_sk", "r_reason_desc"),
              ["wr_reason_sk"], ["r_reason_sk"])
        .filter(
            (
                ((col("cd_marital_status") == lit("M")) & (col("cd_education_status") == lit("Advanced Degree")) & col("ws_sales_price").between(100.0, 150.0))
                | ((col("cd_marital_status") == lit("S")) & (col("cd_education_status") == lit("College")) & col("ws_sales_price").between(50.0, 100.0))
                | ((col("cd_marital_status") == lit("W")) & (col("cd_education_status") == lit("2 yr Degree")) & col("ws_sales_price").between(150.0, 200.0))
            )
            & (col("ca_country") == lit("United States"))
            & (
                (col("ca_state").isin(["CA", "OR", "WA"]) & col("ws_net_profit").between(100.0, 200.0))
                | (col("ca_state").isin(["TX", "OH", "GA"]) & col("ws_net_profit").between(150.0, 300.0))
                | (col("ca_state").isin(["FL", "NM", "KY"]) & col("ws_net_profit").between(50.0, 250.0))
            )
        )
        .aggregate(
            ["r_reason_desc"],
            [
                AggSpec.of("mean", "ws_quantity", "avg_quantity"),
                AggSpec.of("mean", "wr_return_amt", "avg_refunded"),
                AggSpec.of("mean", "wr_fee", "avg_fee"),
            ],
        )
        .sort([("r_reason_desc", True), ("avg_quantity", True)])
        .limit(100)
    )

    # ---- q91: call-center losses for picky demographics.
    q91 = (
        cr.select("cr_returned_date_sk", "cr_returning_customer_sk",
                  "cr_call_center_sk", "cr_net_loss")
        .join(
            dd.select("d_date_sk", "d_year", "d_moy").filter(
                (col("d_year") == lit(1998)) & (col("d_moy") == lit(11))
            ),
            ["cr_returned_date_sk"], ["d_date_sk"],
        )
        .join(cc.select("cc_call_center_sk", "cc_call_center_id", "cc_name",
                        "cc_manager"),
              ["cr_call_center_sk"], ["cc_call_center_sk"])
        .join(cust.select("c_customer_sk", "c_current_cdemo_sk", "c_current_hdemo_sk",
                          "c_current_addr_sk"),
              ["cr_returning_customer_sk"], ["c_customer_sk"])
        .join(
            cd.select("cd_demo_sk", "cd_gender", "cd_marital_status",
                      "cd_education_status").filter(
                ((col("cd_gender") == lit("M")) & (col("cd_education_status") == lit("Unknown")))
                | ((col("cd_gender") == lit("F")) & (col("cd_education_status") == lit("Advanced Degree")))
            ),
            ["c_current_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(hd.select("hd_demo_sk", "hd_buy_potential").filter(
            col("hd_buy_potential").like("0-500%")),
            ["c_current_hdemo_sk"], ["hd_demo_sk"])
        .join(ca.select("ca_address_sk", "ca_gmt_offset").filter(
            col("ca_gmt_offset") == lit(-6.0)),
            ["c_current_addr_sk"], ["ca_address_sk"])
        .aggregate(
            ["cc_call_center_id", "cc_name", "cc_manager", "cd_marital_status",
             "cd_education_status"],
            [AggSpec.of("sum", "cr_net_loss", "returns_loss")],
        )
        .sort([("returns_loss", False)])
    )

    # ---- q21 / q37 / q82 / q22 / q39: the inventory family.
    q21 = (
        inv.select("inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
                   "inv_quantity_on_hand")
        .join(
            dd.select("d_date_sk", "d_date").filter(
                (col("d_date") >= date_lit("2000-02-10"))
                & (col("d_date") <= date_lit("2000-04-10"))
            ),
            ["inv_date_sk"], ["d_date_sk"],
        )
        .join(
            item.select("i_item_sk", "i_item_id", "i_current_price").filter(
                col("i_current_price").between(0.99, 1.49)
            ),
            ["inv_item_sk"], ["i_item_sk"],
        )
        .join(wh.select("w_warehouse_sk", "w_warehouse_name"),
              ["inv_warehouse_sk"], ["w_warehouse_sk"])
        .aggregate(
            ["w_warehouse_name", "i_item_id"],
            [
                AggSpec.of("sum", when(col("d_date") < date_lit("2000-03-11"), col("inv_quantity_on_hand")).otherwise(0), "inv_before"),
                AggSpec.of("sum", when(col("d_date") >= date_lit("2000-03-11"), col("inv_quantity_on_hand")).otherwise(0), "inv_after"),
            ],
        )
        .filter(
            (col("inv_before") > lit(0))
            & ((col("inv_after") * lit(1.0)) / col("inv_before") >= lit(2.0 / 3.0))
            & ((col("inv_after") * lit(1.0)) / col("inv_before") <= lit(3.0 / 2.0))
        )
        .sort([("w_warehouse_name", True), ("i_item_id", True)])
        .limit(100)
    )

    def inv_item_window(fact, ik, d_lo, d_hi, price_lo, manufact_ids):
        """q37/q82: items in a price/manufacturer band with 100-500 units
        on hand inside a 60-day window, sold through the channel."""
        items = item.select(
            "i_item_sk", "i_item_id", "i_item_desc", "i_current_price", "i_manufact_id"
        ).filter(
            col("i_current_price").between(price_lo, price_lo + 30.0)
            & col("i_manufact_id").isin(manufact_ids)
        )
        on_hand = (
            inv.select("inv_date_sk", "inv_item_sk", "inv_quantity_on_hand")
            .join(
                dd.select("d_date_sk", "d_date").filter(
                    (col("d_date") >= date_lit(d_lo)) & (col("d_date") <= date_lit(d_hi))
                ),
                ["inv_date_sk"], ["d_date_sk"],
            )
            .filter(col("inv_quantity_on_hand").between(100, 500))
            .select("inv_item_sk")
        )
        return (
            fact.select(ik)
            .join(items, [ik], ["i_item_sk"])
            .join(on_hand, [ik], ["inv_item_sk"], how="semi")
            .aggregate(["i_item_id", "i_item_desc", "i_current_price"], [])
            .sort([("i_item_id", True)])
            .limit(100)
        )

    q37 = inv_item_window(cs, "cs_item_sk", "2000-02-01", "2000-04-01", 68.0,
                          list(range(677, 700, 3)))
    q82 = inv_item_window(ss, "ss_item_sk", "2000-05-25", "2000-07-24", 62.0,
                          list(range(129, 176, 7)))

    q22 = (
        inv.select("inv_date_sk", "inv_item_sk", "inv_quantity_on_hand")
        .join(dd.select("d_date_sk", "d_month_seq").filter(
            col("d_month_seq").between(1200, 1211)),
            ["inv_date_sk"], ["d_date_sk"])
        .join(item.select("i_item_sk", "i_item_id", "i_brand", "i_class", "i_category"),
              ["inv_item_sk"], ["i_item_sk"])
        .rollup(
            ["i_item_id", "i_brand", "i_class", "i_category"],
            [AggSpec.of("mean", "inv_quantity_on_hand", "qoh")],
        )
        .sort([("qoh", True), ("i_item_id", True), ("i_brand", True),
               ("i_class", True), ("i_category", True)])
        .limit(100)
    )

    def inv_moy_stats(moy, suffix):
        g = (
            inv.select("inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
                       "inv_quantity_on_hand")
            .join(
                dd.select("d_date_sk", "d_year", "d_moy").filter(
                    (col("d_year") == lit(2000)) & (col("d_moy") == lit(moy))
                ),
                ["inv_date_sk"], ["d_date_sk"],
            )
            .join(wh.select("w_warehouse_sk", "w_warehouse_name"),
                  ["inv_warehouse_sk"], ["w_warehouse_sk"])
            .aggregate(
                ["inv_item_sk", "inv_warehouse_sk"],
                [
                    AggSpec.of("count", "inv_quantity_on_hand", "__n"),
                    AggSpec.of("sum", "inv_quantity_on_hand", "__s"),
                    AggSpec.of("sum", col("inv_quantity_on_hand") * col("inv_quantity_on_hand"), "__sq"),
                ],
            )
        )
        n, s, sq = col("__n"), col("__s"), col("__sq")
        var = (sq - s * s / n) / (n - lit(1))
        return (
            g.select(
                (f"item{suffix}", col("inv_item_sk")),
                (f"wh{suffix}", col("inv_warehouse_sk")),
                (f"mean{suffix}", s / n),
                (f"cov{suffix}", sqrt(var) / (s / n)),
            )
            .filter(col(f"cov{suffix}") > lit(1.0))
        )

    q39 = (
        inv_moy_stats(1, "1")
        .join(inv_moy_stats(2, "2"), ["item1", "wh1"], ["item2", "wh2"])
        .select("wh1", "item1", "mean1", "cov1", "mean2", "cov2")
        .sort([("wh1", True), ("item1", True)])
        .limit(100)
    )

    # ---- q62 / q99: shipping latency buckets (web / catalog).
    def ship_buckets(fact, sold_dk, ship_dk, whk, smk, extra_dim, extra_join_keys,
                     extra_group):
        return (
            fact.select(sold_dk, ship_dk, whk, smk, extra_join_keys[0])
            .join(dd.select("d_date_sk", "d_month_seq").filter(
                col("d_month_seq").between(1200, 1211)),
                [ship_dk], ["d_date_sk"])
            .join(wh.select("w_warehouse_sk", "w_warehouse_name"),
                  [whk], ["w_warehouse_sk"])
            .join(sm.select("sm_ship_mode_sk", "sm_type"), [smk], ["sm_ship_mode_sk"])
            .join(extra_dim, [extra_join_keys[0]], [extra_join_keys[1]])
            .select(
                ("wh_name", col("w_warehouse_name").substr(1, 20)),
                "sm_type", extra_group,
                ("lag_days", col(ship_dk) - col(sold_dk)),
            )
            .aggregate(
                ["wh_name", "sm_type", extra_group],
                [
                    AggSpec.of("sum", when(col("lag_days") <= lit(30), 1).otherwise(0), "d30"),
                    AggSpec.of("sum", when((col("lag_days") > lit(30)) & (col("lag_days") <= lit(60)), 1).otherwise(0), "d31_60"),
                    AggSpec.of("sum", when((col("lag_days") > lit(60)) & (col("lag_days") <= lit(90)), 1).otherwise(0), "d61_90"),
                    AggSpec.of("sum", when((col("lag_days") > lit(90)) & (col("lag_days") <= lit(120)), 1).otherwise(0), "d91_120"),
                    AggSpec.of("sum", when(col("lag_days") > lit(120), 1).otherwise(0), "d120_plus"),
                ],
            )
            .sort([("wh_name", True), ("sm_type", True), (extra_group, True)])
            .limit(100)
        )

    q62 = ship_buckets(ws, "ws_sold_date_sk", "ws_ship_date_sk", "ws_warehouse_sk",
                       "ws_ship_mode_sk",
                       web_site.select("web_site_sk", "web_name"),
                       ("ws_web_site_sk", "web_site_sk"), "web_name")
    q99 = ship_buckets(cs, "cs_sold_date_sk", "cs_ship_date_sk", "cs_warehouse_sk",
                       "cs_ship_mode_sk",
                       cc.select("cc_call_center_sk", "cc_name"),
                       ("cs_call_center_sk", "cc_call_center_sk"), "cc_name")

    # ---- q16 / q94: on-time multi-warehouse shipping with no returns
    # (EXISTS with a cross-row condition as a residual semi join; NOT
    # EXISTS as an anti join; COUNT DISTINCT order numbers).
    def ship_report(fact, pre, ship_dk, ak, order_col, whc, ship_cost, profit,
                    rt, r_order, site_join, d_lo, d_hi):
        other = fact.select(("__o2", col(order_col)), ("__wh2", col(whc)))
        return (
            pre
            .join(
                dd.select("d_date_sk", "d_date").filter(
                    (col("d_date") >= date_lit(d_lo)) & (col("d_date") <= date_lit(d_hi))
                ),
                [ship_dk], ["d_date_sk"],
            )
            .join(ca.select("ca_address_sk", "ca_state").filter(
                col("ca_state") == lit("GA")), [ak], ["ca_address_sk"])
            .join(site_join[0], [site_join[1]], [site_join[2]])
            .join(other, [order_col], ["__o2"],
                  how="semi", condition=col(whc) != col("__wh2"))
            .join(rt.select(r_order), [order_col], [r_order], how="anti")
            .aggregate(
                [],
                [
                    AggSpec.of("count_distinct", order_col, "order_count"),
                    AggSpec.of("sum", ship_cost, "total_shipping_cost"),
                    AggSpec.of("sum", profit, "total_net_profit"),
                ],
            )
        )

    q16 = ship_report(
        cs,
        cs.select("cs_ship_date_sk", "cs_ship_addr_sk", "cs_order_number",
                  "cs_warehouse_sk", "cs_ext_ship_cost", "cs_net_profit",
                  "cs_call_center_sk"),
        "cs_ship_date_sk", "cs_ship_addr_sk", "cs_order_number", "cs_warehouse_sk",
        "cs_ext_ship_cost", "cs_net_profit",
        cr, "cr_order_number",
        (cc.select("cc_call_center_sk", "cc_county").filter(
            col("cc_county") == lit("Williamson County")),
         "cs_call_center_sk", "cc_call_center_sk"),
        "2002-02-01", "2002-04-02",
    )
    q94 = ship_report(
        ws,
        ws.select("ws_ship_date_sk", "ws_ship_addr_sk", "ws_order_number",
                  "ws_warehouse_sk", "ws_ext_ship_cost", "ws_net_profit",
                  "ws_web_site_sk"),
        "ws_ship_date_sk", "ws_ship_addr_sk", "ws_order_number", "ws_warehouse_sk",
        "ws_ext_ship_cost", "ws_net_profit",
        wr, "wr_order_number",
        (web_site.select("web_site_sk", "web_company_name").filter(
            col("web_company_name") == lit("pri")),
         "ws_web_site_sk", "web_site_sk"),
        "1999-02-01", "1999-04-02",
    )

    # ---- q95: both-returned two-warehouse web orders.
    ws_wh = (
        ws.select(("o1", col("ws_order_number")), ("wh1", col("ws_warehouse_sk")))
        .join(
            ws.select(("o2", col("ws_order_number")), ("wh2", col("ws_warehouse_sk"))),
            ["o1"], ["o2"], condition=col("wh1") != col("wh2"),
        )
        .select("o1")
        .distinct()
    )
    q95 = (
        ws.select("ws_ship_date_sk", "ws_ship_addr_sk", "ws_order_number",
                  "ws_ext_ship_cost", "ws_net_profit", "ws_web_site_sk")
        .join(
            dd.select("d_date_sk", "d_date").filter(
                (col("d_date") >= date_lit("1999-02-01"))
                & (col("d_date") <= date_lit("1999-04-01"))
            ),
            ["ws_ship_date_sk"], ["d_date_sk"],
        )
        .join(ca.select("ca_address_sk", "ca_state").filter(
            col("ca_state") == lit("GA")), ["ws_ship_addr_sk"], ["ca_address_sk"])
        .join(web_site.select("web_site_sk", "web_company_name").filter(
            col("web_company_name") == lit("pri")),
            ["ws_web_site_sk"], ["web_site_sk"])
        .join(ws_wh, ["ws_order_number"], ["o1"], how="semi")
        .join(
            wr.select("wr_order_number")
            .join(ws_wh.select(("o1b", col("o1"))), ["wr_order_number"], ["o1b"],
                  how="semi")
            .select("wr_order_number"),
            ["ws_order_number"], ["wr_order_number"], how="semi",
        )
        .aggregate(
            [],
            [
                AggSpec.of("count_distinct", "ws_order_number", "order_count"),
                AggSpec.of("sum", "ws_ext_ship_cost", "total_shipping_cost"),
                AggSpec.of("sum", "ws_net_profit", "total_net_profit"),
            ],
        )
    )

    # ---- q32 / q92: excess-discount sales (per-item 1.3x average
    # discount threshold over a 90-day window).
    def excess_discount(fact, dk, ik, disc, manufact_id, d_lo, d_hi):
        window_dd = dd.select("d_date_sk", "d_date").filter(
            (col("d_date") >= date_lit(d_lo)) & (col("d_date") <= date_lit(d_hi))
        )
        avg_disc = (
            fact.select(dk, ik, disc)
            .join(window_dd, [dk], ["d_date_sk"])
            .aggregate([ik], [AggSpec.of("mean", disc, "avg_disc")])
            .select(("item2", col(ik)), "avg_disc")
        )
        return (
            fact.select(dk, ik, disc)
            .join(window_dd, [dk], ["d_date_sk"])
            .join(item.select("i_item_sk", "i_manufact_id").filter(
                col("i_manufact_id") == lit(manufact_id)), [ik], ["i_item_sk"])
            .join(avg_disc, [ik], ["item2"])
            .filter(col(disc) > col("avg_disc") * lit(1.3))
            .aggregate([], [AggSpec.of("sum", disc, "excess_discount_amount")])
        )

    q32 = excess_discount(cs, "cs_sold_date_sk", "cs_item_sk", "cs_ext_discount_amt",
                          610, "2000-01-27", "2000-04-26")
    q92 = excess_discount(ws, "ws_sold_date_sk", "ws_item_sk", "ws_ext_discount_amt",
                          350, "2000-01-27", "2000-04-26")

    # ---- q56: three-channel totals for items of probe colors (the
    # q33/q60 family keyed by i_color).
    def channel_sum56(fact, dk, ik, ak, price, item_side):
        return (
            fact.select(dk, ik, ak, price)
            .join(
                dd.select("d_date_sk", "d_year", "d_moy").filter(
                    (col("d_year") == lit(2000)) & (col("d_moy") == lit(2))
                ),
                [dk], ["d_date_sk"],
            )
            .join(ca.select("ca_address_sk", "ca_gmt_offset").filter(
                col("ca_gmt_offset") == lit(-5.0)), [ak], ["ca_address_sk"])
            .join(item_side, [ik], ["i_item_sk"])
            .aggregate(["i_item_id"], [AggSpec.of("sum", price, "total_sales")])
        )

    color_ids = (
        item.select("i_item_id", "i_color")
        .filter(col("i_color").isin(["slate", "blanched", "powder"]))
        .select("i_item_id")
        .distinct()
    )
    q56_items = item.select("i_item_sk", "i_item_id").join(
        color_ids, ["i_item_id"], how="semi"
    )
    q56 = (
        Union([
            channel_sum56(ss, "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk",
                          "ss_ext_sales_price", q56_items),
            channel_sum56(cs, "cs_sold_date_sk", "cs_item_sk", "cs_bill_addr_sk",
                          "cs_ext_sales_price", q56_items),
            channel_sum56(ws, "ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk",
                          "ws_ext_sales_price", q56_items),
        ])
        .aggregate(["i_item_id"], [AggSpec.of("sum", "total_sales", "total_sales2")])
        .select("i_item_id", ("total_sales", col("total_sales2")))
        .sort([("total_sales", True), ("i_item_id", True)])
        .limit(100)
    )

    # ---- q71: brand revenue at breakfast/dinner across all channels.
    def meal_part(fact, dk, ik, tk, price):
        return (
            fact.select(dk, ik, tk, price)
            .join(
                dd.select("d_date_sk", "d_moy", "d_year").filter(
                    (col("d_moy") == lit(11)) & (col("d_year") == lit(1999))
                ),
                [dk], ["d_date_sk"],
            )
            .select(("ext_price", col(price)), ("sold_item_sk", col(ik)),
                    ("time_sk", col(tk)))
        )

    q71 = (
        Union([
            meal_part(ws, "ws_sold_date_sk", "ws_item_sk", "ws_sold_time_sk",
                      "ws_ext_sales_price"),
            meal_part(cs, "cs_sold_date_sk", "cs_item_sk", "cs_sold_time_sk",
                      "cs_ext_sales_price"),
            meal_part(ss, "ss_sold_date_sk", "ss_item_sk", "ss_sold_time_sk",
                      "ss_ext_sales_price"),
        ])
        .join(
            item.select("i_item_sk", "i_brand_id", "i_brand", "i_manager_id").filter(
                col("i_manager_id") == lit(1)
            ),
            ["sold_item_sk"], ["i_item_sk"],
        )
        .join(
            td.select("t_time_sk", "t_hour", "t_minute", "t_meal_time").filter(
                col("t_meal_time").isin(["breakfast", "dinner"])
            ),
            ["time_sk"], ["t_time_sk"],
        )
        .aggregate(["i_brand_id", "i_brand", "t_hour", "t_minute"],
                   [AggSpec.of("sum", "ext_price", "ext_price_sum")])
        .sort([("ext_price_sum", False), ("i_brand_id", True), ("t_hour", True),
               ("t_minute", True)])
        .limit(100)
    )

    # ---- q76: rows sold with a NULL channel FK.
    def null_fk_part(fact, null_col, channel, dk, ik, price):
        return (
            fact.select(dk, ik, price, null_col)
            .filter(col(null_col).is_null())
            .select(
                ("channel", lit(channel)), ("col_name", lit(null_col)),
                ("sold_date_sk", col(dk)), ("item_sk", col(ik)),
                ("ext_sales_price", col(price)),
            )
        )

    q76 = (
        Union([
            null_fk_part(ss, "ss_addr_sk", "store", "ss_sold_date_sk",
                         "ss_item_sk", "ss_ext_sales_price"),
            null_fk_part(ws, "ws_ship_customer_sk", "web", "ws_sold_date_sk",
                         "ws_item_sk", "ws_ext_sales_price"),
            null_fk_part(cs, "cs_ship_addr_sk", "catalog", "cs_sold_date_sk",
                         "cs_item_sk", "cs_ext_sales_price"),
        ])
        .join(dd.select("d_date_sk", "d_year", "d_qoy"), ["sold_date_sk"], ["d_date_sk"])
        .join(item.select("i_item_sk", "i_category"), ["item_sk"], ["i_item_sk"])
        .aggregate(
            ["channel", "col_name", "d_year", "d_qoy", "i_category"],
            [
                AggSpec.of("count", None, "sales_cnt"),
                AggSpec.of("sum", "ext_sales_price", "sales_amt"),
            ],
        )
        .sort([("channel", True), ("col_name", True), ("d_year", True),
               ("d_qoy", True), ("i_category", True)])
        .limit(100)
    )

    # ---- q45: web customers by zip, probe zips OR probe item ids (the
    # IN-subquery OR rides a LEFT join flag).
    probe_ids = (
        item.select("i_item_sk", "i_item_id")
        .filter(col("i_item_sk").isin([2, 3, 5, 7, 11, 13, 17, 19, 23, 29]))
        .select(("fid", col("i_item_id")), ("flag", one))
        .distinct()
    )
    q45 = (
        ws.select("ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk",
                  "ws_sales_price")
        .join(
            dd.select("d_date_sk", "d_qoy", "d_year").filter(
                (col("d_qoy") == lit(2)) & (col("d_year") == lit(2001))
            ),
            ["ws_sold_date_sk"], ["d_date_sk"],
        )
        .join(cust.select("c_customer_sk", "c_current_addr_sk"),
              ["ws_bill_customer_sk"], ["c_customer_sk"])
        .join(ca.select("ca_address_sk", "ca_zip", "ca_city"),
              ["c_current_addr_sk"], ["ca_address_sk"])
        .join(item.select("i_item_sk", "i_item_id"), ["ws_item_sk"], ["i_item_sk"])
        .join(probe_ids, ["i_item_id"], ["fid"], how="left")
        .filter(
            col("ca_zip").substr(1, 5).isin(
                ["85669", "86197", "88274", "83405", "86475"]
            )
            | col("flag").is_not_null()
        )
        .aggregate(["ca_zip", "ca_city"],
                   [AggSpec.of("sum", "ws_sales_price", "sum_ws_sales_price")])
        .sort([("ca_zip", True), ("ca_city", True)])
        .limit(100)
    )

    # ---- q18: catalog buyer demographics ROLLUP over geography.
    q18 = (
        cs.select("cs_sold_date_sk", "cs_bill_customer_sk", "cs_bill_cdemo_sk",
                  "cs_item_sk", "cs_quantity", "cs_list_price", "cs_coupon_amt",
                  "cs_sales_price", "cs_net_profit")
        .join(
            cd.select("cd_demo_sk", "cd_gender", "cd_education_status",
                      "cd_dep_count").filter(
                (col("cd_gender") == lit("F"))
                & (col("cd_education_status") == lit("Unknown"))
            ),
            ["cs_bill_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(1998)),
              ["cs_sold_date_sk"], ["d_date_sk"])
        .join(item.select("i_item_sk", "i_item_id"), ["cs_item_sk"], ["i_item_sk"])
        .join(
            cust.select("c_customer_sk", "c_current_addr_sk", "c_birth_month",
                        "c_birth_year").filter(
                col("c_birth_month").isin([1, 6, 8, 9, 12, 2])
            ),
            ["cs_bill_customer_sk"], ["c_customer_sk"],
        )
        .join(
            ca.select("ca_address_sk", "ca_country", "ca_state", "ca_county").filter(
                col("ca_state").isin(["MS", "IN", "ND", "OK", "NM", "VA"])
                | col("ca_county").isin(["Ziebach County", "Luce County",
                                         "Fairfield County"])
            ),
            ["c_current_addr_sk"], ["ca_address_sk"],
        )
        .rollup(
            ["i_item_id", "ca_country", "ca_state", "ca_county"],
            [
                AggSpec.of("mean", "cs_quantity", "agg1"),
                AggSpec.of("mean", "cs_list_price", "agg2"),
                AggSpec.of("mean", "cs_coupon_amt", "agg3"),
                AggSpec.of("mean", "cs_sales_price", "agg4"),
                AggSpec.of("mean", "cs_net_profit", "agg5"),
                AggSpec.of("mean", "c_birth_year", "agg6"),
                AggSpec.of("mean", "cd_dep_count", "agg7"),
            ],
        )
        .sort([("ca_country", True), ("ca_state", True), ("ca_county", True),
               ("i_item_id", True)])
        .limit(100)
    )

    # ---- q72: catalog orders promised from low inventory (same-week
    # inventory below the ordered quantity, shipped 5+ days out).
    cs_side = (
        cs.select("cs_item_sk", "cs_order_number", "cs_quantity", "cs_sold_date_sk",
                  "cs_ship_date_sk", "cs_bill_cdemo_sk", "cs_bill_hdemo_sk",
                  "cs_promo_sk")
        .join(
            dd.select("d_date_sk", "d_week_seq", "d_date", "d_year").filter(
                col("d_year") == lit(2000)
            ),
            ["cs_sold_date_sk"], ["d_date_sk"],
        )
    )
    inv_side = (
        inv.select("inv_item_sk", "inv_date_sk", "inv_warehouse_sk",
                   "inv_quantity_on_hand")
        .join(
            dd.select(("d2_sk", col("d_date_sk")), ("inv_week", col("d_week_seq"))),
            ["inv_date_sk"], ["d2_sk"],
        )
    )
    q72 = (
        cs_side.join(
            inv_side, ["cs_item_sk", "d_week_seq"], ["inv_item_sk", "inv_week"],
            condition=col("inv_quantity_on_hand") < col("cs_quantity"),
        )
        .join(
            dd.select(("d3_sk", col("d_date_sk")), ("d3_date", col("d_date"))),
            ["cs_ship_date_sk"], ["d3_sk"],
            condition=col("d3_date") > col("d_date") + lit(5),
        )
        .join(wh.select("w_warehouse_sk", "w_warehouse_name"),
              ["inv_warehouse_sk"], ["w_warehouse_sk"])
        .join(item.select("i_item_sk", "i_item_desc"), ["cs_item_sk"], ["i_item_sk"])
        .join(cd.select("cd_demo_sk", "cd_marital_status").filter(
            col("cd_marital_status") == lit("D")),
            ["cs_bill_cdemo_sk"], ["cd_demo_sk"])
        .join(hd.select("hd_demo_sk", "hd_buy_potential").filter(
            col("hd_buy_potential") == lit(">10000")),
            ["cs_bill_hdemo_sk"], ["hd_demo_sk"])
        .join(promo.select("p_promo_sk", ("p_flag", one)),
              ["cs_promo_sk"], ["p_promo_sk"], how="left")
        .join(
            cr.select("cr_item_sk", "cr_order_number"),
            ["cs_order_number", "cs_item_sk"], ["cr_order_number", "cr_item_sk"],
            how="left",
        )
        .aggregate(
            ["i_item_desc", "w_warehouse_name", "d_week_seq"],
            [
                AggSpec.of("sum", when(col("p_flag").is_null(), 1).otherwise(0), "no_promo"),
                AggSpec.of("sum", when(col("p_flag").is_not_null(), 1).otherwise(0), "promo"),
                AggSpec.of("count", None, "total_cnt"),
            ],
        )
        .sort([("total_cnt", False), ("i_item_desc", True),
               ("w_warehouse_name", True), ("d_week_seq", True)])
        .limit(100)
    )

    return {
        "q2": q2, "q12": q12, "q15": q15, "q20": q20, "q38": q38,
        "q47": q47, "q51": q51, "q57": q57, "q61": q61, "q69": q69,
        "q74": q74, "q86": q86, "q87": q87, "q90": q90, "q97": q97,
        "q1": q1, "q16": q16, "q17": q17, "q18": q18, "q21": q21,
        "q22": q22, "q25": q25, "q29": q29, "q30": q30, "q32": q32,
        "q37": q37, "q39": q39, "q40": q40, "q45": q45, "q50": q50,
        "q56": q56, "q62": q62, "q71": q71, "q72": q72, "q76": q76,
        "q81": q81, "q82": q82, "q83": q83, "q84": q84, "q85": q85,
        "q91": q91, "q92": q92, "q93": q93, "q94": q94, "q95": q95,
        "q99": q99,
    }
