"""Run every benchmark; one JSON document per benchmark on stdout
(single-line for most; bench_tpcds/bench_venues pretty-print theirs).

`python bench.py` at the repo root remains the driver's flagship entry
(TPC-H point lookup); this harness covers the remaining BASELINE configs.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks import (  # noqa: E402
    bench_ann,
    bench_hybrid,
    bench_join,
    bench_refresh,
    bench_tpcds,
    bench_tpch_queries,
)


def main():
    for mod in (bench_join, bench_tpch_queries, bench_tpcds, bench_hybrid, bench_refresh, bench_ann):
        print(f"=== {mod.__name__} ===", file=sys.stderr, flush=True)
        mod.main()


if __name__ == "__main__":
    main()
