"""BASELINE config 2 at SF100: the shuffle-free orders ⋈ lineitem join.

Generates TPC-H SF100 (~600M-row lineitem, 150M orders) chunk by chunk
(bounded memory), builds both covering indexes through the STREAMING
out-of-core path, and times the join with an aggregate consumer (sum of
revenue by order priority — the fused join-aggregate never materializes
the ~600M joined rows) indexed vs raw. Emits one JSON line and is meant
to be captured into BENCH_SF100.json. Times are single-shot (a run costs
minutes); the build GB/s extends the BENCH_SCALE curve to SF100.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from benchmarks.harness import log  # noqa: E402


def main(sf: float = 100.0):
    from benchmarks.datagen import cached_tpch
    from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.dataset import list_data_files

    t0 = time.perf_counter()
    li_root, o_root = cached_tpch(sf=sf)
    t_gen = time.perf_counter() - t0
    log(f"datagen (cached ok) sf={sf:g}: {t_gen:.1f}s")

    tmp = Path(tempfile.mkdtemp(prefix="hs_sf100_"))
    out: dict = {"metric": "tpch_sf100_shuffle_free_join", "sf": sf}
    try:
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=64)
        hs = Hyperspace(session)
        li = session.parquet(li_root)
        orders = session.parquet(o_root)

        li_cols = ["l_orderkey", "l_extendedprice", "l_discount"]
        li_bytes = hio.estimate_uncompressed_bytes(
            [fi.path for fi in list_data_files(li_root)], li_cols
        )
        t0 = time.perf_counter()
        hs.create_index(li, IndexConfig("li_ok", ["l_orderkey"], li_cols[1:]))
        t_li = time.perf_counter() - t0
        li_stats = session.last_build_stats
        log(
            f"lineitem index: {t_li:.1f}s  {li_bytes/1e9:.2f} GB selected -> "
            f"{li_bytes/1e9/t_li:.4f} GB/s/chip  path={li_stats.get('path')} "
            f"phases={li_stats.get('phases_s')}"
        )

        o_cols = ["o_orderkey", "o_orderpriority"]
        o_bytes = hio.estimate_uncompressed_bytes(
            [fi.path for fi in list_data_files(o_root)], o_cols
        )
        t0 = time.perf_counter()
        hs.create_index(orders, IndexConfig("o_ok", ["o_orderkey"], ["o_orderpriority"]))
        t_o = time.perf_counter() - t0
        o_stats = session.last_build_stats
        log(
            f"orders index:   {t_o:.1f}s  {o_bytes/1e9:.2f} GB selected -> "
            f"{o_bytes/1e9/t_o:.4f} GB/s/chip  path={o_stats.get('path')}"
        )

        # The join, consumed by an aggregation (5 priority groups): the
        # fused join-aggregate path never materializes the joined rows.
        q = (
            li.select("l_orderkey", "l_extendedprice", "l_discount")
            .join(
                orders.select("o_orderkey", "o_orderpriority"),
                ["l_orderkey"], ["o_orderkey"],
            )
            .aggregate(
                ["o_orderpriority"],
                [
                    AggSpec.of("sum", "l_extendedprice", "rev"),
                    AggSpec.of("count", None, "n"),
                ],
            )
        )

        session.enable_hyperspace()
        t0 = time.perf_counter()
        r_idx = session.run(q)
        t_indexed = time.perf_counter() - t0
        stats = dict(session.last_query_stats)
        log(
            f"indexed: {t_indexed:.1f}s  join={stats['join_path']} "
            f"agg={stats['agg_path']} kernel={stats.get('join_kernel')}"
        )

        session.disable_hyperspace()
        t0 = time.perf_counter()
        r_raw = session.run(q)
        t_raw = time.perf_counter() - t0
        log(f"raw:     {t_raw:.1f}s")

        import numpy as np

        gi = {k: v for k, v in zip(r_idx.decode()["o_orderpriority"], r_idx.columns["n"])}
        gr = {k: v for k, v in zip(r_raw.decode()["o_orderpriority"], r_raw.columns["n"])}
        assert gi == gr, f"result mismatch: {gi} vs {gr}"
        total_rows = int(np.sum(r_idx.columns["n"]))

        out.update({
            "value": round(t_raw / t_indexed, 3),
            "unit": "x",
            "vs_baseline": round(t_raw / t_indexed, 3),
            "joined_rows": total_rows,
            "indexed_s": round(t_indexed, 2),
            "raw_s": round(t_raw, 2),
            "build": {
                "lineitem_s": round(t_li, 2),
                "lineitem_selected_gb": round(li_bytes / 1e9, 3),
                "lineitem_gbps": round(li_bytes / 1e9 / t_li, 4),
                "lineitem_phases_s": li_stats.get("phases_s"),
                "lineitem_path": li_stats.get("path"),
                "orders_s": round(t_o, 2),
                "orders_gbps": round(o_bytes / 1e9 / t_o, 4),
                "orders_path": o_stats.get("path"),
            },
            "datagen_s": round(t_gen, 1),
            "notes": (
                "single-shot wall times on the 1-core bench host; the "
                "aggregate consumer keeps the ~4-lines-per-order join "
                "from materializing its output"
            ),
        })
        print(json.dumps(out))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 100.0)
