"""Chaos soak harness: prove the ops controller heals the system.

The gate of docs/fault_tolerance.md "self-driving operations": under a
deterministic fault schedule plus an overload burst, **SLOs recover
without a human** — no unbounded burn, no permanent quarantine, zero
untyped errors, bounded time-to-recover per fault episode — and the
identical schedule with `hyperspace.controller.enabled=false` shows the
degraded counterfactual (the quarantine REMAINS), proving the
controller, not luck, did the healing.

Mixed query + refresh traffic flows through a real QueryServer over a
real indexed store for the whole run while four fault episodes fire in
sequence:

1. **transient_io** — `faults.inject("bucket.read")` makes every data
   read fail (after the retry layer gives up): availability burns, the
   SLO pages, the controller sheds load + tightens quotas; the fault
   clears and the burn must age back below the page threshold with the
   overrides released.
2. **corruption_quarantine** — a live index bucket file is corrupted on
   disk: the next indexed query raises IndexCorruptionError, the index
   is quarantined (queries keep answering via fallback), and the
   controller must heal it — `recover()` + full rebuild through the
   crash-safe Action protocol — leaving `session.index_health` empty.
3. **overload_burst** — submit bursts far past capacity with tight
   deadlines: queued queries expire (serve.timeouts), availability
   burns, the controller tightens the shed threshold; every refusal
   must be TYPED (AdmissionRejected/QuotaExceeded/QueryTimeout), the
   p99 of completed queries stays bounded, and the burn recovers when
   the burst ends.
4. **worker_sigkill** — a real fleet member is SIGKILLed: the
   supervisor must respawn it (WARN `fleet.worker.restarted`) within
   the bound — the crash-loop backoff satellite keeps repeat crashes
   from burning the restart budget in milliseconds.

`--fleet N` (N >= 2) adds the fleet-coordination regime on top — a
real FleetSupervisor handle on the controller plus a SECOND live
controller over the SAME store — and three more episodes:

5. **brownout** — `faults.inject("bucket.read", delay_s=...)` makes
   every read SLOW instead of failed: the tightened latency objective
   pages, the controller sheds AND grows the member count through
   `FleetSupervisor.set_target_workers` (sustained queue saturation);
   the delay clears, overrides release, and the fleet scales back to
   its pre-episode baseline — with bounded completed-query p99 and
   zero untyped errors throughout.
6. **fleet_heal_two_controllers** — the corruption episode under TWO
   live controllers: the per-index single-flight lease must yield
   exactly ONE executed heal fleet-wide while the other member audits
   `outcome="observed"` and lifts its local quarantine via the
   idempotent recover().
7. **sigkill_mid_heal_takeover** — a phantom healer dies (SIGKILL)
   holding the heal lease: the surviving controller reaps it after the
   TTL (`fleet.singleflight.takeovers`) and completes the heal.

Determinism: the controller and the SLO tracker run on a VIRTUAL clock
advanced a fixed 5 s per tick (burn windows are clamped spans over the
sample ring, so compressed time keeps the multi-window math exact while
a CI run finishes in ~a minute); fault injection counts calls, never
wall time. Real wall time only enters through measured query latencies
(the latency histogram) and the SIGKILL episode's respawn bound.

The incident flight recorder runs throughout: the durable telemetry
journal is ON (its overhead rides every query, so the completed-p99
gate doubles as the journal-overhead gate) and every paging or
quarantine episode must leave exactly ONE finalized incident bundle
behind — open.json carrying the paging burn verdict, manifest.json
carrying the actuation audit trail and the recovery resolution, plus
the snapshotted journal segments — while the controller-disabled
counterfactual leaves ZERO. `--incidents-out=DIR` copies the bundles
out of the scratch tree before teardown (the CI soak job's artifact).

Writes BENCH_SOAK.json. `--smoke` is the CI-scaled run (the `soak`
job); gates are ALWAYS enforced — exit 1 on any failure.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

STEP_V = 5.0  # virtual seconds per tick (the controller/SLO clock)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class VirtualClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt: float = STEP_V) -> float:
        self.t += dt
        return self.t


def _gen_data(root: Path, rows: int, files: int) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    per = rows // files
    root.mkdir(parents=True, exist_ok=True)
    for f in range(files):
        t = pa.table(
            {
                "id": pa.array(np.arange(f * per, (f + 1) * per, dtype=np.int64)),
                "key": pa.array(rng.integers(0, 16, per, dtype=np.int64)),
                "value": pa.array(rng.standard_normal(per)),
            }
        )
        pq.write_table(t, root / f"part-{f}.parquet")


class SoakBench:
    """One soak run: fleet-of-one serving stack + controller + schedule."""

    INDEX = "soak_idx"

    def __init__(self, tmp: Path, smoke: bool, fleet_n: int = 0):
        self.tmp = tmp
        self.smoke = smoke
        self.fleet_n = fleet_n  # >= 2 switches on the fleet regime
        self.rows = 8_000 if smoke else 32_000
        self.clock = VirtualClock()
        self.errors_typed: dict[str, int] = {}
        self.errors_untyped: dict[str, int] = {}
        self.completed_lat: list[float] = []
        self.queries = 0
        self._key = 0
        self.sup = None

    # -- setup ------------------------------------------------------------
    def build(self):
        from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
        from hyperspace_tpu.serve.fleet.quota import TenantQuotas

        self.data = self.tmp / "data"
        _gen_data(self.data, self.rows, 2)
        self.session = HyperspaceSession(system_path=str(self.tmp / "indexes"))
        conf = self.session.conf
        # Compressed-time control loop: cooldowns/windows are VIRTUAL.
        conf.set("hyperspace.controller.enabled", "true")
        conf.set("hyperspace.controller.cooldownSeconds", 20.0)
        conf.set("hyperspace.obs.events.maxEvents", 4096)
        # Durable telemetry journal ON for the whole soak: the overhead
        # rides every query/actuation, so the existing completed-p99
        # gate doubles as the journal-overhead gate; the incident
        # bundles snapshot its segments at episode close.
        conf.set("hyperspace.obs.journal.enabled", "true")
        self.hs = Hyperspace(self.session)
        df = self.session.parquet(self.data)
        self.hs.create_index(df, IndexConfig(self.INDEX, ["key"], ["value", "id"]))
        self.session.enable_hyperspace()
        self.df = df
        self.server = self.session.serve(
            workers=4,
            max_queue_depth=64,
            quotas=TenantQuotas(rate=10_000.0, burst=10_000.0),
        )
        if self.fleet_n >= 2:
            # The scale actuator's real fleet handle (separate dir from
            # the SIGKILL episode's throwaway supervisor).
            from hyperspace_tpu.serve.fleet.supervisor import FleetSupervisor

            self.sup = FleetSupervisor(
                _soak_fleet_worker, fleet_dir=str(self.tmp / "fleet-scale"),
                n=self.fleet_n, max_restarts=6,
            )
            self.sup.start()
        self.ctrl = self.hs.controller(
            server=self.server, clock=lambda: self.clock.t,
            member_id="member-0", supervisor=self.sup,
        )
        # warm compile + plan caches so episode latencies are steady-state
        self.run_batch(8)
        self.tick(batch=8)

    def shutdown(self):
        self.server.shutdown()
        if self.sup is not None:
            self.sup.stop(timeout=30)

    # -- traffic ----------------------------------------------------------
    def _plan(self):
        from hyperspace_tpu import col

        self._key = (self._key + 1) % 16
        return self.df.filter(col("key") == self._key).select("id", "key", "value")

    def run_batch(self, n: int, timeout: float | None = None, tenant: bool = True):
        """Submit n point lookups and wait for each; every error must be
        typed (the zero-untyped-errors gate folds from here)."""
        self._await(self._submit(n, timeout=timeout, tenant=tenant))

    def _submit(self, n: int, timeout: float | None = None, tenant: bool = True):
        """Submit n point lookups WITHOUT waiting — the brownout episode
        steps the controller while the queue is still loaded, so the
        saturation signal is sampled live rather than post-drain."""
        from hyperspace_tpu.exceptions import HyperspaceError

        handles = []
        for i in range(n):
            self.queries += 1
            try:
                handles.append(
                    self.server.submit(
                        self._plan(),
                        tenant=f"t{i % 4}" if tenant else None,
                        timeout=timeout,
                    )
                )
            except BaseException as e:  # noqa: HSL017 — harness accounting:
                # every refusal is recorded by type and judged by the
                # zero-untyped gate below; nothing is swallowed silently.
                self._record_error(e, HyperspaceError)
        return handles

    def _await(self, handles) -> None:
        from hyperspace_tpu.exceptions import HyperspaceError

        for h in handles:
            t0 = time.perf_counter()
            try:
                h.result(timeout=60.0)
                self.completed_lat.append(time.perf_counter() - t0)
            except BaseException as e:  # noqa: HSL017 — same accounting
                self._record_error(e, HyperspaceError)

    def _record_error(self, e: BaseException, HyperspaceError) -> None:
        name = type(e).__name__
        if isinstance(e, (HyperspaceError, OSError)):
            self.errors_typed[name] = self.errors_typed.get(name, 0) + 1
        else:
            self.errors_untyped[name] = self.errors_untyped.get(name, 0) + 1

    def tick(self, batch: int = 12, timeout: float | None = None) -> dict:
        """One soak tick: a traffic batch, one virtual-time step, one
        controller reconciliation pass."""
        self.run_batch(batch, timeout=timeout)
        self.ctrl.step(now=self.clock.advance())
        return self.ctrl.snapshot()

    def refresh_traffic(self):
        """The 'mixed refresh traffic' leg: append rows, full-refresh the
        index through the normal crash-safe action."""
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        n = 512
        rng = np.random.default_rng(int(self.clock.t) + 1)
        pq.write_table(
            pa.table({
                "id": pa.array(np.arange(self.rows, self.rows + n, dtype=np.int64)),
                "key": pa.array(rng.integers(0, 16, n, dtype=np.int64)),
                "value": pa.array(rng.standard_normal(n)),
            }),
            self.data / f"append-{int(self.clock.t)}.parquet",
        )
        self.rows += n
        self.hs.refresh_index(self.INDEX, "full")

    # -- verdict helpers --------------------------------------------------
    def paging(self, snap: dict) -> bool:
        return any(v == "page" for v in snap["verdicts"].values())

    def drive_until(self, pred, max_ticks: int, batch: int = 12) -> tuple[bool, int]:
        for i in range(max_ticks):
            snap = self.tick(batch=batch)
            if pred(snap):
                return True, i + 1
        return False, max_ticks

    def quarantined(self) -> list[str]:
        with self.session._state_lock:
            return sorted(self.session.index_health)

    # -- incident-bundle accounting ---------------------------------------
    def run_episode(self, fn, *args, **kw) -> dict:
        """Run one episode with flight-recorder accounting: which
        incident bundles are NEW afterwards, and whether each closed
        with the paging burn verdict, the actuation audit trail, and a
        recovery resolution — the bundle gates fold from here."""
        before = {b["name"] for b in self.ctrl.list_incidents()}
        ep = fn(*args, **kw)
        new = [b for b in self.ctrl.list_incidents() if b["name"] not in before]
        bundles = []
        for b in new:
            detail = self.ctrl.read_incident(b["name"]) or {}
            man = detail.get("manifest") or {}
            opened = detail.get("open") or {}
            bundles.append({
                "name": b["name"],
                "trigger": b.get("trigger"),
                "closed": "manifest" in detail,
                "resolution": man.get("resolution"),
                "paged_objectives": sorted(
                    k for k, v in (opened.get("verdicts") or {}).items()
                    if v == "page"
                ),
                "audited_actions": sorted(
                    {a["action"] for a in man.get("actions", [])}
                ),
                "journal_segments": int(man.get("journal_segments") or 0),
            })
        ep["incident_bundles"] = bundles
        return ep

    # -- episodes ---------------------------------------------------------
    def episode_transient_io(self) -> dict:
        from hyperspace_tpu import faults
        from hyperspace_tpu.execution import io as hio

        t_start = self.clock.t
        faults.inject("bucket.read")  # transient FaultError on every read
        # The warm decoded-table/footer caches would serve every bucket
        # without touching the disk — drop them so the injected IO fault
        # reaches the read path (exactly what a real cache eviction or
        # process restart does mid-incident).
        hio.clear_table_cache()
        hio.clear_footer_cache()
        paged = False
        try:
            for _ in range(6):
                snap = self.tick()
                paged = paged or self.paging(snap)
                if snap["engaged"]:
                    break
        finally:
            faults.reset()
        engaged = self.ctrl.snapshot()["engaged"]
        recovered, ticks = self.drive_until(
            lambda s: not self.paging(s) and not s["engaged"], max_ticks=40
        )
        return {
            "name": "transient_io",
            "paged": paged,
            "controller_engaged": engaged,
            "recovered": recovered,
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def _corrupt_latest_bucket(self) -> None:
        index_root = Path(
            self.session.manager.path_resolver.get_index_path(self.INDEX)
        )
        versions = sorted(
            (d for d in index_root.glob("v__=*") if d.is_dir()),
            key=lambda d: int(d.name.split("=")[1]),
        )
        bucket = sorted(versions[-1].glob("*.parquet"))[0]
        with open(bucket, "r+b") as f:
            f.write(b"\x00GARBAGE\x00" * 4)
            f.truncate(128)

    def episode_corruption_quarantine(self, expect_heal: bool) -> dict:
        t_start = self.clock.t
        self._corrupt_latest_bucket()
        # drive traffic until the corruption is hit and (controller on)
        # healed — index_health must drain back to empty without a human
        recovered, ticks = self.drive_until(
            lambda s: not self.quarantined() and not self.paging(s),
            max_ticks=20 if expect_heal else 8,
        )
        heals = [
            e for e in self._controller_events("controller.actuation")
            if e["fields"]["action"].startswith("heal.")
            and e["fields"]["outcome"] == "executed"
        ]
        return {
            "name": "corruption_quarantine",
            "recovered": recovered if expect_heal else not recovered,
            "quarantine_remains": bool(self.quarantined()),
            "heal_actuations": len(heals),
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def episode_overload_burst(self) -> dict:
        t_start = self.clock.t
        burst = 150 if self.smoke else 300
        paged = False
        shed_before = self.server.get_shed_depth()
        min_shed = shed_before
        for _ in range(4):
            snap = self.tick(batch=burst, timeout=0.03)
            paged = paged or self.paging(snap)
            min_shed = min(min_shed, self.server.get_shed_depth())
        recovered, ticks = self.drive_until(
            lambda s: not self.paging(s) and not s["engaged"], max_ticks=40
        )
        import numpy as np

        lat = np.sort(np.asarray(self.completed_lat))
        p99 = float(np.percentile(lat, 99)) if len(lat) else 0.0
        return {
            "name": "overload_burst",
            "paged": paged,
            "shed_tightened_to": min_shed,
            "shed_restored_to": self.server.get_shed_depth(),
            "completed_p99_s": round(p99, 4),
            "p99_bounded": p99 < 5.0,
            "recovered": recovered,
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def episode_worker_sigkill(self) -> dict:
        import os
        import signal

        from hyperspace_tpu.serve.fleet.supervisor import FleetSupervisor

        t0 = time.monotonic()
        sup = FleetSupervisor(
            _soak_fleet_worker, fleet_dir=str(self.tmp / "fleet"), n=2,
            max_restarts=3,
        )
        sup.start()
        try:
            deadline = time.monotonic() + 120
            while sup.alive_count() < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            victim = sup.pids()[0]
            os.kill(victim, signal.SIGKILL)
            t_kill = time.monotonic()
            recovered = False
            while time.monotonic() < t_kill + 90:
                if sup.alive_count() == 2 and sup.pids()[0] != victim:
                    recovered = True
                    break
                time.sleep(0.05)
            ttr = time.monotonic() - t_kill
        finally:
            sup.stop(timeout=30)
        from hyperspace_tpu.obs import events

        restarted = [
            e for e in events.recent() if e["name"] == "fleet.worker.restarted"
        ]
        return {
            "name": "worker_sigkill",
            "recovered": recovered,
            "restart_events": len(restarted),
            "time_to_recover_s": round(ttr, 2),
            "setup_s": round(time.monotonic() - t0, 2),
        }

    # -- fleet episodes (--fleet N) ---------------------------------------
    def episode_brownout(self) -> dict:
        """Slow-path fault injection: every bucket read dawdles instead
        of failing. The tightened latency objective pages, the
        controller sheds AND scales the fleet up on sustained queue
        saturation; the delay clears, and both the overrides and the
        member count must come back to baseline."""
        from hyperspace_tpu import faults, stats
        from hyperspace_tpu.execution import io as hio
        from hyperspace_tpu.obs import events

        t_start = self.clock.t
        conf = self.session.conf
        base_workers = int(self.sup.n)
        delays0 = stats.get("faults.delays_injected")
        # A 20 ms latency objective against ~60-80 ms injected reads:
        # the SLOW path (not a failed one) is what pages. The saturation
        # bar drops so the 4-worker queue saturates within the episode.
        conf.set("hyperspace.obs.slo.latencyP99Seconds", 0.02)
        conf.set("hyperspace.controller.scale.saturation", 0.3)
        faults.inject("bucket.read", delay_s=0.06, jitter_s=0.02)
        paged = False
        try:
            for _ in range(8):
                # Cold caches every tick so the delay reaches the reads.
                hio.clear_table_cache()
                hio.clear_footer_cache()
                handles = self._submit(48, timeout=2.0)
                snap = self.ctrl.step(now=self.clock.advance())
                self._await(handles)
                paged = paged or self.paging(snap)
                if paged and snap["engaged"] and int(self.sup.n) > base_workers:
                    break
        finally:
            faults.reset()
        engaged = self.ctrl.snapshot()["engaged"]
        peak_workers = int(self.sup.n)
        # Incident over: restore the default latency objective (the
        # 20 ms bar exists so compressed-time delays page at all) — the
        # page must now AGE OUT through the burn windows, not flip off.
        conf.set("hyperspace.obs.slo.latencyP99Seconds", 1.0)
        recovered, ticks = self.drive_until(
            lambda s: not self.paging(s) and not s["engaged"], max_ticks=40
        )
        # Calm ticks release the scale episode (budget-free) — allow a
        # few more ticks for the hysteresis to drain.
        scaled_back = int(self.sup.n) == base_workers
        for _ in range(10):
            if scaled_back:
                break
            self.tick()
            scaled_back = int(self.sup.n) == base_workers
        conf.set("hyperspace.controller.scale.saturation", 0.75)
        scale_events = [
            e for e in events.recent()
            if e["name"] == "controller.actuation"
            and e["fields"]["action"] == "fleet.scale.up"
            and e["fields"]["outcome"] == "executed"
        ]
        import numpy as np

        lat = np.asarray(self.completed_lat)
        p99 = float(np.percentile(lat, 99)) if len(lat) else 0.0
        return {
            "name": "brownout",
            "paged": paged,
            "controller_engaged": engaged,
            "recovered": recovered,
            "delays_injected": stats.get("faults.delays_injected") - delays0,
            "scale_up_actuated": bool(scale_events),
            "peak_workers": peak_workers,
            "scaled_back": scaled_back,
            "workers_at_end": int(self.sup.n),
            "completed_p99_s": round(p99, 4),
            "p99_bounded": p99 < 5.0,
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def episode_fleet_heal(self) -> dict:
        """The corruption episode under TWO live controllers over the
        SAME store: the per-index single-flight lease must yield exactly
        ONE executed heal fleet-wide; the other member audits
        outcome="observed" and lifts its local quarantine."""
        from hyperspace_tpu import Hyperspace, HyperspaceSession, col
        from hyperspace_tpu.exceptions import HyperspaceError
        from hyperspace_tpu.obs import events

        t_start = self.clock.t
        sess_b = HyperspaceSession(system_path=str(self.tmp / "indexes"))
        sess_b.conf.set("hyperspace.controller.enabled", "true")
        sess_b.conf.set("hyperspace.controller.cooldownSeconds", 20.0)
        hs_b = Hyperspace(sess_b)
        df_b = sess_b.parquet(self.data)
        sess_b.enable_hyperspace()
        ctrl_b = hs_b.controller(clock=lambda: self.clock.t, member_id="member-1")

        def traffic_b():
            # A full key sweep so member B hits the corrupt bucket in
            # the same tick member A does.
            for k in range(16):
                try:
                    sess_b.run(df_b.filter(col("key") == k).select("id", "value"))
                except BaseException as e:  # noqa: HSL017 — harness accounting
                    self._record_error(e, HyperspaceError)

        def b_quarantined():
            with sess_b._state_lock:
                return sorted(sess_b.index_health)

        seq0 = max((e["seq"] for e in events.recent()), default=0)
        self._corrupt_latest_bucket()
        both_saw = False
        for _ in range(20):
            self.run_batch(12)
            traffic_b()
            both_saw = both_saw or (
                bool(self.quarantined()) and bool(b_quarantined())
            )
            now = self.clock.advance()
            self.ctrl.step(now=now)
            ctrl_b.step(now=now)
            if both_saw and not self.quarantined() and not b_quarantined():
                break
        heals = [
            e for e in events.recent()
            if e["seq"] > seq0 and e["name"] == "controller.actuation"
            and e["fields"]["action"].startswith("heal.")
            and e["fields"]["outcome"] in ("executed", "observed")
        ]
        executed = [e for e in heals if e["fields"]["outcome"] == "executed"]
        observed = [e for e in heals if e["fields"]["outcome"] == "observed"]
        return {
            "name": "fleet_heal_two_controllers",
            "both_members_quarantined": both_saw,
            "executed_heals": len(executed),
            "executed_by": sorted(
                {e["fields"].get("member", "?") for e in executed}
            ),
            "observed_heals": len(observed),
            "observed_by": sorted(
                {e["fields"].get("member", "?") for e in observed}
            ),
            "recovered": bool(
                both_saw and not self.quarantined() and not b_quarantined()
            ),
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def episode_sigkill_mid_heal(self) -> dict:
        """A healer SIGKILLed mid-heal leaves its heal lease live; the
        surviving controller must wait out the TTL, reap it
        (`fleet.singleflight.takeovers`), and complete the heal."""
        from hyperspace_tpu import stats
        from hyperspace_tpu.serve.fleet.singleflight import key_name

        t_start = self.clock.t
        conf = self.session.conf
        conf.set("hyperspace.fleet.lease.seconds", 1.0)
        self._corrupt_latest_bucket()
        heal_dir = Path(conf.system_path) / "_fleet" / "heal"
        heal_dir.mkdir(parents=True, exist_ok=True)
        lease = heal_dir / f"{key_name(f'heal.{self.INDEX}')}.lease"
        # The phantom dead healer: a freshly-stamped lease whose holder
        # (pid 999999) will never release it — exactly what a SIGKILL
        # mid-heal leaves behind. Claimed with O_EXCL like a real holder
        # would, so the survivor must outwait the 1 s TTL.
        fd = os.open(str(lease), os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        try:
            os.write(fd, f"{time.time():.6f}:999999:deadbeef".encode())
        finally:
            os.close(fd)
        takeovers0 = stats.get("fleet.singleflight.takeovers")
        t_wall = time.monotonic()
        recovered, ticks = self.drive_until(
            lambda s: not self.quarantined() and not self.paging(s),
            max_ticks=24,
        )
        return {
            "name": "sigkill_mid_heal_takeover",
            "recovered": recovered,
            "lease_takeovers": stats.get("fleet.singleflight.takeovers")
            - takeovers0,
            "takeover_wall_s": round(time.monotonic() - t_wall, 2),
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def _controller_events(self, name: str) -> list[dict]:
        from hyperspace_tpu.obs import events

        return [e for e in events.recent() if e["name"] == name]


def _soak_fleet_worker(ctx):
    """Dummy fleet member: hold the slot until told to stop (the SIGKILL
    target — jax-free, so respawn cost is pure process spawn)."""
    while not ctx.stop_event.is_set():
        time.sleep(0.05)


def main(argv) -> int:
    smoke = "--smoke" in argv
    out = Path("BENCH_SOAK.json")
    incidents_out: Path | None = None
    fleet_n = 0
    for i, a in enumerate(argv):
        if a.startswith("--out="):
            out = Path(a.split("=", 1)[1])
        elif a.startswith("--incidents-out="):
            incidents_out = Path(a.split("=", 1)[1])
        elif a.startswith("--fleet="):
            fleet_n = int(a.split("=", 1)[1])
        elif a == "--fleet" and i + 1 < len(argv):
            fleet_n = int(argv[i + 1])
    t0 = time.perf_counter()
    tmp = Path(tempfile.mkdtemp(prefix="hs-soak-"))
    total = 7 if fleet_n >= 2 else 4
    doc: dict = {
        "bench": "soak",
        "smoke": smoke,
        "fleet": fleet_n,
        "step_virtual_s": STEP_V,
        "episodes": [],
    }
    try:
        log(f"[soak] setup (rows per phase: {8_000 if smoke else 32_000})")
        bench = SoakBench(tmp, smoke, fleet_n=fleet_n)
        bench.build()
        try:
            log(f"[soak] episode 1/{total}: transient_io")
            doc["episodes"].append(bench.run_episode(bench.episode_transient_io))
            bench.refresh_traffic()  # mixed refresh traffic between episodes
            log(f"[soak] episode 2/{total}: corruption_quarantine")
            doc["episodes"].append(
                bench.run_episode(
                    bench.episode_corruption_quarantine, expect_heal=True
                )
            )
            log(f"[soak] episode 3/{total}: overload_burst")
            doc["episodes"].append(bench.run_episode(bench.episode_overload_burst))
            log(f"[soak] episode 4/{total}: worker_sigkill")
            doc["episodes"].append(bench.run_episode(bench.episode_worker_sigkill))
            if fleet_n >= 2:
                log(f"[soak] episode 5/{total}: brownout")
                doc["episodes"].append(bench.episode_brownout())
                bench.refresh_traffic()  # cold caches before corrupting
                log(f"[soak] episode 6/{total}: fleet_heal_two_controllers")
                doc["episodes"].append(bench.episode_fleet_heal())
                bench.refresh_traffic()
                log(f"[soak] episode 7/{total}: sigkill_mid_heal_takeover")
                doc["episodes"].append(bench.episode_sigkill_mid_heal())
            # Flight-recorder inventory, captured while the controlled
            # run's bundles are still on disk (tmp dies in the finally).
            incident_index = bench.ctrl.list_incidents()
            inc_root = bench.ctrl._incident_root(bench.session.conf)
            actuations = bench._controller_events("controller.actuation")
            doc["controlled"] = {
                "incident_bundles": incident_index,
                "queries": bench.queries,
                "errors_typed": bench.errors_typed,
                "errors_untyped": bench.errors_untyped,
                "quarantined_at_end": bench.quarantined(),
                "controller": bench.ctrl.snapshot(),
                "audit_executed_actions": sorted(
                    {
                        e["fields"]["action"]
                        for e in actuations
                        if e["fields"]["outcome"] == "executed"
                    }
                ),
            }
        finally:
            bench.shutdown()

        # -- counterfactual: the IDENTICAL corruption with the controller
        # disabled must leave the quarantine in place — the controller,
        # not luck, does the healing.
        log("[soak] counterfactual: corruption with controller disabled")
        from hyperspace_tpu.obs import events, slo

        slo.reset()
        events.reset()
        cf_tmp = tmp / "cf"
        cf = SoakBench(cf_tmp, smoke=True)
        cf.build()
        try:
            cf.session.conf.set("hyperspace.controller.enabled", "false")
            cf_episode = cf.episode_corruption_quarantine(expect_heal=False)
            doc["counterfactual"] = {
                **cf_episode,
                "errors_untyped": cf.errors_untyped,
                "controller_mode": cf.ctrl.snapshot()["mode"],
                # A disabled controller must record NOTHING: the flight
                # recorder is a controller behavior, not ambient.
                "incident_bundles_total": len(cf.ctrl.list_incidents()),
            }
        finally:
            cf.shutdown()

        # -- hard gates (ALWAYS enforced) ---------------------------------
        by_name = {e["name"]: e for e in doc["episodes"]}

        def _sole_bundle(ep_name: str):
            bs = by_name[ep_name]["incident_bundles"]
            return bs[0] if len(bs) == 1 else None

        # The flight-recorder contract: each injected episode leaves
        # exactly ONE finalized bundle with snapshotted journal segments
        # and a recovery resolution; the paging episodes' bundles carry
        # the paging burn verdict plus the shed engage/release audit
        # (transient_io ALSO quarantines — injected reads fail — so its
        # bundle opens on the quarantine trigger and closes "healed");
        # the corruption bundle carries the heal audit; the SIGKILL
        # episode (no SLO interplay) records nothing.
        b_io = _sole_bundle("transient_io")
        b_corrupt = _sole_bundle("corruption_quarantine")
        b_burst = _sole_bundle("overload_burst")
        gates = {
            "every_episode_recovered": all(
                e["recovered"] for e in doc["episodes"]
            ),
            "transient_io_paged_and_controller_engaged": (
                by_name["transient_io"]["paged"]
                and by_name["transient_io"]["controller_engaged"]
            ),
            "no_permanent_quarantine": not doc["controlled"]["quarantined_at_end"],
            "heal_actuated": by_name["corruption_quarantine"]["heal_actuations"] >= 1,
            "overload_p99_bounded": by_name["overload_burst"]["p99_bounded"],
            "zero_untyped_errors": not doc["controlled"]["errors_untyped"],
            "sigkill_respawned": by_name["worker_sigkill"]["recovered"],
            "counterfactual_quarantine_remains": doc["counterfactual"][
                "quarantine_remains"
            ],
            "counterfactual_zero_untyped": not doc["counterfactual"][
                "errors_untyped"
            ],
            "incident_bundle_per_episode": (
                None not in (b_io, b_corrupt, b_burst)
                and not by_name["worker_sigkill"]["incident_bundles"]
            ),
            "incident_bundles_paged_audited_recovered": (
                all(
                    b is not None
                    and b["closed"]
                    and b["resolution"] in ("healed", "slo.recovered")
                    and b["journal_segments"] >= 1  # journal rode along
                    for b in (b_io, b_corrupt, b_burst)
                )
                and all(
                    b is not None
                    and b["paged_objectives"]  # the paging burn verdict
                    and "shed.engage" in b["audited_actions"]
                    and "shed.release" in b["audited_actions"]
                    for b in (b_io, b_burst)
                )
                and b_corrupt is not None
                and any(
                    a.startswith("heal.")
                    for a in b_corrupt["audited_actions"]
                )
            ),
            "counterfactual_zero_bundles": (
                doc["counterfactual"]["incident_bundles_total"] == 0
            ),
        }
        if fleet_n >= 2:
            gates.update({
                "brownout_paged_and_recovered": (
                    by_name["brownout"]["paged"]
                    and by_name["brownout"]["recovered"]
                ),
                "brownout_delays_injected": (
                    by_name["brownout"]["delays_injected"] >= 1
                ),
                "brownout_p99_bounded": by_name["brownout"]["p99_bounded"],
                "scale_up_actuated": by_name["brownout"]["scale_up_actuated"],
                "scaled_back_to_baseline": by_name["brownout"]["scaled_back"],
                "fleet_heal_exactly_one": (
                    by_name["fleet_heal_two_controllers"]["executed_heals"] == 1
                ),
                "fleet_heal_follower_observed": (
                    by_name["fleet_heal_two_controllers"]["observed_heals"] >= 1
                ),
                "sigkill_heal_takeover": (
                    by_name["sigkill_mid_heal_takeover"]["lease_takeovers"] >= 1
                    and by_name["sigkill_mid_heal_takeover"]["recovered"]
                ),
            })
        doc["gates"] = gates
        # Export the bundles OUT of tmp (the finally below removes it)
        # so CI can upload them as the incident-bundle artifact.
        if incidents_out is not None and inc_root is not None and inc_root.is_dir():
            if incidents_out.exists():
                shutil.rmtree(incidents_out)
            shutil.copytree(inc_root, incidents_out)
            log(
                f"[soak] exported {len(incident_index)} incident "
                f"bundle(s) -> {incidents_out}"
            )
        doc["elapsed_s"] = round(time.perf_counter() - t0, 1)
        out.write_text(json.dumps(doc, indent=2, default=str) + "\n")
        log(f"[soak] wrote {out} in {doc['elapsed_s']}s")
        for k, ok in gates.items():
            log(f"[soak]   gate {k}: {'PASS' if ok else 'FAIL'}")
        return 0 if all(gates.values()) else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
