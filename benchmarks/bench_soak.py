"""Chaos soak harness: prove the ops controller heals the system.

The gate of docs/fault_tolerance.md "self-driving operations": under a
deterministic fault schedule plus an overload burst, **SLOs recover
without a human** — no unbounded burn, no permanent quarantine, zero
untyped errors, bounded time-to-recover per fault episode — and the
identical schedule with `hyperspace.controller.enabled=false` shows the
degraded counterfactual (the quarantine REMAINS), proving the
controller, not luck, did the healing.

Mixed query + refresh traffic flows through a real QueryServer over a
real indexed store for the whole run while four fault episodes fire in
sequence:

1. **transient_io** — `faults.inject("bucket.read")` makes every data
   read fail (after the retry layer gives up): availability burns, the
   SLO pages, the controller sheds load + tightens quotas; the fault
   clears and the burn must age back below the page threshold with the
   overrides released.
2. **corruption_quarantine** — a live index bucket file is corrupted on
   disk: the next indexed query raises IndexCorruptionError, the index
   is quarantined (queries keep answering via fallback), and the
   controller must heal it — `recover()` + full rebuild through the
   crash-safe Action protocol — leaving `session.index_health` empty.
3. **overload_burst** — submit bursts far past capacity with tight
   deadlines: queued queries expire (serve.timeouts), availability
   burns, the controller tightens the shed threshold; every refusal
   must be TYPED (AdmissionRejected/QuotaExceeded/QueryTimeout), the
   p99 of completed queries stays bounded, and the burn recovers when
   the burst ends.
4. **worker_sigkill** — a real fleet member is SIGKILLed: the
   supervisor must respawn it (WARN `fleet.worker.restarted`) within
   the bound — the crash-loop backoff satellite keeps repeat crashes
   from burning the restart budget in milliseconds.

Determinism: the controller and the SLO tracker run on a VIRTUAL clock
advanced a fixed 5 s per tick (burn windows are clamped spans over the
sample ring, so compressed time keeps the multi-window math exact while
a CI run finishes in ~a minute); fault injection counts calls, never
wall time. Real wall time only enters through measured query latencies
(the latency histogram) and the SIGKILL episode's respawn bound.

Writes BENCH_SOAK.json. `--smoke` is the CI-scaled run (the `soak`
job); gates are ALWAYS enforced — exit 1 on any failure.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

STEP_V = 5.0  # virtual seconds per tick (the controller/SLO clock)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class VirtualClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt: float = STEP_V) -> float:
        self.t += dt
        return self.t


def _gen_data(root: Path, rows: int, files: int) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    per = rows // files
    root.mkdir(parents=True, exist_ok=True)
    for f in range(files):
        t = pa.table(
            {
                "id": pa.array(np.arange(f * per, (f + 1) * per, dtype=np.int64)),
                "key": pa.array(rng.integers(0, 16, per, dtype=np.int64)),
                "value": pa.array(rng.standard_normal(per)),
            }
        )
        pq.write_table(t, root / f"part-{f}.parquet")


class SoakBench:
    """One soak run: fleet-of-one serving stack + controller + schedule."""

    INDEX = "soak_idx"

    def __init__(self, tmp: Path, smoke: bool):
        self.tmp = tmp
        self.smoke = smoke
        self.rows = 8_000 if smoke else 32_000
        self.clock = VirtualClock()
        self.errors_typed: dict[str, int] = {}
        self.errors_untyped: dict[str, int] = {}
        self.completed_lat: list[float] = []
        self.queries = 0
        self._key = 0

    # -- setup ------------------------------------------------------------
    def build(self):
        from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
        from hyperspace_tpu.serve.fleet.quota import TenantQuotas

        self.data = self.tmp / "data"
        _gen_data(self.data, self.rows, 2)
        self.session = HyperspaceSession(system_path=str(self.tmp / "indexes"))
        conf = self.session.conf
        # Compressed-time control loop: cooldowns/windows are VIRTUAL.
        conf.set("hyperspace.controller.enabled", "true")
        conf.set("hyperspace.controller.cooldownSeconds", 20.0)
        conf.set("hyperspace.obs.events.maxEvents", 4096)
        self.hs = Hyperspace(self.session)
        df = self.session.parquet(self.data)
        self.hs.create_index(df, IndexConfig(self.INDEX, ["key"], ["value", "id"]))
        self.session.enable_hyperspace()
        self.df = df
        self.server = self.session.serve(
            workers=4,
            max_queue_depth=64,
            quotas=TenantQuotas(rate=10_000.0, burst=10_000.0),
        )
        self.ctrl = self.hs.controller(server=self.server, clock=lambda: self.clock.t)
        # warm compile + plan caches so episode latencies are steady-state
        self.run_batch(8)
        self.tick(batch=8)

    def shutdown(self):
        self.server.shutdown()

    # -- traffic ----------------------------------------------------------
    def _plan(self):
        from hyperspace_tpu import col

        self._key = (self._key + 1) % 16
        return self.df.filter(col("key") == self._key).select("id", "key", "value")

    def run_batch(self, n: int, timeout: float | None = None, tenant: bool = True):
        """Submit n point lookups and wait for each; every error must be
        typed (the zero-untyped-errors gate folds from here)."""
        from hyperspace_tpu.exceptions import HyperspaceError

        handles = []
        for i in range(n):
            self.queries += 1
            try:
                handles.append(
                    self.server.submit(
                        self._plan(),
                        tenant=f"t{i % 4}" if tenant else None,
                        timeout=timeout,
                    )
                )
            except BaseException as e:  # noqa: HSL017 — harness accounting:
                # every refusal is recorded by type and judged by the
                # zero-untyped gate below; nothing is swallowed silently.
                self._record_error(e, HyperspaceError)
        for h in handles:
            t0 = time.perf_counter()
            try:
                h.result(timeout=60.0)
                self.completed_lat.append(time.perf_counter() - t0)
            except BaseException as e:  # noqa: HSL017 — same accounting
                self._record_error(e, HyperspaceError)

    def _record_error(self, e: BaseException, HyperspaceError) -> None:
        name = type(e).__name__
        if isinstance(e, (HyperspaceError, OSError)):
            self.errors_typed[name] = self.errors_typed.get(name, 0) + 1
        else:
            self.errors_untyped[name] = self.errors_untyped.get(name, 0) + 1

    def tick(self, batch: int = 12, timeout: float | None = None) -> dict:
        """One soak tick: a traffic batch, one virtual-time step, one
        controller reconciliation pass."""
        self.run_batch(batch, timeout=timeout)
        self.ctrl.step(now=self.clock.advance())
        return self.ctrl.snapshot()

    def refresh_traffic(self):
        """The 'mixed refresh traffic' leg: append rows, full-refresh the
        index through the normal crash-safe action."""
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        n = 512
        rng = np.random.default_rng(int(self.clock.t) + 1)
        pq.write_table(
            pa.table({
                "id": pa.array(np.arange(self.rows, self.rows + n, dtype=np.int64)),
                "key": pa.array(rng.integers(0, 16, n, dtype=np.int64)),
                "value": pa.array(rng.standard_normal(n)),
            }),
            self.data / f"append-{int(self.clock.t)}.parquet",
        )
        self.rows += n
        self.hs.refresh_index(self.INDEX, "full")

    # -- verdict helpers --------------------------------------------------
    def paging(self, snap: dict) -> bool:
        return any(v == "page" for v in snap["verdicts"].values())

    def drive_until(self, pred, max_ticks: int, batch: int = 12) -> tuple[bool, int]:
        for i in range(max_ticks):
            snap = self.tick(batch=batch)
            if pred(snap):
                return True, i + 1
        return False, max_ticks

    def quarantined(self) -> list[str]:
        with self.session._state_lock:
            return sorted(self.session.index_health)

    # -- episodes ---------------------------------------------------------
    def episode_transient_io(self) -> dict:
        from hyperspace_tpu import faults
        from hyperspace_tpu.execution import io as hio

        t_start = self.clock.t
        faults.inject("bucket.read")  # transient FaultError on every read
        # The warm decoded-table/footer caches would serve every bucket
        # without touching the disk — drop them so the injected IO fault
        # reaches the read path (exactly what a real cache eviction or
        # process restart does mid-incident).
        hio.clear_table_cache()
        hio.clear_footer_cache()
        paged = False
        try:
            for _ in range(6):
                snap = self.tick()
                paged = paged or self.paging(snap)
                if snap["engaged"]:
                    break
        finally:
            faults.reset()
        engaged = self.ctrl.snapshot()["engaged"]
        recovered, ticks = self.drive_until(
            lambda s: not self.paging(s) and not s["engaged"], max_ticks=40
        )
        return {
            "name": "transient_io",
            "paged": paged,
            "controller_engaged": engaged,
            "recovered": recovered,
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def episode_corruption_quarantine(self, expect_heal: bool) -> dict:
        t_start = self.clock.t
        index_root = Path(
            self.session.manager.path_resolver.get_index_path(self.INDEX)
        )
        versions = sorted(
            (d for d in index_root.glob("v__=*") if d.is_dir()),
            key=lambda d: int(d.name.split("=")[1]),
        )
        bucket = sorted(versions[-1].glob("*.parquet"))[0]
        with open(bucket, "r+b") as f:
            f.write(b"\x00GARBAGE\x00" * 4)
            f.truncate(128)
        # drive traffic until the corruption is hit and (controller on)
        # healed — index_health must drain back to empty without a human
        recovered, ticks = self.drive_until(
            lambda s: not self.quarantined() and not self.paging(s),
            max_ticks=20 if expect_heal else 8,
        )
        heals = [
            e for e in self._controller_events("controller.actuation")
            if e["fields"]["action"].startswith("heal.")
            and e["fields"]["outcome"] == "executed"
        ]
        return {
            "name": "corruption_quarantine",
            "recovered": recovered if expect_heal else not recovered,
            "quarantine_remains": bool(self.quarantined()),
            "heal_actuations": len(heals),
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def episode_overload_burst(self) -> dict:
        t_start = self.clock.t
        burst = 150 if self.smoke else 300
        paged = False
        shed_before = self.server.get_shed_depth()
        min_shed = shed_before
        for _ in range(4):
            snap = self.tick(batch=burst, timeout=0.03)
            paged = paged or self.paging(snap)
            min_shed = min(min_shed, self.server.get_shed_depth())
        recovered, ticks = self.drive_until(
            lambda s: not self.paging(s) and not s["engaged"], max_ticks=40
        )
        import numpy as np

        lat = np.sort(np.asarray(self.completed_lat))
        p99 = float(np.percentile(lat, 99)) if len(lat) else 0.0
        return {
            "name": "overload_burst",
            "paged": paged,
            "shed_tightened_to": min_shed,
            "shed_restored_to": self.server.get_shed_depth(),
            "completed_p99_s": round(p99, 4),
            "p99_bounded": p99 < 5.0,
            "recovered": recovered,
            "time_to_recover_vs": round(self.clock.t - t_start, 1),
        }

    def episode_worker_sigkill(self) -> dict:
        import os
        import signal

        from hyperspace_tpu.serve.fleet.supervisor import FleetSupervisor

        t0 = time.monotonic()
        sup = FleetSupervisor(
            _soak_fleet_worker, fleet_dir=str(self.tmp / "fleet"), n=2,
            max_restarts=3,
        )
        sup.start()
        try:
            deadline = time.monotonic() + 120
            while sup.alive_count() < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            victim = sup.pids()[0]
            os.kill(victim, signal.SIGKILL)
            t_kill = time.monotonic()
            recovered = False
            while time.monotonic() < t_kill + 90:
                if sup.alive_count() == 2 and sup.pids()[0] != victim:
                    recovered = True
                    break
                time.sleep(0.05)
            ttr = time.monotonic() - t_kill
        finally:
            sup.stop(timeout=30)
        from hyperspace_tpu.obs import events

        restarted = [
            e for e in events.recent() if e["name"] == "fleet.worker.restarted"
        ]
        return {
            "name": "worker_sigkill",
            "recovered": recovered,
            "restart_events": len(restarted),
            "time_to_recover_s": round(ttr, 2),
            "setup_s": round(time.monotonic() - t0, 2),
        }

    def _controller_events(self, name: str) -> list[dict]:
        from hyperspace_tpu.obs import events

        return [e for e in events.recent() if e["name"] == name]


def _soak_fleet_worker(ctx):
    """Dummy fleet member: hold the slot until told to stop (the SIGKILL
    target — jax-free, so respawn cost is pure process spawn)."""
    while not ctx.stop_event.is_set():
        time.sleep(0.05)


def main(argv) -> int:
    smoke = "--smoke" in argv
    out = Path("BENCH_SOAK.json")
    for a in argv:
        if a.startswith("--out="):
            out = Path(a.split("=", 1)[1])
    t0 = time.perf_counter()
    tmp = Path(tempfile.mkdtemp(prefix="hs-soak-"))
    doc: dict = {
        "bench": "soak",
        "smoke": smoke,
        "step_virtual_s": STEP_V,
        "episodes": [],
    }
    try:
        log(f"[soak] setup (rows per phase: {8_000 if smoke else 32_000})")
        bench = SoakBench(tmp, smoke)
        bench.build()
        try:
            log("[soak] episode 1/4: transient_io")
            doc["episodes"].append(bench.episode_transient_io())
            bench.refresh_traffic()  # mixed refresh traffic between episodes
            log("[soak] episode 2/4: corruption_quarantine")
            doc["episodes"].append(bench.episode_corruption_quarantine(expect_heal=True))
            log("[soak] episode 3/4: overload_burst")
            doc["episodes"].append(bench.episode_overload_burst())
            log("[soak] episode 4/4: worker_sigkill")
            doc["episodes"].append(bench.episode_worker_sigkill())
            actuations = bench._controller_events("controller.actuation")
            doc["controlled"] = {
                "queries": bench.queries,
                "errors_typed": bench.errors_typed,
                "errors_untyped": bench.errors_untyped,
                "quarantined_at_end": bench.quarantined(),
                "controller": bench.ctrl.snapshot(),
                "audit_executed_actions": sorted(
                    {
                        e["fields"]["action"]
                        for e in actuations
                        if e["fields"]["outcome"] == "executed"
                    }
                ),
            }
        finally:
            bench.shutdown()

        # -- counterfactual: the IDENTICAL corruption with the controller
        # disabled must leave the quarantine in place — the controller,
        # not luck, does the healing.
        log("[soak] counterfactual: corruption with controller disabled")
        from hyperspace_tpu.obs import events, slo

        slo.reset()
        events.reset()
        cf_tmp = tmp / "cf"
        cf = SoakBench(cf_tmp, smoke=True)
        cf.build()
        try:
            cf.session.conf.set("hyperspace.controller.enabled", "false")
            cf_episode = cf.episode_corruption_quarantine(expect_heal=False)
            doc["counterfactual"] = {
                **cf_episode,
                "errors_untyped": cf.errors_untyped,
                "controller_mode": cf.ctrl.snapshot()["mode"],
            }
        finally:
            cf.shutdown()

        # -- hard gates (ALWAYS enforced) ---------------------------------
        by_name = {e["name"]: e for e in doc["episodes"]}
        gates = {
            "every_episode_recovered": all(
                e["recovered"] for e in doc["episodes"]
            ),
            "transient_io_paged_and_controller_engaged": (
                by_name["transient_io"]["paged"]
                and by_name["transient_io"]["controller_engaged"]
            ),
            "no_permanent_quarantine": not doc["controlled"]["quarantined_at_end"],
            "heal_actuated": by_name["corruption_quarantine"]["heal_actuations"] >= 1,
            "overload_p99_bounded": by_name["overload_burst"]["p99_bounded"],
            "zero_untyped_errors": not doc["controlled"]["errors_untyped"],
            "sigkill_respawned": by_name["worker_sigkill"]["recovered"],
            "counterfactual_quarantine_remains": doc["counterfactual"][
                "quarantine_remains"
            ],
            "counterfactual_zero_untyped": not doc["counterfactual"][
                "errors_untyped"
            ],
        }
        doc["gates"] = gates
        doc["elapsed_s"] = round(time.perf_counter() - t0, 1)
        out.write_text(json.dumps(doc, indent=2, default=str) + "\n")
        log(f"[soak] wrote {out} in {doc['elapsed_s']}s")
        for k, ok in gates.items():
            log(f"[soak]   gate {k}: {'PASS' if ok else 'FAIL'}")
        return 0 if all(gates.values()) else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
